"""Correlation volume + lookup vs the reference math (torch oracle) and
cross-implementation equivalence (volume vs on-the-fly)."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from raft_ncup_tpu.ops import (
    build_corr_pyramid,
    coords_grid,
    corr_lookup,
    corr_lookup_onthefly,
)


def torch_corr_block(fmap1, fmap2, num_levels=4, radius=4):
    """Reimplementation of the reference CorrBlock (core/corr.py:6-55) as a
    test oracle (NCHW torch tensors in, (B, L*K*K, H, W) out)."""
    batch, dim, ht, wd = fmap1.shape
    f1 = fmap1.view(batch, dim, ht * wd)
    f2 = fmap2.view(batch, dim, ht * wd)
    corr = torch.matmul(f1.transpose(1, 2), f2)
    corr = corr.view(batch * ht * wd, 1, ht, wd) / torch.sqrt(
        torch.tensor(dim).float()
    )
    pyramid = [corr]
    for _ in range(num_levels - 1):
        corr = F.avg_pool2d(corr, 2, stride=2)
        pyramid.append(corr)

    def lookup(coords):
        r = radius
        coords = coords.permute(0, 2, 3, 1)
        batch, h1, w1, _ = coords.shape
        out_pyramid = []
        for i, corr in enumerate(pyramid):
            dx = torch.linspace(-r, r, 2 * r + 1)
            dy = torch.linspace(-r, r, 2 * r + 1)
            delta = torch.stack(torch.meshgrid(dy, dx, indexing="ij"), axis=-1)
            centroid_lvl = coords.reshape(batch * h1 * w1, 1, 1, 2) / 2**i
            delta_lvl = delta.view(1, 2 * r + 1, 2 * r + 1, 2)
            coords_lvl = centroid_lvl + delta_lvl
            H, W = corr.shape[-2:]
            xgrid, ygrid = coords_lvl.split([1, 1], dim=-1)
            xgrid = 2 * xgrid / (W - 1) - 1
            ygrid = 2 * ygrid / (H - 1) - 1
            grid = torch.cat([xgrid, ygrid], dim=-1)
            sampled = F.grid_sample(corr, grid, align_corners=True)
            out_pyramid.append(sampled.view(batch, h1, w1, -1))
        out = torch.cat(out_pyramid, dim=-1)
        return out.permute(0, 3, 1, 2).contiguous().float()

    return lookup


@pytest.mark.parametrize("radius", [3, 4])
def test_corr_volume_lookup_matches_torch(radius):
    # H, W large enough that the deepest pyramid level is > 1 pixel (the
    # reference's coordinate normalization divides by W-1).
    rng = np.random.default_rng(0)
    B, H, W, C = 2, 16, 24, 16
    f1 = rng.standard_normal((B, H, W, C)).astype(np.float32)
    f2 = rng.standard_normal((B, H, W, C)).astype(np.float32)
    coords = (
        coords_grid(B, H, W)
        + rng.uniform(-3, 3, size=(B, H, W, 2)).astype(np.float32)
    )

    pyr = build_corr_pyramid(jnp.asarray(f1), jnp.asarray(f2), num_levels=4)
    ours = np.asarray(corr_lookup(pyr, jnp.asarray(coords), radius))

    t1 = torch.from_numpy(f1).permute(0, 3, 1, 2)
    t2 = torch.from_numpy(f2).permute(0, 3, 1, 2)
    tcoords = torch.from_numpy(np.asarray(coords)).permute(0, 3, 1, 2)
    lookup = torch_corr_block(t1, t2, num_levels=4, radius=radius)
    theirs = lookup(tcoords).permute(0, 2, 3, 1).numpy()

    np.testing.assert_allclose(ours, theirs, atol=1e-4)


def test_onthefly_matches_volume():
    rng = np.random.default_rng(1)
    B, H, W, C = 1, 16, 22, 8
    f1 = jnp.asarray(rng.standard_normal((B, H, W, C)).astype(np.float32))
    f2 = jnp.asarray(rng.standard_normal((B, H, W, C)).astype(np.float32))
    coords = coords_grid(B, H, W) + jnp.asarray(
        rng.uniform(-4, 4, size=(B, H, W, 2)).astype(np.float32)
    )
    pyr = build_corr_pyramid(f1, f2, num_levels=4)
    vol = np.asarray(corr_lookup(pyr, coords, radius=4))
    otf = np.asarray(
        corr_lookup_onthefly(f1, f2, coords, radius=4, num_levels=4, row_chunk=3)
    )
    np.testing.assert_allclose(vol, otf, atol=2e-4)


def test_corr_pyramid_shapes():
    B, H, W, C = 2, 16, 24, 4
    f = jnp.zeros((B, H, W, C))
    pyr = build_corr_pyramid(f, f, num_levels=4)
    assert [lvl.shape for lvl in pyr.levels] == [
        (B, H * W, 16, 24),
        (B, H * W, 8, 12),
        (B, H * W, 4, 6),
        (B, H * W, 2, 3),
    ]
    out = corr_lookup(pyr, coords_grid(B, H, W), radius=4)
    assert out.shape == (B, H, W, 4 * 81)
