"""Adaptive compute per request (docs/PERF.md "Early exit"): in-graph
per-sample convergence detection in the refinement scan.

The contracts pinned here:

- **Bitwise freeze.** A lane detected converged at iteration k commits
  its own k-th update and rides frozen (``jnp.where`` select) to the
  end of the budget — its flow is BITWISE the plain forward truncated
  at k iterations, even though the two come from different executables
  (the while_loop program vs the scan program).
- **Quality budget.** The early-exit forward's mean EPE against its own
  full-budget twin stays inside the pinned ``EARLYEXIT_EPE_BUDGET``
  (precision/policy.py), for f32 and bf16_infer.
- **Guard cleanliness.** Detection lives in-graph: a warm early-exit
  window performs ZERO implicit host transfers and ZERO recompiles —
  no host code ever inspects the convergence mask.
- **Segment quantization.** Under the pipe axis the tick schedule is
  fixed, so exits bill whole segments:
  ``exec_pipe == ceil(exec_mono / seg_len) * seg_len`` (S in {1,2,4}),
  with the flow unchanged.
- **Expected-iteration budgeting.** ``IterationBudgetController``
  scales occupancy by the executed-iters EWMA — admitted depth before
  degrade RISES as the EWMA falls — while the unfed controller and the
  SLO degrade path keep their exact PR-12 semantics.

Tolerances are probed from the fixture weights' actual convergence
dynamics at runtime (untrained weights have no decaying deltas, so a
hard-coded threshold would silently stop splitting lanes when the init
changes).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import ServeConfig, small_model_config
from raft_ncup_tpu.inference.costs import CostLedger
from raft_ncup_tpu.inference.pipe_schedule import PipelinedForward
from raft_ncup_tpu.inference.pipeline import (
    ShapeCachedForward,
    env_earlyexit_tol,
)
from raft_ncup_tpu.models import get_model
from raft_ncup_tpu.precision import EARLYEXIT_EPE_BUDGET
from raft_ncup_tpu.serving import STATUS_OK, FlowServer
from raft_ncup_tpu.serving.budget import IterationBudgetController

HW = (32, 32)
B = 3
ITERS = 4  # divisible by S in {1, 2, 4}


@pytest.fixture(scope="module")
def raft():
    cfg = small_model_config("raft", dataset="chairs")
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, *HW, 3))
    return model, variables


@pytest.fixture(scope="module")
def fwd(raft):
    model, variables = raft
    return ShapeCachedForward(model, variables)


@pytest.fixture(scope="module")
def images():
    g = np.random.default_rng(7)
    return (
        jnp.asarray(g.random((B, *HW, 3)) * 255.0, jnp.float32),
        jnp.asarray(g.random((B, *HW, 3)) * 255.0, jnp.float32),
    )


def _dnorm1(fwd, i1, i2, policy=None):
    """Per-lane detection norm of the FIRST iteration — exactly what the
    in-graph detector sees at step 1: flow starts at zero, so
    ``|flow_lr(1)|`` mean IS ``|delta_1|`` mean. Probed at runtime so the
    tolerance choice tracks the fixture weights' real dynamics."""
    lr, _up = fwd.forward_device(i1, i2, 1, policy=policy)
    lr = np.asarray(jax.device_get(lr))
    return np.abs(lr).mean(axis=(1, 2, 3))


def _splitting_tol(d1):
    """A tolerance strictly between the lanes' first-iteration norms:
    at least one lane converges at iteration 1, at least one does not
    (untrained-weight deltas GROW with depth, so a lane that misses the
    first check never converges later — the split is stable)."""
    lo, hi = float(d1.min()), float(d1.max())
    assert lo < hi, f"degenerate probe: all lanes at {lo}"
    return (lo + hi) / 2.0


def _pull(x):
    return np.asarray(jax.device_get(x))


# ------------------------------------------------------- bitwise freeze


class TestBitwiseFreeze:
    def test_converged_lane_equals_truncated_run(self, fwd, images):
        """Lane i of the early-exit forward is BITWISE lane i of the
        plain forward at exec_iters[i] iterations — across executables
        (while_loop vs scan programs)."""
        i1, i2 = images
        tol = _splitting_tol(_dnorm1(fwd, i1, i2))
        lr, up, ex = fwd.forward_device(i1, i2, ITERS, early_exit_tol=tol)
        lr, up, ex = _pull(lr), _pull(up), _pull(ex)
        assert ex.min() >= 1 and ex.max() <= ITERS
        # The probed tolerance really split the batch: heterogeneous
        # executed counts, not an all-or-nothing window.
        assert ex.min() < ex.max()
        for i, k in enumerate(ex):
            ref_lr, ref_up = fwd.forward_device(i1, i2, int(k))
            np.testing.assert_array_equal(lr[i], _pull(ref_lr)[i])
            np.testing.assert_array_equal(up[i], _pull(ref_up)[i])

    def test_tiny_tol_runs_full_budget_bitwise(self, fwd, images):
        """A tolerance below every delta never fires: exec == budget and
        the result is bitwise the plain scan — detection costs no
        numerics when it does nothing."""
        i1, i2 = images
        lr, up, ex = fwd.forward_device(
            i1, i2, ITERS, early_exit_tol=1e-9
        )
        assert (_pull(ex) == ITERS).all()
        ref_lr, ref_up = fwd.forward_device(i1, i2, ITERS)
        np.testing.assert_array_equal(_pull(lr), _pull(ref_lr))
        np.testing.assert_array_equal(_pull(up), _pull(ref_up))


# -------------------------------------------------------- quality budget


class TestEpeParity:
    @pytest.mark.parametrize("policy", ["f32", "bf16_infer"])
    def test_epe_within_budget(self, fwd, images, policy):
        """Early exit vs the full-budget twin on the same inputs and
        weights: detection must fire AND the mean EPE delta must stay
        inside the pinned budget. Budget 2 here — each converged lane
        skips one refinement step, the granularity the EPE bound is
        written against (docs/PERF.md derives ~8*tol px per skipped
        step)."""
        i1, i2 = images
        tol = _splitting_tol(_dnorm1(fwd, i1, i2, policy=policy))
        _lr, up, ex = fwd.forward_device(
            i1, i2, 2, early_exit_tol=tol, policy=policy
        )
        _lr_f, up_f = fwd.forward_device(i1, i2, 2, policy=policy)
        ex = _pull(ex)
        assert ex.min() == 1  # detection fired on the converged lane(s)
        epe = float(
            np.sqrt(((_pull(up) - _pull(up_f)) ** 2).sum(-1)).mean()
        )
        assert epe <= EARLYEXIT_EPE_BUDGET, (
            f"{policy}: {epe:.4f} px vs budget {EARLYEXIT_EPE_BUDGET}"
        )


# ------------------------------------------------------ guard cleanliness


class TestGuards:
    def test_warm_window_zero_recompiles_zero_transfers(self, fwd, images):
        """With detection LIVE, a warm window is guard-clean: the mask,
        the while_loop condition, and the executed-iters counter all
        stay on device; the executable set is closed after warmup."""
        from raft_ncup_tpu.analysis.guards import (
            GuardStats,
            RecompileWatchdog,
            forbid_host_transfers,
        )

        i1, i2 = images
        tol = _splitting_tol(_dnorm1(fwd, i1, i2))
        # Warm the early-exit executable and the scalar-slice pull.
        out = fwd.forward_device(i1, i2, ITERS, early_exit_tol=tol)
        jax.device_get(out[1][0, 0, 0, 0])
        g = np.random.default_rng(23)
        stats = GuardStats()
        with RecompileWatchdog() as wd, forbid_host_transfers(
            stats, raise_on_violation=True
        ):
            outs = []
            for _ in range(3):
                j1 = jnp.asarray(g.random((B, *HW, 3)) * 255.0, jnp.float32)
                j2 = jnp.asarray(g.random((B, *HW, 3)) * 255.0, jnp.float32)
                outs.append(
                    fwd.forward_device(j1, j2, ITERS, early_exit_tol=tol)
                )
            # The one sanctioned explicit pull.
            jax.device_get(outs[-1][1][0, 0, 0, 0])
        assert wd.count == 0
        assert stats.host_transfers == 0


# --------------------------------------------------- segment quantization


class TestPipeQuantization:
    @pytest.mark.parametrize("segments", [1, 2, 4])
    def test_exec_quantizes_to_segment_boundaries(
        self, raft, fwd, images, segments
    ):
        """``exec_pipe == ceil(exec_mono / seg_len) * seg_len``: the
        tick schedule is fixed, so a converged lane rides frozen to the
        next seam and bills the whole segment — and the flow itself is
        unchanged (the freeze inside a segment is still per-iteration
        and bitwise)."""
        model, variables = raft
        i1, i2 = images
        tol = _splitting_tol(_dnorm1(fwd, i1, i2))
        lr_m, up_m, ex_m = fwd.forward_device(
            i1, i2, ITERS, early_exit_tol=tol
        )
        ex_m = _pull(ex_m)
        pf = PipelinedForward(model, variables, segments=segments)
        outs = pf.forward_many([(i1, i2)], ITERS, early_exit_tol=tol)
        assert len(outs) == 1 and len(outs[0]) == 3
        lr_p, up_p, ex_p = outs[0]
        if segments == 1:
            # Delegation path: no tick schedule, so no quantization —
            # the true per-sample counts pass through.
            want = [int(k) for k in ex_m]
        else:
            seg_len = ITERS // segments
            want = [math.ceil(int(k) / seg_len) * seg_len for k in ex_m]
        assert list(_pull(ex_p)) == want
        np.testing.assert_allclose(
            _pull(up_p), _pull(up_m), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            _pull(lr_p), _pull(lr_m), rtol=1e-5, atol=1e-5
        )


# ------------------------------------------------------------- API edges


class TestApiContracts:
    def test_detection_off_path_unchanged(self, fwd, images):
        """No tolerance → the exact pre-existing contract: a 2-tuple
        from a 4-tuple cache key (zero churn for existing callers)."""
        i1, i2 = images
        out = fwd.forward_device(i1, i2, ITERS)
        assert len(out) == 2

    def test_apply_validation(self, raft, images):
        model, variables = raft
        i1, i2 = images
        with pytest.raises(ValueError, match="test_mode"):
            model.apply(
                variables, i1, i2, iters=2, early_exit_tol=0.1
            )
        with pytest.raises(ValueError, match="early_exit_tol"):
            model.apply(
                variables, i1, i2, iters=2, test_mode=True,
                return_exec_iters=True,
            )

    def test_tolerances_are_distinct_executables(self, raft, images):
        """The tolerance is baked into the compiled loop condition, so
        each tolerance is its own cache entry — and the detection-off
        key stays a plain 4-tuple alongside them. The same fresh
        instance also pins the cost-ledger meta (one executable set,
        both contracts — compiles are the expensive part of this
        file)."""
        model, variables = raft
        led = CostLedger(enabled=True)
        fwd = ShapeCachedForward(model, variables, cost_ledger=led)
        i1, i2 = images
        fwd.forward_device(i1, i2, 2, early_exit_tol=0.5)
        fwd.forward_device(i1, i2, 2, early_exit_tol=0.25)
        fwd.forward_device(i1, i2, 2)
        assert fwd.stats["compiles"] == 3
        fwd.forward_device(i1, i2, 2, early_exit_tol=0.5)
        assert fwd.stats["hits"] == 1
        # Ledger meta: the threshold rides the executable entry, so
        # flip_recommendations (and the autotuner after it) can judge
        # EPE-vs-speedup against the exact tolerance that compiled.
        entry = led.lookup(kind="forward", earlyexit_tol=0.5)
        assert entry is not None
        assert entry["meta"]["iters"] == 2
        # The detection-off executable's meta carries NO tolerance.
        plain = led.lookup(kind="forward", iters=2, earlyexit_tol=None)
        assert plain is not None
        assert "earlyexit_tol" not in plain["meta"]

    def test_env_chokepoint(self, monkeypatch):
        monkeypatch.delenv("RAFT_NCUP_EARLYEXIT", raising=False)
        assert env_earlyexit_tol() is None
        monkeypatch.setenv("RAFT_NCUP_EARLYEXIT", "1")
        monkeypatch.setenv("RAFT_NCUP_EARLYEXIT_TOL", "0.125")
        assert env_earlyexit_tol() == 0.125


# ----------------------------------------------- expected-iteration budget


class TestBudgetEwma:
    LEVELS = (8, 4)
    CAP = 10

    def _ctl(self, **kw):
        return IterationBudgetController(
            self.LEVELS, capacity=self.CAP, high_water=0.75,
            low_water=0.25, recover_patience=2, **kw,
        )

    def test_unfed_controller_is_worst_case(self):
        """Never-fed → expected == top level, scale == 1.0: occupancy
        arithmetic (and therefore every decide trajectory) is bitwise
        the pre-early-exit controller."""
        ctl = self._ctl()
        assert ctl.expected_iters == 8.0
        assert ctl.expected_scale() == 1.0
        assert ctl.decide(8) == 4  # 0.8 >= 0.75: degrades, as before
        assert ctl.drops == 1

    def test_admitted_depth_rises_as_ewma_falls(self):
        """The tentpole serving claim: a queue of early-exiting requests
        is cheaper than its depth suggests, so the SAME depth that
        degrades the worst-case controller holds full quality once the
        executed-iters EWMA reflects the real cost."""
        ctl = self._ctl()
        for _ in range(32):  # converge the EWMA to ~2 of 8 iters
            ctl.note_executed(2.0)
        assert ctl.expected_iters == pytest.approx(2.0, abs=1e-3)
        assert ctl.expected_scale() == pytest.approx(0.25, abs=1e-3)
        # Depth 8 of 10: worst-case occupancy 0.8 (degrades, previous
        # test); expected-work occupancy 0.8 * 0.25 = 0.2 (holds).
        assert ctl.decide(8) == 8
        assert ctl.drops == 0

    def test_slo_degrade_not_scaled(self):
        """A burning SLO degrades immediately no matter how cheap the
        model thinks a request is — the PR-12 page semantics."""
        ctl = self._ctl()
        for _ in range(32):
            ctl.note_executed(1.0)
        assert ctl.decide(0, slo_degraded=True) == 4
        assert ctl.drops == 1 and ctl.slo_drops == 1

    def test_note_executed_clamps_and_smooths(self):
        ctl = self._ctl()
        ctl.note_executed(0.0)  # bogus: clamps to 1
        assert ctl.expected_iters == 1.0
        ctl.note_executed(99.0)  # bogus: clamps to levels[0]
        assert ctl.expected_iters == pytest.approx(
            0.25 * 8.0 + 0.75 * 1.0
        )

    def test_recovery_hysteresis_preserved(self):
        """Earned-calm recovery is untouched by the cost model: the
        scaled occupancy feeds the SAME watermark machinery."""
        ctl = self._ctl()
        assert ctl.decide(8) == 4
        assert ctl.decide(1) == 4  # calm 1
        assert ctl.decide(1) == 8  # calm 2 == patience: recovers
        assert ctl.recoveries == 1


# ----------------------------------------------------- server integration


class TestServerIntegration:
    def test_early_exit_serving_end_to_end(self, raft, fwd, images, monkeypatch):
        """The env knob turns detection on at server construction; the
        response flow is bitwise the direct early-exit forward, the
        executed-iters histogram fills, and the budget controller's
        expected-iters model moves off worst case."""
        model, variables = raft
        i1, i2 = images
        tol = _splitting_tol(_dnorm1(fwd, i1, i2))
        monkeypatch.setenv("RAFT_NCUP_EARLYEXIT", "1")
        monkeypatch.setenv("RAFT_NCUP_EARLYEXIT_TOL", repr(float(tol)))
        cfg = ServeConfig(
            queue_capacity=8, batch_sizes=(1,), iter_levels=(ITERS, 2),
            recover_patience=2,
        )
        img1 = np.asarray(i1[0])
        img2 = np.asarray(i2[0])
        srv = FlowServer(model, variables, cfg)
        try:
            assert srv._earlyexit_tol == pytest.approx(float(tol))
            rs = [
                srv.submit(img1, img2).result(120) for _ in range(3)
            ]
        finally:
            srv.drain()
        assert [r.status for r in rs] == [STATUS_OK] * 3
        _lr, ref_up, ref_ex = fwd.forward_device(
            i1[:1], i2[:1], ITERS, early_exit_tol=float(tol)
        )
        np.testing.assert_array_equal(rs[0].flow, _pull(ref_up)[0])
        hist = srv._tel.registry.get("serve_exec_iters")
        assert hist is not None and hist.count == 3
        report = srv.report()
        assert report["budget_expected_iters"] == pytest.approx(
            float(_pull(ref_ex)[0])
        )
