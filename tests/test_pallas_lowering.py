"""TPU-target lowering of the Pallas kernels, validated WITHOUT a chip.

Interpret-mode equivalence (test_corr_pallas.py, test_nconv.py) proves
the math; these tests prove the kernels survive the Pallas -> Mosaic
MLIR conversion for a real TPU lowering target (`lowering_platforms=
("tpu",)` runs that conversion on any host) — the layer where dynamic
`pl.ds` slices, SMEM operands, and scratch shapes typically fail
(VERDICT r3 weak #4). The remaining hardware-gated step is only the
Mosaic -> TPU binary compile + execution, covered by tests_tpu/.

Shapes mirror the real workloads: the Sintel fine-tune crop's 1/8-res
feature maps for the corr lookup, full-res 1-2 channel NCUP convs for
the fused NConv, and the 1080p mixed per-level dispatch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.ops import corr_pallas as cpk
from raft_ncup_tpu.ops.geometry import coords_grid
from raft_ncup_tpu.ops.nconv import positivity
from raft_ncup_tpu.ops.nconv_pallas import nconv2d_fused

pytestmark = pytest.mark.skipif(
    cpk.pltpu is None, reason="pallas-tpu unavailable in this jax build"
)


@pytest.fixture(autouse=True)
def _pin_vmem_budget(monkeypatch):
    """The dispatch-count pins below are budget-sensitive (1080p level 1
    misses the default 16 MiB budget by ~1.3%), so test the gating logic
    against the default budget, not the ambient RAFT_NCUP_VMEM_BYTES
    override."""
    from raft_ncup_tpu.ops import nconv_pallas as npk

    monkeypatch.setattr(cpk, "_VMEM_BYTES", 16 * 1024 * 1024)
    monkeypatch.setattr(npk, "_VMEM_BYTES", 16 * 1024 * 1024)


def _lower_for_tpu(fn, *args):
    return jax.jit(fn).trace(*args).lower(
        lowering_platforms=("tpu",)
    ).as_text()


def _count_mosaic_calls(text: str) -> int:
    return text.count("tpu_custom_call")


class TestCorrLowering:
    def test_training_crop_all_levels_lower(self):
        """368x768 crop -> 46x96 1/8-res fmaps, C=256: every pyramid
        level fits VMEM and must emit one Mosaic call."""
        B, H, W, C = 1, 46, 96, 256
        g = np.random.default_rng(0)
        f1 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
        f2 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
        coords = coords_grid(B, H, W)

        cpk.reset_dispatch_counts()
        text = _lower_for_tpu(
            lambda a, b, c: cpk.corr_lookup_pallas(a, b, c, 4, 4, False),
            f1, f2, coords,
        )
        counts = cpk.dispatch_counts()
        assert counts["kernel"] == 4 and counts["fallback"] == 0
        assert _count_mosaic_calls(text) == 4

    def test_1080p_mixed_dispatch_lowers(self):
        """1088x1920 -> 136x240 1/8-res: levels 0 AND 1 exceed the
        default VMEM RESIDENCY budget (level 1's 68x120 padded slab
        needs ~15.29 MB vs the 15.1 MB 0.9x budget) and now take the
        BANDED kernel — the correlation memory wall no longer demotes
        the two largest levels to XLA; levels 2-3 stay resident — and
        the stitched four-kernel graph lowers for a TPU target. Counts
        pinned exactly so a gating change can't pass vacuously."""
        B, H, W, C = 1, 136, 240, 256
        g = np.random.default_rng(1)
        f1 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
        f2 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
        coords = coords_grid(B, H, W)

        cpk.reset_dispatch_counts()
        text = _lower_for_tpu(
            lambda a, b, c: cpk.corr_lookup_pallas(a, b, c, 4, 4, False),
            f1, f2, coords,
        )
        counts = cpk.dispatch_counts()
        assert counts["kernel"] == 2 and counts["banded"] == 2
        assert counts["fallback"] == 0
        assert _count_mosaic_calls(text) == 4

    def test_4k_every_level_qualifies_for_a_kernel_tier(self):
        """The ISSUE-15 residency pin: at 4K (2176x3840 -> 272x480
        1/8-res, C=256) NO pyramid level is forced to the pure-XLA
        fallback by the VMEM budget — at f32 or bf16. Exact tier split
        pinned: f32 = 1 resident + 3 banded, bf16 = 2 + 2 (bf16 halves
        the slab, so one more level re-qualifies for residency)."""
        C = 256
        levels_4k = [(272, 480), (136, 240), (68, 120), (34, 60)]
        expect = {
            None: (1, 3),           # f32: resident, banded
            jnp.bfloat16: (2, 2),   # bf16
        }
        for dtype, (want_res, want_band) in expect.items():
            resident = banded = 0
            for h, w in levels_4k:
                if cpk.fits_vmem(h, w, C, 4, dtype=dtype):
                    resident += 1
                else:
                    plan = cpk.band_plan(h, w, C, 4, dtype=dtype)
                    assert plan is not None, (h, w, dtype)
                    band_rows, n_bands = plan
                    assert cpk._banded_vmem_bytes(
                        h, w, C, 4, band_rows,
                        itemsize=2 if dtype is not None else 4,
                    ) <= int(0.9 * cpk._VMEM_BYTES)
                    banded += 1
            assert (resident, banded) == (want_res, want_band), dtype

    def test_4k_dispatch_counts_pinned_at_trace_time(self):
        """Three-tier accounting at the 4K shape, pinned by an abstract
        trace (eval_shape — dispatch is a trace-time choice, no
        compile, no execution): f32 routes 1 level resident + 3 banded,
        0 fallback."""
        B, H, W, C = 1, 272, 480, 256
        f1 = jax.ShapeDtypeStruct((B, H, W, C), jnp.float32)
        f2 = jax.ShapeDtypeStruct((B, H, W, C), jnp.float32)
        cds = jax.ShapeDtypeStruct((B, H, W, 2), jnp.float32)

        cpk.reset_dispatch_counts()
        jax.eval_shape(
            lambda a, b, c: cpk.corr_lookup_pallas(a, b, c, 4, 4, False),
            f1, f2, cds,
        )
        counts = cpk.dispatch_counts()
        assert counts["kernel"] == 1 and counts["banded"] == 3
        assert counts["fallback"] == 0 and counts["levels_total"] == 4

    def test_gradient_graph_lowers(self):
        """The custom-VJP backward graph must lower for TPU too."""
        B, H, W, C = 1, 16, 24, 64
        g = np.random.default_rng(2)
        f1 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
        f2 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
        coords = coords_grid(B, H, W)

        def loss(a, b, c):
            return (cpk.corr_lookup_pallas(a, b, c, 4, 2, False) ** 2).sum()

        text = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), f1, f2, coords)
        assert text  # lowering itself is the assertion


class TestFullModelLowering:
    def test_flagship_forward_lowers_with_both_kernels(self, monkeypatch):
        """The integration the chip will actually run: the FULL flagship
        raft_nc_dbl forward, corr_impl='pallas' + nconv impl 'pallas',
        lowered for a TPU target with the kernels fused in (not
        interpret mode). Abstract init (eval_shape) + ShapeDtypeStruct
        args — nothing executes on the CPU host."""
        from raft_ncup_tpu.config import flagship_config
        from raft_ncup_tpu.models import get_model
        from raft_ncup_tpu.utils import runtime

        # The model and nconv2d gate Mosaic on the *current* backend;
        # pretend it is TPU-class so the lowered graph takes the real
        # kernel paths (interpret=False) rather than the interpreter.
        monkeypatch.setattr(runtime, "is_tpu_class_backend", lambda: True)
        monkeypatch.setenv("RAFT_NCUP_NCONV_IMPL", "pallas")

        model = get_model(
            flagship_config(dataset="sintel", corr_impl="pallas")
        )
        shape = (1, 96, 128, 3)
        variables = jax.eval_shape(
            lambda k: model.init(k, shape), jax.random.PRNGKey(0)
        )
        img = jax.ShapeDtypeStruct(shape, jnp.float32)

        def fwd(v, a, b):
            return model.apply(v, a, b, iters=2, test_mode=True)

        text = jax.jit(fwd).trace(variables, img, img).lower(
            lowering_platforms=("tpu",)
        ).as_text()
        # At 96x128 (12x16 1/8-res fmaps) every corr level fits VMEM and
        # the NCUP convs pass their gate: Mosaic calls must be present.
        assert _count_mosaic_calls(text) > 0


class TestNConvLowering:
    # Only shapes the dispatch gate actually routes to the kernel
    # (nconv_pallas.fits_vmem at the default 16 MiB budget): full-res
    # k=5/k=1 passes; the k=3 two-channel conv only fits at the UNet's
    # downsampled half resolution.
    @pytest.mark.parametrize("k,cin,cout,h,w", [
        (5, 1, 2, 368, 768),
        (3, 2, 2, 184, 384),
        (1, 2, 1, 368, 768),
    ])
    def test_flagship_shapes_lower(self, k, cin, cout, h, w):
        """NCUP convs at the shapes the gate dispatches to the kernel —
        the NConvUNet runs these 12x per forward."""
        from raft_ncup_tpu.ops.nconv_pallas import fits_vmem, supported

        assert supported((k, k, cin, cout), stride=1, groups=1)
        assert fits_vmem(h, w, cin, cout, k)
        g = np.random.default_rng(3)
        data = jnp.asarray(g.normal(size=(2, h, w, cin)), jnp.float32)
        conf = jnp.asarray(g.random((2, h, w, cin)), jnp.float32)
        wt = positivity(
            jnp.asarray(g.normal(size=(k, k, cin, cout)), jnp.float32)
        )
        b = jnp.asarray(g.normal(size=(cout,)), jnp.float32)
        text = _lower_for_tpu(
            lambda d, c, w, b: nconv2d_fused(d, c, w, b, 1e-20, False),
            data, conf, wt, b,
        )
        assert _count_mosaic_calls(text) == 1

    def test_gradient_graph_lowers(self):
        g = np.random.default_rng(4)
        data = jnp.asarray(g.normal(size=(1, 32, 48, 1)), jnp.float32)
        conf = jnp.asarray(g.random((1, 32, 48, 1)), jnp.float32)
        w = positivity(
            jnp.asarray(g.normal(size=(3, 3, 1, 2)), jnp.float32)
        )
        b = jnp.asarray(g.normal(size=(2,)), jnp.float32)

        def loss(d, c, w, b):
            out, co = nconv2d_fused(d, c, w, b, 1e-20, False)
            return (out ** 2).sum() + (co ** 2).sum()

        text = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2, 3)),
                              data, conf, w, b)
        assert text
