"""Reference-keyed checkpoint EXPORT (VERDICT r4 #5).

The inverse of the import path: our variables serialize to the exact
state dict the PyTorch reference's strict ``load_state_dict`` consumes
(reference: evaluate.py:246-257 — DataParallel wrap, strict load), so a
model trained in this framework drops into the reference unchanged.
Validated three ways: exact key-set equality against the real reference
models, a strict torch-side load + full-model forward parity on exported
random weights, and a lossless import(export(v)) round trip.
"""

import argparse
import os
import sys

import numpy as np
import pytest

REFERENCE = "/root/reference"
pytestmark = [
    pytest.mark.reference,
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REFERENCE, "core")),
        reason="reference repo not mounted",
    ),
]

if os.path.isdir(os.path.join(REFERENCE, "core")):
    sys.path.insert(0, os.path.join(REFERENCE, "core"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from raft_ncup_tpu.config import ModelConfig  # noqa: E402
from raft_ncup_tpu.models import RAFT  # noqa: E402
from raft_ncup_tpu.utils.torch_export import (  # noqa: E402
    export_torch_state,
    save_torch_checkpoint,
)
from raft_ncup_tpu.utils.torch_import import import_torch_state  # noqa: E402

from test_torch_parity import (  # noqa: E402
    base_args,
    make_pair,
    ncup_args,
    run_reference,
)


def _ref_model(variant: str, small: bool = False, dataset: str = "sintel"):
    if variant == "raft":
        from raft import RAFT as TorchRAFT

        return TorchRAFT(base_args(small=small))
    from raft_nc_dbl import RAFT as TorchNCUP

    return TorchNCUP(ncup_args(dataset=dataset))


@pytest.mark.parametrize(
    "variant,small,dataset",
    [
        ("raft", False, "chairs"),
        ("raft", True, "chairs"),
        ("raft_nc_dbl", False, "sintel"),
        ("raft_nc_dbl", False, "kitti"),
    ],
)
def test_export_key_set_matches_reference(variant, small, dataset):
    """Every key the reference model's strict load expects, no extras —
    including the regenerated aliases (num_batches_tracked, duplicate
    downsample norms, shared-encoder aliases)."""
    import torch

    torch.manual_seed(0)
    tmodel = _ref_model(variant, small, dataset)
    want = set(tmodel.state_dict().keys())

    ours = RAFT(ModelConfig(variant=variant, small=small, dataset=dataset))
    variables = ours.init(jax.random.key(0), (1, 64, 96, 3))
    got = set(export_torch_state(variables).keys())
    # num_batches_tracked is a torch buffer with no flax counterpart;
    # everything else must match exactly too.
    assert got == want


def test_strict_torch_load_and_forward_parity():
    """The reference model strict-loads our exported random weights and
    computes the same flow (the parity harness run in reverse)."""
    import torch

    ours = RAFT(ModelConfig(variant="raft_nc_dbl", dataset="sintel"))
    variables = ours.init(jax.random.key(5), (1, 128, 160, 3))
    state = {
        k: torch.from_numpy(np.ascontiguousarray(v))
        for k, v in export_torch_state(variables).items()
    }
    tmodel = _ref_model("raft_nc_dbl")
    tmodel.load_state_dict(state, strict=True)  # raises on any mismatch

    img1, img2 = make_pair(3)
    t_lr, t_up = run_reference(tmodel, img1, img2, iters=2)
    j_lr, j_up = ours.apply(
        variables, jnp.asarray(img1), jnp.asarray(img2), iters=2,
        test_mode=True,
    )
    np.testing.assert_allclose(np.asarray(j_lr), t_lr, atol=2e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(j_up), t_up, atol=5e-3, rtol=1e-3)


@pytest.mark.parametrize("variant", ["raft", "raft_nc_dbl"])
def test_import_export_round_trip(variant):
    """import(export(v)) == v bit-for-bit (float32 both ways)."""
    ours = RAFT(ModelConfig(variant=variant, dataset="sintel"))
    variables = ours.init(jax.random.key(2), (1, 64, 96, 3))
    exported = export_torch_state(variables)
    fresh = ours.init(jax.random.key(9), (1, 64, 96, 3))
    back = import_torch_state(exported, fresh, strict=True)

    flat_a = jax.tree_util.tree_leaves_with_path(variables)
    flat_b = jax.tree_util.tree_leaves_with_path(back)
    assert len(flat_a) == len(flat_b)
    for (pa, va), (pb, vb) in zip(flat_a, flat_b):
        assert pa == pb
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=str(pa)
        )


def test_save_torch_checkpoint_reference_eval_load(tmp_path):
    """The saved .pth file loads into the reference exactly as its eval
    driver does: torch.load + DataParallel-keyed strict load_state_dict
    (reference: evaluate.py:246-257)."""
    import torch

    ours = RAFT(ModelConfig(variant="raft_nc_dbl", dataset="kitti"))
    variables = ours.init(jax.random.key(4), (1, 64, 96, 3))
    path = str(tmp_path / "ours_export.pth")
    save_torch_checkpoint(path, variables, data_parallel=True)

    tmodel = torch.nn.DataParallel(_ref_model("raft_nc_dbl", dataset="kitti"))
    loaded = torch.load(path, map_location="cpu", weights_only=True)
    tmodel.load_state_dict(loaded, strict=True)
