"""Unit tests for raft_ncup_tpu/resilience/: retry + quarantine, the
divergence sentinel (pure and folded into the real jitted step), chaos
primitives, preemption handler, and the checkpoint metadata / leak-fix
satellites. End-to-end chaos runs through train.main live in
tests/test_chaos_train.py."""

from __future__ import annotations

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from raft_ncup_tpu.config import TrainConfig, small_model_config
from raft_ncup_tpu.resilience import (
    ChaosDataset,
    ChaosSpec,
    PreemptionHandler,
    RetryStats,
    chaos_batches,
    guard_update,
    init_sentinel,
    resume_metadata,
    retry_io,
)
from raft_ncup_tpu.training.state import TrainState


# ------------------------------------------------------------------ retry


class TestRetryIO:
    def test_backoff_then_success(self):
        calls, delays = [], []
        stats = RetryStats()

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = retry_io(
            flaky, attempts=3, base_delay_s=0.05, stats=stats,
            sleep=delays.append,
        )
        assert out == "ok"
        assert stats.retries == 2 and stats.giveups == 0
        assert delays == [0.05, 0.1]  # exponential

    def test_bounded_giveup_reraises_original(self):
        stats = RetryStats()

        def doomed():
            raise OSError("disk on fire")

        with pytest.raises(OSError, match="disk on fire"):
            retry_io(doomed, attempts=2, stats=stats, sleep=lambda _: None)
        assert stats.retries == 2 and stats.giveups == 1
        assert not stats.clean

    def test_non_retryable_exception_passes_through(self):
        def typo():
            raise ValueError("not IO")

        with pytest.raises(ValueError):
            retry_io(typo, attempts=5, sleep=lambda _: None)

    def test_delay_caps_at_max(self):
        delays = []

        def doomed():
            raise OSError("x")

        with pytest.raises(OSError):
            retry_io(
                doomed, attempts=6, base_delay_s=0.5, max_delay_s=1.0,
                sleep=delays.append,
            )
        assert delays == [0.5, 1.0, 1.0, 1.0, 1.0, 1.0]


# ------------------------------------------------------------------ chaos


class TestChaosSpec:
    def test_parse_roundtrip(self):
        spec = ChaosSpec.parse("nan@3,nan@7, ioerror@2,sigterm@5")
        assert spec.nan_steps == frozenset({3, 7})
        assert spec.ioerror_reads == frozenset({2})
        assert spec.sigterm_after == 5
        assert spec.active
        assert spec.render() == "nan@3,nan@7,ioerror@2,sigterm@5"

    def test_empty_spec_inactive(self):
        assert not ChaosSpec.parse(None).active
        assert not ChaosSpec.parse("").active

    def test_bad_tokens_raise(self):
        with pytest.raises(ValueError, match="bad chaos event"):
            ChaosSpec.parse("explode@3")
        with pytest.raises(ValueError):
            ChaosSpec.parse("nan7")


def test_chaos_batches_poisons_exactly_the_configured_step():
    batches = [
        {"flow": np.zeros((1, 2, 2, 2), np.float32), "valid": np.ones(1)}
        for _ in range(4)
    ]
    out = list(chaos_batches(iter(batches), frozenset({6}), start_step=5))
    assert len(out) == 4
    # Stream position 1 == step 6: poisoned, copy-on-write.
    assert np.isnan(out[1]["flow"]).all()
    assert not np.isnan(batches[1]["flow"]).any()  # original untouched
    for i in (0, 2, 3):
        assert out[i] is batches[i]  # pass-through, no copies


class _StubDataset:
    """6 samples; flow encodes the index so substitution is observable."""

    def __init__(self, poisoned=()):
        self.poisoned = set(poisoned)
        self.is_test = False

    def __len__(self):
        return 6

    def sample(self, index, rng=None):
        if index in self.poisoned:
            raise OSError(f"unreadable sample {index}")
        return {
            "image1": np.zeros((4, 4, 3), np.uint8),
            "image2": np.zeros((4, 4, 3), np.uint8),
            "flow": np.full((4, 4, 2), float(index), np.float32),
            "valid": np.ones((4, 4), np.float32),
        }


def test_chaos_dataset_injects_ioerror_on_nth_read():
    ds = ChaosDataset(_StubDataset(), frozenset({1}))
    assert len(ds) == 6
    assert ds.is_test is False  # attribute pass-through
    ds.sample(0)  # read 0: fine
    with pytest.raises(IOError, match="injected IOError on dataset read 1"):
        ds.sample(0)  # read 1: injected
    ds.sample(0)  # read 2: fine again — count-based, deterministic


# ------------------------------------------------- loader retry/quarantine


def test_flow_loader_retries_transient_and_quarantines_poison():
    from raft_ncup_tpu.data.loader import FlowLoader

    # Index 2 is permanently poisoned; everything else reads fine.
    loader = FlowLoader(
        _StubDataset(poisoned={2}),
        batch_size=2,
        shuffle=False,
        num_workers=1,
        shard_index=0,
        num_shards=1,
        io_retries=2,
        io_retry_backoff_s=0.0,
    )
    batches = loader.batches(start_epoch=0, start_batch=0)
    first_epoch = [next(batches) for _ in range(3)]  # 6 samples / 2
    second_epoch = [next(batches) for _ in range(3)]
    batches.close()

    # Batches keep their shape; index 2 was substituted by index 3.
    flows = sorted(
        float(b["flow"][i, 0, 0, 0])
        for b in first_epoch
        for i in range(2)
    )
    assert flows == [0.0, 1.0, 3.0, 3.0, 4.0, 5.0]
    # Accounting: io_retries failed attempts, then quarantine.
    assert loader.retry_stats.retries == 2
    assert loader.retry_stats.quarantined == [2]
    assert not loader.retry_stats.clean
    # Second epoch: the quarantined index short-circuits to the
    # substitute without burning retries again.
    assert loader.retry_stats.retries == 2
    flows2 = sorted(
        float(b["flow"][i, 0, 0, 0])
        for b in second_epoch
        for i in range(2)
    )
    assert flows2 == [0.0, 1.0, 3.0, 3.0, 4.0, 5.0]


def test_flow_loader_substitute_read_also_retries_and_quarantines():
    """The substitute path is covered by the same retry/quarantine
    policy: a poisoned substitute is quarantined too and the next
    candidate is used — a flaky stand-in must not kill the run the
    quarantine exists to protect."""
    from raft_ncup_tpu.data.loader import FlowLoader

    loader = FlowLoader(
        _StubDataset(poisoned={2, 3}),
        batch_size=2,
        shuffle=False,
        num_workers=1,
        shard_index=0,
        num_shards=1,
        io_retries=1,
        io_retry_backoff_s=0.0,
    )
    batches = loader.batches(start_epoch=0, start_batch=0)
    epoch = [next(batches) for _ in range(3)]
    batches.close()
    flows = sorted(
        float(b["flow"][i, 0, 0, 0]) for b in epoch for i in range(2)
    )
    # Indices 2 AND 3 both land on substitute 4.
    assert flows == [0.0, 1.0, 4.0, 4.0, 4.0, 5.0]
    assert sorted(loader.retry_stats.quarantined) == [2, 3]


def test_flow_loader_substitute_stays_inside_host_shard():
    """On a sharded loader, a quarantined sample's stand-in must come
    from THIS host's shard — an index another host also serves would let
    a multihost global batch double-load a sample."""
    from raft_ncup_tpu.data.loader import FlowLoader

    loader = FlowLoader(
        _StubDataset(poisoned={2}),
        batch_size=1,
        shuffle=False,
        num_workers=1,
        shard_index=0,
        num_shards=2,  # this host owns indices 0, 2, 4
        io_retries=0,
        io_retry_backoff_s=0.0,
    )
    batches = loader.batches(start_epoch=0, start_batch=0)
    flows = [float(next(batches)["flow"][0, 0, 0, 0]) for _ in range(3)]
    batches.close()
    # Index 2 substitutes with 4 (the shard's next index), NOT 3
    # (host 1's sample).
    assert flows == [0.0, 4.0, 4.0]
    assert loader.retry_stats.quarantined == [2]


def test_flow_loader_all_quarantined_raises_clearly():
    """Every sample unreadable = the data source is gone, not flaky:
    the loader must surface a clear error, not spin forever."""
    from raft_ncup_tpu.data.loader import FlowLoader

    loader = FlowLoader(
        _StubDataset(poisoned={0, 1, 2, 3, 4, 5}),
        batch_size=2,
        shuffle=False,
        num_workers=1,
        shard_index=0,
        num_shards=1,
        io_retries=0,
        io_retry_backoff_s=0.0,
    )
    batches = loader.batches(start_epoch=0, start_batch=0)
    with pytest.raises(RuntimeError, match="quarantined"):
        next(batches)
    batches.close()


# --------------------------------------------------------------- sentinel


def _tiny_state() -> TrainState:
    params = {"w": jnp.ones((3,), jnp.float32)}
    tx = optax.sgd(0.1)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
        tx=tx,
        sentinel=init_sentinel(),
    )


_CFG = TrainConfig(
    anomaly_sentinel=True, sentinel_spike_factor=20.0,
    sentinel_ema_decay=0.99, sentinel_warmup=2, sentinel_halt_after=3,
)


class TestGuardUpdate:
    def test_nonfinite_step_is_skipped_bitwise(self):
        state = _tiny_state()
        new = state.apply_gradients({"w": jnp.full((3,), jnp.nan)})
        guarded, m = guard_update(
            state, new, jnp.float32(jnp.nan), jnp.float32(jnp.nan), _CFG
        )
        np.testing.assert_array_equal(
            np.asarray(guarded.params["w"]), np.ones(3, np.float32)
        )
        sen = jax.device_get(guarded.sentinel)
        assert int(sen["skipped"]) == 1 and int(sen["consecutive"]) == 1
        assert float(m["bad_step"]) == 1.0
        # Attempted-step counter still advances (data-stream position).
        assert int(guarded.step) == 1

    def test_good_step_passes_through_bitwise(self):
        state = _tiny_state()
        new = state.apply_gradients({"w": jnp.full((3,), 0.5)})
        guarded, m = guard_update(
            state, new, jnp.float32(1.0), jnp.float32(0.5), _CFG
        )
        np.testing.assert_array_equal(
            np.asarray(guarded.params["w"]), np.asarray(new.params["w"])
        )
        sen = jax.device_get(guarded.sentinel)
        assert int(sen["skipped"]) == 0 and int(sen["good"]) == 1
        assert float(sen["ema_grad_norm"]) == 0.5  # first good step seeds
        assert float(m["bad_step"]) == 0.0

    def test_grad_norm_spike_is_skipped_after_warmup(self):
        state = _tiny_state()
        # Warm the EMA: sentinel_warmup good steps at grad_norm 1.0.
        for _ in range(_CFG.sentinel_warmup):
            new = state.apply_gradients({"w": jnp.full((3,), 0.01)})
            state, _ = guard_update(
                state, new, jnp.float32(1.0), jnp.float32(1.0), _CFG
            )
        before = np.asarray(state.params["w"]).copy()
        new = state.apply_gradients({"w": jnp.full((3,), 5.0)})
        state, m = guard_update(
            state, new, jnp.float32(1.0), jnp.float32(1000.0), _CFG
        )
        assert float(m["bad_step"]) == 1.0
        np.testing.assert_array_equal(np.asarray(state.params["w"]), before)
        # A merely-large (not spiking) step passes.
        new = state.apply_gradients({"w": jnp.full((3,), 0.01)})
        state, m = guard_update(
            state, new, jnp.float32(1.0), jnp.float32(5.0), _CFG
        )
        assert float(m["bad_step"]) == 0.0

    def test_consecutive_counts_and_resets(self):
        state = _tiny_state()
        nan = jnp.float32(jnp.nan)
        for expect in (1, 2):
            new = state.apply_gradients({"w": jnp.full((3,), jnp.nan)})
            state, _ = guard_update(state, new, nan, nan, _CFG)
            assert int(jax.device_get(state.sentinel["consecutive"])) == expect
        new = state.apply_gradients({"w": jnp.full((3,), 0.1)})
        state, _ = guard_update(
            state, new, jnp.float32(1.0), jnp.float32(1.0), _CFG
        )
        sen = jax.device_get(state.sentinel)
        assert int(sen["consecutive"]) == 0 and int(sen["skipped"]) == 2


def test_sentinel_in_real_jitted_step_skips_nan_batch():
    """The sentinel folded into make_train_step, against the real small
    model: a NaN batch leaves params AND optimizer moments bitwise
    unchanged, the run continues, and the next good step trains."""
    from raft_ncup_tpu.parallel.step import make_train_step
    from raft_ncup_tpu.training.state import create_train_state

    B, H, W = 2, 16, 24
    mcfg = small_model_config(variant="raft")
    tcfg = TrainConfig(
        stage="chairs", lr=1e-4, num_steps=50, batch_size=B,
        image_size=(H, W), iters=2, anomaly_sentinel=True,
    )
    model, state = create_train_state(jax.random.key(0), mcfg, tcfg)
    assert state.sentinel is not None
    step = make_train_step(model, tcfg)
    g = np.random.default_rng(0)

    def batch(nan=False):
        flow = g.standard_normal((B, H, W, 2)).astype(np.float32)
        if nan:
            flow[...] = np.nan
        return {
            "image1": g.uniform(0, 255, (B, H, W, 3)).astype(np.float32),
            "image2": g.uniform(0, 255, (B, H, W, 3)).astype(np.float32),
            "flow": flow,
            "valid": np.ones((B, H, W), np.float32),
        }

    state, m = step(state, batch(), jax.random.key(1))
    assert float(m["bad_step"]) == 0.0
    params_snap = [np.array(x) for x in jax.tree.leaves(state.params)]
    opt_snap = [np.array(x) for x in jax.tree.leaves(state.opt_state)]

    state, m = step(state, batch(nan=True), jax.random.key(2))
    assert float(m["bad_step"]) == 1.0
    for a, b in zip(params_snap, jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(a, np.asarray(b))
    for a, b in zip(opt_snap, jax.tree.leaves(state.opt_state)):
        np.testing.assert_array_equal(a, np.asarray(b))
    sen = jax.device_get(state.sentinel)
    assert int(sen["skipped"]) == 1 and int(sen["consecutive"]) == 1
    assert int(state.step) == 2  # attempted steps keep counting

    state, m = step(state, batch(), jax.random.key(3))
    assert float(m["bad_step"]) == 0.0
    changed = any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(params_snap, jax.tree.leaves(state.params))
    )
    assert changed  # the good step trained
    assert int(jax.device_get(state.sentinel["consecutive"])) == 0


# ------------------------------------------------------------- preemption


def test_preemption_handler_flag_poll_and_restore():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as h:
        assert not h.poll(0)
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.requested
        assert h.poll(17)  # single-process: any step boundary sees it
    assert signal.getsignal(signal.SIGTERM) is prev  # restored on exit


def test_resume_metadata_fields():
    meta = resume_metadata(
        small_model_config("raft", dataset="chairs"),
        TrainConfig(seed=77),
    )
    assert meta["model_variant"] == "raft"
    assert meta["seed"] == 77
    assert len(meta["config_fingerprint"]) == 16
    # Any model-config change moves the fingerprint.
    other = resume_metadata(
        small_model_config("raft", dataset="sintel"), TrainConfig(seed=77)
    )
    assert other["config_fingerprint"] != meta["config_fingerprint"]


# --------------------------------------------- checkpoint metadata + leak


class TestCheckpointMetadata:
    def test_mismatch_fails_with_clear_message(self, tmp_path):
        from raft_ncup_tpu.training.checkpoint import CheckpointManager

        state = _tiny_state().replace(step=jnp.asarray(3, jnp.int32))
        tcfg = TrainConfig(seed=1)
        meta = resume_metadata(small_model_config("raft"), tcfg)
        mgr = CheckpointManager(str(tmp_path / "run"), metadata=meta)
        mgr.save(state)
        mgr.wait()
        assert mgr.saved_metadata() == meta
        mgr.close()

        wrong = resume_metadata(
            small_model_config("raft_nc_dbl"), TrainConfig(seed=2)
        )
        mgr2 = CheckpointManager(str(tmp_path / "run"), metadata=wrong)
        with pytest.raises(ValueError, match="resume metadata mismatch"):
            mgr2.restore(state)
        try:
            mgr2.restore(state)
        except ValueError as e:
            msg = str(e)
            assert "model_variant" in msg and "seed" in msg
            assert "config_fingerprint" in msg
        mgr2.close()

        # Matching metadata restores fine (sentinel counters round-trip).
        mgr3 = CheckpointManager(str(tmp_path / "run"), metadata=meta)
        restored = mgr3.restore(state)
        assert int(restored.step) == 3
        mgr3.close()

    def test_pre_sentinel_checkpoint_restores(self, tmp_path):
        """A checkpoint written by the pre-resilience code (payload
        without the 'sentinel' subtree) must still restore — into a
        sentinel-enabled state with fresh zeroed counters — instead of
        dying on an orbax structure mismatch."""
        import orbax.checkpoint as ocp

        from raft_ncup_tpu.training.checkpoint import CheckpointManager

        state = _tiny_state()
        old_payload = {
            "step": np.asarray(4),
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        raw = ocp.CheckpointManager(
            str(tmp_path / "old"),
            options=ocp.CheckpointManagerOptions(create=True),
        )
        raw.save(4, args=ocp.args.StandardSave(old_payload))
        raw.wait_until_finished()
        raw.close()

        mgr = CheckpointManager(str(tmp_path / "old"))
        restored = mgr.restore(state)
        mgr.close()
        assert int(restored.step) == 4
        sen = jax.device_get(restored.sentinel)
        assert int(sen["skipped"]) == 0  # fresh counters, not garbage

    def test_save_retries_transient_oserror(self, tmp_path, monkeypatch):
        from raft_ncup_tpu.training.checkpoint import CheckpointManager

        state = _tiny_state()
        mgr = CheckpointManager(str(tmp_path / "run"))
        real_save = mgr._mgr.save
        attempts = []

        def flaky_save(*a, **kw):
            attempts.append(1)
            if len(attempts) == 1:
                raise OSError("transient fs stall")
            return real_save(*a, **kw)

        monkeypatch.setattr(mgr._mgr, "save", flaky_save)
        mgr.save(state, step=1)
        mgr.wait()
        assert mgr.latest_step == 1
        assert mgr.retry_stats.retries == 1
        mgr.close()


def test_restore_variables_closes_manager_on_failure(tmp_path, monkeypatch):
    """Satellite fix: the orbax manager must not leak when restore (or
    the empty-directory check) raises."""
    import raft_ncup_tpu.training.checkpoint as ckpt_mod

    closed = []

    class FakeMgr:
        def __init__(self, *a, **kw):
            pass

        def latest_step(self):
            return 3

        def restore(self, step, args=None):
            raise RuntimeError("corrupt checkpoint")

        def close(self):
            closed.append("closed")

    monkeypatch.setattr(ckpt_mod.ocp, "CheckpointManager", FakeMgr)
    with pytest.raises(RuntimeError, match="corrupt"):
        ckpt_mod.restore_variables(str(tmp_path))
    assert closed == ["closed"]

    class EmptyMgr(FakeMgr):
        def latest_step(self):
            return None

    closed.clear()
    monkeypatch.setattr(ckpt_mod.ocp, "CheckpointManager", EmptyMgr)
    with pytest.raises(FileNotFoundError):
        ckpt_mod.restore_variables(str(tmp_path))
    assert closed == ["closed"]
