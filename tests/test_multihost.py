"""Multi-host helpers under the single-process 8-device CPU mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.parallel import (
    batch_sharding,
    global_batch,
    initialize_distributed,
    is_multihost,
    make_mesh,
)


class TestMultihost:
    def test_initialize_is_noop_single_process(self):
        initialize_distributed()  # must not raise
        assert not is_multihost()

    def test_global_batch_shards_over_mesh(self):
        mesh = make_mesh(data=4, spatial=2)
        shardings = batch_sharding(mesh)
        B, H, W = 4, 16, 24
        batch = {
            "image1": np.zeros((B, H, W, 3), np.uint8),
            "image2": np.zeros((B, H, W, 3), np.uint8),
            "flow": np.zeros((B, H, W, 2), np.float32),
            "valid": np.ones((B, H, W), np.float32),
            "extra_info": ["a"] * B,  # passes through unsharded
        }
        out = global_batch(batch, mesh, shardings)
        assert out["extra_info"] == ["a"] * B
        img = out["image1"]
        assert isinstance(img, jax.Array)
        assert img.shape == (B, H, W, 3)
        assert img.sharding == shardings["image1"]
        # Each device holds a (1, 8, 24, 3) shard.
        shard_shapes = {s.data.shape for s in img.addressable_shards}
        assert shard_shapes == {(1, 8, 24, 3)}

    def test_sharded_batch_feeds_train_step(self):
        from raft_ncup_tpu.config import TrainConfig, small_model_config
        from raft_ncup_tpu.parallel import make_train_step
        from raft_ncup_tpu.training.state import create_train_state

        mesh = make_mesh(data=2, spatial=1, devices=jax.devices()[:2])
        mcfg = small_model_config("raft", dataset="chairs")
        tcfg = TrainConfig(
            stage="chairs", batch_size=2, image_size=(16, 32), iters=1,
            num_steps=5,
        )
        model, state = create_train_state(
            jax.random.PRNGKey(0), mcfg, tcfg, (1, 16, 32, 3)
        )
        step = make_train_step(model, tcfg, mesh=mesh)
        g = np.random.default_rng(0)
        batch = global_batch(
            {
                "image1": g.uniform(0, 255, (2, 16, 32, 3)).astype(np.float32),
                "image2": g.uniform(0, 255, (2, 16, 32, 3)).astype(np.float32),
                "flow": g.normal(size=(2, 16, 32, 2)).astype(np.float32),
                "valid": np.ones((2, 16, 32), np.float32),
            },
            mesh,
            batch_sharding(mesh),
        )
        state, metrics = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_four_process_distributed_train_step(tmp_path):
    """VERDICT r3 #6 + r4 #4 + r5 weak #5: exercise
    initialize_distributed's NON-trivial branch with a real 4-process
    jax.distributed runtime — each process owns two virtual CPU devices
    (XLA's CPU cross-process collectives want symmetric multi-device
    hosts), one sharded train step runs over the 8-device global mesh,
    and all four processes must agree on the loss (SPMD). Then the
    output-hygiene matrix against one SHARED tmpdir:

    - validation host-shards the frames (``_HostShard``: 6 frames over
      4 hosts = shard lengths [2, 2, 1, 1]), every frame is decoded by
      EXACTLY one process, metric sums all-reduce to identical global
      metrics everywhere, and the console line prints once;
    - the submission path (real ``create_sintel_submission`` over a
      stubbed 2-sequence dataset, warm start on — the device splat runs
      multi-process too) writes each .flo file exactly once, from the
      main process only;
    - exactly one process writes log.txt.

    The children ride the fleet tier's :class:`ChildProcess` lifecycle
    (raft_ncup_tpu/fleet/replica.py) — the 4-process distributed rig
    and the replica supervisor share ONE spawn/liveness/reap
    implementation instead of two (docs/FLEET.md)."""
    import json
    import socket
    import sys

    from raft_ncup_tpu.fleet import ChildProcess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    nprocs = 4
    child = os.path.join(os.path.dirname(__file__), "_distributed_child.py")
    env = dict(os.environ)
    # The children build their own 2-device CPU platforms; drop the
    # conftest's 8-device flag so it doesn't override theirs.
    env["XLA_FLAGS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    run_dir = str(tmp_path / "shared_run")

    procs = [
        ChildProcess(
            [sys.executable, child, str(port), str(pid), run_dir,
             str(nprocs)],
            name=f"dist-{pid}",
            env=env,
        ).spawn()
        for pid in range(nprocs)
    ]
    # reap() bounds the wait and escalates to SIGKILL itself — the
    # drain/reap half of the shared lifecycle contract. ONE deadline
    # spans the pod: a wedged rig costs ~540 s total, not 540 s per
    # child (the old communicate-timeout semantics, preserved).
    import time as time_mod

    deadline = time_mod.monotonic() + 540
    outs = [
        p.reap(timeout=max(1.0, deadline - time_mod.monotonic()))
        for p in procs
    ]

    def field(out: str, prefix: str) -> str:
        return next(
            l[len(prefix):] for l in out.splitlines() if l.startswith(prefix)
        )

    losses, vals, actives, val_prints = [], [], [], 0
    validated, subwrites = [], []
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\n{out}\n{err[-2000:]}"
        losses.append(float(field(out, "LOSS=")))
        vals.append(field(out, "VAL="))
        actives.append(int(field(out, "LOGACTIVE=")))
        validated.append(json.loads(field(out, "VALIDATED=")))
        subwrites.append(int(field(out, "SUBWRITES=")))
        val_prints += sum(
            1 for l in out.splitlines() if l.startswith("Validation Synthetic")
        )
    assert all(
        losses[0] == pytest.approx(x, rel=1e-6) for x in losses[1:]
    )
    # Host-sharded validation reduced to IDENTICAL global metrics.
    assert all(v == vals[0] for v in vals[1:])
    # Every frame validated EXACTLY once across the pod: the shards are
    # disjoint and their union is the whole agreed dataset.
    flat = [i for shard in validated for i in shard]
    assert sorted(flat) == list(range(6)), validated
    assert [len(s) for s in validated] == [2, 2, 1, 1]
    # One writer per pod: only the main process touched the submission
    # tree, and every expected file exists exactly once on the shared
    # disk (2 dstypes x 2 sequences x 2 frames).
    assert subwrites[0] > 0 and subwrites[1:] == [0] * (nprocs - 1)
    flo_files = sorted(
        os.path.relpath(os.path.join(root, f), run_dir)
        for root, _, files in os.walk(
            os.path.join(run_dir, "submission")
        )
        for f in files
        if f.endswith(".flo")
    )
    assert len(flo_files) == subwrites[0] == 8, flo_files
    # Console line from exactly one process; exactly one log.txt writer.
    assert val_prints == 1
    assert sorted(actives) == [0, 0, 0, 1]
    log = (tmp_path / "shared_run" / "log.txt").read_text()
    assert log.count("hello from process") == 1
