"""Multi-host helpers under the single-process 8-device CPU mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.parallel import (
    batch_sharding,
    global_batch,
    initialize_distributed,
    is_multihost,
    make_mesh,
)


class TestMultihost:
    def test_initialize_is_noop_single_process(self):
        initialize_distributed()  # must not raise
        assert not is_multihost()

    def test_global_batch_shards_over_mesh(self):
        mesh = make_mesh(data=4, spatial=2)
        shardings = batch_sharding(mesh)
        B, H, W = 4, 16, 24
        batch = {
            "image1": np.zeros((B, H, W, 3), np.uint8),
            "image2": np.zeros((B, H, W, 3), np.uint8),
            "flow": np.zeros((B, H, W, 2), np.float32),
            "valid": np.ones((B, H, W), np.float32),
            "extra_info": ["a"] * B,  # passes through unsharded
        }
        out = global_batch(batch, mesh, shardings)
        assert out["extra_info"] == ["a"] * B
        img = out["image1"]
        assert isinstance(img, jax.Array)
        assert img.shape == (B, H, W, 3)
        assert img.sharding == shardings["image1"]
        # Each device holds a (1, 8, 24, 3) shard.
        shard_shapes = {s.data.shape for s in img.addressable_shards}
        assert shard_shapes == {(1, 8, 24, 3)}

    def test_sharded_batch_feeds_train_step(self):
        from raft_ncup_tpu.config import TrainConfig, small_model_config
        from raft_ncup_tpu.parallel import make_train_step
        from raft_ncup_tpu.training.state import create_train_state

        mesh = make_mesh(data=2, spatial=1, devices=jax.devices()[:2])
        mcfg = small_model_config("raft", dataset="chairs")
        tcfg = TrainConfig(
            stage="chairs", batch_size=2, image_size=(16, 32), iters=1,
            num_steps=5,
        )
        model, state = create_train_state(
            jax.random.PRNGKey(0), mcfg, tcfg, (1, 16, 32, 3)
        )
        step = make_train_step(model, tcfg, mesh=mesh)
        g = np.random.default_rng(0)
        batch = global_batch(
            {
                "image1": g.uniform(0, 255, (2, 16, 32, 3)).astype(np.float32),
                "image2": g.uniform(0, 255, (2, 16, 32, 3)).astype(np.float32),
                "flow": g.normal(size=(2, 16, 32, 2)).astype(np.float32),
                "valid": np.ones((2, 16, 32), np.float32),
            },
            mesh,
            batch_sharding(mesh),
        )
        state, metrics = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_two_process_distributed_train_step(tmp_path):
    """VERDICT r3 #6 + r4 #4: exercise initialize_distributed's NON-trivial
    branch with a real 2-process jax.distributed runtime — each process
    owns 2 virtual CPU devices, one sharded train step runs over the
    4-device global mesh, and both processes must agree on the loss
    (SPMD). Then the output-hygiene contract: validation host-shards the
    frames (3 each), all-reduces to identical global metrics on both
    processes, prints its console line from the main process only, and
    exactly one process writes log.txt."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    child = os.path.join(os.path.dirname(__file__), "_distributed_child.py")
    env = dict(os.environ)
    # The children build their own 2-device CPU platform; drop the
    # conftest's 8-device flag so it doesn't override theirs.
    env["XLA_FLAGS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    run_dir = str(tmp_path / "shared_run")

    procs = [
        subprocess.Popen(
            [sys.executable, child, str(port), str(pid), run_dir],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    def field(out: str, prefix: str) -> str:
        return next(
            l[len(prefix):] for l in out.splitlines() if l.startswith(prefix)
        )

    losses, vals, actives, val_prints = [], [], [], 0
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\n{out}\n{err[-2000:]}"
        losses.append(float(field(out, "LOSS=")))
        vals.append(field(out, "VAL="))
        actives.append(int(field(out, "LOGACTIVE=")))
        val_prints += sum(
            1 for l in out.splitlines() if l.startswith("Validation Synthetic")
        )
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)
    # Host-sharded validation reduced to IDENTICAL global metrics.
    assert vals[0] == vals[1]
    # Console line from exactly one process; exactly one log.txt writer.
    assert val_prints == 1
    assert sorted(actives) == [0, 1]
    log = (tmp_path / "shared_run" / "log.txt").read_text()
    assert log.count("hello from process") == 1
