"""graftlint (raft_ncup_tpu/analysis): one positive + one negative fixture
snippet per JGL rule, engine/allowlist behaviors, and the self-check that
puts the linter inside the tier-1 gate: the shipped tree lints clean.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

import pytest

from raft_ncup_tpu.analysis.lint import (
    AllowlistError,
    load_allowlist,
    run_lint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, source, name="snippet.py", axes=None, select=None):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    result = run_lint(
        [str(path)],
        declared_axes=frozenset(axes) if axes is not None else None,
        select=select,
    )
    assert not result.parse_errors, result.parse_errors
    return result.findings


# --------------------------------------------------------------- JGL001


def test_jgl001_flags_host_sync_in_traced_code(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def step(state, batch):
            loss = (batch - state).sum()
            log_val = float(loss)      # per-step sync
            arr = np.asarray(loss)     # implicit pull
            scalar = loss.item()       # method pull
            return loss, log_val, arr, scalar
        """,
        select=["JGL001"],
    )
    assert [f.rule for f in findings] == ["JGL001"] * 3
    assert {f.qualname for f in findings} == {"step"}


def test_jgl001_ignores_host_side_code(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax
        import numpy as np

        def host_loop(step_fn, state, batches):
            for batch in batches:
                state, metrics = step_fn(state, batch)
            return float(np.asarray(metrics))  # host side: fine
        """,
    )
    assert findings == []


def test_jgl001_traced_through_scan_and_assignment(tmp_path):
    """The repo's own pattern: body = jax.checkpoint(step);
    jax.lax.scan(body, ...) must mark `step` traced."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def forward(xs, remat):
            def step(carry, x):
                v = carry + x
                bad = v.item()
                return v, bad

            body = step
            if remat:
                body = jax.checkpoint(step)
            return jax.lax.scan(body, 0.0, xs)
        """,
    )
    assert [f.rule for f in findings] == ["JGL001"]
    assert findings[0].qualname == "forward.step"


# --------------------------------------------------------------- JGL002


def test_jgl002_flags_undonated_state_step(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def make_step(model):
            def step(state, batch, rng):
                return state, {}

            return jax.jit(step)
        """,
    )
    assert [f.rule for f in findings] == ["JGL002"]
    assert "donate" in findings[0].message


def test_jgl002_decorator_form_flagged(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(state, batch):
            return state
        """,
        select=["JGL002"],
    )
    assert [f.rule for f in findings] == ["JGL002"]


def test_jgl002_negative_donated_or_stateless(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def make_steps(model):
            def step(state, batch, rng):
                return state, {}

            def eval_step(variables, image1, image2):
                return model(variables, image1, image2)

            donated = jax.jit(step, donate_argnums=0)
            eval_jit = jax.jit(eval_step)  # no state: nothing to donate
            return donated, eval_jit
        """,
        select=["JGL002"],
    )
    assert findings == []


def test_jgl002_sibling_scopes_do_not_cross_contaminate(tmp_path):
    """Same-named inner functions in sibling factories (the repo's
    make_train_step.step vs make_eval_step.step) must resolve per scope."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def make_train_step():
            def step(state, batch):
                return state

            return jax.jit(step, donate_argnums=0)

        def make_eval_step():
            def step(variables, image1):
                return variables

            return jax.jit(step)
        """,
        select=["JGL002"],
    )
    assert findings == []


# --------------------------------------------------------------- JGL003


def test_jgl003_flags_trace_time_nondeterminism(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import time
        import random
        import numpy as np
        import jax

        @jax.jit
        def step(x):
            noise = np.random.randn()        # baked at trace time
            jitter = random.random()         # baked at trace time
            t = time.time()                  # baked at trace time
            return x + noise + jitter + t
        """,
    )
    assert [f.rule for f in findings] == ["JGL003"] * 3


def test_jgl003_jax_random_is_exempt(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax
        from jax import random

        @jax.jit
        def step(x, key):
            k1, k2 = random.split(key)
            return x + jax.random.normal(k1, x.shape), k2
        """,
        select=["JGL003"],
    )
    assert findings == []


# --------------------------------------------------------------- JGL004


def test_jgl004_flags_python_branch_on_traced_value(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def clamp(x):
            if jnp.any(x > 10):        # tracer branch
                x = jnp.clip(x, 0, 10)
            while (x < 0).all():       # tracer loop
                x = x + 1
            return x
        """,
    )
    assert [f.rule for f in findings] == ["JGL004"] * 2


def test_jgl004_static_branches_are_fine(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def forward(x, *, test_mode=False, iters=12):
            if test_mode:              # static python flag
                iters = 2
            if x.shape[0] % 8:         # static shape arithmetic
                raise ValueError("pad first")
            if jax.process_count() > 1:  # static runtime query
                pass
            return x * iters
        """,
        select=["JGL004"],
    )
    assert findings == []


# --------------------------------------------------------------- JGL005


def test_jgl005_flags_dtypeless_and_f64_in_ops(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        import jax.numpy as jnp
        import numpy as np

        KERNEL = jnp.asarray([0.25, 0.5, 0.25])   # dtype-less
        BAD = np.float64(1.0)                      # f64 in the core

        def widen(x):
            return x.astype("float64")             # string-spelled f64

        WIDE = jnp.asarray([1.0], dtype="float64")  # string-spelled f64
        """,
        name="ops/constants.py",
    )
    assert [f.rule for f in findings] == ["JGL005"] * 4


def test_jgl005_negative_explicit_dtype_and_out_of_scope(tmp_path):
    # explicit dtype in ops/: clean
    assert (
        lint_snippet(
            tmp_path,
            """
            import jax.numpy as jnp

            KERNEL = jnp.asarray([0.25, 0.5, 0.25], jnp.float32)
            IDX = jnp.asarray([1, 2], dtype=jnp.int32)
            """,
            name="ops/clean.py",
        )
        == []
    )
    # dtype-less outside ops//nn/: out of the rule's scope
    assert (
        lint_snippet(
            tmp_path,
            """
            import jax.numpy as jnp

            X = jnp.asarray([1.0, 2.0])
            """,
            name="drivers/free.py",
        )
        == []
    )


# --------------------------------------------------------------- JGL006


def test_jgl006_flags_undeclared_axis(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        from jax.sharding import PartitionSpec as P

        SPEC = P("data", "spatail")   # typo: silently replicates
        """,
        axes={"data", "spatial"},
    )
    assert [f.rule for f in findings] == ["JGL006"]
    assert "spatail" in findings[0].message


def test_jgl006_declared_axes_and_discovery(tmp_path):
    # declared axes (incl. tuple form and None) are clean
    assert (
        lint_snippet(
            tmp_path,
            """
            from jax.sharding import PartitionSpec as P

            A = P("data", "spatial", None)
            B = P(("data", "spatial"))
            C = P()
            """,
            axes={"data", "spatial"},
        )
        == []
    )
    # axis names are discovered from a Mesh() declaration in the lint set
    # (fresh subdir: the snippet above declared data/spatial axes)
    disc = tmp_path / "disc"
    disc.mkdir()
    (disc / "mesh.py").write_text(
        textwrap.dedent(
            """
            from jax.sharding import Mesh

            def make(devices):
                return Mesh(devices, ("rows", "cols"))
            """
        )
    )
    (disc / "user.py").write_text(
        textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P

            GOOD = P("rows")
            BAD = P("data")
            """
        )
    )
    result = run_lint([str(disc)])
    assert result.declared_axes == frozenset({"rows", "cols"})
    assert [f.rule for f in result.findings] == ["JGL006"]
    assert "'data'" in result.findings[0].message


def test_jgl006_silent_without_declaration(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        from jax.sharding import PartitionSpec as P

        SPEC = P("whatever")
        """,
        axes=set(),
    )
    assert findings == []


def test_jgl006_standalone_subsystem_lint_uses_production_axes(tmp_path):
    """Linting inference//serving//streaming/ WITHOUT parallel/mesh.py in
    the set must still judge PartitionSpec axes against the production
    declarer's axes (lint.production_declared_axes fallback): a typo'd
    axis in a serving module silently replicates — the exact JGL006
    hazard — and the pre-fallback engine went silent on standalone
    lints."""
    from raft_ncup_tpu.analysis.lint import run_lint

    for sub in ("inference", "serving", "streaming"):
        d = tmp_path / sub
        d.mkdir()
        (d / "sharded.py").write_text(
            textwrap.dedent(
                """
                from jax.sharding import PartitionSpec as P

                BAD = P("spatail")
                GOOD = P("data", "spatial")
                """
            )
        )
        result = run_lint([str(d)])
        assert result.declared_axes >= {"data", "spatial"}, sub
        assert [f.rule for f in result.findings] == ["JGL006"], sub
        assert "spatail" in result.findings[0].message


def test_jgl006_standalone_subsystem_negative_declared_axes(tmp_path):
    """The negative half: standalone subsystem files whose PartitionSpecs
    name only declared production axes lint clean under the fallback."""
    from raft_ncup_tpu.analysis.lint import run_lint

    d = tmp_path / "serving"
    d.mkdir()
    (d / "ok.py").write_text(
        textwrap.dedent(
            """
            from jax.sharding import NamedSharding, PartitionSpec as P

            def shardings(mesh):
                return {
                    "image1": NamedSharding(mesh, P("data", "spatial")),
                    "table": NamedSharding(mesh, P("data")),
                    "repl": NamedSharding(mesh, P()),
                }
            """
        )
    )
    result = run_lint([str(d)])
    assert result.findings == []


def test_jgl006_discovers_conditional_axis_tuple(tmp_path):
    """Declared-axes discovery descends conditional-expression axis
    tuples — ``Mesh(arr, (..., "pipe") if pipe > 1 else (...))`` is how
    make_mesh declares the pipeline axis in ONE call (both branches
    count as declarations), so 'pipe' must be usable in PartitionSpecs
    without a JGL006 false positive, while a typo'd axis still fires."""
    from raft_ncup_tpu.analysis.lint import run_lint

    d = tmp_path / "pipe_ok"
    d.mkdir()
    (d / "mesh.py").write_text(
        textwrap.dedent(
            """
            from jax.sharding import Mesh

            def make(arr, pipe):
                return Mesh(
                    arr,
                    ("data", "spatial", "pipe")
                    if pipe > 1
                    else ("data", "spatial"),
                )
            """
        )
    )
    (d / "use.py").write_text(
        textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P

            STATE = P("pipe")
            IMG = P("data", "spatial")
            """
        )
    )
    result = run_lint([str(d)])
    assert result.declared_axes == frozenset({"data", "spatial", "pipe"})
    assert result.findings == []

    # negative half: an axis in NEITHER branch still fires
    bad = tmp_path / "pipe_bad"
    bad.mkdir()
    (bad / "mesh.py").write_text((d / "mesh.py").read_text())
    (bad / "use.py").write_text(
        textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P

            STATE = P("pip")   # typo: silently replicates
            """
        )
    )
    result = run_lint([str(bad)])
    assert [f.rule for f in result.findings] == ["JGL006"]
    assert "pip" in result.findings[0].message


def test_jgl006_production_axes_include_pipe():
    """The real make_mesh's conditional axis tuple feeds discovery: the
    production fallback set must see all three axes, or every
    P('pipe') in inference/pipe_schedule.py would be a false positive
    in standalone subsystem lint runs."""
    from raft_ncup_tpu.analysis.lint import production_declared_axes

    assert production_declared_axes() >= {"data", "spatial", "pipe"}


# --------------------------------------------------------------- JGL007


def test_jgl007_flags_swallowed_exceptions(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        def load(path):
            try:
                return open(path).read()
            except Exception:
                pass

        def drain(q):
            while True:
                try:
                    return q.get_nowait()
                except:
                    continue
        """,
        name="data/bad.py",
    )
    assert [f.rule for f in findings] == ["JGL007"] * 2
    assert {f.qualname for f in findings} == {"load", "drain"}


def test_jgl007_negative_handled_or_narrow(tmp_path):
    """Re-raised, logged/accounted, or narrow handlers are all fine —
    the rule only hunts silent broad swallows."""
    findings = lint_snippet(
        tmp_path,
        """
        import sys

        def save(fn):
            try:
                fn()
            except Exception as e:
                print(f"save failed: {e}", file=sys.stderr)
                raise

        def close(handle):
            try:
                handle.close()
            except OSError:
                pass  # narrow: an expected, decided-on drop

        def teardown(handle, stats):
            try:
                handle.close()
            except Exception as e:
                stats.record(e)  # accounted
        """,
        name="training/ok.py",
    )
    assert findings == []


def test_jgl007_out_of_scope_paths_exempt(tmp_path):
    """The same swallow outside resilience//training//data/ is not this
    rule's business (drivers and analysis code have their own idioms)."""
    findings = lint_snippet(
        tmp_path,
        """
        def f(x):
            try:
                return x()
            except Exception:
                pass
        """,
        name="drivers/free.py",
    )
    assert findings == []


# --------------------------------------------------------------- JGL009


def test_jgl009_flags_inline_dtype_literals_on_hot_path(tmp_path):
    """Raw jnp dtype literals in models//nn//inference/ function bodies
    bypass the precision policy — both the narrow (bfloat16) and the
    wide (float32) direction are dtype decisions the policy must own."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax.numpy as jnp

        def forward(policy, x, coords):
            feats = x.astype(jnp.bfloat16)       # inline narrow
            coords = coords.astype(jnp.float32)  # inline wide
            acc = jnp.zeros((2,), jnp.float32)   # inline wide
            return feats, coords, acc
        """,
        name="models/hotpath.py",
    )
    assert [f.rule for f in findings] == ["JGL009"] * 3
    assert {f.qualname for f in findings} == {"forward"}


def test_jgl009_sanctioned_routings_are_clean(tmp_path):
    """The three sanctioned shapes: policy reads, flax class-attribute
    defaults, and named module-level constants — plus out-of-scope paths
    (ops/ keeps JGL005's narrower dtype-hygiene rule)."""
    assert (
        lint_snippet(
            tmp_path,
            """
            import jax.numpy as jnp
            from typing import Any

            PARAM_DTYPE = jnp.float32  # mirrors PrecisionPolicy.param_jnp

            class Conv:
                dtype: Any = jnp.float32  # policy-settable knob

                def __call__(self, policy, x):
                    y = x.astype(self.dtype or x.dtype)
                    return y.astype(policy.compute_jnp), PARAM_DTYPE
            """,
            name="nn/clean.py",
        )
        == []
    )
    assert (
        lint_snippet(
            tmp_path,
            """
            import jax.numpy as jnp

            def widen(x):
                return x.astype(jnp.float32)
            """,
            name="ops/free.py",
            select=["JGL009"],
        )
        == []
    )


def test_jgl009_sentinel_module_in_scope(tmp_path):
    """resilience/anomaly.py is scoped in deliberately: the sentinel's
    f32 arithmetic is policy-pinned, so its literals must be VISIBLE
    (allowlisted with justification), not invisible."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax.numpy as jnp

        def guard(x):
            return jnp.float32(0.5) * x
        """,
        name="resilience/anomaly.py",
        select=["JGL009"],
    )
    assert [f.rule for f in findings] == ["JGL009"]


# --------------------------------------------------------------- JGL010


def test_jgl010_flags_jax_and_pulls_in_observability(tmp_path):
    """Telemetry is host-only: jax imports, jax.* calls, numpy pulls,
    and .item()/.tolist() inside observability/ all violate the
    no-device-access / no-added-sync constraint."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax
        import numpy as np

        def record(registry, value):
            host = jax.device_get(value)       # pull inside telemetry
            arr = np.asarray(value)            # implicit pull
            scalar = value.item()              # method pull
            registry.counter("x").inc(host + arr.sum() + scalar)
        """,
        name="observability/bad.py",
        select=["JGL010"],
    )
    assert [f.rule for f in findings] == ["JGL010"] * 4
    # The import finding is module-level; the pulls are inside record().
    assert "record" in {f.qualname for f in findings}


def test_jgl010_from_jax_import_flagged(tmp_path):
    findings = lint_snippet(
        tmp_path,
        """
        from jax import profiler
        """,
        name="observability/spans.py",
        select=["JGL010"],
    )
    assert [f.rule for f in findings] == ["JGL010"]


def test_jgl010_host_only_telemetry_is_clean(tmp_path):
    """The package's real shape — stdlib locks, clocks, math on host
    scalars — is clean, and the same code outside observability/ is not
    this rule's business."""
    clean = """
        import threading
        import time

        def observe(hist, seconds):
            hist.observe_ms(float(seconds) * 1000.0)

        def snapshot(metrics):
            return {k: m.value for k, m in sorted(metrics.items())}
        """
    assert lint_snippet(
        tmp_path, clean, name="observability/good.py", select=["JGL010"]
    ) == []
    pulls_elsewhere = """
        import jax

        def boundary(x):
            return jax.device_get(x)  # a producer's sanctioned pull
        """
    assert lint_snippet(
        tmp_path, pulls_elsewhere, name="serving/free.py",
        select=["JGL010"],
    ) == []


@pytest.mark.parametrize(
    "module", ["health.py", "slo.py", "flight.py"]
)
def test_jgl010_covers_the_consumer_half_modules(tmp_path, module):
    """The PR 12 consumer modules (health state machine, SLO burn-rate
    engine, flight recorder) sit under the same host-only contract as
    the producers: a jax import or device pull inside any of them is a
    finding, and their real shapes (stdlib state machines, counter
    deltas, atomic JSON writes) are clean. Zero allowlist entries."""
    dirty = """
        import jax

        def evaluate(registry, value):
            return float(jax.device_get(value))  # sync inside telemetry
        """
    findings = lint_snippet(
        tmp_path, dirty, name=f"observability/{module}",
        select=["JGL010"],
    )
    assert [f.rule for f in findings] == ["JGL010"] * 2
    clean = """
        import json
        import os
        import time

        ALLOWED = {"ready": {"degraded", "draining"}}

        def transition(state, to):
            return to if to in ALLOWED.get(state, set()) else state

        def burn(bad, total, budget):
            return (bad / total) / budget if total else 0.0

        def atomic_write(path, payload):
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, path)
        """
    assert lint_snippet(
        tmp_path, clean, name=f"observability/{module}",
        select=["JGL010"],
    ) == []


def test_jgl010_covers_aggregate_module(tmp_path):
    """The fleet trace/registry aggregator (PR 14) is the offline tool
    most tempted to import jax 'for convenience' — it sits under the
    same host-only contract, pinned explicitly: a jax import or device
    pull inside observability/aggregate.py is a finding; its real shape
    (json merges, clock-offset arithmetic on host floats) is clean."""
    dirty = """
        import jax

        def merge(records, value):
            return records + [float(jax.device_get(value))]
        """
    findings = lint_snippet(
        tmp_path, dirty, name="observability/aggregate.py",
        select=["JGL010"],
    )
    assert [f.rule for f in findings] == ["JGL010"] * 2
    clean = """
        import json
        import os

        def translate(records, offset_s):
            return [
                {**r, "t": r["t_s"] - offset_s}
                for r in records if "t_s" in r
            ]

        def read_tolerant(path):
            out, skipped = [], 0
            with open(path) as fh:
                for line in fh:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        skipped += 1
            return out, skipped
        """
    assert lint_snippet(
        tmp_path, clean, name="observability/aggregate.py",
        select=["JGL010"],
    ) == []


def test_jgl010_fleet_trace_header_must_stay_optional(tmp_path):
    """Wire-compat contract: the frame schema's trace-context field is
    OPTIONAL — a mandatory `header[\"trace\"]` READ in fleet/ would make
    old peers' frames unparsable by new fleet code, so it is a finding;
    reading with .get and WRITING the field (a producer knows its own
    schema) are clean, as is the same subscript outside fleet/."""
    dirty = """
        def adopt(header):
            ctx = header["trace"]  # mandatory read: old frames crash
            return ctx
        """
    findings = lint_snippet(
        tmp_path, dirty, name="fleet/router.py", select=["JGL010"],
    )
    assert [f.rule for f in findings] == ["JGL010"]
    assert "optional" in findings[0].message.lower()
    clean = """
        def dispatch(header, ctx):
            header["trace"] = ctx          # producer write: fine
            return header.get("trace")     # tolerant read: fine
        """
    assert lint_snippet(
        tmp_path, clean, name="fleet/router.py", select=["JGL010"],
    ) == []
    elsewhere = """
        def adopt(header):
            return header["trace"]  # not fleet/ wire code
        """
    assert lint_snippet(
        tmp_path, elsewhere, name="serving/server.py", select=["JGL010"],
    ) == []


# ------------------------------------------------------------- allowlist


def test_jgl008_flags_per_batch_pulls_in_eval_loop(tmp_path):
    """Per-iteration host pulls in the eval hot loop: the exact bug class
    the async eval pipeline removed (per-batch full-field device_get)."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def validate(fwd, batches):
            total = 0.0
            for batch in batches:
                acc = fwd(batch)
                total += jax.device_get(acc)[0]
            return total

        def drain(q):
            while q:
                q.pop().item()

        def collect(accs):
            return [a.tolist() for a in accs]
        """,
        name="inference/bad.py",
    )
    assert [f.rule for f in findings] == ["JGL008"] * 3
    assert {f.qualname for f in findings} == {"validate", "drain", "collect"}


def test_jgl008_negative_window_pull_throttle_and_nested_def(tmp_path):
    """Sanctioned shapes: ONE pull at the window boundary (after the
    loop), a bounded block_until_ready (sync, not transfer), and a pull
    inside a callback merely DEFINED in the loop (runs off-loop, e.g. on
    the AsyncDrain worker)."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def validate(fwd, batches, throttle):
            acc = None
            for batch in batches:
                acc = fwd(batch)
                jax.block_until_ready(acc)
            return jax.device_get(acc)

        def submit_all(drain, outs):
            for out in outs:
                def write_cb():
                    return jax.device_get(out)
                drain.submit(write_cb)
        """,
        name="raft_ncup_tpu/evaluation.py",
    )
    assert findings == []


def test_jgl008_serving_dispatcher_in_scope(tmp_path):
    """The serving dispatcher is the same hot loop facing an open-loop
    stream: a per-batch pull on the dispatch thread re-serializes every
    batch with d2h transfer — the AsyncDrain worker owns the pull."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def dispatch(queue, fwd):
            while queue:
                batch = queue.pop()
                flow = fwd(batch)
                return_to_client(jax.device_get(flow))
        """,
        name="raft_ncup_tpu/serving/server.py",
    )
    assert [f.rule for f in findings] == ["JGL008"]
    assert findings[0].qualname == "dispatch"


def test_jgl008_streaming_dispatcher_in_scope(tmp_path):
    """The streaming engine's dispatch loop is in scope: per-stream
    recurrent state lives in the device slot table precisely so nothing
    needs pulling between frames — a per-batch pull there reintroduces
    the serialization the subsystem deletes."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def dispatch(queue, step, table):
            while queue:
                batch = queue.pop()
                table, flow, bad = step(table, batch)
                notify(jax.device_get(bad))
        """,
        name="raft_ncup_tpu/streaming/engine.py",
    )
    assert [f.rule for f in findings] == ["JGL008"]
    assert findings[0].qualname == "dispatch"


def test_jgl008_streaming_negative_device_resident_loop(tmp_path):
    """The sanctioned streaming shape: the slot-table carry stays on
    device across iterations, the bounded throttle syncs without
    transferring, and the flow+flags pull rides a callback that runs on
    the AsyncDrain worker (defined in the loop, executed off it)."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def dispatch(queue, step, table, throttle, drain):
            while queue:
                batch = queue.pop()
                table, flow, bad = step(table, batch)
                jax.block_until_ready(flow)

                def deliver(host):
                    complete(host)

                drain.submit((flow, bad), deliver)
        """,
        name="raft_ncup_tpu/streaming/engine.py",
    )
    assert findings == []


def test_jgl008_out_of_scope_paths_exempt(tmp_path):
    """The same per-iteration pull outside inference//evaluation.py is
    JGL001's business (when traced) or legitimate driver code."""
    findings = lint_snippet(
        tmp_path,
        """
        import jax

        def summarize(metrics_list):
            return [jax.device_get(m) for m in metrics_list]
        """,
        name="training/logger.py",
        select=["JGL008"],
    )
    assert findings == []


def test_allowlist_suppresses_with_justification(tmp_path):
    snippet = tmp_path / "mod.py"
    snippet.write_text(
        textwrap.dedent(
            """
            import jax

            @jax.jit
            def step(x):
                return float(x)
            """
        )
    )
    allow = tmp_path / "allow.txt"
    allow.write_text("mod.py::JGL001::step  # audited: test fixture\n")
    result = run_lint([str(snippet)], allowlist_path=str(allow))
    assert result.findings == []
    assert len(result.suppressed) == 1
    assert result.stale_entries == []


def test_allowlist_requires_justification(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text("mod.py::JGL001::step\n")
    with pytest.raises(AllowlistError, match="justification"):
        load_allowlist(str(allow))


def test_allowlist_stale_entry_reported(tmp_path):
    snippet = tmp_path / "clean.py"
    snippet.write_text("X = 1\n")
    allow = tmp_path / "allow.txt"
    allow.write_text("clean.py::JGL001::*  # obsolete\n")
    result = run_lint([str(snippet)], allowlist_path=str(allow))
    assert len(result.stale_entries) == 1


def test_allowlist_not_stale_when_rule_deselected(tmp_path):
    """`--select` must not mark entries of skipped rules stale — lint.sh
    --select <rule> would otherwise fail spuriously under
    --strict-allowlist."""
    snippet = tmp_path / "mod.py"
    snippet.write_text(
        textwrap.dedent(
            """
            import jax

            @jax.jit
            def step(x):
                return float(x)
            """
        )
    )
    allow = tmp_path / "allow.txt"
    allow.write_text("mod.py::JGL001::step  # audited: test fixture\n")
    result = run_lint(
        [str(snippet)], allowlist_path=str(allow), select=["JGL005"]
    )
    assert result.stale_entries == []  # JGL001 never ran: undecidable
    # ...but with the rule selected and the finding gone, it IS stale
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "mod.py").write_text("X = 1\n")
    result = run_lint(
        [str(clean / "mod.py")], allowlist_path=str(allow), select=["JGL001"]
    )
    assert len(result.stale_entries) == 1


def test_parse_error_is_reported_not_raised(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    result = run_lint([str(bad)])
    assert len(result.parse_errors) == 1


# ------------------------------------------- JGL007/JGL010 fleet scope


def test_jgl010_fleet_scope_flags_jax_and_pulls(tmp_path):
    """The fleet control plane shares observability/'s host-only
    contract (zero allowlist entries): a router that can touch a device
    array can add a sync to every request it routes."""
    dirty = """
        import jax
        import numpy as np

        def route(request, value):
            flow = np.asarray(value)        # implicit pull in the router
            return jax.device_get(flow)     # explicit device access
        """
    findings = lint_snippet(
        tmp_path, dirty, name="fleet/router.py", select=["JGL010"]
    )
    assert [f.rule for f in findings] == ["JGL010"] * 3


def test_jgl010_fleet_scope_wire_idioms_are_clean(tmp_path):
    """The fleet's real shape — stdlib sockets/json/signals plus
    numpy frombuffer/tobytes on HOST arrays — is clean: the rule bans
    the pull shapes (asarray/array/.item()/.tolist()), not numpy."""
    clean = """
        import json
        import socket
        import struct

        import numpy as np

        def send(sock, header, arr):
            blob = json.dumps(header).encode()
            sock.sendall(struct.pack(">I", len(blob)) + blob
                         + arr.tobytes())

        def recv_payload(buf, dtype, shape):
            return np.frombuffer(buf, dtype=dtype).reshape(shape)
        """
    assert lint_snippet(
        tmp_path, clean, name="fleet/wire.py", select=["JGL010"]
    ) == []


def test_jgl007_fleet_scope_supervisor_must_not_eat_deaths(tmp_path):
    """A supervisor that silently eats a child's death is the exact
    failure mode the fleet tier exists to prevent — JGL007's swallowed-
    exception hunt covers fleet/ too."""
    dirty = """
        def poll(children):
            for child in children:
                try:
                    child.check()
                except Exception:
                    pass  # a dead replica vanishes silently
        """
    findings = lint_snippet(
        tmp_path, dirty, name="fleet/replica.py", select=["JGL007"]
    )
    assert [f.rule for f in findings] == ["JGL007"]
    accounted = """
        def poll(children, stats):
            for child in children:
                try:
                    child.check()
                except Exception as e:
                    stats.note_death(child, e)  # counted, never silent

        def close(sock):
            try:
                sock.close()
            except OSError:
                pass  # narrow: a decided-on drop, out of scope
        """
    assert lint_snippet(
        tmp_path, accounted, name="fleet/replica.py", select=["JGL007"]
    ) == []


def test_jgl010_autoscaler_scope_control_loop_is_host_only(tmp_path):
    """The autoscaler decides fleet topology from healthz dicts and
    router counters — a control loop that can pull a device array can
    stall every replica it sizes. fleet/ directory scope covers the
    new module with zero allowlist entries."""
    dirty = """
        import jax
        import numpy as np

        def occupancy(replica_outputs):
            flows = [np.asarray(o) for o in replica_outputs]  # pull
            return float(jax.device_get(flows[0]).mean())
        """
    findings = lint_snippet(
        tmp_path, dirty, name="fleet/autoscaler.py", select=["JGL010"]
    )
    assert findings and all(f.rule == "JGL010" for f in findings)
    clean = """
        import threading
        import time

        def tick(handles, router, cfg):
            ups = [h for h in handles if h.state == "up"]
            cap = len(ups) * cfg.max_inflight_per_replica
            used = sum(router.inflight_of(h.index) for h in ups)
            paging = [
                p for h in ups
                for p in ((h.last_healthz or {}).get("slo") or {})
                .get("paging", [])
            ]
            return {"occupancy": used / cap if cap else 1.0,
                    "paging": paging, "t": time.monotonic()}
        """
    assert lint_snippet(
        tmp_path, clean, name="fleet/autoscaler.py", select=["JGL010"]
    ) == []


def test_jgl007_host_supervisor_must_not_eat_agent_errors(tmp_path):
    """A manager that silently eats a host agent's RPC failure turns a
    dead host into a vanished host — the staleness/fencing contract
    only works if every agent error is counted. JGL007 covers the new
    host_supervisor module via the fleet/ scope."""
    dirty = """
        def poll_hosts(agents):
            snapshots = {}
            for host, agent in agents.items():
                try:
                    snapshots[host] = agent.call("snapshot")
                except Exception:
                    continue  # silent: the host just disappears
            return snapshots
        """
    findings = lint_snippet(
        tmp_path, dirty, name="fleet/host_supervisor.py",
        select=["JGL007"],
    )
    assert [f.rule for f in findings] == ["JGL007"]
    accounted = """
        def poll_hosts(agents, tel, missed):
            snapshots = {}
            for host, agent in agents.items():
                try:
                    snapshots[host] = agent.call("snapshot")
                except Exception as e:
                    missed[host] = missed.get(host, 0) + 1
                    tel.event("fleet_host_poll_miss", host=host,
                              error=repr(e))  # counted, never silent
            return snapshots

        def fence_sock(sock):
            try:
                sock.close()
            except OSError:
                pass  # narrow: a decided-on drop, out of scope
        """
    assert lint_snippet(
        tmp_path, accounted, name="fleet/host_supervisor.py",
        select=["JGL007"],
    ) == []


def test_jgl010_host_supervisor_fencing_idioms_are_clean(tmp_path):
    """The host-supervisor's real vocabulary — signals, /proc reads,
    wire sockets, healthz JSON — is exactly the host-only shape JGL010
    protects; the rule must not cry wolf on it."""
    clean = """
        import os
        import signal

        def fence(pids):
            reaped = []
            for pid in pids:
                try:
                    os.kill(pid, signal.SIGKILL)
                    reaped.append(pid)
                except ProcessLookupError:
                    reaped.append(pid)  # already gone counts as fenced
            return reaped

        def alive(pid):
            try:
                with open(f"/proc/{pid}/stat") as fh:
                    stat = fh.read()
            except OSError:
                return False
            return stat.rpartition(")")[2].split()[0] != "Z"
        """
    assert lint_snippet(
        tmp_path, clean, name="fleet/host_supervisor.py",
        select=["JGL010"],
    ) == []


def lint_files(tmp_path, files, select=None):
    """Multi-file fixture helper for the whole-program rules: write each
    ``rel_path -> source`` pair under tmp_path and lint the directory."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    result = run_lint([str(tmp_path)], select=select)
    assert not result.parse_errors, result.parse_errors
    return result.findings


# --------------------------------------------------------------- JGL011


def test_jgl011_flags_unlocked_read_of_guarded_attr(tmp_path):
    """An attr written under the class lock in one method and read bare
    in another is exactly the race the fleet tier keeps hitting — the
    finding names BOTH sites."""
    findings = lint_files(
        tmp_path,
        {
            "fleet/reg.py": """
            import threading

            class Registry:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def add(self, key, value):
                    with self._lock:
                        self._items[key] = value

                def peek(self, key):
                    return self._items.get(key)   # unlocked read
            """,
        },
        select=["JGL011"],
    )
    assert [f.rule for f in findings] == ["JGL011"]
    f = findings[0]
    assert f.qualname == "peek"
    assert "Registry._items" in f.message
    assert "written under the class lock" in f.message
    assert "[add]" in f.message  # the guarded-write site is named too


def test_jgl011_locked_reads_and_always_locked_helpers_clean(tmp_path):
    """The discipline the fixed fleet code follows is clean: every
    access under the lock, __init__ exempt, and a private helper whose
    call sites all hold the lock inherits the guard (the always-locked
    fixpoint — no false positive on the helper's bare reads)."""
    findings = lint_files(
        tmp_path,
        {
            "fleet/reg.py": """
            import threading

            class Registry:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def add(self, key, value):
                    with self._lock:
                        self._items[key] = value

                def peek(self, key):
                    with self._lock:
                        return self._items.get(key)

                def _locked_size(self):
                    return len(self._items)   # guarded via callers

                def size(self):
                    with self._lock:
                        return self._locked_size()
            """,
        },
        select=["JGL011"],
    )
    assert findings == []


def test_jgl011_scope_is_fleet_and_observability_only(tmp_path):
    """The same racy shape outside fleet//observability/ is not this
    rule's business (single-threaded modules own their own state)."""
    findings = lint_files(
        tmp_path,
        {
            "inference/reg.py": """
            import threading

            class Registry:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def add(self, key, value):
                    with self._lock:
                        self._items[key] = value

                def peek(self, key):
                    return self._items.get(key)
            """,
        },
        select=["JGL011"],
    )
    assert findings == []


# --------------------------------------------------------------- JGL012


def test_jgl012_flags_bare_subscript_and_both_drift_halves(tmp_path):
    """Across the two protocol ends: a bare-subscript read (optional-
    field contract), a key written but never read, and a key read but
    never written are each findings."""
    findings = lint_files(
        tmp_path,
        {
            "fleet/worker.py": """
            def handle(header):
                value = header["payload"]      # bare subscript
                if header.get("kind") != "job":
                    return None
                return value

            def reply_ok(rid):
                reply = {"kind": "ok", "orphan_field": rid}
                return reply
            """,
            "serve.py": """
            def consume(header):
                return header.get("ghost_field")
            """,
        },
        select=["JGL012"],
    )
    assert findings and all(f.rule == "JGL012" for f in findings)
    messages = [f.message for f in findings]
    assert any(
        "'payload'" in m and "bare" in m for m in messages
    ), messages
    assert any(
        "'orphan_field'" in m and "never read" in m for m in messages
    ), messages
    assert any(
        "'ghost_field'" in m and "never written" in m for m in messages
    ), messages


def test_jgl012_matched_keys_and_carveouts_are_clean(tmp_path):
    """A produced-and-consumed key is clean; 'kind' (the one REQUIRED
    field) may be subscripted; the 'trace' key inside fleet/ belongs to
    JGL010's carve-out, not this rule."""
    findings = lint_files(
        tmp_path,
        {
            "fleet/worker.py": """
            def reply_ok(rid, header, ctx):
                kind = header["kind"]          # required field: honest
                header["trace"] = ctx
                trace = header["trace"]        # JGL010's carve-out
                reply = {"kind": "ok", "result": rid}
                return reply, kind, trace
            """,
            "serve.py": """
            def consume(header):
                return header.get("result"), header.get("kind")
            """,
        },
        select=["JGL012"],
    )
    assert findings == []


def test_jgl012_drift_needs_both_protocol_ends(tmp_path):
    """A standalone lint of one directory cannot distinguish drift from
    out-of-scope use: without serve.py in the linted set, the drift
    halves stay silent (the per-site bare-subscript check still runs)."""
    findings = lint_files(
        tmp_path,
        {
            "fleet/worker.py": """
            def reply_ok(rid):
                return {"kind": "ok", "half_seen": rid}
            """,
        },
        select=["JGL012"],
    )
    assert findings == []


# --------------------------------------------------------------- JGL013


def test_jgl013_flags_stragglers_unregistered_and_dead_knobs(tmp_path):
    """All three halves: a direct os.environ read of a knob-prefixed
    name (resolved through a module constant), a knob_* getter naming
    an undeclared knob, and a registered knob nobody reads."""
    findings = lint_files(
        tmp_path,
        {
            "utils/knobs.py": """
            KNOBS = (
                Knob("RAFT_NCUP_ALPHA", "str", "a", "alpha knob"),
                Knob("RAFT_NCUP_DEAD", "str", "d", "dead knob"),
            )
            """,
            # The unread-knob half is gated on the full driver scope.
            "train.py": "",
            "serve.py": "",
            "bench.py": """
            import os
            from raft_ncup_tpu.utils.knobs import knob_str

            ALPHA_ENV = "RAFT_NCUP_ALPHA"

            def f():
                direct = os.environ.get(ALPHA_ENV)      # straggler
                good = knob_str("RAFT_NCUP_ALPHA")
                bad = knob_str("RAFT_NCUP_GHOST")       # undeclared
                benign = os.environ.get("PATH")         # not a knob
                return direct, good, bad, benign
            """,
        },
        select=["JGL013"],
    )
    assert [f.rule for f in findings] == ["JGL013"] * 3
    messages = [f.message for f in findings]
    assert any(
        "direct os.environ read" in m and "'RAFT_NCUP_ALPHA'" in m
        for m in messages
    ), messages
    assert any("'RAFT_NCUP_GHOST'" in m for m in messages), messages
    assert any(
        "'RAFT_NCUP_DEAD'" in m and "ever reads it" in m for m in messages
    ), messages


def test_jgl013_registered_reads_and_non_knob_names_clean(tmp_path):
    """Getter reads of registered names are the sanctioned shape;
    non-prefixed env vars (PATH, _BENCH_* internals) are not knobs."""
    findings = lint_files(
        tmp_path,
        {
            "utils/knobs.py": """
            KNOBS = (
                Knob("RAFT_NCUP_ALPHA", "str", "a", "alpha knob"),
            )
            """,
            "mod.py": """
            import os
            from raft_ncup_tpu.utils.knobs import knob_str

            def f():
                good = knob_str("RAFT_NCUP_ALPHA")
                benign = os.environ.get("PATH")
                internal = os.environ.get("_BENCH_FORCE_PLATFORM")
                return good, benign, internal
            """,
        },
        select=["JGL013"],
    )
    assert findings == []


def test_jgl013_unread_half_needs_registry_and_drivers_in_scope(tmp_path):
    """A package-only lint sees the registry but not the driver entry
    points where most readers live — it cannot call a knob dead (the
    same scope-completeness gate JGL012 applies to drift). The other
    two halves still run per-site."""
    findings = lint_files(
        tmp_path,
        {
            "utils/knobs.py": """
            KNOBS = (
                Knob("RAFT_NCUP_ELSEWHERE", "str", "x",
                     "read only by an out-of-scope driver"),
            )
            """,
        },
        select=["JGL013"],
    )
    assert findings == []


def test_jgl013_runtime_registry_matches_static_declarations():
    """The shipped registry is importable pure-stdlib, every declared
    knob resolves through get(), and unregistered names raise — the
    runtime half that covers dynamic getter names JGL013 cannot see."""
    from raft_ncup_tpu.utils import knobs

    assert len(knobs.KNOBS) == len({k.name for k in knobs.KNOBS})
    for knob in knobs.KNOBS:
        assert knobs.get(knob.name) is knob
    with pytest.raises(KeyError):
        knobs.get("RAFT_NCUP_NOT_A_KNOB")


# ------------------------------------------------- astutil name resolution


def test_collect_aliases_edge_cases():
    import ast as _ast

    from raft_ncup_tpu.analysis.astutil import collect_aliases

    tree = _ast.parse(textwrap.dedent("""
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from os import path
        import threading
    """))
    aliases = collect_aliases(tree)
    assert aliases["np"] == "numpy"
    assert aliases["jnp"] == "jax.numpy"
    assert aliases["P"] == "jax.sharding.PartitionSpec"
    assert aliases["path"] == "os.path"
    assert aliases["threading"] == "threading"


def test_dotted_name_resolution_edge_cases():
    import ast as _ast

    from raft_ncup_tpu.analysis.astutil import (
        collect_aliases,
        dotted_name,
    )

    tree = _ast.parse("import numpy as np")
    aliases = collect_aliases(tree)

    def expr(src):
        return _ast.parse(src).body[0].value

    # Aliased import expands the leading segment only.
    assert dotted_name(expr("np.random.default_rng"), aliases) == (
        "numpy.random.default_rng"
    )
    # Attribute chains through self stay rooted at the literal name.
    assert dotted_name(expr("self.tel.registry.counter"), {}) == (
        "self.tel.registry.counter"
    )
    # Dynamic bases (subscripts, calls) are honestly unresolvable.
    assert dotted_name(expr("items[0].attr"), {}) is None
    assert dotted_name(expr("get_tel().inc"), {}) is None


def test_qualname_nested_functions():
    import ast as _ast

    from raft_ncup_tpu.analysis.astutil import attach_parents, qualname

    tree = _ast.parse(textwrap.dedent("""
        def outer():
            def inner():
                return probe
    """))
    attach_parents(tree)
    probe = next(
        n for n in _ast.walk(tree)
        if isinstance(n, _ast.Name) and n.id == "probe"
    )
    assert qualname(probe) == "outer.inner"


# -------------------------------------------------------- JSON output


def test_cli_json_output_schema(tmp_path):
    """`--format json` is a STABLE machine surface: top-level keys,
    per-finding keys, and the suppressed flag are pinned here so CI
    tooling can diff lint runs across versions."""
    import json as _json

    bad = tmp_path / "fleet" / "reg.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def add(self, key, value):
                with self._lock:
                    self._items[key] = value

            def peek(self, key):
                return self._items.get(key)
    """))
    proc = subprocess.run(
        [
            sys.executable, "-m", "raft_ncup_tpu.analysis",
            str(tmp_path), "--format", "json",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    payload = _json.loads(proc.stdout)
    assert set(payload) == {
        "files_checked", "findings", "parse_errors",
        "stale_allowlist_entries", "exit_code",
    }
    assert payload["exit_code"] == 1 and proc.returncode == 1
    assert payload["parse_errors"] == []
    assert payload["files_checked"] >= 1
    [finding] = payload["findings"]
    assert set(finding) == {
        "rule", "path", "line", "col", "qualname", "message", "suppressed",
    }
    assert finding["rule"] == "JGL011"
    assert finding["suppressed"] is False
    assert isinstance(finding["line"], int)


def test_cli_json_output_clean_tree_exits_zero(tmp_path):
    import json as _json

    good = tmp_path / "mod.py"
    good.write_text("x = 1\n")
    proc = subprocess.run(
        [
            sys.executable, "-m", "raft_ncup_tpu.analysis",
            str(tmp_path), "--format", "json",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    payload = _json.loads(proc.stdout)
    assert proc.returncode == 0
    assert payload["exit_code"] == 0
    assert payload["findings"] == []


# ------------------------------------------------------- knob catalog


def test_perf_md_names_every_registered_knob():
    """docs/PERF.md carries the generated knob catalog: every registered
    knob name appears there (regenerate with
    `python -m raft_ncup_tpu.utils.knobs`)."""
    from raft_ncup_tpu.utils import knobs

    with open(os.path.join(REPO, "docs", "PERF.md"), encoding="utf-8") as fh:
        text = fh.read()
    missing = [k.name for k in knobs.KNOBS if f"`{k.name}`" not in text]
    assert not missing, (
        f"knobs missing from docs/PERF.md (regenerate the catalog with "
        f"`python -m raft_ncup_tpu.utils.knobs`): {missing}"
    )


def test_catalog_markdown_covers_registry():
    from raft_ncup_tpu.utils import knobs

    table = knobs.catalog_markdown()
    for knob in knobs.KNOBS:
        assert f"`{knob.name}`" in table


# ------------------------------------------------------------ self-check


def test_whole_program_pass_stays_fast():
    """The project pass (one extra AST walk + three cross-module rules)
    must not turn lint.sh into a coffee break: the full tree-wide run,
    all rules, stays under 5 CPU-seconds. Budgeted on process time, not
    wall — the pass is single-threaded in-process work, and wall time on
    a loaded single-core CI host measures the host's OTHER tenants, not
    a lint regression."""
    import time as _time

    from raft_ncup_tpu.analysis.lint import DEFAULT_ALLOWLIST

    paths = [
        os.path.join(REPO, p)
        for p in (
            "raft_ncup_tpu", "train.py", "evaluate.py", "demo.py",
            "serve.py", "bench.py", "scripts",
        )
    ]
    t0 = _time.process_time()
    run_lint(paths, allowlist_path=DEFAULT_ALLOWLIST)
    assert _time.process_time() - t0 < 5.0


def test_shipped_tree_lints_clean_via_module_cli():
    """The acceptance contract: `python -m raft_ncup_tpu.analysis
    raft_ncup_tpu/` exits 0 on the shipped tree (allowlisted exceptions
    only). Run exactly as documented, from the repo root."""
    proc = subprocess.run(
        [sys.executable, "-m", "raft_ncup_tpu.analysis", "raft_ncup_tpu/"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, (
        f"graftlint found regressions:\n{proc.stdout}\n{proc.stderr}"
    )


def test_drivers_and_scripts_lint_clean():
    """lint.sh's wider scope (drivers, bench, scripts) stays clean too —
    in-process, so the tier-1 gate catches driver regressions without a
    subprocess."""
    from raft_ncup_tpu.analysis.lint import DEFAULT_ALLOWLIST

    paths = [
        os.path.join(REPO, p)
        for p in (
            "raft_ncup_tpu", "train.py", "evaluate.py", "demo.py",
            "serve.py", "bench.py", "scripts",
        )
    ]
    result = run_lint(paths, allowlist_path=DEFAULT_ALLOWLIST)
    assert result.findings == [], [f.render() for f in result.findings]
    assert result.parse_errors == []
    assert result.stale_entries == [], [
        e.render() for e in result.stale_entries
    ]
