"""Runtime guard rails (raft_ncup_tpu/analysis/guards.py).

The headline test pins PR 1's zero-per-step-sync claim as a regression
test: N steady-state training steps through the real pipeline
(FlowLoader over the synthetic dataset -> DevicePrefetcher -> jitted
train step -> device-accumulating Logger) run under
``forbid_host_transfers`` + ``max_recompiles(1)`` — one compile for the
step, zero forbidden host pulls, the Logger's single explicit
``jax.device_get`` per sum_freq window being the only sanctioned pull.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_ncup_tpu.analysis.guards import (
    GuardViolation,
    RecompileWatchdog,
    StepGuard,
    forbid_host_transfers as fht,
)
from raft_ncup_tpu.config import TrainConfig, small_model_config
from raft_ncup_tpu.data import DevicePrefetcher, FlowLoader
from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
from raft_ncup_tpu.parallel.step import make_train_step
from raft_ncup_tpu.training.logger import Logger
from raft_ncup_tpu.training.state import create_train_state


class TestForbidHostTransfers:
    def test_catches_float_pull(self, forbid_host_transfers):
        x = jnp.ones(()) * 2.0
        with pytest.raises(GuardViolation, match="device->host"):
            with forbid_host_transfers():
                float(x)

    def test_catches_np_asarray_pull(self, forbid_host_transfers):
        with pytest.raises(GuardViolation):
            with forbid_host_transfers():
                np.asarray(jnp.arange(4))

    def test_catches_item_and_bool(self, forbid_host_transfers):
        x = jnp.ones(())
        with pytest.raises(GuardViolation):
            with forbid_host_transfers():
                x.item()
        with pytest.raises(GuardViolation):
            with forbid_host_transfers():
                bool(x > 0)

    def test_explicit_device_get_sanctioned(self, forbid_host_transfers):
        x = jnp.arange(3)
        with forbid_host_transfers() as stats:
            out = jax.device_get(x)
        np.testing.assert_array_equal(out, [0, 1, 2])
        assert stats.host_transfers == 0
        assert stats.sanctioned_gets == 1

    def test_count_mode_does_not_raise(self):
        x = jnp.ones(())
        with fht(raise_on_violation=False) as stats:
            float(x)
            np.asarray(jnp.ones(2))
        assert stats.host_transfers == 2
        assert len(stats.violations) == 2

    def test_uninstalls_cleanly(self, forbid_host_transfers):
        x = jnp.ones(())
        with forbid_host_transfers():
            pass
        # outside the scope nothing is intercepted
        assert float(x) == 1.0
        np.asarray(x)

    def test_device_put_unaffected(self, forbid_host_transfers):
        # host->device (the prefetcher's direction) is not the guarded
        # class; the worker thread must keep transferring during a
        # guarded step.
        with forbid_host_transfers():
            y = jax.device_put(np.ones(3, np.float32))
        assert isinstance(y, jax.Array)


class TestRecompileWatchdog:
    def test_counts_compiles_and_cache_hits(self, max_recompiles):
        @jax.jit
        def f(a):
            return a * 2

        # Inputs created OUTSIDE the scope: jnp.ones itself dispatches a
        # tiny jitted program whose compile would otherwise be counted.
        a3, a4 = jnp.ones(3), jnp.ones(4)
        with max_recompiles(2) as wd:
            f(a3)
            f(a3)  # cache hit
            f(a4)  # new shape
        assert wd.count == 2

    def test_budget_violation_raises(self, max_recompiles):
        @jax.jit
        def f(a):
            return a + 1

        with pytest.raises(GuardViolation, match="drifting"):
            with max_recompiles(0):
                f(jnp.ones(5))

    def test_disarm_gates_counting(self):
        with RecompileWatchdog() as wd:
            wd.disarm()
            jax.jit(lambda a: a - 1)(jnp.ones(6))
            wd.arm()
        assert wd.count == 0


def test_steady_state_train_loop_sync_free_and_compile_once(tmp_path):
    """N steady-state steps of the real pipeline under
    ``forbid_host_transfers`` + ``max_recompiles(1)``: the PR-1 invariant
    (zero per-step host syncs, no steady-state recompilation) as an
    executable regression test.

    Two warm-up steps run first, outside the guards — they compile the
    step and its satellite programs (rng fold-in, the logger's on-device
    metric adds), exactly like bench.py's warm-up. The guarded window
    must then run transfer-free with at most the one compile the budget
    allows (measured: zero)."""
    B, H, W = 2, 16, 24
    warmup, steps = 2, 4
    mcfg = small_model_config(variant="raft")
    tcfg = TrainConfig(
        stage="chairs", lr=1e-4, num_steps=50, batch_size=B,
        image_size=(H, W), iters=2,
    )
    model, state = create_train_state(jax.random.key(0), mcfg, tcfg)
    step = make_train_step(model, tcfg)
    loader = FlowLoader(
        SyntheticFlowDataset((H, W), length=8, seed=3),
        batch_size=B, seed=11, num_workers=2,
        shard_index=0, num_shards=1,
    )
    # sum_freq=2: Logger window boundaries fire INSIDE the guarded run,
    # proving the explicit batched device_get is the sanctioned channel.
    logger = Logger(str(tmp_path), sum_freq=2, use_tensorboard=False)
    from raft_ncup_tpu.analysis.guards import (
        forbid_host_transfers as fht_ctx,
        max_recompiles as mr_ctx,
    )

    with DevicePrefetcher(loader.batches(), depth=2) as pf:
        for i in range(warmup):
            rng = jax.random.fold_in(jax.random.key(7), i)
            state, metrics = step(state, next(pf), rng)
            logger.push(i, metrics)
        with fht_ctx() as stats, mr_ctx(1) as wd:
            for i in range(warmup, warmup + steps):
                rng = jax.random.fold_in(jax.random.key(7), i)
                state, metrics = step(state, next(pf), rng)
                logger.push(i, metrics)
    logger.close()
    assert stats.host_transfers == 0, stats.violations
    assert wd.count <= 1  # steady state: measured 0, budget allows 1
    # sum_freq=2 boundaries at i=3 and i=5 pulled through the sanctioned
    # channel only
    assert stats.sanctioned_gets == steps // 2
    assert int(state.step) == warmup + steps


def test_step_guard_catches_planted_per_step_sync(tmp_path):
    """Plant the exact regression the guard exists for — a per-step
    float() on the loss — and watch it trip."""
    B, H, W = 2, 16, 24
    mcfg = small_model_config(variant="raft")
    tcfg = TrainConfig(
        stage="chairs", lr=1e-4, num_steps=50, batch_size=B,
        image_size=(H, W), iters=2,
    )
    model, state = create_train_state(jax.random.key(0), mcfg, tcfg)
    step = make_train_step(model, tcfg)
    loader = FlowLoader(
        SyntheticFlowDataset((H, W), length=4, seed=3),
        batch_size=B, seed=11, num_workers=2,
        shard_index=0, num_shards=1,
    )
    with DevicePrefetcher(loader.batches(), depth=2) as pf:
        with StepGuard() as guard:
            with pytest.raises(GuardViolation, match="device->host"):
                with guard.scope():
                    state, metrics = step(
                        state, next(pf), jax.random.key(7)
                    )
                    float(metrics["loss"])  # the planted per-step sync
    assert guard.stats.host_transfers == 1
