"""Precision-policy subsystem (raft_ncup_tpu/precision/; docs/PRECISION.md).

The acceptance contract of ROADMAP item 3, pinned as tests:

- policy semantics: presets resolve, the pinned dtypes (master weights,
  outputs, coords, accumulators) really are pinned, configs validate;
- ``fits_vmem`` budgets by element size, so bf16 exactly halves every
  per-level byte count and re-qualifies levels f32 rejects;
- MEASURED parity: the bf16 presets' predictions sit within the
  test-pinned EPE budget of f32 on the synthetic set — for the plain
  forward, the serving front-end, and the streaming warm-start chain —
  and a short bf16_train run tracks the f32 loss trajectory within
  ``TRAIN_LOSS_RTOL`` while every master-weight leaf stays f32;
- the executable caches can never collide policies: same shape, two
  policies, two entries, two compiles.

Everything runs the tiny RAFT-small model at 40x48 (the test suite's
standard real-model scale) on the rigid synthetic set — real flow
magnitudes, sharp boundaries — so the budgets measure real refinement
behavior, not toy zeros.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import (
    ModelConfig,
    ServeConfig,
    StreamConfig,
    TrainConfig,
    small_model_config,
)
from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.precision import (
    BF16_INFER,
    F32,
    FORWARD_EPE_BUDGET,
    PRESETS,
    TRAIN_LOSS_RTOL,
    PrecisionPolicy,
    resolve_policy,
)

HW = (40, 48)
ITERS = 2


def _epe(a: np.ndarray, b: np.ndarray) -> float:
    return float(
        np.sqrt(((np.asarray(a, np.float64) - np.asarray(b, np.float64)) ** 2)
                .sum(-1)).mean()
    )


# ------------------------------------------------------------ policy unit


class TestPolicySemantics:
    def test_presets_resolve(self):
        assert resolve_policy(None) is F32
        assert resolve_policy("bf16_infer") is BF16_INFER
        assert resolve_policy(BF16_INFER) is BF16_INFER
        assert set(PRESETS) == {"f32", "bf16_infer", "bf16_train"}

    def test_unknown_preset_raises(self):
        with pytest.raises(ValueError, match="unknown precision"):
            resolve_policy("fp8")

    def test_master_weights_and_outputs_are_pinned(self):
        """The policy CONSTRUCTOR rejects narrow master weights and
        narrow outputs — the pins are structural, not conventions."""
        with pytest.raises(ValueError, match="param_dtype"):
            PrecisionPolicy(name="bad", param_dtype="bfloat16")
        with pytest.raises(ValueError, match="output_dtype"):
            PrecisionPolicy(name="bad", output_dtype="bfloat16")

    def test_pinned_dtypes_ignore_compute(self):
        for pol in PRESETS.values():
            assert pol.coord_jnp == jnp.float32
            assert pol.acc_jnp == jnp.float32
            assert pol.norm_jnp == jnp.float32
            assert pol.upsampler_jnp == jnp.float32
            assert pol.param_jnp == jnp.float32

    def test_module_dtype_and_itemsize(self):
        assert F32.module_dtype is None  # input-dtype passthrough
        assert BF16_INFER.module_dtype == jnp.bfloat16
        assert F32.corr_itemsize == 4
        assert BF16_INFER.corr_itemsize == 2

    def test_norm_constant_matches_policy_pin(self):
        """nn/layers.py's named constants ARE the policy pins — a drift
        between them would silently fork the authority."""
        from raft_ncup_tpu.nn.layers import NORM_DTYPE, PARAM_DTYPE

        assert jnp.dtype(PARAM_DTYPE) == F32.param_jnp
        assert jnp.dtype(NORM_DTYPE) == F32.norm_jnp

    def test_config_validation(self):
        with pytest.raises(ValueError, match="precision"):
            ModelConfig(precision="fp8")
        with pytest.raises(ValueError, match="precision"):
            ServeConfig(precision="fp8")
        with pytest.raises(ValueError, match="precision"):
            StreamConfig(precision="fp8")

    def test_legacy_mixed_precision_maps_to_bf16_infer(self):
        assert ModelConfig(mixed_precision=True).precision_policy is BF16_INFER
        assert ModelConfig().precision_policy is F32
        # An explicit preset wins over the legacy bool.
        cfg = ModelConfig(precision="bf16_train", mixed_precision=True)
        assert cfg.precision_policy.name == "bf16_train"

    def test_explicit_f32_flag_beats_legacy_bool(self):
        """--precision f32 next to --mixed_precision must force f32 (the
        CLI zeroes the legacy bool whenever --precision is given — an
        explicit 'f32' is otherwise indistinguishable from the unset
        default)."""
        import argparse

        from raft_ncup_tpu.cli import add_model_args, model_config_from_args

        p = argparse.ArgumentParser()
        add_model_args(p)
        a = p.parse_args(["--mixed_precision", "--precision", "f32"])
        cfg = model_config_from_args(a, dataset="sintel")
        assert cfg.precision_policy is F32
        a = p.parse_args(["--mixed_precision"])
        cfg = model_config_from_args(a, dataset="sintel")
        assert cfg.precision_policy is BF16_INFER

    def test_serve_stream_inherit_model_policy_by_default(self, tiny_setup):
        """ServeConfig/StreamConfig precision defaults to None =
        'inherit the model's own policy': wrapping a bf16-configured
        model must not silently serve f32."""
        import dataclasses

        from raft_ncup_tpu.inference.pipeline import ShapeCachedForward
        from raft_ncup_tpu.models.raft import get_model

        model, variables, _ = tiny_setup
        assert ServeConfig().precision is None
        assert StreamConfig().precision is None
        m16 = get_model(
            dataclasses.replace(model.cfg, precision="bf16_infer")
        )
        fwd = ShapeCachedForward(m16, variables)  # the server's default
        assert fwd.policy.name == "bf16_infer"


# --------------------------------------------------- fits_vmem (satellite)


class TestFitsVmemItemsize:
    def test_bytes_scale_exactly_with_itemsize(self):
        from raft_ncup_tpu.ops.corr_pallas import _level_vmem_bytes

        for h, w, c in ((46, 96, 256), (135, 240, 256), (17, 33, 128)):
            assert (
                2 * _level_vmem_bytes(h, w, c, 4, itemsize=2)
                == _level_vmem_bytes(h, w, c, 4, itemsize=4)
            )

    def test_bf16_doubles_the_onchip_threshold(self):
        """The dispatch-threshold contract: scanning level heights, the
        largest level that fits at bf16 holds about twice the bytes of
        the largest that fits at f32 — i.e. there is a band of levels
        that f32 rejects and bf16 keeps on-chip."""
        from raft_ncup_tpu.ops.corr_pallas import fits_vmem

        c, r = 256, 4
        max_f32 = max_bf16 = 0
        for h in range(8, 600, 4):
            w = 2 * h
            if fits_vmem(h, w, c, r):
                max_f32 = h
            if fits_vmem(h, w, c, r, dtype=jnp.bfloat16):
                max_bf16 = h
        assert 0 < max_f32 < max_bf16
        # Byte threshold doubles => area threshold doubles => linear
        # dimension grows ~sqrt(2) (padding shifts it slightly).
        assert max_bf16 >= 1.3 * max_f32
        # And the band really exists: a level just above the f32 cut
        # takes the kernel at bf16.
        band_h = max_f32 + 4
        assert not fits_vmem(band_h, 2 * band_h, c, r)
        assert fits_vmem(band_h, 2 * band_h, c, r, dtype=jnp.bfloat16)

    def test_banded_budget_scales_exactly_with_itemsize(self):
        """The band-budget extension of the itemsize contract: the
        BANDED tier's VMEM bytes (_banded_vmem_bytes — single-buffered
        band slab + query blocks + scratch) halve exactly at bf16, for
        any band geometry."""
        from raft_ncup_tpu.ops.corr_pallas import _banded_vmem_bytes

        for h, w, c, br in (
            (136, 240, 256, 8), (272, 480, 256, 8), (68, 120, 128, 32),
        ):
            assert (
                2 * _banded_vmem_bytes(h, w, c, 4, br, itemsize=2)
                == _banded_vmem_bytes(h, w, c, 4, br, itemsize=4)
            )

    def test_bf16_buys_wider_bands(self):
        """Threshold ratio at the banded tier: bf16 halves the per-row
        slab bytes, so band_plan's auto choice gets wider bands (fewer
        bands, fewer slab DMAs) at the same budget — pinned at the 4K
        and 1080p level-0 shapes."""
        from raft_ncup_tpu.ops.corr_pallas import band_plan

        for h, w in ((272, 480), (136, 240)):
            f32_plan = band_plan(h, w, 256, 4)
            b16_plan = band_plan(h, w, 256, 4, dtype=jnp.bfloat16)
            assert f32_plan is not None and b16_plan is not None
            assert b16_plan[0] > f32_plan[0]  # wider bands
            assert b16_plan[1] <= f32_plan[1]  # never more bands

    def test_pallas_dispatch_uses_policy_dtype(self):
        """corr_lookup_pallas at a shape in the bf16-only band routes
        MORE levels to the kernel under the bf16 policy than under f32
        (trace-time dispatch counts; interpret mode, no TPU needed)."""
        from raft_ncup_tpu.ops import corr_pallas as cp

        if cp.pltpu is None:
            pytest.skip("pallas-tpu unavailable in this jax build")
        rng = np.random.default_rng(5)
        B, H, W, C = 1, 8, 8, 16
        f1 = jnp.asarray(rng.normal(size=(B, H, W, C)), jnp.float32)
        f2 = jnp.asarray(rng.normal(size=(B, H, W, C)), jnp.float32)
        coords = jnp.asarray(
            rng.uniform(0, 7, size=(B, H, W, 2)), jnp.float32
        )
        out32 = cp.corr_lookup_pallas(f1, f2, coords, 3, 2, True)
        out16 = cp.corr_lookup_pallas(
            f1, f2, coords, 3, 2, True, jnp.bfloat16
        )
        assert out32.dtype == jnp.float32 and out16.dtype == jnp.float32
        # bf16 storage, f32 accumulation: small relative error only.
        np.testing.assert_allclose(
            np.asarray(out16), np.asarray(out32), rtol=0.05, atol=0.05
        )


# ------------------------------------------------------- model-level setup


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = small_model_config("raft", dataset="chairs")
    model = RAFT(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1,) + HW + (3,))
    ds = SyntheticFlowDataset(HW, length=4, seed=123, style="rigid")
    return model, variables, ds


def _stack(ds, idx):
    s = [ds.sample(i) for i in idx]
    img1 = np.stack([x["image1"] for x in s]).astype(np.float32)
    img2 = np.stack([x["image2"] for x in s]).astype(np.float32)
    gt = np.stack([x["flow"] for x in s]).astype(np.float32)
    return img1, img2, gt


# -------------------------------- cache keys (satellite) + forward parity


@pytest.fixture(scope="module")
def fwd_pair(tiny_setup):
    """ONE ShapeCachedForward driven under both policies on the same
    4-frame batch — the two compiles every test in this section shares
    (tier-1 budget: the suite runs against a hard wall clock, so the
    f32/bf16 executables compile once here, not once per test)."""
    from raft_ncup_tpu.inference.pipeline import ShapeCachedForward

    model, variables, ds = tiny_setup
    img1, img2, gt = _stack(ds, [0, 1, 2, 3])
    fwd = ShapeCachedForward(model, variables)
    out32 = jax.device_get(
        fwd.forward_device(img1, img2, ITERS, policy="f32")
    )
    out16 = jax.device_get(
        fwd.forward_device(img1, img2, ITERS, policy="bf16_infer")
    )
    return fwd, (img1, img2, gt), out32, out16


class TestPolicyCacheKeys:
    def test_two_policies_two_entries_two_compiles(self, fwd_pair):
        """Same shape, two policies: the LRU holds TWO executables and
        the compiles counter reads 2 — an f32 and a bf16 program can
        never collide on a shape key (the regression the policy
        fingerprint in the key exists to prevent)."""
        fwd, (img1, img2, _), _, _ = fwd_pair
        assert fwd.stats["compiles"] == 2
        assert len(fwd._fns) == 2
        # Repeat calls hit, never recompile; the instance policy (f32
        # default here) keys identically to naming it explicitly.
        hits0 = fwd.stats["hits"]
        fwd.forward_device(img1, img2, ITERS)
        fwd.forward_device(img1, img2, ITERS, policy="bf16_infer")
        assert fwd.stats["compiles"] == 2
        assert fwd.stats["hits"] == hits0 + 2


class TestForwardParity:
    def test_bf16_forward_within_epe_budget(self, fwd_pair):
        """The headline contract: bf16_infer's prediction sits within
        the test-pinned EPE budget of the f32 prediction on the rigid
        synthetic set, and the EPE-vs-ground-truth of the two runs
        agrees to the same budget."""
        _, (_, _, gt), (_, up32), (_, up16) = fwd_pair
        assert np.isfinite(up16).all()
        delta = _epe(up16, up32)
        assert 0.0 < delta <= FORWARD_EPE_BUDGET, delta
        # Metric-level agreement: the two runs' EPE-vs-gt differ by at
        # most the field budget (triangle inequality made concrete).
        assert abs(_epe(up16, gt) - _epe(up32, gt)) <= FORWARD_EPE_BUDGET

    def test_outputs_and_carry_stay_f32_under_bf16(self, fwd_pair):
        """Policy pins, observed at the output surface: the low-res
        flow (coordinate carry) and the upsampled field come back f32
        from the bf16 executable."""
        fwd, (img1, img2, _), _, _ = fwd_pair
        flow_lr, flow_up = fwd.forward_device(
            img1, img2, ITERS, policy="bf16_infer"
        )
        assert flow_lr.dtype == jnp.float32
        assert flow_up.dtype == jnp.float32

    def test_metric_accumulate_upcasts_to_f32(self):
        """The accumulator pin at the fold itself (no compile needed):
        a bf16 prediction folded into the f32 accumulator yields f32
        sums — bf16 forwards change the flow, never the metric
        arithmetic."""
        from raft_ncup_tpu.inference import metrics as metrics_mod

        flow16 = jnp.ones((1, 8, 8, 2), jnp.bfloat16)
        gt = jnp.zeros((1, 8, 8, 2), jnp.float32)
        acc = metrics_mod.accumulate(
            "epe", metrics_mod.init_acc("epe"), flow16, gt
        )
        assert acc.dtype == jnp.float32
        out = metrics_mod.finalize("epe", np.asarray(acc))
        assert np.isfinite(out["epe"])


# ----------------------------------------------------- serving parity


class TestServingParity:
    @pytest.mark.slow
    def test_bf16_server_within_budget_of_f32_forward(self, tiny_setup):
        """Slow tier (tier-1 runs against a hard wall clock and this
        compiles a server's own program set): the fast tier keeps the
        forward-parity budget + the policy-keyed cache contract, the
        CLI drive (.claude/skills/verify) and the guarded
        `serve_*_bf16` bench row re-measure this path end to end."""
        from raft_ncup_tpu.inference.pipeline import ShapeCachedForward
        from raft_ncup_tpu.serving import FlowServer

        model, variables, ds = tiny_setup
        img1, img2, _ = _stack(ds, [1])
        cfg = ServeConfig(
            batch_sizes=(1,), iter_levels=(ITERS,),
            precision="bf16_infer",
        )
        with FlowServer(model, variables, cfg) as srv:
            r = srv.submit(img1[0], img2[0]).result(180)
        assert r.ok, r.status
        fwd = ShapeCachedForward(model, variables)
        _, ref = fwd(img1, img2, ITERS)
        delta = _epe(r.flow, ref[0])
        assert 0.0 < delta <= FORWARD_EPE_BUDGET, delta

    def test_report_names_the_policy(self, tiny_setup):
        from raft_ncup_tpu.serving import FlowServer

        model, variables, _ = tiny_setup
        cfg = ServeConfig(batch_sizes=(1,), iter_levels=(ITERS,),
                          precision="bf16_infer")
        with FlowServer(model, variables, cfg) as srv:
            assert srv.report()["precision"] == "bf16_infer"


# ------------------------------------------------ streaming warm-start


class TestStreamingParity:
    def _run_stream(self, model, variables, ds, precision):
        from raft_ncup_tpu.streaming import StreamEngine

        cfg = StreamConfig(
            capacity=1, frame_hw=HW, iters=ITERS, batch_sizes=(1,),
            precision=precision,
        )
        flows = []
        with StreamEngine(model, variables, cfg) as eng:
            if precision != "f32":
                assert eng._table["flow"].dtype == jnp.bfloat16
            else:
                assert eng._table["flow"].dtype == jnp.float32
            for i in range(2):
                s = ds.sample(i)
                r = eng.submit(
                    "cam0",
                    np.asarray(s["image1"], np.float32),
                    np.asarray(s["image2"], np.float32),
                    frame_index=i,
                ).result(180)
                assert r.ok, r.status
                flows.append(np.asarray(r.flow))
        return flows

    @pytest.mark.slow
    def test_bf16_warm_start_chain_within_budget(self, tiny_setup):
        """Two consecutive frames of one stream — the second warm-starts
        from the (bf16-stored) slot table. Every frame of the bf16
        engine sits within the EPE budget of the f32 engine's frame, so
        narrow state storage does not drift the warm chain. Slow tier
        (two engines' step programs): the slot-table dtype itself is
        asserted here, and the `stream_*_bf16` bench row + the chaos CLI
        drive re-measure the path end to end."""
        model, variables, ds = tiny_setup
        f32_flows = self._run_stream(model, variables, ds, "f32")
        bf16_flows = self._run_stream(model, variables, ds, "bf16_infer")
        for k, (a, b) in enumerate(zip(f32_flows, bf16_flows)):
            assert _epe(b, a) <= FORWARD_EPE_BUDGET, (k, _epe(b, a))


# ------------------------------------------------------- train parity


class TestTrainParity:
    def _run_short_train(self, precision, steps=5):
        from raft_ncup_tpu.parallel.step import (
            make_synthetic_batch,
            make_train_step,
        )
        from raft_ncup_tpu.training.state import create_train_state

        model_cfg = small_model_config(
            "raft", dataset="chairs", precision=precision
        )
        train_cfg = TrainConfig(
            stage="chairs", batch_size=2, image_size=HW, iters=ITERS,
            num_steps=steps, precision=precision,
        )
        model, state = create_train_state(
            jax.random.PRNGKey(7), model_cfg, train_cfg,
            image_shape=(1,) + HW + (3,),
        )
        step = make_train_step(model, train_cfg)
        losses = []
        for i in range(steps):
            batch = make_synthetic_batch(
                jax.random.PRNGKey(100 + i), 2, *HW
            )
            rng = jax.random.fold_in(jax.random.PRNGKey(7), i)
            state, metrics = step(state, batch, rng)
            losses.append(float(jax.device_get(metrics["loss"])))
        return state, losses

    @pytest.mark.slow
    def test_bf16_train_tracks_f32_loss_trajectory(self):
        """The phase-2 contract: a short bf16_train run's per-step loss
        trajectory stays within TRAIN_LOSS_RTOL of f32 (identical init,
        identical batches), and the master weights/optimizer/sentinel
        arithmetic never narrow. Slow tier: two fwd+bwd compiles (the
        suite's convention for its most expensive real-model runs —
        cf. the streaming bitwise-isolation tests)."""
        state32, l32 = self._run_short_train("f32")
        state16, l16 = self._run_short_train("bf16_train")
        assert all(np.isfinite(l16))
        np.testing.assert_allclose(l16, l32, rtol=TRAIN_LOSS_RTOL)
        # bf16 compute really ran: trajectories differ beyond float noise.
        assert max(abs(a - b) for a, b in zip(l16, l32)) > 0.0
        # f32 master weights: every param and Adam-moment leaf is f32.
        for leaf in jax.tree.leaves(state16.params):
            assert leaf.dtype == jnp.float32
        for leaf in jax.tree.leaves(state16.opt_state):
            if hasattr(leaf, "dtype") and jnp.issubdtype(
                leaf.dtype, jnp.floating
            ):
                assert leaf.dtype == jnp.float32
        # Sentinel arithmetic untouched by the preset.
        assert state16.sentinel["ema_grad_norm"].dtype == jnp.float32

    def test_step_cache_keys_on_precision(self):
        """make_train_step memoization cannot hand a bf16 config the f32
        executable: the model config (which carries `precision`) is in
        the cache key."""
        from raft_ncup_tpu.parallel.step import _step_cache_key

        cfg32 = small_model_config("raft", dataset="chairs")
        cfg16 = small_model_config(
            "raft", dataset="chairs", precision="bf16_train"
        )
        t = TrainConfig(stage="chairs", batch_size=2, image_size=HW)
        assert _step_cache_key(cfg32, t, None) != _step_cache_key(
            cfg16, t, None
        )


# -------------------------------------------------- evaluation surface


def test_validators_accept_precision(tiny_setup, tmp_path):
    """validate_synthetic runs end to end under an explicit bf16 policy
    and returns a finite EPE within the budget of the f32 pass."""
    from raft_ncup_tpu.evaluation import validate_synthetic

    model, variables, _ = tiny_setup
    kwargs = dict(
        iters=ITERS, batch_size=2, size_hw=HW, length=2, style="rigid",
    )
    r32 = validate_synthetic(model, variables, **kwargs)
    r16 = validate_synthetic(
        model, variables, precision="bf16_infer", **kwargs
    )
    key = "synthetic_rigid"
    assert np.isfinite(r16[key])
    assert abs(r16[key] - r32[key]) <= FORWARD_EPE_BUDGET


def test_get_model_registry_distinguishes_precisions(tiny_setup):
    """dataclasses.replace on precision reaches a distinct (cached)
    model whose modules compute at the preset's dtype."""
    from raft_ncup_tpu.models.raft import get_model

    model, _, _ = tiny_setup
    cfg16 = dataclasses.replace(model.cfg, precision="bf16_infer")
    m16 = get_model(cfg16)
    assert m16 is not model
    assert m16.policy.name == "bf16_infer"
    assert m16 is get_model(cfg16)  # lru-cached
