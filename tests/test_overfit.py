"""Integration check: overfit one synthetic batch (SURVEY.md §4 layer 4).

A RAFT-small model trained on a single fixed batch must drive EPE far
below its initial value — exercising the full loss/optimizer/scan/remat
path, not just one step's direction.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import TrainConfig, small_model_config
from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
from raft_ncup_tpu.parallel.step import make_train_step
from raft_ncup_tpu.training.state import create_train_state


# Tier-2: ~160s of real optimization — the single heaviest test in the
# tree. Convergence stays covered every run by the cheaper loss-descent
# checks; this full overfit demonstration runs in the unfiltered suite.
@pytest.mark.slow
def test_overfit_one_batch():
    H, W = 48, 64
    ds = SyntheticFlowDataset((H, W), length=2, seed=7, max_mag=4.0)
    samples = [ds.sample(i) for i in range(2)]
    batch = {
        "image1": jnp.stack(
            [jnp.asarray(s["image1"], jnp.float32) for s in samples]
        ),
        "image2": jnp.stack(
            [jnp.asarray(s["image2"], jnp.float32) for s in samples]
        ),
        "flow": jnp.stack([jnp.asarray(s["flow"]) for s in samples]),
        "valid": jnp.stack([jnp.asarray(s["valid"]) for s in samples]),
    }

    mcfg = small_model_config("raft", dataset="chairs")
    tcfg = TrainConfig(
        stage="chairs",
        batch_size=2,
        image_size=(H, W),
        iters=4,
        num_steps=120,
        lr=2e-4,
        scheduler="step",
        scheduler_step=1000,
    )
    model, state = create_train_state(
        jax.random.PRNGKey(0), mcfg, tcfg, (1, H, W, 3)
    )
    step = make_train_step(model, tcfg)

    first_epe = None
    epe = None
    for i in range(120):
        state, metrics = step(state, batch, jax.random.PRNGKey(i))
        if first_epe is None:
            first_epe = float(metrics["epe"])
        epe = float(metrics["epe"])

    assert np.isfinite(epe)
    # Synthetic smooth flow of magnitude ~2px: random init starts around
    # 2-3 EPE; a working training path overfits well below half of that.
    assert epe < first_epe * 0.35, (first_epe, epe)
    assert epe < 1.0, (first_epe, epe)
