"""End-to-end resilience: the chaos harness (resilience/chaos.py) driven
through the REAL pipeline — synthetic dataset → FlowLoader (+retry) →
DevicePrefetcher → sentinel-guarded jitted step → Logger → orbax — via
``train.main``. The acceptance contracts of docs/RESILIENCE.md:

- injected NaN batch ⇒ that step is a skip-update, the run continues,
  skip counters land in log.txt;
- K consecutive bad steps ⇒ halt, rollback to the last good checkpoint,
  EXIT_DIVERGED;
- SIGTERM mid-run ⇒ one atomic checkpoint, EXIT_PREEMPTED, and a resumed
  run whose loss trajectory is bitwise-identical to an uninterrupted one;
- injected IOError ⇒ retried with backoff, accounted, run unaffected;
- all of it under ``--strict_guards``: 0 steady-state recompiles, 0
  forbidden host transfers.

The in-process tests use chaos's step-pinned self-SIGTERM (the same
handler path as an external kill, deterministic); the slow test spawns a
real child train process and SIGTERMs it from outside.
"""

from __future__ import annotations

import os
import re
import signal
import subprocess
import sys
import time

import pytest

from raft_ncup_tpu.resilience import EXIT_DIVERGED, EXIT_PREEMPTED

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _args(tmp_path, name, extra):
    return [
        "--name", name,
        "--model", "raft",
        "--small",
        "--stage", "chairs",
        "--image_size", "16", "32",
        "--batch_size", "2",
        "--iters", "1",
        "--sum_freq", "1",
        "--val_freq", "100",
        "--synthetic_ok",
        "--num_workers", "1",
        "--data_parallel", "1",
        "--checkpoint_dir", str(tmp_path / "checkpoints"),
        "--root_chairs", str(tmp_path / "missing"),
    ] + extra


def _run(tmp_path, name, extra):
    import train as train_driver

    return train_driver.main(_args(tmp_path, name, extra))


def _log(tmp_path, name) -> str:
    return (tmp_path / "checkpoints" / name / "log.txt").read_text()


def _flight_dumps(tmp_path, name) -> list:
    """Flight-recorder dumps a run left under its run dir (sorted)."""
    d = tmp_path / "checkpoints" / name / "flight"
    return sorted(os.listdir(d)) if d.exists() else []


def _postmortem(argv):
    """Run scripts/postmortem.py in-process; returns its exit code."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "postmortem", os.path.join(REPO, "scripts", "postmortem.py")
    )
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    return pm.main(argv)


def _trajectory(log: str) -> dict:
    """step -> the summary line's metric portion. The it/s field is
    wall-clock (never reproducible); everything after it — the loss and
    metric means printed at 1e-4 — must be."""
    out = {}
    for line in log.splitlines():
        m = re.match(r"\[\s*(\d+) .*it/s\](.*)$", line)
        if m:
            out[int(m.group(1))] = m.group(2)
    return out


# Tier-2: ~80s (two full in-process train runs plus a resume). The
# SIGTERM-save path itself stays tier-1 via the cheaper preemption
# tests; the bitwise resumed-trajectory pin runs in the unfiltered
# suite.
@pytest.mark.slow
def test_kill_resume_bitwise_identical_trajectory(tmp_path):
    """SIGTERM after step 4 ⇒ atomic checkpoint + EXIT_PREEMPTED; the
    resumed run's steps 5..7 match an uninterrupted run's bit-for-bit.
    The uninterrupted run additionally absorbs an injected IOError
    (retried + accounted) — which must NOT perturb its trajectory, or
    the comparison below fails."""
    rc = _run(tmp_path, "solo", ["--num_steps", "7", "--chaos", "ioerror@6"])
    assert rc == 0
    log_solo = _log(tmp_path, "solo")
    assert "io-retry: retries=1 giveups=0" in log_solo

    # val_freq=4 makes step 4 BOTH a boundary save and the preemption
    # step: the preempted path must notice the step is already on disk
    # and not re-save (orbax raises StepAlreadyExists on a re-save,
    # which would turn the clean 75 exit into a crash).
    rc = _run(
        tmp_path, "killed",
        ["--num_steps", "7", "--val_freq", "4", "--chaos", "sigterm@4"],
    )
    assert rc == EXIT_PREEMPTED
    run_dir = tmp_path / "checkpoints" / "killed"
    assert (run_dir / "4").exists()  # the one atomic preemption save
    assert (run_dir / "resume_meta.json").exists()
    assert "preempted @ 4" in _log(tmp_path, "killed")
    # The clean solo run left NO flight dumps; the preempted run left
    # exactly ONE, for the drain trigger, naming the saved step
    # (observability/flight.py; docs/OBSERVABILITY.md trigger matrix).
    assert _flight_dumps(tmp_path, "solo") == []
    dumps = _flight_dumps(tmp_path, "killed")
    assert len(dumps) == 1 and dumps[0].startswith(
        "flight_preemption_drain_"
    )
    import json as _json

    dump = _json.load(open(run_dir / "flight" / dumps[0]))
    assert dump["context"] == {"step": 4, "checkpoint_step": 4}
    assert dump["report"]["health"]["train"]["state"] == "draining"

    rc = _run(
        tmp_path, "killed",
        ["--num_steps", "7", "--restore_ckpt", str(run_dir)],
    )
    assert rc == 0
    log_resumed = _log(tmp_path, "killed")
    assert "restored step 4" in log_resumed
    # The clean resume added no dump: still exactly one.
    assert _flight_dumps(tmp_path, "killed") == dumps

    solo, resumed = _trajectory(log_solo), _trajectory(log_resumed)
    assert set(range(1, 8)) <= set(solo)
    for step in (5, 6, 7):  # the post-resume steps
        assert resumed[step] == solo[step], (
            f"step {step} diverged after resume:\n"
            f"  uninterrupted: {solo[step]}\n"
            f"  resumed:       {resumed[step]}"
        )


def test_nan_chaos_under_strict_guards_skips_and_stays_sync_free(tmp_path):
    """A NaN batch mid-run: the sentinel skips it, counters reach
    log.txt, the run completes cleanly — and the strict guards prove the
    sentinel added no per-step host sync and no steady-state recompile."""
    rc = _run(
        tmp_path, "strict",
        ["--num_steps", "6", "--sum_freq", "2", "--strict_guards",
         "--chaos", "nan@2"],
    )
    assert rc == 0
    log = _log(tmp_path, "strict")
    assert "chaos: NaN flow injected into the batch for step 2" in log
    assert "sentinel @ 4: skipped=1" in log
    assert "steady_recompiles=0" in log
    assert "host_transfers=0" in log


def test_consecutive_bad_steps_halt_and_roll_back(tmp_path):
    """K consecutive bad steps ⇒ halt with EXIT_DIVERGED and rollback to
    the last good checkpoint. Steps 0-2 are good; the val_freq=2
    boundary saves at steps 2 and 4 (skip-updates keep the params
    last-good, so the step-4 save is still a good state); bad steps 3+
    trip the halt at consecutive=3."""
    nan = ",".join(f"nan@{s}" for s in range(3, 9))
    rc = _run(
        tmp_path, "diverge",
        ["--num_steps", "10", "--val_freq", "2",
         "--sentinel_halt_after", "3", "--chaos", nan],
    )
    assert rc == EXIT_DIVERGED
    log = _log(tmp_path, "diverge")
    assert "sentinel halt @ 6" in log
    assert "rolled back to last good checkpoint (step 4)" in log
    run_dir = tmp_path / "checkpoints" / "diverge"
    assert (run_dir / "4").exists()
    # The halt path must NOT have saved the post-halt state: no step
    # directory beyond the last boundary save.
    steps = sorted(int(d) for d in os.listdir(run_dir) if d.isdigit())
    assert steps[-1] == 4


def test_sentinel_halt_leaves_one_flight_dump_postmortem_reads(
    tmp_path, capsys
):
    """The rc-76 half of the flight-recorder acceptance: a sentinel-halt
    run leaves EXACTLY one valid dump (trigger sentinel_halt, health
    train=halted, the halt's step/consecutive context), and
    scripts/postmortem.py reassembles the fault's timeline from it —
    the train_sentinel_halt event is on the printed journey."""
    nan = ",".join(f"nan@{s}" for s in range(2, 8))
    rc = _run(
        tmp_path, "halted",
        ["--num_steps", "10", "--val_freq", "100",
         "--sentinel_halt_after", "3", "--chaos", nan],
    )
    assert rc == EXIT_DIVERGED
    dumps = _flight_dumps(tmp_path, "halted")
    assert len(dumps) == 1 and dumps[0].startswith(
        "flight_sentinel_halt_"
    )
    path = str(tmp_path / "checkpoints" / "halted" / "flight" / dumps[0])
    import json as _json

    dump = _json.load(open(path))
    assert dump["context"]["consecutive"] >= 3
    assert dump["report"]["health"]["train"]["state"] == "halted"
    capsys.readouterr()
    assert _postmortem([path]) == 0
    out = capsys.readouterr().out
    assert "trigger:      sentinel_halt" in out
    assert "train=halted" in out
    assert "train_sentinel_halt" in out  # the halt event on the journey


@pytest.mark.slow
def test_child_process_external_sigterm_kill_resume(tmp_path):
    """The satellite contract, with a real OS boundary: spawn a child
    train run, SIGTERM it from OUTSIDE mid-run, resume from its
    checkpoint, and the continued loss trajectory is bitwise-identical
    to an uninterrupted child run."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # NOT opting into RAFT_NCUP_COMPILATION_CACHE here: this host's XLA
    # CPU cache entries have produced glibc heap corruption on reload
    # (observed as SIGABRT in the resumed child). Cold compiles are
    # slower but deterministic.

    def spawn(name, extra):
        return subprocess.Popen(
            [sys.executable, os.path.join(REPO, "train.py")]
            + _args(tmp_path, name, extra),
            cwd=REPO,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )

    steps = 60
    proc = spawn("solo_child", ["--num_steps", str(steps)])
    out, err = proc.communicate(timeout=540)
    assert proc.returncode == 0, f"uninterrupted child failed:\n{out}\n{err}"
    solo = _trajectory(_log(tmp_path, "solo_child"))
    assert set(range(1, steps + 1)) <= set(solo)

    # Killed run: wait until the log shows real step progress (past
    # compile), then deliver a genuine external SIGTERM.
    proc = spawn("killed_child", ["--num_steps", str(steps)])
    log_path = tmp_path / "checkpoints" / "killed_child" / "log.txt"
    deadline = time.monotonic() + 480
    while time.monotonic() < deadline:
        if log_path.exists() and _trajectory(log_path.read_text()):
            break
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    assert proc.poll() is None, "child finished before it could be killed"
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=540)
    assert proc.returncode == EXIT_PREEMPTED, (
        f"killed child rc={proc.returncode}\n{out}\n{err}"
    )
    log = _log(tmp_path, "killed_child")
    assert "preempted @" in log
    run_dir = tmp_path / "checkpoints" / "killed_child"
    saved = sorted(int(d) for d in os.listdir(run_dir) if d.isdigit())
    assert saved, "preemption saved no checkpoint"

    proc = spawn(
        "killed_child",
        ["--num_steps", str(steps), "--restore_ckpt", str(run_dir)],
    )
    out, err = proc.communicate(timeout=540)
    assert proc.returncode == 0, f"resumed child failed:\n{out}\n{err}"
    resumed = _trajectory(_log(tmp_path, "killed_child"))
    resume_from = saved[-1]
    post = [s for s in range(resume_from + 1, steps + 1)]
    assert post, "kill landed at the very end; nothing to compare"
    for step in post:
        assert resumed[step] == solo[step], (
            f"step {step} diverged after resume"
        )
