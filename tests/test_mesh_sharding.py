"""Mesh-first inference/serving/streaming (docs/SHARDING.md).

Sharding regressions must fail fast, not only under ``-m slow``
(tests/test_highres.py keeps the 1080p-scale claims): these tests run
the REAL subsystems on the forced 8-virtual-device CPU platform
(tests/conftest.py) at small shapes and pin

- ``make_mesh`` device-coverage honesty (a stripped device is a loud
  warning, never silence),
- the mesh fingerprint in every ``ShapeCachedForward`` cache key
  (sharded and unsharded executables can never collide),
- sharded-vs-unsharded numerical parity for the forward, the serving
  data path, the streaming warm-start step, and an eval validator pass,
- the guard-clean steady state (zero implicit host transfers, zero
  steady-state recompiles) under the mesh.
"""

from __future__ import annotations

import warnings

import jax
import numpy as np
import pytest

from raft_ncup_tpu.config import (
    ServeConfig,
    StreamConfig,
    small_model_config,
)
from raft_ncup_tpu.inference.pipeline import ShapeCachedForward
from raft_ncup_tpu.models import get_model
from raft_ncup_tpu.parallel.mesh import make_mesh, mesh_fingerprint

HW = (32, 32)  # h8=4: divides spatial=2, tiny compiles


@pytest.fixture(scope="module")
def small_model():
    cfg = small_model_config("raft", dataset="chairs")
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, *HW, 3))
    return model, variables


def _mesh(data=1, spatial=2):
    return make_mesh(
        data=data, spatial=spatial, devices=jax.devices()[: data * spatial]
    )


def _img(seed, hw=HW, batch=1):
    g = np.random.default_rng(seed)
    return (g.random((batch, *hw, 3)) * 255.0).astype(np.float32)


# ------------------------------------------------------------- make_mesh


class TestMakeMesh:
    def test_warns_loudly_when_devices_stripped(self):
        """Satellite regression: data*spatial < n used to silently strip
        the extra devices — a mis-sized mesh that idles 6 of 8 chips
        must announce itself."""
        with pytest.warns(UserWarning, match="only 2 of 8"):
            mesh = make_mesh(data=1, spatial=2)
        assert dict(mesh.shape) == {"data": 1, "spatial": 2}

    def test_exact_coverage_is_silent(self):
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            mesh = make_mesh(data=4, spatial=2)  # exactly the 8 devices
            make_mesh(data=1, spatial=2, devices=jax.devices()[:2])
        assert dict(mesh.shape) == {"data": 4, "spatial": 2}

    def test_oversubscription_still_raises(self):
        with pytest.raises(ValueError, match="needs 16 devices"):
            make_mesh(data=8, spatial=2)

    def test_fingerprint_identity(self):
        assert mesh_fingerprint(None) == "nomesh"
        fp = mesh_fingerprint(_mesh(1, 2))
        assert fp == "mesh(data=1,spatial=2:cpu)"
        assert fp != mesh_fingerprint(_mesh(2, 1))

    def test_pipe_axis_mesh_and_fingerprint(self):
        """The third axis (docs/SHARDING.md "Pipeline axis"): explicit
        pipe>1 grows the mesh and the fingerprint; every compiled-
        program key downstream inherits the distinction for free."""
        mesh = make_mesh(
            data=1, spatial=1, pipe=4, devices=jax.devices()[:4]
        )
        assert dict(mesh.shape) == {"data": 1, "spatial": 1, "pipe": 4}
        assert mesh_fingerprint(mesh) == "mesh(data=1,spatial=1,pipe=4:cpu)"
        # data=None spans all devices after spatial*pipe partitioning.
        auto = make_mesh(spatial=1, pipe=4)
        assert dict(auto.shape) == {"data": 2, "spatial": 1, "pipe": 4}
        with pytest.raises(ValueError, match="not divisible by spatial"):
            make_mesh(spatial=1, pipe=3)

    def test_pipe_default_is_the_identical_two_axis_mesh(self):
        """pipe=1 must yield the exact 2-axis mesh this function always
        built — same axis names, same fingerprint — so no existing
        cache key or bench provenance string changes under the
        default."""
        a = _mesh(1, 2)
        b = make_mesh(
            data=1, spatial=2, pipe=1, devices=jax.devices()[:2]
        )
        assert tuple(b.axis_names) == ("data", "spatial")
        assert dict(a.shape) == dict(b.shape)
        assert mesh_fingerprint(a) == mesh_fingerprint(b)

    def test_resolve_config_mesh_accepts_pipe_triple(self):
        from raft_ncup_tpu.parallel.mesh import resolve_config_mesh

        mesh, div = resolve_config_mesh(None, (1, 1, 4))
        assert dict(mesh.shape) == {"data": 1, "spatial": 1, "pipe": 4}
        # The pipe axis never shards image dims: pad divisor is still
        # 8 * spatial.
        assert div == 8
        mesh2, div2 = resolve_config_mesh(None, (1, 2))
        assert dict(mesh2.shape) == {"data": 1, "spatial": 2}
        assert div2 == 16


# ------------------------------------------------------ collective_stats


class TestCollectiveStats:
    """Per-op-kind breakout (``by_op``) next to the aggregate counters
    the highres/uhd bench rows already consume — pipeline handoffs
    (collective-permute) must be attributable separately from halo
    exchanges and fmap2 all-gathers."""

    def test_by_op_breakout_and_aggregates(self):
        from raft_ncup_tpu.parallel.mesh import collective_stats

        hlo = (
            "  %cp = f32[2,4]{1,0} collective-permute(%x), channel_id=1\n"
            "  %cp2 = f32[2,4]{1,0} collective-permute-start(%y)\n"
            "  %cp3 = f32[2,4]{1,0} collective-permute-done(%cp2)\n"
            "  %ag = bf16[8]{0} all-gather(%z), dimensions={0}\n"
            "  not_an_op collective-permute(%q)\n"
        )
        cs = collective_stats(hlo)
        cp = cs["by_op"]["collective-permute"]
        # The -done half of the async pair (and the no-result line)
        # must not double count.
        assert cp == {"count": 2, "bytes": 2 * (2 * 4 * 4)}
        assert cs["by_op"]["all-gather"] == {"count": 1, "bytes": 16}
        assert cs["collectives"] == 3
        assert cs["collective_bytes"] == 64 + 16

    def test_unsharded_program_is_all_zeros(self):
        """Existing consumers (bench ``highres_collectives`` /
        ``highres_collective_bytes``, scripts/highres_forward.py) index
        the named aggregate keys; every op kind is present zero-filled
        so by_op consumers never need existence guards."""
        from raft_ncup_tpu.parallel.mesh import (
            _COLLECTIVE_OPS,
            collective_stats,
        )

        cs = collective_stats("%r = f32[4]{0} add(%a, %b)\n")
        assert cs["collectives"] == 0 and cs["collective_bytes"] == 0
        assert set(cs["by_op"]) == set(_COLLECTIVE_OPS)
        assert all(
            v == {"count": 0, "bytes": 0} for v in cs["by_op"].values()
        )


# -------------------------------------------------- cache-key isolation


class _DummyModel:
    """apply()-compatible stand-in: cache-key tests need no compile."""

    def apply(self, variables, image1, image2, **kw):
        return image1, image2


class TestMeshKeyedCache:
    def test_every_cache_key_carries_the_mesh_fingerprint(self):
        mesh = _mesh(1, 2)
        sharded = ShapeCachedForward(_DummyModel(), {}, mesh=mesh)
        plain = ShapeCachedForward(_DummyModel(), {})

        def build():
            return lambda *a: a

        sharded.custom(("stream", 2), build)
        plain.custom(("stream", 2), build)
        (skey,) = sharded._fns
        (pkey,) = plain._fns
        assert skey[0] == mesh_fingerprint(mesh)
        assert pkey[0] == "nomesh"
        assert skey != pkey  # same logical key, different executables

    def test_config_rejects_batch_not_divisible_by_data_axis(self):
        with pytest.raises(ValueError, match="not divisible by mesh"):
            ServeConfig(batch_sizes=(1, 2), mesh=(2, 1))
        with pytest.raises(ValueError, match="not divisible by mesh"):
            StreamConfig(batch_sizes=(1, 2, 4), mesh=(4, 2))
        # data=1 spatial-only meshes impose nothing on batch sizes.
        assert ServeConfig(mesh=(1, 2)).mesh == (1, 2)

    def test_config_rejects_pad_bucket_off_the_mesh_divisor(self):
        """Mesh pads round to 8*spatial, and InputPadder rejects a
        bucket the divisor doesn't divide — that must be a config-time
        error, not an exception escaping FlowServer.submit() past the
        terminal-status contract."""
        with pytest.raises(ValueError, match="pad divisor 8\\*spatial"):
            ServeConfig(mesh=(1, 3), pad_bucket=64)
        with pytest.raises(ValueError, match="pad divisor 8\\*spatial"):
            StreamConfig(mesh=(1, 3), pad_bucket=64)
        # A bucket the divisor divides is fine.
        assert ServeConfig(mesh=(1, 2), pad_bucket=32).pad_bucket == 32

    def test_cli_mesh_spec(self):
        import argparse

        from raft_ncup_tpu.cli import str2mesh

        assert str2mesh("1,2") == (1, 2)
        assert str2mesh("1,1,2") == (1, 1, 2)
        with pytest.raises(argparse.ArgumentTypeError):
            str2mesh("2")
        with pytest.raises(argparse.ArgumentTypeError):
            str2mesh("0,2")
        with pytest.raises(argparse.ArgumentTypeError):
            str2mesh("1,1,0")
        with pytest.raises(argparse.ArgumentTypeError):
            str2mesh("1,1,2,2")

    def test_cli_mesh_triple_builds_pipe_mesh(self):
        import argparse

        from raft_ncup_tpu.cli import mesh_from_args

        mesh = mesh_from_args(argparse.Namespace(mesh=(1, 1, 2)))
        assert dict(mesh.shape) == {"data": 1, "spatial": 1, "pipe": 2}
        # The 2-tuple path still yields the identical 2-axis mesh.
        assert mesh_from_args(argparse.Namespace(mesh=(1, 2))).axis_names == (
            "data",
            "spatial",
        )


# ------------------------------------------------------ forward parity


class TestShardedParity:
    def test_forward_sharded_matches_unsharded(self, small_model):
        model, variables = small_model
        plain = ShapeCachedForward(model, variables)
        sharded = ShapeCachedForward(model, variables, mesh=_mesh(1, 2))
        i1, i2 = _img(1), _img(2)
        lr_p, up_p = plain(i1, i2, iters=2)
        lr_s, up_s = sharded(i1, i2, iters=2)
        np.testing.assert_allclose(lr_s, lr_p, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(up_s, up_p, rtol=1e-4, atol=1e-4)

    def test_eval_validator_sharded_parity(self, small_model):
        """The tier-1 eval parity check (promoted out of the slow tier):
        a (2 data x 2 spatial) mesh validator pass over the held-out
        synthetic split must reproduce the unsharded EPE — this is the
        whole-pipeline parity (EvalPipeline staging shardings + on-device
        metric fold + SPMD forward), small enough to fail fast on every
        run."""
        from raft_ncup_tpu.evaluation import validate_synthetic

        model, variables = small_model
        kw = dict(
            iters=2, batch_size=2, size_hw=(64, 64), length=4, seed=999
        )
        ref = validate_synthetic(model, variables, None, **kw)
        out = validate_synthetic(
            model, variables, None, mesh=_mesh(2, 2), **kw
        )
        assert ref and out
        np.testing.assert_allclose(
            out["synthetic"], ref["synthetic"], rtol=1e-4
        )

    def test_serve_sharded_parity(self, small_model):
        """One request through a spatially-sharded FlowServer must return
        the same flow as the unsharded server (pads ride 8*spatial, the
        compiled program is SPMD, the drain pull is unchanged)."""
        from raft_ncup_tpu.serving import FlowServer

        model, variables = small_model
        cfg = ServeConfig(batch_sizes=(1,), iter_levels=(2,))
        img1, img2 = _img(3)[0], _img(4)[0]
        flows = {}
        for tag, mesh in (("plain", None), ("sharded", _mesh(1, 2))):
            with FlowServer(model, variables, cfg, mesh=mesh) as server:
                res = server.submit(img1, img2).result(timeout=120.0)
                assert res.ok, res.detail
                flows[tag] = res.flow
                assert server.report()["mesh"] == mesh_fingerprint(mesh)
        assert flows["plain"].shape == flows["sharded"].shape == (*HW, 2)
        np.testing.assert_allclose(
            flows["sharded"], flows["plain"], rtol=1e-4, atol=1e-4
        )

    def test_stream_sharded_parity_and_guard_clean(self, small_model):
        """Two warm-chained frames through a spatially-sharded
        StreamEngine (mesh from StreamConfig.mesh — the serve.py --mesh
        path) must match the unsharded engine bitwise-or-tolerance on
        BOTH frames (the second one exercises the sharded slot-table
        gather → in-graph splat → scatter chain), and the sharded steady
        state must stay guard-clean: zero implicit host transfers, zero
        recompiles after warmup."""
        from raft_ncup_tpu.analysis.guards import (
            GuardStats,
            RecompileWatchdog,
            forbid_host_transfers,
        )
        from raft_ncup_tpu.streaming import StreamEngine

        model, variables = small_model
        frames = [(_img(5)[0], _img(6)[0]), (_img(6)[0], _img(7)[0])]
        results = {}
        for tag, mesh_spec in (("plain", None), ("sharded", (1, 2))):
            cfg = StreamConfig(
                capacity=1, frame_hw=HW, iters=2, batch_sizes=(1,),
                queue_capacity=8, mesh=mesh_spec,
            )
            eng = StreamEngine(model, variables, cfg)
            try:
                eng.warmup()
                out = []
                stats = GuardStats()
                with RecompileWatchdog() as wd, forbid_host_transfers(
                    stats
                ):
                    for i1, i2 in frames:
                        r = eng.submit("s", i1, i2).result(timeout=120.0)
                        assert r.ok, r.detail
                        out.append(r.flow)
                results[tag] = out
                assert wd.count == 0, f"{tag}: recompiled under traffic"
                assert stats.host_transfers == 0, tag
                assert eng.report()["mesh"] == (
                    "mesh(data=1,spatial=2:cpu)"
                    if mesh_spec
                    else "nomesh"
                )
            finally:
                eng.drain()
        for k in range(2):
            np.testing.assert_allclose(
                results["sharded"][k], results["plain"][k],
                rtol=1e-4, atol=1e-4,
                err_msg=f"frame {k} (k=1 is the warm-started one)",
            )
