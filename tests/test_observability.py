"""Unified telemetry subsystem (raft_ncup_tpu/observability/;
docs/OBSERVABILITY.md): registry thread-safety, histogram percentile
parity with the shared nearest-rank discipline, span correlation through
a real FlowServer batch, report() back-compat keys (pinned alias table),
the bounded export sinks, and the platform invariant — a steady-state
serving window stays sync-free and recompile-free with tracing FULLY
enabled.
"""

import json
import os
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import ServeConfig, StreamConfig, small_model_config
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.observability import (
    DEGRADED,
    DRAINING,
    HALTED,
    READY,
    STARTING,
    STATE_CODES,
    WARMING,
    FlightRecorder,
    HealthTracker,
    JsonlSink,
    LEGACY_KEY_ALIASES,
    MetricsRegistry,
    PeriodicSnapshot,
    SloEngine,
    SloSpec,
    SpanTracer,
    Telemetry,
    host_number,
    load_dump,
    match_records,
    overall_state,
    serve_slos,
    stream_slos,
    telemetry_report,
    write_healthz,
)
from raft_ncup_tpu.observability.telemetry import Histogram
from raft_ncup_tpu.serving import AdmissionQueue, FlowServer
from raft_ncup_tpu.serving.request import (
    STATUS_OK,
    FlowRequest,
    ServeStats,
    nearest_rank_ms,
)
from raft_ncup_tpu.streaming import StreamEngine
from raft_ncup_tpu.streaming.engine import StreamStats


# ------------------------------------------------------------- test rigs


class _DummyModel:
    """apply()-compatible stand-in (tests/test_serving.py's rig)."""

    def apply(self, variables, image1, image2, iters=1, flow_init=None,
              test_mode=True, mesh=None, metric_head=None, **kw):
        flow_up = jnp.stack(
            [image1[..., 0] * iters, image1[..., 1]], axis=-1
        )
        return image1.mean(), flow_up


class _DummyVideoModel:
    """apply()-compatible streaming stand-in (tests/test_streaming.py)."""

    cfg = SimpleNamespace(hidden_dim=4)

    def apply(self, variables, image1, image2, iters=1, flow_init=None,
              test_mode=True, return_net=False, net_init=None,
              net_warm=None, **kw):
        B, H, W, _ = image1.shape
        lr = image1[:, ::8, ::8, :2] * 0.01
        if flow_init is not None:
            lr = lr + flow_init
        up = jnp.repeat(jnp.repeat(lr, 8, axis=1), 8, axis=2)
        if return_net:
            net = jnp.full((B, H // 8, W // 8, 4), 0.5, jnp.float32)
            return lr, up, net
        return lr, up


def _img(seed=0, hw=(24, 32)):
    g = np.random.default_rng(seed)
    return (g.random((*hw, 3)) * 255.0).astype(np.float32)


def _cfg(**kw):
    base = dict(
        queue_capacity=8, batch_sizes=(1, 2), iter_levels=(4, 2),
        recover_patience=2,
    )
    base.update(kw)
    return ServeConfig(**base)


# ------------------------------------------------------------- registry


class TestRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc()
        reg.counter("a_total").inc(4)
        reg.gauge("depth").set(3)
        reg.gauge("depth").set(1)
        reg.histogram("lat_ms").observe_ms(12.0)
        snap = reg.snapshot()
        assert snap["counters"]["a_total"] == 5
        assert snap["gauges"]["depth"] == {"value": 1.0, "peak": 3.0}
        assert snap["histograms"]["lat_ms"]["count"] == 1
        assert json.loads(json.dumps(snap)) == snap  # JSON-able

    def test_kind_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("x")

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("x").inc(-1)

    def test_thread_safety_no_lost_updates(self):
        """The accounting-under-concurrency property the registry exists
        for: N threads x M increments lose nothing."""
        reg = MetricsRegistry()
        n_threads, per_thread = 8, 500

        def work():
            c = reg.counter("hits_total")
            h = reg.histogram("work_ms")
            for i in range(per_thread):
                c.inc()
                h.observe_ms(float(i % 50))

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("hits_total").value == n_threads * per_thread
        assert reg.histogram("work_ms").count == n_threads * per_thread

    def test_rejects_jax_typed_values_without_converting(self):
        """The no-added-sync contract at runtime: anything device-side
        is refused BEFORE conversion (float() on a device array is the
        sync). Pinned against a REAL concrete array (whose type lives
        under jaxlib, not jax) AND a jax-module stand-in (tracers)."""
        real = jnp.float32(3.5)  # type module: jaxlib.xla_extension
        with pytest.raises(TypeError, match="device sync"):
            host_number(real)
        fake = type("Tracer", (), {"__module__": "jax._src.array"})()
        with pytest.raises(TypeError, match="device sync"):
            host_number(fake)
        reg = MetricsRegistry()
        for bad in (real, fake):
            with pytest.raises(TypeError):
                reg.counter("c").inc(bad)
            with pytest.raises(TypeError):
                reg.gauge("g").set(bad)
            with pytest.raises(TypeError):
                reg.histogram("h_ms").observe_ms(bad)

    def test_prometheus_text_shape(self):
        reg = MetricsRegistry()
        reg.counter("serve_requests_shed_total").inc(2)
        reg.gauge("serve_queue_depth").set(5)
        reg.histogram("serve_drain_ms").observe_ms(3.0)
        text = reg.prometheus_text()
        assert "# TYPE serve_requests_shed_total counter" in text
        assert "serve_requests_shed_total 2" in text
        assert "serve_queue_depth_peak 5" in text
        assert 'serve_drain_ms_bucket{le="+Inf"} 1' in text
        assert "serve_drain_ms_count 1" in text


class TestHistogramPercentiles:
    def test_parity_with_serving_nearest_rank_ms(self):
        """The shared percentile discipline: the histogram's nearest-rank
        over its raw-sample window must equal serving.nearest_rank_ms on
        the identical latency sample (seconds -> ms)."""
        g = np.random.default_rng(7)
        lat_s = list(g.gamma(2.0, 0.05, size=257))
        hist = Histogram("lat_ms")
        for s in lat_s:
            hist.observe_ms(s * 1000.0)
        for p in (0.5, 0.9, 0.95, 0.99):
            assert hist.percentile_ms(p) == nearest_rank_ms(lat_s, p)

    def test_empty_percentile_is_none(self):
        assert Histogram("x_ms").percentile_ms(0.5) is None

    def test_sample_window_bounds_memory(self):
        hist = Histogram("x_ms", sample_cap=10)
        for i in range(100):
            hist.observe_ms(float(i))
        # Bucket counts keep the full history, percentiles the window
        # (the most recent sample_cap observations: 90..99 ms).
        assert hist.count == 100
        assert hist.percentile_ms(0.5) == 94.0


# ----------------------------------------------------------- span tracer


class TestSpanTracer:
    def test_span_feeds_stage_histogram(self):
        t = [0.0]
        tel = Telemetry(clock=lambda: t[0])
        with tel.span("serve_dispatch", batch_id=1):
            t[0] += 0.25
        assert tel.registry.histogram("serve_dispatch_ms").count == 1
        assert tel.tracer.stage_summary()["serve_dispatch"]["p50_ms"] == 250.0

    def test_event_counts_and_correlates(self):
        tel = Telemetry()
        tel.event("stream_slot_evicted", stream_id="s1", slot=2)
        assert tel.counter_value("stream_slot_evicted_total") == 1
        (rec,) = tel.tracer.for_attr(stream_id="s1")
        assert rec["name"] == "stream_slot_evicted"

    def test_singular_key_matches_plural_list_attr(self):
        tel = Telemetry()
        tel.event("serve_dispatch_done", request_ids=[4, 5])
        assert tel.tracer.for_attr(request_id=4)
        assert not tel.tracer.for_attr(request_id=6)

    def test_ring_is_bounded_and_counts_drops(self):
        tel = Telemetry(span_capacity=4)
        for i in range(10):
            tel.event("e", i=i)
        assert len(tel.tracer.records()) == 4
        assert tel.tracer.dropped == 6
        assert [r["attrs"]["i"] for r in tel.tracer.records()] == [
            6, 7, 8, 9,
        ]

    def test_span_attrs_reject_jax_values(self):
        tel = Telemetry()
        fake = type("Arr", (), {"__module__": "jax"})()
        with pytest.raises(TypeError, match="device sync"):
            tel.event("e", value=fake)
        with pytest.raises(TypeError, match="device sync"):
            tel.event("e", value=jnp.ones(()))  # real device scalar

    def test_disabled_hub_is_inert(self):
        tel = Telemetry(enabled=False)
        tel.inc("c_total")
        tel.gauge_set("g", 1)
        tel.event("e")
        tel.observe_ms("stage", 5.0)
        with tel.span("s"):
            pass
        assert tel.registry.names() == []
        assert tel.tracer.records() == []


# ------------------------------------------- stats mirroring / aliases


class TestLegacyAliases:
    def test_every_serve_stats_field_has_a_pinned_alias(self):
        s = ServeStats()
        int_fields = [
            k for k, v in vars(s).items()
            if isinstance(v, int) and not k.startswith("_")
        ]
        assert sorted(int_fields) == sorted(LEGACY_KEY_ALIASES["serve"])

    def test_every_stream_stats_field_has_a_pinned_alias(self):
        s = StreamStats()
        int_fields = [
            k for k, v in vars(s).items()
            if isinstance(v, int) and not k.startswith("_")
        ]
        assert sorted(int_fields) == sorted(LEGACY_KEY_ALIASES["stream"])

    def test_serve_stats_mirror_values_match_legacy_fields(self):
        tel = Telemetry()
        s = ServeStats(telemetry=tel)
        s.note_submitted()
        s.note_submitted()
        s.note_accepted()
        s.note_shed()
        s.note_timeout()
        s.note_error()
        s.note_completed()
        s.note_batch(padded_rows=3)
        s.note_rejected(9, quarantine=True)
        canon = LEGACY_KEY_ALIASES["serve"]
        for legacy, name in canon.items():
            assert tel.counter_value(name) == getattr(s, legacy), legacy
        # The dispatch-time quarantine also lands as a correlated event.
        assert tel.tracer.for_attr(request_id=9)

    def test_stream_stats_mirror_values_match_legacy_fields(self):
        tel = Telemetry()
        s = StreamStats(telemetry=tel)
        s.note("submitted")
        s.note("accepted")
        s.note("shed_streams")
        s.note("padded_rows", 4)
        s.note("cold_starts")
        canon = LEGACY_KEY_ALIASES["stream"]
        for legacy, name in canon.items():
            assert tel.counter_value(name) == getattr(s, legacy), legacy

    def test_summary_keys_survive_verbatim(self):
        """The exact legacy summary lines downstream parsers read."""
        assert ServeStats().summary() == (
            "submitted=0 accepted=0 completed=0 shed=0 timeouts=0 "
            "rejected=0 errors=0 batches=0 padded_rows=0 quarantined=[-]"
        )
        assert StreamStats().summary() == (
            "submitted=0 accepted=0 completed=0 shed_streams=0 "
            "shed_frames=0 rejected=0 resets=0 errors=0 batches=0 "
            "padded_rows=0 opened=0 closed=0 evicted=0 cold_starts=0"
        )


# ------------------------------------------------------ admission gauges


class TestAdmissionQueueGauges:
    def _req(self, rid):
        return FlowRequest(rid, None, None, shape_key="a")

    def test_depth_observable_between_offer_and_pop(self):
        """The satellite fix: live depth is a gauge from the first
        offer, not something inferred from shed events after the fact."""
        tel = Telemetry()
        q = AdmissionQueue(8, telemetry=tel, name="serve")
        for i in range(3):
            q.offer(self._req(i))
        g = tel.registry.get("serve_queue_depth")
        assert g is not None and g.value == 3
        q.pop_batch(2)
        assert g.value == 1
        q.pop_batch(2)
        assert g.value == 0
        assert g.peak == 3

    def test_service_time_ema_gauge(self):
        tel = Telemetry()
        srv = FlowServer(_DummyModel(), {}, _cfg(), telemetry=tel)
        try:
            assert srv.submit(_img(1), _img(2)).result(60).ok
        finally:
            srv.drain()
        g = tel.registry.get("serve_service_time_ema_ms")
        assert g is not None and g.value > 0


# ------------------------------------ server spans / report back-compat


# Pre-telemetry report() keys, pinned verbatim (acceptance criterion).
SERVE_REPORT_KEYS = {
    "stats", "budget", "budget_drops", "budget_recoveries",
    "executables", "precision", "mesh",
}
STREAM_REPORT_KEYS = {
    "stats", "capacity", "occupancy", "peak_occupancy", "mean_occupancy",
    "evicted", "executables", "precision", "mesh",
}


class TestServerTelemetry:
    def test_span_correlation_through_a_real_two_request_batch(self):
        """Two requests paused into ONE batch: the journey of each
        request is reassemblable from the ring — its own queue-wait plus
        the batch-level assembly/stage/dispatch/drain spans, all tied by
        one batch id, with mesh+policy fingerprints on the dispatch."""
        tel = Telemetry()
        srv = FlowServer(_DummyModel(), {}, _cfg(), telemetry=tel)
        try:
            srv.pause()
            h1 = srv.submit(_img(1), _img(2))
            h2 = srv.submit(_img(3), _img(4))
            srv.resume()
            assert h1.result(60).ok and h2.result(60).ok
        finally:
            srv.drain()
        disp = tel.tracer.records("serve_dispatch")
        assert len(disp) == 1
        assert sorted(disp[0]["attrs"]["request_ids"]) == [0, 1]
        assert disp[0]["attrs"]["policy"] == "f32"
        assert "mesh" in disp[0]["attrs"]
        batch_id = disp[0]["attrs"]["batch_id"]
        journey = {
            r["name"] for r in tel.tracer.for_attr(request_id=0)
        }
        assert {
            "serve_queue_wait", "serve_dispatch", "serve_drain",
        } <= journey
        # Batch-level stages share the batch correlation id.
        for name in ("serve_batch_assembly", "serve_pad_stage",
                     "serve_drain"):
            recs = tel.tracer.records(name)
            assert recs and recs[-1]["attrs"]["batch_id"] == batch_id
        # Queue-wait recorded once per request.
        assert tel.registry.histogram("serve_queue_wait_ms").count == 2
        # One sanctioned pull for the one batch.
        assert tel.counter_value("serve_drain_pulls_total") == 1

    def test_serve_report_backcompat_plus_stages(self):
        tel = Telemetry()
        srv = FlowServer(_DummyModel(), {}, _cfg(), telemetry=tel)
        try:
            assert srv.submit(_img(1), _img(2)).result(60).ok
            report = srv.report()
        finally:
            srv.drain()
        assert SERVE_REPORT_KEYS <= set(report)
        assert "stages" in report
        assert report["stages"]["serve_dispatch"]["count"] == 1
        assert report["stages"]["serve_dispatch"]["p50_ms"] is not None
        # stats summary still parses with the legacy fields.
        assert report["stats"].startswith("submitted=1 accepted=1 ")

    def test_stream_report_backcompat_plus_stages(self):
        tel = Telemetry()
        eng = StreamEngine(
            _DummyVideoModel(), {},
            StreamConfig(capacity=2, frame_hw=(24, 32), iters=1,
                         batch_sizes=(1, 2), queue_capacity=8),
            telemetry=tel,
        )
        try:
            assert eng.submit("s0", _img(1), _img(2)).result(60).ok
            report = eng.report()
        finally:
            eng.drain()
        assert STREAM_REPORT_KEYS <= set(report)
        assert report["stages"]["stream_dispatch"]["count"] == 1
        # Slot admission landed as a correlated lifecycle event.
        (admit,) = tel.tracer.records("stream_slot_admitted")
        assert admit["attrs"]["stream_id"] == "s0"
        assert tel.counter_value("stream_drain_pulls_total") == 1

    def test_disabled_telemetry_serves_identically(self):
        tel = Telemetry(enabled=False)
        srv = FlowServer(_DummyModel(), {}, _cfg(), telemetry=tel)
        try:
            r = srv.submit(_img(1), _img(2)).result(60)
        finally:
            stats = srv.drain()
        assert r.ok and stats.completed == 1
        assert tel.tracer.records() == []
        assert srv.report()["stages"] == {}


# --------------------------------------------------------- export layer


class TestExport:
    def test_jsonl_sink_is_bounded(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        with JsonlSink(path, max_events=5) as sink:
            written = [sink.write({"i": i}) for i in range(9)]
        assert written == [True] * 5 + [False] * 4
        lines = [
            json.loads(ln) for ln in open(path, encoding="utf-8")
        ]
        # 5 events + the closing record carrying the drop count.
        assert len(lines) == 6
        assert lines[-1] == {"name": "jsonl_sink_closed", "dropped": 4}

    def test_periodic_snapshot_writes_reports(self, tmp_path):
        path = str(tmp_path / "snap.jsonl")
        tel = Telemetry()
        tel.inc("serve_requests_submitted_total", 3)
        with JsonlSink(path) as sink:
            snap = PeriodicSnapshot(tel, sink, interval_s=0.05).start()
            time.sleep(0.12)
            snap.stop()
        lines = [
            json.loads(ln) for ln in open(path, encoding="utf-8")
        ]
        assert len(lines) >= 2  # >=1 periodic + the final stop() one
        rep = lines[-1]["report"]
        assert rep["metrics"]["counters"][
            "serve_requests_submitted_total"
        ] == 3

    def test_telemetry_report_shape(self):
        tel = Telemetry()
        tel.inc("c_total")
        with tel.span("stage_x"):
            pass
        rep = telemetry_report(tel)
        assert rep["enabled"] is True
        assert rep["metrics"]["counters"]["c_total"] == 1
        assert "stage_x" in rep["stages"]
        assert rep["spans_recorded"] == 1
        assert json.loads(json.dumps(rep)) == rep


# ------------------------------------------- the platform invariant


@pytest.fixture(scope="module")
def tiny_model():
    cfg = small_model_config("raft", dataset="chairs")
    model = RAFT(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 40, 48, 3))
    return model, variables


class TestTracingPreservesInvariants:
    def test_steady_state_sync_free_recompile_free_under_full_tracing(
        self, tiny_model, forbid_host_transfers, max_recompiles
    ):
        """The tentpole's hard constraint: with telemetry FULLY enabled
        (counters, spans, queue gauges all live), a warm steady-state
        serving window still performs ZERO implicit host pulls and ZERO
        compiles, and each batch still does exactly ONE sanctioned
        device_get — the observer adds bookkeeping, never a sync."""
        model, variables = tiny_model
        tel = Telemetry()
        cfg = _cfg(batch_sizes=(1,), iter_levels=(2, 1))
        srv = FlowServer(model, variables, cfg, telemetry=tel)
        try:
            srv.warmup((40, 48))
            warm = srv.submit(_img(30, (40, 48)), _img(31, (40, 48)))
            assert warm.result(120).ok
            pulls_before = tel.counter_value("serve_drain_pulls_total")
            with forbid_host_transfers() as stats, max_recompiles(0):
                handles = [
                    srv.submit(_img(40 + i, (40, 48)),
                               _img(50 + i, (40, 48)))
                    for i in range(3)
                ]
                rs = [h.result(120) for h in handles]
        finally:
            srv.drain()
        assert [r.status for r in rs] == [STATUS_OK] * 3
        assert stats.host_transfers == 0
        assert stats.sanctioned_gets == 3  # one per batch, as before
        # ...and tracing really was live through the guarded window:
        assert (
            tel.counter_value("serve_drain_pulls_total") - pulls_before
            == 3
        )
        assert tel.registry.histogram("serve_queue_wait_ms").count >= 3
        assert tel.tracer.records("serve_dispatch")


# -------------------------------------------- executable cache events


class TestExecutableCacheEvents:
    def test_compile_hit_evict_events_keyed_like_the_cache(self):
        from raft_ncup_tpu.inference.pipeline import ShapeCachedForward

        tel = Telemetry()
        fwd = ShapeCachedForward(
            _DummyModel(), {}, cache_size=1, telemetry=tel
        )
        calls = []
        fwd.custom(("k1",), lambda: calls.append("a") or (lambda: 1))
        fwd.custom(("k1",), lambda: calls.append("b") or (lambda: 2))
        fwd.custom(("k2",), lambda: calls.append("c") or (lambda: 3))
        assert calls == ["a", "c"]  # second k1 was a hit
        assert tel.counter_value(
            "inference_executable_compiles_total"
        ) == 2
        assert tel.counter_value("inference_executable_hits_total") == 1
        assert tel.counter_value(
            "inference_executable_evictions_total"
        ) == 1
        (compile1, compile2) = tel.tracer.records(
            "inference_executable_compile"
        )
        (evict,) = tel.tracer.records("inference_executable_evict")
        # Events carry the cache's own key (mesh fingerprint prefix
        # included) — "keyed like the cache".
        assert "k1" in compile1["attrs"]["key"]
        assert "k2" in compile2["attrs"]["key"]
        assert "k1" in evict["attrs"]["key"]
        assert fwd.stats == {"compiles": 2, "hits": 1, "evictions": 1}


# ------------------------------- guard + logger registry producers


class TestGuardAndLoggerMirrors:
    def test_guard_violation_lands_as_event(self):
        """GuardStats re-expressed over the registry: an intercepted
        implicit pull shows on the process-default hub's timeline."""
        from raft_ncup_tpu.analysis.guards import forbid_host_transfers
        from raft_ncup_tpu.observability import set_telemetry

        prev = set_telemetry(Telemetry())
        try:
            x = jnp.ones((2,))
            with forbid_host_transfers(raise_on_violation=False) as gs:
                float(x[0])  # the planted implicit pull
                jax.device_get(x)  # sanctioned
            from raft_ncup_tpu.observability import get_telemetry

            tel = get_telemetry()
            assert gs.host_transfers == 1
            assert tel.counter_value(
                "guard_host_transfer_violation_total"
            ) == 1
            (ev,) = tel.tracer.records("guard_host_transfer_violation")
            assert "jax.Array" in ev["attrs"]["desc"]
            assert tel.counter_value("guard_sanctioned_gets_total") >= 1
        finally:
            set_telemetry(prev)

    def test_logger_window_means_land_as_gauges(self, tmp_path):
        from raft_ncup_tpu.observability import set_telemetry
        from raft_ncup_tpu.training.logger import Logger

        prev = set_telemetry(Telemetry())
        try:
            log = Logger(str(tmp_path), sum_freq=2, use_tensorboard=False)
            log.push(0, {"loss": jnp.asarray(4.0)}, lr=1e-4)
            log.push(1, {"loss": jnp.asarray(2.0)}, lr=1e-4)
            log.close()
            from raft_ncup_tpu.observability import get_telemetry

            reg = get_telemetry().registry
            assert reg.get("train_loss").value == 3.0  # window mean
            assert reg.get("train_lr").value == pytest.approx(1e-4)
            assert reg.get("train_steps_per_sec").value > 0
        finally:
            set_telemetry(prev)


# ------------------------------------------------ health state machine


class TestHealthStateMachine:
    def test_lifecycle_path_and_codes(self):
        tel = Telemetry()
        h = HealthTracker("serve", telemetry=tel)
        assert h.state == STARTING
        assert h.warming() and h.state == WARMING
        assert h.ready("warmup done") and h.state == READY
        assert h.degrade("slo burning") and h.state == DEGRADED
        assert h.ready("slo recovered") and h.state == READY
        assert h.draining() and h.state == DRAINING
        assert h.halted("fatal") and h.state == HALTED
        snap = h.snapshot()
        assert snap["state"] == HALTED
        assert snap["code"] == STATE_CODES[HALTED] == 5
        assert snap["transitions"] == 6
        # Transitions published as gauge + correlated events.
        assert tel.registry.get("serve_health_state").value == 5
        recs = tel.tracer.records("serve_health_transition")
        assert [r["attrs"]["to_state"] for r in recs] == [
            WARMING, READY, DEGRADED, READY, DRAINING, HALTED,
        ]

    def test_illegal_transitions_are_counted_noops_never_raise(self):
        tel = Telemetry()
        h = HealthTracker("x", telemetry=tel)
        assert not h.degrade("no")  # STARTING -> DEGRADED illegal
        assert h.state == STARTING
        h.draining()
        assert not h.ready("no")  # DRAINING -> READY illegal
        h.halted("end")
        assert not h.draining()  # HALTED is terminal
        assert h.snapshot()["invalid_transitions"] == 3
        assert tel.counter_value("x_health_invalid_transition_total") == 3

    def test_same_state_is_silent_noop(self):
        h = HealthTracker("x")
        h.draining()
        assert not h.draining()  # drain() is idempotent upstream
        assert h.snapshot()["transitions"] == 1

    def test_unknown_state_raises(self):
        with pytest.raises(ValueError, match="unknown health state"):
            HealthTracker("x").to("broken")

    def test_state_tracks_even_when_hub_disabled(self):
        """Health is product logic (budget gate, healthz): the STATE
        machine runs with telemetry off; only the exports are muted."""
        tel = Telemetry(enabled=False)
        h = tel.health("serve")
        h.warming(), h.ready()
        assert h.state == READY
        assert tel.tracer.records() == []
        assert tel.registry.names() == []

    def test_hub_accessor_get_or_create_and_fresh(self):
        tel = Telemetry()
        a = tel.health("serve")
        assert tel.health("serve") is a
        a.draining()
        b = tel.health("serve", fresh=True)  # re-entrant driver run
        assert b is not a and b.state == STARTING
        assert tel.health_snapshot()["serve"]["state"] == STARTING

    def test_overall_state_is_worst(self):
        assert overall_state({}) == READY
        assert overall_state({
            "serve": {"state": READY}, "stream": {"state": DEGRADED},
        }) == DEGRADED
        assert overall_state({
            "serve": {"state": STARTING}, "train": {"state": HALTED},
        }) == HALTED


# ------------------------------------------------------ slo burn engine


def _clocked(start=0.0):
    t = {"now": float(start)}

    def clk():
        return t["now"]

    return t, clk


class TestSloSpec:
    def test_validation(self):
        with pytest.raises(ValueError, match="objective"):
            SloSpec("a", "serve", "ratio", objective=1.0,
                    bad="b", total="t")
        with pytest.raises(ValueError, match="sli"):
            SloSpec("a", "serve", "nope", objective=0.9)
        with pytest.raises(ValueError, match="metric fields"):
            SloSpec("a", "serve", "ratio", objective=0.9, bad="b")
        with pytest.raises(ValueError, match="fast_window_s"):
            SloSpec("a", "serve", "gauge", objective=0.9, gauge="g",
                    max_value=1, fast_window_s=10, slow_window_s=5)

    def test_scaled_shrinks_windows_only(self):
        s = serve_slos(window_scale=0.01)[0]
        assert s.fast_window_s == pytest.approx(3.0)
        assert s.slow_window_s == pytest.approx(36.0)
        assert s.objective == serve_slos()[0].objective


class TestSloEngine:
    def _engine(self, spec, tel, clk):
        return SloEngine([spec], tel, clock=clk)

    def test_ratio_burn_math_is_exact(self):
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        spec = SloSpec("shed", "serve", "ratio", objective=0.9,
                       bad="bad_total", total="all_total",
                       fast_window_s=10, slow_window_s=60,
                       page_burn=2.0, min_events=1)
        eng = self._engine(spec, tel, clk)
        eng.evaluate()  # baseline
        tel.inc("all_total", 10)
        tel.inc("bad_total", 5)
        t["now"] = 1.0
        v = eng.evaluate()["shed"]
        # bad fraction 0.5 over budget 0.1 => burn 5.0, both windows.
        assert v.burn_fast == pytest.approx(5.0)
        assert v.burn_slow == pytest.approx(5.0)
        assert v.page and eng.paging("serve") and eng.paging()

    def test_min_events_gates_paging(self):
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        spec = SloSpec("shed", "serve", "ratio", objective=0.9,
                       bad="bad_total", total="all_total",
                       fast_window_s=10, slow_window_s=60,
                       page_burn=2.0, min_events=8)
        eng = self._engine(spec, tel, clk)
        eng.evaluate()
        tel.inc("all_total", 2)
        tel.inc("bad_total", 2)  # 100% bad, but only 2 events
        t["now"] = 1.0
        assert not eng.evaluate()["shed"].page

    def test_page_requires_both_windows(self):
        """The multi-window discipline: an old burst still inside the
        slow window but outside the fast one must NOT page."""
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        spec = SloSpec("shed", "serve", "ratio", objective=0.9,
                       bad="bad_total", total="all_total",
                       fast_window_s=3, slow_window_s=60,
                       page_burn=2.0, min_events=1)
        eng = self._engine(spec, tel, clk)
        eng.evaluate()
        tel.inc("all_total", 10)
        tel.inc("bad_total", 10)
        t["now"] = 1.0
        assert eng.evaluate()["shed"].page  # fresh burst: pages
        t["now"] = 30.0  # burst now outside fast window, inside slow
        v = eng.evaluate()["shed"]
        assert v.burn_fast == 0.0 and v.burn_slow > 2.0
        assert not v.page

    def test_latency_sli_counts_over_threshold_fraction(self):
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        spec = SloSpec("p99", "serve", "latency", objective=0.5,
                       histogram="e2e_ms", threshold_ms=100.0,
                       fast_window_s=10, slow_window_s=60,
                       page_burn=1.5, min_events=1)
        eng = self._engine(spec, tel, clk)
        eng.evaluate()
        for _ in range(10):
            tel.hist_observe("e2e_ms", 50.0)  # <= 100: good
        for _ in range(10):
            tel.hist_observe("e2e_ms", 500.0)  # > 100: bad
        t["now"] = 1.0
        v = eng.evaluate()["p99"]
        assert v.bad_fraction_fast == pytest.approx(0.5)
        assert v.burn_fast == pytest.approx(1.0)  # 0.5 / budget 0.5
        assert not v.page  # burn 1.0 < page_burn 1.5

    def test_gauge_sli_fraction_of_bad_samples(self):
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        spec = SloSpec("occ", "stream", "gauge", objective=0.5,
                       gauge="occupancy", max_value=3.0,
                       fast_window_s=10, slow_window_s=60,
                       page_burn=1.9, min_events=2)
        eng = self._engine(spec, tel, clk)
        for i, val in enumerate([4, 4, 4, 4]):
            tel.gauge_set("occupancy", val)
            t["now"] = float(i)
            eng.evaluate()
        v = eng.verdicts()["occ"]
        assert v.bad_fraction_fast == 1.0
        assert v.burn_fast == pytest.approx(2.0)
        assert v.page

    def test_page_edge_flips_health_and_clear_restores(self):
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        tel.health("serve").ready("test")
        spec = SloSpec("shed", "serve", "ratio", objective=0.9,
                       bad="bad_total", total="all_total",
                       fast_window_s=3, slow_window_s=30,
                       page_burn=2.0, min_events=1)
        eng = self._engine(spec, tel, clk)
        tel.slo = eng
        eng.evaluate()
        tel.inc("all_total", 10)
        tel.inc("bad_total", 10)
        t["now"] = 1.0
        eng.evaluate()
        assert tel.health("serve").state == DEGRADED
        assert tel.counter_value("slo_page_total") == 1
        assert tel.slo_paging("serve") and not tel.slo_paging("stream")
        # Burn gauges published for the scrape surface.
        assert tel.registry.get("slo_shed_burn_fast").value > 2.0
        t["now"] = 60.0  # everything aged out of both windows
        eng.evaluate()
        assert tel.health("serve").state == READY
        assert tel.counter_value("slo_clear_total") == 1
        assert not tel.slo_paging("serve")
        snap = eng.snapshot()
        assert snap["paging"] == [] and snap["pages_total"] == 1
        assert json.loads(json.dumps(snap)) == snap

    def test_no_engine_means_no_paging(self):
        assert not Telemetry().slo_paging("serve")


# ------------------------------------------------------ flight recorder


class TestFlightRecorder:
    def _hub(self, tmp_path, **kw):
        tel = Telemetry()
        tel.flight = FlightRecorder(
            str(tmp_path / "flight"), min_interval_s=0.0, **kw
        )
        return tel

    def test_dump_contains_ring_report_and_fingerprints(self, tmp_path):
        tel = self._hub(tmp_path)
        tel.health("serve").ready("test")
        with tel.span("serve_dispatch", batch_id=3, request_ids=[7, 8],
                      mesh="mesh(d1s2)", policy="bf16_infer"):
            pass
        tel.event("serve_request_quarantined", request_id=7)
        path = tel.flight_dump("poison_quarantine", request_id=7,
                               batch_id=3, detail="nan in image1")
        assert path and path.endswith(".json")
        assert not [p for p in os.listdir(tmp_path / "flight")
                    if p.endswith(".tmp")]  # atomic rename, no residue
        dump = load_dump(path)
        assert dump["trigger"] == "poison_quarantine"
        assert dump["context"]["request_id"] == 7
        assert dump["fingerprints"] == {
            "mesh": "mesh(d1s2)", "policy": "bf16_infer",
        }
        assert dump["report"]["health"]["serve"]["state"] == READY
        journey = match_records(dump["spans"], request_id=7)
        assert {r["name"] for r in journey} == {
            "serve_dispatch", "serve_request_quarantined",
        }
        assert tel.counter_value("flight_dump_total") == 1

    def test_rate_limit_suppresses_and_counts(self, tmp_path):
        tel = Telemetry()
        tel.flight = FlightRecorder(
            str(tmp_path / "flight"), min_interval_s=100.0
        )
        assert tel.flight_dump("poison_quarantine") is not None
        assert tel.flight_dump("poison_quarantine") is None  # limited
        assert tel.flight_dump("slo_page") is not None  # per-trigger
        assert tel.flight.suppressed == 1
        assert tel.counter_value("flight_dump_suppressed_total") == 1

    def test_failed_write_does_not_rate_limit_the_retry(self, tmp_path):
        """Review regression: the limiter throttles SUCCESSES — a
        transient write failure must leave the window open, or one I/O
        hiccup at the first fault silences the whole interval."""
        blocker = tmp_path / "blocked"
        blocker.write_text("a file where the dump dir should be")
        tel = Telemetry()
        tel.flight = FlightRecorder(str(blocker), min_interval_s=100.0)
        assert tel.flight_dump("guard_violation") is None  # write fails
        assert tel.flight.failed == 1
        tel.flight.directory = str(tmp_path / "flight")  # I/O recovers
        # Immediately retryable: NOT suppressed by the failed attempt.
        assert tel.flight_dump("guard_violation") is not None
        assert tel.flight.suppressed == 0
        # A SUCCESS does arm the limiter.
        assert tel.flight_dump("guard_violation") is None
        assert tel.flight.suppressed == 1

    def test_dump_cap_deletes_oldest(self, tmp_path):
        tel = self._hub(tmp_path, max_dumps=2)
        for i in range(4):
            assert tel.flight_dump("guard_violation", i=i)
        names = sorted(os.listdir(tmp_path / "flight"))
        assert len(names) == 2
        kept = [load_dump(str(tmp_path / "flight" / n))["context"]["i"]
                for n in names]
        assert kept == [2, 3]

    def test_disabled_hub_and_absent_recorder_are_noops(self, tmp_path):
        assert Telemetry().flight_dump("x") is None
        tel = self._hub(tmp_path)
        tel.enabled = False
        assert tel.flight_dump("x") is None
        assert not (tmp_path / "flight").exists()

    def test_load_dump_rejects_foreign_json(self, tmp_path):
        p = tmp_path / "not_a_dump.json"
        p.write_text('{"hello": 1}')
        with pytest.raises(ValueError, match="not a flight-recorder"):
            load_dump(str(p))

    def test_match_records_parity_with_for_attr(self):
        """The offline matcher and the live tracer must agree — the
        postmortem tool reads dumps with match_records."""
        tel = Telemetry()
        tel.event("a", request_ids=[1, 2], batch_id=9)
        tel.event("b", request_id=1)
        tel.event("c", request_id=3)
        recs = tel.tracer.records()
        assert match_records(recs, request_id=1) == tel.tracer.for_attr(
            request_id=1
        )
        assert match_records(recs, batch_id=9) == tel.tracer.for_attr(
            batch_id=9
        )


# --------------------------------------- periodic snapshot lifecycle


class TestPeriodicSnapshotLifecycle:
    def test_stop_before_start_is_noop(self, tmp_path):
        """The satellite fix: stop() on a never-started monitor must not
        write a phantom 'final' snapshot."""
        path = str(tmp_path / "snap.jsonl")
        with JsonlSink(path) as sink:
            snap = PeriodicSnapshot(Telemetry(), sink, interval_s=5.0)
            snap.stop()  # never started
            assert sink.write({"probe": 1})  # sink untouched and open
        lines = [json.loads(l) for l in open(path, encoding="utf-8")]
        assert lines == [{"probe": 1}]

    def test_teardown_orders_final_snapshot_before_sink_close(
        self, tmp_path
    ):
        """The serve.py teardown contract: the final stop() snapshot —
        the one describing the drained end state — lands in the sink
        BEFORE it closes (nested contexts, inner exits first)."""
        path = str(tmp_path / "snap.jsonl")
        tel = Telemetry()
        with JsonlSink(path) as sink:
            with PeriodicSnapshot(tel, sink, interval_s=60.0):
                tel.inc("late_fact_total", 7)  # only the final tick sees it
        lines = [json.loads(l) for l in open(path, encoding="utf-8")]
        snaps = [l for l in lines if l.get("name") == "telemetry_snapshot"]
        assert len(snaps) >= 2  # immediate start tick + final stop tick
        assert snaps[-1]["report"]["metrics"]["counters"][
            "late_fact_total"
        ] == 7  # the final snapshot was WRITTEN, not dropped on a closed sink

    def test_healthz_written_immediately_and_atomically(self, tmp_path):
        path = str(tmp_path / "healthz.json")
        tel = Telemetry()
        tel.health("serve").ready("test")
        snap = PeriodicSnapshot(tel, None, interval_s=60.0,
                                healthz_path=path)
        snap.start()
        hz = json.load(open(path, encoding="utf-8"))
        assert hz["overall"] == READY and not hz["draining"]
        assert hz["exit_contract"] == {"draining": 75, "halted": 76}
        tel.health("serve").draining()
        snap.stop()
        hz = json.load(open(path, encoding="utf-8"))
        assert hz["overall"] == DRAINING and hz["draining"]
        assert not os.path.exists(path + ".tmp")

    def test_snapshot_tick_evaluates_attached_slo(self, tmp_path):
        tel = Telemetry()
        tel.slo = SloEngine(serve_slos(), tel)
        with PeriodicSnapshot(tel, None, interval_s=60.0):
            pass
        assert set(tel.slo.snapshot()["verdicts"]) == {
            s.name for s in serve_slos()
        }

    def test_write_healthz_direct(self, tmp_path):
        path = str(tmp_path / "hz.json")
        tel = Telemetry()
        write_healthz(path, tel)
        hz = json.load(open(path, encoding="utf-8"))
        assert hz["health"] == {} and hz["slo"] is None

    def test_healthz_replica_identity_schema(self, tmp_path):
        """The fleet-facing healthz schema (docs/FLEET.md): pid +
        process start time always present; the producer-deposited
        identity (mesh fingerprint, warmed executable set) merged
        verbatim; the cadence published WITH its staleness contract
        (stale_after_s = 2x interval) so a consumer never has to guess
        how old is dead."""
        import os as _os

        path = str(tmp_path / "hz.json")
        tel = Telemetry()
        tel.identity.update({
            "replica": 3,
            "mesh": "mesh(data=1,spatial=1)",
            "warmed": [[48, 64, 1, 2], [48, 64, 2, 2]],
        })
        write_healthz(path, tel, interval_s=0.25)
        hz = json.load(open(path, encoding="utf-8"))
        # Replica identity: who is answering this file.
        assert hz["pid"] == _os.getpid()
        assert hz["start_time_unix_s"] <= hz["time_unix_s"]
        assert hz["replica"] == 3
        assert hz["mesh"] == "mesh(data=1,spatial=1)"
        assert hz["warmed"] == [[48, 64, 1, 2], [48, 64, 2, 2]]
        # The staleness contract, pinned: the writer promises the
        # cadence, the consumer must treat 2x it as dead.
        assert hz["interval_s"] == 0.25
        assert hz["stale_after_s"] == 0.5
        from raft_ncup_tpu.fleet import healthz_fresh

        assert healthz_fresh(hz, hz["stale_after_s"])
        assert not healthz_fresh(
            hz, hz["stale_after_s"],
            now_unix=hz["time_unix_s"] + 2.01 * hz["interval_s"],
        )
        # Without an interval the identity fields still land, and the
        # cadence fields are absent rather than invented.
        write_healthz(path, tel)
        hz = json.load(open(path, encoding="utf-8"))
        assert "interval_s" not in hz and "stale_after_s" not in hz
        assert hz["pid"] == _os.getpid()


# -------------------------------------------- prometheus compliance


_SAMPLE_RE = None


class TestPrometheusCompliance:
    """A mini-parser pinning the exposition format a real scraper
    ingests unmodified: name charset, TYPE lines for every family,
    histogram bucket/sum/count triplet with cumulative +Inf."""

    def _parse(self, text):
        import re

        name_re = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
        sample_re = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
            r'(\{le="[^"]+"\})? '
            r"(-?[0-9.eE+]+|\+Inf|NaN)$"
        )
        types, samples = {}, []
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                _, _, name, kind = line.split(" ", 3)
                assert name_re.match(name), line
                assert kind in ("counter", "gauge", "histogram"), line
                assert name not in types, f"duplicate TYPE: {line}"
                types[name] = kind
            elif line.startswith("# HELP "):
                assert "\n" not in line
            else:
                m = sample_re.match(line)
                assert m, f"malformed sample line: {line!r}"
                samples.append((m.group(1), m.group(2), m.group(3)))
        return types, samples

    def _family(self, name, types):
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                return base
        return name

    def test_every_sample_has_a_typed_family(self):
        reg = MetricsRegistry()
        reg.counter("serve_requests_shed_total", help="shed requests").inc(2)
        reg.gauge("serve_queue_depth").set(5)
        reg.histogram("serve_drain_ms").observe_ms(3.0)
        reg.histogram("serve_drain_ms").observe_ms(7000.0)
        types, samples = self._parse(reg.prometheus_text())
        assert samples, "no samples emitted"
        for name, _, _ in samples:
            fam = self._family(name, types)
            assert fam in types, f"untyped family for sample {name}"
        # The gauge's peak companion is its own typed gauge family.
        assert types["serve_queue_depth_peak"] == "gauge"

    def test_histogram_triplet_cumulative_plus_inf(self):
        reg = MetricsRegistry()
        h = reg.histogram("x_ms")
        for ms in (0.5, 3.0, 3.0, 250.0, 99999.0):
            h.observe_ms(ms)
        types, samples = self._parse(reg.prometheus_text())
        buckets = [
            (label, float(v)) for name, label, v in samples
            if name == "x_ms_bucket"
        ]
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), "buckets must be cumulative"
        assert buckets[-1][0] == '{le="+Inf"}'
        count = next(
            float(v) for name, _, v in samples if name == "x_ms_count"
        )
        assert buckets[-1][1] == count == 5
        assert any(name == "x_ms_sum" for name, _, _ in samples)

    def test_names_sanitized_to_exposition_charset(self):
        reg = MetricsRegistry()
        reg.counter("serve queue.depth-total").inc()
        reg.counter("0starts_with_digit").inc()
        types, samples = self._parse(reg.prometheus_text())
        names = {n for n, _, _ in samples}
        assert "serve_queue_depth_total" in names
        assert "_0starts_with_digit" in names

    def test_help_text_escaped_to_one_line(self):
        reg = MetricsRegistry()
        reg.counter("c_total", help="line one\nline two \\ backslash")
        text = reg.prometheus_text()
        self._parse(text)  # no malformed lines
        assert r"line one\nline two \\ backslash" in text


# ------------------------------------------- the closed loop, end to end


class TestClosedLoop:
    def test_chaos_burst_poison_drives_degrade_then_recovery(
        self, tmp_path
    ):
        """The tentpole acceptance trajectory, deterministic end to end:
        a burst past queue capacity (sheds) plus a poison request drive
        the declared shed-rate SLO into burn -> the page edge flips
        health READY -> DEGRADED and arms the budget controller's second
        degrade input -> the controller walks down the level set (at
        least one drop attributable to the SLO alone, occupancy below
        high water) -> the burst ages out of both burn windows -> the
        clear edge restores READY -> sustained calm recovers the budget
        level by level. Exact state and level trajectories asserted;
        the slo_page and poison_quarantine faults each left a flight
        dump."""
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        tel.flight = FlightRecorder(
            str(tmp_path / "flight"), min_interval_s=0.0
        )
        tel.slo = SloEngine(serve_slos(window_scale=0.01), tel, clock=clk)
        cfg = _cfg(
            queue_capacity=8, batch_sizes=(1, 2),
            iter_levels=(8, 4, 2), high_water=1.0, low_water=0.25,
            recover_patience=2,
        )
        srv = FlowServer(_DummyModel(), {}, cfg, telemetry=tel)
        try:
            srv.warmup((24, 32))
            assert srv.health.state == READY
            tel.slo.evaluate()  # baseline sample at t=0

            # ---- burst + poison: 12 submits against capacity 8 ------
            srv.pause()
            poison = _img(5)
            poison[3, 3, 0] = np.nan
            handles = []
            for i in range(12):
                img = poison if i == 7 else _img(10 + i)
                handles.append(srv.submit(img, _img(30 + i)))
            assert srv.stats.shed == 4  # 12 offered, capacity 8
            t["now"] = 1.0
            verdicts = tel.slo.evaluate()
            # shed fraction 4/12 over budget 0.01 -> burn ~33x: page.
            assert verdicts["serve_shed_rate"].page
            assert srv.health.state == DEGRADED

            # ---- degraded dispatch: the SLO drives the knob ---------
            srv.resume()
            responses = [h.result(60) for h in handles]
        finally:
            srv.drain()
        ok = [r for r in responses if r.status == STATUS_OK]
        rejected = [r for r in responses if r.status == "rejected"]
        assert len(ok) == 7 and len(rejected) == 1  # poison quarantined
        # 4 batches of 2: level 0 -> 1 (occupancy at full queue, paging)
        # -> 2 (paging ALONE: occupancy already back under high water)
        # -> floor. Per-batch budgets land in the responses.
        assert sorted(r.iters for r in ok) == [2, 2, 2, 2, 2, 4, 4]
        assert srv.budget.level == 2
        assert srv.budget.drops == 2
        assert srv.budget.slo_drops >= 1  # telemetry drove the knob
        assert srv.report()["budget_slo_drops"] == srv.budget.slo_drops

        # ---- recovery: burn windows drain, then earned calm ---------
        t["now"] = 60.0  # past the scaled slow window
        tel.slo.evaluate()
        assert not tel.slo_paging("serve")
        assert srv.health.state == DRAINING  # drain() above ran already

        # Re-run the recovery phase on a fresh server sharing the hub's
        # (now clean) SLO verdicts: four calm single-request decisions
        # recover 2 levels with patience 2.
        srv2 = FlowServer(_DummyModel(), {}, cfg, telemetry=tel)
        try:
            srv2.warmup((24, 32))
            srv2.budget._level = 2  # resume from the degraded level
            iters_seen = []
            for i in range(4):
                r = srv2.submit(_img(70 + i), _img(80 + i)).result(60)
                assert r.ok
                iters_seen.append(r.iters)
        finally:
            srv2.drain()
        assert iters_seen == [2, 4, 4, 8]
        assert srv2.budget.recoveries == 2 and srv2.budget.level == 0

        # ---- health trajectory + flight evidence --------------------
        transitions = [
            (h["from"], h["to"]) for h in srv.health.history()
        ]
        assert transitions == [
            (STARTING, WARMING),
            (WARMING, READY),
            (READY, DEGRADED),
            (DEGRADED, DRAINING),
        ]
        dumps = sorted(os.listdir(tmp_path / "flight"))
        assert sum("slo_page" in d for d in dumps) == 1
        assert sum("poison_quarantine" in d for d in dumps) == 1
        # The poison dump reassembles the faulting request's journey.
        poison_dump = next(
            d for d in dumps if "poison_quarantine" in d
        )
        dump = load_dump(str(tmp_path / "flight" / poison_dump))
        assert dump["context"]["request_id"] == 7
        journey = match_records(dump["spans"], request_id=7)
        assert "serve_queue_wait" in {r["name"] for r in journey}


# ------------------- guarded window with the full consumer half armed


class TestConsumersPreserveInvariants:
    def test_guarded_window_with_health_slo_flight_enabled(
        self, tiny_model, forbid_host_transfers, max_recompiles,
        tmp_path,
    ):
        """The tentpole's standing constraint extended to the consumer
        half: with health tracking, the SLO engine (evaluated INSIDE the
        guarded window), and the flight recorder all armed, a warm
        steady-state serving window still performs ZERO implicit host
        pulls and ZERO compiles, with exactly one sanctioned get per
        batch — the closed loop observes and decides without ever
        touching the device."""
        model, variables = tiny_model
        tel = Telemetry()
        tel.flight = FlightRecorder(str(tmp_path / "flight"))
        tel.slo = SloEngine(serve_slos(), tel)
        cfg = _cfg(batch_sizes=(1,), iter_levels=(2, 1))
        srv = FlowServer(model, variables, cfg, telemetry=tel)
        try:
            srv.warmup((40, 48))
            warm = srv.submit(_img(30, (40, 48)), _img(31, (40, 48)))
            assert warm.result(120).ok
            tel.slo.evaluate()  # baseline
            with forbid_host_transfers() as stats, max_recompiles(0):
                handles = [
                    srv.submit(_img(40 + i, (40, 48)),
                               _img(50 + i, (40, 48)))
                    for i in range(3)
                ]
                rs = [h.result(120) for h in handles]
                verdicts = tel.slo.evaluate()  # burn math inside guards
        finally:
            srv.drain()
        assert [r.status for r in rs] == [STATUS_OK] * 3
        assert stats.host_transfers == 0
        assert stats.sanctioned_gets == 3  # one per batch, unchanged
        assert srv.health.state == DRAINING  # via drain(); READY inside
        assert not any(v.page for v in verdicts.values())
        # No fault triggered: the recorder stayed quiet.
        assert tel.flight.dumps == 0
        # e2e latency histogram fed the latency SLI without a ring record.
        assert tel.registry.get("serve_e2e_ms").count >= 3
        rep = telemetry_report(tel)
        assert rep["health"]["serve"]["state"] == DRAINING
        assert rep["slo"]["verdicts"]


class TestSloEngineReviewRegressions:
    def test_ring_overflow_thins_resolution_not_the_window(
        self, monkeypatch
    ):
        """Review regression: at a sub-second cadence (fleet replicas
        tick every 0.25 s) a blind sample cap would evict the slow
        window's delta base and silently compute burn_slow over
        cap x cadence seconds instead of the DECLARED slow window. On
        overflow the ring must halve resolution, keeping its oldest
        in-window sample."""
        import raft_ncup_tpu.observability.slo as slo_mod

        monkeypatch.setattr(slo_mod, "_RING_CAP", 64)
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        spec = SloSpec("shed", "serve", "ratio", objective=0.9,
                       bad="bad_total", total="all_total",
                       fast_window_s=10, slow_window_s=100,
                       page_burn=2.0, min_events=1)
        eng = SloEngine([spec], tel, clock=clk)
        # A burst of bad events early, then a long clean steady state:
        # only a full-width slow window still sees the burst's delta.
        tel.inc("all_total", 10)
        tel.inc("bad_total", 10)
        for i in range(400):  # 200 s at 0.5 s cadence >> cap 64
            t["now"] = i * 0.5
            tel.inc("all_total", 1)  # clean traffic
            eng.evaluate()
        ring = eng._samples["shed"]
        # Memory stays bounded near the cap...
        assert len(ring) <= 2 * 64
        # ...and the base still spans the DECLARED window: the oldest
        # kept sample is ~100 s old, not 64 x 0.5 = 32 s.
        now = t["now"]
        assert now - ring[0][0] >= spec.slow_window_s * 0.8
        # burn_slow therefore reflects the full window's clean delta,
        # not a truncated horizon.
        v = eng.verdicts()["shed"]
        assert v.burn_slow < 2.0 and not v.page

    def test_gauge_occupancy_slo_can_actually_page(self):
        """Review regression: a gauge SLI saturates at bad_fraction 1.0,
        so its max burn is 1/(1-objective) — the declared occupancy SLO
        must keep that above page_burn or it can NEVER page (the 0.9
        objective capped burn at 10 < 14.4, silently)."""
        spec = next(
            s for s in stream_slos(capacity=4)
            if s.name == "stream_slot_occupancy"
        )
        assert 1.0 / spec.budget >= spec.page_burn
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        eng = SloEngine(
            [spec.scaled(0.001)], tel, clock=clk
        )  # fast 0.3s / slow 3.6s windows
        tel.gauge_set("stream_slot_occupancy", 4)  # pinned full table
        for i in range(80):
            t["now"] = i * 0.05
            eng.evaluate()
        assert eng.verdicts()["stream_slot_occupancy"].page

    def test_page_during_warming_degrades_once_ready(self):
        """Review regression: a page edge while the tracker is still
        STARTING/WARMING is an illegal degrade edge (no-op); the ONGOING
        page must still flip health the next evaluation after the
        subsystem becomes READY — edges alone would leave it 'ready'
        for the whole page."""
        t, clk = _clocked()
        tel = Telemetry(clock=clk)
        spec = SloSpec("shed", "serve", "ratio", objective=0.9,
                       bad="bad_total", total="all_total",
                       fast_window_s=30, slow_window_s=300,
                       page_burn=2.0, min_events=1)
        eng = SloEngine([spec], tel, clock=clk)
        tracker = tel.health("serve")
        tracker.warming()  # page will fire during warmup
        eng.evaluate()
        tel.inc("all_total", 10)
        tel.inc("bad_total", 10)
        t["now"] = 1.0
        eng.evaluate()
        assert eng.paging("serve")
        assert tracker.state == WARMING  # degrade edge was illegal here
        tracker.ready("warmup done")
        t["now"] = 2.0
        eng.evaluate()  # page still ongoing: degrade re-asserted
        assert tracker.state == DEGRADED
        # And a fresh tracker (re-entrant driver) degrades too.
        fresh = tel.health("serve", fresh=True)
        fresh.ready("second server")
        t["now"] = 3.0
        eng.evaluate()
        assert fresh.state == DEGRADED


# ---------------------------------------------------------------- traces


class TestTraceContext:
    """Cross-process trace context (observability/spans.py): the
    serializable (trace_id, parent span_id, clock offset) that rides the
    fleet wire header as an OPTIONAL field."""

    def test_wire_round_trip(self):
        from raft_ncup_tpu.observability import TraceContext

        ctx = TraceContext("abcd1234", "router-7", 0.125, 42.5)
        wire = ctx.to_wire()
        assert json.loads(json.dumps(wire)) == wire  # JSON-able
        back = TraceContext.from_wire(wire)
        assert back == ctx

    def test_from_wire_tolerates_absent_and_garbage(self):
        """Old peers send no context; corrupt headers send nonsense —
        both parse to None, never an exception (the wire-compat
        contract JGL010 pins statically)."""
        from raft_ncup_tpu.observability import TraceContext

        assert TraceContext.from_wire(None) is None
        assert TraceContext.from_wire("not-a-dict") is None
        assert TraceContext.from_wire({}) is None
        assert TraceContext.from_wire({"trace_id": 7}) is None
        assert TraceContext.from_wire(
            {"trace_id": "x", "sent_s": "garbage"}
        ) is None
        # Minimal valid: just a trace id.
        ctx = TraceContext.from_wire({"trace_id": "x"})
        assert ctx is not None and ctx.trace_id == "x"
        assert ctx.clock_offset_s == 0.0 and ctx.sent_s is None

    def test_child_reparents_same_trace(self):
        from raft_ncup_tpu.observability import TraceContext

        ctx = TraceContext("t1", "root", 0.5, 1.0)
        kid = ctx.child("replica-3", sent_s=2.0)
        assert kid.trace_id == "t1"
        assert kid.span_id == "replica-3"
        assert kid.clock_offset_s == 0.5
        assert kid.sent_s == 2.0

    def test_trace_ids_are_unique(self):
        from raft_ncup_tpu.observability import new_trace_id

        ids = {new_trace_id() for _ in range(64)}
        assert len(ids) == 64
        assert all(len(i) == 16 for i in ids)


class TestRecordTimestamps:
    """Every ring record stamps ``t_s`` (its start on the tracer's
    monotonic clock) — the absolute anchor aggregate.py orders
    cross-process timelines by."""

    def test_span_event_and_observe_carry_t_s(self):
        t = {"now": 100.0}
        tracer = SpanTracer(MetricsRegistry(), clock=lambda: t["now"])
        with tracer.span("stage_a"):
            t["now"] = 100.25
        tracer.event("thing_happened")
        t["now"] = 101.0
        tracer.observe_ms("stage_b", 500.0)  # ended now, started -0.5s
        recs = {r["name"]: r for r in tracer.records()}
        assert recs["stage_a"]["t_s"] == 100.0
        assert recs["stage_a"]["duration_ms"] == 250.0
        assert recs["thing_happened"]["t_s"] == 100.25
        assert recs["stage_b"]["t_s"] == pytest.approx(100.5)


class TestAggregate:
    """observability/aggregate.py: tolerant readers, the stitched fleet
    trace tree with clock-offset translation, per-hop attribution, and
    the merged registry view that marks dead replicas as gaps."""

    @staticmethod
    def _dump(path, spans, context=None):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({
                "flight_recorder_version": 1,
                "trigger": "test",
                "time_unix_s": 0.0,
                "context": context or {},
                "fingerprints": {},
                "report": None,
                "spans": spans,
            }, fh)

    def test_read_jsonl_tolerant_skips_truncated_tail(self, tmp_path):
        """A replica killed mid-write leaves a partial last line: the
        reader skips and COUNTS it instead of raising (the satellite
        fix — a postmortem must survive the evidence of the fault)."""
        from raft_ncup_tpu.observability import read_jsonl_tolerant

        p = tmp_path / "replica_0_telemetry.jsonl"
        with open(p, "w") as fh:
            fh.write(json.dumps({"name": "telemetry_snapshot",
                                 "report": {"metrics": {}}}) + "\n")
            fh.write('{"name": "telemetry_snapshot", "repo')  # truncated
        records, skipped = read_jsonl_tolerant(str(p))
        assert len(records) == 1
        assert skipped == 1
        # Missing file: empty, not an exception.
        assert read_jsonl_tolerant(str(tmp_path / "absent.jsonl")) == ([], 0)

    def _fleet_tree(self, tmp_path, offset=5.0):
        """A synthetic two-process export: the router's ring (root span
        + dispatch event, offsets in the drain dump context) and replica
        1's ring (wire hop + queue wait + dispatch + drain), with the
        replica's clock ``offset`` seconds AHEAD of the router's."""
        tid = "aaaa000011112222"
        router = [
            {"name": "fleet_dispatch", "event": True, "t_s": 10.001,
             "attrs": {"request_id": 7, "replica": 1, "trace_id": tid}},
            {"name": "fleet_request", "duration_ms": 250.0, "t_s": 10.0,
             "attrs": {"request_id": 7, "replica": 1, "trace_id": tid}},
        ]
        replica = [
            {"name": "fleet_wire_hop", "duration_ms": 2.0,
             "t_s": 10.003 + offset,
             "attrs": {"request_id": 7, "trace_id": tid,
                       "parent_span_id": "router-7"}},
            {"name": "serve_queue_wait", "duration_ms": 40.0,
             "t_s": 10.003 + offset,
             "attrs": {"request_id": 7, "batch_id": 0,
                       "trace_id": tid}},
            {"name": "serve_dispatch", "duration_ms": 5.0,
             "t_s": 10.044 + offset,
             "attrs": {"batch_id": 0, "request_ids": [7],
                       "trace_ids": [tid], "iters": 2,
                       "mesh": "nomesh", "policy": "f32"}},
            {"name": "serve_drain", "duration_ms": 180.0,
             "t_s": 10.049 + offset,
             "attrs": {"batch_id": 0, "request_ids": [7],
                       "trace_ids": [tid]}},
        ]
        self._dump(
            str(tmp_path / "router_flight" /
                "flight_router_drain_20260801T000000_0001.json"),
            router,
            context={"clock_offsets": {"1": offset}},
        )
        self._dump(
            str(tmp_path / "replica_1_flight" /
                "flight_preemption_drain_20260801T000000_0001.json"),
            replica,
        )
        return tid

    def test_trace_tree_spans_processes_with_nonnegative_hops(
        self, tmp_path
    ):
        """One request → ONE trace_id across router and replica records,
        replica timestamps translated through the handshake offset, and
        every per-hop delta non-negative."""
        from raft_ncup_tpu.observability import (
            collect_fleet_records,
            fleet_traces,
            render_trace,
        )

        tid = self._fleet_tree(tmp_path, offset=5.0)
        collected = collect_fleet_records(str(tmp_path))
        assert collected["clock_offsets"] == {1: 5.0}
        traces = fleet_traces(collected)
        assert len(traces) == 1
        tr = traces[0]
        assert tr["trace_id"] == tid
        assert tr["request_id"] == 7
        assert tr["origins"] == ["replica_1", "router"]
        assert tr["total_ms"] == 250.0
        # Translated timeline is ordered: root first, drain last.
        names = [r["name"] for r in tr["records"]]
        assert names[0] == "fleet_request"
        assert names.index("fleet_wire_hop") < names.index("serve_drain")
        hops = tr["hops"]
        for key in ("router_queue_ms", "wire_ms", "replica_queue_ms",
                    "device_ms", "return_ms"):
            assert key in hops, hops
            assert hops[key] >= 0.0
        assert hops["replica_queue_ms"] == 40.0
        assert hops["device_ms"] == 180.0
        assert hops["wire_ms"] == 2.0
        # total = hops + residual, exactly.
        assert sum(hops.values()) == pytest.approx(250.0)
        # Renderable without error, mentions both origins.
        text = "\n".join(render_trace(tr))
        assert "router" in text and "replica_1" in text

    def test_request_id_filter_and_skewed_offset_clamps(self, tmp_path):
        """A wrong offset estimate must clamp hops at zero, never go
        negative; the request_id filter narrows to one journey."""
        from raft_ncup_tpu.observability import (
            collect_fleet_records,
            fleet_traces,
        )

        self._fleet_tree(tmp_path, offset=5.0)
        collected = collect_fleet_records(str(tmp_path))
        # Sabotage the offset by a full second: the translated replica
        # records now precede the router's dispatch.
        collected["clock_offsets"][1] = 6.0
        traces = fleet_traces(collected, request_id=7)
        assert len(traces) == 1
        assert all(v >= 0.0 for v in traces[0]["hops"].values())
        assert fleet_traces(collected, request_id=999) == []

    def test_aggregate_registry_marks_dead_replica_gap(self, tmp_path):
        """The merged registry view SUMS counters and MAXES gauges over
        the replicas that exported, and NAMES the one that did not
        (dead replica ⇒ gap) instead of silently shrinking the fleet."""
        from raft_ncup_tpu.observability import aggregate_registry

        def snap(path, completed, depth):
            with open(path, "w") as fh:
                fh.write(json.dumps({
                    "name": "telemetry_snapshot",
                    "time_unix_s": 0.0,
                    "report": {"metrics": {
                        "counters": {"serve_completed_total": completed},
                        "gauges": {"serve_queue_depth":
                                   {"value": depth, "peak": depth + 1}},
                    }},
                }) + "\n")

        snap(tmp_path / "replica_0_telemetry.jsonl", 10, 2)
        snap(tmp_path / "replica_2_telemetry.jsonl", 32, 5)
        # Replica 1 existed (its socket path names it) but died without
        # an export.
        (tmp_path / "replica_1.sock").write_text("")
        agg = aggregate_registry(str(tmp_path))
        assert agg["counters"]["serve_completed_total"] == 42
        assert agg["gauges"]["serve_queue_depth"]["value"] == 5
        assert agg["gauges"]["serve_queue_depth"]["peak"] == 6
        assert agg["replicas"] == [0, 2]
        assert agg["gaps"] == [1]

    def test_aggregate_registry_tolerates_truncated_jsonl(self, tmp_path):
        from raft_ncup_tpu.observability import aggregate_registry

        p = tmp_path / "replica_0_telemetry.jsonl"
        with open(p, "w") as fh:
            fh.write(json.dumps({
                "name": "telemetry_snapshot",
                "report": {"metrics": {"counters": {"x_total": 3}}},
            }) + "\n")
            fh.write('{"name": "telemetry_snapsho')  # killed mid-write
        agg = aggregate_registry(str(tmp_path))
        assert agg["counters"] == {"x_total": 3}
        assert agg["skipped_lines"] == 1
        assert agg["gaps"] == []

    def test_collect_skips_torn_dump_falls_back_to_older(self, tmp_path):
        """The newest dump of a process may be torn (killed mid-write
        pre-os.replace never happens, but copies/foreign files do):
        collection walks back to the newest PARSABLE one and counts the
        skip."""
        from raft_ncup_tpu.observability import collect_fleet_records

        good = [{"name": "fleet_request", "duration_ms": 1.0,
                 "t_s": 0.0, "attrs": {"trace_id": "t", "request_id": 1}}]
        self._dump(
            str(tmp_path / "router_flight" /
                "flight_router_drain_20260801T000000_0001.json"),
            good,
        )
        torn = (tmp_path / "router_flight" /
                "flight_router_drain_20260801T000001_0002.json")
        torn.write_text('{"flight_recorder_version": 1, "spa')
        collected = collect_fleet_records(str(tmp_path))
        assert collected["skipped_dumps"] == 1
        assert [r["name"] for r in collected["origins"]["router"]] == [
            "fleet_request"
        ]
