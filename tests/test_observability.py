"""Unified telemetry subsystem (raft_ncup_tpu/observability/;
docs/OBSERVABILITY.md): registry thread-safety, histogram percentile
parity with the shared nearest-rank discipline, span correlation through
a real FlowServer batch, report() back-compat keys (pinned alias table),
the bounded export sinks, and the platform invariant — a steady-state
serving window stays sync-free and recompile-free with tracing FULLY
enabled.
"""

import json
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import ServeConfig, StreamConfig, small_model_config
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.observability import (
    JsonlSink,
    LEGACY_KEY_ALIASES,
    MetricsRegistry,
    PeriodicSnapshot,
    SpanTracer,
    Telemetry,
    host_number,
    telemetry_report,
)
from raft_ncup_tpu.observability.telemetry import Histogram
from raft_ncup_tpu.serving import AdmissionQueue, FlowServer
from raft_ncup_tpu.serving.request import (
    STATUS_OK,
    FlowRequest,
    ServeStats,
    nearest_rank_ms,
)
from raft_ncup_tpu.streaming import StreamEngine
from raft_ncup_tpu.streaming.engine import StreamStats


# ------------------------------------------------------------- test rigs


class _DummyModel:
    """apply()-compatible stand-in (tests/test_serving.py's rig)."""

    def apply(self, variables, image1, image2, iters=1, flow_init=None,
              test_mode=True, mesh=None, metric_head=None, **kw):
        flow_up = jnp.stack(
            [image1[..., 0] * iters, image1[..., 1]], axis=-1
        )
        return image1.mean(), flow_up


class _DummyVideoModel:
    """apply()-compatible streaming stand-in (tests/test_streaming.py)."""

    cfg = SimpleNamespace(hidden_dim=4)

    def apply(self, variables, image1, image2, iters=1, flow_init=None,
              test_mode=True, return_net=False, net_init=None,
              net_warm=None, **kw):
        B, H, W, _ = image1.shape
        lr = image1[:, ::8, ::8, :2] * 0.01
        if flow_init is not None:
            lr = lr + flow_init
        up = jnp.repeat(jnp.repeat(lr, 8, axis=1), 8, axis=2)
        if return_net:
            net = jnp.full((B, H // 8, W // 8, 4), 0.5, jnp.float32)
            return lr, up, net
        return lr, up


def _img(seed=0, hw=(24, 32)):
    g = np.random.default_rng(seed)
    return (g.random((*hw, 3)) * 255.0).astype(np.float32)


def _cfg(**kw):
    base = dict(
        queue_capacity=8, batch_sizes=(1, 2), iter_levels=(4, 2),
        recover_patience=2,
    )
    base.update(kw)
    return ServeConfig(**base)


# ------------------------------------------------------------- registry


class TestRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc()
        reg.counter("a_total").inc(4)
        reg.gauge("depth").set(3)
        reg.gauge("depth").set(1)
        reg.histogram("lat_ms").observe_ms(12.0)
        snap = reg.snapshot()
        assert snap["counters"]["a_total"] == 5
        assert snap["gauges"]["depth"] == {"value": 1.0, "peak": 3.0}
        assert snap["histograms"]["lat_ms"]["count"] == 1
        assert json.loads(json.dumps(snap)) == snap  # JSON-able

    def test_kind_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("x")

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("x").inc(-1)

    def test_thread_safety_no_lost_updates(self):
        """The accounting-under-concurrency property the registry exists
        for: N threads x M increments lose nothing."""
        reg = MetricsRegistry()
        n_threads, per_thread = 8, 500

        def work():
            c = reg.counter("hits_total")
            h = reg.histogram("work_ms")
            for i in range(per_thread):
                c.inc()
                h.observe_ms(float(i % 50))

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("hits_total").value == n_threads * per_thread
        assert reg.histogram("work_ms").count == n_threads * per_thread

    def test_rejects_jax_typed_values_without_converting(self):
        """The no-added-sync contract at runtime: anything device-side
        is refused BEFORE conversion (float() on a device array is the
        sync). Pinned against a REAL concrete array (whose type lives
        under jaxlib, not jax) AND a jax-module stand-in (tracers)."""
        real = jnp.float32(3.5)  # type module: jaxlib.xla_extension
        with pytest.raises(TypeError, match="device sync"):
            host_number(real)
        fake = type("Tracer", (), {"__module__": "jax._src.array"})()
        with pytest.raises(TypeError, match="device sync"):
            host_number(fake)
        reg = MetricsRegistry()
        for bad in (real, fake):
            with pytest.raises(TypeError):
                reg.counter("c").inc(bad)
            with pytest.raises(TypeError):
                reg.gauge("g").set(bad)
            with pytest.raises(TypeError):
                reg.histogram("h_ms").observe_ms(bad)

    def test_prometheus_text_shape(self):
        reg = MetricsRegistry()
        reg.counter("serve_requests_shed_total").inc(2)
        reg.gauge("serve_queue_depth").set(5)
        reg.histogram("serve_drain_ms").observe_ms(3.0)
        text = reg.prometheus_text()
        assert "# TYPE serve_requests_shed_total counter" in text
        assert "serve_requests_shed_total 2" in text
        assert "serve_queue_depth_peak 5" in text
        assert 'serve_drain_ms_bucket{le="+Inf"} 1' in text
        assert "serve_drain_ms_count 1" in text


class TestHistogramPercentiles:
    def test_parity_with_serving_nearest_rank_ms(self):
        """The shared percentile discipline: the histogram's nearest-rank
        over its raw-sample window must equal serving.nearest_rank_ms on
        the identical latency sample (seconds -> ms)."""
        g = np.random.default_rng(7)
        lat_s = list(g.gamma(2.0, 0.05, size=257))
        hist = Histogram("lat_ms")
        for s in lat_s:
            hist.observe_ms(s * 1000.0)
        for p in (0.5, 0.9, 0.95, 0.99):
            assert hist.percentile_ms(p) == nearest_rank_ms(lat_s, p)

    def test_empty_percentile_is_none(self):
        assert Histogram("x_ms").percentile_ms(0.5) is None

    def test_sample_window_bounds_memory(self):
        hist = Histogram("x_ms", sample_cap=10)
        for i in range(100):
            hist.observe_ms(float(i))
        # Bucket counts keep the full history, percentiles the window
        # (the most recent sample_cap observations: 90..99 ms).
        assert hist.count == 100
        assert hist.percentile_ms(0.5) == 94.0


# ----------------------------------------------------------- span tracer


class TestSpanTracer:
    def test_span_feeds_stage_histogram(self):
        t = [0.0]
        tel = Telemetry(clock=lambda: t[0])
        with tel.span("serve_dispatch", batch_id=1):
            t[0] += 0.25
        assert tel.registry.histogram("serve_dispatch_ms").count == 1
        assert tel.tracer.stage_summary()["serve_dispatch"]["p50_ms"] == 250.0

    def test_event_counts_and_correlates(self):
        tel = Telemetry()
        tel.event("stream_slot_evicted", stream_id="s1", slot=2)
        assert tel.counter_value("stream_slot_evicted_total") == 1
        (rec,) = tel.tracer.for_attr(stream_id="s1")
        assert rec["name"] == "stream_slot_evicted"

    def test_singular_key_matches_plural_list_attr(self):
        tel = Telemetry()
        tel.event("serve_dispatch_done", request_ids=[4, 5])
        assert tel.tracer.for_attr(request_id=4)
        assert not tel.tracer.for_attr(request_id=6)

    def test_ring_is_bounded_and_counts_drops(self):
        tel = Telemetry(span_capacity=4)
        for i in range(10):
            tel.event("e", i=i)
        assert len(tel.tracer.records()) == 4
        assert tel.tracer.dropped == 6
        assert [r["attrs"]["i"] for r in tel.tracer.records()] == [
            6, 7, 8, 9,
        ]

    def test_span_attrs_reject_jax_values(self):
        tel = Telemetry()
        fake = type("Arr", (), {"__module__": "jax"})()
        with pytest.raises(TypeError, match="device sync"):
            tel.event("e", value=fake)
        with pytest.raises(TypeError, match="device sync"):
            tel.event("e", value=jnp.ones(()))  # real device scalar

    def test_disabled_hub_is_inert(self):
        tel = Telemetry(enabled=False)
        tel.inc("c_total")
        tel.gauge_set("g", 1)
        tel.event("e")
        tel.observe_ms("stage", 5.0)
        with tel.span("s"):
            pass
        assert tel.registry.names() == []
        assert tel.tracer.records() == []


# ------------------------------------------- stats mirroring / aliases


class TestLegacyAliases:
    def test_every_serve_stats_field_has_a_pinned_alias(self):
        s = ServeStats()
        int_fields = [
            k for k, v in vars(s).items()
            if isinstance(v, int) and not k.startswith("_")
        ]
        assert sorted(int_fields) == sorted(LEGACY_KEY_ALIASES["serve"])

    def test_every_stream_stats_field_has_a_pinned_alias(self):
        s = StreamStats()
        int_fields = [
            k for k, v in vars(s).items()
            if isinstance(v, int) and not k.startswith("_")
        ]
        assert sorted(int_fields) == sorted(LEGACY_KEY_ALIASES["stream"])

    def test_serve_stats_mirror_values_match_legacy_fields(self):
        tel = Telemetry()
        s = ServeStats(telemetry=tel)
        s.note_submitted()
        s.note_submitted()
        s.note_accepted()
        s.note_shed()
        s.note_timeout()
        s.note_error()
        s.note_completed()
        s.note_batch(padded_rows=3)
        s.note_rejected(9, quarantine=True)
        canon = LEGACY_KEY_ALIASES["serve"]
        for legacy, name in canon.items():
            assert tel.counter_value(name) == getattr(s, legacy), legacy
        # The dispatch-time quarantine also lands as a correlated event.
        assert tel.tracer.for_attr(request_id=9)

    def test_stream_stats_mirror_values_match_legacy_fields(self):
        tel = Telemetry()
        s = StreamStats(telemetry=tel)
        s.note("submitted")
        s.note("accepted")
        s.note("shed_streams")
        s.note("padded_rows", 4)
        s.note("cold_starts")
        canon = LEGACY_KEY_ALIASES["stream"]
        for legacy, name in canon.items():
            assert tel.counter_value(name) == getattr(s, legacy), legacy

    def test_summary_keys_survive_verbatim(self):
        """The exact legacy summary lines downstream parsers read."""
        assert ServeStats().summary() == (
            "submitted=0 accepted=0 completed=0 shed=0 timeouts=0 "
            "rejected=0 errors=0 batches=0 padded_rows=0 quarantined=[-]"
        )
        assert StreamStats().summary() == (
            "submitted=0 accepted=0 completed=0 shed_streams=0 "
            "shed_frames=0 rejected=0 resets=0 errors=0 batches=0 "
            "padded_rows=0 opened=0 closed=0 evicted=0 cold_starts=0"
        )


# ------------------------------------------------------ admission gauges


class TestAdmissionQueueGauges:
    def _req(self, rid):
        return FlowRequest(rid, None, None, shape_key="a")

    def test_depth_observable_between_offer_and_pop(self):
        """The satellite fix: live depth is a gauge from the first
        offer, not something inferred from shed events after the fact."""
        tel = Telemetry()
        q = AdmissionQueue(8, telemetry=tel, name="serve")
        for i in range(3):
            q.offer(self._req(i))
        g = tel.registry.get("serve_queue_depth")
        assert g is not None and g.value == 3
        q.pop_batch(2)
        assert g.value == 1
        q.pop_batch(2)
        assert g.value == 0
        assert g.peak == 3

    def test_service_time_ema_gauge(self):
        tel = Telemetry()
        srv = FlowServer(_DummyModel(), {}, _cfg(), telemetry=tel)
        try:
            assert srv.submit(_img(1), _img(2)).result(60).ok
        finally:
            srv.drain()
        g = tel.registry.get("serve_service_time_ema_ms")
        assert g is not None and g.value > 0


# ------------------------------------ server spans / report back-compat


# Pre-telemetry report() keys, pinned verbatim (acceptance criterion).
SERVE_REPORT_KEYS = {
    "stats", "budget", "budget_drops", "budget_recoveries",
    "executables", "precision", "mesh",
}
STREAM_REPORT_KEYS = {
    "stats", "capacity", "occupancy", "peak_occupancy", "mean_occupancy",
    "evicted", "executables", "precision", "mesh",
}


class TestServerTelemetry:
    def test_span_correlation_through_a_real_two_request_batch(self):
        """Two requests paused into ONE batch: the journey of each
        request is reassemblable from the ring — its own queue-wait plus
        the batch-level assembly/stage/dispatch/drain spans, all tied by
        one batch id, with mesh+policy fingerprints on the dispatch."""
        tel = Telemetry()
        srv = FlowServer(_DummyModel(), {}, _cfg(), telemetry=tel)
        try:
            srv.pause()
            h1 = srv.submit(_img(1), _img(2))
            h2 = srv.submit(_img(3), _img(4))
            srv.resume()
            assert h1.result(60).ok and h2.result(60).ok
        finally:
            srv.drain()
        disp = tel.tracer.records("serve_dispatch")
        assert len(disp) == 1
        assert sorted(disp[0]["attrs"]["request_ids"]) == [0, 1]
        assert disp[0]["attrs"]["policy"] == "f32"
        assert "mesh" in disp[0]["attrs"]
        batch_id = disp[0]["attrs"]["batch_id"]
        journey = {
            r["name"] for r in tel.tracer.for_attr(request_id=0)
        }
        assert {
            "serve_queue_wait", "serve_dispatch", "serve_drain",
        } <= journey
        # Batch-level stages share the batch correlation id.
        for name in ("serve_batch_assembly", "serve_pad_stage",
                     "serve_drain"):
            recs = tel.tracer.records(name)
            assert recs and recs[-1]["attrs"]["batch_id"] == batch_id
        # Queue-wait recorded once per request.
        assert tel.registry.histogram("serve_queue_wait_ms").count == 2
        # One sanctioned pull for the one batch.
        assert tel.counter_value("serve_drain_pulls_total") == 1

    def test_serve_report_backcompat_plus_stages(self):
        tel = Telemetry()
        srv = FlowServer(_DummyModel(), {}, _cfg(), telemetry=tel)
        try:
            assert srv.submit(_img(1), _img(2)).result(60).ok
            report = srv.report()
        finally:
            srv.drain()
        assert SERVE_REPORT_KEYS <= set(report)
        assert "stages" in report
        assert report["stages"]["serve_dispatch"]["count"] == 1
        assert report["stages"]["serve_dispatch"]["p50_ms"] is not None
        # stats summary still parses with the legacy fields.
        assert report["stats"].startswith("submitted=1 accepted=1 ")

    def test_stream_report_backcompat_plus_stages(self):
        tel = Telemetry()
        eng = StreamEngine(
            _DummyVideoModel(), {},
            StreamConfig(capacity=2, frame_hw=(24, 32), iters=1,
                         batch_sizes=(1, 2), queue_capacity=8),
            telemetry=tel,
        )
        try:
            assert eng.submit("s0", _img(1), _img(2)).result(60).ok
            report = eng.report()
        finally:
            eng.drain()
        assert STREAM_REPORT_KEYS <= set(report)
        assert report["stages"]["stream_dispatch"]["count"] == 1
        # Slot admission landed as a correlated lifecycle event.
        (admit,) = tel.tracer.records("stream_slot_admitted")
        assert admit["attrs"]["stream_id"] == "s0"
        assert tel.counter_value("stream_drain_pulls_total") == 1

    def test_disabled_telemetry_serves_identically(self):
        tel = Telemetry(enabled=False)
        srv = FlowServer(_DummyModel(), {}, _cfg(), telemetry=tel)
        try:
            r = srv.submit(_img(1), _img(2)).result(60)
        finally:
            stats = srv.drain()
        assert r.ok and stats.completed == 1
        assert tel.tracer.records() == []
        assert srv.report()["stages"] == {}


# --------------------------------------------------------- export layer


class TestExport:
    def test_jsonl_sink_is_bounded(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        with JsonlSink(path, max_events=5) as sink:
            written = [sink.write({"i": i}) for i in range(9)]
        assert written == [True] * 5 + [False] * 4
        lines = [
            json.loads(ln) for ln in open(path, encoding="utf-8")
        ]
        # 5 events + the closing record carrying the drop count.
        assert len(lines) == 6
        assert lines[-1] == {"name": "jsonl_sink_closed", "dropped": 4}

    def test_periodic_snapshot_writes_reports(self, tmp_path):
        path = str(tmp_path / "snap.jsonl")
        tel = Telemetry()
        tel.inc("serve_requests_submitted_total", 3)
        with JsonlSink(path) as sink:
            snap = PeriodicSnapshot(tel, sink, interval_s=0.05).start()
            time.sleep(0.12)
            snap.stop()
        lines = [
            json.loads(ln) for ln in open(path, encoding="utf-8")
        ]
        assert len(lines) >= 2  # >=1 periodic + the final stop() one
        rep = lines[-1]["report"]
        assert rep["metrics"]["counters"][
            "serve_requests_submitted_total"
        ] == 3

    def test_telemetry_report_shape(self):
        tel = Telemetry()
        tel.inc("c_total")
        with tel.span("stage_x"):
            pass
        rep = telemetry_report(tel)
        assert rep["enabled"] is True
        assert rep["metrics"]["counters"]["c_total"] == 1
        assert "stage_x" in rep["stages"]
        assert rep["spans_recorded"] == 1
        assert json.loads(json.dumps(rep)) == rep


# ------------------------------------------- the platform invariant


@pytest.fixture(scope="module")
def tiny_model():
    cfg = small_model_config("raft", dataset="chairs")
    model = RAFT(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 40, 48, 3))
    return model, variables


class TestTracingPreservesInvariants:
    def test_steady_state_sync_free_recompile_free_under_full_tracing(
        self, tiny_model, forbid_host_transfers, max_recompiles
    ):
        """The tentpole's hard constraint: with telemetry FULLY enabled
        (counters, spans, queue gauges all live), a warm steady-state
        serving window still performs ZERO implicit host pulls and ZERO
        compiles, and each batch still does exactly ONE sanctioned
        device_get — the observer adds bookkeeping, never a sync."""
        model, variables = tiny_model
        tel = Telemetry()
        cfg = _cfg(batch_sizes=(1,), iter_levels=(2, 1))
        srv = FlowServer(model, variables, cfg, telemetry=tel)
        try:
            srv.warmup((40, 48))
            warm = srv.submit(_img(30, (40, 48)), _img(31, (40, 48)))
            assert warm.result(120).ok
            pulls_before = tel.counter_value("serve_drain_pulls_total")
            with forbid_host_transfers() as stats, max_recompiles(0):
                handles = [
                    srv.submit(_img(40 + i, (40, 48)),
                               _img(50 + i, (40, 48)))
                    for i in range(3)
                ]
                rs = [h.result(120) for h in handles]
        finally:
            srv.drain()
        assert [r.status for r in rs] == [STATUS_OK] * 3
        assert stats.host_transfers == 0
        assert stats.sanctioned_gets == 3  # one per batch, as before
        # ...and tracing really was live through the guarded window:
        assert (
            tel.counter_value("serve_drain_pulls_total") - pulls_before
            == 3
        )
        assert tel.registry.histogram("serve_queue_wait_ms").count >= 3
        assert tel.tracer.records("serve_dispatch")


# -------------------------------------------- executable cache events


class TestExecutableCacheEvents:
    def test_compile_hit_evict_events_keyed_like_the_cache(self):
        from raft_ncup_tpu.inference.pipeline import ShapeCachedForward

        tel = Telemetry()
        fwd = ShapeCachedForward(
            _DummyModel(), {}, cache_size=1, telemetry=tel
        )
        calls = []
        fwd.custom(("k1",), lambda: calls.append("a") or (lambda: 1))
        fwd.custom(("k1",), lambda: calls.append("b") or (lambda: 2))
        fwd.custom(("k2",), lambda: calls.append("c") or (lambda: 3))
        assert calls == ["a", "c"]  # second k1 was a hit
        assert tel.counter_value(
            "inference_executable_compiles_total"
        ) == 2
        assert tel.counter_value("inference_executable_hits_total") == 1
        assert tel.counter_value(
            "inference_executable_evictions_total"
        ) == 1
        (compile1, compile2) = tel.tracer.records(
            "inference_executable_compile"
        )
        (evict,) = tel.tracer.records("inference_executable_evict")
        # Events carry the cache's own key (mesh fingerprint prefix
        # included) — "keyed like the cache".
        assert "k1" in compile1["attrs"]["key"]
        assert "k2" in compile2["attrs"]["key"]
        assert "k1" in evict["attrs"]["key"]
        assert fwd.stats == {"compiles": 2, "hits": 1, "evictions": 1}


# ------------------------------- guard + logger registry producers


class TestGuardAndLoggerMirrors:
    def test_guard_violation_lands_as_event(self):
        """GuardStats re-expressed over the registry: an intercepted
        implicit pull shows on the process-default hub's timeline."""
        from raft_ncup_tpu.analysis.guards import forbid_host_transfers
        from raft_ncup_tpu.observability import set_telemetry

        prev = set_telemetry(Telemetry())
        try:
            x = jnp.ones((2,))
            with forbid_host_transfers(raise_on_violation=False) as gs:
                float(x[0])  # the planted implicit pull
                jax.device_get(x)  # sanctioned
            from raft_ncup_tpu.observability import get_telemetry

            tel = get_telemetry()
            assert gs.host_transfers == 1
            assert tel.counter_value(
                "guard_host_transfer_violation_total"
            ) == 1
            (ev,) = tel.tracer.records("guard_host_transfer_violation")
            assert "jax.Array" in ev["attrs"]["desc"]
            assert tel.counter_value("guard_sanctioned_gets_total") >= 1
        finally:
            set_telemetry(prev)

    def test_logger_window_means_land_as_gauges(self, tmp_path):
        from raft_ncup_tpu.observability import set_telemetry
        from raft_ncup_tpu.training.logger import Logger

        prev = set_telemetry(Telemetry())
        try:
            log = Logger(str(tmp_path), sum_freq=2, use_tensorboard=False)
            log.push(0, {"loss": jnp.asarray(4.0)}, lr=1e-4)
            log.push(1, {"loss": jnp.asarray(2.0)}, lr=1e-4)
            log.close()
            from raft_ncup_tpu.observability import get_telemetry

            reg = get_telemetry().registry
            assert reg.get("train_loss").value == 3.0  # window mean
            assert reg.get("train_lr").value == pytest.approx(1e-4)
            assert reg.get("train_steps_per_sec").value > 0
        finally:
            set_telemetry(prev)
