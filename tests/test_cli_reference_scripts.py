"""Drop-in CLI compatibility with the reference's shipped launch scripts.

Extracts the exact flag lines from the reference's own shell scripts
(reference: train_raft_nc_{things,sintel,kitti}.sh,
eval_raft_nc_{sintel,kitti}.sh) and feeds them to this framework's
parsers — a user must be able to reuse their launch scripts verbatim
(modulo dataset staging). Pinned here rather than hand-copied so drift
in either direction fails the suite.
"""

import os
import shlex

import pytest

from raft_ncup_tpu.cli import parse_eval, parse_train

_REF = "/root/reference"

# These tests parse the reference repo's OWN shell scripts, so they can
# only run where that read-only checkout is mounted. Without the skip,
# every container that lacks /root/reference turned the 6 tests into
# perpetual tier-1 failures — environmental noise that buried real
# regressions. The reason is loud on purpose: a skip here means "this
# host can't check script-compat", never "script-compat is fine".
pytestmark = [
    pytest.mark.reference,
    pytest.mark.skipif(
        not os.path.isdir(_REF),
        reason=(
            f"reference checkout {_REF} is not mounted on this host — "
            "CLI script-compat is UNVERIFIED here, not passing; run on a "
            "host with the reference repo to exercise these pins"
        ),
    ),
]


def _extract_args(script: str, driver: str) -> list[str]:
    """Flags of the `python <driver> ...` invocation, continuation lines
    joined, `$VAR`s substituted with placeholders."""
    path = os.path.join(_REF, script)
    with open(path) as f:
        text = f.read()
    # Join "\"-continued lines, find the python invocation.
    joined = text.replace("\\\n", " ")
    for line in joined.splitlines():
        line = line.strip()
        if line.startswith("python") and driver in line:
            toks = shlex.split(line)
            toks = [t.replace("$EXP", "exp") for t in toks]
            i = toks.index(driver)
            return toks[i + 1 :]
    raise AssertionError(f"no `python {driver}` line in {script}")


@pytest.mark.parametrize(
    "script",
    [
        "train_raft_nc_things.sh",
        "train_raft_nc_sintel.sh",
        "train_raft_nc_kitti.sh",
    ],
)
def test_reference_train_scripts_parse(script):
    argv = _extract_args(script, "train.py")
    args, model_cfg, train_cfg, data_cfg = parse_train(argv)
    # The NCUP configuration every script pins (reference:
    # train_raft_nc_things.sh:31-50).
    ups = model_cfg.upsampler
    assert model_cfg.variant == "raft_nc_dbl"
    assert ups.kind == "nconv" and ups.scale == 4
    assert ups.channels_multiplier == 2 and ups.num_downsampling == 1
    assert ups.encoder_filter_sz == 5 and ups.decoder_filter_sz == 3
    assert ups.shared_encoder and not ups.use_bias
    assert ups.weights_est_net == "simple"
    assert ups.weights_est_num_ch == (64, 32)
    assert ups.weights_est_filter_sz == (3, 3, 1)
    assert train_cfg.batch_size == 6
    assert train_cfg.optimizer == "adamw"  # script says 'adamW'
    assert train_cfg.scheduler == "cyclic"


def test_things_script_hyperparameters():
    argv = _extract_args("train_raft_nc_things.sh", "train.py")
    _, model_cfg, train_cfg, data_cfg = parse_train(argv)
    assert train_cfg.stage == "things"
    assert train_cfg.num_steps == 100_000
    assert train_cfg.lr == 0.000125
    assert train_cfg.image_size == (400, 720)
    assert train_cfg.validation == ("sintel",)
    assert data_cfg.compressed_ft
    assert train_cfg.load_pretrained == "models/raft-things.pth"


@pytest.mark.parametrize(
    "script,dataset",
    [
        ("eval_raft_nc_sintel.sh", "sintel"),
        ("eval_raft_nc_kitti.sh", "kitti"),
    ],
)
def test_reference_eval_scripts_parse(script, dataset):
    argv = _extract_args(script, "evaluate.py")
    args, model_cfg, data_cfg = parse_eval(argv)
    assert args.dataset == dataset
    assert model_cfg.variant == "raft_nc_dbl"
    assert model_cfg.upsampler.kind == "nconv"
    # BatchNorm-in-weights-net rule: ON for sintel, OFF otherwise
    # (reference: core/upsampler.py:41-46).
    assert model_cfg.dataset == dataset
