"""Fast-tier autoscaler matrix (fleet/autoscaler.py; docs/FLEET.md
"Autoscaler").

No jax, no processes, no sleeps: a fake supervisor (handle objects),
a fake router (counters), and an injected clock make ``tick()`` fully
deterministic — every test asserts the EXACT decision trajectory, not
a property of it. The chaos tier (tests/test_fleet.py) proves the same
loop against real replica processes; this tier proves the decisions.
"""

import pytest

from raft_ncup_tpu.fleet import FleetAutoscaler, FleetConfig
from raft_ncup_tpu.fleet.replica import BROKEN, DRAINING, SPAWNING, UP
from raft_ncup_tpu.observability import Telemetry


class _Handle:
    def __init__(self, index, state=UP, healthz=None):
        self.index = index
        self.state = state
        self.circuit_open = False
        self.last_healthz = healthz if healthz is not None else {
            "overall": "ready"
        }


class _FakeSup:
    """Replica handles without processes; spawn/drain mutate the list
    the way the real supervisor's add/remove do."""

    def __init__(self, indices):
        self.replicas = [_Handle(i) for i in indices]

    def handle(self, i):
        for h in self.replicas:
            if h.index == i:
                return h
        return None

    def spawn(self, i):
        self.replicas.append(_Handle(i, state=SPAWNING))

    def drain(self, i):
        self.replicas = [h for h in self.replicas if h.index != i]


class _FakeRouter:
    def __init__(self):
        self.stats = {"shed": 0}
        self.inflight = {}
        self.scale_eta = None
        self.eta_log = []

    def inflight_of(self, i):
        return self.inflight.get(i, 0)

    def set_scale_eta(self, eta_s):
        self.scale_eta = eta_s
        self.eta_log.append(eta_s)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _scaler(tmp_path, sup, router, clock, **cfg_kw):
    kw = dict(
        n_replicas=1, min_replicas=1, max_replicas=3,
        scale_hysteresis_ticks=2, scale_cooldown_s=5.0,
        scale_fail_budget=2, scale_eta_prior_s=20.0,
        max_inflight_per_replica=4,
    )
    kw.update(cfg_kw)
    cfg = FleetConfig(base_dir=str(tmp_path), **kw)
    return cfg, FleetAutoscaler(
        cfg, sup, router, telemetry=Telemetry(), clock=clock,
        spawn_fn=sup.spawn, drain_fn=sup.drain,
    )


def _trajectory(scaler, clock, n, dt=1.0):
    out = []
    for _ in range(n):
        clock.t += dt
        out.append(scaler.tick())
    return out


class TestScaleUp:
    def test_saturation_trajectory_is_exact(self, tmp_path):
        """Hysteresis holds, then ONE spawn, then in-flight blocks —
        the exact sequence, not a property of it."""
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        router.inflight[0] = 4  # occupancy 1.0 >= 0.8
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        recs = _trajectory(sc, clock, 4)
        assert [r["decision"] for r in recs] == [
            "hold", "up", "hold", "hold",
        ]
        assert recs[0]["reason"] == "hysteresis 1/2"
        assert recs[1]["reason"].startswith("spawned slot 1")
        assert all(
            r["reason"].startswith("topology change in flight")
            for r in recs[2:]
        )
        assert sc.scale_ups == 1
        assert sup.handle(1).state == SPAWNING

    def test_settle_observes_time_to_ready(self, tmp_path):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        router.inflight[0] = 4
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        _trajectory(sc, clock, 2)  # hold, up @ t=2
        sup.handle(1).state = UP   # READY after 3 more ticks
        clock.t = 5.0
        rec = sc.tick()
        assert sc.scale_ups_completed == 1
        # First real observation REPLACES the 20s prior (3s spawn→READY).
        assert sc.time_to_ready_s() == pytest.approx(3.0)
        assert sc.report()["time_to_ready_observed"] == 1
        # The settled tick can decide again (no phantom pending).
        assert "in flight" not in rec["reason"]

    def test_ttr_ewma_tracks_later_observations(self, tmp_path):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        router.inflight[0] = 4
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        _trajectory(sc, clock, 2)
        sup.handle(1).state = UP
        clock.t = 4.0  # 2s observed
        sc.tick()
        # Second scale-up: cooldown expires at t=7; hysteresis rebuilt.
        router.inflight[1] = 4
        _trajectory(sc, clock, 4)  # t=5..8: streak, spawn slot 2
        assert sup.handle(2) is not None
        sup.handle(2).state = UP
        t_spawn = [r for r in sc.decisions if r["decision"] == "up"][-1]["t"]
        clock.t = t_spawn + 6.0  # 6s observed
        sc.tick()
        assert sc.time_to_ready_s() == pytest.approx(
            0.5 * 2.0 + 0.5 * 6.0
        )

    def test_at_max_replicas_holds_with_reason(self, tmp_path):
        sup, router, clock = _FakeSup([0, 1, 2]), _FakeRouter(), _Clock()
        for i in range(3):
            router.inflight[i] = 4
        cfg, sc = _scaler(tmp_path, sup, router, clock, n_replicas=3)
        recs = _trajectory(sc, clock, 3)
        assert [r["decision"] for r in recs] == ["hold"] * 3
        assert recs[-1]["reason"] == "at max_replicas (3)"
        assert sc.scale_ups == 0

    def test_paging_and_shed_delta_trigger_without_occupancy(
        self, tmp_path
    ):
        """Pressure is paging OR occupancy OR a fresh shed — an SLO
        burn page at 10% occupancy must still scale."""
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        sup.handle(0).last_healthz = {
            "overall": "ready",
            "slo": {"paging": ["availability"],
                    "verdicts": {"availability": {"burn_fast": 14.4}}},
        }
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        recs = _trajectory(sc, clock, 2)
        assert recs[1]["decision"] == "up"
        assert recs[1]["paging"] == ["availability"]
        assert recs[1]["burn_fast"] == pytest.approx(14.4)
        # Fresh fleet, shed counter moving: same verdict.
        sup2, router2, clock2 = _FakeSup([0]), _FakeRouter(), _Clock()
        cfg2, sc2 = _scaler(tmp_path, sup2, router2, clock2)
        for _ in range(2):
            router2.stats["shed"] += 3
            clock2.t += 1.0
            rec = sc2.tick()
            assert rec["shed_delta"] == 3
        assert rec["decision"] == "up"


class TestScaleDown:
    def _calm_fleet(self, tmp_path, n=2):
        sup, router, clock = _FakeSup(list(range(n))), _FakeRouter(), _Clock()
        cfg, sc = _scaler(tmp_path, sup, router, clock, n_replicas=n)
        return sup, router, clock, cfg, sc

    def test_calm_trajectory_drains_exactly_one(self, tmp_path):
        sup, router, clock, cfg, sc = self._calm_fleet(tmp_path)
        recs = _trajectory(sc, clock, 4)
        assert [r["decision"] for r in recs] == [
            "hold", "down", "hold", "hold",
        ]
        # Drain completed instantly (fake), so later holds are steady
        # "at min_replicas", not in-flight blocks.
        assert recs[2]["reason"] == "at min_replicas (1)"
        assert sc.scale_downs == 1
        assert [h.index for h in sup.replicas] == [0]

    def test_victim_is_least_loaded_ties_retire_newest(self, tmp_path):
        sup, router, clock, cfg, sc = self._calm_fleet(tmp_path, n=3)
        router.inflight = {0: 1, 1: 0, 2: 0}  # occ 1/12 <= 0.25
        _trajectory(sc, clock, 2)
        # 1 and 2 tie on load; the NEWEST slot retires so the stable
        # low-index replicas keep their warm streams sticky.
        assert [h.index for h in sup.replicas] == [0, 1]

    def test_min_replicas_floor_holds(self, tmp_path):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        recs = _trajectory(sc, clock, 3)
        assert [r["decision"] for r in recs] == ["hold"] * 3
        assert recs[-1]["reason"] == "at min_replicas (1)"

    def test_draining_replica_not_counted_up(self, tmp_path):
        sup, router, clock, cfg, sc = self._calm_fleet(tmp_path)
        sup.handle(1).state = DRAINING
        rec = sc.tick()
        assert rec["n_up"] == 1
        assert rec["n_draining"] == 1
        assert rec["reason"] == "at min_replicas (1)"


class TestAntiFlap:
    def test_oscillating_signal_never_scales(self, tmp_path):
        """The flap scenario: load alternating sat/idle each tick —
        period shorter than hysteresis — must produce zero topology
        changes, ever."""
        sup, router, clock = _FakeSup([0, 1]), _FakeRouter(), _Clock()
        cfg, sc = _scaler(tmp_path, sup, router, clock, n_replicas=2)
        for k in range(12):
            load = 4 if k % 2 == 0 else 0
            router.inflight = {0: load, 1: load}
            clock.t += 1.0
            rec = sc.tick()
            assert rec["decision"] == "hold", rec
        assert sc.scale_ups == 0 and sc.scale_downs == 0

    def test_cooldown_blocks_consecutive_scale_ups(self, tmp_path):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        router.inflight[0] = 4
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        _trajectory(sc, clock, 2)       # up @ t=2
        sup.handle(1).state = UP        # settles immediately
        router.inflight[1] = 4          # still saturated
        recs = _trajectory(sc, clock, 4)  # t=3..6 < cooldown end (t=7)
        assert [r["decision"] for r in recs] == ["hold"] * 4
        assert recs[-1]["reason"] == "cooldown"
        recs = _trajectory(sc, clock, 1)  # t=7: cooldown satisfied
        assert recs[0]["decision"] == "up"

    def test_mid_band_occupancy_resets_both_streaks(self, tmp_path):
        """Between the thresholds is a healthy steady state: one
        mid-band tick must erase accumulated evidence in BOTH
        directions."""
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        cfg, sc = _scaler(tmp_path, sup, router, clock,
                          scale_hysteresis_ticks=2)
        router.inflight[0] = 4          # pressure: streak 1
        _trajectory(sc, clock, 1)
        router.inflight[0] = 2          # 0.5: neither
        rec = _trajectory(sc, clock, 1)[0]
        assert rec["reason"] == "steady"
        router.inflight[0] = 4          # pressure again: streak restarts
        rec = _trajectory(sc, clock, 1)[0]
        assert rec["reason"] == "hysteresis 1/2"


class TestFailBudgetBreaker:
    def test_breaker_opens_after_budget_and_blocks_spawns(
        self, tmp_path
    ):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        router.inflight[0] = 4
        cfg, sc = _scaler(tmp_path, sup, router, clock,
                          scale_cooldown_s=0.001)
        fails = 0
        while not sc.breaker_open:
            clock.t += 1.0
            rec = sc.tick()
            if rec["decision"] == "up":
                # The spawned replica breaks before ever reaching READY.
                spawned = [h for h in sup.replicas
                           if h.state == SPAWNING]
                spawned[0].state = BROKEN
                fails += 1
            assert fails <= cfg.scale_fail_budget + 1
        assert sc.failed_scale_ups == cfg.scale_fail_budget == 2
        clock.t += 1.0
        rec = sc.tick()
        assert rec["decision"] == "hold"
        assert rec["reason"].startswith("breaker open after 2 failed")
        assert "respawn storm bounded" in rec["reason"]
        before = sc.scale_ups
        _trajectory(sc, clock, 3)
        assert sc.scale_ups == before

    def test_successful_scale_up_resets_fail_streak(self, tmp_path):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        router.inflight[0] = 4
        cfg, sc = _scaler(tmp_path, sup, router, clock,
                          scale_cooldown_s=0.001)
        _trajectory(sc, clock, 2)  # up: slot 1
        sup.handle(1).state = BROKEN  # fail #1
        _trajectory(sc, clock, 1)     # settle; streak rebuilds
        sup.drain(1)
        _trajectory(sc, clock, 2)     # up again: slot 1
        sup.handle(1).state = UP      # SUCCESS — streak must reset
        _trajectory(sc, clock, 1)
        assert sc.failed_scale_ups == 1
        assert not sc.breaker_open
        router.inflight = {0: 4, 1: 4}
        _trajectory(sc, clock, 2)     # next up still allowed
        assert sc.scale_ups == 3


class TestEtaPublication:
    def test_eta_floors_sheds_while_warming_and_clears_calm(
        self, tmp_path
    ):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        router.inflight[0] = 4
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        _trajectory(sc, clock, 1)
        # Pressure (even pre-decision): the ETA is already honest.
        assert router.scale_eta == pytest.approx(20.0)
        _trajectory(sc, clock, 2)  # up + warming
        assert router.scale_eta == pytest.approx(20.0)
        sup.handle(1).state = UP
        router.inflight = {0: 0, 1: 0}  # calm
        clock.t += 1.0
        sc.tick()
        assert router.scale_eta is None

    def test_stop_clears_a_published_eta(self, tmp_path):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        router.inflight[0] = 4
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        _trajectory(sc, clock, 1)
        assert router.scale_eta is not None
        sc.stop()  # no thread running: stop is still the eta janitor
        assert router.scale_eta is None


class TestSignalsAndReport:
    def test_empty_fleet_reads_as_saturated(self, tmp_path):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        sup.handle(0).state = BROKEN
        s = sc.signals()
        assert s["n_up"] == 0
        assert s["occupancy"] == 1.0  # pressure, not 0% busy

    def test_circuit_open_handle_is_not_capacity(self, tmp_path):
        sup, router, clock = _FakeSup([0, 1]), _FakeRouter(), _Clock()
        sup.handle(1).circuit_open = True
        cfg, sc = _scaler(tmp_path, sup, router, clock, n_replicas=2)
        s = sc.signals()
        assert s["up_indices"] == [0]

    def test_report_shape_and_decision_log(self, tmp_path):
        sup, router, clock = _FakeSup([0]), _FakeRouter(), _Clock()
        cfg, sc = _scaler(tmp_path, sup, router, clock)
        _trajectory(sc, clock, 3)
        rep = sc.report()
        assert rep["ticks"] == 3
        for key in ("scale_ups", "scale_ups_completed", "scale_downs",
                    "failed_scale_ups", "breaker_open",
                    "time_to_ready_s", "time_to_ready_observed"):
            assert key in rep
        for rec in sc.decisions:
            for key in ("t", "decision", "reason", "occupancy",
                        "eta_published", "breaker_open"):
                assert key in rec

    def test_background_loop_ticks_and_stops(self, tmp_path):
        import time as _time

        sup, router = _FakeSup([0]), _FakeRouter()
        cfg, sc = _scaler(tmp_path, sup, router, _time.monotonic)
        with sc.start(interval_s=0.01):
            deadline = _time.monotonic() + 5.0
            while len(sc.decisions) < 3 and _time.monotonic() < deadline:
                _time.sleep(0.01)
        assert len(sc.decisions) >= 3
        assert router.scale_eta is None  # stop() cleared it
