"""Async training pipeline: DevicePrefetcher + non-blocking Logger.

Pins the contracts the asynchronous train loop relies on:

- the prefetcher is a pure pipeline stage — loader order and batch
  contents come through untouched, worker errors surface at ``next()``,
  and shutdown mid-stream closes the wrapped generator;
- training through the prefetcher is BITWISE identical to the serial
  host→device path (the overlap is free — no numerics drift);
- ``Logger.push`` performs ZERO host transfers between ``sum_freq``
  boundaries (counted by instrumenting ``jax.device_get`` and the pushed
  values' ``__float__``).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import TrainConfig, small_model_config
from raft_ncup_tpu.data import DevicePrefetcher, FlowLoader, SyntheticFlowDataset
from raft_ncup_tpu.parallel import device_put_batch, make_mesh, make_train_step
from raft_ncup_tpu.parallel.mesh import batch_sharding
from raft_ncup_tpu.training.logger import Logger
from raft_ncup_tpu.training.state import create_train_state


def _host_batches(n, B=2, H=16, W=24, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "image1": rng.integers(0, 255, (B, H, W, 3)).astype(np.uint8),
            "image2": rng.integers(0, 255, (B, H, W, 3)).astype(np.uint8),
            "flow": rng.standard_normal((B, H, W, 2)).astype(np.float32),
            "valid": np.ones((B, H, W), np.float32),
            "extra_info": [("frame", i)],
        }
        for i in range(n)
    ]


class TestDevicePrefetcher:
    def test_preserves_order_and_contents(self):
        batches = _host_batches(6)
        with DevicePrefetcher(iter(batches), depth=2) as pf:
            out = list(pf)
        assert len(out) == len(batches)
        for got, want in zip(out, batches):
            assert "extra_info" not in got  # metadata dropped pre-transfer
            assert set(got) == {"image1", "image2", "flow", "valid"}
            for k in got:
                assert isinstance(got[k], jax.Array)
                assert got[k].dtype == want[k].dtype
                np.testing.assert_array_equal(np.asarray(got[k]), want[k])

    def test_matches_flowloader_stream(self):
        """Prefetching a FlowLoader stream yields the loader's own batches
        in the loader's own order (determinism per (seed, epoch, index))."""
        ds = SyntheticFlowDataset((16, 24), length=8, seed=3)

        def fresh_stream():
            return FlowLoader(
                ds, batch_size=2, seed=11, num_workers=2,
                shard_index=0, num_shards=1,
            ).batches()

        direct = fresh_stream()
        want = [next(direct) for _ in range(6)]
        direct.close()

        with DevicePrefetcher(fresh_stream(), depth=3) as pf:
            got = [next(pf) for _ in range(6)]
        for g, w in zip(got, want):
            w.pop("extra_info", None)
            assert set(g) == set(w)
            for k in g:
                np.testing.assert_array_equal(np.asarray(g[k]), w[k])

    def test_propagates_worker_exception(self):
        def stream():
            yield _host_batches(1)[0]
            raise RuntimeError("decode failed")

        pf = DevicePrefetcher(stream(), depth=2)
        next(pf)
        with pytest.raises(RuntimeError, match="decode failed"):
            next(pf)
        # After the raise the prefetcher is shut down, not wedged.
        assert not pf._thread.is_alive()

    def test_close_mid_stream_closes_generator(self):
        closed = threading.Event()

        def infinite():
            try:
                while True:
                    yield _host_batches(1)[0]
            finally:
                closed.set()

        pf = DevicePrefetcher(infinite(), depth=2)
        next(pf)
        next(pf)
        pf.close()
        assert closed.wait(timeout=5.0), "wrapped generator never closed"
        assert not pf._thread.is_alive()
        pf.close()  # idempotent
        with pytest.raises(StopIteration):
            next(pf)

    def test_close_unblocks_stalled_worker(self):
        """A consumer that stops pulling leaves the worker blocked on a
        full queue; close() must still stop and join it."""
        pf = DevicePrefetcher(iter(_host_batches(50)), depth=1)
        next(pf)
        time.sleep(0.2)  # let the worker fill the queue and block on put
        pf.close()
        assert not pf._thread.is_alive()

    def test_exhaustion_raises_stop_iteration(self):
        pf = DevicePrefetcher(iter(_host_batches(2)), depth=4)
        assert len(list(pf)) == 2
        with pytest.raises(StopIteration):
            next(pf)

    def test_rejects_zero_depth(self):
        with pytest.raises(ValueError):
            DevicePrefetcher(iter([]), depth=0)


class TestDevicePutBatch:
    def test_mesh_shardings_apply_single_process(self):
        mesh = make_mesh(data=4, spatial=2)
        shardings = batch_sharding(mesh)
        batch = {k: v for k, v in _host_batches(1, B=4, H=16, W=16)[0].items()
                 if k != "extra_info"}
        out = device_put_batch(batch, mesh, shardings)
        for k, v in out.items():
            assert v.sharding == shardings[k], k
            np.testing.assert_array_equal(np.asarray(v), batch[k])

    def test_no_shardings_default_placement(self):
        batch = {"a": np.arange(6, dtype=np.float32)}
        out = device_put_batch(batch, None, None)
        assert isinstance(out["a"], jax.Array)
        np.testing.assert_array_equal(np.asarray(out["a"]), batch["a"])


def test_loss_trajectory_bitwise_identical_with_prefetch():
    """>=3 steps: the async pipeline (device prefetch + device-accumulated
    metrics, no per-step host sync) reproduces the serial path's losses
    BIT FOR BIT — same executable, same inputs, no numerics drift."""
    B, H, W = 2, 16, 24
    mcfg = small_model_config(variant="raft")
    tcfg = TrainConfig(
        stage="chairs", lr=1e-4, num_steps=50, batch_size=B,
        image_size=(H, W), iters=2,
    )
    model, _ = create_train_state(jax.random.key(0), mcfg, tcfg)
    step = make_train_step(model, tcfg)  # one jit: both runs share it
    batches = _host_batches(4, B=B, H=H, W=W, seed=42)
    rngs = [jax.random.key(100 + i) for i in range(len(batches))]

    def fresh_state():
        _, state = create_train_state(jax.random.key(0), mcfg, tcfg)
        return state

    # Serial path: per-step host transfer + per-step float() sync.
    state = fresh_state()
    serial_losses = []
    for batch, rng in zip(batches, rngs):
        host = {k: v for k, v in batch.items() if k != "extra_info"}
        state, metrics = step(state, {k: jnp.asarray(v) for k, v in host.items()}, rng)
        serial_losses.append(float(metrics["loss"]))

    # Async path: prefetcher feeds device batches, losses stay on device
    # until one device_get at the end.
    state = fresh_state()
    async_losses = []
    with DevicePrefetcher(iter(batches), depth=2) as pf:
        for rng in rngs:
            state, metrics = step(state, next(pf), rng)
            async_losses.append(metrics["loss"])
    async_losses = [float(v) for v in jax.device_get(async_losses)]

    assert async_losses == serial_losses  # bitwise, not allclose


class _CountingScalar:
    """Device-scalar stand-in that counts host conversions."""

    floats = 0

    def __init__(self, v):
        self.v = v

    def __add__(self, other):
        return _CountingScalar(self.v + getattr(other, "v", other))

    __radd__ = __add__

    def __float__(self):
        _CountingScalar.floats += 1
        return float(self.v)


def test_logger_push_no_host_transfer_between_boundaries(tmp_path, monkeypatch):
    """Zero jax.device_get and zero float() between sum_freq boundaries;
    exactly one device_get at the boundary."""
    import raft_ncup_tpu.training.logger as logger_mod

    calls = {"device_get": 0}

    def counting_device_get(tree):
        calls["device_get"] += 1
        return tree  # pass-through keeps _CountingScalar leaves intact

    monkeypatch.setattr(logger_mod.jax, "device_get", counting_device_get)
    _CountingScalar.floats = 0

    log = Logger(str(tmp_path), sum_freq=4, use_tensorboard=False)
    for s in range(3):
        log.push(s, {"loss": _CountingScalar(float(s)),
                     "epe": _CountingScalar(2.0 * s)}, lr=1e-4)
    assert calls["device_get"] == 0
    assert _CountingScalar.floats == 0  # no per-push host sync

    log.push(3, {"loss": _CountingScalar(3.0), "epe": _CountingScalar(6.0)},
             lr=1e-4)
    assert calls["device_get"] == 1  # ONE pull for the whole window
    log.close()
    text = (tmp_path / "log.txt").read_text()
    assert "loss 1.5000" in text and "epe 3.0000" in text

    # The next window starts clean: accumulators were reset.
    assert log._acc == {} and log._acc_n == 0


def test_logger_push_device_arrays_end_to_end(tmp_path):
    """With real jax scalars the accumulated means are correct."""
    log = Logger(str(tmp_path), sum_freq=3, use_tensorboard=False)
    for s in range(3):
        log.push(s, {"loss": jnp.float32(s + 1)})
    log.close()
    assert "loss 2.0000" in (tmp_path / "log.txt").read_text()
