"""Child program for the REAL multi-process jax.distributed test.

Each of N processes runs this file with two virtual CPU devices, joins
the distributed runtime through ``initialize_distributed`` (the
non-trivial branch of parallel/multihost.py), assembles its host-local
slice of a global batch, and executes ONE sharded train step over the
2N-device global mesh. Prints ``LOSS=<value>`` on success; the parent
test asserts all processes exit 0 and agree on the loss.

Then the multi-host output-hygiene matrix (VERDICT r4 #4, scaled to 4
processes per VERDICT r5 weak #5):

- host-sharded validation (``_HostShard``): every process computes its
  round-robin slice of the held-out frames and prints the GLOBAL frame
  indices it actually decoded (``VALIDATED=[...]``) — the parent
  asserts the union covers every frame exactly once;
- the one-writer-per-pod submission path: every process calls
  ``create_sintel_submission`` against a shared tmpdir (with the Sintel
  dataset stubbed by a tiny synthetic sequence) and prints how many
  .flo files it wrote (``SUBWRITES=n``) — the parent asserts exactly
  one process wrote, and that each expected file exists;
- Logger hygiene: one log.txt writer (``LOGACTIVE=0|1``).

Not a pytest file — invoked by tests/test_multihost.py.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    port, pid, run_dir, nprocs = (
        sys.argv[1], int(sys.argv[2]), sys.argv[3], int(sys.argv[4])
    )
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()

    from raft_ncup_tpu.utils.runtime import (
        enable_compilation_cache,
        force_platform,
    )

    force_platform("cpu")
    enable_compilation_cache()  # repeat suite runs hit warm executables

    import jax
    import numpy as np

    from raft_ncup_tpu.config import TrainConfig, small_model_config
    from raft_ncup_tpu.parallel import (
        batch_sharding,
        global_batch,
        initialize_distributed,
        is_multihost,
        make_mesh,
        make_train_step,
    )
    from raft_ncup_tpu.parallel.mesh import replicated
    from raft_ncup_tpu.training.state import create_train_state

    initialize_distributed(f"127.0.0.1:{port}", nprocs, pid)
    assert jax.process_count() == nprocs, jax.process_count()
    assert is_multihost()
    assert len(jax.devices()) == 2 * nprocs  # 2 local CPU devices per host

    mesh = make_mesh(data=2 * nprocs, spatial=1)
    mcfg = small_model_config("raft", dataset="chairs")
    tcfg = TrainConfig(
        stage="chairs", batch_size=2 * nprocs, image_size=(16, 32),
        iters=1, num_steps=5,
    )
    # Same seed on every process -> identical replicated init (SPMD).
    model, state = create_train_state(
        jax.random.PRNGKey(0), mcfg, tcfg, (1, 16, 32, 3)
    )
    repl = replicated(mesh)
    state = jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            repl, np.asarray(x)
        ),
        state,
    )

    # Each host contributes its disjoint rows of the global batch
    # (rows [2*pid, 2*pid+2)) — the FlowLoader host-sharding contract.
    g = np.random.default_rng(42)
    nb = 2 * nprocs
    full = {
        "image1": g.uniform(0, 255, (nb, 16, 32, 3)).astype(np.float32),
        "image2": g.uniform(0, 255, (nb, 16, 32, 3)).astype(np.float32),
        "flow": g.normal(size=(nb, 16, 32, 2)).astype(np.float32),
        "valid": np.ones((nb, 16, 32), np.float32),
    }
    local = {k: v[2 * pid : 2 * pid + 2] for k, v in full.items()}
    batch = global_batch(local, mesh, batch_sharding(mesh))

    # AOT-compile (pure local work, arbitrary cross-process skew allowed
    # — on a loaded host the children's compiles can drift apart by
    # minutes), then BARRIER before executing. The execution is where
    # every cross-process wait with a short hard deadline lives (Gloo
    # context init: 30s; collective op waits), so all processes must
    # enter it near-simultaneously — an unaligned entry was the
    # observed CI flake.
    from raft_ncup_tpu.parallel import barrier

    step = make_train_step(model, tcfg, mesh=mesh)
    rng = jax.random.PRNGKey(7)
    compiled = step.lower(state, batch, rng).compile()
    barrier("step-compiled")

    state, metrics = compiled(state, batch, rng)
    loss = float(metrics["loss"])
    assert np.isfinite(loss)
    print(f"LOSS={loss:.6f}", flush=True)

    # --- host-sharded validation: each frame exactly once -------------
    # Record the GLOBAL indices this process actually decodes. The
    # validator builds its own dataset, so the class method is patched
    # (the _HostShard view maps shard-local -> global before sampling).
    import raft_ncup_tpu.data.synthetic as synth_mod

    from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
    from raft_ncup_tpu.evaluation import (
        _shard_for_validation,
        validate_synthetic,
    )
    from raft_ncup_tpu.parallel.multihost import is_main_process

    n_frames = 6  # over 4 hosts: shard lengths [2, 2, 1, 1]
    shard, n_agreed, do_reduce = _shard_for_validation(
        SyntheticFlowDataset((32, 48), length=n_frames, seed=999),
        mesh=None,
    )
    expect_len = (n_frames - pid + nprocs - 1) // nprocs
    assert (len(shard), n_agreed, do_reduce) == (expect_len, n_frames, True)

    sampled: list = []
    orig_sample = synth_mod.SyntheticFlowDataset.sample

    def recording_sample(self, index, rng=None):
        sampled.append(int(index))
        return orig_sample(self, index, rng)

    synth_mod.SyntheticFlowDataset.sample = recording_sample
    variables = {"params": jax.tree.map(np.asarray, state.params)}
    barrier("pre-validate")  # realign before the collective reduction
    out = validate_synthetic(
        model, variables, iters=1, batch_size=2, size_hw=(32, 48),
        length=n_frames,
    )
    synth_mod.SyntheticFlowDataset.sample = orig_sample
    print(f"VAL={json.dumps(out, sort_keys=True)}", flush=True)
    print(f"VALIDATED={json.dumps(sorted(sampled))}", flush=True)

    # --- one-writer-per-pod submission into the shared tmpdir ---------
    # Sintel is stubbed with a tiny synthetic two-sequence video; the
    # REAL create_sintel_submission runs (warm start included, so the
    # device splat executes multi-process too). Host-local forwards +
    # no mesh => non-main processes must skip compute AND writes.
    import raft_ncup_tpu.evaluation as eval_mod

    class _FakeSintel:
        def __init__(self, *a, **kw):
            self._ds = SyntheticFlowDataset((32, 48), length=4, seed=55)

        def __len__(self):
            return 4

        def sample(self, i, rng=None):
            s = self._ds.sample(i)
            s["extra_info"] = (f"seq{i // 2}", i % 2)
            return s

    writes: list = []
    orig_mpisintel = eval_mod.ds_mod.MpiSintel
    orig_write_flo = eval_mod.write_flo

    def counting_write_flo(path, flow):
        writes.append(path)
        return orig_write_flo(path, flow)

    eval_mod.ds_mod.MpiSintel = _FakeSintel
    eval_mod.write_flo = counting_write_flo
    try:
        eval_mod.create_sintel_submission(
            model, variables, iters=1, warm_start=True,
            output_path=os.path.join(run_dir, "submission"),
        )
    finally:
        eval_mod.ds_mod.MpiSintel = orig_mpisintel
        eval_mod.write_flo = orig_write_flo
    print(f"SUBWRITES={len(writes)}", flush=True)

    # --- Logger hygiene: one log.txt writer ---------------------------
    from raft_ncup_tpu.training.logger import Logger

    logger = Logger(
        run_dir, sum_freq=1, use_tensorboard=False,
        active=is_main_process(),
    )
    logger.write_text(f"hello from process {pid}")
    logger.close()
    print(f"LOGACTIVE={int(logger.active)}", flush=True)


if __name__ == "__main__":
    main()
