"""Child program for the REAL 2-process jax.distributed test.

Each of two processes runs this file with 2 virtual CPU devices, joins
the distributed runtime through ``initialize_distributed`` (the
non-trivial branch of parallel/multihost.py), assembles its host-local
half of a global batch, and executes ONE sharded train step over the
4-device global mesh. Prints ``LOSS=<value>`` on success; the parent
test asserts both processes exit 0 and agree on the loss.

Not a pytest file — invoked by tests/test_multihost.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    port, pid = sys.argv[1], int(sys.argv[2])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()

    from raft_ncup_tpu.utils.runtime import (
        enable_compilation_cache,
        force_platform,
    )

    force_platform("cpu")
    enable_compilation_cache()  # repeat suite runs hit warm executables

    import jax
    import numpy as np

    from raft_ncup_tpu.config import TrainConfig, small_model_config
    from raft_ncup_tpu.parallel import (
        batch_sharding,
        global_batch,
        initialize_distributed,
        is_multihost,
        make_mesh,
        make_train_step,
    )
    from raft_ncup_tpu.parallel.mesh import replicated
    from raft_ncup_tpu.training.state import create_train_state

    initialize_distributed(f"127.0.0.1:{port}", 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert is_multihost()
    assert len(jax.devices()) == 4  # 2 hosts x 2 local CPU devices

    mesh = make_mesh(data=4, spatial=1)
    mcfg = small_model_config("raft", dataset="chairs")
    tcfg = TrainConfig(
        stage="chairs", batch_size=4, image_size=(16, 32), iters=1,
        num_steps=5,
    )
    # Same seed on every process -> identical replicated init (SPMD).
    model, state = create_train_state(
        jax.random.PRNGKey(0), mcfg, tcfg, (1, 16, 32, 3)
    )
    repl = replicated(mesh)
    state = jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            repl, np.asarray(x)
        ),
        state,
    )

    # Each host contributes its disjoint half of the global batch of 4
    # (rows [2*pid, 2*pid+2)) — the FlowLoader host-sharding contract.
    g = np.random.default_rng(42)
    full = {
        "image1": g.uniform(0, 255, (4, 16, 32, 3)).astype(np.float32),
        "image2": g.uniform(0, 255, (4, 16, 32, 3)).astype(np.float32),
        "flow": g.normal(size=(4, 16, 32, 2)).astype(np.float32),
        "valid": np.ones((4, 16, 32), np.float32),
    }
    local = {k: v[2 * pid : 2 * pid + 2] for k, v in full.items()}
    batch = global_batch(local, mesh, batch_sharding(mesh))

    # AOT-compile (pure local work, arbitrary cross-process skew allowed
    # — on a loaded 1-core host the two children's compiles can drift
    # apart by minutes), then BARRIER before executing. The execution is
    # where every cross-process wait with a short hard deadline lives
    # (Gloo context init: 30s; collective op waits), so both processes
    # must enter it near-simultaneously — an unaligned entry was the
    # observed CI flake.
    from raft_ncup_tpu.parallel import barrier

    step = make_train_step(model, tcfg, mesh=mesh)
    rng = jax.random.PRNGKey(7)
    compiled = step.lower(state, batch, rng).compile()
    barrier("step-compiled")

    state, metrics = compiled(state, batch, rng)
    loss = float(metrics["loss"])
    assert np.isfinite(loss)
    print(f"LOSS={loss:.6f}", flush=True)

    # --- multi-host output hygiene (VERDICT r4 #4) ---------------------
    # Host-sharded validation: each process computes its slice of the
    # held-out frames, the metric sums all-reduce, and both processes
    # must report the SAME global EPE. The validator's console line must
    # come from the main process only.
    import json

    from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
    from raft_ncup_tpu.evaluation import _shard_for_validation, validate_synthetic
    from raft_ncup_tpu.parallel.multihost import is_main_process

    shard, n_agreed, do_reduce = _shard_for_validation(
        SyntheticFlowDataset((32, 48), length=6, seed=999), mesh=None
    )
    assert (len(shard), n_agreed, do_reduce) == (3, 6, True)  # 6 over 2 hosts

    variables = {"params": jax.tree.map(np.asarray, state.params)}
    barrier("pre-validate")  # realign before the collective reduction
    out = validate_synthetic(
        model, variables, iters=1, batch_size=2, size_hw=(32, 48), length=6
    )
    print(f"VAL={json.dumps(out, sort_keys=True)}", flush=True)

    # Logger hygiene: both processes construct a Logger on the same
    # shared run_dir; only the main process may create/write log.txt.
    from raft_ncup_tpu.training.logger import Logger

    run_dir = sys.argv[3]
    logger = Logger(
        run_dir, sum_freq=1, use_tensorboard=False,
        active=is_main_process(),
    )
    logger.write_text(f"hello from process {pid}")
    logger.close()
    print(f"LOGACTIVE={int(logger.active)}", flush=True)


if __name__ == "__main__":
    main()
