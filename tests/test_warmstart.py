"""Parity of the pure-JAX warm-start splat vs the host cKDTree version.

The acceptance bar for deleting the eval loop's last sanctioned
per-frame pull (ops/warmstart.py): ``forward_interpolate_jax`` must
match ``forward_interpolate`` on dense and sparse-survivor flows
(including the all-points-out-of-bounds ⇒ zeros path), and the Sintel
warm-start validator must produce IDENTICAL EPE with the device splat
swapped in for the host one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.ops.warmstart import (
    forward_interpolate,
    forward_interpolate_batch,
    forward_interpolate_jax,
)


def _jx(flow, **kw):
    return np.asarray(forward_interpolate_jax(jnp.asarray(flow), **kw))


class TestForwardInterpolateJaxParity:
    def test_dense_small_flow_matches_host_bitwise(self):
        """Smooth small flow: nearly every cell receives a splat; the
        nearest fill only bridges sub-pixel gaps."""
        g = np.random.default_rng(0)
        flow = g.normal(0, 1.5, (20, 31, 2)).astype(np.float32)
        np.testing.assert_array_equal(_jx(flow), forward_interpolate(flow))

    def test_sparse_survivors_match_host_bitwise(self):
        """Huge flow pushes most destinations out of bounds: the few
        survivors fill large regions by genuine Euclidean nearest —
        the case an iterated-dilation approximation would get wrong."""
        g = np.random.default_rng(1)
        flow = g.normal(0, 60.0, (16, 16, 2)).astype(np.float32)
        host = forward_interpolate(flow)
        # Fixture sanity: this really is the sparse regime.
        x0, y0 = np.meshgrid(np.arange(16), np.arange(16))
        x1 = (x0 + flow[..., 0]).ravel()
        y1 = (y0 + flow[..., 1]).ravel()
        valid = (x1 > 0) & (x1 < 16) & (y1 > 0) & (y1 < 16)
        assert 0 < valid.sum() < 40
        np.testing.assert_array_equal(_jx(flow), host)

    def test_all_points_out_of_bounds_is_zeros(self):
        flow = np.full((8, 8, 2), 1000.0, np.float32)
        out = _jx(flow)
        assert (out == 0).all()
        np.testing.assert_array_equal(out, forward_interpolate(flow))

    def test_zero_flow_is_zero(self):
        flow = np.zeros((10, 12, 2), np.float32)
        np.testing.assert_array_equal(_jx(flow), np.zeros_like(flow))

    def test_strict_open_interval_bounds(self):
        """Destinations exactly ON the boundary are dropped (the
        reference's strict inequalities) — a flow moving everything to
        x=0 must not survive."""
        flow = np.zeros((6, 6, 2), np.float32)
        x0, _ = np.meshgrid(np.arange(6), np.arange(6))
        flow[..., 0] = -x0  # every destination lands exactly at x=0
        host = forward_interpolate(flow)
        np.testing.assert_array_equal(_jx(flow), host)
        assert (host == 0).all()

    def test_chunk_size_does_not_change_result(self):
        g = np.random.default_rng(2)
        flow = g.normal(0, 8.0, (12, 18, 2)).astype(np.float32)
        full = _jx(flow, chunk=12 * 18)
        np.testing.assert_array_equal(_jx(flow, chunk=7), full)
        np.testing.assert_array_equal(_jx(flow, chunk=1), full)

    def test_batch_rows_are_independent(self):
        """vmap rows match the single-frame function — a NaN row cannot
        leak into its batch-mates (the streaming isolation contract's
        numerical foundation)."""
        g = np.random.default_rng(3)
        a = g.normal(0, 2.0, (16, 16, 2)).astype(np.float32)
        b = g.normal(0, 50.0, (16, 16, 2)).astype(np.float32)
        poison = np.full((16, 16, 2), np.nan, np.float32)
        out = np.asarray(
            forward_interpolate_batch(jnp.asarray(np.stack([a, poison, b])))
        )
        np.testing.assert_array_equal(out[0], _jx(a))
        np.testing.assert_array_equal(out[2], _jx(b))

    def test_traceable_under_jit_one_program_per_shape(self):
        g = np.random.default_rng(4)
        fn = jax.jit(lambda f: forward_interpolate_jax(f))
        a = g.normal(0, 3.0, (8, 10, 2)).astype(np.float32)
        b = g.normal(0, 3.0, (8, 10, 2)).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(fn(jnp.asarray(a))), forward_interpolate(a)
        )
        np.testing.assert_array_equal(
            np.asarray(fn(jnp.asarray(b))), forward_interpolate(b)
        )

    def test_rejects_bad_shape(self):
        with pytest.raises(ValueError):
            forward_interpolate_jax(jnp.zeros((4, 4, 3)))


# -------------------------------------- warm-start validator EPE parity


@pytest.fixture(scope="module")
def tiny_model():
    from raft_ncup_tpu.config import small_model_config
    from raft_ncup_tpu.models import get_model

    cfg = small_model_config("raft", dataset="chairs")
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 36, 44, 3))
    return model, variables


class _SeqDataset:
    """Synthetic 'video': all frames belong to one sequence."""

    def __init__(self, n, hw=(36, 44), seed=77):
        from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset

        self._ds = SyntheticFlowDataset(hw, length=n, seed=seed)
        self._n = n

    def __len__(self):
        return self._n

    def sample(self, i, rng=None):
        s = self._ds.sample(i)
        s["extra_info"] = ("seq0", i)
        return s


def test_warmstart_validator_identical_epe_device_vs_host_splat(tiny_model):
    """The Sintel warm-start validator path
    (evaluation._run_warmstart_metric_pass, all-device splat) produces
    IDENTICAL metrics to a host-splat reference loop over the same
    frames — swapping the splat implementation changes nothing, because
    the splats themselves are bitwise equal."""
    from raft_ncup_tpu.evaluation import (
        _pad_host,
        _run_warmstart_metric_pass,
    )
    from raft_ncup_tpu.inference import metrics as metrics_mod
    from raft_ncup_tpu.inference.pipeline import ShapeCachedForward
    from raft_ncup_tpu.ops import InputPadder

    model, variables = tiny_model
    ds = _SeqDataset(4)

    fwd = ShapeCachedForward(model, variables)
    acc_dev = _run_warmstart_metric_pass(fwd, ds, kind="px", iters=2)
    m_dev = metrics_mod.finalize("px", acc_dev)

    # Host-splat reference: same frames, same executable, but the warm
    # chain goes through the cKDTree splat with a per-frame pull.
    fwd_ref = ShapeCachedForward(model, variables)
    acc = metrics_mod.init_acc("px")
    flow_prev = None
    for i in range(len(ds)):
        s = ds.sample(i)
        img1 = np.asarray(s["image1"], np.float32)[None]
        img2 = np.asarray(s["image2"], np.float32)[None]
        gt = np.asarray(s["flow"], np.float32)[None]
        padder = InputPadder(img1.shape, mode="sintel")
        pad = padder.pad_spec
        img1, img2 = _pad_host(pad, img1, img2)
        if flow_prev is None:
            h8, w8 = img1.shape[1] // 8, img1.shape[2] // 8
            flow_prev = jnp.zeros((1, h8, w8, 2), jnp.float32)
        acc, flow_lr = fwd_ref.metrics(
            {"image1": img1, "image2": img2, "flow": gt},
            iters=2, acc=acc, kind="px", pad=pad, flow_init=flow_prev,
        )
        flow_prev = jnp.asarray(
            forward_interpolate(np.asarray(jax.device_get(flow_lr))[0])[None]
        )
    m_host = metrics_mod.finalize(
        "px", np.asarray(jax.device_get(acc), np.float64)
    )
    assert m_dev == m_host

    # And warm start genuinely changed the chain vs cold evaluation:
    fwd_cold = ShapeCachedForward(model, variables)
    acc_cold = metrics_mod.init_acc("px")
    for i in range(len(ds)):
        s = ds.sample(i)
        img1 = np.asarray(s["image1"], np.float32)[None]
        img2 = np.asarray(s["image2"], np.float32)[None]
        gt = np.asarray(s["flow"], np.float32)[None]
        padder = InputPadder(img1.shape, mode="sintel")
        img1, img2 = _pad_host(padder.pad_spec, img1, img2)
        acc_cold = fwd_cold.metrics(
            {"image1": img1, "image2": img2, "flow": gt},
            iters=2, acc=acc_cold, kind="px", pad=padder.pad_spec,
        )
    m_cold = metrics_mod.finalize(
        "px", np.asarray(jax.device_get(acc_cold), np.float64)
    )
    assert m_dev["epe"] != m_cold["epe"]


def test_warmstart_pass_is_pull_free(tiny_model):
    """The device-splat pass performs ONE sanctioned pull (the window
    accumulator) and zero implicit transfers — the deleted JGL008
    allowlist entry stays deleted."""
    from raft_ncup_tpu.analysis.guards import forbid_host_transfers
    from raft_ncup_tpu.evaluation import _run_warmstart_metric_pass
    from raft_ncup_tpu.inference.pipeline import ShapeCachedForward

    model, variables = tiny_model
    ds = _SeqDataset(3)
    fwd = ShapeCachedForward(model, variables)
    # Warm the executables outside the guard (compiles pull constants).
    _run_warmstart_metric_pass(fwd, ds, kind="epe", iters=1)
    with forbid_host_transfers() as stats:
        _run_warmstart_metric_pass(fwd, ds, kind="epe", iters=1)
    assert stats.host_transfers == 0
    assert stats.sanctioned_gets == 1
