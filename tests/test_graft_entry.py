"""Driver entry-point contract tests (__graft_entry__).

The driver compile-checks ``entry()`` single-chip and executes
``dryrun_multichip(8)`` on a virtual CPU mesh; three rounds of rc=124
artifacts traced to the caller's process initializing the wedged axon
backend (round-3 postmortem). These tests pin the guards that prevent
that, plus the dryrun itself exactly as the driver invokes it.
"""

import os

import numpy as np
import pytest

import __graft_entry__ as ge
from raft_ncup_tpu.utils import backend_probe


@pytest.fixture(autouse=True)
def _clear_guard_cache():
    ge._BACKEND_GUARD_CACHE.clear()
    yield
    ge._BACKEND_GUARD_CACHE.clear()


def test_guard_trusts_inherited_cpu_env(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")

    def boom(*a, **k):  # the probe must not run when env is already cpu
        raise AssertionError("probe_backend called")

    monkeypatch.setattr(backend_probe, "probe_backend", boom)
    assert ge.ensure_live_backend_for_caller() == "inherited-cpu"


def test_guard_passes_live_accelerator_through(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(
        backend_probe,
        "probe_backend",
        lambda *a, **k: backend_probe.ProbeResult("axon", "ok"),
    )
    assert ge.ensure_live_backend_for_caller() == "live"
    # A live backend must be left untouched.
    assert os.environ["JAX_PLATFORMS"] == "axon"


def test_guard_forces_cpu_when_backend_hangs(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(
        backend_probe,
        "probe_backend",
        lambda *a, **k: backend_probe.ProbeResult(
            None, "hung", "probe exceeded 90s"
        ),
    )
    assert ge.ensure_live_backend_for_caller() == "forced-cpu"
    # force_platform must have repointed BOTH the env var and jax.config
    # (the config side is what the caller's jit actually reads).
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    import jax

    assert jax.config.jax_platforms == "cpu"


def test_backend_already_initialized_detection(monkeypatch):
    import jax

    jax.devices()  # ensure a backend exists in this process
    assert ge._backend_already_initialized() is True
    # Unimportable/absent registry degrades to False (open fail).
    import sys as _sys

    monkeypatch.setitem(_sys.modules, "jax._src.xla_bridge", None)
    assert ge._backend_already_initialized() is False


def test_repoint_warns_instead_of_noop_when_backend_initialized(
    monkeypatch, capsys
):
    """With a backend already initialized, jax.config.update silently
    no-ops — the guard must say the re-point cannot apply rather than
    claim success (ADVICE r5), and must leave the config untouched."""
    import jax

    jax.devices()  # the tests' CPU backend counts as prior init
    assert jax.config.jax_platforms == "cpu"
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(
        backend_probe,
        "probe_backend",
        lambda *a, **k: backend_probe.ProbeResult("axon", "ok"),
    )
    assert ge.ensure_live_backend_for_caller() == "live"
    err = capsys.readouterr().err
    assert "cannot apply" in err and "restart" in err
    # The config was NOT rewritten (the update would not apply anyway —
    # and rewriting it would desync config from the live backend).
    assert jax.config.jax_platforms == "cpu"


def test_guard_probes_at_most_once(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    calls = []

    def probe(*a, **k):
        calls.append(1)
        return backend_probe.ProbeResult("axon", "ok")

    monkeypatch.setattr(backend_probe, "probe_backend", probe)
    assert ge.ensure_live_backend_for_caller() == "live"
    assert ge.ensure_live_backend_for_caller() == "live"
    assert len(calls) == 1


def test_cpu_mesh_ready_reads_env_only(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    assert ge._cpu_mesh_ready(8)
    assert not ge._cpu_mesh_ready(16)
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert not ge._cpu_mesh_ready(8)


@pytest.mark.slow
def test_entry_returns_jittable_fn_with_numpy_args():
    """The driver's single-chip compile check: entry() then jit-trace."""
    fn, args = ge.entry()
    variables, img1, img2 = args
    assert isinstance(img1, np.ndarray) and isinstance(img2, np.ndarray)
    import jax

    # .lower() traces the full flagship forward (what the driver's
    # compile check does before .compile()).
    lowered = jax.jit(fn).lower(variables, img1, img2)
    assert lowered is not None


@pytest.mark.slow
def test_dryrun_multichip_in_process_8_devices(capsys):
    """The driver artifact, exactly as invoked: conftest's env matches
    _cpu_mesh_ready so this exercises the in-process path."""
    ge.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert "dryrun_multichip ok" in out
