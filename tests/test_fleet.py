"""Fleet tier tests (raft_ncup_tpu/fleet/; docs/FLEET.md).

Fast tier: topology validation, the wire protocol, the pad-arithmetic
mirror, rendezvous routing, the ChildProcess lifecycle, supervisor
restart/backoff/circuit-breaker logic against instant-crash children,
and the router's shed/retry-after aggregation + failover against FAKE
in-process replica servers speaking the real wire protocol (no jax, no
model, sub-second).

Slow tier: the chaos-pinned blast radius against REAL serve.py replica
processes — killreplica (SIGKILL) mid-stream with bitwise surviving-
replica parity, drainreplica with zero in-flight losses + the
DRAINING/exit-75 contract, stallreplica through the healthz staleness
contract, restart accounting, and the postmortem reassembly of a
request's journey across the router hop. Plus the elastic-fleet tier:
partitionhost/killsupervisor against a REAL multi-host TCP fleet
(HostSupervisor agents, fencing, fleet-level staleness) and the
autoscaler driving a real scale-down-under-load → scale-up cycle with
zero in-flight loss.
"""

import json
import os
import signal
import socket
import sys
import threading
import time

import numpy as np
import pytest

from raft_ncup_tpu.config import ServeConfig, StreamConfig
from raft_ncup_tpu.fleet import (
    ChildProcess,
    FleetAutoscaler,
    FleetConfig,
    FleetManager,
    FleetRouter,
    ReplicaSupervisor,
    healthz_fresh,
    padded_shape,
    read_healthz,
)
from raft_ncup_tpu.fleet.replica import (
    BROKEN,
    DEAD,
    SPAWNING,
    UP,
    last_json_line,
)
from raft_ncup_tpu.fleet.router import rendezvous_choice
from raft_ncup_tpu.fleet.wire import (
    FrameTimeout,
    Transport,
    recv_msg,
    send_msg,
    set_read_timeout,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- topology


class TestTopology:
    def test_paths_and_defaults(self, tmp_path):
        cfg = FleetConfig(base_dir=str(tmp_path), n_replicas=3)
        spec = cfg.replica(2)
        assert spec.socket_path == str(tmp_path / "replica_2.sock")
        assert spec.healthz_path == str(
            tmp_path / "replica_2.healthz.json"
        )
        assert spec.flight_dir == str(tmp_path / "replica_2_flight")
        assert len(cfg.replicas()) == 3
        # The staleness contract: 2x the snapshot cadence by default.
        assert cfg.stale_after_s == pytest.approx(
            2.0 * cfg.snapshot_interval_s
        )

    def test_validation_rejects_bad_topologies(self, tmp_path):
        base = str(tmp_path)
        with pytest.raises(ValueError):
            FleetConfig(base_dir=base, n_replicas=0)
        with pytest.raises(ValueError):
            FleetConfig(base_dir="")
        with pytest.raises(ValueError):  # meshes must name every replica
            FleetConfig(base_dir=base, n_replicas=3,
                        meshes=((1, 1), (1, 1)))
        with pytest.raises(ValueError):
            FleetConfig(base_dir=base, circuit_break_after=0)
        with pytest.raises(ValueError):
            FleetConfig(base_dir=base, max_inflight_per_replica=0)
        with pytest.raises(ValueError):
            FleetConfig(base_dir=base, stale_after_factor=0.5)
        with pytest.raises(ValueError):
            FleetConfig(base_dir=base, max_failovers=-1)
        with pytest.raises(ValueError):
            FleetConfig(base_dir=base, snapshot_interval_s=0.0)
        with pytest.raises(ValueError):
            FleetConfig(base_dir=base, size_hw=(8, 8))

    def test_replica_argv_is_the_topology(self, tmp_path):
        """The spawn argv is DERIVED from the one config object —
        serve/stream knobs, paths, cadence, mesh — so the supervisor,
        bench, and a human reproducing a replica all run the same
        thing."""
        cfg = FleetConfig(
            base_dir=str(tmp_path), n_replicas=2,
            size_hw=(48, 64),
            serve=ServeConfig(batch_sizes=(1, 2), iter_levels=(4, 2),
                              queue_capacity=7),
            stream=StreamConfig(capacity=3, iters=2, batch_sizes=(1, 2),
                                frame_hw=(48, 64)),
            meshes=((1, 1), (2, 1)),
            extra_args=("--small", "--platform", "cpu"),
        )
        argv = cfg.replica_argv(1)
        joined = " ".join(argv)
        assert "--replica_socket " + str(tmp_path / "replica_1.sock") in joined
        assert "--replica_index 1" in joined
        assert "--iter_levels 4,2" in joined
        assert "--queue_capacity 7" in joined
        assert "--stream_capacity 3" in joined
        assert "--mesh 2,1" in joined
        assert "--small" in joined
        # Request-only fleet: stream knobs absent, streams disabled.
        cfg2 = FleetConfig(base_dir=str(tmp_path), stream=None)
        argv2 = cfg2.replica_argv(0)
        assert "--replica_streams" in argv2
        assert argv2[argv2.index("--replica_streams") + 1] == "false"
        assert "--stream_capacity" not in argv2

    def test_padded_shape_matches_input_padder(self):
        """The router's pure-host pad arithmetic must agree with the
        real InputPadder for every (shape, divisor, bucket) it routes
        on — a drifting mirror would mis-match warmed executables."""
        from raft_ncup_tpu.ops.padding import InputPadder

        for h, w in ((48, 64), (97, 130), (100, 100), (437, 1023)):
            for divisor in (8, 16, 32):
                p = InputPadder((h, w, 3), mode="sintel", divisor=divisor)
                (t, b), (le, r) = p.pad_spec
                assert padded_shape(h, w, divisor=divisor) == (
                    h + t + b, w + le + r
                )
            for bucket in (32, 64):
                p = InputPadder((h, w, 3), mode="sintel", bucket=bucket)
                (t, b), (le, r) = p.pad_spec
                assert padded_shape(h, w, bucket=bucket) == (
                    h + t + b, w + le + r
                )

    def test_shape_key_uses_replica_mesh_divisor(self, tmp_path):
        cfg = FleetConfig(
            base_dir=str(tmp_path), n_replicas=2,
            meshes=(None, (1, 2)),
        )
        assert cfg.pad_divisor(0) == 8
        assert cfg.pad_divisor(1) == 16
        assert cfg.shape_key(97, 130, 0) == (104, 136)
        assert cfg.shape_key(97, 130, 1) == (112, 136)

    def test_fleet_package_is_jax_free(self):
        """JGL010's runtime half: importing the whole fleet package
        must not pull jax into the process (the router must never be
        ABLE to add a device sync)."""
        import subprocess

        code = (
            "import sys; import raft_ncup_tpu.fleet; "
            "import raft_ncup_tpu.fleet.router; "
            "assert 'jax' not in sys.modules, 'jax leaked'; print('ok')"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], cwd=_REPO,
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert out.stdout.strip() == "ok"


# ----------------------------------------------------------------- wire


class TestWire:
    def _pair(self):
        return socket.socketpair()

    def test_roundtrip_header_and_arrays(self):
        a, b = self._pair()
        img = np.arange(24, dtype=np.float32).reshape(2, 4, 3)
        mask = np.ones((3, 3), np.uint8)
        send_msg(a, {"kind": "request", "id": 7, "deadline_s": 1.5},
                 [img, mask])
        header, arrays = recv_msg(b)
        assert header == {"kind": "request", "id": 7, "deadline_s": 1.5}
        np.testing.assert_array_equal(arrays[0], img)
        np.testing.assert_array_equal(arrays[1], mask)
        assert arrays[0].dtype == np.float32
        a.close(), b.close()

    def test_trace_context_header_is_optional_both_ways(self):
        """Wire-compat contract (JGL010's static check is the other
        half): a frame WITH the trace-context field round-trips it
        verbatim; a frame WITHOUT it parses identically — old and new
        peers interoperate in both directions."""
        from raft_ncup_tpu.fleet.wire import TRACE_KEY
        from raft_ncup_tpu.observability import TraceContext

        a, b = self._pair()
        img = np.zeros((2, 4, 3), np.float32)
        ctx = TraceContext("feed1234beef5678", "router-3", 0.25, 9.5)
        send_msg(a, {"kind": "request", "id": 3, TRACE_KEY: ctx.to_wire()},
                 [img, img])
        header, _ = recv_msg(b)
        assert TraceContext.from_wire(header.get(TRACE_KEY)) == ctx
        # Old-router frame: no trace key; the tolerant parse is None.
        send_msg(a, {"kind": "request", "id": 4}, [img, img])
        header, _ = recv_msg(b)
        assert TRACE_KEY not in header
        assert TraceContext.from_wire(header.get(TRACE_KEY)) is None
        a.close(), b.close()

    def test_non_contiguous_array_survives(self):
        a, b = self._pair()
        img = np.arange(48, dtype=np.float32).reshape(4, 4, 3)[::2]
        send_msg(a, {"kind": "x"}, [img])
        _, arrays = recv_msg(b)
        np.testing.assert_array_equal(arrays[0], img)
        a.close(), b.close()

    def test_clean_eof_returns_none(self):
        a, b = self._pair()
        a.close()
        assert recv_msg(b) is None
        b.close()

    def test_mid_frame_eof_raises(self):
        a, b = self._pair()
        img = np.zeros((8, 8, 3), np.float32)
        # Hand-build a frame and truncate it mid-payload.
        import struct

        blob = json.dumps({
            "kind": "request",
            "arrays": [{"shape": [8, 8, 3], "dtype": "float32"}],
        }).encode()
        a.sendall(struct.pack(">I", len(blob)) + blob
                  + img.tobytes()[:10])
        a.close()
        with pytest.raises(ConnectionError):
            recv_msg(b)
        b.close()

    def test_reserved_arrays_key_and_non_ndarray_rejected(self):
        a, b = self._pair()
        with pytest.raises(ValueError):
            send_msg(a, {"arrays": []})
        with pytest.raises(TypeError):
            send_msg(a, {"kind": "x"}, [[1, 2, 3]])
        a.close(), b.close()

    def test_corrupt_length_prefix_fails_loudly(self):
        a, b = self._pair()
        import struct

        a.sendall(struct.pack(">I", 1 << 24))  # over MAX_HEADER_BYTES
        with pytest.raises(ValueError):
            recv_msg(b)
        a.close(), b.close()


# ----------------------------------------- transport address abstraction


class TestTransport:
    def test_parse_matrix(self):
        """The ONE address string both ends share decides the family —
        the parse is syntactic and total, so a topology moves from UDS
        to TCP by changing addresses, nothing else."""
        t = Transport.parse("127.0.0.1:5001")
        assert t.is_inet and (t.host, t.port) == ("127.0.0.1", 5001)
        assert t.render() == "127.0.0.1:5001"
        assert Transport.parse("replica-host:65000").is_inet
        # Anything with a path separator is a UDS path, colon or not.
        t = Transport.parse("/tmp/fleet/replica_0.sock")
        assert not t.is_inet and t.path == "/tmp/fleet/replica_0.sock"
        assert Transport.parse("/tmp/odd:5000/x.sock").path.endswith(
            "x.sock"
        )
        # No host:digits shape -> UDS path, verbatim.
        assert not Transport.parse("replica.sock").is_inet
        assert not Transport.parse("host:notaport").is_inet
        assert not Transport.parse(":5000").is_inet
        with pytest.raises(ValueError):
            Transport.parse("")

    def test_topology_addresses_swap_family_only(self, tmp_path):
        uds = FleetConfig(base_dir=str(tmp_path), n_replicas=2)
        tcp = FleetConfig(
            base_dir=str(tmp_path), n_replicas=2,
            transport="tcp", base_port=15000,
        )
        assert not Transport.parse(uds.replica_address(1)).is_inet
        t = Transport.parse(tcp.replica_address(1))
        assert t.is_inet and t.port == 15001
        # Host-agent control ports sit directly above the replica slots.
        tcp_h = FleetConfig(
            base_dir=str(tmp_path), n_replicas=2,
            transport="tcp", base_port=15000, hosts=("hA", "hB"),
        )
        assert Transport.parse(
            tcp_h.host_control_address("hB")
        ).port == 15003

    def test_listen_connect_cleanup_uds(self, tmp_path):
        addr = str(tmp_path / "t.sock")
        t = Transport.parse(addr)
        lsock = t.listen(2)
        # A stale path from a dead incarnation must not lock out the
        # next listener.
        lsock.close()
        lsock = t.listen(2)
        client = t.connect(timeout_s=5.0)
        server, _ = lsock.accept()
        send_msg(client, {"kind": "ping"})
        assert recv_msg(server)[0] == {"kind": "ping"}
        client.close(), server.close(), lsock.close()
        t.cleanup()
        assert not os.path.exists(addr)


def _tcp_pair():
    """A connected (client, server) TCP pair through the real
    Transport listen/connect path on an ephemeral loopback port."""
    lsock = Transport(socket.AF_INET, host="127.0.0.1", port=0).listen(4)
    port = lsock.getsockname()[1]
    client = Transport.parse(f"127.0.0.1:{port}").connect(timeout_s=5.0)
    server, _ = lsock.accept()
    lsock.close()
    return client, server


class TestWireInet:
    """Satellite: the framing contract re-pinned for the INET family,
    plus the failure modes only a LAN shows — torn frames at seeded
    truncation points, slow-loris dribble, and half-open silence under
    ``SO_RCVTIMEO``."""

    def test_roundtrip_and_clean_eof_inet(self):
        client, server = _tcp_pair()
        img = np.arange(24, dtype=np.float32).reshape(2, 4, 3)
        send_msg(client, {"kind": "request", "id": 1}, [img])
        header, arrays = recv_msg(server)
        assert header == {"kind": "request", "id": 1}
        np.testing.assert_array_equal(arrays[0], img)
        # Clean EOF at a frame boundary is None over TCP exactly as
        # over UDS: a closed peer between frames is not an error.
        client.close()
        assert recv_msg(server) is None
        server.close()

    def test_keepalive_and_nodelay_armed(self):
        client, server = _tcp_pair()
        assert client.getsockopt(
            socket.SOL_SOCKET, socket.SO_KEEPALIVE
        ) != 0
        assert client.getsockopt(
            socket.IPPROTO_TCP, socket.TCP_NODELAY
        ) != 0
        client.close(), server.close()

    @staticmethod
    def _frame_bytes():
        import struct

        img = np.arange(12, dtype=np.float32)
        blob = json.dumps({
            "kind": "request", "id": 9,
            "arrays": [{"shape": [12], "dtype": "float32"}],
        }).encode()
        return struct.pack(">I", len(blob)) + blob + img.tobytes()

    def test_torn_frames_at_every_seeded_truncation_point(self):
        """A peer death at ANY byte offset inside a frame must raise
        ConnectionError — never return a half-trusted frame, never
        hang. Offset 0 is the one clean EOF."""
        frame = self._frame_bytes()
        header_len = 4 + len(frame[4:]) - 48  # 4 + blob; payload is 48
        cuts = [0, 1, 3, 4, 4 + 7, header_len, header_len + 1,
                header_len + 47]
        for cut in cuts:
            client, server = _tcp_pair()
            client.sendall(frame[:cut])
            client.close()
            if cut == 0:
                assert recv_msg(server) is None, f"cut={cut}"
            else:
                with pytest.raises(ConnectionError):
                    recv_msg(server)
            server.close()
        # And the untruncated frame still parses (the cut points were
        # the fault, not the frame).
        client, server = _tcp_pair()
        client.sendall(frame)
        header, arrays = recv_msg(server)
        assert header["id"] == 9 and arrays[0].shape == (12,)
        client.close(), server.close()

    def test_boundary_silence_is_frame_timeout(self):
        """No bytes within the read deadline at a frame boundary: the
        link is merely idle (or half-open — the router's link reader
        answers with a ping probe). FrameTimeout, not ConnectionError."""
        client, server = _tcp_pair()
        set_read_timeout(server, 0.15)
        t0 = time.monotonic()
        with pytest.raises(FrameTimeout):
            recv_msg(server)
        assert time.monotonic() - t0 < 5.0
        # The link is still usable after a boundary timeout.
        send_msg(client, {"kind": "ping"})
        assert recv_msg(server)[0] == {"kind": "ping"}
        client.close(), server.close()

    def test_slow_loris_mid_frame_is_connection_error(self):
        """A peer that sends the length prefix (or half the header) and
        then dribbles nothing holds a reader hostage forever without a
        deadline — with one, the frame is as dead as a torn one."""
        frame = self._frame_bytes()
        for cut in (4, 10):
            client, server = _tcp_pair()
            set_read_timeout(server, 0.15)
            client.sendall(frame[:cut])
            with pytest.raises(ConnectionError):
                recv_msg(server)
            client.close(), server.close()


# ------------------------------------------------------------ lifecycle


class TestChildProcess:
    def test_spawn_reap_captures_output(self):
        c = ChildProcess([
            sys.executable, "-c",
            "import json, sys; print('hello'); "
            "print(json.dumps({'a': 1})); "
            "print('warn', file=sys.stderr)",
        ], name="t").spawn()
        rc, out, err = c.reap(timeout=30)
        assert rc == 0
        assert "hello" in out and "warn" in err
        assert last_json_line(out) == {"a": 1}

    def test_reap_timeout_escalates_to_kill(self):
        c = ChildProcess([
            sys.executable, "-c", "import time; time.sleep(600)",
        ], name="t").spawn()
        t0 = time.monotonic()
        rc, _, _ = c.reap(timeout=0.5)
        assert rc == -9
        assert time.monotonic() - t0 < 30

    def test_suspend_resume_and_kill(self):
        c = ChildProcess([
            sys.executable, "-c", "import time; time.sleep(600)",
        ], name="t").spawn()
        assert c.running
        assert c.suspend()
        assert c.resume()
        assert c.kill()
        rc, _, _ = c.reap(timeout=10)
        assert rc == -9 and not c.running

    def test_last_json_line_skips_noise(self):
        text = "noise\n{broken\n" + json.dumps({"k": 2}) + "\ntrailing\n"
        assert last_json_line(text) == {"k": 2}
        assert last_json_line("no json at all") is None


class TestHealthzContract:
    def test_freshness_is_the_2x_cadence_contract(self):
        now = 1000.0
        fresh = {"time_unix_s": now - 0.4}
        stale = {"time_unix_s": now - 0.6}
        assert healthz_fresh(fresh, 0.5, now_unix=now)
        assert not healthz_fresh(stale, 0.5, now_unix=now)
        assert not healthz_fresh(None, 0.5, now_unix=now)
        assert not healthz_fresh({}, 0.5, now_unix=now)
        assert not healthz_fresh({"time_unix_s": "x"}, 0.5, now_unix=now)

    def test_read_healthz_missing_or_torn(self, tmp_path):
        assert read_healthz(str(tmp_path / "nope.json")) is None
        p = tmp_path / "torn.json"
        p.write_text("{not json")
        assert read_healthz(str(p)) is None


# ------------------------------------ supervisor restart/circuit logic


def _crashy_supervisor(tmp_path, **cfg_kw):
    """Supervisor over children that exit 1 instantly — the crash-loop
    the restart budget and circuit breaker exist for. argv_prefix
    replaces serve.py with a stub that ignores the replica argv."""
    cfg = FleetConfig(
        base_dir=str(tmp_path),
        n_replicas=1,
        poll_interval_s=0.02,
        restart_backoff_s=0.05,
        restart_backoff_max_s=0.2,
        **cfg_kw,
    )
    from raft_ncup_tpu.observability import Telemetry

    sup = ReplicaSupervisor(
        cfg,
        argv_prefix=[sys.executable, "-c", "import sys; sys.exit(1)"],
        telemetry=Telemetry(),
    )
    return cfg, sup


def _pump(sup, until, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sup.poll()
        if until():
            return
        time.sleep(0.02)
    raise AssertionError("supervisor never reached expected state")


class TestSupervisorRobustness:
    def test_restart_is_bounded_counted_with_backoff(self, tmp_path):
        cfg, sup = _crashy_supervisor(
            tmp_path, max_restarts=2, circuit_break_after=10,
        )
        sup.start(wait_ready=False)
        sup._poll_stop.set()  # drive poll() deterministically
        handle = sup.replicas[0]
        _pump(sup, lambda: handle.state == BROKEN)
        # Budget exhausted, every attempt counted, breaker NOT blamed.
        assert handle.restarts == cfg.max_restarts == 2
        assert handle.deaths == 3  # initial + one per restart
        assert not handle.circuit_open
        assert not handle.admittable()
        rep = sup.report()
        assert rep["restarts"] == 2 and rep["deaths"] == 3
        sup.stop(drain=False)

    def test_circuit_breaker_opens_after_k_consecutive(self, tmp_path):
        cfg, sup = _crashy_supervisor(
            tmp_path, max_restarts=10, circuit_break_after=3,
        )
        sup.start(wait_ready=False)
        sup._poll_stop.set()
        handle = sup.replicas[0]
        _pump(sup, lambda: handle.circuit_open)
        # K consecutive failures without an intervening READY: breaker
        # open, no further restarts, no traffic.
        assert handle.consecutive_failures == cfg.circuit_break_after == 3
        assert handle.state == BROKEN
        assert handle.restarts == 2  # the attempts BEFORE the breaker
        assert not handle.admittable()
        restarts_at_open = handle.restarts
        for _ in range(5):
            sup.poll()
            time.sleep(0.03)
        assert handle.restarts == restarts_at_open  # stays open
        assert sup.report()["circuits_open"] == 1
        sup.stop(drain=False)

    def test_backoff_doubles_and_caps(self, tmp_path):
        cfg, sup = _crashy_supervisor(
            tmp_path, max_restarts=10, circuit_break_after=10,
        )
        sup.start(wait_ready=False)
        sup._poll_stop.set()
        handle = sup.replicas[0]
        delays = []
        prev_deaths = 0
        deadline = time.monotonic() + 10
        while len(delays) < 4 and time.monotonic() < deadline:
            sup.poll()
            if handle.deaths > prev_deaths and handle.state == DEAD:
                delays.append(handle.restart_at - time.monotonic())
                prev_deaths = handle.deaths
            time.sleep(0.01)
        assert len(delays) == 4
        # 0.05, 0.1, 0.2, then capped at 0.2 (restart_backoff_max_s).
        assert delays[1] > delays[0]
        assert all(d <= cfg.restart_backoff_max_s + 0.02 for d in delays)
        sup.stop(drain=False)


# ----------------------------- router against fake in-process replicas


class _FakeReplica:
    """An in-process replica server speaking the real wire protocol.

    ``plan`` decides each message's fate: "ok" answers with a zero
    flow, "shed" answers shed with ``retry_after_s``, "hold" never
    answers (a wedged replica). One behavior per message, in order;
    the last entry repeats.
    """

    def __init__(self, spec, plan, retry_after_s=1.0):
        self.spec = spec
        self.plan = list(plan)
        self.retry_after = retry_after_s
        self.telemetry_enabled = True
        self.seen = []
        self._n = 0
        # Listen wherever the topology put this replica — UDS path or
        # host:port, decided by the same Transport parse serve.py uses.
        self._transport = Transport.parse(
            spec.address or spec.socket_path
        )
        self._lsock = self._transport.listen(4)
        self._lsock.settimeout(0.1)
        self._stop = threading.Event()
        self._threads = [threading.Thread(
            target=self._accept_loop, daemon=True
        )]
        self._threads[0].start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve(self, conn):
        try:
            while True:
                msg = recv_msg(conn)
                if msg is None:
                    return
                t_recv = time.monotonic()
                header, arrays = msg
                kind = header.get("kind")
                if kind == "ping":
                    # Clock handshake (control traffic never consumes a
                    # plan entry): echo t0, stamp our monotonic clock.
                    send_msg(conn, {
                        "kind": "pong", "pid": os.getpid(),
                        "t0": header.get("t0"),
                        "t_mono": time.monotonic(),
                    })
                    continue
                if kind == "set_telemetry":
                    self.telemetry_enabled = bool(
                        header.get("enabled", True)
                    )
                    send_msg(conn, {
                        "kind": "telemetry_ack",
                        "enabled": self.telemetry_enabled,
                        "replica": self.spec.index,
                    })
                    continue
                self.seen.append(header)
                behavior = self.plan[min(self._n, len(self.plan) - 1)]
                self._n += 1
                if behavior == "hold":
                    continue
                if behavior == "shed":
                    send_msg(conn, {
                        "kind": "response", "id": header["id"],
                        "status": "shed",
                        "retry_after_s": self.retry_after,
                        "detail": "fake shed",
                    })
                    continue
                h, w = arrays[0].shape[:2]
                send_msg(conn, {
                    "kind": "response", "id": header["id"],
                    "status": "ok", "iters": 2, "latency_s": 0.001,
                    "detail": "",
                    # Per-hop stamps on the fake's clock, like a real
                    # replica (router translates via the handshake).
                    "t_recv_s": t_recv,
                    "t_done_s": time.monotonic(),
                }, [np.zeros((h, w, 2), np.float32)])
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            conn.close()

    def close(self):
        self._stop.set()
        self._lsock.close()
        # No transport.cleanup(): the socket path staying behind is how
        # aggregate.py knows replica slots EXISTED (gap detection).


def _free_base_port(n, tries=50):
    """A base port with ``n`` consecutive free loopback ports above it
    (TCP fleet topologies allocate replica + control ports as a
    contiguous block)."""
    rng = np.random.default_rng()
    for _ in range(tries):
        base = int(rng.integers(20000, 60000))
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no {n} consecutive free ports found")


def _fake_fleet(tmp_path, plans, retry_afters, **cfg_kw):
    """A router over N fake replicas: supervisor handles hand-marked UP
    (no processes), fake servers on the topology's socket paths."""
    from raft_ncup_tpu.observability import Telemetry

    cfg = FleetConfig(
        base_dir=str(tmp_path), n_replicas=len(plans), **cfg_kw
    )
    sup = ReplicaSupervisor(cfg, telemetry=Telemetry())
    fakes = []
    for i, (plan, ra) in enumerate(zip(plans, retry_afters)):
        fakes.append(_FakeReplica(cfg.replica(i), plan, ra))
        sup.replicas[i].state = UP
        sup.replicas[i].last_healthz = {"overall": "ready"}
    router = FleetRouter(cfg, sup, telemetry=Telemetry())
    return cfg, sup, router, fakes


def _img(h=32, w=48, seed=0):
    return np.random.default_rng(seed).uniform(
        0, 255, (h, w, 3)
    ).astype(np.float32)


class TestRouterAgainstFakes:
    def test_ok_roundtrip_and_least_loaded_spread(self, tmp_path):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"], ["ok"]], [1.0, 1.0],
        )
        try:
            rs = [
                router.submit(_img(), _img()).result(timeout=10)
                for _ in range(4)
            ]
            assert [r.status for r in rs] == ["ok"] * 4
            assert all(r.flow.shape == (32, 48, 2) for r in rs)
            # Sequential submits against instant fakes drain each time;
            # the cumulative-dispatch tie-break must still spread the
            # load instead of pinning replica 0.
            assert router.report()["per_replica_dispatched"] == {
                0: 2, 1: 2,
            }
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_fleet_shed_never_smaller_than_any_consulted_hint(
        self, tmp_path
    ):
        """Satellite regression: the fleet-level shed's retry_after_s
        aggregates the per-replica hints as a MAX over the replicas the
        routing consulted — never an invented constant smaller than a
        replica's own backpressure."""
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path,
            [["shed", "hold"], ["shed", "hold"]],
            [2.5, 0.5],
            max_inflight_per_replica=1,
            default_retry_after_s=0.25,
        )
        try:
            # One shed from each replica populates the hints: the
            # dispatch tie-break alternates 0 then 1.
            r0 = router.submit(_img(), _img()).result(timeout=10)
            assert r0.status == "shed" and r0.retry_after_s >= 2.5
            r1 = router.submit(_img(), _img()).result(timeout=10)
            assert r1.status == "shed"
            # Replica 1's own hint is 0.5, but the routing consulted
            # replica 0 too (hint 2.5): the aggregate must not be
            # smaller than EVERY consulted replica's hint.
            assert r1.retry_after_s >= 2.5
            router.submit(_img(), _img())  # held by replica 0 forever
            router.submit(_img(), _img())  # held by replica 1 forever
            # Both replicas at the inflight bound: the router sheds
            # BEFORE the socket, aggregating both hints.
            r2 = router.submit(_img(), _img()).result(timeout=10)
            assert r2.status == "shed"
            assert r2.detail.startswith("fleet at capacity")
            assert r2.retry_after_s >= max(2.5, 0.5)
            hints = router.report()["shed_hints"]
            assert r2.retry_after_s >= max(hints.values())
        finally:
            router.drain(timeout=0.2)
            [f.close() for f in fakes]

    def test_no_admittable_replica_sheds_honestly(self, tmp_path):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"]], [1.0],
        )
        try:
            sup.replicas[0].state = DEAD
            r = router.submit(_img(), _img()).result(timeout=10)
            assert r.status == "shed"
            assert "no admittable replica" in r.detail
            assert r.retry_after_s >= cfg.default_retry_after_s
        finally:
            router.drain(timeout=0.2)
            [f.close() for f in fakes]

    def test_tcp_transport_end_to_end_with_fakes(self, tmp_path):
        """The family swap is addresses, nothing else: the same router,
        supervisor handles, and fakes work over host:port with zero
        code branches in the test."""
        base = _free_base_port(2)
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"], ["ok"]], [1.0, 1.0],
            transport="tcp", base_port=base,
        )
        try:
            assert cfg.replica(0).address == f"127.0.0.1:{base}"
            assert Transport.parse(cfg.replica(1).address).port == base + 1
            rs = [
                router.submit(_img(), _img()).result(timeout=10)
                for _ in range(4)
            ]
            assert [r.status for r in rs] == ["ok"] * 4
            assert router.report()["per_replica_dispatched"] == {
                0: 2, 1: 2,
            }
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_shed_retry_after_scaled_by_scale_eta(self, tmp_path):
        """Satellite regression: a shed at min_replicas + all-busy must
        carry the autoscaler's time-to-READY estimate, not the 250ms
        default — a client told "retry in 250ms" during a cold compile
        just re-sheds; one told the ETA lands on the new capacity."""
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["hold"]], [1.0],
            max_inflight_per_replica=1,
            default_retry_after_s=0.25,
            min_replicas=1, max_replicas=2,
        )
        try:
            router.submit(_img(), _img())  # held forever: at capacity
            r = router.submit(_img(), _img()).result(timeout=10)
            assert r.status == "shed"
            assert r.retry_after_s == pytest.approx(0.25)
            # The autoscaler's published estimate floors every shed.
            router.set_scale_eta(12.5)
            r = router.submit(_img(), _img()).result(timeout=10)
            assert r.status == "shed"
            assert r.retry_after_s >= 12.5
            # Cleared (scale-up settled / calm): back to the default.
            router.set_scale_eta(None)
            r = router.submit(_img(), _img()).result(timeout=10)
            assert r.status == "shed"
            assert r.retry_after_s == pytest.approx(0.25)
            # End-to-end with the real loop: one tick under saturation
            # publishes the prior; the next shed carries it.
            from raft_ncup_tpu.fleet import FleetAutoscaler
            from raft_ncup_tpu.observability import Telemetry

            scaler = FleetAutoscaler(
                cfg, sup, router, telemetry=Telemetry(),
            )
            rec = scaler.tick()
            assert rec["occupancy"] == 1.0  # all-busy at min_replicas
            r = router.submit(_img(), _img()).result(timeout=10)
            assert r.status == "shed"
            assert r.retry_after_s >= cfg.scale_eta_prior_s
            assert r.retry_after_s >= scaler.time_to_ready_s()
            scaler.stop()
            r = router.submit(_img(), _img()).result(timeout=10)
            assert r.retry_after_s == pytest.approx(0.25)
        finally:
            router.drain(timeout=0.2)
            [f.close() for f in fakes]

    def test_stream_affinity_sticky_and_rendezvous(self, tmp_path):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"], ["ok"], ["ok"]], [1.0, 1.0, 1.0],
        )
        try:
            for fi in range(3):
                for s in ("sa", "sb", "sc", "sd"):
                    r = router.submit(
                        _img(), _img(), stream_id=s, frame_index=fi
                    ).result(timeout=10)
                    assert r.status == "ok"
            aff = router.report()["affinity"]
            # Sticky: every frame of a stream hit ONE replica.
            for s, home in aff.items():
                frames = [
                    h for f in fakes for h in f.seen
                    if h.get("stream_id") == s
                ]
                homes = {
                    i for i, f in enumerate(fakes)
                    if any(h.get("stream_id") == s for h in f.seen)
                }
                assert homes == {aff[s]}, (s, homes)
                assert len(frames) == 3
            # And the choice is the rendezvous hash over the live set.
            for s, home in aff.items():
                assert home == rendezvous_choice(s, [0, 1, 2])
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_rendezvous_minimal_movement(self):
        keys = [f"stream-{i}" for i in range(50)]
        before = {k: rendezvous_choice(k, [0, 1, 2]) for k in keys}
        after = {k: rendezvous_choice(k, [0, 2]) for k in keys}
        for k in keys:
            if before[k] != 1:
                # Only the dead replica's keys move.
                assert after[k] == before[k]

    def test_shape_aware_routing_prefers_warm_replica(self, tmp_path):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"], ["ok"]], [1.0, 1.0],
        )
        try:
            # Replica 1 advertises the padded shape for 32x48 as warm.
            sup.replicas[1].last_healthz = {
                "overall": "ready",
                "warmed": [[32, 48, 1, 2], [32, 48, 2, 2]],
            }
            for _ in range(3):
                r = router.submit(_img(), _img()).result(timeout=10)
                assert r.status == "ok"
            # Every request preferred the warm replica despite equal
            # load — the cold replica would pay a compile.
            assert router.report()["per_replica_dispatched"] == {
                0: 0, 1: 3,
            }
            # A shape NO replica has warmed falls back to least-loaded.
            r = router.submit(
                _img(40, 56), _img(40, 56)
            ).result(timeout=10)
            assert r.status == "ok"
            assert router.report()["per_replica_dispatched"][0] == 1
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_draining_replica_gets_nothing_new(self, tmp_path):
        from raft_ncup_tpu.fleet.replica import DRAINING

        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"], ["ok"]], [1.0, 1.0],
        )
        try:
            sup.replicas[0].state = DRAINING
            for _ in range(3):
                r = router.submit(_img(), _img()).result(timeout=10)
                assert r.status == "ok"
            assert router.report()["per_replica_dispatched"] == {
                0: 0, 1: 3,
            }
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_failover_on_death_redispatches_within_deadline(
        self, tmp_path
    ):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["hold"], ["ok"]], [1.0, 1.0],
        )
        try:
            # Pin the request to replica 0 (holds forever), then declare
            # it dead: the router must re-dispatch to replica 1 and the
            # client sees ONE ok — no silent drop, no double answer.
            sup.replicas[1].state = DEAD  # force routing to 0
            h = router.submit(_img(), _img(), deadline_s=30.0)
            time.sleep(0.1)
            sup.replicas[1].state = UP
            sup.replicas[0].state = DEAD
            router._on_replica_death(0, "test kill")
            r = h.result(timeout=10)
            assert r.status == "ok"
            assert router.stats["failovers"] == 1
        finally:
            router.drain(timeout=0.2)
            [f.close() for f in fakes]

    def test_failover_respects_deadline_and_budget(self, tmp_path):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["hold"], ["ok"]], [1.0, 1.0],
            max_failovers=1,
        )
        try:
            sup.replicas[1].state = DEAD
            # Deadline already unmeetable at death time: honest error,
            # zero re-dispatch.
            h = router.submit(_img(), _img(), deadline_s=0.05)
            time.sleep(0.15)
            sup.replicas[1].state = UP
            sup.replicas[0].state = DEAD
            router._on_replica_death(0, "test kill")
            r = h.result(timeout=10)
            assert r.status == "error"
            assert "deadline expired before failover" in r.detail
            assert router.stats["failovers"] == 0
            assert router.stats["failover_errors"] == 1
        finally:
            router.drain(timeout=0.2)
            [f.close() for f in fakes]

    def test_failover_with_no_survivor_sheds_honestly(self, tmp_path):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["hold"]], [1.0],
        )
        try:
            h = router.submit(_img(), _img(), deadline_s=30.0)
            time.sleep(0.1)
            sup.replicas[0].state = DEAD
            router._on_replica_death(0, "test kill")
            r = h.result(timeout=10)
            assert r.status == "shed"
            assert "no admittable replica" in r.detail
        finally:
            router.drain(timeout=0.2)
            [f.close() for f in fakes]

    def test_router_drain_sheds_new_and_errors_stuck(self, tmp_path):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["hold"]], [1.0],
        )
        try:
            h = router.submit(_img(), _img())
            out = router.drain(timeout=0.3)
            r = h.result(timeout=5)
            assert r.status == "error"  # bounded wait expired: explicit
            r2 = router.submit(_img(), _img()).result(timeout=5)
            assert r2.status == "shed" and "draining" in r2.detail
            assert out["stats"]["routed"] == 1
        finally:
            [f.close() for f in fakes]


class TestTracePropagation:
    """Cross-process tracing at the router (fast tier, fake replicas):
    one trace per request on the wire, the clock handshake, the per-hop
    histograms, and the fleet-wide telemetry toggle."""

    def test_dispatch_carries_one_trace_per_request(self, tmp_path):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"]], [1.0],
        )
        try:
            r1 = router.submit(_img(), _img()).result(timeout=10)
            r2 = router.submit(_img(), _img()).result(timeout=10)
            assert r1.status == r2.status == "ok"
            seen = fakes[0].seen
            assert len(seen) == 2
            from raft_ncup_tpu.observability import TraceContext

            ctxs = [TraceContext.from_wire(h.get("trace")) for h in seen]
            assert all(c is not None for c in ctxs)
            # Distinct requests, distinct traces; sender clock stamped.
            assert ctxs[0].trace_id != ctxs[1].trace_id
            assert all(c.sent_s is not None for c in ctxs)
            assert [c.span_id for c in ctxs] == [
                f"router-{h['id']}" for h in seen
            ]
            # The router's ring holds ONE root span per request, each
            # carrying its wire trace id verbatim.
            roots = router._tel.tracer.records("fleet_request")
            assert sorted(r["attrs"]["trace_id"] for r in roots) == \
                sorted(c.trace_id for c in ctxs)
            # …and the journey reassembles by trace id: root + dispatch.
            journey = router._tel.tracer.for_attr(
                trace_id=ctxs[0].trace_id
            )
            assert {r["name"] for r in journey} == {
                "fleet_dispatch", "fleet_request",
            }
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_handshake_offset_and_hop_histograms(self, tmp_path):
        """The ping/pong handshake lands a per-replica clock offset
        (≈0 on one host — both processes share CLOCK_MONOTONIC) and the
        response stamps produce non-negative per-hop histograms that
        surface in telemetry_report()['stages']."""
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"]], [1.0],
        )
        try:
            assert router.submit(_img(), _img()).result(
                timeout=10
            ).status == "ok"
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if router.clock_offsets():
                    break
                time.sleep(0.01)
            offsets = router.clock_offsets()
            assert set(offsets) == {0}
            assert abs(offsets[0]) < 0.25  # same host, same clock
            from raft_ncup_tpu.observability import telemetry_report

            stages = telemetry_report(router._tel)["stages"]
            for hop in ("fleet_hop_router_queue", "fleet_hop_wire",
                        "fleet_hop_replica", "fleet_hop_return",
                        "fleet_request"):
                assert hop in stages, sorted(stages)
                assert stages[hop]["count"] >= 1
                assert stages[hop]["p50_ms"] >= 0.0
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_set_fleet_telemetry_toggles_replicas_in_place(
        self, tmp_path
    ):
        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"], ["ok"]], [1.0, 1.0],
        )
        try:
            # Establish links first (the toggle rides live links).
            for _ in range(2):
                assert router.submit(_img(), _img()).result(
                    timeout=10
                ).status == "ok"
            acked = router.set_fleet_telemetry(False, timeout=5.0)
            assert acked == 2
            assert all(not f.telemetry_enabled for f in fakes)
            acked = router.set_fleet_telemetry(True, timeout=5.0)
            assert acked == 2
            assert all(f.telemetry_enabled for f in fakes)
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_router_drain_banks_tree_with_clock_offsets(self, tmp_path):
        """router.drain() dumps the router's half of the fleet trace
        tree — ring + clock offsets — where aggregate.py expects it."""
        from raft_ncup_tpu.observability import (
            Telemetry,
            collect_fleet_records,
            fleet_traces,
        )

        cfg = FleetConfig(base_dir=str(tmp_path), n_replicas=1)
        sup = ReplicaSupervisor(cfg, telemetry=Telemetry())
        fakes = [_FakeReplica(cfg.replica(0), ["ok"], 1.0)]
        sup.replicas[0].state = UP
        sup.replicas[0].last_healthz = {"overall": "ready"}
        tel = Telemetry(
            flight_dir=os.path.join(str(tmp_path), "router_flight")
        )
        router = FleetRouter(cfg, sup, telemetry=tel)
        try:
            assert router.submit(_img(), _img()).result(
                timeout=10
            ).status == "ok"
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not router.clock_offsets():
                time.sleep(0.01)
        finally:
            router.drain()
            [f.close() for f in fakes]
        collected = collect_fleet_records(str(tmp_path))
        assert "router" in collected["origins"]
        assert 0 in collected["clock_offsets"]
        traces = fleet_traces(collected)
        assert len(traces) == 1
        assert traces[0]["origins"] == ["router"]
        # The fake exported no ring (no real replica): it is a GAP the
        # tree names, not a silent absence.
        assert collected["gaps"] == [0]


class TestReplayFleetChaos:
    def test_faults_target_the_replica_that_carried_the_submission(
        self, tmp_path
    ):
        """The fleet chaos grammar's coordinate semantics: after
        submission N dispatches, killreplica@N / stallreplica@N /
        drainreplica@N hit the replica that CARRIED submission N —
        deterministic because routing is."""
        from raft_ncup_tpu.fleet import replay_fleet
        from raft_ncup_tpu.resilience.chaos import ChaosSpec

        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"], ["ok"]], [1.0, 1.0],
        )
        calls = []
        sup.kill = lambda i: calls.append(("kill", i))
        sup.stall = lambda i: calls.append(("stall", i))
        sup.drain = lambda i: calls.append(("drain", i)) or {}
        try:
            spec = ChaosSpec.parse(
                "killreplica@1,stallreplica@2,drainreplica@3"
            )
            items = [
                {"image1": _img(), "image2": _img()} for _ in range(4)
            ]
            handles = replay_fleet(
                router, items, supervisor=sup, chaos=spec,
            )
            assert len(handles) == 4
            for h in handles:
                assert h.result(timeout=10).status == "ok"
            time.sleep(0.1)  # drain thread records asynchronously
            got = {kind: i for kind, i in calls}
            assert set(got) == {"kill", "stall", "drain"}
            # Each fault's target is submission N's carrier.
            assert got["kill"] == router.replica_of(1)
            assert got["stall"] == router.replica_of(2)
            assert got["drain"] == router.replica_of(3)
        finally:
            router.drain()
            [f.close() for f in fakes]

    def test_host_kinds_target_the_carriers_host_via_manager(
        self, tmp_path
    ):
        """Fleet-scale grammar: partitionhost@N / killsupervisor@N hit
        the HOST of submission N's carrier, derived through the
        placement — the manager records the blast, traffic continues."""
        from raft_ncup_tpu.fleet import replay_fleet
        from raft_ncup_tpu.resilience.chaos import ChaosSpec

        cfg, sup, router, fakes = _fake_fleet(
            tmp_path, [["ok"], ["ok"]], [1.0, 1.0],
            hosts=("hA", "hB"),  # round-robin: 0 -> hA, 1 -> hB
        )

        class _RecordingManager:
            def __init__(self, cfg):
                self.cfg = cfg
                self.calls = []

            def host_of(self, i):
                return self.cfg.host_of(i)

            def partition(self, host):
                self.calls.append(("partition", host))

            def kill_agent(self, host):
                self.calls.append(("kill_agent", host))

        mgr = _RecordingManager(cfg)
        try:
            spec = ChaosSpec.parse("partitionhost@1,killsupervisor@2")
            items = [
                {"image1": _img(), "image2": _img()} for _ in range(4)
            ]
            handles = replay_fleet(
                router, items, chaos=spec, manager=mgr,
            )
            for h in handles:
                assert h.result(timeout=10).status == "ok"
            got = dict(mgr.calls)
            assert got["partition"] == cfg.host_of(router.replica_of(1))
            assert got["kill_agent"] == cfg.host_of(router.replica_of(2))
        finally:
            router.drain()
            [f.close() for f in fakes]


# --------------------------------------------- postmortem over a fleet


class TestFleetPostmortem:
    def _mk_dump(self, tel_dir, walltime, spans, trigger, **context):
        from raft_ncup_tpu.observability import Telemetry
        from raft_ncup_tpu.observability.flight import FlightRecorder

        tel = Telemetry()
        for name, attrs in spans:
            tel.event(name, **attrs)
        rec = FlightRecorder(tel_dir, walltime=lambda: walltime)
        path = rec.record(trigger, tel, **context)
        assert path is not None
        return path

    def test_selection_by_replica_and_latest_deterministic(
        self, tmp_path, capsys
    ):
        """Satellite: a fleet flight tree holds several replicas' dumps;
        selection is by replica subtree + latest-by-filename (never
        mtime), and the router-side correlation id attached at dispatch
        matches the replica-side span attrs — one --request_id
        reassembles the journey across the router hop."""
        import importlib.util

        base = tmp_path / "fleet_run"
        rid = 41
        # Replica 1: two dumps at different embedded timestamps; the
        # replica-side spans carry the ROUTER's request id (FlowServer
        # registered the request under it).
        d_old = self._mk_dump(
            str(base / "replica_1_flight"), 1_700_000_000.0,
            [("serve_request_quarantined", {"request_id": 999})],
            "poison_quarantine", request_id=999,
        )
        d_new = self._mk_dump(
            str(base / "replica_1_flight"), 1_700_000_100.0,
            [("serve_request_quarantined", {"request_id": rid,
                                            "batch_id": 3})],
            "poison_quarantine", request_id=rid,
        )
        # Replica 0 + the router's own failover dump referencing the
        # same id from the OTHER side of the hop.
        self._mk_dump(
            str(base / "replica_0_flight"), 1_700_000_050.0,
            [("serve_request_quarantined", {"request_id": 7})],
            "poison_quarantine", request_id=7,
        )
        self._mk_dump(
            str(base / "router_flight"), 1_700_000_060.0,
            [("fleet_dispatch", {"request_id": rid, "replica": 1})],
            "replica_failover", replica=1, request_ids=[rid],
        )
        # Deliberately scramble mtimes: selection must not read them.
        for root, _, files in os.walk(base):
            for i, f in enumerate(sorted(files)):
                os.utime(os.path.join(root, f), (1, 1 + i))

        spec = importlib.util.spec_from_file_location(
            "postmortem", os.path.join(_REPO, "scripts", "postmortem.py")
        )
        pm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pm)

        # --replica narrows to that subtree; latest wins by filename.
        assert pm.select_dump(str(base), replica=1) == d_new
        assert pm.select_dump(str(base), replica=1) == d_new  # stable
        with pytest.raises(FileNotFoundError):
            pm.select_dump(str(base), replica=9)

        # Full reassembly through the CLI: replica side of the hop...
        assert pm.main([str(base), "--replica", "1",
                        "--request_id", str(rid)]) == 0
        out = capsys.readouterr().out
        assert "serve_request_quarantined" in out
        assert f"request_id={rid}" in out
        # ...and the router side carries the SAME correlation id.
        router_dump = pm.select_dump(str(base / "router_flight"))
        from raft_ncup_tpu.observability import load_dump, match_records

        dump = load_dump(router_dump)
        matched = match_records(dump["spans"], request_id=rid)
        assert any(r["name"] == "fleet_dispatch" for r in matched)
        assert dump["context"]["request_ids"] == [rid]

    def test_selection_falls_back_past_torn_latest_dump(
        self, tmp_path, capsys
    ):
        """Satellite fix: a replica killed mid-run can leave the NEWEST
        file in its flight dir truncated (copies, foreign tooling —
        the recorder's own writes are atomic). Selection used to raise
        on it; now it warns and falls back to the newest PARSABLE
        dump."""
        import importlib.util

        base = tmp_path / "fleet_run"
        good = self._mk_dump(
            str(base / "replica_0_flight"), 1_700_000_000.0,
            [("serve_request_quarantined", {"request_id": 5})],
            "poison_quarantine", request_id=5,
        )
        torn = (base / "replica_0_flight" /
                "flight_preemption_drain_20990101T000000_9999.json")
        torn.write_text('{"flight_recorder_version": 1, "spans": [tru')

        spec = importlib.util.spec_from_file_location(
            "postmortem", os.path.join(_REPO, "scripts", "postmortem.py")
        )
        pm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pm)
        assert pm.select_dump(str(base), replica=0) == good
        err = capsys.readouterr().err
        assert "torn" in err
        # A tree with ONLY torn dumps still fails loudly, naming why.
        torn_only = tmp_path / "torn_only"
        (torn_only / "replica_0_flight").mkdir(parents=True)
        (torn_only / "replica_0_flight" /
         "flight_x_20990101T000000_0001.json").write_text("{")
        with pytest.raises(FileNotFoundError, match="torn"):
            pm.select_dump(str(torn_only), replica=0)


def _mesh_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # drop the conftest's 8-device flag
    return env


def _fleet_cfg(tmp_path, n=3, **kw):
    kw.setdefault("serve", ServeConfig(
        batch_sizes=(1, 2), iter_levels=(2,), queue_capacity=16,
    ))
    # idle_timeout_s is generous: on a loaded host the restart-backoff
    # wait between chaos phases can exceed the 30s default, and an
    # idle-evicted stream legitimately re-admits COLD — which would
    # make the bitwise reference comparison depend on wall-clock.
    kw.setdefault("stream", StreamConfig(
        capacity=4, iters=2, batch_sizes=(1, 2), frame_hw=(48, 64),
        max_frame_gap=10, idle_timeout_s=600.0,
    ))
    kw.setdefault("extra_args", ("--small", "--platform", "cpu"))
    kw.setdefault("snapshot_interval_s", 0.25)
    kw.setdefault("poll_interval_s", 0.05)
    return FleetConfig(
        base_dir=str(tmp_path / "fleet"), n_replicas=n,
        size_hw=(48, 64), **kw,
    )


@pytest.mark.slow
class TestFleetBlastRadius:
    """The acceptance chaos matrix against REAL serve.py replica
    processes: one 3-replica fleet serves mixed request+stream traffic
    through killreplica (SIGKILL mid-stream), drainreplica (SIGTERM
    contract), and stallreplica (healthz staleness) — with bitwise
    surviving-replica parity against an uninjected in-process reference
    and exact terminal-status accounting throughout."""

    def test_chaos_blast_radius_kill_drain_stall(self, tmp_path):
        from raft_ncup_tpu.observability import Telemetry
        from raft_ncup_tpu.resilience.chaos import ChaosSpec

        # The fleet chaos grammar rides the PR 5/6 machinery.
        spec = ChaosSpec.parse(
            "killreplica@0,drainreplica@1,stallreplica@2"
        )
        assert spec.active
        assert spec.kill_replica_at == frozenset({0})
        assert spec.drain_replica_at == frozenset({1})
        assert spec.stall_replica_at == frozenset({2})
        assert "killreplica@0" in spec.render()

        cfg = _fleet_cfg(
            tmp_path, n=3,
            max_restarts=1, restart_backoff_s=0.3,
            circuit_break_after=5,
        )
        tel = Telemetry(
            flight_dir=os.path.join(cfg.base_dir, "router_flight")
        )
        sup = ReplicaSupervisor(cfg, env=_mesh_env(), telemetry=tel)
        sup.start()
        router = FleetRouter(cfg, sup, telemetry=tel)
        rng = np.random.default_rng(7)
        streams = ("s0", "s1", "s2", "s3")
        frames = {
            s: [
                rng.uniform(0, 255, (48, 64, 3)).astype(np.float32)
                for _ in range(7)
            ]
            for s in streams
        }
        reqs = [
            rng.uniform(0, 255, (48, 64, 3)).astype(np.float32)
            for _ in range(2)
        ]
        results: dict = {}     # (stream, fi) -> FlowResponse
        carried: dict = {}     # (stream, fi) -> replica that answered
        req_results = []
        all_responses = []

        def submit_frame(s, fi, wait=True):
            with router._lock:
                rid = router._next_id
            h = router.submit(
                frames[s][fi], frames[s][fi + 1],
                stream_id=s, frame_index=fi,
            )
            if not wait:
                return h, rid
            r = h.result(timeout=180)
            results[(s, fi)] = r
            carried[(s, fi)] = router.replica_of(rid)
            all_responses.append(r)
            return r

        h_stuck = None
        try:
            # ---- phase 1: warm mixed traffic, sequential (every batch
            # is a single frame — bitwise-comparable to the reference).
            for fi in range(2):
                for s in streams:
                    assert submit_frame(s, fi).status == "ok"
            for img in reqs:
                r = router.submit(img, img).result(timeout=180)
                req_results.append(r)
                all_responses.append(r)
                assert r.status == "ok"
            aff = dict(router.report()["affinity"])
            assert set(aff.values()) <= {0, 1, 2}

            # ---- phase 2: killreplica (SIGKILL, not SIGTERM) with a
            # frame in flight: the victim is s0's home, suspended first
            # so the in-flight frame deterministically never answers.
            victim = aff["s0"]
            sup.replicas[victim].child.suspend()
            h_inflight, rid_inflight = submit_frame("s0", 2, wait=False)
            time.sleep(0.2)
            sup.kill(victim)  # SIGKILL; poll detects, router fails over
            r = h_inflight.result(timeout=180)
            results[("s0", 2)] = r
            carried[("s0", 2)] = router.replica_of(rid_inflight)
            all_responses.append(r)
            # The stranded mid-stream frame failed over and completed —
            # cold on the new home, never silently dropped.
            assert r.status == "ok"
            assert router.stats["failovers"] >= 1
            new_home = router.report()["affinity"]["s0"]
            assert new_home != victim
            assert carried[("s0", 2)] == new_home
            # s0 keeps streaming warm on its new home; batch-mates on
            # surviving replicas continue their chains untouched.
            assert submit_frame("s0", 3).status == "ok"
            for fi in (2, 3):
                for s in ("s1", "s2", "s3"):
                    assert submit_frame(s, fi).status == "ok"
            # Restart: bounded, counted, backed off — and it came back.
            _deadline = time.monotonic() + 60
            while time.monotonic() < _deadline:
                if sup.replicas[victim].state == UP:
                    break
                time.sleep(0.1)
            assert sup.replicas[victim].state == UP
            assert sup.replicas[victim].restarts == 1
            assert sup.replicas[victim].deaths == 1

            # ---- phase 3: drainreplica on a live home with work in
            # flight: zero in-flight losses, DRAINING observed in
            # healthz, exit 75.
            live_aff = router.report()["affinity"]
            drain_stream = next(
                s for s in ("s1", "s2", "s3")
                if sup.replicas[live_aff[s]].admittable()
            )
            survivor = live_aff[drain_stream]
            sup.replicas[survivor].child.suspend()
            h1, rid1 = submit_frame(drain_stream, 4, wait=False)
            h2, rid2 = submit_frame(drain_stream, 5, wait=False)
            time.sleep(0.2)
            sup.replicas[survivor].child.resume()
            out = sup.drain(survivor)
            assert out["observed_draining"] is True
            assert out["returncode"] == 75
            assert sup.replicas[survivor].contract_violations == []
            r1 = h1.result(timeout=180)
            r2 = h2.result(timeout=180)
            all_responses += [r1, r2]
            # Zero in-flight losses: both flushed through compute ON
            # the draining replica (the router observed DRAINING only
            # for NEW work).
            assert r1.status == "ok" and r2.status == "ok"
            results[(drain_stream, 4)] = r1
            results[(drain_stream, 5)] = r2
            carried[(drain_stream, 4)] = router.replica_of(rid1)
            carried[(drain_stream, 5)] = router.replica_of(rid2)
            assert carried[(drain_stream, 4)] == survivor
            # Its final report survived the reap, guard-clean.
            rep = out["report"]
            assert rep is not None and rep["interrupted"] is True
            assert rep["recompiles"] == 0
            assert rep["host_transfers"] == 0
            assert not sup.replicas[survivor].admittable()

            # ---- phase 4: stallreplica — the process LINGERS but the
            # heartbeat stops; the staleness contract (healthz older
            # than stale_after_s) declares it dead and SIGKILLs it.
            remaining = [
                h.index for h in sup.replicas if h.admittable()
            ]
            assert len(remaining) == 2
            target = remaining[0]
            sup.stall(target)
            assert sup.replicas[target].child.running  # lingering zombie
            h_stuck = router.submit(
                frames["s0"][0], frames["s0"][1],
                stream_id="stall_probe", frame_index=0,
            )
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if sup.replicas[target].stale_deaths >= 1:
                    break
                time.sleep(0.1)
            assert sup.replicas[target].stale_deaths == 1
            assert sup.replicas[target].deaths >= 1
            r = h_stuck.result(timeout=180)
            all_responses.append(r)
            # Stall probe either failed over (it was homed on the
            # stalled replica) or served normally — terminal either way.
            assert r.status in ("ok", "shed")
        finally:
            router.drain()
            reports = sup.stop()

        # ---- exact terminal-status accounting: every submission
        # reached exactly one terminal status (result() would have
        # raised otherwise), none silently dropped, none server-error.
        from raft_ncup_tpu.serving.request import TERMINAL_STATUSES

        assert all(r.status in TERMINAL_STATUSES for r in all_responses)
        n_ok = sum(1 for r in all_responses if r.status == "ok")
        assert n_ok >= len(results) + len(req_results)
        assert sum(
            1 for r in all_responses if r.status == "error"
        ) == 0

        # ---- the fleet flight tree tells the same story: the router
        # banked a replica_failover dump whose correlation ids match
        # the replica-side span attrs (postmortem reassembles across
        # the hop; fast-tier TestFleetPostmortem pins the selection
        # semantics on a synthetic tree).
        from raft_ncup_tpu.observability import load_dump, match_records

        router_dumps = [
            f for f in os.listdir(
                os.path.join(cfg.base_dir, "router_flight")
            )
            if f.startswith("flight_replica_failover_")
        ]
        assert router_dumps
        dump = load_dump(os.path.join(
            cfg.base_dir, "router_flight", sorted(router_dumps)[0]
        ))
        assert rid_inflight in dump["context"]["request_ids"]
        assert match_records(dump["spans"], request_id=rid_inflight)

        # ---- cross-process trace adoption (the tentpole acceptance,
        # pinned on this 4-process rig): stitch the run's exports —
        # the router_drain dump (full ring + handshake clock offsets)
        # against the replicas' own drain dumps — and require at least
        # one request whose ONE trace_id spans ≥ 2 processes, with
        # every per-hop delta non-negative under the clock handshake.
        from raft_ncup_tpu.observability import (
            collect_fleet_records,
            fleet_traces,
        )

        collected = collect_fleet_records(cfg.base_dir)
        assert "router" in collected["origins"]
        assert collected["replicas"], collected
        assert collected["clock_offsets"], (
            "router_drain dump carried no handshake offsets"
        )
        # Same host, shared CLOCK_MONOTONIC: every offset is near zero.
        assert all(
            abs(o) < 0.5 for o in collected["clock_offsets"].values()
        )
        traces = fleet_traces(collected)
        spanning = [t for t in traces if len(t["origins"]) >= 2]
        assert spanning, (
            f"no trace spans processes: "
            f"{[(t['trace_id'], t['origins']) for t in traces][:10]}"
        )
        probe = spanning[0]
        assert "router" in probe["origins"]
        assert any(
            o.startswith("replica_") for o in probe["origins"]
        )
        # One request -> exactly ONE trace.
        assert probe["request_id"] is not None
        assert len(fleet_traces(
            collected, request_id=probe["request_id"]
        )) == 1
        # Per-hop deltas exist and are non-negative; the replica-side
        # evidence (wire adoption span + queue wait) made it across.
        assert probe["hops"], probe
        assert all(v >= 0.0 for v in probe["hops"].values()), probe["hops"]
        spanning_hops = set().union(*(t["hops"] for t in spanning))
        assert {"wire_ms", "replica_queue_ms", "device_ms"} <= \
            spanning_hops, spanning_hops

        # ---- bitwise blast radius: every surviving-replica response
        # equals an UNINJECTED run. The reference is a fresh
        # single-replica fleet in the SAME environment (same argv, same
        # env, same deterministic PRNGKey(0) weights — an in-process
        # reference would differ in the last float bits because the
        # test process runs 8 virtual CPU devices). Each per-replica
        # segment replays under a fresh stream id: a fresh stream is
        # cold at the segment head, exactly what the re-homed replica's
        # engine saw; warm within.
        def segments(s):
            """Consecutive same-replica runs of a stream's answered
            frames, in frame order."""
            fis = sorted(fi for (ss, fi) in results if ss == s)
            segs = []
            for fi in fis:
                rep = carried[(s, fi)]
                if segs and segs[-1][0] == rep:
                    segs[-1][1].append(fi)
                else:
                    segs.append((rep, [fi]))
            return segs

        # Slot capacity covers every segment's fresh stream id at once
        # (the reference never closes streams); per-row numerics are
        # independent of the table size.
        ref_cfg = _fleet_cfg(
            tmp_path / "reference", n=1,
            stream=StreamConfig(
                capacity=12, iters=2, batch_sizes=(1, 2),
                frame_hw=(48, 64), max_frame_gap=10,
                idle_timeout_s=600.0,
            ),
        )
        ref_sup = ReplicaSupervisor(ref_cfg, env=_mesh_env())
        ref_sup.start()
        ref_router = FleetRouter(ref_cfg, ref_sup)
        try:
            for s in streams:
                for k, (rep_idx, fis) in enumerate(segments(s)):
                    sid = f"{s}#seg{k}"
                    for fi in fis:
                        rr = ref_router.submit(
                            frames[s][fi], frames[s][fi + 1],
                            stream_id=sid, frame_index=fi,
                        ).result(timeout=180)
                        assert rr.status == "ok"
                        np.testing.assert_array_equal(
                            results[(s, fi)].flow, rr.flow,
                            err_msg=f"{s} frame {fi} (replica "
                            f"{rep_idx}) diverged from the uninjected "
                            "reference",
                        )
            # Plain requests: stateless, one reference answer each.
            for img, fleet_r in zip(reqs, req_results):
                rr = ref_router.submit(img, img).result(timeout=180)
                assert rr.status == "ok"
                np.testing.assert_array_equal(fleet_r.flow, rr.flow)
        finally:
            ref_router.drain()
            ref_sup.stop()

        # Per-replica guard counters across every drained report: 0.
        for idx, rep in reports.items():
            body = rep.get("report")
            if body is not None:
                assert body.get("recompiles") == 0, (idx, body)
                assert body.get("host_transfers") == 0, (idx, body)


# ----------------------------------------------------- elastic fleet tier


def _proc_alive(pid):
    """True iff ``pid`` exists AND is not a zombie. ``os.kill(pid, 0)``
    succeeds on zombies, so fencing assertions must read the /proc stat
    state instead (a SIGKILLed orphan reparented to a non-reaping init
    lingers as Z forever)."""
    try:
        with open(f"/proc/{pid}/stat") as fh:
            stat = fh.read()
    except OSError:
        return False
    return stat.rpartition(")")[2].split()[0] != "Z"


@pytest.mark.slow
class TestElasticFleetChaos:
    """Fleet-scale chaos against a REAL multi-host TCP fleet: one
    HostSupervisor agent per named host supervising real serve.py
    replicas, a FleetManager mirroring their republishes over the wire.

    - ``partitionhost``: the manager stops hearing one host; the
      fleet-level staleness contract declares it dead, FENCES it
      (SIGKILLs the lingering pids so a zombie on the far side of the
      partition can never answer), and the router fails the stranded
      in-flight frame over to a survivor — with bitwise parity against
      an uninjected reference for everything the survivors answered.
    - ``killsupervisor``: SIGKILL the agent only; its replica lingers
      as a live orphan still heartbeating files nobody republishes —
      until the same staleness → fence path reaps it.
    - the autoscaler runs its whole elastic cycle on a real fleet:
      scale-down WITH work in flight on the victim (zero loss, exit
      75), then a load step forcing a scale-up through the pre-warm
      READY gate, then giving the capacity back when the burst clears.
    """

    def test_partitionhost_and_killsupervisor_fence_and_failover(
        self, tmp_path
    ):
        from raft_ncup_tpu.observability import Telemetry
        from raft_ncup_tpu.serving.request import TERMINAL_STATUSES

        # 3 hosts, one replica each (round-robin placement), TCP
        # transport: 3 replica ports + 3 agent control ports.
        base = _free_base_port(6)
        cfg = _fleet_cfg(
            tmp_path, n=3,
            hosts=("hA", "hB", "hC"),
            transport="tcp", base_port=base,
            # The per-replica staleness bound doubles as the FLEET
            # staleness bound; 2s keeps the orphan-heartbeat window
            # observable without slowing detection much.
            stale_after_factor=8,
            # No restarts: the agent's stale-kill of the suspended
            # victim must not respawn a replica the fence would then
            # miss (its pid would postdate the last republish).
            max_restarts=0,
        )
        assert [cfg.host_of(i) for i in range(3)] == ["hA", "hB", "hC"]
        tel = Telemetry(
            flight_dir=os.path.join(cfg.base_dir, "router_flight")
        )
        manager = FleetManager(cfg, env=_mesh_env(), telemetry=tel)
        manager.start()
        router = FleetRouter(cfg, manager, telemetry=tel)

        rng = np.random.default_rng(11)
        streams = ("sa", "sb", "sc", "sd")
        frames = {
            s: [
                rng.uniform(0, 255, (48, 64, 3)).astype(np.float32)
                for _ in range(7)
            ]
            for s in streams
        }
        results: dict = {}   # (stream, fi) -> FlowResponse
        carried: dict = {}   # (stream, fi) -> replica that answered
        all_responses = []

        def submit_frame(s, fi, wait=True):
            with router._lock:
                rid = router._next_id
            h = router.submit(
                frames[s][fi], frames[s][fi + 1],
                stream_id=s, frame_index=fi,
            )
            if not wait:
                return h, rid
            r = h.result(timeout=180)
            results[(s, fi)] = r
            carried[(s, fi)] = router.replica_of(rid)
            all_responses.append(r)
            return r

        def wait_host_dead(host, deadline_s=90):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if host in manager.report()["dead_hosts"]:
                    return
                time.sleep(0.1)
            raise AssertionError(
                f"host {host!r} never declared dead: "
                f"{manager.report()}"
            )

        try:
            # ---- warm: every stream answers over TCP on all 3 hosts.
            for fi in range(2):
                for s in streams:
                    assert submit_frame(s, fi).status == "ok"
            aff = dict(router.report()["affinity"])
            assert set(aff.values()) <= {0, 1, 2}

            # ---- partitionhost on sa's home, with a frame pinned in
            # flight there: SIGSTOP the remote replica (its healthz
            # goes stale, so the partitioned host's OWN agent stale-
            # kills it — the real per-replica contract running on the
            # far side), then cut the manager's control link.
            victim = aff["sa"]
            vhost = manager.host_of(victim)
            vpid = manager.handle(victim).remote_pid
            assert isinstance(vpid, int) and _proc_alive(vpid)
            os.kill(vpid, signal.SIGSTOP)
            h_inflight, rid_inflight = submit_frame("sa", 2, wait=False)
            time.sleep(0.2)
            manager.partition(vhost)

            # The stranded frame failed over and completed — cold on a
            # survivor, never silently dropped.
            r = h_inflight.result(timeout=180)
            results[("sa", 2)] = r
            carried[("sa", 2)] = router.replica_of(rid_inflight)
            all_responses.append(r)
            assert r.status == "ok"
            assert carried[("sa", 2)] != victim
            assert router.stats["failovers"] >= 1

            # Fleet-level staleness declared the silent host dead and
            # fenced it: replica pid gone (or zombie), agent killed.
            wait_host_dead(vhost)
            rep = manager.report()
            assert rep["partitioned_hosts"] == [vhost]
            assert manager.handle(victim).state == DEAD
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and _proc_alive(vpid):
                time.sleep(0.1)
            assert not _proc_alive(vpid), (
                f"fenced replica pid {vpid} still running"
            )
            assert not manager.agents[vhost].running

            # Traffic continues on the survivors.
            assert submit_frame("sa", 3).status == "ok"
            for fi in (2, 3):
                for s in ("sb", "sc", "sd"):
                    assert submit_frame(s, fi).status == "ok"

            # ---- killsupervisor on a SURVIVING host: the agent dies,
            # its replica lingers as a live orphan, still heartbeating
            # a healthz file nobody republishes anymore.
            live = [
                h.index for h in manager.replicas if h.state == UP
            ]
            assert len(live) == 2
            orphan_idx = live[0]
            ohost = manager.host_of(orphan_idx)
            assert ohost != vhost
            opid = manager.handle(orphan_idx).remote_pid
            assert isinstance(opid, int)
            hz1 = read_healthz(cfg.replica(orphan_idx).healthz_path)
            assert hz1 is not None
            manager.kill_agent(ohost)
            assert not manager.agents[ohost].running
            assert _proc_alive(opid)  # orphaned, not dead
            time.sleep(0.6)
            hz2 = read_healthz(cfg.replica(orphan_idx).healthz_path)
            assert hz2["time_unix_s"] > hz1["time_unix_s"], (
                "the orphan stopped heartbeating — it should outlive "
                "its supervisor until the fleet staleness reap"
            )

            # Staleness → host death → fence: the orphan is reaped
            # (SIGKILLed; dead or an unreaped zombie, never serving).
            wait_host_dead(ohost)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and _proc_alive(opid):
                time.sleep(0.1)
            assert not _proc_alive(opid), (
                f"orphan replica pid {opid} survived the fence"
            )
            assert manager.handle(orphan_idx).state == DEAD
            assert sorted(manager.report()["dead_hosts"]) == sorted(
                [vhost, ohost]
            )

            # The last replica standing carries everything.
            for fi in (4, 5):
                for s in streams:
                    assert submit_frame(s, fi).status == "ok"
            last = {
                h.index for h in manager.replicas if h.state == UP
            }
            assert len(last) == 1
            assert set(router.report()["affinity"].values()) == last
        finally:
            router.drain()
            manager.stop()

        # ---- exact terminal-status accounting: every submission
        # reached a terminal status, zero lost, zero server-error.
        assert all(r.status in TERMINAL_STATUSES for r in all_responses)
        assert len(all_responses) == 24
        assert all(r.status == "ok" for r in all_responses)
        assert sum(
            1 for r in all_responses if r.status == "error"
        ) == 0

        # ---- bitwise surviving-replica parity: per-stream segments
        # replayed against an UNINJECTED single-replica UDS reference
        # (fresh stream id per segment: a re-homed replica admits the
        # stream cold at the segment head, warm within — PR 13's
        # pinned semantics, now across the TCP transport).
        def segments(s):
            fis = sorted(fi for (ss, fi) in results if ss == s)
            segs = []
            for fi in fis:
                rep_idx = carried[(s, fi)]
                if segs and segs[-1][0] == rep_idx:
                    segs[-1][1].append(fi)
                else:
                    segs.append((rep_idx, [fi]))
            return segs

        ref_cfg = _fleet_cfg(
            tmp_path / "reference", n=1,
            stream=StreamConfig(
                capacity=12, iters=2, batch_sizes=(1, 2),
                frame_hw=(48, 64), max_frame_gap=10,
                idle_timeout_s=600.0,
            ),
        )
        ref_sup = ReplicaSupervisor(ref_cfg, env=_mesh_env())
        ref_sup.start()
        ref_router = FleetRouter(ref_cfg, ref_sup)
        try:
            for s in streams:
                for k, (rep_idx, fis) in enumerate(segments(s)):
                    sid = f"{s}#seg{k}"
                    for fi in fis:
                        rr = ref_router.submit(
                            frames[s][fi], frames[s][fi + 1],
                            stream_id=sid, frame_index=fi,
                        ).result(timeout=180)
                        assert rr.status == "ok"
                        np.testing.assert_array_equal(
                            results[(s, fi)].flow, rr.flow,
                            err_msg=f"{s} frame {fi} (replica "
                            f"{rep_idx}) diverged from the uninjected "
                            "reference",
                        )
        finally:
            ref_router.drain()
            ref_sup.stop()

    def test_autoscaler_elastic_cycle_on_real_fleet_zero_loss(
        self, tmp_path
    ):
        from raft_ncup_tpu.observability import Telemetry
        from raft_ncup_tpu.serving.request import TERMINAL_STATUSES

        base = _free_base_port(2)
        cfg = _fleet_cfg(
            tmp_path, n=2, transport="tcp", base_port=base,
            min_replicas=1, max_replicas=2,
            scale_hysteresis_ticks=2, scale_cooldown_s=2.0,
            max_inflight_per_replica=4,
            # Suspensions below must not trip the per-replica
            # staleness contract — this test is about elasticity.
            stale_after_factor=480,
        )
        tel = Telemetry()
        sup = ReplicaSupervisor(cfg, env=_mesh_env(), telemetry=tel)
        sup.start()
        router = FleetRouter(cfg, sup, telemetry=tel)
        # REAL spawn/drain paths: add_replica / threaded
        # remove_replica, real clock, manual ticks.
        sc = FleetAutoscaler(cfg, sup, router, telemetry=tel)
        rng = np.random.default_rng(13)
        img = rng.uniform(0, 255, (48, 64, 3)).astype(np.float32)
        all_responses = []

        try:
            # ---- phase A: scale-down UNDER LOAD. Suspend both
            # replicas so one request pins in flight on each; two calm
            # ticks (occupancy 2/8 = 0.25) decide "down"; the victim
            # is the NEWEST of the least-loaded tie — slot 1, which
            # holds an in-flight request the drain must flush.
            for h in sup.replicas:
                h.child.suspend()
            h1 = router.submit(img, img)
            h2 = router.submit(img, img)
            assert router.inflight_of(0) == 1
            assert router.inflight_of(1) == 1
            t1 = sc.tick()
            assert (t1["decision"], t1["reason"]) == (
                "hold", "hysteresis 1/2"
            )
            t2 = sc.tick()
            assert t2["decision"] == "down"
            assert t2["reason"].startswith("draining slot 1")
            for h in sup.replicas:
                h.child.resume()
            r1 = h1.result(timeout=180)
            r2 = h2.result(timeout=180)
            all_responses += [r1, r2]
            # ZERO in-flight loss through the scale-down.
            assert r1.status == "ok" and r2.status == "ok", (
                r1.status, r1.detail, r2.status, r2.detail,
            )

            deadline = time.monotonic() + 150
            while (time.monotonic() < deadline
                   and sc.report()["scale_downs"] < 1):
                sc.tick()
                time.sleep(0.2)
            assert sc.report()["scale_downs"] == 1
            retired = sup.retired[-1]
            assert retired.index == 1
            # The drain contract held: DRAINING observed, exit 75,
            # no violations recorded.
            assert retired.contract_violations == []
            assert retired.child.returncode == 75
            assert [h.index for h in sup.replicas] == [0]

            # The floor is pinned: calm forever, still 1 replica.
            sc.tick()
            t_floor = sc.tick()
            assert t_floor["decision"] == "hold"
            assert t_floor["reason"] == "at min_replicas (1)"

            # ---- phase B: a load step forces a scale-up through the
            # pre-warm READY gate. Sustained arrivals beat one
            # replica's service rate: occupancy saturates, the
            # overflow sheds, and the autoscaler re-spawns slot 1.
            time.sleep(2.1)  # cooldown since the scale-down
            stop_load = threading.Event()
            surge = threading.Event()  # high rate until the up fires
            surge.set()
            load_handles = []

            def _load():
                # The step must decisively beat one replica's service
                # rate (the admission cap bounds the socket pressure;
                # the overflow sheds at the router) — then throttle
                # once the decision fired, keeping the warming window
                # under load without flooding the accounting.
                while not stop_load.is_set():
                    load_handles.append(router.submit(img, img))
                    load_handles.append(router.submit(img, img))
                    time.sleep(0.004 if surge.is_set() else 0.05)

            lt = threading.Thread(target=_load, daemon=True)
            lt.start()
            saw_up = saw_warming_hold = probed = False
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                rec = sc.tick()
                if rec["decision"] == "up":
                    saw_up = True
                    surge.clear()
                    assert rec["reason"].startswith("spawned slot 1")
                if (rec["decision"] == "hold"
                        and rec["n_spawning"] == 1):
                    saw_warming_hold = (
                        "topology change in flight" in rec["reason"]
                        or saw_warming_hold
                    )
                    # Backpressure honesty while capacity warms: the
                    # ETA is published, so sheds answer "retry when
                    # the new replica can admit".
                    assert rec["eta_published"] is True
                    if not probed:
                        probed = True
                        st_before = sup.handle(1).state
                        with router._lock:
                            rid = router._next_id
                        pr = router.submit(img, img).result(
                            timeout=180
                        )
                        all_responses.append(pr)
                        if (pr.status == "ok"
                                and st_before == SPAWNING):
                            # READY gate: cold capacity never takes
                            # traffic before its warmed executable
                            # set is advertised.
                            assert router.replica_of(rid) != 1
                if sc.report()["scale_ups_completed"] >= 1:
                    break
                time.sleep(0.2)
            stop_load.set()
            lt.join(timeout=10)

            rep = sc.report()
            assert saw_up and rep["scale_ups"] == 1
            assert rep["scale_ups_completed"] == 1
            assert rep["failed_scale_ups"] == 0
            assert rep["breaker_open"] is False
            assert rep["time_to_ready_observed"] == 1
            assert rep["time_to_ready_s"] > 0
            assert sup.handle(1).state == UP

            # Every load-step submission is terminal: ok or an honest
            # shed (with the warming ETA floor), never lost.
            shed_hints = []
            n_ok = n_shed = 0
            for h in load_handles:
                r = h.result(timeout=300)
                all_responses.append(r)
                if r.status == "ok":
                    n_ok += 1
                elif r.status == "shed":
                    n_shed += 1
                    shed_hints.append(r.retry_after_s)
                else:
                    raise AssertionError(f"lost/errored: {r}")
            assert n_ok + n_shed == len(load_handles)
            assert n_ok >= 1
            assert any(
                hint >= cfg.scale_eta_prior_s for hint in shed_hints
            ), (
                "no shed carried the time-to-READY floor while "
                f"capacity warmed: {sorted(shed_hints)[-5:]}"
            )

            # The re-spawned replica takes traffic once READY.
            carriers = set()
            for _ in range(4):
                with router._lock:
                    rid = router._next_id
                r = router.submit(img, img).result(timeout=180)
                all_responses.append(r)
                assert r.status == "ok"
                carriers.add(router.replica_of(rid))
            assert 1 in carriers

            # ---- phase C: the burst is over — the loop gives the
            # capacity back (down to the floor), then clears the ETA.
            # Everything is resolved: no outstanding dispatches anywhere.
            assert router.inflight_of(0) == 0
            assert router.inflight_of(1) == 0
            deadline = time.monotonic() + 150
            while (time.monotonic() < deadline
                   and sc.report()["scale_downs"] < 2):
                sc.tick()
                time.sleep(0.2)
            assert sc.report()["scale_downs"] == 2, list(sc.decisions)[-8:]
            assert [h.index for h in sup.replicas] == [0], (
                list(sc.decisions)[-8:]
            )
            rec = sc.tick()
            assert rec["eta_published"] is False
            assert router._scale_eta_s is None
        finally:
            sc.stop()
            router.drain()
            sup.stop()

        assert all(r.status in TERMINAL_STATUSES for r in all_responses)
        assert sum(
            1 for r in all_responses if r.status == "error"
        ) == 0
