"""Tests for flow file I/O, visualization, and warm-start interpolation."""

import numpy as np
import pytest

from raft_ncup_tpu.io import (
    read_flo,
    read_flow_kitti,
    read_gen,
    read_image,
    read_pfm,
    write_flo,
    write_flow_kitti,
    write_pfm,
)
from raft_ncup_tpu.ops.warmstart import forward_interpolate
from raft_ncup_tpu.viz import flow_to_image, make_colorwheel


@pytest.fixture
def rng():
    return np.random.default_rng(0)


class TestFlo:
    def test_roundtrip(self, tmp_path, rng):
        flow = rng.normal(size=(17, 23, 2)).astype(np.float32)
        path = tmp_path / "a.flo"
        write_flo(path, flow)
        np.testing.assert_array_equal(read_flo(path), flow)

    def test_bytes_layout(self, tmp_path):
        # magic, w, h header then row-major interleaved (u, v) float32.
        flow = np.zeros((2, 3, 2), np.float32)
        flow[0, 1] = (5.0, -7.0)
        path = tmp_path / "a.flo"
        write_flo(path, flow)
        raw = path.read_bytes()
        assert np.frombuffer(raw[:4], "<f4")[0] == pytest.approx(202021.25)
        assert np.frombuffer(raw[4:12], "<i4").tolist() == [3, 2]
        body = np.frombuffer(raw[12:], "<f4")
        assert body[2] == 5.0 and body[3] == -7.0

    def test_bad_magic(self, tmp_path):
        path = tmp_path / "bad.flo"
        path.write_bytes(b"\x00" * 32)
        with pytest.raises(ValueError, match="magic"):
            read_flo(path)

    def test_read_gen_dispatch(self, tmp_path, rng):
        flow = rng.normal(size=(6, 8, 2)).astype(np.float32)
        path = tmp_path / "x.flo"
        write_flo(path, flow)
        np.testing.assert_array_equal(read_gen(path), flow)


class TestPfm:
    def test_roundtrip_gray(self, tmp_path, rng):
        data = rng.normal(size=(11, 7)).astype(np.float32)
        path = tmp_path / "a.pfm"
        write_pfm(path, data)
        np.testing.assert_array_equal(read_pfm(path), data)

    def test_roundtrip_color(self, tmp_path, rng):
        data = rng.normal(size=(5, 9, 3)).astype(np.float32)
        path = tmp_path / "a.pfm"
        write_pfm(path, data)
        np.testing.assert_array_equal(read_pfm(path), data)

    def test_read_gen_drops_third_channel(self, tmp_path, rng):
        data = rng.normal(size=(5, 9, 3)).astype(np.float32)
        path = tmp_path / "a.pfm"
        write_pfm(path, data)
        out = read_gen(path)
        assert out.shape == (5, 9, 2)
        np.testing.assert_array_equal(out, data[:, :, :2])

    def test_rows_bottom_up(self, tmp_path):
        # First stored row must be the image's bottom row.
        data = np.arange(12, dtype=np.float32).reshape(4, 3)
        path = tmp_path / "a.pfm"
        write_pfm(path, data)
        raw = path.read_bytes()
        body_off = len(raw) - 4 * 12
        first_stored = np.frombuffer(raw[body_off : body_off + 12], "<f4")
        np.testing.assert_array_equal(first_stored, data[-1])


class TestKitti:
    def test_roundtrip(self, tmp_path, rng):
        # Representable values are multiples of 1/64 within +-512.
        flow = (
            rng.integers(-512 * 64, 512 * 64, size=(10, 14, 2)) / 64.0
        ).astype(np.float32)
        path = tmp_path / "f.png"
        write_flow_kitti(path, flow)
        back, valid = read_flow_kitti(path)
        np.testing.assert_allclose(back, flow, atol=1e-6)
        np.testing.assert_array_equal(valid, np.ones((10, 14), np.float32))


class TestReadImage:
    def test_grayscale_broadcast(self, tmp_path):
        from PIL import Image

        img = Image.fromarray(np.arange(20, dtype=np.uint8).reshape(4, 5))
        path = tmp_path / "g.png"
        img.save(path)
        out = read_image(path)
        assert out.shape == (4, 5, 3)
        np.testing.assert_array_equal(out[..., 0], out[..., 2])


class TestFlowViz:
    def test_wheel_shape_and_anchors(self):
        wheel = make_colorwheel()
        assert wheel.shape == (55, 3)
        np.testing.assert_array_equal(wheel[0], [255, 0, 0])  # pure red
        # Wheel ramps stay in [0, 255].
        assert wheel.min() >= 0 and wheel.max() <= 255

    def test_zero_flow_is_white(self):
        img = flow_to_image(np.zeros((4, 4, 2), np.float32))
        assert img.shape == (4, 4, 3)
        np.testing.assert_array_equal(img, np.full((4, 4, 3), 255, np.uint8))

    def test_leftward_motion_maps_to_cyan_blue(self):
        # u=-10, v=0: arctan2(-v,-u)=0 -> fk=(0+1)/2*54=27 -> CB segment
        # (wheel[27] = (0, 209, 255)).
        flow = np.zeros((2, 2, 2), np.float32)
        flow[0, 0] = (-10.0, 0.0)
        img = flow_to_image(flow)
        r, g, b = img[0, 0]
        assert b == 255 and r == 0 and 200 <= g <= 215

    def test_unknown_flow_black(self):
        flow = np.zeros((2, 2, 2), np.float32)
        flow[1, 1] = (1e8, 0.0)
        img = flow_to_image(flow)
        np.testing.assert_array_equal(img[1, 1], [0, 0, 0])

    def test_bgr_flag_reverses_channels(self):
        flow = np.zeros((2, 2, 2), np.float32)
        flow[0, 0] = (-3.0, 1.0)
        rgb = flow_to_image(flow)
        bgr = flow_to_image(flow, convert_to_bgr=True)
        np.testing.assert_array_equal(rgb[..., ::-1], bgr)

    def test_fixed_rad_max(self):
        flow = np.full((3, 3, 2), 0.5, np.float32)
        a = flow_to_image(flow, rad_max=100.0)
        # Tiny motion w.r.t. fixed scale -> near-white.
        assert a.min() > 240


class TestForwardInterpolate:
    def test_zero_flow_fixed_point(self):
        flow = np.zeros((6, 8, 2), np.float32)
        np.testing.assert_array_equal(forward_interpolate(flow), flow)

    def test_constant_flow_propagates(self):
        flow = np.full((8, 12, 2), 2.0, np.float32)
        out = forward_interpolate(flow)
        # Every queried pixel's nearest splat carries the same value.
        np.testing.assert_allclose(out, flow)

    def test_all_out_of_bounds_gives_zeros(self):
        flow = np.full((4, 4, 2), 100.0, np.float32)
        out = forward_interpolate(flow)
        np.testing.assert_array_equal(out, np.zeros_like(flow))

    def test_matches_griddata_reference(self):
        # Independent check against scipy.interpolate.griddata nearest,
        # the reference's exact algorithm (core/utils/utils.py:49-53).
        from scipy import interpolate as si

        rng = np.random.default_rng(3)
        flow = rng.normal(scale=3.0, size=(10, 11, 2)).astype(np.float32)
        ht, wd = flow.shape[:2]
        dx, dy = flow[..., 0], flow[..., 1]
        x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))
        x1, y1 = (x0 + dx).ravel(), (y0 + dy).ravel()
        valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
        ref_x = si.griddata(
            (x1[valid], y1[valid]), dx.ravel()[valid], (x0, y0),
            method="nearest",
        )
        ref_y = si.griddata(
            (x1[valid], y1[valid]), dy.ravel()[valid], (x0, y0),
            method="nearest",
        )
        out = forward_interpolate(flow)
        np.testing.assert_allclose(out[..., 0], ref_x, atol=1e-6)
        np.testing.assert_allclose(out[..., 1], ref_y, atol=1e-6)


class TestFlowToColorSecondWheel:
    """The VCN-derived second colorwheel (viz/flow_viz.flow_to_color)
    must agree with flow_to_image EXACTLY on shared inputs — the
    reference shipped two implementations of the same map, and the port
    must not have forked them (VERDICT r5 missing #2-#3; the reference's
    th_rmse/th_epe metric helpers map onto inference/metrics.py's
    accumulators, see both module docstrings)."""

    def test_matches_flow_to_image_on_shared_inputs(self):
        from raft_ncup_tpu.viz import flow_to_color, flow_to_image

        for seed in range(3):
            g = np.random.default_rng(seed)
            flow = g.normal(0, 10.0, (31, 45, 2)).astype(np.float32)
            flow[0, 0] = 5e7  # unknown-flow pixel zeroes out
            np.testing.assert_array_equal(
                flow_to_color(flow), flow_to_image(flow)
            )

    def test_bgr_and_fixed_scale_variants_agree(self):
        from raft_ncup_tpu.viz import flow_to_color, flow_to_image

        g = np.random.default_rng(7)
        flow = g.normal(0, 4.0, (16, 20, 2)).astype(np.float32)
        np.testing.assert_array_equal(
            flow_to_color(flow, convert_to_bgr=True),
            flow_to_image(flow, convert_to_bgr=True),
        )
        np.testing.assert_array_equal(
            flow_to_color(flow, rad_max=30.0),
            flow_to_image(flow, rad_max=30.0),
        )

    def test_rejects_bad_shape(self):
        from raft_ncup_tpu.viz import flow_to_color

        with pytest.raises(ValueError):
            flow_to_color(np.zeros((4, 4, 3), np.float32))
