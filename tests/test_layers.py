"""Layer-level parity tests (conv transpose, norms, frozen BN)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch
import torch.nn.functional as F

from raft_ncup_tpu.nn.layers import Conv2d, ConvTranspose2d, Norm


def test_conv_transpose_matches_torch():
    rng = np.random.default_rng(0)
    N, Cin, Cout, H, W, k, s = 2, 3, 5, 4, 6, 2, 2
    x = rng.standard_normal((N, H, W, Cin)).astype(np.float32)
    mod = ConvTranspose2d(Cout, k, stride=s, use_bias=False)
    v = mod.init(jax.random.key(0), jnp.asarray(x))
    ours = np.asarray(mod.apply(v, jnp.asarray(x)))

    # Same weights into torch: ours (kh, kw, out, in) -> torch (in, out, kh, kw).
    w = np.asarray(v["params"]["kernel"]).transpose(3, 2, 0, 1)
    theirs = (
        F.conv_transpose2d(
            torch.from_numpy(x.transpose(0, 3, 1, 2)), torch.from_numpy(w), stride=s
        )
        .permute(0, 2, 3, 1)
        .numpy()
    )
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_instance_norm_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 6, 5, 8)).astype(np.float32)
    mod = Norm("instance")
    v = mod.init(jax.random.key(0), jnp.asarray(x))
    ours = np.asarray(mod.apply(v, jnp.asarray(x)))
    theirs = (
        torch.nn.InstanceNorm2d(8)(torch.from_numpy(x.transpose(0, 3, 1, 2)))
        .permute(0, 2, 3, 1)
        .numpy()
    )
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_group_norm_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 6, 5, 8)).astype(np.float32)
    mod = Norm("group", num_groups=2)
    v = mod.init(jax.random.key(0), jnp.asarray(x))
    ours = np.asarray(mod.apply(v, jnp.asarray(x)))
    theirs = (
        torch.nn.GroupNorm(2, 8)(torch.from_numpy(x.transpose(0, 3, 1, 2)))
        .permute(0, 2, 3, 1)
        .detach()
        .numpy()
    )
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_batch_norm_train_and_frozen():
    """train=True updates stats; train=False (frozen BN) runs off running
    averages without requiring a mutable collection."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 6, 5, 3)).astype(np.float32) * 2 + 1)
    mod = Norm("batch")
    v = mod.init(jax.random.key(0), x)

    # Frozen: stats unused-updated; apply must not demand mutability.
    out_frozen = mod.apply(v, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out_frozen),
        np.asarray(x) / np.sqrt(1 + 1e-5),
        atol=1e-4,
    )

    out_train, mut = mod.apply(v, x, train=True, mutable=["batch_stats"])
    new_mean = np.asarray(
        jax.tree.leaves(mut["batch_stats"])[0]
    )
    assert np.abs(new_mean).max() > 0  # stats moved toward batch mean


def test_conv2d_torch_default_init_range():
    """torch kaiming_uniform(a=sqrt(5)) => bound sqrt(1/fan_in)."""
    mod = Conv2d(8, 3)
    v = mod.init(jax.random.key(0), jnp.zeros((1, 8, 8, 4)))
    k = np.asarray(v["params"]["kernel"])
    bound = np.sqrt(1.0 / (4 * 9))
    assert k.min() >= -bound and k.max() <= bound
    assert k.std() > bound / 3  # roughly uniform, not degenerate
