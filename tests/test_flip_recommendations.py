"""Kernel-default recommendation logic (scripts/flip_recommendations.py).

The ritual's last stage turns a bench record into flip/keep verdicts for
``corr_impl`` and ``RAFT_NCUP_NCONV_IMPL``; these pin the decision rules
so the one short live-chip window cannot hit a regressed recommender.
"""

import importlib.util
import os
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "flip_recommendations",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "flip_recommendations.py",
    ),
)
flip = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(flip)


def _tpu(**kw):
    rec = {"value": 100.0, "baseline_key": "tpu@v5e:volume:2x368x768x12"}
    rec.update(kw)
    return rec


class TestRecommend:
    def test_cpu_record_never_flips(self):
        lines = flip.recommend(
            {"value": 9.0, "baseline_key": "cpu@host:volume:1x96x128x4",
             "pairs_per_sec_onthefly": 20.0}
        )
        assert len(lines) == 1 and "defaults stay" in lines[0]

    def test_corr_flip_requires_margin(self):
        # 2% win: below the 3% margin -> keep.
        lines = flip.recommend(_tpu(pairs_per_sec_onthefly=102.0))
        assert any("keep 'volume'" in l for l in lines)
        lines = flip.recommend(_tpu(pairs_per_sec_pallas=110.0))
        assert any("FLIP default 'volume' -> 'pallas'" in l for l in lines)

    def test_partial_nconv_fusion_blocks_flip(self):
        lines = flip.recommend(
            _tpu(pairs_per_sec_nconv_pallas=150.0, nconv_pallas_calls="2/12")
        )
        joined = "\n".join(lines)
        assert "PARTIALLY fused" in joined and "do NOT flip" in joined
        assert "FLIP default 'xla'" not in joined

    def test_full_nconv_fusion_flips_on_win(self):
        lines = flip.recommend(
            _tpu(pairs_per_sec_nconv_pallas=150.0, nconv_pallas_calls="12/12")
        )
        assert any("FLIP default 'xla' -> 'pallas'" in l for l in lines)

    def test_fell_back_row_keeps_xla(self):
        lines = flip.recommend(
            _tpu(pairs_per_sec_nconv_pallas_FELL_BACK_TO_XLA=150.0)
        )
        assert any("fell back to XLA" in l for l in lines)

    def test_corr_partial_levels_annotated(self):
        lines = flip.recommend(
            _tpu(pairs_per_sec_pallas=180.0, corr_pallas_levels="2/4")
        )
        assert any("2/4 pyramid levels" in l for l in lines)

    def test_missing_volume_row_skips_corr_comparison(self):
        # A watchdog-killed primary attempt can leave variant rows only:
        # no crash, no flip, an explicit "no volume baseline" verdict.
        lines = flip.recommend(
            _tpu(value=None, pairs_per_sec_onthefly=120.0)
        )
        joined = "\n".join(lines)
        assert "no volume baseline in record" in joined
        assert "FLIP" not in joined

    def test_missing_volume_row_keeps_nconv_diagnosis(self):
        # The nconv section is independent of the corr baseline: its
        # fell-back note must survive a missing volume row, and a fused
        # row without a baseline must be reported, not flipped.
        lines = flip.recommend(
            _tpu(value=None,
                 pairs_per_sec_nconv_pallas_FELL_BACK_TO_XLA=150.0)
        )
        assert any("fell back to XLA" in l for l in lines)
        lines = flip.recommend(
            _tpu(value=None, pairs_per_sec_nconv_pallas=150.0,
                 nconv_pallas_calls="12/12")
        )
        joined = "\n".join(lines)
        assert "no volume baseline to compare" in joined
        assert "FLIP" not in joined

    def test_empty_corr_returns_early(self):
        lines = flip.recommend({"baseline_key": "tpu@v5e:volume:x",
                                "value": 0.0})
        assert any("no volume baseline in record" in l for l in lines)


class TestValRow:
    """Eval-pipeline row handling (bench.py val_* fields): absent row →
    silent; guard counters nonzero → unusable; clean → stall verdict."""

    def test_absent_val_row_adds_no_lines(self):
        lines = flip.recommend(_tpu())
        assert not any("val_loop" in l for l in lines)

    def test_violated_invariants_flag_row_unusable(self):
        lines = flip.recommend(
            _tpu(
                val_pairs_per_sec=10.0, val_ms_per_pair=100.0,
                val_stall_ms_per_pair=5.0,
                val_loop_host_transfers=3, val_loop_recompiles=0,
            )
        )
        joined = "\n".join(lines)
        assert "val_loop: INVARIANT VIOLATED" in joined
        assert "3 implicit host transfer(s)" in joined

    def test_clean_row_reports_recovered_stall(self):
        lines = flip.recommend(
            _tpu(
                val_pairs_per_sec=10.0, val_ms_per_pair=100.0,
                val_stall_ms_per_pair=7.5,
                val_loop_host_transfers=0, val_loop_recompiles=0,
            )
        )
        assert any(
            "recovers 7.5 ms/pair" in l for l in lines
        ), lines

    def test_negative_stall_reported_without_flip_advice(self):
        lines = flip.recommend(
            _tpu(
                val_pairs_per_sec=10.0, val_ms_per_pair=100.0,
                val_stall_ms_per_pair=-2.0,
                val_loop_host_transfers=0, val_loop_recompiles=0,
            )
        )
        assert any("no stall recovered" in l for l in lines)

    def test_val_row_reported_even_on_cpu_records(self):
        lines = flip.recommend(
            {
                "value": 9.0, "baseline_key": "cpu@h:volume:x",
                "val_pairs_per_sec": 4.0, "val_ms_per_pair": 250.0,
                "val_stall_ms_per_pair": 5.0,
                "val_loop_host_transfers": 0, "val_loop_recompiles": 1,
            }
        )
        assert any("val_loop: INVARIANT VIOLATED" in l for l in lines)


class TestServeRow:
    """Serving row handling (bench.py serve_* fields; docs/SERVING.md):
    absent row → silent; guard counters nonzero → unusable; an
    overloaded window → backpressure, not service; clean → the latency
    verdict with the degradation note."""

    def _serve(self, **kw):
        base = dict(
            serve_pairs_per_sec=8.5, serve_p50_ms=115.0,
            serve_p99_ms=140.0, serve_requests=16, serve_iters=12,
            serve_shed=0, serve_timeouts=0, serve_budget_drops=0,
            serve_recompiles=0, serve_host_transfers=0,
        )
        base.update(kw)
        return base

    def test_absent_serve_row_adds_no_lines(self):
        lines = flip.recommend(_tpu())
        assert not any("serve" in l for l in lines)

    def test_violated_invariants_flag_row_unusable(self):
        lines = flip.recommend(
            _tpu(**self._serve(serve_recompiles=2,
                               serve_host_transfers=1))
        )
        joined = "\n".join(lines)
        assert "serve: INVARIANT VIOLATED" in joined
        assert "2 recompile(s)" in joined
        assert "1 implicit host transfer(s)" in joined
        assert "p50" not in joined  # unusable latencies never reported

    def test_overloaded_window_flagged_not_reported(self):
        lines = flip.recommend(_tpu(**self._serve(serve_shed=3)))
        joined = "\n".join(lines)
        assert "serve: window OVERLOADED" in joined
        assert "3 shed" in joined
        assert "p50" not in joined

    def test_errored_window_flagged_partial_sample(self):
        lines = flip.recommend(_tpu(**self._serve(serve_errors=1)))
        joined = "\n".join(lines)
        assert "serve: window ERRORED" in joined
        assert "partial sample" in joined
        assert "p50" not in joined

    def test_clean_row_reports_latency_verdict(self):
        lines = flip.recommend(_tpu(**self._serve()))
        joined = "\n".join(lines)
        assert "serve: steady state 8.50 pairs/s" in joined
        assert "p50 115.0 ms / p99 140.0 ms at 12 iters" in joined
        assert "budget never degraded" in joined

    def test_clean_row_with_degradation_notes_it(self):
        lines = flip.recommend(
            _tpu(**self._serve(serve_budget_drops=2))
        )
        assert any("budget degraded 2x" in l for l in lines)

    def test_serve_row_reported_even_on_cpu_records(self):
        lines = flip.recommend(
            {"value": 9.0, "baseline_key": "cpu@h:volume:x",
             **self._serve()}
        )
        assert any("serve: steady state" in l for l in lines)


class TestHighresRow:
    """The spatially-sharded 1080p row verdicts (docs/SHARDING.md): the
    corr_impl flip discipline applied to the serve/stream mesh default."""

    @staticmethod
    def _highres(**kw):
        base = dict(
            highres_pairs_per_sec=4.0,
            highres_pairs_per_sec_unsharded=3.0,
            highres_iters=32,
            highres_mesh="mesh(data=1,spatial=2:tpu)",
            highres_devices=2,
            highres_analysis_temp_gib=0.65,
            highres_analysis_temp_gib_unsharded=1.25,
            highres_collectives=10,
            highres_collective_bytes=123456,
            highres_recompiles=0,
            highres_host_transfers=0,
        )
        base.update(kw)
        return base

    def test_absent_highres_row_adds_no_lines(self):
        lines = flip.recommend(_tpu())
        assert not any("highres" in l for l in lines)

    def test_violated_invariants_flag_row_unusable(self):
        lines = flip.recommend(
            _tpu(**self._highres(highres_recompiles=1,
                                 highres_host_transfers=2))
        )
        joined = "\n".join(lines)
        assert "highres: INVARIANT VIOLATED" in joined
        assert "1 recompile(s)" in joined
        assert "2 implicit host transfer(s)" in joined
        assert "FLIP serve/stream" not in joined

    def test_single_device_row_asks_for_a_mesh(self):
        lines = flip.recommend(
            _tpu(**self._highres(highres_devices=1,
                                 highres_mesh="nomesh"))
        )
        assert any("no mesh to judge" in l for l in lines)

    def test_missing_comparison_blocks_verdict(self):
        rec = self._highres()
        del rec["highres_pairs_per_sec_unsharded"]
        lines = flip.recommend(_tpu(**rec))
        joined = "\n".join(lines)
        assert "no single-device comparison" in joined
        assert "FLIP serve/stream" not in joined

    def test_clean_accelerator_win_flips_mesh_default(self):
        lines = flip.recommend(_tpu(**self._highres()))
        joined = "\n".join(lines)
        assert "highres: FLIP serve/stream default mesh" in joined
        assert "4.000 vs 3.000 pairs/s" in joined

    def test_accelerator_without_margin_keeps_unsharded(self):
        lines = flip.recommend(
            _tpu(**self._highres(highres_pairs_per_sec=3.01))
        )
        joined = "\n".join(lines)
        assert "keep the unsharded default" in joined
        assert "FLIP serve/stream" not in joined
        assert "per-device memory" in joined

    def test_cpu_row_never_flips_but_is_staged(self):
        lines = flip.recommend(
            {"value": 9.0, "baseline_key": "cpu@h:volume:x",
             **self._highres(
                 highres_mesh="mesh(data=1,spatial=2:cpu)")}
        )
        joined = "\n".join(lines)
        assert "no mesh flip from CPU data" in joined
        assert "FLIP serve/stream" not in joined


class TestMain:
    def _run(self, capsys, monkeypatch, text):
        import io

        monkeypatch.setattr(sys, "argv", ["flip_recommendations"])
        monkeypatch.setattr(sys, "stdin", io.StringIO(text))
        flip.main()
        return capsys.readouterr().out

    def test_accepts_bench_stdout_tail(self, capsys, monkeypatch):
        out = self._run(
            capsys, monkeypatch,
            'noise line\n{"value": 9.0, "baseline_key": "cpu@h:volume:x"}\n',
        )
        assert "defaults stay" in out

    def test_empty_input_fails_loudly(self, capsys, monkeypatch):
        with pytest.raises(SystemExit):
            self._run(capsys, monkeypatch, "")

    def test_non_json_input_fails_loudly(self, capsys, monkeypatch):
        with pytest.raises(SystemExit):
            self._run(capsys, monkeypatch, "not json at all")


class TestBf16Row:
    """Precision-default policy for the ``*_bf16`` rows (docs/PRECISION.md):
    absent → silent; dirty guard counters → unusable; parity over budget
    → never flip; clean + parity + margin on ACCELERATOR data → flip."""

    def _bf16(self, **kw):
        rec = dict(
            pairs_per_sec_bf16=150.0,
            bf16_forward_epe_vs_f32=0.02,
            bf16_epe_budget=0.5,
            fwd_bf16_recompiles=0,
            fwd_bf16_host_transfers=0,
        )
        rec.update(kw)
        return rec

    def test_absent_bf16_row_adds_no_lines(self):
        lines = flip.recommend(_tpu())
        assert not any("bf16" in ln for ln in lines)

    def test_dirty_guard_counters_make_row_unusable(self):
        lines = flip.recommend(
            _tpu(**self._bf16(val_loop_recompiles_bf16=2))
        )
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "INVARIANT VIOLATED" in ln and "do NOT flip" in ln

    def test_parity_over_budget_blocks_flip(self):
        lines = flip.recommend(
            _tpu(**self._bf16(bf16_forward_epe_vs_f32=0.9))
        )
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "EXCEEDED" in ln and "do NOT flip" in ln

    def test_missing_parity_is_incomplete(self):
        rec = self._bf16()
        del rec["bf16_forward_epe_vs_f32"]
        lines = flip.recommend(_tpu(**rec))
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "incomplete" in ln

    def test_clean_accelerator_win_flips_precision_default(self):
        lines = flip.recommend(_tpu(**self._bf16()))
        (ln,) = [x for x in lines if x.startswith("precision:")]
        assert "FLIP default 'f32' -> 'bf16_infer'" in ln
        assert "ModelConfig.precision" in ln

    def test_clean_accelerator_without_margin_keeps_f32(self):
        lines = flip.recommend(
            _tpu(**self._bf16(pairs_per_sec_bf16=101.0))
        )
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "keep precision 'f32'" in ln

    def test_cpu_row_reports_parity_but_never_flips(self):
        rec = {"value": 9.0,
               "baseline_key": "cpu@host:volume:1x96x128x4"}
        rec.update(self._bf16(pairs_per_sec_bf16=20.0))
        lines = flip.recommend(rec)
        assert not any(x.startswith("precision:") for x in lines)
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "no flip from CPU data" in ln

    def test_forward_row_guard_counters_also_block(self):
        """fwd_bf16_* spell the guard counters prefix-style — they must
        trip the unusable filter exactly like the *_bf16-suffixed ones."""
        lines = flip.recommend(
            _tpu(**self._bf16(fwd_bf16_recompiles=1))
        )
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "INVARIANT VIOLATED" in ln and "do NOT flip" in ln

    def test_errored_bf16_window_blocks_flip(self):
        lines = flip.recommend(
            _tpu(**self._bf16(serve_errors_bf16=2))
        )
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "ERRORED" in ln

    def test_missing_forward_row_still_flags_dirty_subrows(self):
        """bench's bf16 sub-rows are independently guarded: a record
        with val/serve bf16 rows but no forward row must still surface
        dirty counters (and otherwise say the forward row is missing),
        never stay silent."""
        lines = flip.recommend(
            _tpu(val_pairs_per_sec_bf16=3.0, val_loop_recompiles_bf16=2)
        )
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "INVARIANT VIOLATED" in ln
        lines = flip.recommend(_tpu(val_pairs_per_sec_bf16=3.0,
                                    val_loop_recompiles_bf16=0))
        (ln,) = [x for x in lines if x.startswith("bf16:")]
        assert "forward row missing" in ln


class TestTelemetryLines:
    """Telemetry snapshot consistency (bench serve/stream rows,
    docs/OBSERVABILITY.md): absent -> silent, clean -> one consistency
    line, drifted -> flagged INCONSISTENT; plus the 3%-of-p50 overhead
    budget check."""

    def test_absent_snapshot_is_silent(self):
        lines = flip.recommend(_tpu())
        assert not any(x.startswith("telemetry:") for x in lines)

    def test_consistent_snapshot_confirms(self):
        lines = flip.recommend(
            _tpu(serve_sanctioned_gets=12, serve_batches=12)
        )
        (ln,) = [x for x in lines if x.startswith("telemetry:")]
        assert "consistent" in ln and "12" in ln

    def test_dirty_snapshot_flags_inconsistent(self):
        lines = flip.recommend(
            _tpu(serve_sanctioned_gets=11, serve_batches=12)
        )
        (ln,) = [x for x in lines if x.startswith("telemetry:")]
        assert "INCONSISTENT" in ln and "11" in ln and "12" in ln

    def test_stream_snapshot_judged_independently(self):
        lines = flip.recommend(
            _tpu(
                serve_sanctioned_gets=8, serve_batches=8,
                stream_sanctioned_gets=5, stream_batches=7,
            )
        )
        tl = [x for x in lines if x.startswith("telemetry:")]
        assert len(tl) == 2
        assert "serve snapshot consistent" in tl[0]
        assert "stream snapshot INCONSISTENT" in tl[1]

    def test_overhead_over_budget_is_flagged(self):
        lines = flip.recommend(
            _tpu(
                serve_sanctioned_gets=8, serve_batches=8,
                serve_telemetry_overhead_pct=4.2,
            )
        )
        tl = [x for x in lines if x.startswith("telemetry:")]
        assert any("EXCEEDS the 3% budget" in x for x in tl)

    def test_overhead_within_budget_is_quiet(self):
        lines = flip.recommend(
            _tpu(
                serve_sanctioned_gets=8, serve_batches=8,
                serve_telemetry_overhead_pct=1.1,
            )
        )
        tl = [x for x in lines if x.startswith("telemetry:")]
        assert not any("EXCEEDS" in x for x in tl)

    def test_cpu_records_also_judged(self):
        """The snapshot check is backend-independent: a CPU record's
        early return still carries the telemetry lines."""
        lines = flip.recommend(
            {"value": 9.0, "baseline_key": "cpu@host:volume:1x96x128x4",
             "serve_sanctioned_gets": 3, "serve_batches": 4}
        )
        assert any("INCONSISTENT" in x for x in lines)


class TestSloBlock:
    """Health/SLO verdict block (bench.py serve/stream rows;
    docs/OBSERVABILITY.md): absent block → silent; a DEGRADED window or
    any page → flagged (the latencies include coarsened responses);
    clean → one confirmation line."""

    def _verdicts(self, page=False):
        return {
            "serve_shed_rate": {"page": page, "burn_fast": 33.0 if page
                                else 0.0, "burn_slow": 33.0 if page
                                else 0.0},
            "serve_error_rate": {"page": False, "burn_fast": 0.0,
                                 "burn_slow": 0.0},
        }

    def test_absent_block_adds_no_lines(self):
        assert flip._slo_lines({"serve_pairs_per_sec": 8.5}) == []

    def test_clean_block_confirms_once(self):
        lines = flip._slo_lines({
            "serve_health": "ready", "serve_slo_pages": 0,
            "serve_slo": self._verdicts(),
        })
        assert len(lines) == 1
        assert "clean" in lines[0] and "2 declared SLO(s)" in lines[0]

    def test_degraded_health_flags_the_window(self):
        lines = flip._slo_lines({
            "serve_health": "degraded", "serve_slo_pages": 0,
            "serve_slo": self._verdicts(),
        })
        assert len(lines) == 1 and "DEGRADED" in lines[0]
        assert "health=degraded" in lines[0]

    def test_pages_flag_the_window_and_name_the_slo(self):
        lines = flip._slo_lines({
            "serve_health": "ready", "serve_slo_pages": 1,
            "serve_slo": self._verdicts(page=True),
        })
        assert len(lines) == 1 and "DEGRADED" in lines[0]
        assert "serve_shed_rate" in lines[0]

    def test_stream_block_reported_independently(self):
        lines = flip._slo_lines({
            "serve_health": "ready", "serve_slo_pages": 0,
            "serve_slo": self._verdicts(),
            "stream_health": "degraded", "stream_slo_pages": 2,
            "stream_slo": {},
        })
        assert len(lines) == 2
        assert "serve window clean" in lines[0]
        assert "stream window DEGRADED" in lines[1]

    def test_slo_block_rides_cpu_records_too(self):
        lines = flip.recommend({
            "value": 9.0, "baseline_key": "cpu@host:volume:1x96x128x4",
            "serve_health": "ready", "serve_slo_pages": 0,
            "serve_slo": self._verdicts(),
        })
        assert any("slo: serve window clean" in l for l in lines)


class TestFleetRow:
    """The fleet row's verdict logic (docs/FLEET.md): absent → silent,
    any replica's guard counters dirty → unusable, robustness machinery
    engaged → not steady state, clean → router-hop verdict vs the
    single-replica serve row."""

    def _clean(self, **kw):
        rec = {
            "fleet_pairs_per_sec": 3.1,
            "fleet_p50_ms": 300.0,
            "fleet_p99_ms": 420.0,
            "fleet_replicas": 2,
            "fleet_replica_recompiles": [0, 0],
            "fleet_replica_host_transfers": [0, 0],
            "fleet_per_replica_completed": [7, 7],
            "fleet_shed": 0, "fleet_errors": 0, "fleet_failovers": 0,
            "fleet_deaths": 0, "fleet_contract_violations": [],
        }
        rec.update(kw)
        return rec

    def test_absent_row_adds_no_lines(self):
        assert flip._fleet_lines({}) == []

    def test_any_replica_guard_counter_poisons_the_row(self):
        lines = flip._fleet_lines(
            self._clean(fleet_replica_recompiles=[0, 2])
        )
        assert len(lines) == 1 and "INVARIANT VIOLATED" in lines[0]
        # A replica whose report never arrived is dirty too — an
        # unaccounted replica must not read as a clean one.
        lines = flip._fleet_lines(
            self._clean(fleet_replica_host_transfers=[0, None])
        )
        assert "INVARIANT VIOLATED" in lines[0]

    def test_robustness_machinery_disqualifies_steady_state(self):
        for kw in (
            {"fleet_shed": 1}, {"fleet_errors": 1},
            {"fleet_failovers": 1}, {"fleet_deaths": 1},
            {"fleet_timeouts": 1}, {"fleet_rejected": 1},
            {"fleet_contract_violations": ["rc=1 (want 75)"]},
            # Lossy window with every per-status field reading 0: the
            # ok-vs-requests shortfall alone must disqualify.
            {"fleet_requests": 12, "fleet_ok": 9},
        ):
            lines = flip._fleet_lines(self._clean(**kw))
            assert len(lines) == 1 and "NOT steady state" in lines[0], kw
        # A complete window is NOT lossy.
        lines = flip._fleet_lines(
            self._clean(fleet_requests=12, fleet_ok=12)
        )
        assert "steady state" in lines[0]

    def test_clean_row_reports_router_hop_vs_serve_row(self):
        lines = flip._fleet_lines(self._clean(serve_p50_ms=250.0))
        assert len(lines) == 1
        assert "steady state 3.10 pairs/s" in lines[0]
        assert "router hop vs single-replica serve row: +50.0 ms" in lines[0]
        assert "occupancy [7, 7]" in lines[0]

    def test_clean_row_without_serve_row_says_so(self):
        lines = flip._fleet_lines(self._clean())
        assert "no serve row in this record" in lines[0]

    def test_fleet_row_rides_cpu_records_too(self):
        lines = flip.recommend({
            "value": 9.0, "baseline_key": "cpu@host:volume:1x96x128x4",
            **self._clean(),
        })
        assert any("fleet: steady state" in l for l in lines)

    def test_fleet_telemetry_overhead_over_budget_is_flagged(self):
        """The fleet telemetry on/off window rides the serve row's 3%
        observer budget: over budget → flagged loudly; within → one
        confirmation line; absent (BENCH_SKIP_TELEMETRY_COMPARE or an
        older record) → silent."""
        lines = flip._fleet_lines(self._clean(
            fleet_telemetry_overhead_pct=4.7,
            fleet_p50_ms_notelemetry=286.5,
        ))
        assert len(lines) == 2
        assert "EXCEEDS the 3% budget" in lines[1]
        assert "4.7%" in lines[1]

    def test_fleet_telemetry_overhead_within_budget_confirms(self):
        lines = flip._fleet_lines(self._clean(
            fleet_telemetry_overhead_pct=0.9,
            fleet_p50_ms_notelemetry=297.3,
        ))
        assert len(lines) == 2
        assert "within the 3% budget" in lines[1]
        # Negative delta (noise) is within budget too, not an error.
        lines = flip._fleet_lines(self._clean(
            fleet_telemetry_overhead_pct=-1.2,
        ))
        assert "within the 3% budget" in lines[1]

    def test_fleet_telemetry_overhead_absent_is_silent(self):
        lines = flip._fleet_lines(self._clean())
        assert len(lines) == 1


class TestUhdRow:
    """The uhd (4K) row verdict logic (docs/PERF.md "Banded dispatch"):
    absent → silent, dirty counters → unusable, CPU → staged-never-
    flip, clean accelerator → the corr-tier verdict."""

    def _clean_cpu(self, **kw):
        rec = {
            "value": 9.0, "baseline_key": "cpu@host:volume:1x96x128x4",
            "uhd_pairs_per_sec": 0.02, "uhd_shape": "1x2176x3840",
            "uhd_iters": 1, "uhd_corr_impl": "onthefly",
            "uhd_platform": "cpu", "uhd_corr_row_chunk": 8,
            "uhd_corr_query_block": 512, "uhd_corr_band_rows": "auto",
            "uhd_recompiles": 0, "uhd_host_transfers": 0,
        }
        rec.update(kw)
        return rec

    def _clean_accel(self, **kw):
        rec = self._clean_cpu(
            baseline_key="tpu@v5e:volume:2x368x768x12",
            uhd_platform="tpu", uhd_corr_impl="pallas", uhd_iters=32,
            uhd_pairs_per_sec=4.2,
            uhd_corr_dispatch={
                "kernel": 1, "banded": 3, "fallback": 0,
                "levels_total": 4,
            },
        )
        rec.update(kw)
        return rec

    def test_absent_row_adds_no_lines(self):
        assert flip._uhd_row_lines({}) == []
        assert not [
            l for l in flip.recommend({"value": 1.0}) if l.startswith("uhd")
        ]

    def test_dirty_counters_make_row_unusable(self):
        lines = flip._uhd_row_lines(self._clean_cpu(uhd_recompiles=2))
        assert len(lines) == 1 and "INVARIANT VIOLATED" in lines[0]
        lines = flip._uhd_row_lines(self._clean_cpu(uhd_host_transfers=1))
        assert "INVARIANT VIOLATED" in lines[0]

    def test_missing_counters_make_row_unusable(self):
        rec = self._clean_cpu()
        del rec["uhd_recompiles"]
        lines = flip._uhd_row_lines(rec)
        assert len(lines) == 1 and "unusable" in lines[0]

    def test_cpu_row_is_staged_never_a_flip(self):
        lines = flip._uhd_row_lines(self._clean_cpu())
        assert len(lines) == 1
        assert "staged" in lines[0] and "servable" in lines[0]
        assert "FLIP" not in lines[0] and "VERDICT" not in lines[0]
        # And through recommend() on a CPU record.
        out = flip.recommend(self._clean_cpu())
        assert any("uhd:" in l and "staged" in l for l in out)

    def test_clean_accelerator_full_kernel_gives_corr_tier_verdict(self):
        lines = flip._uhd_row_lines(self._clean_accel())
        assert len(lines) == 1 and "VERDICT" in lines[0]
        assert "banded 3" in lines[0] and "resident 1" in lines[0]
        assert "corr_impl='pallas'" in lines[0]

    def test_accelerator_partial_fallback_asks_for_tuning(self):
        lines = flip._uhd_row_lines(self._clean_accel(
            uhd_corr_dispatch={
                "kernel": 1, "banded": 2, "fallback": 1,
                "levels_total": 4,
            },
        ))
        assert len(lines) == 1
        assert "fell back" in lines[0]
        assert "RAFT_NCUP_CORR_BAND_ROWS" in lines[0]

    def test_accelerator_onthefly_row_asks_for_pallas_rerun(self):
        lines = flip._uhd_row_lines(self._clean_accel(
            uhd_corr_impl="onthefly", uhd_corr_dispatch=None,
        ))
        assert len(lines) == 1 and "BENCH_UHD_CORR=pallas" in lines[0]

    def test_knobs_are_named_in_the_row(self):
        rec = self._clean_cpu(uhd_corr_row_chunk=16,
                              uhd_corr_band_rows=24)
        (line,) = flip._uhd_row_lines(rec)
        assert "row_chunk=16" in line and "band_rows=24" in line


class TestPipelineRow:
    """Iteration-pipeline streaming row (bench.py ``pipeline_*``;
    docs/SHARDING.md "Pipeline axis"): absent row silent, dirty guards
    poison it, S=1 is the delegation path, CPU stages the verdict for
    the chip window, a clean accelerator row judges pipeline vs
    monolithic at the margin."""

    def _clean_cpu(self, **kw):
        rec = {
            "value": 9.0, "baseline_key": "cpu@host:volume:1x96x128x4",
            "pipeline_pairs_per_sec": 0.8, "pipeline_segments": 4,
            "pipeline_micro_batches": 8, "pipeline_shape": "1x256x448",
            "pipeline_iters": 4, "pipeline_platform": "cpu",
            "pipeline_mesh": "mesh(data=1,spatial=1,pipe=4:cpu)",
            "pipeline_collective_permutes": 6,
            "pipeline_recompiles": 0, "pipeline_host_transfers": 0,
        }
        rec.update(kw)
        return rec

    def _clean_accel(self, **kw):
        rec = self._clean_cpu(
            baseline_key="tpu@v5e:volume:2x368x768x12",
            pipeline_platform="tpu", pipeline_iters=32,
            pipeline_pairs_per_sec=12.0,
            pipeline_pairs_per_sec_monolithic=4.0,
            pipeline_mesh="mesh(data=1,spatial=1,pipe=4:tpu)",
            pipeline_flops_per_segment=1.5e12,
        )
        rec.update(kw)
        return rec

    def test_absent_row_adds_no_lines(self):
        assert flip._pipeline_lines({}) == []
        assert not [
            l for l in flip.recommend({"value": 1.0})
            if l.startswith("pipeline")
        ]

    def test_dirty_counters_make_row_unusable(self):
        lines = flip._pipeline_lines(self._clean_cpu(pipeline_recompiles=3))
        assert len(lines) == 1 and "INVARIANT VIOLATED" in lines[0]
        lines = flip._pipeline_lines(
            self._clean_cpu(pipeline_host_transfers=2)
        )
        assert "INVARIANT VIOLATED" in lines[0]

    def test_missing_counters_make_row_unusable(self):
        rec = self._clean_cpu()
        del rec["pipeline_host_transfers"]
        (line,) = flip._pipeline_lines(rec)
        assert "unusable" in line or "INVARIANT VIOLATED" in line

    def test_single_stage_row_is_the_delegation_path(self):
        (line,) = flip._pipeline_lines(
            self._clean_cpu(pipeline_segments=1)
        )
        assert "single-stage" in line and "monolithic delegation" in line
        assert "VERDICT" not in line

    def test_cpu_row_is_staged_never_a_flip(self):
        (line,) = flip._pipeline_lines(self._clean_cpu())
        assert "staged" in line and "S=4" in line
        assert "FLIP" not in line and "VERDICT" not in line
        # Handoff fingerprint rides the staged line.
        assert "collective-permute" in line
        # And through recommend() on a CPU record.
        out = flip.recommend(self._clean_cpu())
        assert any("pipeline:" in l and "staged" in l for l in out)

    def test_clean_accelerator_win_gives_verdict(self):
        (line,) = flip._pipeline_lines(self._clean_accel())
        assert "VERDICT" in line and "S=4" in line
        assert "12.000 vs 4.000" in line

    def test_accelerator_below_margin_keeps_monolithic(self):
        (line,) = flip._pipeline_lines(self._clean_accel(
            pipeline_pairs_per_sec=4.05,
        ))
        assert "keep the monolithic scan" in line

    def test_accelerator_without_comparison_asks_for_rerun(self):
        rec = self._clean_accel()
        del rec["pipeline_pairs_per_sec_monolithic"]
        (line,) = flip._pipeline_lines(rec)
        assert "no monolithic comparison" in line
        assert "BENCH_PIPELINE_COMPARE" in line
