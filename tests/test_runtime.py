"""Tests for the shared runtime/platform facts module
(raft_ncup_tpu.utils.runtime) — the single source of truth for platform
forcing, the per-host XLA cache policy, and the cache wipe-retry rule.
"""

import os

import pytest

from raft_ncup_tpu.utils import runtime


def test_host_fingerprint_stable_and_short():
    fp = runtime.host_fingerprint()
    assert fp == runtime.host_fingerprint()
    assert len(fp) == 8
    int(fp, 16)  # hex


def test_cache_dir_is_host_fingerprinted(tmp_path):
    """Cache entries must never be shared across machines: XLA:CPU AOT
    results bake machine features other hosts load at SIGILL risk."""
    import jax

    restore = {
        k: getattr(jax.config, k)
        for k in (
            "jax_compilation_cache_dir",
            "jax_persistent_cache_min_compile_time_secs",
            "jax_persistent_cache_min_entry_size_bytes",
        )
    }
    try:
        runtime.enable_compilation_cache(str(tmp_path))
        configured = jax.config.jax_compilation_cache_dir
        assert configured == str(
            tmp_path / f"xla-{runtime.host_fingerprint()}"
        )
    finally:
        for k, v in restore.items():
            jax.config.update(k, v)


def test_wipe_policy_budget_and_paths(tmp_path):
    target = tmp_path / f"xla-{runtime.host_fingerprint()}"
    target.mkdir()
    (target / "entry").write_bytes(b"x")
    # Too little budget left: a retry couldn't run, keep the warm cache.
    assert not runtime.wipe_compilation_cache_for_retry(60, str(tmp_path))
    assert target.exists()
    # Enough budget: wipe THIS host's subdir only.
    other = tmp_path / "xla-deadbeef"
    other.mkdir()
    assert runtime.wipe_compilation_cache_for_retry(600, str(tmp_path))
    assert not target.exists()
    assert other.exists()
    # Nothing to wipe -> False.
    assert not runtime.wipe_compilation_cache_for_retry(600, str(tmp_path))


def test_force_platform_writes_env_and_config(monkeypatch):
    import jax

    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    runtime.force_platform("cpu")
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    assert jax.config.jax_platforms == "cpu"


def test_tpu_class_denylist():
    # The conftest forces the cpu backend for the whole suite.
    assert not runtime.is_tpu_class_backend()
    assert "cpu" in runtime.NON_TPU_BACKENDS
