"""The quality-proxy driver's statistics (scripts/ncup_vs_bilinear.py):
the bootstrap CI that puts error bars on the NCUP-vs-bilinear
boundary-band delta must be deterministic, correctly ordered, and honest
about degenerate inputs — the one short window in which the twin
experiment reruns must not hit a regressed estimator.
"""

import importlib.util
import os

import numpy as np
import pytest

_SPEC = importlib.util.spec_from_file_location(
    "ncup_vs_bilinear",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "ncup_vs_bilinear.py",
    ),
)
nvb = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(nvb)


class TestBootstrapCI:
    def test_deterministic_given_seed(self):
        # (With 3 values the resampled-mean distribution is discrete, so
        # DIFFERENT seeds may also coincide — only same-seed equality is
        # part of the contract.)
        vals = [0.09, 0.11, 0.07]
        a = nvb.bootstrap_ci(vals, seed=4)
        b = nvb.bootstrap_ci(vals, seed=4)
        assert a == b

    def test_interval_brackets_mean_and_data(self):
        vals = [0.05, 0.10, 0.15]
        ci = nvb.bootstrap_ci(vals, seed=0)
        assert ci["ci_lo"] <= ci["mean"] <= ci["ci_hi"]
        assert ci["mean"] == pytest.approx(0.10)
        # Resampled means live inside the data's range.
        assert min(vals) <= ci["ci_lo"] and ci["ci_hi"] <= max(vals)
        assert ci["n_values"] == 3

    def test_identical_values_collapse_the_interval(self):
        ci = nvb.bootstrap_ci([0.2, 0.2, 0.2], seed=0)
        assert ci["ci_lo"] == ci["ci_hi"] == pytest.approx(0.2)

    def test_sign_uncertain_claim_straddles_zero(self):
        """The case the satellite exists for: per-seed deltas of mixed
        sign must yield an interval containing 0 — a claim the record
        cannot call established."""
        ci = nvb.bootstrap_ci([0.10, -0.08, 0.02], seed=1)
        assert ci["ci_lo"] < 0.0 < ci["ci_hi"]

    def test_empty_input_raises(self):
        with pytest.raises(ValueError, match="at least one"):
            nvb.bootstrap_ci([])

    def test_wider_alpha_narrows_interval(self):
        vals = list(np.random.default_rng(0).normal(0.1, 0.05, 5))
        wide = nvb.bootstrap_ci(vals, seed=2, alpha=0.05)
        narrow = nvb.bootstrap_ci(vals, seed=2, alpha=0.5)
        assert narrow["ci_lo"] >= wide["ci_lo"]
        assert narrow["ci_hi"] <= wide["ci_hi"]


class TestSeedPlumbing:
    def test_validate_synthetic_passes_seed_to_dataset(self, monkeypatch):
        """The multi-seed CI is only as real as the splits are distinct:
        validate_synthetic(seed=N) must construct its held-out dataset
        with seed=N, not a hardcoded historical value (regression — the
        three --eval_seeds runs were silently identical)."""
        from raft_ncup_tpu import evaluation
        from raft_ncup_tpu.data import synthetic as synth_mod

        captured = {}

        class _Probe:
            def __init__(self, size_hw, length=0, seed=None, style=None):
                captured["seed"] = seed

            def __len__(self):
                return 0  # trips the empty-after-sharding skip path

        monkeypatch.setattr(synth_mod, "SyntheticFlowDataset", _Probe)
        out = evaluation.validate_synthetic(
            None, None, None, size_hw=(16, 24), seed=1234,
        )
        assert out == {}
        assert captured["seed"] == 1234
