"""Loss/schedule parity with torch and end-to-end train-step tests,
including the sharded (data x spatial) step on 8 virtual devices."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from raft_ncup_tpu.config import ModelConfig, TrainConfig, small_model_config
from raft_ncup_tpu.training.loss import sequence_loss
from raft_ncup_tpu.training.optim import (
    build_optimizer,
    freeze_raft_mask,
    onecycle_linear,
)
from raft_ncup_tpu.training.state import create_train_state
from raft_ncup_tpu.parallel import make_mesh, make_train_step


def torch_sequence_loss(flow_preds, flow_gt, valid, gamma=0.8, max_flow=400):
    """Oracle mirroring reference train.py:46-71."""
    n_predictions = len(flow_preds)
    flow_loss = 0.0
    mag = torch.sum(flow_gt**2, dim=1).sqrt()
    valid = (valid >= 0.5) & (mag < max_flow)
    for i in range(n_predictions):
        i_weight = gamma ** (n_predictions - i - 1)
        i_loss = (flow_preds[i] - flow_gt).abs()
        flow_loss += i_weight * (valid[:, None] * i_loss).mean()
    epe = torch.sum((flow_preds[-1] - flow_gt) ** 2, dim=1).sqrt()
    epe = epe.view(-1)[valid.view(-1)]
    metrics = {
        "epe": epe.mean().item(),
        "1px": (epe < 1).float().mean().item(),
        "3px": (epe < 3).float().mean().item(),
        "5px": (epe < 5).float().mean().item(),
    }
    return flow_loss.item(), metrics


def test_sequence_loss_matches_torch():
    rng = np.random.default_rng(0)
    T, B, H, W = 4, 2, 16, 20
    preds = rng.standard_normal((T, B, H, W, 2)).astype(np.float32) * 5
    gt = rng.standard_normal((B, H, W, 2)).astype(np.float32) * 5
    # Mix of valid/invalid plus one huge-flow pixel to exercise max_flow.
    valid = (rng.uniform(size=(B, H, W)) > 0.3).astype(np.float32)
    gt[0, 0, 0] = [500.0, 0.0]

    loss, metrics = sequence_loss(
        jnp.asarray(preds), jnp.asarray(gt), jnp.asarray(valid), gamma=0.8
    )

    tpreds = [torch.from_numpy(preds[t]).permute(0, 3, 1, 2) for t in range(T)]
    tl, tm = torch_sequence_loss(
        tpreds,
        torch.from_numpy(gt).permute(0, 3, 1, 2),
        torch.from_numpy(valid),
    )
    np.testing.assert_allclose(float(loss), tl, rtol=1e-5)
    for k in ("epe", "1px", "3px", "5px"):
        np.testing.assert_allclose(float(metrics[k]), tm[k], rtol=1e-4)


def test_onecycle_matches_torch():
    max_lr, total = 1.25e-4, 1100
    sched = onecycle_linear(max_lr, total, pct_start=0.05)

    dummy = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.AdamW([dummy], lr=max_lr)
    tsched = torch.optim.lr_scheduler.OneCycleLR(
        opt, max_lr, total, pct_start=0.05, cycle_momentum=False,
        anneal_strategy="linear",
    )
    torch_lrs = []
    for _ in range(total):
        torch_lrs.append(tsched.get_last_lr()[0])
        opt.step()
        tsched.step()
    ours = np.asarray(jax.vmap(sched)(jnp.arange(total)))
    # atol covers fp32 cancellation at the ~5e-10 final LR.
    np.testing.assert_allclose(ours, np.asarray(torch_lrs), rtol=1e-4, atol=1e-9)


def test_adamw_update_matches_torch():
    """One AdamW step with grad clipping vs torch on the same tensors."""
    rng = np.random.default_rng(0)
    p = rng.standard_normal((4, 3)).astype(np.float32)
    g = (rng.standard_normal((4, 3)) * 10).astype(np.float32)  # big: clips

    cfg = TrainConfig(lr=1e-3, wdecay=1e-4, epsilon=1e-8, clip=1.0,
                      scheduler="step", scheduler_step=10**9)
    tx = build_optimizer(cfg)
    params = {"w": jnp.asarray(p)}
    opt_state = tx.init(params)
    updates, _ = tx.update({"w": jnp.asarray(g)}, opt_state, params)
    new_p = np.asarray(params["w"] + updates["w"])

    tp = torch.nn.Parameter(torch.from_numpy(p.copy()))
    topt = torch.optim.AdamW([tp], lr=1e-3, weight_decay=1e-4, eps=1e-8)
    tp.grad = torch.from_numpy(g.copy())
    torch.nn.utils.clip_grad_norm_([tp], 1.0)
    topt.step()
    np.testing.assert_allclose(new_p, tp.detach().numpy(), atol=1e-6)


def test_freeze_raft_mask_zeroes_trunk_updates():
    cfg = small_model_config(variant="raft")
    params = {"fnet": {"a": jnp.ones(3)}, "upsampler": {"b": jnp.ones(3)}}
    mask = freeze_raft_mask(params)
    assert mask["fnet"]["a"] is False and mask["upsampler"]["b"] is True

    tcfg = TrainConfig(lr=1e-3, scheduler="step")
    tx = build_optimizer(tcfg, trainable_mask=mask)
    st = tx.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    upd, _ = tx.update(g, st, params)
    assert float(jnp.abs(upd["fnet"]["a"]).sum()) == 0.0
    assert float(jnp.abs(upd["upsampler"]["b"]).sum()) > 0.0


def test_train_step_and_tx_memoized_across_invocations():
    """Repeated in-process trainer invocation (kill/resume tests,
    notebook restarts) must reuse the jitted step AND the optimizer
    transform — a fresh tx per run changes the TrainState treedef and
    forces a full recompile of an identical program."""
    from raft_ncup_tpu.models.raft import RAFT

    cfg = small_model_config(variant="raft")
    tcfg = TrainConfig(stage="chairs", batch_size=1, image_size=(16, 24))
    s1 = make_train_step(RAFT(cfg), tcfg)
    s2 = make_train_step(RAFT(cfg), tcfg)  # new instance, equal config
    assert s1 is s2
    assert build_optimizer(tcfg) is build_optimizer(tcfg)
    # Different run name / restore path: same program, same cache entry.
    assert make_train_step(RAFT(cfg), TrainConfig(
        stage="chairs", batch_size=1, image_size=(16, 24),
        name="other", restore_ckpt="/elsewhere",
    )) is s1
    # Anything the traced program reads busts the cache.
    assert make_train_step(RAFT(cfg), TrainConfig(
        stage="chairs", batch_size=1, image_size=(16, 24), iters=7,
    )) is not s1
    assert build_optimizer(
        TrainConfig(stage="chairs", lr=9e-9)
    ) is not build_optimizer(tcfg)


def _synthetic_batch(rng, B, H, W):
    return {
        "image1": jnp.asarray(rng.uniform(0, 255, (B, H, W, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (B, H, W, 3)), jnp.float32),
        "flow": jnp.asarray(rng.standard_normal((B, H, W, 2)), jnp.float32),
        "valid": jnp.ones((B, H, W), jnp.float32),
    }


@pytest.mark.slow
def test_train_step_single_device_decreases_loss():
    """Overfit one tiny batch for a few steps: loss must drop."""
    mcfg = small_model_config(variant="raft")
    tcfg = TrainConfig(
        stage="chairs", lr=1e-4, num_steps=50, batch_size=1,
        image_size=(64, 96), iters=4,
    )
    model, state = create_train_state(jax.random.key(0), mcfg, tcfg)
    step = make_train_step(model, tcfg)
    batch = _synthetic_batch(np.random.default_rng(0), 1, 64, 96)

    losses = []
    rng = jax.random.key(1)
    for i in range(8):
        rng, k = jax.random.split(rng)
        state, metrics = step(state, batch, k)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


@pytest.mark.slow
def test_train_step_frozen_bn_non_chairs_stage():
    """Regression: the big model (BatchNorm in cnet) must train on
    non-chairs stages, where BN is frozen (reference: train.py:185-186)."""
    mcfg = ModelConfig(variant="raft")
    tcfg = TrainConfig(
        stage="things", lr=1e-4, num_steps=50, batch_size=1,
        image_size=(64, 64), iters=2,
    )
    model, state = create_train_state(jax.random.key(0), mcfg, tcfg)
    step = make_train_step(model, tcfg)
    batch = _synthetic_batch(np.random.default_rng(0), 1, 64, 64)
    # Copy out before stepping: the jitted step donates the state buffers.
    stats_before = [np.asarray(x) for x in jax.tree.leaves(state.batch_stats)]
    state, metrics = step(state, batch, jax.random.key(1))
    assert np.isfinite(float(metrics["loss"]))
    # Frozen BN: running stats unchanged.
    for a, b in zip(stats_before, jax.tree.leaves(state.batch_stats)):
        np.testing.assert_array_equal(a, np.asarray(b))


@pytest.mark.slow
def test_train_step_sharded_matches_single():
    """The (data=4, spatial=2) sharded step must agree with the unsharded
    step — XLA collectives shouldn't change the math."""
    mcfg = small_model_config(variant="raft")
    tcfg = TrainConfig(
        stage="chairs", lr=1e-4, num_steps=50, batch_size=4,
        image_size=(64, 64), iters=2,
    )
    model, state0 = create_train_state(jax.random.key(0), mcfg, tcfg)
    batch = _synthetic_batch(np.random.default_rng(1), 4, 64, 64)
    rngk = jax.random.key(2)

    step_single = make_train_step(model, tcfg)
    s1, m1 = step_single(state0, batch, rngk)

    mesh = make_mesh(data=4, spatial=2)
    model2, state2 = create_train_state(jax.random.key(0), mcfg, tcfg)
    step_sharded = make_train_step(model2, tcfg, mesh=mesh)
    s2, m2 = step_sharded(state2, batch, rngk)

    np.testing.assert_allclose(
        float(m1["loss"]), float(m2["loss"]), rtol=1e-4
    )
    # Updated parameters agree across the two execution strategies.
    l1 = jax.tree.leaves(s1.params)
    l2 = jax.tree.leaves(s2.params)
    for a, b in zip(l1, l2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow
def test_flagship_train_step_sharded_matches_single():
    """The flagship raft_nc_dbl (NCUP upsampler, BN-sintel config) under a
    (2 data x 2 spatial) mesh must agree with the unsharded step. This is
    the component most likely to shard badly: the full-res NConv U-Net runs
    inside the scan body, so spatial sharding pushes halo exchanges through
    zero-stuff scatter, conf-argmax pooling, and the NConv chain
    (reference equivalent being replaced: train.py:169-175)."""
    from raft_ncup_tpu.config import flagship_config

    mcfg = flagship_config(dataset="sintel")
    # 64x64: H/8 = 8 keeps all four correlation-pyramid levels non-empty
    # (smaller inputs are out-of-spec — the reference's smallest crop is
    # 288px, train_raft_nc_kitti.sh:20).
    tcfg = TrainConfig(
        stage="sintel", lr=1e-4, num_steps=50, batch_size=2,
        image_size=(64, 64), iters=2,
    )
    model, state0 = create_train_state(jax.random.key(0), mcfg, tcfg)
    batch = _synthetic_batch(np.random.default_rng(3), 2, 64, 64)
    rngk = jax.random.key(4)

    step_single = make_train_step(model, tcfg)
    s1, m1 = step_single(state0, batch, rngk)

    mesh = make_mesh(data=2, spatial=2)
    model2, state2 = create_train_state(jax.random.key(0), mcfg, tcfg)
    step_sharded = make_train_step(model2, tcfg, mesh=mesh)
    s2, m2 = step_sharded(state2, batch, rngk)

    np.testing.assert_allclose(
        float(m1["loss"]), float(m2["loss"]), rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
    # BN stays frozen on the sintel stage in both strategies.
    for a, b in zip(
        jax.tree.leaves(s1.batch_stats), jax.tree.leaves(s2.batch_stats)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
