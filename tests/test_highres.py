"""High-resolution (1080p-class) memory-efficiency checks.

The reference materializes the full all-pairs correlation volume
(reference: core/corr.py:13-21); at 1/8 res of 1088x1920 that is
(136*240)^2 ~= 1.07e9 entries ~= 4.3 GB fp32 per pair — several times
that with pyramid levels and autodiff residuals. The on-the-fly lookup
(`corr_lookup_onthefly`) never builds the volume, which is what makes
32-iteration 1080p inference fit a single chip's HBM
(SURVEY.md §5 "long-context" analogue; BASELINE.json memory-efficient
config). These tests pin that claim with compiler memory analysis —
platform-independent evidence that works on the CPU backend too.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import flagship_config
from raft_ncup_tpu.models import get_model

H1080, W1080 = 1088, 1920  # 1080p padded to /8 (InputPadder semantics)


def _compiled_test_mode(corr_impl: str, h: int, w: int, iters: int):
    cfg = flagship_config(dataset="sintel", corr_impl=corr_impl)
    model = get_model(cfg)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), (1, h, w, 3))
    )
    variables = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), variables
    )

    def fwd(variables, img1, img2):
        return model.apply(variables, img1, img2, iters=iters, test_mode=True)

    img = jax.ShapeDtypeStruct((1, h, w, 3), jnp.float32)
    return jax.jit(fwd).lower(variables, img, img).compile()


@pytest.mark.slow
def test_onthefly_1080p_fits_single_chip_memory():
    """The flagship model at 1088x1920, 32 iters, corr_impl='onthefly'
    must compile with bounded temporaries: total temp allocation under
    8 GB — comfortable headroom on a 16 GB-HBM chip. The volume impl's
    level-0 pyramid alone is ~4.3 GB and its gather temporaries double
    it, so this is the configuration that makes 1080p viable."""
    compiled = _compiled_test_mode("onthefly", H1080, W1080, iters=32)
    mem = compiled.memory_analysis()
    temp = int(mem.temp_size_in_bytes)
    args_b = int(mem.argument_size_in_bytes)
    out_b = int(mem.output_size_in_bytes)
    total = temp + args_b + out_b
    assert total < 8 * 1024**3, (
        f"onthefly 1080p/32it wants {total/2**30:.2f} GiB "
        f"(temp {temp/2**30:.2f})"
    )


@pytest.mark.slow
def test_onthefly_uses_less_memory_than_volume_at_1080p():
    """Direct comparison at 1080p (2 iters keeps compile cheap): the
    volume impl must allocate the O((HW)^2) pyramid; onthefly must not.
    The gap is the point of the implementation."""
    on = _compiled_test_mode("onthefly", H1080, W1080, iters=2)
    vol = _compiled_test_mode("volume", H1080, W1080, iters=2)
    t_on = int(on.memory_analysis().temp_size_in_bytes)
    t_vol = int(vol.memory_analysis().temp_size_in_bytes)
    # Level-0 volume alone: (136*240)^2 * 4 bytes.
    vol_bytes = (H1080 // 8 * (W1080 // 8)) ** 2 * 4
    assert t_vol > vol_bytes, (t_vol, vol_bytes)
    assert t_on < t_vol / 4, (
        f"onthefly {t_on/2**30:.2f} GiB vs volume {t_vol/2**30:.2f} GiB"
    )


def _spatial_mesh():
    from raft_ncup_tpu.parallel.mesh import make_mesh

    return make_mesh(data=1, spatial=2, devices=jax.devices()[:2])


@pytest.mark.slow
def test_spatial_sharded_1080p_memory_roughly_halves():
    """1080p eval on a (1 data x 2 spatial) mesh: the height axis is split
    across devices, so per-device temporaries must drop to roughly half of
    the single-device footprint (the onthefly lookup's window tensors are
    sharded over query rows; fmap2 may be all-gathered but is only ~33 MB
    at 1/8 res). This pins the SURVEY §5 'long-context' story — spatial
    sharding as the convnet analogue of sequence parallelism — under the
    real SPMD partitioner, not just on paper."""
    from raft_ncup_tpu.parallel.step import make_eval_step

    cfg = flagship_config(dataset="sintel", corr_impl="onthefly")
    model = get_model(cfg)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), (1, H1080, W1080, 3))
    )
    variables = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), variables)
    img = jax.ShapeDtypeStruct((1, H1080, W1080, 3), jnp.float32)

    single = _compiled_test_mode("onthefly", H1080, W1080, iters=8)
    t_single = int(single.memory_analysis().temp_size_in_bytes)

    mesh = _spatial_mesh()
    step = make_eval_step(model, iters=8, mesh=mesh)
    sharded = step.lower(variables, img, img).compile()
    t_sharded = int(sharded.memory_analysis().temp_size_in_bytes)

    assert t_sharded < 0.65 * t_single, (
        f"spatial=2 per-device temp {t_sharded/2**30:.2f} GiB vs "
        f"single-device {t_single/2**30:.2f} GiB — sharding is not"
        " reducing the footprint"
    )


@pytest.mark.slow
def test_spatial_sharded_1080p_matches_single_device():
    """Numerical check: onthefly eval at 1088x1920 on the (1 x 2) spatial
    mesh must produce the same flow as the unsharded run (XLA inserts halo
    exchanges for convs and collectives for the cross-shard corr gather;
    the math must not change)."""
    from raft_ncup_tpu.parallel.step import make_eval_step

    cfg = flagship_config(dataset="sintel", corr_impl="onthefly")
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 64, 64, 3))
    rng = np.random.default_rng(3)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, H1080, W1080, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, H1080, W1080, 3)), jnp.float32)

    lr_ref, up_ref = model.apply(variables, img1, img2, iters=1, test_mode=True)

    mesh = _spatial_mesh()
    step = make_eval_step(model, iters=1, mesh=mesh)
    lr_sh, up_sh = step(variables, img1, img2)

    np.testing.assert_allclose(
        np.asarray(lr_sh), np.asarray(lr_ref), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(up_sh), np.asarray(up_ref), rtol=1e-4, atol=1e-4
    )


@pytest.mark.slow
def test_onthefly_1080p_executes():
    """Actually run one reduced-iteration 1080p pair through the
    on-the-fly path (tiny iteration count keeps CPU runtime sane) and
    check the output is finite and full-res."""
    compiled_model = None
    cfg = flagship_config(dataset="sintel", corr_impl="onthefly")
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 64, 64, 3))
    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, H1080, W1080, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, H1080, W1080, 3)), jnp.float32)
    lr, up = model.apply(variables, img1, img2, iters=1, test_mode=True)
    assert up.shape == (1, H1080, W1080, 2)
    assert lr.shape == (1, H1080 // 8, W1080 // 8, 2)
    assert bool(jnp.isfinite(up).all())


@pytest.mark.slow
def test_train_step_onthefly_spatial_mesh_matches_single():
    """One optimizer step with corr_impl='onthefly' on a (1 x 2) spatial
    mesh must reproduce the unsharded step's loss/metrics: the corr
    lookup's shard_map (replicated fmap2 -> psum'd cotangent) has to be
    transparent to autodiff."""
    from raft_ncup_tpu.config import TrainConfig, small_model_config
    from raft_ncup_tpu.parallel.mesh import make_mesh
    from raft_ncup_tpu.parallel.step import (
        make_synthetic_batch,
        make_train_step,
    )
    from raft_ncup_tpu.training.state import create_train_state

    model_cfg = small_model_config(
        "raft", dataset="chairs", corr_impl="onthefly"
    )
    train_cfg = TrainConfig(
        stage="chairs", batch_size=2, image_size=(32, 32), iters=2,
        num_steps=10,
    )
    batch = make_synthetic_batch(jax.random.PRNGKey(5), 2, 32, 32)
    rng = jax.random.PRNGKey(6)

    def one_step(mesh):
        model, state = create_train_state(
            jax.random.PRNGKey(0), model_cfg, train_cfg,
            image_shape=(1, 32, 32, 3),
        )
        step = make_train_step(model, train_cfg, mesh=mesh)
        _, metrics = step(state, dict(batch), rng)
        return {k: float(v) for k, v in metrics.items()}

    ref = one_step(None)
    mesh = make_mesh(data=1, spatial=2, devices=jax.devices()[:2])
    out = one_step(mesh)
    for k in ("loss", "epe", "grad_norm"):
        np.testing.assert_allclose(out[k], ref[k], rtol=2e-4, err_msg=k)
