"""Normalized-convolution primitive tests against a torch oracle mirroring
core/nconv_modules.py:164-199."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from raft_ncup_tpu.ops import (
    downsample_data_conf,
    nconv2d,
    positivity,
    zero_stuff_upsample,
)


def torch_nconv(data, conf, weight, bias=None, eps=1e-20):
    """Oracle for the reference NConv2d forward (NCHW, OIHW weight)."""
    pad = weight.shape[-1] // 2
    denom = F.conv2d(conf, weight, None, 1, pad)
    nomin = F.conv2d(data * conf, weight, None, 1, pad)
    out = nomin / (denom + eps)
    if bias is not None:
        out = out + bias.view(1, -1, 1, 1)
    s = weight.reshape(weight.shape[0], -1).sum(dim=-1)
    cout = denom / s.view(1, -1, 1, 1)
    return out, cout


def test_nconv2d_matches_torch():
    rng = np.random.default_rng(0)
    B, H, W = 2, 10, 12
    cin, cout, k = 2, 3, 5
    data = rng.standard_normal((B, H, W, cin)).astype(np.float32)
    conf = rng.uniform(0, 1, (B, H, W, cin)).astype(np.float32)
    w = rng.uniform(0.1, 2.0, (k, k, cin, cout)).astype(np.float32)
    b = rng.standard_normal((cout,)).astype(np.float32)

    ours_out, ours_conf = nconv2d(
        jnp.asarray(data), jnp.asarray(conf), jnp.asarray(w), jnp.asarray(b)
    )

    tw = torch.from_numpy(w).permute(3, 2, 0, 1)  # HWIO -> OIHW
    t_out, t_conf = torch_nconv(
        torch.from_numpy(data).permute(0, 3, 1, 2),
        torch.from_numpy(conf).permute(0, 3, 1, 2),
        tw,
        torch.from_numpy(b),
    )
    np.testing.assert_allclose(
        np.asarray(ours_out), t_out.permute(0, 2, 3, 1).numpy(), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(ours_conf), t_conf.permute(0, 2, 3, 1).numpy(), atol=1e-5
    )


def test_positivity_softplus_matches_torch_beta10():
    x = np.linspace(-3, 3, 13).astype(np.float32)
    ours = np.asarray(positivity(jnp.asarray(x), "softplus"))
    theirs = F.softplus(torch.from_numpy(x), beta=10).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)
    assert (ours >= 0).all()


def test_downsample_conf_based_matches_torch():
    rng = np.random.default_rng(0)
    B, H, W, C = 2, 8, 6, 3
    data = rng.standard_normal((B, H, W, C)).astype(np.float32)
    conf = rng.uniform(0, 1, (B, H, W, C)).astype(np.float32)

    d_ds, c_ds = downsample_data_conf(
        jnp.asarray(data), jnp.asarray(conf), "conf_based"
    )

    tconf = torch.from_numpy(conf).permute(0, 3, 1, 2)
    tdata = torch.from_numpy(data).permute(0, 3, 1, 2)
    c_ref, idx = F.max_pool2d(tconf, 2, 2, return_indices=True)
    c_ref = c_ref / 4
    flat = tdata.flatten(start_dim=2)
    d_ref = flat.gather(dim=2, index=idx.flatten(start_dim=2)).view_as(idx)

    np.testing.assert_allclose(
        np.asarray(c_ds), c_ref.permute(0, 2, 3, 1).numpy(), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(d_ds), d_ref.permute(0, 2, 3, 1).numpy(), atol=1e-6
    )


def test_downsample_max_pooling():
    rng = np.random.default_rng(0)
    data = rng.standard_normal((1, 4, 4, 2)).astype(np.float32)
    conf = rng.uniform(0, 1, (1, 4, 4, 2)).astype(np.float32)
    d_ds, c_ds = downsample_data_conf(
        jnp.asarray(data), jnp.asarray(conf), "max_pooling"
    )
    t = torch.from_numpy(data).permute(0, 3, 1, 2)
    ref = F.max_pool2d(t, 2, 2).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(np.asarray(d_ds), ref, atol=1e-6)


def test_zero_stuff_positions():
    x = jnp.ones((1, 3, 3, 2))
    out = np.asarray(zero_stuff_upsample(x, 4, 4))
    assert out.shape == (1, 12, 12, 2)
    # Nonzero exactly at rows/cols 2, 6, 10 (sH//2::sH).
    nz = np.nonzero(out[0, :, :, 0])
    assert set(nz[0]) == {2, 6, 10} and set(nz[1]) == {2, 6, 10}
    assert out.sum() == 2 * 9


def test_nconv_gradient_flows():
    """The divide makes gradients fragile; check they're finite."""
    import jax

    def loss_fn(w_raw):
        w = positivity(w_raw)
        data = jnp.ones((1, 6, 6, 1))
        conf = jnp.full((1, 6, 6, 1), 0.5)
        out, _ = nconv2d(data, conf, w)
        return (out**2).sum()

    g = jax.grad(loss_fn)(jnp.full((3, 3, 1, 2), 2.0))
    assert np.isfinite(np.asarray(g)).all()


class TestFusedNConvPallas:
    """Interpret-mode equivalence of the fused Pallas NConv2d
    (raft_ncup_tpu.ops.nconv_pallas) against the XLA composition."""

    def _setup(self, k=5, cin=1, cout=2, shape=(2, 24, 32)):
        g = np.random.default_rng(7)
        B, H, W = shape
        data = jnp.asarray(g.normal(size=(B, H, W, cin)), jnp.float32)
        conf = jnp.asarray(g.random((B, H, W, cin)), jnp.float32)
        weight = positivity(
            jnp.asarray(g.normal(2.0, 0.5, (k, k, cin, cout)), jnp.float32)
        )
        bias = jnp.asarray(g.normal(size=(cout,)), jnp.float32)
        return data, conf, weight, bias

    @pytest.mark.parametrize("k,cin,cout", [(5, 1, 2), (3, 4, 2), (1, 2, 1)])
    def test_matches_xla_composition(self, k, cin, cout):
        from raft_ncup_tpu.ops.nconv_pallas import nconv2d_fused

        data, conf, weight, bias = self._setup(k, cin, cout)
        ref_out, ref_conf = nconv2d(data, conf, weight, bias)
        out, conf_out = nconv2d_fused(data, conf, weight, bias, 1e-20, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref_out), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(conf_out), np.asarray(ref_conf), rtol=1e-5, atol=1e-5
        )

    def test_no_bias(self):
        from raft_ncup_tpu.ops.nconv_pallas import nconv2d_fused

        data, conf, weight, _ = self._setup()
        ref_out, _ = nconv2d(data, conf, weight, None)
        out, _ = nconv2d_fused(data, conf, weight, None, 1e-20, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref_out), rtol=1e-5, atol=1e-5
        )

    def test_gradients_match_xla(self):
        from raft_ncup_tpu.ops.nconv_pallas import nconv2d_fused

        data, conf, weight, bias = self._setup(k=3, shape=(1, 12, 16))

        def loss_fused(d, c, w, b):
            out, co = nconv2d_fused(d, c, w, b, 1e-20, True)
            return (out**2).sum() + (co**2).sum()

        def loss_ref(d, c, w, b):
            out, co = nconv2d(d, c, w, b)
            return (out**2).sum() + (co**2).sum()

        gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(data, conf, weight, bias)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(data, conf, weight, bias)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_dispatch_gating(self):
        from raft_ncup_tpu.ops import nconv_pallas as npk

        assert npk.supported((5, 5, 1, 2), stride=1, groups=1)
        assert not npk.supported((5, 5, 1, 2), stride=2, groups=1)
        assert not npk.supported((4, 4, 1, 2), stride=1, groups=1)
        assert npk.fits_vmem(368, 768, 1, 2, 5)
        assert not npk.fits_vmem(1088, 1920, 1, 2, 5)

    def test_channel_count_gate(self):
        """VERDICT r3 #3: the kernel body unrolls cout*k*k*cin Python
        iterations; wide-channel shapes must be rejected before they
        become a Mosaic compile-time blowup."""
        from raft_ncup_tpu.ops import nconv_pallas as npk

        assert npk.supported((3, 3, 4, 4), stride=1, groups=1)  # 144
        assert not npk.supported((3, 3, 8, 8), stride=1, groups=1)  # 576
        assert not npk.supported((5, 5, 4, 4), stride=1, groups=1)  # 400

    def test_pallas_fallback_warns_and_counts(self):
        """ADVICE r3 (medium): impl='pallas' falling back to XLA must be
        loud and countable — bench rows labeled nconv=pallas use these
        counters to decide whether the fused kernel actually ran."""
        from raft_ncup_tpu.ops import nconv

        g = np.random.default_rng(11)
        data = jnp.asarray(g.normal(size=(1, 8, 8, 1)), jnp.float32)
        conf = jnp.asarray(g.random((1, 8, 8, 1)), jnp.float32)
        weight = positivity(
            jnp.asarray(g.normal(size=(5, 5, 1, 2)), jnp.float32)
        )
        nconv.reset_dispatch_counts()
        # CPU backend is not TPU-class, so 'pallas' must fall back, warn,
        # and still produce the XLA result.
        with pytest.warns(UserWarning, match="fell back to XLA"):
            out, conf_out = nconv.nconv2d(data, conf, weight, impl="pallas")
        counts = nconv.dispatch_counts()
        assert counts == {"fused": 0, "fallback": 1}
        ref_out, ref_conf = nconv.nconv2d(data, conf, weight, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out))
        np.testing.assert_allclose(
            np.asarray(conf_out), np.asarray(ref_conf)
        )
