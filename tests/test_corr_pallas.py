"""Equivalence tests for the Pallas corr-lookup kernel (interpret mode on
CPU; the same kernel compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.ops.corr import build_corr_pyramid, corr_lookup
from raft_ncup_tpu.ops.corr_pallas import corr_lookup_pallas
from raft_ncup_tpu.ops.geometry import coords_grid

B, H, W, C = 2, 8, 12, 16
RADIUS = 3
LEVELS = 3  # deepest level is 2x3 — exercises tiny-volume handling


def setup():
    g = np.random.default_rng(0)
    fmap1 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
    fmap2 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
    return fmap1, fmap2


class TestPallasLookup:
    def test_matches_volume_path_on_grid(self):
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W)
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
        )
        out = corr_lookup_pallas(
            fmap1, fmap2, coords, RADIUS, LEVELS, True
        )
        assert out.shape == ref.shape
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_matches_volume_path_fractional_and_oob(self):
        fmap1, fmap2 = setup()
        g = np.random.default_rng(1)
        # Fractional offsets plus large displacements that push whole
        # windows out of bounds in every direction.
        coords = coords_grid(B, H, W) + jnp.asarray(
            g.uniform(-1.5 * max(H, W), 1.5 * max(H, W), (B, H, W, 2)),
            jnp.float32,
        ) * jnp.asarray(g.random((B, H, W, 2)) < 0.3, jnp.float32) + jnp.asarray(
            g.uniform(-0.99, 0.99, (B, H, W, 2)), jnp.float32
        )
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
        )
        out = corr_lookup_pallas(
            fmap1, fmap2, coords, RADIUS, LEVELS, True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_gradients_match_xla_path(self):
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W) + 0.3

        def loss_pallas(f1, f2, c):
            return (
                corr_lookup_pallas(f1, f2, c, RADIUS, LEVELS, True) ** 2
            ).sum()

        def loss_ref(f1, f2, c):
            pyr = build_corr_pyramid(f1, f2, LEVELS)
            return (corr_lookup(pyr, c, RADIUS) ** 2).sum()

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(fmap1, fmap2, coords)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(fmap1, fmap2, coords)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            )

    def test_model_runs_with_pallas_impl(self):
        # On a non-TPU backend the model selects interpret mode itself
        # (models/raft.py), so corr_impl='pallas' works unpatched.
        from raft_ncup_tpu.config import small_model_config
        from raft_ncup_tpu.models.raft import RAFT

        cfg = small_model_config(
            "raft", dataset="chairs", corr_impl="pallas"
        )
        model = RAFT(cfg)
        shape = (1, 32, 48, 3)
        variables = model.init(jax.random.PRNGKey(0), shape)
        img = jnp.zeros(shape, jnp.float32)
        lr, up = model.apply(variables, img, img, iters=2, test_mode=True)
        assert up.shape == (1, 32, 48, 2)
        assert np.isfinite(np.asarray(up)).all()
