"""Equivalence tests for the Pallas corr-lookup kernel (interpret mode on
CPU; the same kernel compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.ops.corr import build_corr_pyramid, corr_lookup
from raft_ncup_tpu.ops.corr_pallas import corr_lookup_pallas
from raft_ncup_tpu.ops.geometry import coords_grid

B, H, W, C = 2, 8, 12, 16
RADIUS = 3
LEVELS = 3  # deepest level is 2x3 — exercises tiny-volume handling


def setup():
    g = np.random.default_rng(0)
    fmap1 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
    fmap2 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
    return fmap1, fmap2


class TestPallasLookup:
    def test_matches_volume_path_on_grid(self):
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W)
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
        )
        out = corr_lookup_pallas(
            fmap1, fmap2, coords, RADIUS, LEVELS, True
        )
        assert out.shape == ref.shape
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_matches_volume_path_fractional_and_oob(self):
        fmap1, fmap2 = setup()
        g = np.random.default_rng(1)
        # Fractional offsets plus large displacements that push whole
        # windows out of bounds in every direction.
        coords = coords_grid(B, H, W) + jnp.asarray(
            g.uniform(-1.5 * max(H, W), 1.5 * max(H, W), (B, H, W, 2)),
            jnp.float32,
        ) * jnp.asarray(g.random((B, H, W, 2)) < 0.3, jnp.float32) + jnp.asarray(
            g.uniform(-0.99, 0.99, (B, H, W, 2)), jnp.float32
        )
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
        )
        out = corr_lookup_pallas(
            fmap1, fmap2, coords, RADIUS, LEVELS, True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_gradients_match_xla_path(self):
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W) + 0.3

        def loss_pallas(f1, f2, c):
            return (
                corr_lookup_pallas(f1, f2, c, RADIUS, LEVELS, True) ** 2
            ).sum()

        def loss_ref(f1, f2, c):
            pyr = build_corr_pyramid(f1, f2, LEVELS)
            return (corr_lookup(pyr, c, RADIUS) ** 2).sum()

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(fmap1, fmap2, coords)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(fmap1, fmap2, coords)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            )

    def test_query_count_not_multiple_of_group(self):
        """Adversarial (VERDICT r3 #3): H*W = 35 queries, not a multiple
        of the kernel's group-of-8 tiling — the tail group must still
        match the volume path exactly."""
        h, w = 5, 7
        g = np.random.default_rng(3)
        fmap1 = jnp.asarray(g.normal(size=(1, h, w, C)), jnp.float32)
        fmap2 = jnp.asarray(g.normal(size=(1, h, w, C)), jnp.float32)
        coords = coords_grid(1, h, w) + jnp.asarray(
            g.uniform(-2.0, 2.0, (1, h, w, 2)), jnp.float32
        )
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, 2), coords, RADIUS
        )
        out = corr_lookup_pallas(fmap1, fmap2, coords, RADIUS, 2, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_every_window_fully_out_of_bounds(self):
        """Adversarial: displacements larger than the image in all four
        directions — every tap of every window is OOB, output must be
        exactly the reference's (zeros), no clamping artifacts."""
        fmap1, fmap2 = setup()
        big = 4.0 * max(H, W)
        for dx, dy in ((big, 0.0), (-big, 0.0), (0.0, big), (-big, -big)):
            coords = coords_grid(B, H, W) + jnp.asarray(
                [dx, dy], jnp.float32
            )
            ref = corr_lookup(
                build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
            )
            out = corr_lookup_pallas(
                fmap1, fmap2, coords, RADIUS, LEVELS, True
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
            )

    def test_mixed_level_dispatch_matches(self, monkeypatch):
        """Adversarial: a VMEM budget that rejects level 0's RESIDENT
        tier but accepts deeper levels (the 1080p dispatch boundary) —
        under the three-tier dispatch the rejected level lands on the
        BANDED kernel, not XLA, and the stitched banded+resident output
        must equal the pure XLA path."""
        from raft_ncup_tpu.ops import corr_pallas as cpk

        if cpk.pltpu is None:
            pytest.skip("pallas-tpu unavailable; dispatch loop can't "
                        "take the kernel branch")
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W) + 0.25
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
        )
        level0_bytes = cpk._level_vmem_bytes(H, W, C, RADIUS)
        dispatched = []

        def fits(h, w, c, radius=4, dtype=None):
            ok = cpk._level_vmem_bytes(h, w, c, radius) < level0_bytes
            dispatched.append(((h, w), ok))
            return ok

        monkeypatch.setattr(cpk, "fits_vmem", fits)
        cpk.reset_dispatch_counts()
        out = corr_lookup_pallas(fmap1, fmap2, coords, RADIUS, LEVELS, True)
        # Level 0 missed residency and went BANDED, at least one deeper
        # level took the resident kernel, nothing fell back to XLA —
        # and the module tally (bench.py's honesty signal) agrees.
        assert dispatched[0][1] is False
        assert any(ok for _, ok in dispatched[1:])
        counts = cpk.dispatch_counts()
        assert counts["levels_total"] == LEVELS
        assert counts["banded"] >= 1 and counts["kernel"] >= 1
        assert counts["fallback"] == 0
        assert counts["kernel"] + counts["banded"] == LEVELS
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_all_levels_fallback_warns(self, monkeypatch):
        """ADVICE r3: when BOTH kernel tiers (resident fits_vmem and
        band_plan) reject every level, the 'pallas' label silently
        measures XLA — a warning must say so."""
        from raft_ncup_tpu.ops import corr_pallas as cpk

        if cpk.pltpu is None:
            pytest.skip("pallas-tpu unavailable; pltpu-None branch warns")
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W)
        monkeypatch.setattr(cpk, "fits_vmem", lambda *a, **k: False)
        monkeypatch.setattr(cpk, "band_plan", lambda *a, **k: None)
        with pytest.warns(UserWarning, match="onthefly fallback for every"):
            cpk.corr_lookup_pallas(fmap1, fmap2, coords, RADIUS, LEVELS, True)

    def test_banded_tier_dispatch_matches_onthefly(self, monkeypatch):
        """The full op with residency rejected everywhere: every level
        must land on the BANDED tier (counts pinned) and the output
        must match the XLA onthefly path."""
        from raft_ncup_tpu.ops import corr_pallas as cpk
        from raft_ncup_tpu.ops.corr import corr_lookup_onthefly

        if cpk.pltpu is None:
            pytest.skip("pallas-tpu unavailable")
        fmap1, fmap2 = setup()
        g = np.random.default_rng(7)
        coords = coords_grid(B, H, W) + jnp.asarray(
            g.uniform(-5, 5, (B, H, W, 2)), jnp.float32
        )
        ref = corr_lookup_onthefly(fmap1, fmap2, coords, RADIUS, LEVELS)
        monkeypatch.setattr(cpk, "fits_vmem", lambda *a, **k: False)
        monkeypatch.setattr(cpk, "band_plan", lambda *a, **k: (3, 4))
        cpk.reset_dispatch_counts()
        out = cpk.corr_lookup_pallas(
            fmap1, fmap2, coords, RADIUS, LEVELS, True
        )
        counts = cpk.dispatch_counts()
        assert counts["banded"] == LEVELS
        assert counts["kernel"] == 0 and counts["fallback"] == 0
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_model_runs_with_pallas_impl(self):
        # On a non-TPU backend the model selects interpret mode itself
        # (models/raft.py), so corr_impl='pallas' works unpatched.
        from raft_ncup_tpu.config import small_model_config
        from raft_ncup_tpu.models.raft import RAFT

        cfg = small_model_config(
            "raft", dataset="chairs", corr_impl="pallas"
        )
        model = RAFT(cfg)
        shape = (1, 32, 48, 3)
        variables = model.init(jax.random.PRNGKey(0), shape)
        img = jnp.zeros(shape, jnp.float32)
        lr, up = model.apply(variables, img, img, iters=2, test_mode=True)
        assert up.shape == (1, 32, 48, 2)
        assert np.isfinite(np.asarray(up)).all()


class TestBandedLookup:
    """The banded tier in isolation (ops/corr_pallas.py "Banded tier"):
    level slabs stay in HBM, one band slab + halo is DMA'd per band,
    queries ride a stable argsort-by-band with a masked group loop.

    Parity contracts: BITWISE equality with the resident kernel (same
    per-query math, only regrouped — interpret mode, so bitwise means
    bitwise), and tolerance equality with the XLA onthefly path (a
    different but mathematically equal reduction order). Fully-OOB
    windows are exact zeros on every path, so THAT case is pinned
    bitwise against onthefly too.
    """

    def _run(self, fn, fmap1, fmap2, coords, levels, band_rows=3,
             qblk=16, radius=RADIUS):
        import math

        from raft_ncup_tpu.ops.corr import _pool_fmap_pyramid

        b, h, w, c = fmap1.shape
        f1 = fmap1.reshape(b, h * w, c) * (1.0 / math.sqrt(c))
        cflat = coords.astype(jnp.float32).reshape(b, h * w, 2)
        k2 = (2 * radius + 1) ** 2
        outs = []
        for lvl, f2l in enumerate(_pool_fmap_pyramid(fmap2, levels)):
            outs.append(fn(f1, f2l, cflat, lvl, band_rows, qblk))
        return jnp.concatenate(outs, -1).reshape(b, h, w, levels * k2)

    def _banded(self, fmap1, fmap2, coords, levels, band_rows=3, qblk=16):
        from raft_ncup_tpu.ops import corr_pallas as cpk

        return self._run(
            lambda f1, f2l, cf, lvl, br, qb: cpk._banded_lookup_one_level(
                f1, f2l, cf, RADIUS, lvl, band_rows=br, interpret=True,
                query_block=qb,
            ),
            fmap1, fmap2, coords, levels, band_rows, qblk,
        )

    def _resident(self, fmap1, fmap2, coords, levels, qblk=16):
        from raft_ncup_tpu.ops import corr_pallas as cpk

        return self._run(
            lambda f1, f2l, cf, lvl, br, qb: cpk._lookup_one_level(
                f1, f2l, cf, RADIUS, lvl, interpret=True, query_block=qb,
            ),
            fmap1, fmap2, coords, levels,
        )

    def test_bitwise_vs_resident_kernel(self):
        """Fractional + OOB displacements: the banded kernel must be
        BITWISE the resident kernel — banding regroups the same f32
        math, it must not change a single ulp."""
        fmap1, fmap2 = setup()
        g = np.random.default_rng(11)
        coords = coords_grid(B, H, W) + jnp.asarray(
            g.uniform(-1.5 * max(H, W), 1.5 * max(H, W), (B, H, W, 2)),
            jnp.float32,
        ) * jnp.asarray(
            g.random((B, H, W, 2)) < 0.3, jnp.float32
        ) + jnp.asarray(g.uniform(-0.99, 0.99, (B, H, W, 2)), jnp.float32)
        banded = self._banded(fmap1, fmap2, coords, LEVELS)
        resident = self._resident(fmap1, fmap2, coords, LEVELS)
        assert np.array_equal(np.asarray(banded), np.asarray(resident))

    def test_parity_vs_onthefly(self):
        from raft_ncup_tpu.ops.corr import corr_lookup_onthefly

        fmap1, fmap2 = setup()
        g = np.random.default_rng(12)
        coords = coords_grid(B, H, W) + jnp.asarray(
            g.uniform(-4, 4, (B, H, W, 2)), jnp.float32
        )
        banded = self._banded(fmap1, fmap2, coords, LEVELS)
        ref = corr_lookup_onthefly(fmap1, fmap2, coords, RADIUS, LEVELS)
        np.testing.assert_allclose(
            np.asarray(banded), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_band_boundary_queries(self):
        """Integer and near-integer displacements that park window
        origins exactly on / either side of every band seam (band_rows
        = 3 makes every third row a seam): bitwise vs the resident
        kernel and tolerance vs onthefly."""
        from raft_ncup_tpu.ops.corr import corr_lookup_onthefly

        fmap1, fmap2 = setup()
        for dy in (-1.0, 0.0, 0.5, 1.0):
            coords = coords_grid(B, H, W) + jnp.asarray(
                [0.25, dy], jnp.float32
            )
            banded = self._banded(fmap1, fmap2, coords, LEVELS)
            resident = self._resident(fmap1, fmap2, coords, LEVELS)
            assert np.array_equal(
                np.asarray(banded), np.asarray(resident)
            ), f"dy={dy}"
            if dy == 0.5:  # one cross-path check; bitwise is the pin
                ref = corr_lookup_onthefly(
                    fmap1, fmap2, coords, RADIUS, LEVELS
                )
                np.testing.assert_allclose(
                    np.asarray(banded), np.asarray(ref),
                    rtol=1e-4, atol=1e-4,
                )

    def test_far_oob_windows_bitwise_zero_like_onthefly(self):
        """Displacements larger than the image in all four directions:
        every clamped window lands entirely in a band's zero halo, so
        the output is EXACT zeros — bitwise equal to onthefly (which
        also produces exact zeros), the one case where bitwise
        cross-path parity is mathematically owed."""
        from raft_ncup_tpu.ops.corr import corr_lookup_onthefly

        fmap1, fmap2 = setup()
        big = 4.0 * max(H, W)
        for dx, dy in ((big, 0.0), (-big, 0.0), (0.0, big), (-big, -big)):
            coords = coords_grid(B, H, W) + jnp.asarray(
                [dx, dy], jnp.float32
            )
            banded = self._banded(fmap1, fmap2, coords, LEVELS)
            ref = corr_lookup_onthefly(
                fmap1, fmap2, coords, RADIUS, LEVELS
            )
            assert np.array_equal(np.asarray(banded), np.asarray(ref)), (
                dx, dy,
            )
            assert not np.asarray(banded).any()  # provably the OOB case

    def test_bf16_banded_matches_bf16_resident_bitwise(self):
        """The policy's corr dtype rides the banded tier identically:
        bf16 slab/features with f32 accumulate — still bitwise the
        resident kernel under the same dtype."""
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W) + 0.3
        b16 = jnp.bfloat16
        banded = self._banded(
            fmap1.astype(b16), fmap2.astype(b16), coords, LEVELS
        )
        resident = self._resident(
            fmap1.astype(b16), fmap2.astype(b16), coords, LEVELS
        )
        assert np.array_equal(np.asarray(banded), np.asarray(resident))

    def test_query_count_not_multiple_of_block(self):
        """35 queries, query_block 16, band_rows 2: padded tail slots
        ride the last band and must not corrupt real outputs."""
        h, w = 5, 7
        g = np.random.default_rng(13)
        fmap1 = jnp.asarray(g.normal(size=(1, h, w, C)), jnp.float32)
        fmap2 = jnp.asarray(g.normal(size=(1, h, w, C)), jnp.float32)
        coords = coords_grid(1, h, w) + jnp.asarray(
            g.uniform(-2.0, 2.0, (1, h, w, 2)), jnp.float32
        )
        banded = self._banded(fmap1, fmap2, coords, 2, band_rows=2)
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, 2), coords, RADIUS
        )
        np.testing.assert_allclose(
            np.asarray(banded), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_gradients_still_flow_through_banded_dispatch(self, monkeypatch):
        """The custom-VJP backward (f32 XLA path) is tier-agnostic: with
        every level forced banded, gradients must still match the
        reference — the op stays trainable at banded shapes."""
        from raft_ncup_tpu.ops import corr_pallas as cpk

        if cpk.pltpu is None:
            pytest.skip("pallas-tpu unavailable")
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W) + 0.3
        monkeypatch.setattr(cpk, "fits_vmem", lambda *a, **k: False)
        monkeypatch.setattr(cpk, "band_plan", lambda *a, **k: (3, 4))

        def loss_banded(f1, f2, c):
            return (
                cpk.corr_lookup_pallas(f1, f2, c, RADIUS, LEVELS, True) ** 2
            ).sum()

        def loss_ref(f1, f2, c):
            pyr = build_corr_pyramid(f1, f2, LEVELS)
            return (corr_lookup(pyr, c, RADIUS) ** 2).sum()

        gb = jax.grad(loss_banded, argnums=(0, 1, 2))(fmap1, fmap2, coords)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(fmap1, fmap2, coords)
        for a, b in zip(gb, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            )


class TestBandPlanAndKnobs:
    """band_plan budget math + the env knobs (the autotuner surface)."""

    def test_band_plan_fits_budget(self):
        from raft_ncup_tpu.ops import corr_pallas as cpk

        # 1080p level-0 shape: residency is out, the plan must fit.
        plan = cpk.band_plan(136, 240, 256, 4)
        assert plan is not None
        band_rows, n_bands = plan
        assert band_rows >= 1 and n_bands >= 1
        assert cpk._banded_vmem_bytes(
            136, 240, 256, 4, band_rows
        ) <= int(0.9 * cpk._VMEM_BYTES)
        # Bands cover every clamped origin row of the padded level.
        hp, _, _ = cpk._padded_hw(136, 240, 4)
        assert band_rows * n_bands >= hp - (2 * 4 + 1)

    def test_band_plan_none_when_nothing_fits(self, monkeypatch):
        from raft_ncup_tpu.ops import corr_pallas as cpk

        monkeypatch.setattr(cpk, "_VMEM_BYTES", 1024)
        assert cpk.band_plan(136, 240, 256, 4) is None

    def test_band_rows_env_override_wins(self, monkeypatch):
        from raft_ncup_tpu.ops import corr_pallas as cpk

        monkeypatch.setenv(cpk.BAND_ROWS_ENV, "5")
        plan = cpk.band_plan(136, 240, 256, 4)
        assert plan is not None and plan[0] == 5
        assert cpk.tuning_meta()["corr_band_rows"] == 5

    def test_query_block_env_override(self, monkeypatch):
        from raft_ncup_tpu.ops import corr_pallas as cpk

        monkeypatch.setenv(cpk.QUERY_BLOCK_ENV, "128")
        assert cpk.effective_query_block() == 128
        assert cpk.tuning_meta()["corr_query_block"] == 128
        monkeypatch.delenv(cpk.QUERY_BLOCK_ENV)
        assert cpk.tuning_meta()["corr_band_rows"] == "auto"

    def test_row_chunk_env_override(self, monkeypatch):
        from raft_ncup_tpu.ops import corr

        assert corr.effective_row_chunk() == 8
        monkeypatch.setenv(corr.ROW_CHUNK_ENV, "16")
        assert corr.effective_row_chunk() == 16
        meta = corr.corr_tuning_meta()
        assert meta["corr_row_chunk"] == 16
        # The overridden chunk still computes the same lookup.
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W) + 0.25
        ref = corr.corr_lookup_onthefly(
            fmap1, fmap2, coords, RADIUS, LEVELS, row_chunk=8
        )
        out = corr.corr_lookup_onthefly(
            fmap1, fmap2, coords, RADIUS, LEVELS
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_dispatch_counts_mutation_is_locked(self):
        """The satellite contract: concurrent traces must not lose
        tally increments (the lock exists; hammer it)."""
        import threading

        from raft_ncup_tpu.ops import corr_pallas as cpk

        cpk.reset_dispatch_counts()
        n_threads, n_iter = 8, 500

        def work():
            for _ in range(n_iter):
                cpk._count("levels_total")

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert cpk.dispatch_counts()["levels_total"] == n_threads * n_iter
        cpk.reset_dispatch_counts()
