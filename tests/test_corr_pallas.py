"""Equivalence tests for the Pallas corr-lookup kernel (interpret mode on
CPU; the same kernel compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.ops.corr import build_corr_pyramid, corr_lookup
from raft_ncup_tpu.ops.corr_pallas import corr_lookup_pallas
from raft_ncup_tpu.ops.geometry import coords_grid

B, H, W, C = 2, 8, 12, 16
RADIUS = 3
LEVELS = 3  # deepest level is 2x3 — exercises tiny-volume handling


def setup():
    g = np.random.default_rng(0)
    fmap1 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
    fmap2 = jnp.asarray(g.normal(size=(B, H, W, C)), jnp.float32)
    return fmap1, fmap2


class TestPallasLookup:
    def test_matches_volume_path_on_grid(self):
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W)
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
        )
        out = corr_lookup_pallas(
            fmap1, fmap2, coords, RADIUS, LEVELS, True
        )
        assert out.shape == ref.shape
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_matches_volume_path_fractional_and_oob(self):
        fmap1, fmap2 = setup()
        g = np.random.default_rng(1)
        # Fractional offsets plus large displacements that push whole
        # windows out of bounds in every direction.
        coords = coords_grid(B, H, W) + jnp.asarray(
            g.uniform(-1.5 * max(H, W), 1.5 * max(H, W), (B, H, W, 2)),
            jnp.float32,
        ) * jnp.asarray(g.random((B, H, W, 2)) < 0.3, jnp.float32) + jnp.asarray(
            g.uniform(-0.99, 0.99, (B, H, W, 2)), jnp.float32
        )
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
        )
        out = corr_lookup_pallas(
            fmap1, fmap2, coords, RADIUS, LEVELS, True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_gradients_match_xla_path(self):
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W) + 0.3

        def loss_pallas(f1, f2, c):
            return (
                corr_lookup_pallas(f1, f2, c, RADIUS, LEVELS, True) ** 2
            ).sum()

        def loss_ref(f1, f2, c):
            pyr = build_corr_pyramid(f1, f2, LEVELS)
            return (corr_lookup(pyr, c, RADIUS) ** 2).sum()

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(fmap1, fmap2, coords)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(fmap1, fmap2, coords)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            )

    def test_query_count_not_multiple_of_group(self):
        """Adversarial (VERDICT r3 #3): H*W = 35 queries, not a multiple
        of the kernel's group-of-8 tiling — the tail group must still
        match the volume path exactly."""
        h, w = 5, 7
        g = np.random.default_rng(3)
        fmap1 = jnp.asarray(g.normal(size=(1, h, w, C)), jnp.float32)
        fmap2 = jnp.asarray(g.normal(size=(1, h, w, C)), jnp.float32)
        coords = coords_grid(1, h, w) + jnp.asarray(
            g.uniform(-2.0, 2.0, (1, h, w, 2)), jnp.float32
        )
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, 2), coords, RADIUS
        )
        out = corr_lookup_pallas(fmap1, fmap2, coords, RADIUS, 2, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_every_window_fully_out_of_bounds(self):
        """Adversarial: displacements larger than the image in all four
        directions — every tap of every window is OOB, output must be
        exactly the reference's (zeros), no clamping artifacts."""
        fmap1, fmap2 = setup()
        big = 4.0 * max(H, W)
        for dx, dy in ((big, 0.0), (-big, 0.0), (0.0, big), (-big, -big)):
            coords = coords_grid(B, H, W) + jnp.asarray(
                [dx, dy], jnp.float32
            )
            ref = corr_lookup(
                build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
            )
            out = corr_lookup_pallas(
                fmap1, fmap2, coords, RADIUS, LEVELS, True
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
            )

    def test_mixed_level_dispatch_matches(self, monkeypatch):
        """Adversarial: a VMEM budget that rejects level 0 but accepts
        deeper levels (the 1080p dispatch boundary) — the stitched
        kernel+fallback output must equal the pure XLA path."""
        from raft_ncup_tpu.ops import corr_pallas as cpk

        if cpk.pltpu is None:
            pytest.skip("pallas-tpu unavailable; dispatch loop can't "
                        "take the kernel branch")
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W) + 0.25
        ref = corr_lookup(
            build_corr_pyramid(fmap1, fmap2, LEVELS), coords, RADIUS
        )
        level0_bytes = cpk._level_vmem_bytes(H, W, C, RADIUS)
        dispatched = []
        real_fits = cpk.fits_vmem

        def fits(h, w, c, radius=4, dtype=None):
            ok = cpk._level_vmem_bytes(h, w, c, radius) < level0_bytes
            dispatched.append(((h, w), ok))
            return ok

        monkeypatch.setattr(cpk, "fits_vmem", fits)
        cpk.reset_dispatch_counts()
        out = corr_lookup_pallas(fmap1, fmap2, coords, RADIUS, LEVELS, True)
        monkeypatch.setattr(cpk, "fits_vmem", real_fits)
        # Level 0 fell back, at least one deeper level took the kernel —
        # and the module tally (bench.py's honesty signal) agrees.
        assert dispatched[0][1] is False
        assert any(ok for _, ok in dispatched[1:])
        counts = cpk.dispatch_counts()
        assert counts["levels_total"] == LEVELS
        assert counts["fallback"] >= 1 and counts["kernel"] >= 1
        assert counts["kernel"] + counts["fallback"] == LEVELS
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_all_levels_fallback_warns(self, monkeypatch):
        """ADVICE r3: when fits_vmem rejects every level, the 'pallas'
        label silently measures XLA — a warning must say so."""
        from raft_ncup_tpu.ops import corr_pallas as cpk

        if cpk.pltpu is None:
            pytest.skip("pallas-tpu unavailable; pltpu-None branch warns")
        fmap1, fmap2 = setup()
        coords = coords_grid(B, H, W)
        monkeypatch.setattr(cpk, "fits_vmem", lambda *a, **k: False)
        with pytest.warns(UserWarning, match="onthefly fallback for every"):
            cpk.corr_lookup_pallas(fmap1, fmap2, coords, RADIUS, LEVELS, True)

    def test_model_runs_with_pallas_impl(self):
        # On a non-TPU backend the model selects interpret mode itself
        # (models/raft.py), so corr_impl='pallas' works unpatched.
        from raft_ncup_tpu.config import small_model_config
        from raft_ncup_tpu.models.raft import RAFT

        cfg = small_model_config(
            "raft", dataset="chairs", corr_impl="pallas"
        )
        model = RAFT(cfg)
        shape = (1, 32, 48, 3)
        variables = model.init(jax.random.PRNGKey(0), shape)
        img = jnp.zeros(shape, jnp.float32)
        lr, up = model.apply(variables, img, img, iters=2, test_mode=True)
        assert up.shape == (1, 32, 48, 2)
        assert np.isfinite(np.asarray(up)).all()
