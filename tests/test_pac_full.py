"""Parity tests for the full PAC capability surface — strided/masked
adapting kernels, inv_* kernel types, smooth kernels, shared filters,
channel-wise pooling, and the PacConv2d/PacPool2d module wrappers —
against the PyTorch reference's native_impl code paths
(reference: core/pac_modules.py:332-494,498-816)."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REFERENCE = "/root/reference"
pytestmark = [
    pytest.mark.reference,
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REFERENCE, "core")),
        reason="reference repo not mounted",
    ),
]
if os.path.isdir(os.path.join(REFERENCE, "core")):
    sys.path.insert(0, os.path.join(REFERENCE, "core"))

import torch  # noqa: E402

from raft_ncup_tpu.ops.pac import (  # noqa: E402
    pac_kernel2d,
    pacconv2d,
    pacpool2d,
    smooth_kernel_2d,
)

B, C, H, W = 2, 3, 12, 14
K = 5


def rnp(seed, *shape):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def to_torch(x_nhwc):
    return torch.from_numpy(np.asarray(x_nhwc)).permute(0, 3, 1, 2).contiguous()


def to_np(t_nchw):
    return t_nchw.detach().permute(0, 2, 3, 1).numpy()


def ref_kernel(guide_nhwc, mask=None, **kw):
    import pac_modules as ref

    out, out_mask = ref.packernel2d(
        to_torch(guide_nhwc),
        mask=None if mask is None else to_torch(mask),
        native_impl=True,
        **kw,
    )
    # (B, ch, kh, kw, H', W') -> (B, H', W', k*k[, ch])
    b, ch, kh, kw_, h, w = out.shape
    out = out.reshape(b, ch, kh * kw_, h, w).permute(0, 3, 4, 2, 1)
    out = out.detach().numpy()
    if ch == 1:
        out = out[..., 0]
    return out, out_mask


class TestKernelParity:
    def setup_method(self):
        self.g = rnp(0, B, H, W, C)

    def check(self, ours, theirs, atol=1e-5):
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=atol, rtol=1e-4)

    def test_gaussian_same_pad(self):
        theirs, _ = ref_kernel(self.g, kernel_size=K, padding=2)
        ours, _ = pac_kernel2d(jnp.asarray(self.g), K, padding=2)
        self.check(ours, theirs)

    def test_gaussian_stride2_pad1(self):
        theirs, _ = ref_kernel(self.g, kernel_size=3, stride=2, padding=1)
        ours, _ = pac_kernel2d(jnp.asarray(self.g), 3, stride=2, padding=1)
        self.check(ours, theirs)

    def test_inv_kernel(self):
        theirs, _ = ref_kernel(
            self.g, kernel_size=K, padding=2, kernel_type="inv_0.5_2",
            inv_alpha=torch.tensor(0.5), inv_lambda=torch.tensor(2.0),
        )
        ours, _ = pac_kernel2d(
            jnp.asarray(self.g), K, padding=2, kernel_type="inv",
            inv_alpha=jnp.asarray(0.5), inv_lambda=jnp.asarray(2.0),
        )
        self.check(ours, theirs)

    def test_inv_asym_kernel(self):
        theirs, _ = ref_kernel(
            self.g, kernel_size=K, padding=2, kernel_type="inv_0.1_1_asym",
            inv_alpha=torch.tensor(0.1), inv_lambda=torch.tensor(1.0),
        )
        ours, _ = pac_kernel2d(
            jnp.asarray(self.g), K, padding=2, kernel_type="inv",
            inv_alpha=jnp.asarray(0.1), inv_lambda=jnp.asarray(1.0),
            asym=True,
        )
        self.check(ours, theirs)

    @pytest.mark.parametrize("smooth", ["gaussian", "average_3"])
    def test_smooth_kernel(self, smooth):
        import pac_modules as ref_mod

        sk = smooth_kernel_2d(smooth)
        theirs, _ = ref_kernel(
            self.g, kernel_size=K, padding=2, smooth_kernel_type=smooth,
            smooth_kernel=torch.from_numpy(np.asarray(sk))[None, None],
        )
        ours, _ = pac_kernel2d(
            jnp.asarray(self.g), K, padding=2, smooth_kernel=jnp.asarray(sk)
        )
        self.check(ours, theirs)

    def test_channel_wise(self):
        theirs, _ = ref_kernel(
            self.g, kernel_size=K, padding=2, channel_wise=True
        )
        ours, _ = pac_kernel2d(
            jnp.asarray(self.g), K, padding=2, channel_wise=True
        )
        self.check(ours, theirs)

    def test_normalize_kernel(self):
        theirs, _ = ref_kernel(
            self.g, kernel_size=K, padding=2, normalize_kernel=True
        )
        ours, _ = pac_kernel2d(
            jnp.asarray(self.g), K, padding=2, normalize_kernel=True
        )
        self.check(ours, theirs)

    def test_masked(self):
        """The reference's masked path crashes on modern torch
        (``1 - empty_mask`` on a bool tensor, core/pac_modules.py:419-421,
        written for torch 1.6), so masked semantics are checked against a
        direct computation of the same math: kernel' = gaussian * mask
        taps / (mask coverage / in-bounds coverage)."""
        mask = (rnp(9, B, H, W, 1) > 0).astype(np.float32)
        ours, ours_mask = pac_kernel2d(
            jnp.asarray(self.g), K, padding=2, mask=jnp.asarray(mask)
        )
        base, _ = pac_kernel2d(jnp.asarray(self.g), K, padding=2)
        from raft_ncup_tpu.ops.pac import extract_patches

        mpat = np.asarray(
            extract_patches(jnp.asarray(mask), K)[..., 0]
        )
        ones = np.asarray(
            extract_patches(jnp.ones((B, H, W, 1)), K)[..., 0]
        )
        cover = mpat.sum(-1, keepdims=True) / ones.sum(-1, keepdims=True)
        empty = (cover == 0).astype(np.float32)
        want = np.asarray(base) * mpat / (cover + empty)
        self.check(ours, want)
        assert ours_mask is not None
        np.testing.assert_array_equal(np.asarray(ours_mask), 1.0 - empty)


class TestConvPoolParity:
    def test_pacconv2d_strided(self):
        import pac_modules as ref

        x = rnp(1, B, H, W, C)
        g = rnp(2, B, H, W, C)
        w = rnp(3, K * K, C, 4)
        kt, _ = ref.packernel2d(
            to_torch(g), kernel_size=K, stride=2, padding=2, native_impl=True
        )
        theirs = ref.pacconv2d(
            to_torch(x), kt,
            torch.from_numpy(w.reshape(K, K, C, 4)).permute(3, 2, 0, 1),
            stride=2, padding=2, native_impl=True,
        )
        kj, _ = pac_kernel2d(jnp.asarray(g), K, stride=2, padding=2)
        ours = pacconv2d(
            jnp.asarray(x), kj, jnp.asarray(w),
            pad_lo=(2, 2), pad_hi=(2, 2), stride=2,
        )
        np.testing.assert_allclose(
            np.asarray(ours), to_np(theirs), atol=1e-4, rtol=1e-4
        )

    def test_pacconv2d_shared_filters(self):
        import pac_modules as ref

        x = rnp(4, B, H, W, C)
        g = rnp(5, B, H, W, C)
        w = rnp(6, K, K)
        kt, _ = ref.packernel2d(
            to_torch(g), kernel_size=K, padding=2, native_impl=True
        )
        theirs = ref.pacconv2d(
            to_torch(x), kt, torch.from_numpy(w)[None, None],
            padding=2, shared_filters=True, native_impl=True,
        )
        kj, _ = pac_kernel2d(jnp.asarray(g), K, padding=2)
        ours = pacconv2d(
            jnp.asarray(x), kj, jnp.asarray(w.reshape(-1)),
            pad_lo=(2, 2), pad_hi=(2, 2), shared_filters=True,
        )
        np.testing.assert_allclose(
            np.asarray(ours), to_np(theirs), atol=1e-4, rtol=1e-4
        )

    @pytest.mark.parametrize("channel_wise", [False, True])
    def test_pacpool2d(self, channel_wise):
        import pac_modules as ref

        x = rnp(7, B, H, W, C)
        g = rnp(8, B, H, W, C)
        kt, _ = ref.packernel2d(
            to_torch(g), kernel_size=3, stride=2, padding=1,
            channel_wise=channel_wise, native_impl=True,
        )
        theirs = ref.pacpool2d(
            to_torch(x), kt, 3, stride=2, padding=1, native_impl=True
        )
        kj, _ = pac_kernel2d(
            jnp.asarray(g), 3, stride=2, padding=1, channel_wise=channel_wise
        )
        ours = pacpool2d(jnp.asarray(x), kj, 3, stride=2, padding=1)
        np.testing.assert_allclose(
            np.asarray(ours), to_np(theirs), atol=1e-4, rtol=1e-4
        )


class TestModuleWrappers:
    def test_pacconv2d_module_matches_reference_module(self):
        import pac_modules as ref

        from raft_ncup_tpu.nn.pac import PacConv2d

        torch.manual_seed(0)
        tmod = ref.PacConv2d(
            C, 4, kernel_size=K, padding=2, native_impl=True
        )
        x = rnp(10, B, H, W, C)
        g = rnp(11, B, H, W, C)
        with torch.no_grad():
            theirs = tmod(to_torch(x), to_torch(g))

        jmod = PacConv2d(features=4, kernel_size=K, padding=2)
        variables = jmod.init(
            jax.random.key(0), jnp.asarray(x), jnp.asarray(g)
        )
        # Torch weight (out, in, kh, kw) -> (k*k, in, out).
        w = tmod.weight.detach().numpy().transpose(2, 3, 1, 0).reshape(
            K * K, C, 4
        )
        variables = {
            "params": {
                "weight": jnp.asarray(w),
                "bias": jnp.asarray(tmod.bias.detach().numpy()),
            }
        }
        ours = jmod.apply(variables, jnp.asarray(x), jnp.asarray(g))
        np.testing.assert_allclose(
            np.asarray(ours), to_np(theirs), atol=1e-4, rtol=1e-4
        )

    def test_pacconv2d_module_inv_learnable(self):
        from raft_ncup_tpu.nn.pac import PacConv2d

        x = jnp.asarray(rnp(12, 1, 8, 8, 2))
        g = jnp.asarray(rnp(13, 1, 8, 8, 2))
        mod = PacConv2d(
            features=3, kernel_size=3, padding=1, kernel_type="inv_0.5_2"
        )
        v = mod.init(jax.random.key(1), x, g)
        assert float(v["params"]["inv_alpha"]) == pytest.approx(0.5)
        assert float(v["params"]["inv_lambda"]) == pytest.approx(2.0)
        out = mod.apply(v, x, g)
        assert out.shape == (1, 8, 8, 3)
        # Learnable: gradients reach alpha/lambda.
        grads = jax.grad(
            lambda p: mod.apply({"params": p}, x, g).sum()
        )(v["params"])
        assert float(jnp.abs(grads["inv_alpha"])) > 0

    def test_pacpool2d_module_matches_reference_module(self):
        import pac_modules as ref

        from raft_ncup_tpu.nn.pac import PacPool2d

        x = rnp(14, B, H, W, C)
        g = rnp(15, B, H, W, C)
        tmod = ref.PacPool2d(
            kernel_size=3, stride=2, padding=1, channel_wise=True,
            out_channels=C, native_impl=True,
        )
        with torch.no_grad():
            theirs = tmod(to_torch(x), to_torch(g))

        jmod = PacPool2d(
            kernel_size=3, stride=2, padding=1, channel_wise=True,
            out_channels=C,
        )
        v = jmod.init(jax.random.key(2), jnp.asarray(x), jnp.asarray(g))
        ours = jmod.apply(v, jnp.asarray(x), jnp.asarray(g))
        np.testing.assert_allclose(
            np.asarray(ours), to_np(theirs), atol=1e-4, rtol=1e-4
        )

    def test_transpose_linear_filler_matches_reference_init(self):
        import pac_modules as ref

        from raft_ncup_tpu.nn.pac import PacConvTranspose2d

        tmod = ref.PacConvTranspose2d(
            2, 2, kernel_size=5, stride=2, padding=2, output_padding=1,
            filler="linear", native_impl=True,
        )
        jmod = PacConvTranspose2d(
            in_ch=2, out_ch=2, kernel_size=5, stride=2, padding=2,
            output_padding=1, filler="linear",
        )
        x = jnp.asarray(rnp(20, 1, 6, 6, 2))
        g = jnp.asarray(rnp(21, 1, 12, 12, 3))
        v = jmod.init(jax.random.key(4), x, g)
        # Torch transposed weight (in, out, kh, kw) -> (k*k, in, out).
        want = tmod.weight.detach().numpy().transpose(2, 3, 0, 1).reshape(
            25, 2, 2
        )
        np.testing.assert_allclose(np.asarray(v["params"]["weight"]), want)
        # And the full forward agrees with the reference native path.
        with torch.no_grad():
            theirs = tmod(to_torch(np.asarray(x)), to_torch(np.asarray(g)))
        ours = jmod.apply(v, x, g)
        np.testing.assert_allclose(
            np.asarray(ours), to_np(theirs), atol=1e-4, rtol=1e-4
        )

    def test_transpose_inv_kernel_runs(self):
        from raft_ncup_tpu.nn.pac import PacConvTranspose2d

        x = jnp.asarray(rnp(22, 1, 6, 6, 2))
        g = jnp.asarray(rnp(23, 1, 12, 12, 3))
        mod = PacConvTranspose2d(
            in_ch=2, out_ch=2, kernel_size=5, stride=2, padding=2,
            output_padding=1, kernel_type="inv_0.2_1",
        )
        v = mod.init(jax.random.key(5), x, g)
        assert float(v["params"]["inv_alpha"]) == pytest.approx(0.2)
        assert mod.apply(v, x, g).shape == (1, 12, 12, 2)

    def test_shared_filters_module(self):
        from raft_ncup_tpu.nn.pac import PacConv2d

        x = jnp.asarray(rnp(16, 1, 8, 8, 3))
        g = jnp.asarray(rnp(17, 1, 8, 8, 2))
        mod = PacConv2d(
            features=3, kernel_size=3, padding=1, shared_filters=True
        )
        v = mod.init(jax.random.key(3), x, g)
        assert v["params"]["weight"].shape == (9,)
        assert mod.apply(v, x, g).shape == (1, 8, 8, 3)
