"""Golden parity tests: run the read-only PyTorch reference on CPU with
random weights, import those weights, and compare full-model outputs.

These are the strongest correctness checks in the suite — they cover the
encoders, correlation, GRU recurrence, convex upsampling and the NCUP
stack end-to-end, at the numerical level.
"""

import argparse
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

REFERENCE = "/root/reference"
pytestmark = [
    pytest.mark.reference,
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REFERENCE, "core")),
        reason="reference repo not mounted",
    ),
]

if os.path.isdir(os.path.join(REFERENCE, "core")):
    sys.path.insert(0, os.path.join(REFERENCE, "core"))

import torch  # noqa: E402

from raft_ncup_tpu.config import ModelConfig, UpsamplerConfig  # noqa: E402
from raft_ncup_tpu.models import RAFT  # noqa: E402
from raft_ncup_tpu.utils.torch_import import import_torch_state  # noqa: E402

# Big enough that the deepest correlation level isn't degenerate.
H, W = 128, 160


def base_args(**kw):
    ns = argparse.Namespace(
        small=False,
        mixed_precision=False,
        align_corners=True,
        dropout=0.0,
        upsampler_bi=False,
    )
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def ncup_args(dataset="sintel", **kw):
    """The shipped NCUP flag set (reference: train_raft_nc_things.sh:31-50)."""
    return base_args(
        dataset=dataset,
        load_pretrained=None,
        freeze_raft=False,
        final_upsampling="NConvUpsampler",
        final_upsampling_scale=4,
        final_upsampling_use_data_for_guidance=True,
        final_upsampling_channels_to_batch=True,
        final_upsampling_use_residuals=False,
        final_upsampling_est_on_high_res=False,
        interp_net="NConvUNet",
        interp_net_channels_multiplier=2,
        interp_net_num_downsampling=1,
        interp_net_data_pooling="conf_based",
        interp_net_encoder_filter_sz=5,
        interp_net_decoder_filter_sz=3,
        interp_net_out_filter_sz=1,
        interp_net_shared_encoder=True,
        interp_net_use_double_conv=False,
        interp_net_use_bias=False,
        weights_est_net="Simple",
        weights_est_net_num_ch=[64, 32],
        weights_est_net_filter_sz=[3, 3, 1],
        weights_est_net_dilation=[1, 1, 1],
        **kw,
    )


def run_reference(model, img1, img2, iters):
    model.eval()
    with torch.no_grad():
        t1 = torch.from_numpy(img1).permute(0, 3, 1, 2).contiguous()
        t2 = torch.from_numpy(img2).permute(0, 3, 1, 2).contiguous()
        flow_lr, flow_up = model(t1, t2, iters=iters, test_mode=True)
    return (
        flow_lr.permute(0, 2, 3, 1).numpy(),
        flow_up.permute(0, 2, 3, 1).numpy(),
    )


def make_pair(seed=0):
    rng = np.random.default_rng(seed)
    img1 = rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32)
    return img1, img2


@pytest.mark.parametrize("small", [False, True])
def test_raft_parity(small):
    import raft as ref_raft
    from raft import RAFT as TorchRAFT

    if small:
        # The reference calls upflow8(..., align_corners=...) but its
        # definition takes (flow, mode) — a latent TypeError on the small
        # path (SURVEY.md §0.3). Patch the oracle with the intended
        # signature.
        import torch.nn.functional as F

        def upflow8_fixed(flow, align_corners=True):
            new_size = (8 * flow.shape[2], 8 * flow.shape[3])
            return 8 * F.interpolate(
                flow, size=new_size, mode="bilinear", align_corners=align_corners
            )

        ref_raft.upflow8 = upflow8_fixed

    torch.manual_seed(7)
    tmodel = TorchRAFT(base_args(small=small))
    state = {k: v.numpy() for k, v in tmodel.state_dict().items()}

    cfg = ModelConfig(variant="raft", small=small)
    ours = RAFT(cfg)
    import jax

    variables = ours.init(jax.random.key(0), (1, H, W, 3))
    variables = import_torch_state(state, variables, strict=True)

    img1, img2 = make_pair()
    t_lr, t_up = run_reference(tmodel, img1, img2, iters=3)
    j_lr, j_up = ours.apply(
        variables, jnp.asarray(img1), jnp.asarray(img2), iters=3, test_mode=True
    )

    np.testing.assert_allclose(np.asarray(j_lr), t_lr, atol=2e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(j_up), t_up, atol=5e-3, rtol=1e-3)


@pytest.mark.parametrize("dataset", ["sintel", "kitti"])
def test_raft_nc_dbl_parity(dataset):
    from raft_nc_dbl import RAFT as TorchNCUP

    torch.manual_seed(3)
    tmodel = TorchNCUP(ncup_args(dataset=dataset))
    state = {k: v.numpy() for k, v in tmodel.state_dict().items()}

    cfg = ModelConfig(variant="raft_nc_dbl", dataset=dataset)
    ours = RAFT(cfg)
    import jax

    variables = ours.init(jax.random.key(0), (1, H, W, 3))
    variables = import_torch_state(state, variables, strict=True)

    img1, img2 = make_pair(1)
    t_lr, t_up = run_reference(tmodel, img1, img2, iters=2)
    j_lr, j_up = ours.apply(
        variables, jnp.asarray(img1), jnp.asarray(img2), iters=2, test_mode=True
    )

    np.testing.assert_allclose(np.asarray(j_lr), t_lr, atol=2e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(j_up), t_up, atol=5e-3, rtol=1e-3)


def test_train_mode_sequence_parity():
    """Training-mode forward returns all per-iteration predictions
    (reference: core/raft.py:119-143)."""
    from raft import RAFT as TorchRAFT

    torch.manual_seed(11)
    tmodel = TorchRAFT(base_args())
    state = {k: v.numpy() for k, v in tmodel.state_dict().items()}
    cfg = ModelConfig(variant="raft")
    ours = RAFT(cfg)
    import jax

    variables = ours.init(jax.random.key(0), (1, H, W, 3))
    variables = import_torch_state(state, variables, strict=True)

    img1, img2 = make_pair(2)
    # Reference in eval() to freeze BN stats, but full prediction list.
    tmodel.eval()
    with torch.no_grad():
        t1 = torch.from_numpy(img1).permute(0, 3, 1, 2)
        t2 = torch.from_numpy(img2).permute(0, 3, 1, 2)
        preds = tmodel(t1, t2, iters=3)
    theirs = np.stack([p.permute(0, 2, 3, 1).numpy() for p in preds])

    flows = ours.apply(
        variables, jnp.asarray(img1), jnp.asarray(img2), iters=3, train=False
    )
    np.testing.assert_allclose(np.asarray(flows), theirs, atol=5e-3, rtol=1e-3)


def test_load_raft_trunk_into_ncup():
    """load_pretrained semantics: a plain RAFT checkpoint warm-starts the
    raft_nc_dbl trunk (reference: core/raft_nc_dbl.py:57-66); the mask-head
    weights are dropped."""
    from raft import RAFT as TorchRAFT

    torch.manual_seed(5)
    tmodel = TorchRAFT(base_args())
    state = {"module." + k: v.numpy() for k, v in tmodel.state_dict().items()}

    cfg = ModelConfig(variant="raft_nc_dbl", dataset="kitti")
    ours = RAFT(cfg)
    import jax

    variables = ours.init(jax.random.key(0), (1, H, W, 3))
    merged = import_torch_state(state, variables, strict=False)

    got = merged["params"]["fnet"]["conv1"]["kernel"]
    want = state["module.fnet.conv1.weight"].transpose(2, 3, 1, 0)
    np.testing.assert_allclose(np.asarray(got), want)
    # Upsampler params untouched (fresh init).
    assert "interpolation_net" in merged["params"]["upsampler"]


def test_load_pretrained_trunk_from_stock_raft_pth(tmp_path):
    """Regression: ``--load_pretrained models/raft-things.pth`` style
    warm start. A stock RAFT checkpoint carries ``update_block.mask.*``
    keys; the raft_nc_dbl destination has no mask head, and the strict
    trunk load must skip exactly those (the reference loads the full
    state dict *before* deleting the head — core/raft_nc_dbl.py:57-68)
    while still raising on genuinely unknown keys."""
    from raft import RAFT as TorchRAFT

    from raft_ncup_tpu.training.checkpoint import load_pretrained_trunk
    from raft_ncup_tpu.utils.torch_import import strip_module_prefix

    torch.manual_seed(7)
    tmodel = TorchRAFT(base_args())
    state = {"module." + k: v for k, v in tmodel.state_dict().items()}
    assert any(".mask." in k for k in state)  # stock RAFT has the head
    path = tmp_path / "raft-things.pth"
    torch.save(state, path)

    cfg = ModelConfig(variant="raft_nc_dbl", dataset="kitti")
    ours = RAFT(cfg)
    import jax

    variables = ours.init(jax.random.key(1), (1, H, W, 3))
    assert "mask_conv1" not in variables["params"]["update_block"]

    merged = load_pretrained_trunk(str(path), variables)
    got = merged["params"]["fnet"]["conv1"]["kernel"]
    want = state["module.fnet.conv1.weight"].numpy().transpose(2, 3, 1, 0)
    np.testing.assert_allclose(np.asarray(got), want)

    # Unknown keys outside the mask-head allowlist still fail loudly.
    bogus = dict(strip_module_prefix({k: v.numpy() for k, v in state.items()}))
    bogus["definitely_not_a_module.weight"] = np.zeros((3, 3), np.float32)
    with pytest.raises(KeyError):
        import_torch_state(
            bogus, variables, strict=True,
            allow_unmatched=(r"^update_block\.mask\.",),
        )
