"""Tests for orbax checkpoint/resume, trunk warm-start, and the logger."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import (
    ModelConfig,
    TrainConfig,
    small_model_config,
)
from raft_ncup_tpu.training.checkpoint import (
    CheckpointManager,
    load_pretrained_trunk,
)
from raft_ncup_tpu.training.logger import Logger
from raft_ncup_tpu.training.state import create_train_state

SHAPE = (1, 32, 48, 3)


def tiny_upsampler_overrides():
    from raft_ncup_tpu.config import UpsamplerConfig

    return UpsamplerConfig(weights_est_num_ch=(8, 8))


@pytest.fixture(scope="module")
def raft_state():
    cfg = small_model_config("raft", dataset="chairs")
    tcfg = TrainConfig(stage="chairs", batch_size=1, image_size=(32, 48), num_steps=10)
    return create_train_state(jax.random.PRNGKey(0), cfg, tcfg, SHAPE)


class TestCheckpointRoundtrip:
    def test_save_restore_exact(self, tmp_path, raft_state):
        model, state = raft_state
        state = state.replace(step=jnp.asarray(7, jnp.int32))
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(state)
        mgr.wait()
        assert mgr.latest_step == 7

        # Perturb, then restore into the perturbed structure.
        wrecked = state.replace(
            step=jnp.zeros((), jnp.int32),
            params=jax.tree.map(lambda x: x * 0.0, state.params),
        )
        restored = mgr.restore(wrecked)
        assert int(restored.step) == 7
        orig = jax.tree.leaves(state.params)
        back = jax.tree.leaves(restored.params)
        for a, b in zip(orig, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # Optimizer moments restored too.
        for a, b in zip(
            jax.tree.leaves(state.opt_state), jax.tree.leaves(restored.opt_state)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        mgr.close()

    def test_max_to_keep(self, tmp_path, raft_state):
        _, state = raft_state
        mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
        for s in (1, 2, 3):
            mgr.save(state, step=s)
        mgr.wait()
        assert mgr.latest_step == 3
        steps = sorted(
            int(d) for d in os.listdir(tmp_path / "ckpt") if d.isdigit()
        )
        assert steps == [2, 3]
        mgr.close()

    def test_restore_empty_raises(self, tmp_path, raft_state):
        _, state = raft_state
        mgr = CheckpointManager(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            mgr.restore(state)
        mgr.close()


class TestTrunkWarmStart:
    def test_orbax_raft_into_nc_dbl(self, tmp_path):
        # Train-state checkpoint of a small RAFT...
        raft_cfg = small_model_config("raft", dataset="chairs")
        tcfg = TrainConfig(stage="chairs", batch_size=1, image_size=(32, 48), num_steps=10)
        _, src_state = create_train_state(
            jax.random.PRNGKey(1), raft_cfg, tcfg, SHAPE
        )
        mgr = CheckpointManager(str(tmp_path / "raft_ckpt"))
        mgr.save(src_state, step=5)
        mgr.wait()
        mgr.close()

        # ...warm-starts the trunk of a small raft_nc_dbl.
        ncup_cfg = ModelConfig(
            variant="raft_nc_dbl",
            small=True,
            dataset="chairs",
            upsampler=tiny_upsampler_overrides(),
        )
        from raft_ncup_tpu.models.raft import RAFT

        model = RAFT(ncup_cfg)
        dest = model.init(jax.random.PRNGKey(2), SHAPE)
        before_up = jax.tree.leaves(dest["params"]["upsampler"])

        merged = load_pretrained_trunk(str(tmp_path / "raft_ckpt"), dest)
        # Trunk params replaced by source values...
        src_leaf = jax.tree.leaves(src_state.params["fnet"])[0]
        dst_leaf = jax.tree.leaves(merged["params"]["fnet"])[0]
        np.testing.assert_array_equal(np.asarray(src_leaf), np.asarray(dst_leaf))
        # ...upsampler untouched.
        after_up = jax.tree.leaves(merged["params"]["upsampler"])
        for a, b in zip(before_up, after_up):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # Model still runs with merged variables.
        img = jnp.zeros(SHAPE, jnp.float32)
        lr_flow, up = model.apply(merged, img, img, iters=2, test_mode=True)
        assert up.shape == (1, 32, 48, 2)


class TestLogger:
    def test_push_and_val(self, tmp_path, capsys):
        logger = Logger(
            str(tmp_path / "run"), config=TrainConfig(), sum_freq=2,
            use_tensorboard=False,
        )
        logger.push(0, {"loss": 2.0, "epe": 4.0}, lr=1e-4)
        logger.push(1, {"loss": 1.0, "epe": 2.0}, lr=1e-4)  # triggers summary
        logger.write_dict(2, {"chairs_epe": 3.5})
        logger.close()
        text = (tmp_path / "run" / "log.txt").read_text()
        assert "loss 1.5000" in text and "epe 3.0000" in text
        assert "chairs_epe" in text
        out = capsys.readouterr().out
        assert "loss 1.5000" in out
