"""PAC primitive parity tests (vs the PyTorch reference's native_impl
code paths) and PAC/DJIF head behavior tests."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REFERENCE = "/root/reference"
HAVE_REF = os.path.isdir(os.path.join(REFERENCE, "core"))
if HAVE_REF:
    sys.path.insert(0, os.path.join(REFERENCE, "core"))

from raft_ncup_tpu.ops.pac import (  # noqa: E402
    extract_patches,
    pac_gaussian_kernel,
    pacconv2d,
    pacconv_transpose2d,
)

B, C, H, W = 2, 3, 10, 12
K = 5


def rnp(seed, *shape):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestPrimitives:
    def test_patches_center_is_input(self):
        x = jnp.asarray(rnp(0, B, H, W, C))
        p = extract_patches(x, K)
        assert p.shape == (B, H, W, K * K, C)
        np.testing.assert_allclose(p[:, :, :, (K * K) // 2, :], x)

    def test_kernel_center_is_one_and_uniform_guide_all_ones(self):
        g = jnp.asarray(rnp(1, B, H, W, C))
        k = pac_gaussian_kernel(g, K)
        assert k.shape == (B, H, W, K * K)
        np.testing.assert_allclose(k[:, :, :, (K * K) // 2], 1.0, atol=1e-6)
        ku = pac_gaussian_kernel(jnp.ones((B, H, W, C)), K)
        # Interior windows see identical features -> all taps 1; borders
        # see zero padding -> < 1.
        np.testing.assert_allclose(ku[:, 2:-2, 2:-2, :], 1.0, atol=1e-6)

    def test_uniform_kernel_equals_plain_conv(self):
        x = jnp.asarray(rnp(2, B, H, W, C))
        w = jnp.asarray(rnp(3, K * K, C, 4))
        ones_kernel = jnp.ones((B, H, W, K * K))
        out = pacconv2d(x, ones_kernel, w)
        ref = jax.lax.conv_general_dilated(
            x,
            w.reshape(K, K, C, 4),
            window_strides=(1, 1),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_gradients_flow(self):
        x = jnp.asarray(rnp(4, 1, 6, 6, 2))
        g = jnp.asarray(rnp(5, 1, 12, 12, 3))
        w = jnp.asarray(rnp(6, K * K, 2, 2))

        def loss(x, g, w):
            kern = pac_gaussian_kernel(g, K)
            out = pacconv_transpose2d(
                x, kern, w, stride=2, padding=2, output_padding=1
            )
            return (out**2).sum()

        grads = jax.grad(loss, argnums=(0, 1, 2))(x, g, w)
        for gr in grads:
            assert np.isfinite(np.asarray(gr)).all()
            assert float(jnp.abs(gr).max()) > 0


@pytest.mark.reference
@pytest.mark.skipif(not HAVE_REF, reason="reference repo not mounted")
class TestTorchParity:
    def _torch(self):
        import torch

        import pac_modules as pm

        return torch, pm

    def test_gaussian_kernel_parity(self):
        torch, pm = self._torch()
        g = rnp(7, B, C, H, W)
        ref, _ = pm.packernel2d(
            torch.from_numpy(g), kernel_size=K, stride=1, padding=2,
            dilation=1, kernel_type="gaussian", smooth_kernel_type="none",
            normalize_kernel=False, transposed=False, native_impl=True,
        )
        ref = ref.detach().numpy()  # (B, 1, K, K, H, W)
        ours = np.asarray(
            pac_gaussian_kernel(jnp.asarray(g.transpose(0, 2, 3, 1)), K)
        )  # (B, H, W, K*K)
        ref_r = ref.reshape(B, K * K, H, W).transpose(0, 2, 3, 1)
        np.testing.assert_allclose(ours, ref_r, rtol=1e-4, atol=1e-5)

    def test_pacconv2d_parity(self):
        torch, pm = self._torch()
        x = rnp(8, B, C, H, W)
        g = rnp(9, B, 2, H, W)
        wt = rnp(10, 4, C, K, K)  # (Cout, Cin, kh, kw)
        bias = rnp(11, 4)

        kern_t, _ = pm.packernel2d(
            torch.from_numpy(g), kernel_size=K, stride=1, padding=2,
            dilation=1, kernel_type="gaussian", smooth_kernel_type="none",
            normalize_kernel=False, transposed=False, native_impl=True,
        )
        ref = pm.pacconv2d(
            torch.from_numpy(x), kern_t, torch.from_numpy(wt),
            torch.from_numpy(bias), stride=1, padding=2, dilation=1,
            native_impl=True,
        ).detach().numpy()

        kern = pac_gaussian_kernel(jnp.asarray(g.transpose(0, 2, 3, 1)), K)
        w_ours = jnp.asarray(wt.transpose(2, 3, 1, 0).reshape(K * K, C, 4))
        ours = pacconv2d(
            jnp.asarray(x.transpose(0, 2, 3, 1)), kern, w_ours,
            jnp.asarray(bias),
        )
        np.testing.assert_allclose(
            np.asarray(ours), ref.transpose(0, 2, 3, 1), rtol=1e-3, atol=1e-4
        )

    def test_pacconv_transpose2d_parity(self):
        torch, pm = self._torch()
        Cin, Cout = 3, 2
        x = rnp(12, B, Cin, H, W)
        g_hr = rnp(13, B, 2, H * 2, W * 2)
        wt = rnp(14, Cin, Cout, K, K)  # torch convT layout (in, out, kh, kw)
        bias = rnp(15, Cout)

        kern_t, _ = pm.packernel2d(
            torch.from_numpy(g_hr), kernel_size=K, stride=2, padding=2,
            output_padding=1, dilation=1, kernel_type="gaussian",
            smooth_kernel_type="none", normalize_kernel=False,
            transposed=True, native_impl=True,
        )
        ref = pm.pacconv_transpose2d(
            torch.from_numpy(x), kern_t, torch.from_numpy(wt),
            torch.from_numpy(bias), stride=2, padding=2, output_padding=1,
            native_impl=True,
        ).detach().numpy()
        assert ref.shape == (B, Cout, H * 2, W * 2)

        kern = pac_gaussian_kernel(
            jnp.asarray(g_hr.transpose(0, 2, 3, 1)), K
        )
        w_ours = jnp.asarray(
            wt.transpose(2, 3, 0, 1).reshape(K * K, Cin, Cout)
        )
        ours = pacconv_transpose2d(
            jnp.asarray(x.transpose(0, 2, 3, 1)), kern, w_ours,
            jnp.asarray(bias), stride=2, padding=2, output_padding=1,
        )
        np.testing.assert_allclose(
            np.asarray(ours), ref.transpose(0, 2, 3, 1), rtol=1e-3, atol=1e-4
        )


class TestHeads:
    def test_pac_joint_upsample_shapes_and_grads(self):
        from raft_ncup_tpu.nn.pac import PacJointUpsample

        head = PacJointUpsample(factor=4, channels=2, guide_channels=8)
        x = jnp.asarray(rnp(16, 1, 6, 8, 2))
        g = jnp.asarray(rnp(17, 1, 24, 32, 8))
        params = head.init(jax.random.PRNGKey(0), x, g)
        out = head.apply(params, x, g)
        assert out.shape == (1, 24, 32, 2)
        assert np.isfinite(np.asarray(out)).all()

        grads = jax.grad(
            lambda p: (head.apply(p, x, g) ** 2).sum()
        )(params)
        assert all(
            np.isfinite(np.asarray(le)).all() for le in jax.tree.leaves(grads)
        )

    def test_djif_shapes(self):
        from raft_ncup_tpu.nn.pac import DJIF

        head = DJIF(factor=4, channels=2, guide_channels=8)
        x = jnp.asarray(rnp(18, 1, 6, 8, 2))
        g = jnp.asarray(rnp(19, 1, 24, 32, 8))
        params = head.init(jax.random.PRNGKey(0), x, g)
        out = head.apply(params, x, g)
        assert out.shape == (1, 24, 32, 2)

    def test_joint_bilateral_constant_field(self):
        from raft_ncup_tpu.nn.pac import JointBilateral

        head = JointBilateral(factor=2, kernel_size=5)
        x = jnp.full((1, 6, 8, 2), 3.0)
        g = jnp.zeros((1, 12, 16, 1))
        params = head.init(jax.random.PRNGKey(0), x, g)
        out = head.apply(params, x, g)
        assert out.shape == (1, 12, 16, 2)
        # Identity weights + normalized kernel on a constant field must
        # reproduce the constant.
        np.testing.assert_allclose(np.asarray(out), 3.0, rtol=1e-5)

    def test_registry_builds_pac_and_djif(self):
        from raft_ncup_tpu.config import UpsamplerConfig
        from raft_ncup_tpu.nn.upsampler import build_upsampler

        for kind in ("pac", "djif"):
            cfg = UpsamplerConfig(kind=kind, scale=4)
            mod = build_upsampler(cfg, dataset="things")
            x = jnp.asarray(rnp(20, 1, 4, 6, 2))
            g = jnp.asarray(rnp(21, 1, 4, 6, 16))
            params = mod.init(jax.random.PRNGKey(0), x, g)
            out = mod.apply(params, x, g)
            assert out.shape == (1, 16, 24, 2)
