"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip sharding (data x spatial meshes) is tested on virtual CPU
devices, mirroring how the driver dry-runs the multi-chip path
(``xla_force_host_platform_device_count``).
"""

import os

# Overwrite, not setdefault: the axon TPU boot hook (sitecustomize) sets
# JAX_PLATFORMS=axon for every interpreter; tests run on virtual CPU
# devices so the sharded paths can be exercised without a pod.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon boot hook may have imported jax already (baking JAX_PLATFORMS=axon
# into jax.config before this file runs), so update the config directly too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture
def forbid_host_transfers():
    """The runtime guard as a fixture: a context-manager factory.
    ``with forbid_host_transfers() as stats: ...`` raises GuardViolation
    on any implicit device->host pull inside the scope (explicit
    jax.device_get stays sanctioned)."""
    from raft_ncup_tpu.analysis.guards import forbid_host_transfers as fht

    return fht


@pytest.fixture
def max_recompiles():
    """Compile-budget guard as a fixture: ``with max_recompiles(1): ...``
    raises GuardViolation when the scope compiles more than n times."""
    from raft_ncup_tpu.analysis.guards import max_recompiles as mr

    return mr


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "reference: tests that import the read-only reference repo"
    )
    config.addinivalue_line("markers", "slow: long-running tests")
