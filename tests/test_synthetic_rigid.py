"""Piecewise-rigid synthetic data + boundary-band EPE (VERDICT r4 #2).

The rigid generator renders both frames independently from parametric
surface motions (background + 2-4 shapes), so the GT flow is exact,
sharply discontinuous at shape boundaries, and includes real occlusion —
the data on which guided (NCUP) upsampling can beat bilinear (reference
claim: core/upsampler.py:75-210). No reference analogue: the reference
only loads such data (core/datasets.py:169-186), never generates it.
"""

import numpy as np
import pytest

from raft_ncup_tpu.data.synthetic import (
    SyntheticFlowDataset,
    flow_boundary_mask,
    make_rigid_pair,
)


class TestRigidPair:
    def test_deterministic_per_seed_index(self):
        ds = SyntheticFlowDataset((48, 64), length=4, seed=5, style="rigid")
        a, b = ds.sample(2), ds.sample(2)
        np.testing.assert_array_equal(a["image1"], b["image1"])
        np.testing.assert_array_equal(a["flow"], b["flow"])
        c = ds.sample(3)
        assert np.abs(a["flow"] - c["flow"]).max() > 0.1

    def test_shapes_and_dtypes(self):
        p = make_rigid_pair(np.random.default_rng(0), (40, 56))
        assert p["image1"].shape == (40, 56, 3) and p["image1"].dtype == np.uint8
        assert p["flow"].shape == (40, 56, 2) and p["flow"].dtype == np.float32
        assert p["valid"].shape == (40, 56)

    def test_flow_has_sharp_discontinuities(self):
        """The point of the rigid style: per-pixel flow jumps at shape
        boundaries that the smooth style cannot produce."""
        p = make_rigid_pair(np.random.default_rng(1), (96, 128))
        gx = np.abs(np.diff(p["flow"], axis=1)).sum(-1)
        assert gx.max() > 2.0  # a multi-pixel jump between adjacent pixels
        smooth = SyntheticFlowDataset((96, 128), length=1, seed=1).sample(0)
        gxs = np.abs(np.diff(smooth["flow"], axis=1)).sum(-1)
        assert gx.max() > 4 * gxs.max()

    @pytest.mark.parametrize("seed", [0, 3])
    def test_photometric_consistency_away_from_occlusion(self, seed):
        """Backward-warping frame 2 by the GT flow reproduces frame 1 away
        from boundaries (interior error ~ bilinear resampling noise); the
        boundary band carries genuine occlusion error."""
        import cv2

        h, w = 96, 128
        p = make_rigid_pair(np.random.default_rng(seed), (h, w))
        xx, yy = np.meshgrid(np.arange(w, dtype=np.float32),
                             np.arange(h, dtype=np.float32))
        warped = cv2.remap(
            p["image2"].astype(np.float32),
            xx + p["flow"][..., 0], yy + p["flow"][..., 1],
            cv2.INTER_LINEAR, borderMode=cv2.BORDER_REFLECT,
        )
        err = np.abs(warped - p["image1"].astype(np.float32)).mean(-1)
        band = flow_boundary_mask(p["flow"])
        assert err[~band].mean() < 4.0
        assert err[band].mean() > err[~band].mean()

    def test_boundary_mask_sane(self):
        p = make_rigid_pair(np.random.default_rng(2), (96, 128))
        band = flow_boundary_mask(p["flow"])
        assert 0.01 < band.mean() < 0.6
        # smooth flow has (almost) no boundary pixels at the same threshold
        smooth = SyntheticFlowDataset((96, 128), length=1, seed=2).sample(0)
        assert flow_boundary_mask(smooth["flow"]).mean() < band.mean()

    def test_unknown_style_raises(self):
        with pytest.raises(ValueError, match="style"):
            SyntheticFlowDataset((32, 32), style="cubist")


def test_fetch_training_set_respects_style(tmp_path):
    from raft_ncup_tpu.config import DataConfig
    from raft_ncup_tpu.data import fetch_training_set

    cfg = DataConfig(
        root_chairs=str(tmp_path / "nope"), synthetic_ok=True,
        synthetic_style="rigid",
    )
    ds = fetch_training_set("chairs", (32, 48), cfg)
    assert isinstance(ds, SyntheticFlowDataset) and ds.style == "rigid"


def test_validate_synthetic_rigid_reports_boundary_epe():
    import jax

    from raft_ncup_tpu.config import small_model_config
    from raft_ncup_tpu.evaluation import validate_synthetic_rigid
    from raft_ncup_tpu.models import get_model

    model = get_model(small_model_config("raft", dataset="chairs"))
    variables = model.init(jax.random.PRNGKey(0), (1, 32, 48, 3))
    out = validate_synthetic_rigid(
        model, variables, iters=2, batch_size=2, size_hw=(32, 48), length=4
    )
    assert set(out) == {
        "synthetic_rigid", "synthetic_rigid_bnd", "synthetic_rigid_interior"
    }
    assert all(np.isfinite(v) for v in out.values())
