"""Async inference subsystem (raft_ncup_tpu/inference/): pipeline
contracts (order, exceptions, clean close), the bounded shape cache, the
device-resident metric parity against the pre-refactor host NumPy
formulas, and the eval loop's sync-free/recompile-free invariants under
the runtime guards.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import DataConfig, small_model_config
from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
from raft_ncup_tpu.inference import metrics as metrics_mod
from raft_ncup_tpu.inference.pipeline import (
    AsyncDrain,
    DispatchThrottle,
    EvalPipeline,
    SamplePrefetcher,
    ShapeCachedForward,
    uniform_batches,
)
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.ops import InputPadder


# ------------------------------------------------------------- test rigs


class _ListDataset:
    """Minimal dataset protocol over a list of sample dicts."""

    def __init__(self, samples):
        self._samples = samples

    def __len__(self):
        return len(self._samples)

    def sample(self, index):
        return self._samples[index]


class _FailingDataset(_ListDataset):
    def __init__(self, samples, fail_at: int):
        super().__init__(samples)
        self._fail_at = fail_at

    def sample(self, index):
        if index == self._fail_at:
            raise ValueError(f"decode failed at {index}")
        return super().sample(index)


class _DummyModel:
    """apply()-compatible stand-in whose jitted programs compile
    instantly — exercises the cache/LRU machinery without RAFT compiles."""

    def apply(self, variables, image1, image2, iters=1, flow_init=None,
              test_mode=True, mesh=None, metric_head=None, **kw):
        flow_up = jnp.stack([image1[..., 0], image1[..., 1]], axis=-1)
        if metric_head is not None:
            return image1.mean(), metric_head(flow_up)
        return image1.mean(), flow_up


def _mk_samples(n, hw=(8, 10)):
    g = np.random.default_rng(3)
    return [
        {
            "image1": g.random((*hw, 3), np.float32),
            "image2": g.random((*hw, 3), np.float32),
            "flow": g.random((*hw, 2), np.float32),
        }
        for _ in range(n)
    ]


# ----------------------------------------------------- SamplePrefetcher


class TestSamplePrefetcher:
    def test_order_and_contents(self):
        samples = _mk_samples(7)
        with SamplePrefetcher(_ListDataset(samples), num_workers=3,
                              lookahead=2) as sp:
            got = list(sp)
        assert len(got) == 7
        for a, b in zip(got, samples):
            np.testing.assert_array_equal(a["image1"], b["image1"])

    def test_exception_propagates_and_pool_closes(self):
        sp = SamplePrefetcher(
            _FailingDataset(_mk_samples(6), fail_at=3), num_workers=2
        )
        got = []
        with pytest.raises(ValueError, match="decode failed at 3"):
            for s in sp:
                got.append(s)
        assert len(got) == 3
        assert sp._pool._shutdown  # pool joined, no leaked threads

    def test_early_exit_closes_pool(self):
        """The old _prefetch_samples generator, abandoned mid-validation,
        left its pool threads parked forever; the context manager (and
        close()) must tear them down."""
        sp = SamplePrefetcher(_ListDataset(_mk_samples(16)), num_workers=2)
        next(iter(sp))
        sp.close()
        assert sp._pool._shutdown
        sp.close()  # idempotent

    def test_exhaustion_closes_pool(self):
        sp = SamplePrefetcher(_ListDataset(_mk_samples(3)), num_workers=2)
        list(sp)
        assert sp._pool._shutdown


# ------------------------------------------------------ uniform_batches


class TestUniformBatches:
    def test_groups_and_shape_breaks(self):
        a = {"image1": np.zeros((4, 6, 3), np.float32)}
        b = {"image1": np.zeros((6, 4, 3), np.float32)}
        groups = list(uniform_batches(iter([a, a, a, b, b, a]), 2))
        sizes = [len(g) for g in groups]
        assert sizes == [2, 1, 2, 1]  # short group at each shape change


# --------------------------------------------------------- EvalPipeline


class TestEvalPipeline:
    @staticmethod
    def _stage(group):
        return (
            {"image1": np.stack([s["image1"] for s in group])},
            {"n": len(group)},
        )

    def test_yields_device_batches_with_aligned_meta(self):
        samples = _mk_samples(5)
        with EvalPipeline(
            _ListDataset(samples), self._stage, batch_size=2
        ) as pipe:
            out = list(pipe)
        assert [m["n"] for _, m in out] == [2, 2, 1]
        assert all(isinstance(b["image1"], jax.Array) for b, _ in out)
        np.testing.assert_allclose(
            np.asarray(out[0][0]["image1"][1]), samples[1]["image1"],
            rtol=1e-6,
        )

    def test_stage_exception_propagates(self):
        def bad_stage(group):
            raise RuntimeError("stage blew up")

        with pytest.raises(RuntimeError, match="stage blew up"):
            with EvalPipeline(
                _ListDataset(_mk_samples(4)), bad_stage, batch_size=2
            ) as pipe:
                list(pipe)

    def test_decode_exception_propagates(self):
        with pytest.raises(ValueError, match="decode failed"):
            with EvalPipeline(
                _FailingDataset(_mk_samples(6), fail_at=2),
                self._stage,
                batch_size=2,
            ) as pipe:
                list(pipe)

    def test_close_mid_epoch_leaks_no_threads(self):
        pipe = EvalPipeline(
            _ListDataset(_mk_samples(32)), self._stage, batch_size=2
        )
        next(iter(pipe))
        pipe.close()
        deadline = time.time() + 5.0
        while pipe._pf._thread.is_alive() and time.time() < deadline:
            time.sleep(0.01)
        assert not pipe._pf._thread.is_alive()
        assert pipe._sp._pool._shutdown


# ----------------------------------------------------------- AsyncDrain


class TestAsyncDrain:
    def test_order_preserving_callbacks(self):
        got = []
        with AsyncDrain(depth=2) as drain:
            for i in range(6):
                drain.submit(
                    jnp.full((3,), i),
                    lambda host, i=i: got.append((i, float(host[0]))),
                )
        assert got == [(i, float(i)) for i in range(6)]

    def test_callback_error_reraises(self):
        drain = AsyncDrain(depth=1)

        def boom(host):
            raise RuntimeError("writer failed")

        drain.submit(jnp.zeros(()), boom)
        with pytest.raises(RuntimeError, match="writer failed"):
            for _ in range(50):
                drain.submit(jnp.zeros(()), lambda host: None)
                time.sleep(0.01)
            drain.close()

    def test_close_flushes_pending(self):
        got = []
        drain = AsyncDrain(depth=4)
        for i in range(4):
            drain.submit(jnp.full((1,), i), lambda h, i=i: got.append(i))
        drain.close()
        assert got == [0, 1, 2, 3]
        assert not drain._thread.is_alive()


# ----------------------------------------------------- DispatchThrottle


class TestDispatchThrottle:
    def test_bounds_pending_and_drains(self):
        th = DispatchThrottle(inflight=2)
        xs = [jnp.full((2,), i) for i in range(5)]
        for x in xs:
            th.push(x)
            assert len(th._pending) <= 1  # <= inflight - 1 after push
        th.drain()
        assert not th._pending

    def test_serial_mode_keeps_nothing_pending(self):
        th = DispatchThrottle(inflight=1)
        th.push(jnp.zeros((2,)))
        assert not th._pending


# ------------------------------------------------- ShapeCachedForward LRU


class TestShapeCacheLRU:
    def _fwd(self, cache_size):
        return ShapeCachedForward(
            _DummyModel(), {"params": {}}, cache_size=cache_size
        )

    def _img(self, h, w):
        return np.zeros((1, h, w, 3), np.float32)

    def test_bounded_lru_evicts_and_counts(self, capsys):
        fwd = self._fwd(cache_size=2)
        fwd.forward_device(self._img(8, 8), self._img(8, 8), iters=1)
        fwd.forward_device(self._img(8, 16), self._img(8, 16), iters=1)
        assert fwd.stats == {"compiles": 2, "hits": 0, "evictions": 0}
        # Third shape evicts the least-recently-used first shape, loudly.
        fwd.forward_device(self._img(16, 8), self._img(16, 8), iters=1)
        assert fwd.stats["evictions"] == 1
        assert "EVICTING compiled executable" in capsys.readouterr().err
        # The evicted shape recompiles; the resident one hits.
        fwd.forward_device(self._img(8, 16), self._img(8, 16), iters=1)
        assert fwd.stats["hits"] == 1
        fwd.forward_device(self._img(8, 8), self._img(8, 8), iters=1)
        assert fwd.stats["compiles"] == 4
        assert fwd.stats["evictions"] == 2

    def test_lru_recency_order(self):
        fwd = self._fwd(cache_size=2)
        fwd.forward_device(self._img(8, 8), self._img(8, 8), iters=1)
        fwd.forward_device(self._img(8, 16), self._img(8, 16), iters=1)
        # Touch the first entry so the SECOND is now least-recent...
        fwd.forward_device(self._img(8, 8), self._img(8, 8), iters=1)
        fwd.forward_device(self._img(16, 8), self._img(16, 8), iters=1)
        # ...and the first survives the eviction.
        fwd.forward_device(self._img(8, 8), self._img(8, 8), iters=1)
        assert fwd.stats["hits"] == 2
        assert fwd.stats["compiles"] == 3

    def test_pad_bucketing_collapses_executables(self):
        """Two KITTI-ish native shapes bucket to ONE padded shape → one
        compiled executable on the forward path (the submission loop)."""
        fwd = self._fwd(cache_size=8)
        for h, w in ((37, 41), (38, 44)):
            img = np.zeros((1, h, w, 3), np.float32)
            padder = InputPadder(img.shape, mode="kitti", bucket=48)
            p1, p2 = padder.pad(img, img)
            assert np.asarray(p1).shape[1:3] == (48, 48)
            fwd.forward_device(np.asarray(p1), np.asarray(p2), iters=1)
        assert fwd.stats == {"compiles": 1, "hits": 1, "evictions": 0}

    def test_bad_bucket_rejected(self):
        with pytest.raises(ValueError, match="multiple of"):
            InputPadder((1, 37, 41, 3), bucket=12)  # not divisible by 8


# ------------------------------------------- device-metric parity + guards


def _epe_band_dataset(n, hw):
    return SyntheticFlowDataset(hw, length=n, seed=11, style="smooth")


class _MaskedValid(_ListDataset):
    """Synthetic samples with a nontrivial valid mask (upper half of
    every even frame invalid) so the KITTI fold's masking is exercised."""

    def __init__(self, base):
        samples = []
        for i in range(len(base)):
            s = dict(base.sample(i))
            valid = np.ones(s["flow"].shape[:2], np.float32)
            if i % 2 == 0:
                valid[: valid.shape[0] // 2] = 0.0
            s["valid"] = valid
            samples.append(s)
        super().__init__(samples)


@pytest.fixture(scope="module", params=["volume", "onthefly"])
def tiny_fwd(request):
    cfg = small_model_config(
        "raft", dataset="chairs", corr_impl=request.param
    )
    model = RAFT(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 40, 48, 3))
    return ShapeCachedForward(model, variables)


class TestDeviceMetricParity:
    """The acceptance contract: validators' on-device sums reproduce the
    pre-refactor host-side NumPy computation (reference formulas:
    evaluate.py:90-182) for both corr implementations."""

    ITERS = 2

    def _run_device(self, fwd, dataset, kind, batch_size=2, pad_mode=None,
                    with_valid=False):
        from raft_ncup_tpu.evaluation import _run_metric_pass

        return _run_metric_pass(
            fwd, dataset, kind=kind, iters=self.ITERS,
            batch_size=batch_size, pad_mode=pad_mode,
            with_valid=with_valid, num_workers=2,
        )

    def _host_flow(self, fwd, group, pad_mode=None):
        """The pre-refactor per-batch path: stack, pad, forward, PULL
        full fields, unpad host-side."""
        img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
        img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
        if pad_mode is None:
            _, flow_up = fwd(img1, img2, self.ITERS)
            return flow_up
        padder = InputPadder(img1.shape, mode=pad_mode)
        p1, p2 = padder.pad(img1, img2)
        _, flow_up = fwd(np.asarray(p1), np.asarray(p2), self.ITERS)
        return np.asarray(padder.unpad(flow_up))

    def test_epe_parity_unpadded(self, tiny_fwd):
        ds = _epe_band_dataset(6, (40, 48))
        acc = self._run_device(tiny_fwd, ds, "epe")
        # Host reference: evaluate.py:90-108 (chairs EPE).
        host = np.zeros(2)
        for g0 in range(0, 6, 2):
            group = [ds.sample(g0 + k) for k in range(2)]
            flow_up = self._host_flow(tiny_fwd, group)
            for k, s in enumerate(group):
                epe = np.sqrt(((flow_up[k] - s["flow"]) ** 2).sum(-1))
                host += (float(epe.sum()), epe.size)
        np.testing.assert_allclose(acc, host, rtol=1e-4)

    def test_px_parity_padded(self, tiny_fwd):
        # Native 36x44 pads to 40x48 (sintel-centered), so the in-graph
        # unpad crop is live in the compiled program.
        ds = _epe_band_dataset(4, (36, 44))
        acc = self._run_device(tiny_fwd, ds, "px", pad_mode="sintel")
        # Host reference: evaluate.py:111-143 (sintel EPE + 1/3/5px).
        host = np.zeros(5)
        for g0 in range(0, 4, 2):
            group = [ds.sample(g0 + k) for k in range(2)]
            flow_b = self._host_flow(tiny_fwd, group, pad_mode="sintel")
            for k, s in enumerate(group):
                epe = np.sqrt(((flow_b[k] - s["flow"]) ** 2).sum(-1))
                host += (
                    float(epe.sum()), epe.size,
                    int((epe < 1).sum()), int((epe < 3).sum()),
                    int((epe < 5).sum()),
                )
        np.testing.assert_allclose(acc[:2], host[:2], rtol=1e-4)
        # Threshold counts are integers: exact equality required.
        np.testing.assert_array_equal(acc[2:], host[2:])

    def test_kitti_parity_padded_masked(self, tiny_fwd):
        ds = _MaskedValid(_epe_band_dataset(4, (36, 44)))
        acc = self._run_device(
            tiny_fwd, ds, "kitti", pad_mode="kitti", with_valid=True
        )
        # Host reference: evaluate.py:146-182 (KITTI EPE + F1 sums).
        host = np.zeros(4)
        for g0 in range(0, 4, 2):
            group = [ds.sample(g0 + k) for k in range(2)]
            flow_b = self._host_flow(tiny_fwd, group, pad_mode="kitti")
            for k, s in enumerate(group):
                epe = np.sqrt(((flow_b[k] - s["flow"]) ** 2).sum(-1)).ravel()
                mag = np.sqrt((s["flow"] ** 2).sum(-1)).ravel()
                val = s["valid"].ravel() >= 0.5
                out = (epe > 3.0) & ((epe / np.maximum(mag, 1e-12)) > 0.05)
                host += (
                    float(epe[val].mean()), 1,
                    int(out[val].sum()), int(val.sum()),
                )
        np.testing.assert_allclose(acc[0], host[0], rtol=1e-4)
        np.testing.assert_array_equal(acc[1:], host[1:])

    def test_finalize_matches_reference_reduction(self):
        acc = np.array([10.0, 4.0, 2.0, 3.0, 4.0])
        m = metrics_mod.finalize("px", acc)
        assert m == {
            "epe": 2.5, "1px": 0.5, "3px": 0.75, "5px": 1.0,
        }
        k = metrics_mod.finalize("kitti", np.array([6.0, 3.0, 5.0, 50.0]))
        assert k == {"epe": 2.0, "f1": 10.0}


class TestKittiEmptyValidMask:
    """ROADMAP carry-over regression: a frame with ZERO valid pixels made
    the host path's per-frame EPE mean NaN (0-valid sum / 0 count) and
    poisoned the dataset mean; with nothing valid pooled at all,
    ``finalize``'s ``acc[2]/acc[3]`` divided 0/0. Empty frames now
    contribute neither EPE nor frame count; degenerate pools finalize to
    0.0, never NaN."""

    def _acc(self, valid: np.ndarray) -> np.ndarray:
        g = np.random.default_rng(5)
        b, h, w = valid.shape
        flow_up = jnp.asarray(g.normal(size=(b, h, w, 2)).astype(np.float32))
        gt = jnp.asarray(g.normal(size=(b, h, w, 2)).astype(np.float32))
        acc = metrics_mod.accumulate(
            "kitti", metrics_mod.init_acc("kitti"), flow_up, gt,
            valid=jnp.asarray(valid),
        )
        self._flow_up, self._gt = np.asarray(flow_up), np.asarray(gt)
        return np.asarray(jax.device_get(acc))

    def test_all_invalid_frame_excluded_not_nan(self):
        valid = np.ones((2, 8, 10), np.float32)
        valid[1] = 0.0  # frame 1: zero valid pixels
        acc = self._acc(valid)
        assert np.isfinite(acc).all()
        # The empty frame contributes neither EPE nor frame count, so
        # the remaining frame's mean is undiluted.
        epe0 = np.sqrt(
            ((self._flow_up[0] - self._gt[0]) ** 2).sum(-1)
        )
        assert acc[1] == 1.0
        np.testing.assert_allclose(acc[0], epe0.mean(), rtol=1e-5)
        m = metrics_mod.finalize("kitti", acc)
        np.testing.assert_allclose(m["epe"], epe0.mean(), rtol=1e-5)
        assert np.isfinite(m["f1"])

    def test_every_frame_invalid_finalizes_to_zero(self):
        acc = self._acc(np.zeros((2, 8, 10), np.float32))
        assert np.isfinite(acc).all() and acc[1] == 0.0
        assert metrics_mod.finalize("kitti", acc) == {"epe": 0.0, "f1": 0.0}


class TestEvalLoopInvariants:
    """N eval batches under forbid_host_transfers + max_recompiles: only
    the sanctioned window pull touches the host, and the warm loop never
    recompiles — the train loop's invariants, inherited by eval."""

    def test_metric_pass_is_sync_free_and_recompile_free(
        self, forbid_host_transfers, max_recompiles
    ):
        cfg = small_model_config("raft", dataset="chairs")
        model = RAFT(cfg)
        variables = model.init(jax.random.PRNGKey(0), (1, 40, 48, 3))
        fwd = ShapeCachedForward(model, variables)
        from raft_ncup_tpu.evaluation import _run_metric_pass

        ds = _epe_band_dataset(6, (40, 48))
        # Warm pass compiles the metric executable + init_acc programs.
        warm = _run_metric_pass(
            fwd, ds, kind="epe", iters=2, batch_size=2, num_workers=2
        )
        with forbid_host_transfers() as stats, max_recompiles(0):
            guarded = _run_metric_pass(
                fwd, ds, kind="epe", iters=2, batch_size=2, num_workers=2
            )
        assert stats.host_transfers == 0
        assert stats.sanctioned_gets == 1  # ONE window pull, nothing else
        np.testing.assert_allclose(guarded, warm, rtol=1e-6)

    def test_validator_outputs_unchanged_by_guards(self):
        """validate_synthetic through the full pipeline equals a direct
        old-style host computation over the same held-out split."""
        from raft_ncup_tpu.evaluation import validate_synthetic

        cfg = small_model_config("raft", dataset="chairs")
        model = RAFT(cfg)
        variables = model.init(jax.random.PRNGKey(0), (1, 40, 48, 3))
        out = validate_synthetic(
            model, variables, DataConfig(), iters=2, batch_size=2,
            size_hw=(40, 48), length=4,
        )
        fwd = ShapeCachedForward(model, variables)
        ds = SyntheticFlowDataset((40, 48), length=4, seed=999,
                                  style="smooth")
        host = np.zeros(2)
        for g0 in range(0, 4, 2):
            group = [ds.sample(g0 + k) for k in range(2)]
            img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
            img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
            _, flow_up = fwd(img1, img2, 2)
            for k, s in enumerate(group):
                epe = np.sqrt(((flow_up[k] - s["flow"]) ** 2).sum(-1))
                host += (float(epe.sum()), epe.size)
        np.testing.assert_allclose(
            out["synthetic"], host[0] / host[1], rtol=1e-4
        )


# ----------------------------------------------------------- cost ledger


class TestCostLedger:
    """The executable cost ledger (inference/costs.py; docs/PERF.md):
    XLA cost facts recorded once at compile time, keys stable across
    re-warm, MFU non-null for any backend with a peak-FLOPs entry."""

    def _fwd_with_ledger(self):
        from raft_ncup_tpu.inference.costs import CostLedger

        ledger = CostLedger(enabled=True)
        fwd = ShapeCachedForward(
            _DummyModel(), {}, cache_size=4, cost_ledger=ledger
        )
        return fwd, ledger

    def test_records_costs_at_compile_time_only(self):
        fwd, ledger = self._fwd_with_ledger()
        img = np.zeros((1, 8, 10, 3), np.float32)
        fwd.forward_device(img, img, 2)
        assert fwd.stats["compiles"] == 1
        assert len(ledger) == 1
        entry = ledger.lookup(kind="forward", shape=(1, 8, 10, 3), iters=2)
        assert entry is not None
        assert entry["backend"] == jax.default_backend()
        assert entry["compile_ms"] is not None and entry["compile_ms"] > 0
        assert entry["flops"] is None or entry["flops"] >= 0
        assert isinstance(entry["memory_stats"], dict)
        # Warm calls touch the ledger no further (one entry, same key).
        before = ledger.keys()
        fwd.forward_device(img, img, 2)
        assert fwd.stats["hits"] == 1
        assert ledger.keys() == before

    def test_key_stable_across_rewarm_zero_recompiles(self):
        """The acceptance pin: same shape ⇒ same ledger key, and a
        re-warm of the warm executable performs ZERO XLA compiles."""
        from raft_ncup_tpu.analysis.guards import RecompileWatchdog

        fwd, ledger = self._fwd_with_ledger()
        img = np.zeros((1, 8, 10, 3), np.float32)
        jax.block_until_ready(fwd.forward_device(img, img, 2))
        keys_first = ledger.keys()
        assert len(keys_first) == 1
        with RecompileWatchdog() as wd:
            jax.block_until_ready(fwd.forward_device(img, img, 2))
        assert wd.count == 0
        assert ledger.keys() == keys_first
        # A fresh same-config cache writes the SAME ledger key (the key
        # is the executable's identity, not the instance's).
        fwd2 = ShapeCachedForward(
            _DummyModel(), {}, cache_size=4, cost_ledger=ledger
        )
        jax.block_until_ready(fwd2.forward_device(img, img, 2))
        assert ledger.keys() == keys_first

    def test_distinct_shapes_distinct_keys(self):
        fwd, ledger = self._fwd_with_ledger()
        a = np.zeros((1, 8, 10, 3), np.float32)
        b = np.zeros((1, 16, 10, 3), np.float32)
        fwd.forward_device(a, a, 2)
        fwd.forward_device(b, b, 2)
        fwd.forward_device(a, a, 4)
        assert len(ledger) == 3
        metas = [
            (e["meta"]["shape"], e["meta"]["iters"])
            for e in (ledger.entry(k) for k in ledger.keys())
        ]
        assert len(set(map(str, metas))) == 3

    def test_disabled_ledger_records_nothing(self):
        from raft_ncup_tpu.inference.costs import CostLedger

        ledger = CostLedger(enabled=False)
        fwd = ShapeCachedForward(
            _DummyModel(), {}, cache_size=4, cost_ledger=ledger
        )
        img = np.zeros((1, 8, 10, 3), np.float32)
        fwd.forward_device(img, img, 2)
        assert len(ledger) == 0
        assert fwd.stats["compiles"] == 1  # cache accounting unchanged

    def test_peak_table_and_mfu(self):
        """MFU is non-null for every backend with a peak entry (CPU
        included — a nominal per-core figure) and null ONLY for an
        unknown backend, never for 'we did not measure'."""
        from raft_ncup_tpu.inference import costs

        assert costs.peak_flops("cpu") > 0
        assert costs.peak_flops("tpu", tpu_gen="v5e") == 197e12
        assert costs.peak_flops("tpu", device_kind="TPU v4") == 275e12
        assert costs.peak_flops("tpu", device_kind="weird") is None
        assert costs.peak_flops("quantum") is None
        assert costs.peak_flops(None) is None
        assert costs.mfu(1e9, 3.0, 48e9) == pytest.approx(0.0625)
        assert costs.mfu(None, 3.0, 48e9) is None
        assert costs.mfu(1e9, 3.0, None) is None
        # Env override wins for CPU (autotuner/operator escape hatch).
        import os as _os

        _os.environ["RAFT_NCUP_CPU_PEAK_FLOPS"] = "1e12"
        try:
            assert costs.peak_flops("cpu") == 1e12
        finally:
            del _os.environ["RAFT_NCUP_CPU_PEAK_FLOPS"]

    def test_snapshot_is_json_able(self):
        import json as _json

        fwd, ledger = self._fwd_with_ledger()
        img = np.zeros((1, 8, 10, 3), np.float32)
        fwd.forward_device(img, img, 2)
        snap = _json.loads(_json.dumps(ledger.snapshot()))
        assert snap["enabled"] is True
        (entry,) = snap["entries"].values()
        assert entry["meta"]["shape"] == [1, 8, 10, 3]
