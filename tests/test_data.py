"""Tests for augmentors, dataset index construction, and the loader."""

import numpy as np
import pytest
from PIL import Image

from raft_ncup_tpu.config import DataConfig
from raft_ncup_tpu.data import (
    ColorJitter,
    FlowAugmentor,
    FlowLoader,
    FlyingChairs,
    KITTI,
    MixedDataset,
    MpiSintel,
    SparseFlowAugmentor,
    SyntheticFlowDataset,
    fetch_training_set,
    resize_sparse_flow_map,
)
from raft_ncup_tpu.io import write_flo, write_flow_kitti


def rng(seed=0):
    return np.random.default_rng(seed)


# ------------------------------------------------------------------ augment


class TestColorJitter:
    def test_shape_dtype_and_determinism(self):
        img = rng().integers(0, 255, (40, 30, 3), dtype=np.uint8)
        out1 = ColorJitter()(img, rng(7))
        out2 = ColorJitter()(img, rng(7))
        assert out1.shape == img.shape and out1.dtype == np.uint8
        np.testing.assert_array_equal(out1, out2)

    def test_identity_factors(self):
        jitter = ColorJitter(0.0, 0.0, 0.0, 0.0)
        img = rng().integers(0, 255, (16, 16, 3), dtype=np.uint8)
        out = jitter(img, rng(1))
        # All factors exactly 1 / hue shift 0 -> image roundtrips through
        # float and HSV within rounding.
        assert np.abs(out.astype(int) - img.astype(int)).max() <= 1

    def test_hue_preserves_value_channel(self):
        jitter = ColorJitter(0.0, 0.0, 0.0, 0.4)
        img = rng(2).integers(0, 255, (16, 16, 3), dtype=np.uint8)
        out = jitter(img, rng(3))
        # Hue rotation keeps max channel (HSV value) within rounding.
        np.testing.assert_allclose(
            out.max(axis=-1).astype(int), img.max(axis=-1).astype(int), atol=2
        )


class TestFlowAugmentor:
    def test_output_is_crop_size(self):
        aug = FlowAugmentor(crop_size=(64, 96), min_scale=-0.2, max_scale=0.5)
        img1 = rng(0).integers(0, 255, (128, 160, 3), dtype=np.uint8)
        img2 = rng(1).integers(0, 255, (128, 160, 3), dtype=np.uint8)
        flow = rng(2).normal(size=(128, 160, 2)).astype(np.float32)
        for seed in range(8):
            a, b, f = aug(img1, img2, flow, rng(seed))
            assert a.shape == (64, 96, 3)
            assert b.shape == (64, 96, 3)
            assert f.shape == (64, 96, 2)
            assert a.dtype == np.uint8 and f.dtype == np.float32

    def test_hflip_negates_u(self):
        aug = FlowAugmentor(
            crop_size=(32, 32),
            spatial_aug_prob=0.0,
            stretch_prob=0.0,
            asymmetric_color_aug_prob=0.0,
            eraser_aug_prob=0.0,
            h_flip_prob=1.0,
            v_flip_prob=0.0,
            do_flip=True,
        )
        img = np.zeros((32, 32, 3), np.uint8)
        flow = np.tile(
            np.array([3.0, 5.0], np.float32), (32, 32, 1)
        )
        # Neutralize color jitter by monkey-looking at flow only.
        _, _, f = aug(img, img, flow, rng(4))
        np.testing.assert_allclose(f[..., 0], -3.0)
        np.testing.assert_allclose(f[..., 1], 5.0)

    def test_scale_multiplies_flow(self):
        aug = FlowAugmentor(
            crop_size=(32, 32),
            min_scale=1.0,
            max_scale=1.0,  # scale = 2.0 exactly
            spatial_aug_prob=1.0,
            stretch_prob=0.0,
            asymmetric_color_aug_prob=0.0,
            eraser_aug_prob=0.0,
            do_flip=False,
        )
        img = rng(0).integers(0, 255, (64, 64, 3), dtype=np.uint8)
        flow = np.full((64, 64, 2), 2.0, np.float32)
        _, _, f = aug(img, img, flow, rng(5))
        np.testing.assert_allclose(f, 4.0, atol=1e-5)


class TestSparse:
    def test_resize_sparse_scatter(self):
        flow = np.zeros((8, 8, 2), np.float32)
        valid = np.zeros((8, 8), np.float32)
        flow[4, 4] = (1.0, -2.0)
        valid[4, 4] = 1.0
        f2, v2 = resize_sparse_flow_map(flow, valid, fx=2.0, fy=2.0)
        assert f2.shape == (16, 16, 2) and v2.shape == (16, 16)
        assert v2.sum() == 1
        np.testing.assert_allclose(f2[8, 8], (2.0, -4.0))

    def test_sparse_augmentor_shapes(self):
        aug = SparseFlowAugmentor(crop_size=(48, 64))
        img1 = rng(0).integers(0, 255, (96, 128, 3), dtype=np.uint8)
        img2 = rng(1).integers(0, 255, (96, 128, 3), dtype=np.uint8)
        flow = rng(2).normal(size=(96, 128, 2)).astype(np.float32)
        valid = (rng(3).random((96, 128)) > 0.5).astype(np.float32)
        for seed in range(6):
            a, b, f, v = aug(img1, img2, flow, valid, rng(seed))
            assert a.shape == (48, 64, 3)
            assert f.shape == (48, 64, 2)
            assert v.shape == (48, 64)
            assert set(np.unique(v)).issubset({0, 1})


# ----------------------------------------------------------------- fixtures


def make_chairs_fixture(root, n=6):
    root.mkdir(parents=True)
    g = rng(0)
    for i in range(1, n + 1):
        for k in (1, 2):
            Image.fromarray(
                g.integers(0, 255, (96, 128, 3), dtype=np.uint8)
            ).save(root / f"{i:05d}_img{k}.png")
        write_flo(
            root / f"{i:05d}_flow.flo",
            g.normal(size=(96, 128, 2)).astype(np.float32),
        )
    split = np.array([1, 1, 2, 1, 2, 1][:n])
    split_file = root.parent / "chairs_split.txt"
    np.savetxt(split_file, split, fmt="%d")
    return split_file


def make_sintel_fixture(root, scenes=("alley_1", "market_2"), frames=4):
    g = rng(1)
    for dstype in ("clean", "final"):
        for scene in scenes:
            d = root / "training" / dstype / scene
            d.mkdir(parents=True, exist_ok=True)
            for i in range(frames):
                Image.fromarray(
                    g.integers(0, 255, (64, 96, 3), dtype=np.uint8)
                ).save(d / f"frame_{i:04d}.png")
    for scene in scenes:
        d = root / "training" / "flow" / scene
        d.mkdir(parents=True, exist_ok=True)
        for i in range(frames - 1):
            write_flo(
                d / f"frame_{i:04d}.flo",
                g.normal(size=(64, 96, 2)).astype(np.float32),
            )


def make_kitti_fixture(root, n=3):
    d = root / "training"
    (d / "image_2").mkdir(parents=True)
    (d / "flow_occ").mkdir(parents=True)
    g = rng(2)
    for i in range(n):
        for suffix in ("10", "11"):
            Image.fromarray(
                g.integers(0, 255, (80, 120, 3), dtype=np.uint8)
            ).save(d / "image_2" / f"{i:06d}_{suffix}.png")
        write_flow_kitti(
            d / "flow_occ" / f"{i:06d}_10.png",
            g.normal(size=(80, 120, 2)).astype(np.float32),
        )


# ----------------------------------------------------------------- datasets


class TestDatasets:
    def test_chairs_split(self, tmp_path):
        split_file = make_chairs_fixture(tmp_path / "data")
        train = FlyingChairs(
            None, split="training", root=str(tmp_path / "data"),
            split_file=str(split_file),
        )
        val = FlyingChairs(
            None, split="validation", root=str(tmp_path / "data"),
            split_file=str(split_file),
        )
        assert len(train) == 4 and len(val) == 2
        s = train.sample(0)
        assert s["image1"].shape == (96, 128, 3)
        assert s["flow"].shape == (96, 128, 2)
        assert s["valid"].shape == (96, 128)
        assert s["valid"].all()  # all synthetic flows are small

    def test_packaged_chairs_split_counts(self):
        """The vendored split file reproduces the reference's exact
        1/2-label semantics: 22,871 lines, 22,232 train / 640 val
        (reference: chairs_split.txt via core/datasets.py:128)."""
        import os

        from raft_ncup_tpu.config import PACKAGED_CHAIRS_SPLIT

        assert os.path.exists(PACKAGED_CHAIRS_SPLIT)
        labels = np.loadtxt(PACKAGED_CHAIRS_SPLIT, dtype=np.int32)
        assert labels.shape == (22872,)
        assert int((labels == 1).sum()) == 22232
        assert int((labels == 2).sum()) == 640
        # Config default points at the packaged file out of the box.
        assert DataConfig().chairs_split_file == PACKAGED_CHAIRS_SPLIT

    def test_sintel_pairs_per_scene(self, tmp_path):
        make_sintel_fixture(tmp_path / "Sintel")
        ds = MpiSintel(None, root=str(tmp_path / "Sintel"), dstype="clean")
        # 2 scenes x (4 frames - 1) pairs
        assert len(ds) == 6
        assert len(ds.flow_list) == 6
        s = ds.sample(2)
        assert s["image1"].shape == (64, 96, 3)

    def test_kitti_sparse(self, tmp_path):
        make_kitti_fixture(tmp_path / "KITTI")
        ds = KITTI(None, root=str(tmp_path / "KITTI"))
        assert len(ds) == 3
        s = ds.sample(1)
        assert s["valid"].shape == (80, 120)

    def test_mixture_table(self, tmp_path):
        make_sintel_fixture(tmp_path / "Sintel")
        clean = MpiSintel(None, root=str(tmp_path / "Sintel"), dstype="clean")
        final = MpiSintel(None, root=str(tmp_path / "Sintel"), dstype="final")
        mix = MixedDataset([(clean, 3), (final, 1)])
        assert len(mix) == 3 * 6 + 6
        s = mix.sample(0)
        assert s["image1"].shape == (64, 96, 3)

    def test_fetch_training_set_sintel_stage(self, tmp_path):
        make_sintel_fixture(tmp_path / "Sintel")
        make_kitti_fixture(tmp_path / "KITTI")
        cfg = DataConfig(
            root_sintel=str(tmp_path / "Sintel"),
            root_kitti=str(tmp_path / "KITTI"),
            root_things=str(tmp_path / "nonexistent"),
            root_hd1k=str(tmp_path / "nonexistent"),
        )
        mix = fetch_training_set("sintel", (32, 48), cfg)
        # 100*6 + 100*6 + 200*3 (things/hd1k empty and dropped)
        assert len(mix) == 1800
        s = mix.sample(0, rng(0))
        assert s["image1"].shape == (32, 48, 3)


# ------------------------------------------------------------------- loader


class TestLoader:
    def test_batches_shapes_and_determinism(self):
        ds = SyntheticFlowDataset((40, 56), length=16, seed=3)
        loader = FlowLoader(
            ds, batch_size=4, seed=5, num_workers=2,
            shard_index=0, num_shards=1,
        )
        it = loader.batches()
        b = next(it)
        assert b["image1"].shape == (4, 40, 56, 3)
        assert b["flow"].shape == (4, 40, 56, 2)
        assert b["valid"].shape == (4, 40, 56)
        assert b["image1"].dtype == np.uint8  # images ship uint8 to device
        assert b["flow"].dtype == np.float32
        it2 = FlowLoader(
            ds, batch_size=4, seed=5, num_workers=2,
            shard_index=0, num_shards=1,
        ).batches()
        b2 = next(it2)
        np.testing.assert_array_equal(b["image1"], b2["image1"])
        it.close()
        it2.close()

    def test_mid_epoch_resume_matches_uninterrupted_stream(self):
        """``batches(start_epoch, start_batch)`` reproduces the exact
        stream an uninterrupted run would have seen from that position —
        the mid-epoch checkpoint-resume contract train.py relies on."""
        ds = SyntheticFlowDataset((16, 24), length=12, seed=7)
        kw = dict(batch_size=3, seed=11, num_workers=1,
                  shard_index=0, num_shards=1)
        full = FlowLoader(ds, **kw).batches()
        stream = [next(full) for _ in range(7)]  # into epoch 1 (4/epoch)
        full.close()

        resumed = FlowLoader(ds, **kw).batches(start_epoch=1, start_batch=2)
        got = next(resumed)
        resumed.close()
        np.testing.assert_array_equal(got["image1"], stream[6]["image1"])
        np.testing.assert_array_equal(got["flow"], stream[6]["flow"])

    def test_host_sharding_is_disjoint(self):
        ds = SyntheticFlowDataset((16, 16), length=12, seed=0)
        seen = []
        for shard in (0, 1):
            loader = FlowLoader(
                ds, batch_size=2, seed=9, shuffle=True,
                shard_index=shard, num_shards=2, num_workers=1,
            )
            seen.append(np.concatenate([loader._epoch_indices(0)]))
        assert set(seen[0]).isdisjoint(seen[1])
        assert len(set(seen[0]) | set(seen[1])) == 12

    def test_one_epoch_length(self):
        ds = SyntheticFlowDataset((16, 16), length=10, seed=0)
        loader = FlowLoader(
            ds, batch_size=3, shard_index=0, num_shards=1, num_workers=1
        )
        batches = list(loader.one_epoch())
        assert len(batches) == 3  # drop_last

    def test_len_matches_one_epoch_on_uneven_shards(self):
        # 13 samples over 2 shards: shard 0 gets ceil(13/2)=7 -> 7 batches.
        ds = SyntheticFlowDataset((16, 16), length=13, seed=0)
        loader = FlowLoader(
            ds, batch_size=1, shard_index=0, num_shards=2, num_workers=1
        )
        assert len(loader) == len(list(loader.one_epoch())) == 7

    def test_empty_dataset_raises(self):
        ds = SyntheticFlowDataset((16, 16), length=2, seed=0)
        with pytest.raises(ValueError, match="zero batches"):
            FlowLoader(ds, batch_size=4, shard_index=0, num_shards=1)

    def test_synthetic_fallback(self, tmp_path):
        cfg = DataConfig(
            root_kitti=str(tmp_path / "nope"), synthetic_ok=True
        )
        ds = fetch_training_set("kitti", (32, 48), cfg)
        assert isinstance(ds, SyntheticFlowDataset) and len(ds) > 0
        cfg_strict = DataConfig(root_kitti=str(tmp_path / "nope"))
        assert len(fetch_training_set("kitti", (32, 48), cfg_strict)) == 0

    def test_synthetic_pair_consistency(self):
        # image2 should be approximately image1 warped by flow: check EPE of
        # zero-flow is worse than the generating flow under photometric loss.
        ds = SyntheticFlowDataset((64, 64), length=2, seed=1, max_mag=6.0)
        s = ds.sample(0)
        import cv2

        h, w = 64, 64
        xx, yy = np.meshgrid(np.arange(w, dtype=np.float32),
                             np.arange(h, dtype=np.float32))
        warped = cv2.remap(
            s["image1"],
            xx - s["flow"][..., 0],
            yy - s["flow"][..., 1],
            cv2.INTER_LINEAR,
            borderMode=cv2.BORDER_REFLECT,
        )
        err_warp = np.abs(
            warped.astype(float) - s["image2"].astype(float)
        ).mean()
        err_identity = np.abs(
            s["image1"].astype(float) - s["image2"].astype(float)
        ).mean()
        assert err_warp < err_identity * 0.5
