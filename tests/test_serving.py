"""Online serving subsystem (raft_ncup_tpu/serving/): admission/shedding
semantics, iteration-budget hysteresis, deterministic traffic, poison
quarantine with batch-mate isolation, deadline handling, graceful drain
on SIGTERM, and the sync-free/recompile-free steady state under the
runtime guards — the chaos matrix of docs/SERVING.md, end to end.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from types import SimpleNamespace

from raft_ncup_tpu.config import ServeConfig, small_model_config
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.resilience import PreemptionHandler
from raft_ncup_tpu.resilience.chaos import ChaosSpec
from raft_ncup_tpu.serving import (
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHED,
    STATUS_TIMEOUT,
    TERMINAL_STATUSES,
    AdmissionQueue,
    FlowRequest,
    FlowServer,
    IterationBudgetController,
    ServeHandle,
    SyntheticTraffic,
    replay,
)
from raft_ncup_tpu.serving.request import FlowResponse


# ------------------------------------------------------------- test rigs


class _DummyModel:
    """apply()-compatible stand-in: the 'flow' is a deterministic
    function of image1 AND the iteration count, so responses prove which
    budget level computed them without a RAFT compile."""

    def apply(self, variables, image1, image2, iters=1, flow_init=None,
              test_mode=True, mesh=None, metric_head=None, **kw):
        flow_up = jnp.stack(
            [image1[..., 0] * iters, image1[..., 1]], axis=-1
        )
        return image1.mean(), flow_up


def _img(seed=0, hw=(24, 32)):
    g = np.random.default_rng(seed)
    return (g.random((*hw, 3)) * 255.0).astype(np.float32)


def _cfg(**kw):
    base = dict(
        queue_capacity=8,
        batch_sizes=(1, 2),
        iter_levels=(4, 2),
        high_water=0.75,
        low_water=0.25,
        recover_patience=2,
    )
    base.update(kw)
    return ServeConfig(**base)


def _server(**kw) -> FlowServer:
    return FlowServer(_DummyModel(), {}, _cfg(**kw))


def _wait_idle(server, timeout=10.0):
    """Block until everything admitted so far has terminated."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not server._handles and not len(server._queue):
            return
        time.sleep(0.01)
    raise TimeoutError("server did not go idle")


# -------------------------------------------------------- AdmissionQueue


class TestAdmissionQueue:
    def _req(self, rid, key="a"):
        return FlowRequest(rid, None, None, shape_key=key)

    def test_offer_sheds_at_capacity(self):
        q = AdmissionQueue(capacity=3)
        assert all(q.offer(self._req(i)) for i in range(3))
        assert not q.offer(self._req(3))
        assert len(q) == 3

    def test_pop_batch_groups_fifo_runs_by_key(self):
        q = AdmissionQueue(capacity=10)
        for rid, key in enumerate("aabba"):
            q.offer(self._req(rid, key))
        batches = []
        while len(q):
            batches.append([r.request_id for r in q.pop_batch(4)])
        # Grouping never reorders across a key change: the trailing 'a'
        # must NOT jump the 'b' run.
        assert batches == [[0, 1], [2, 3], [4]]

    def test_pop_batch_respects_max_n(self):
        q = AdmissionQueue(capacity=10)
        for rid in range(5):
            q.offer(self._req(rid))
        assert len(q.pop_batch(2)) == 2
        assert len(q) == 3

    def test_closed_queue_sheds_but_drains(self):
        q = AdmissionQueue(capacity=4)
        q.offer(self._req(0))
        q.close()
        assert not q.offer(self._req(1))  # no new admissions
        assert [r.request_id for r in q.pop_batch(4)] == [0]  # drainable
        assert q.pop_batch(4) == []  # closed + empty = exit signal

    def test_pop_batch_times_out_empty(self):
        q = AdmissionQueue(capacity=2)
        t0 = time.monotonic()
        assert q.pop_batch(2, timeout=0.05) == []
        assert time.monotonic() - t0 < 1.0


# ------------------------------------------------- IterationBudgetController


class TestBudgetController:
    def _ctl(self, **kw):
        base = dict(levels=(24, 16, 8), capacity=8, high_water=0.75,
                    low_water=0.25, recover_patience=2)
        base.update(kw)
        return IterationBudgetController(**base)

    def test_degrades_immediately_at_high_water(self):
        ctl = self._ctl()
        assert ctl.decide(0) == 24
        assert ctl.decide(6) == 16  # 0.75 occupancy: one level, now
        assert ctl.decide(8) == 8  # saturated: next level
        assert ctl.decide(8) == 8  # floor: stays at the coarsest
        assert ctl.drops == 2

    def test_recovery_needs_sustained_calm(self):
        ctl = self._ctl()
        ctl.decide(8)  # -> 16
        assert ctl.iters == 16
        assert ctl.decide(1) == 16  # calm 1: not yet
        assert ctl.decide(1) == 24  # calm 2 = patience: recover
        assert ctl.recoveries == 1

    def test_mid_band_resets_patience(self):
        """Load oscillating through the low band must not recover: the
        calm streak restarts whenever occupancy leaves it."""
        ctl = self._ctl()
        ctl.decide(8)  # -> 16
        ctl.decide(1)  # calm 1
        ctl.decide(4)  # mid-band (0.5): streak reset
        assert ctl.decide(1) == 16  # calm 1 again — no recovery
        assert ctl.decide(1) == 24
        assert (ctl.drops, ctl.recoveries) == (1, 1)

    def test_full_burst_trajectory(self):
        """The documented drain-a-burst trajectory: saturate, walk down,
        hold through the mid band, recover after sustained calm."""
        ctl = self._ctl(levels=(4, 2), recover_patience=2)
        depths = [8, 7, 6, 5, 4, 3, 2, 1]
        iters = [ctl.decide(d) for d in depths]
        assert iters == [2, 2, 2, 2, 2, 2, 2, 4]
        assert (ctl.drops, ctl.recoveries) == (1, 1)
        assert ctl.decisions == [1, 7]

    def test_validation(self):
        with pytest.raises(ValueError, match="descending"):
            self._ctl(levels=(8, 16))
        with pytest.raises(ValueError, match="positive"):
            self._ctl(levels=(8, 0))
        with pytest.raises(ValueError, match="low_water"):
            self._ctl(low_water=0.8)

    def test_serve_config_validation(self):
        with pytest.raises(ValueError, match="batch_sizes"):
            ServeConfig(batch_sizes=(2, 1))
        with pytest.raises(ValueError, match="iter_levels"):
            ServeConfig(iter_levels=(8, 8))


# ----------------------------------------------------------- ServeHandle


class TestHandleAndStats:
    def test_handle_completes_once(self):
        h = ServeHandle()
        h.complete(FlowResponse(0, STATUS_OK))
        with pytest.raises(RuntimeError, match="twice"):
            h.complete(FlowResponse(0, STATUS_OK))
        assert h.result(0.1).ok

    def test_result_timeout(self):
        with pytest.raises(TimeoutError):
            ServeHandle().result(timeout=0.01)


# ------------------------------------------------------------ traffic


class TestSyntheticTraffic:
    def test_deterministic_and_ordered(self):
        mk = lambda: list(SyntheticTraffic((8, 10), 4, seed=3,
                                           interval_s=0.5))
        a, b = mk(), mk()
        assert [x[0] for x in a] == [0.0, 0.5, 1.0, 1.5]
        for (_, i1, i2), (_, j1, j2) in zip(a, b):
            np.testing.assert_array_equal(i1, j1)
            np.testing.assert_array_equal(i2, j2)

    def test_burst_expands_request(self):
        chaos = ChaosSpec.parse("burst@1")
        tr = SyntheticTraffic((8, 10), 3, seed=0, interval_s=1.0,
                              burst_size=4, chaos=chaos)
        events = list(tr)
        assert len(events) == len(tr) == 6  # 3 + (4 - 1)
        assert [e[0] for e in events] == [0.0, 1.0, 1.0, 1.0, 1.0, 2.0]

    def test_len_ignores_bursts_past_stream_end(self):
        # burst@5 on a 3-request stream never fires: len must agree
        # with what __iter__ actually emits.
        chaos = ChaosSpec.parse("burst@5")
        tr = SyntheticTraffic((8, 10), 3, seed=0, burst_size=4,
                              chaos=chaos)
        assert len(list(tr)) == len(tr) == 3

    def test_poison_event_is_nan(self):
        chaos = ChaosSpec.parse("poison@2")
        events = list(SyntheticTraffic((8, 10), 3, seed=0, chaos=chaos))
        assert np.isnan(events[2][1]).all()
        assert not np.isnan(events[1][1]).any()

    def test_chaos_spec_round_trip(self):
        spec = ChaosSpec.parse("burst@4,poison@7,sigterm@9")
        assert spec.burst_requests == frozenset({4})
        assert spec.poison_requests == frozenset({7})
        assert spec.sigterm_after == 9
        assert spec.active
        assert ChaosSpec.parse(spec.render()) == spec


# --------------------------------------------------------- server: paths


class TestFlowServerPaths:
    def test_ok_response_and_native_unpad(self):
        with _server() as srv:
            img = _img(1, hw=(22, 30))  # needs padding to /8
            r = srv.submit(img, img).result(10)
        assert r.status == STATUS_OK
        assert r.flow.shape == (22, 30, 2)
        assert r.iters == 4 and r.latency_s > 0
        # _DummyModel's flow channel 0 is image1[...,0] * iters: the
        # response must be the NATIVE crop of the padded computation.
        np.testing.assert_allclose(r.flow[..., 0], img[..., 0] * 4,
                                   rtol=1e-6)

    def test_malformed_rejected_at_admission(self):
        with _server() as srv:
            cases = [
                np.zeros((24, 32), np.float32),  # not HWC
                np.zeros((24, 32, 4), np.float32),  # not 3-channel
                np.zeros((4, 4, 3), np.float32),  # below minimum
                np.zeros((24, 32, 3), "U5"),  # non-numeric dtype
            ]
            good = _img()
            out = [srv.submit(bad, good).result(5) for bad in cases]
            mixed = srv.submit(good, _img(2, hw=(40, 48))).result(5)
        assert all(r.status == STATUS_REJECTED for r in out)
        assert mixed.status == STATUS_REJECTED
        assert "differ" in mixed.detail
        assert srv.stats.rejected == 5
        # Malformed requests never occupied queue capacity, and an
        # admission-time validation reject is NOT a quarantine — that
        # list means "poison isolated from live batch-mates".
        assert srv.stats.accepted == 0
        assert srv.stats.quarantined == []

    def test_shed_with_retry_after(self):
        srv = _server(queue_capacity=4)
        try:
            srv.pause()
            img = _img()
            handles = [srv.submit(img, img) for _ in range(7)]
            # Sheds terminate synchronously at submit, before dispatch.
            early = [h.result(0.5) for h in handles if h.done()]
            assert [r.status for r in early] == [STATUS_SHED] * 3
            assert all(r.retry_after_s > 0 for r in early)
            srv.resume()
            responses = [h.result(10) for h in handles]
        finally:
            stats = srv.drain()
        assert stats.shed == 3 and stats.completed == 4
        assert [r.status for r in responses].count(STATUS_OK) == 4

    def test_deadline_expires_in_queue_without_compute(self):
        srv = _server()
        try:
            srv.pause()
            img = _img()
            h_dead = srv.submit(img, img, deadline_s=0.0)
            h_live = srv.submit(img, img)  # no deadline
            time.sleep(0.05)
            srv.resume()
            r_dead, r_live = h_dead.result(10), h_live.result(10)
        finally:
            srv.drain()
        assert r_dead.status == STATUS_TIMEOUT
        assert r_live.status == STATUS_OK
        assert srv.stats.timeouts == 1
        # The expired request consumed zero device compute: only the
        # live one formed a batch.
        assert srv.stats.batches == 1

    def test_batch_padding_accounting(self):
        """3 same-shape requests with batch_sizes (1, 2): one full batch
        of 2, one single — zero-row padding only when a batch lands
        between allowed sizes."""
        srv = _server(batch_sizes=(2, 4))
        try:
            srv.pause()
            img = _img()
            hs = [srv.submit(img, img) for _ in range(3)]
            srv.resume()
            rs = [h.result(10) for h in hs]
        finally:
            srv.drain()
        assert [r.status for r in rs] == [STATUS_OK] * 3
        assert srv.stats.padded_rows >= 1  # the odd request rode a
        # zero-padded program from the fixed set


class TestPoisonIsolation:
    def test_poison_quarantined_batch_mates_unaffected(self):
        """The acceptance contract: a NaN request popped INTO a batch is
        rejected alone; its batch-mates' flow is exactly what the same
        executable returns for them without the poison present."""
        srv = _server(batch_sizes=(1, 2, 4))
        try:
            srv.pause()
            g1, g2 = _img(11), _img(12)
            poison = np.full(g1.shape, np.nan, np.float32)
            h1 = srv.submit(g1, g1)
            hp = srv.submit(poison, poison)
            h2 = srv.submit(g2, g2)
            srv.resume()
            r1, rp, r2 = h1.result(10), hp.result(10), h2.result(10)
        finally:
            srv.drain()
        assert rp.status == STATUS_REJECTED
        assert "non-finite" in rp.detail
        assert srv.stats.quarantined == [hp.result(1).request_id]
        assert r1.status == STATUS_OK and r2.status == STATUS_OK
        np.testing.assert_allclose(r1.flow[..., 0], g1[..., 0] * 4,
                                   rtol=1e-6)
        np.testing.assert_allclose(r2.flow[..., 0], g2[..., 0] * 4,
                                   rtol=1e-6)


class TestServerErrorPath:
    def test_forward_failure_is_error_status_and_server_survives(self):
        """An internal failure terminates the batch's requests with an
        explicit `error` (the fault is the server's, not the client's)
        and the dispatcher keeps serving later batches."""

        class FlakyModel:
            fail = True

            def apply(self, variables, image1, image2, iters=1,
                      flow_init=None, test_mode=True, mesh=None,
                      metric_head=None, **kw):
                if self.fail:
                    raise ValueError("boom")
                flow = jnp.stack([image1[..., 0], image1[..., 1]], axis=-1)
                return image1.mean(), flow

        model = FlakyModel()
        srv = FlowServer(model, {}, _cfg())
        try:
            img = _img()
            r1 = srv.submit(img, img).result(10)
            assert r1.status == "error" and "boom" in r1.detail
            model.fail = False
            assert srv.submit(img, img).result(10).status == STATUS_OK
        finally:
            stats = srv.drain()
        assert stats.errors == 1 and stats.completed == 1


class TestDrainWorkerFailure:
    def test_stranded_batch_flushed_with_correct_attribution(self):
        """AsyncDrain surfaces a worker error from a LATER submit; the
        in-flight registry must complete the batch the worker actually
        stranded (with a drain-failure detail) instead of leaving its
        clients hanging and blaming only the next batch."""

        class AsyncDeadDrainer:
            calls = 0

            def submit(self, tree, cb):
                self.calls += 1
                if self.calls == 1:
                    return  # accepted; worker dies before delivering
                raise RuntimeError("pull failed")

            def close(self):
                pass

        srv = _server(batch_sizes=(1,))
        srv._drainer = AsyncDeadDrainer()
        try:
            img = _img()
            ha = srv.submit(img, img)  # batch 1: stranded by the worker
            hb = srv.submit(img, img)  # batch 2: submit raises
            ra, rb = ha.result(10), hb.result(10)
        finally:
            srv.drain()
        assert ra.status == "error" and "result drain failed" in ra.detail
        assert rb.status == "error"
        assert srv.stats.errors == 2
        assert srv._handles == {} and srv._inflight == {}


class TestNearestRank:
    def test_nearest_rank_percentiles(self):
        from raft_ncup_tpu.serving import nearest_rank_ms

        lat = [i / 1000.0 for i in range(1, 17)]  # 1..16 ms
        # p50 of 16 samples is the 8th smallest (ceil(0.5*16)-1 = idx 7),
        # not the floor-index 9th.
        assert nearest_rank_ms(lat, 0.50) == 8.0
        assert nearest_rank_ms(lat, 0.99) == 16.0
        assert nearest_rank_ms(list(reversed(lat)), 0.50) == 8.0  # sorts
        assert nearest_rank_ms([0.005], 0.50) == 5.0
        assert nearest_rank_ms([], 0.50) is None


class TestBudgetEndToEnd:
    def test_burst_degrades_and_recovers_with_hysteresis(self):
        """Saturate the queue, then let it drain request by request:
        the budget must drop immediately and recover only after the
        patience window — the controller's unit trajectory, reproduced
        through the real dispatcher."""
        srv = _server(queue_capacity=8, batch_sizes=(1,),
                      iter_levels=(4, 2), recover_patience=2)
        try:
            srv.pause()
            img = _img()
            handles = [srv.submit(img, img) for _ in range(8)]
            srv.resume()
            iters_seq = [h.result(20).iters for h in handles]
        finally:
            srv.drain()
        # Depth at assembly walks 8,7,...,1 (submissions finished before
        # resume; max_batch=1): drop at occupancy 1.0, floor through the
        # mid band, recover at the second calm decision.
        assert iters_seq == [2, 2, 2, 2, 2, 2, 2, 4]
        assert srv.budget.drops == 1
        assert srv.budget.recoveries == 1

    def test_burst_chaos_sheds_explicitly_not_unboundedly(self):
        """burst@0 with burst_size > capacity: overflow is shed with a
        retry hint; everything admitted completes. No request is
        silently dropped — submitted == terminal responses."""
        srv = _server(queue_capacity=4, batch_sizes=(1, 2))
        try:
            srv.pause()
            chaos = ChaosSpec.parse("burst@0")
            traffic = SyntheticTraffic((24, 32), 1, seed=5, burst_size=7,
                                       chaos=chaos)
            handles, interrupted = replay(srv, traffic)
            srv.resume()
            responses = [h.result(20) for h in handles]
        finally:
            srv.drain()
        assert not interrupted
        assert len(responses) == 7
        # The no-silent-drop protocol: every handle resolves to one of
        # the five explicit terminal statuses.
        assert all(r.status in TERMINAL_STATUSES for r in responses)
        by_status = {}
        for r in responses:
            by_status.setdefault(r.status, []).append(r)
        assert len(by_status[STATUS_SHED]) == 3  # 7 - capacity 4
        assert len(by_status[STATUS_OK]) == 4
        assert all(r.retry_after_s is not None
                   for r in by_status[STATUS_SHED])
        assert srv.stats.submitted == 7
        assert srv.stats.shed == 3 and srv.stats.completed == 4


class TestGracefulDrain:
    def test_sigterm_mid_flight_drains_all_admitted(self):
        """The drain contract through the REAL signal machinery: a
        SIGTERM delivered mid-stream stops submissions at once, every
        admitted request is flushed through compute, nothing hangs."""
        srv = _server(queue_capacity=16)
        with PreemptionHandler() as preempt:
            traffic = SyntheticTraffic((24, 32), 12, seed=7)
            chaos = ChaosSpec.parse("sigterm@5")
            handles, interrupted = replay(
                srv, traffic, preempt=preempt,
                sigterm_after=chaos.sigterm_after,
            )
            stats = srv.drain(timeout=30)
        assert interrupted
        assert len(handles) == 5  # submissions stopped at the signal
        responses = [h.result(10) for h in handles]
        assert [r.status for r in responses] == [STATUS_OK] * 5
        assert stats.accepted == stats.completed == 5
        assert not srv._thread.is_alive()
        assert srv._handles == {}  # nothing admitted was dropped

    def test_drain_sheds_new_submissions_flushes_old(self):
        srv = _server()
        srv.pause()
        img = _img()
        admitted = [srv.submit(img, img) for _ in range(3)]
        drainer = threading.Thread(target=srv.drain)
        drainer.start()
        time.sleep(0.05)
        refused = srv.submit(img, img)
        srv.resume()
        drainer.join(timeout=30)
        assert not drainer.is_alive()
        assert [h.result(10).status for h in admitted] == [STATUS_OK] * 3
        r = refused.result(5)
        assert r.status == STATUS_SHED and r.detail == "draining"

    def test_drain_idempotent(self):
        srv = _server()
        img = _img()
        h = srv.submit(img, img)
        assert h.result(10).ok
        s1 = srv.drain()
        s2 = srv.drain()
        assert s1 is s2


# ---------------------------------------------- real model + invariants


@pytest.fixture(scope="module")
def tiny_model():
    cfg = small_model_config("raft", dataset="chairs")
    model = RAFT(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 40, 48, 3))
    return model, variables


class TestRealModelServing:
    def test_response_matches_direct_forward_bitwise(self, tiny_model):
        """A served request's flow equals the same executable invoked
        directly on the identically staged batch — serving adds routing,
        never numerics."""
        from raft_ncup_tpu.inference.pipeline import ShapeCachedForward

        model, variables = tiny_model
        cfg = _cfg(batch_sizes=(1,), iter_levels=(2, 1))
        img1, img2 = _img(21, (40, 48)), _img(22, (40, 48))
        with FlowServer(model, variables, cfg) as srv:
            r = srv.submit(img1, img2).result(120)
        assert r.status == STATUS_OK and r.iters == 2
        ref_fwd = ShapeCachedForward(model, variables)
        _, ref = ref_fwd(img1[None], img2[None], 2)
        np.testing.assert_array_equal(r.flow, ref[0])

    def test_steady_state_sync_free_recompile_free(
        self, tiny_model, forbid_host_transfers, max_recompiles
    ):
        """The serving invariant the bench row records: once warmup has
        compiled the executable set, a steady window performs ZERO
        implicit host pulls and ZERO compiles — each batch's single
        result pull rides the sanctioned explicit device_get in the
        AsyncDrain worker."""
        model, variables = tiny_model
        cfg = _cfg(batch_sizes=(1,), iter_levels=(2, 1))
        srv = FlowServer(model, variables, cfg)
        try:
            srv.warmup((40, 48))
            warm = srv.submit(_img(30, (40, 48)), _img(31, (40, 48)))
            assert warm.result(120).ok
            with forbid_host_transfers() as stats, max_recompiles(0):
                handles = [
                    srv.submit(_img(40 + i, (40, 48)),
                               _img(50 + i, (40, 48)))
                    for i in range(3)
                ]
                rs = [h.result(120) for h in handles]
        finally:
            srv.drain()
        assert [r.status for r in rs] == [STATUS_OK] * 3
        assert stats.host_transfers == 0
        # One sanctioned pull per batch: the product path.
        assert stats.sanctioned_gets == 3

    def test_pad_bucket_collapses_shapes_into_one_program(self, tiny_model):
        """Two native shapes inside one bucket share a padded shape —
        they batch together and compile ONE executable (the bounded
        executable-set contract under mixed-resolution traffic)."""
        model, variables = tiny_model
        cfg = _cfg(batch_sizes=(1, 2), iter_levels=(2,), pad_bucket=48)
        srv = FlowServer(model, variables, cfg)
        try:
            srv.pause()
            ha = srv.submit(_img(61, (37, 45)), _img(62, (37, 45)))
            hb = srv.submit(_img(63, (40, 48)), _img(64, (40, 48)))
            srv.resume()
            ra, rb = ha.result(120), hb.result(120)
        finally:
            srv.drain()
        assert ra.status == STATUS_OK and rb.status == STATUS_OK
        assert ra.flow.shape == (37, 45, 2)
        assert rb.flow.shape == (40, 48, 2)
        assert srv.stats.batches == 1  # same bucket -> one micro-batch
        assert srv._fwd.stats["compiles"] == 1


class TestUhdAdmission:
    """4K requests are admissible by default (docs/PERF.md "Banded
    dispatch"): the ServeConfig ceiling is UHD 2176x3840 — the banded
    corr tier broke the memory wall that justified the old 1088x1920
    rejection — while oversized frames still reject crisply."""

    def test_default_ceiling_is_uhd(self):
        assert ServeConfig().max_image_hw == (2176, 3840)

    def test_4k_passes_admission_validation(self):
        server = _server()
        try:
            fake = SimpleNamespace(
                shape=(2176, 3840, 3), dtype=np.float32
            )
            assert server._admission_error(fake) is None
            too_big = SimpleNamespace(
                shape=(2184, 3840, 3), dtype=np.float32
            )
            err = server._admission_error(too_big)
            assert err is not None and "exceeds maximum" in err
        finally:
            server.drain()
