"""Tests for the CLI bridge, evaluation functions, and the train driver."""

import os
import sys

import jax
import numpy as np
import pytest
from PIL import Image

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_ncup_tpu.cli import parse_eval, parse_train
from raft_ncup_tpu.config import small_model_config, TrainConfig, UpsamplerConfig
from raft_ncup_tpu.evaluation import (
    create_kitti_submission,
    validate_chairs,
    validate_kitti,
)
from raft_ncup_tpu.io import read_flow_kitti, write_flo, write_flow_kitti
from raft_ncup_tpu.models.raft import RAFT

# The exact flag block every shipped reference script passes
# (reference: train_raft_nc_things.sh:19-50).
REFERENCE_SCRIPT_FLAGS = [
    "--name", "raft_nc_things_ft",
    "--model", "raft_nc_dbl",
    "--stage", "things",
    "--validation", "sintel",
    "--compressed_ft",
    "--gpus", "0", "1",
    "--num_steps", "100000",
    "--batch_size", "6",
    "--lr", "0.000125",
    "--image_size", "400", "720",
    "--optimizer", "adamW",
    "--scheduler", "cyclic",
    "--final_upsampling=NConvUpsampler",
    "--final_upsampling_scale=4",
    "--final_upsampling_use_data_for_guidance=True",
    "--final_upsampling_channels_to_batch=True",
    "--final_upsampling_use_residuals=False",
    "--final_upsampling_est_on_high_res=False",
    "--interp_net=NConvUNet",
    "--interp_net_channels_multiplier=2",
    "--interp_net_num_downsampling=1",
    "--interp_net_data_pooling=conf_based",
    "--interp_net_encoder_filter_sz=5",
    "--interp_net_decoder_filter_sz=3",
    "--interp_net_out_filter_sz=1",
    "--interp_net_shared_encoder=True",
    "--interp_net_use_double_conv=False",
    "--interp_net_use_bias=False",
    "--weights_est_net=Simple",
    "--weights_est_net_num_ch=[64, 32]",
    "--weights_est_net_filter_sz=[3, 3, 1]",
    "--weights_est_net_dilation=[1, 1, 1]",
]


class TestCli:
    def test_reference_script_flags_resolve(self):
        args, model_cfg, train_cfg, data_cfg = parse_train(
            REFERENCE_SCRIPT_FLAGS
        )
        assert model_cfg.variant == "raft_nc_dbl"
        assert model_cfg.dataset == "things"  # BN off outside sintel
        ups = model_cfg.upsampler
        assert ups.kind == "nconv" and ups.scale == 4
        assert ups.weights_est_num_ch == (64, 32)
        assert ups.weights_est_filter_sz == (3, 3, 1)
        assert ups.shared_encoder and not ups.use_bias
        assert train_cfg.num_steps == 100000
        assert train_cfg.lr == pytest.approx(0.000125)
        assert train_cfg.image_size == (400, 720)
        assert train_cfg.optimizer == "adamw"
        assert train_cfg.validation == ("sintel",)
        assert data_cfg.compressed_ft

    def test_eval_parser(self):
        args, model_cfg, data_cfg = parse_eval(
            ["--model", "raft_nc_dbl", "--dataset", "sintel",
             "--restore_ckpt", "x"]
        )
        assert model_cfg.dataset == "sintel"  # upsampler BN on for sintel
        assert args.dataset == "sintel"

    def test_upsampler_bi_overrides(self):
        _, model_cfg, *_ = parse_train(
            ["--stage", "chairs", "--model", "raft_nc_dbl", "--upsampler_bi"]
        )
        assert model_cfg.upsampler.kind == "bilinear"


# ------------------------------------------------------------------ fixtures


def make_chairs_fixture(root, n=3, hw=(48, 64)):
    root.mkdir(parents=True)
    g = np.random.default_rng(0)
    for i in range(1, n + 1):
        for k in (1, 2):
            Image.fromarray(
                g.integers(0, 255, (*hw, 3), dtype=np.uint8)
            ).save(root / f"{i:05d}_img{k}.png")
        write_flo(
            root / f"{i:05d}_flow.flo",
            g.normal(size=(*hw, 2)).astype(np.float32),
        )
    split_file = root.parent / "chairs_split.txt"
    np.savetxt(split_file, np.full(n, 2), fmt="%d")  # all validation
    return split_file


def make_kitti_fixture(root, split, n=2, hw=(48, 64)):
    d = root / split
    (d / "image_2").mkdir(parents=True)
    g = np.random.default_rng(1)
    for i in range(n):
        for suffix in ("10", "11"):
            Image.fromarray(
                g.integers(0, 255, (*hw, 3), dtype=np.uint8)
            ).save(d / "image_2" / f"{i:06d}_{suffix}.png")
    if split == "training":
        (d / "flow_occ").mkdir(parents=True)
        for i in range(n):
            write_flow_kitti(
                d / "flow_occ" / f"{i:06d}_10.png",
                g.normal(size=(*hw, 2)).astype(np.float32),
            )


def make_sintel_fixture(root, hw=(48, 64), frames=3):
    """training split (clean+final+flow) and test split (images only)."""
    g = np.random.default_rng(5)
    for split, dstypes in (("training", ("clean", "final")),
                           ("test", ("clean", "final"))):
        for dstype in dstypes:
            d = root / split / dstype / "scene_x"
            d.mkdir(parents=True, exist_ok=True)
            for i in range(frames):
                Image.fromarray(
                    g.integers(0, 255, (*hw, 3), dtype=np.uint8)
                ).save(d / f"frame_{i:04d}.png")
    fd = root / "training" / "flow" / "scene_x"
    fd.mkdir(parents=True)
    for i in range(frames - 1):
        write_flo(
            fd / f"frame_{i:04d}.flo",
            g.normal(size=(*hw, 2)).astype(np.float32),
        )


@pytest.fixture(scope="module")
def tiny_raft():
    cfg = small_model_config("raft", dataset="chairs")
    model = RAFT(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 48, 64, 3))
    return model, variables


class TestEvaluation:
    def test_validate_chairs(self, tmp_path, tiny_raft):
        from raft_ncup_tpu.config import DataConfig

        split_file = make_chairs_fixture(tmp_path / "chairs")
        model, variables = tiny_raft
        cfg = DataConfig(
            root_chairs=str(tmp_path / "chairs"),
            chairs_split_file=str(split_file),
        )
        out = validate_chairs(model, variables, cfg, iters=2)
        assert "chairs" in out and np.isfinite(out["chairs"])

    def test_validate_kitti(self, tmp_path, tiny_raft):
        from raft_ncup_tpu.config import DataConfig

        make_kitti_fixture(tmp_path / "KITTI", "training")
        model, variables = tiny_raft
        cfg = DataConfig(root_kitti=str(tmp_path / "KITTI"))
        out = validate_kitti(model, variables, cfg, iters=2)
        assert np.isfinite(out["kitti-epe"])
        assert 0.0 <= out["kitti-f1"] <= 100.0

    def test_validate_sintel_and_submission(self, tmp_path, tiny_raft):
        from raft_ncup_tpu.config import DataConfig
        from raft_ncup_tpu.evaluation import (
            create_sintel_submission,
            validate_sintel,
        )
        from raft_ncup_tpu.io import read_flo

        make_sintel_fixture(tmp_path / "Sintel")
        model, variables = tiny_raft
        cfg = DataConfig(root_sintel=str(tmp_path / "Sintel"))
        out = validate_sintel(model, variables, cfg, iters=2)
        assert np.isfinite(out["clean"]) and np.isfinite(out["final"])
        assert 0.0 <= out["clean_1px"] <= 1.0

        sub = tmp_path / "sub"
        create_sintel_submission(
            model, variables, cfg, iters=2, warm_start=True,
            output_path=str(sub),
        )
        flo = sub / "clean" / "scene_x" / "frame0001.flo"
        assert flo.exists()
        assert read_flo(flo).shape == (48, 64, 2)

    def test_kitti_submission_roundtrip(self, tmp_path, tiny_raft):
        from raft_ncup_tpu.config import DataConfig

        make_kitti_fixture(tmp_path / "KITTI", "testing")
        model, variables = tiny_raft
        cfg = DataConfig(root_kitti=str(tmp_path / "KITTI"))
        out_dir = tmp_path / "subm"
        create_kitti_submission(
            model, variables, cfg, iters=2, output_path=str(out_dir)
        )
        files = sorted(os.listdir(out_dir))
        assert files == ["000000_10.png", "000001_10.png"]
        flow, valid = read_flow_kitti(out_dir / files[0])
        assert flow.shape == (48, 64, 2)
        assert valid.all()


class TestEvalDriverMesh:
    def test_evaluate_cli_spatial_parallel(self, tmp_path, capsys):
        """VERDICT r3 #7: the driver-flag path for spatially-sharded eval
        — evaluate.py --spatial_parallel 2 — end-to-end over a Sintel
        fixture, and numerically equal to the single-device CLI run.
        Reference driver anchor: evaluate.py:111-143."""
        import evaluate as eval_driver

        make_sintel_fixture(tmp_path / "Sintel")
        base = [
            "--model", "raft", "--small",
            "--dataset", "sintel",
            "--corr_impl", "onthefly",
            "--iters", "2",
            "--root_sintel", str(tmp_path / "Sintel"),
        ]
        eval_driver.main(base)
        single = capsys.readouterr().out.strip().splitlines()[-1]
        eval_driver.main(base + ["--spatial_parallel", "2"])
        sharded = capsys.readouterr().out.strip().splitlines()[-1]
        # Both runs print the validator dict; EPEs must match closely.
        import ast

        s1, s2 = ast.literal_eval(single), ast.literal_eval(sharded)
        assert np.isfinite(s2["clean"]) and np.isfinite(s2["final"])
        np.testing.assert_allclose(s2["clean"], s1["clean"], rtol=1e-4)
        np.testing.assert_allclose(s2["final"], s1["final"], rtol=1e-4)


class TestDemoDriver:
    def test_demo_writes_flow_visualizations(self, tmp_path, capsys):
        """demo.py end-to-end: folder of frames in, side-by-side flow
        pngs out (reference: demo.py:50-68; C18)."""
        import demo as demo_driver

        frames = tmp_path / "frames"
        frames.mkdir()
        g = np.random.default_rng(9)
        for i in range(3):
            Image.fromarray(
                g.integers(0, 255, (48, 64, 3), dtype=np.uint8)
            ).save(frames / f"frame_{i:02d}.png")
        out = tmp_path / "out"
        demo_driver.main([
            "--path", str(frames), "--output", str(out),
            "--model", "raft", "--small", "--iters", "2",
        ])
        written = sorted(os.listdir(out))
        assert written == ["frame_00_flow.png", "frame_01_flow.png"]
        vis = np.asarray(Image.open(out / written[0]))
        # Side-by-side stack: frame on top, colorized flow below.
        assert vis.shape == (96, 64, 3)


class TestTrainDriver:
    # Tier-2: ~47s (two full train.py main() invocations). Resume
    # correctness stays tier-1 via test_checkpoint.py and the chaos
    # preemption tests; this CLI-level composition runs unfiltered.
    @pytest.mark.slow
    def test_train_resume_cycle(self, tmp_path, monkeypatch):
        """End-to-end composition through ``main(argv)``: loader, val
        cadence, checkpoint, restore (reference: train.py:167-261)."""
        import train as train_driver
        from raft_ncup_tpu import evaluation as eval_mod

        # Record the validation hook instead of scanning real datasets.
        val_calls: list[int] = []

        def fake_validator(model, variables, data_cfg=None):
            val_calls.append(1)
            return {"chairs_epe": 0.0}

        monkeypatch.setitem(eval_mod.VALIDATORS, "chairs", fake_validator)

        monkeypatch.chdir(tmp_path)
        base = [
            "--name", "smoke",
            "--model", "raft",
            "--small",
            "--stage", "chairs",
            "--image_size", "32", "48",
            "--batch_size", "2",
            "--iters", "2",
            "--val_freq", "2",
            "--sum_freq", "1",
            "--validation", "chairs",
            "--synthetic_ok",
            "--num_workers", "1",
            "--root_chairs", str(tmp_path / "missing"),
        ]
        train_driver.main(base + ["--num_steps", "3"])
        run_dir = tmp_path / "checkpoints" / "smoke"
        assert (run_dir / "log.txt").exists()
        steps = [d for d in os.listdir(run_dir) if d.isdigit()]
        assert "3" in steps
        # val_freq=2 with 3 steps: validation at steps 2 and 3 (final).
        assert len(val_calls) == 2
        log = (run_dir / "log.txt").read_text()
        assert "chairs_epe" in log

        # Resume from the saved state and run 2 more steps.
        train_driver.main(
            base + ["--num_steps", "5", "--restore_ckpt", str(run_dir)]
        )
        steps = {d for d in os.listdir(run_dir) if d.isdigit()}
        assert "5" in steps
        log = (run_dir / "log.txt").read_text()
        assert "restored step 3" in log

    def test_train_cli_mesh_flags(self, tmp_path, monkeypatch):
        """The driver-flag multichip path: train.py --data_parallel 2
        --spatial_parallel 2 builds a (2 x 2) mesh over the virtual
        devices and trains on it (reference's 2-GPU DataParallel
        analogue, train.py:169-175)."""
        import train as train_driver

        monkeypatch.chdir(tmp_path)
        train_driver.main([
            "--name", "mesh_smoke",
            "--model", "raft",
            "--small",
            "--stage", "chairs",
            "--image_size", "32", "48",
            "--batch_size", "2",
            "--iters", "2",
            "--num_steps", "2",
            "--sum_freq", "1",
            "--synthetic_ok",
            "--num_workers", "1",
            "--data_parallel", "2",
            "--spatial_parallel", "2",
            "--root_chairs", str(tmp_path / "missing"),
        ])
        run_dir = tmp_path / "checkpoints" / "mesh_smoke"
        log = (run_dir / "log.txt").read_text()
        assert "mesh=(2 data x 2 spatial)" in log
        assert (run_dir / "2").exists()


def test_validate_synthetic_heldout():
    """The synthetic validator runs on a held-out procedural split and
    returns a finite EPE for an untrained model."""
    import jax

    from raft_ncup_tpu.config import small_model_config
    from raft_ncup_tpu.evaluation import validate_synthetic
    from raft_ncup_tpu.models import get_model

    model = get_model(small_model_config("raft", dataset="chairs"))
    variables = model.init(jax.random.PRNGKey(0), (1, 32, 48, 3))
    out = validate_synthetic(
        model, variables, iters=2, batch_size=2, size_hw=(32, 48), length=4
    )
    assert set(out) == {"synthetic"}
    assert np.isfinite(out["synthetic"])


def test_validate_synthetic_empty_shard_skips():
    """Agreed length 0 (empty host shard) must skip like the real-data
    validators, not divide by zero — the guard fires before any forward,
    so model/variables are never touched."""
    from raft_ncup_tpu.evaluation import validate_synthetic

    out = validate_synthetic(None, {}, iters=2, batch_size=2,
                             size_hw=(32, 48), length=0)
    assert out == {}


def test_validate_synthetic_spatial_mesh_matches():
    """The mesh-sharded eval path (evaluate.py --spatial_parallel) must
    reproduce the single-device validator EPE."""
    import jax

    from raft_ncup_tpu.config import small_model_config
    from raft_ncup_tpu.evaluation import validate_synthetic
    from raft_ncup_tpu.models import get_model
    from raft_ncup_tpu.parallel.mesh import make_mesh

    model = get_model(
        small_model_config("raft", dataset="chairs", corr_impl="onthefly")
    )
    variables = model.init(jax.random.PRNGKey(0), (1, 32, 48, 3))
    kwargs = dict(iters=2, batch_size=2, size_hw=(32, 48), length=4)
    ref = validate_synthetic(model, variables, **kwargs)
    mesh = make_mesh(data=1, spatial=2, devices=jax.devices()[:2])
    out = validate_synthetic(model, variables, mesh=mesh, **kwargs)
    np.testing.assert_allclose(out["synthetic"], ref["synthetic"], rtol=1e-4)


class TestServeDriver:
    def test_sigterm_drain_leaves_one_flight_dump_and_healthz(
        self, tmp_path, capsys, monkeypatch
    ):
        """The rc-75 half of the flight-recorder acceptance through the
        REAL driver: a serve.py run SIGTERMed mid-stream drains (exit
        75), leaves EXACTLY one valid preemption_drain dump, rewrites
        the --healthz_file to draining, and scripts/postmortem.py
        reassembles a served request's full span journey (queue wait →
        dispatch → drain) from the dump."""
        import importlib.util
        import json

        import serve as serve_driver
        from raft_ncup_tpu.observability import get_telemetry, set_telemetry

        flight = tmp_path / "flight"
        healthz = tmp_path / "healthz.json"
        # The driver arms the PROCESS hub; isolate it from other tests.
        prev = set_telemetry(None)
        try:
            rc = serve_driver.main([
                "--platform", "cpu",
                "--small",
                "--num_requests", "8",
                "--size", "48", "64",
                "--iter_levels", "2,1",
                "--serve_batch_sizes", "1,2",
                "--chaos", "sigterm@3",
                "--flight_dir", str(flight),
                "--healthz_file", str(healthz),
                "--telemetry_interval_s", "0.5",
            ])
        finally:
            tel = get_telemetry()
            tel.flight = None
            tel.slo = None
            set_telemetry(prev)
        assert rc == 75  # EXIT_PREEMPTED: the SIGTERM/exit-75 contract
        out = capsys.readouterr().out
        report = json.loads(out.strip().splitlines()[-1])
        assert report["interrupted"] is True
        assert report["health"]["state"] == "draining"
        assert "slo" in report
        hz = json.load(open(healthz))
        assert hz["draining"] is True and hz["overall"] == "draining"
        dumps = sorted(os.listdir(flight))
        assert len(dumps) == 1 and dumps[0].startswith(
            "flight_preemption_drain_"
        )
        spec = importlib.util.spec_from_file_location(
            "postmortem",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts", "postmortem.py",
            ),
        )
        pm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pm)
        assert pm.main([str(flight / dumps[0]), "--request_id", "0"]) == 0
        journey = capsys.readouterr().out
        for stage in ("serve_queue_wait", "serve_dispatch", "serve_drain"):
            assert stage in journey  # the request's full span journey


class TestReplicaDriver:
    def test_replica_mode_serves_wire_and_drains_on_sigterm(
        self, tmp_path, capsys
    ):
        """The fleet replica half of the drain contract through the
        REAL driver, in-process: serve.py --replica_socket answers a
        request and a stream frame over the wire protocol, advertises
        its identity (warmed executable set) through --healthz_file,
        and on SIGTERM shows DRAINING in healthz, flushes, exits 75
        with guard counters 0 (docs/FLEET.md)."""
        import json
        import signal
        import socket
        import threading
        import time

        import serve as serve_driver
        from raft_ncup_tpu.fleet.wire import recv_msg, send_msg
        from raft_ncup_tpu.observability import get_telemetry, set_telemetry

        sock_path = str(tmp_path / "replica.sock")
        healthz = tmp_path / "healthz.json"
        client_out = {}

        def client():
            deadline = time.monotonic() + 120
            while not os.path.exists(sock_path):
                if time.monotonic() > deadline:
                    client_out["error"] = "socket never appeared"
                    os.kill(os.getpid(), signal.SIGTERM)
                    return
                time.sleep(0.05)
            img = np.random.default_rng(0).uniform(
                0, 255, (48, 64, 3)
            ).astype(np.float32)
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(sock_path)
                send_msg(s, {"kind": "request", "id": 5}, [img, img])
                hdr, arrs = recv_msg(s)
                client_out["request"] = (hdr, arrs[0].shape if arrs else None)
                send_msg(s, {"kind": "frame", "id": 6, "stream_id": "sA",
                             "frame_index": 0}, [img, img])
                hdr, arrs = recv_msg(s)
                client_out["frame"] = (hdr, arrs[0].shape if arrs else None)
                client_out["healthz_live"] = json.load(open(healthz))
            except Exception as e:  # surfaced via the asserts below
                client_out["error"] = repr(e)
            finally:
                os.kill(os.getpid(), signal.SIGTERM)

        prev = set_telemetry(None)
        t = threading.Thread(target=client, daemon=True)
        try:
            t.start()
            rc = serve_driver.main([
                "--platform", "cpu", "--small",
                "--replica_socket", sock_path,
                "--replica_index", "2",
                "--size", "48", "64",
                "--iter_levels", "2",
                "--serve_batch_sizes", "1,2",
                "--replica_streams", "true",
                "--stream_capacity", "2",
                "--stream_iters", "2",
                "--stream_batch_sizes", "1,2",
                "--healthz_file", str(healthz),
                "--flight_dir", str(tmp_path / "flight"),
                "--telemetry_interval_s", "0.25",
            ])
            t.join(timeout=30)
        finally:
            tel = get_telemetry()
            tel.flight = None
            tel.slo = None
            tel.identity.clear()
            set_telemetry(prev)
        assert "error" not in client_out, client_out
        assert rc == 75  # the SIGTERM -> drain -> exit-75 contract
        hdr, flow_shape = client_out["request"]
        assert hdr["id"] == 5 and hdr["status"] == "ok"
        assert flow_shape == (48, 64, 2)
        hdr, flow_shape = client_out["frame"]
        assert hdr["id"] == 6 and hdr["status"] == "ok"
        assert flow_shape == (48, 64, 2)
        # Live healthz carried the replica identity the router routes on.
        live = client_out["healthz_live"]
        assert live["replica"] == 2
        assert [48, 64, 1, 2] in live["warmed"]
        assert live["stale_after_s"] == 0.5
        # Final healthz: DRAINING, per the contract.
        hz = json.load(open(healthz))
        assert hz["draining"] is True and hz["overall"] == "draining"
        # Final report: guard-clean window, every request accounted.
        out = capsys.readouterr().out
        report = json.loads(out.strip().splitlines()[-1])
        assert report["interrupted"] is True
        assert report["replica"] == 2
        assert report["recompiles"] == 0
        assert report["host_transfers"] == 0
        assert report["completed"] == 1
        assert report["stream_completed"] == 1
