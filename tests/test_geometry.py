"""Unit tests for sampling/geometry ops, including bit-level comparisons
against PyTorch's grid_sample / interpolate / unfold semantics (torch-cpu
is available in the image; these are semantics oracles, not a runtime
dependency)."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from raft_ncup_tpu.ops import (
    InputPadder,
    adaptive_area_resize,
    bilinear_resize_align_corners,
    convex_upsample,
    coords_grid,
    grid_sample,
    upflow,
    upsample_nearest,
)
from raft_ncup_tpu.ops.geometry import avg_pool2, extract_3x3_patches


def torch_bilinear_sampler(img_nchw, coords_xy):
    """The reference's bilinear_sampler (core/utils/utils.py:59-73)."""
    H, W = img_nchw.shape[-2:]
    xgrid, ygrid = coords_xy.split([1, 1], dim=-1)
    xgrid = 2 * xgrid / (W - 1) - 1
    ygrid = 2 * ygrid / (H - 1) - 1
    grid = torch.cat([xgrid, ygrid], dim=-1)
    return F.grid_sample(img_nchw, grid, align_corners=True)


def test_coords_grid():
    g = coords_grid(2, 3, 4)
    assert g.shape == (2, 3, 4, 2)
    assert np.allclose(g[0, :, :, 0], np.tile(np.arange(4), (3, 1)))
    assert np.allclose(g[0, :, :, 1], np.tile(np.arange(3)[:, None], (1, 4)))


@pytest.mark.parametrize("seed", [0, 1])
def test_grid_sample_matches_torch(seed):
    rng = np.random.default_rng(seed)
    B, H, W, C = 2, 7, 9, 3
    img = rng.standard_normal((B, H, W, C)).astype(np.float32)
    # Coordinates deliberately straddle the borders and go out of bounds.
    coords = rng.uniform(-2.5, max(H, W) + 1.5, size=(B, 5, 6, 2)).astype(np.float32)

    ours = np.asarray(grid_sample(jnp.asarray(img), jnp.asarray(coords)))

    t_img = torch.from_numpy(img).permute(0, 3, 1, 2)
    t_coords = torch.from_numpy(coords)
    theirs = torch_bilinear_sampler(t_img, t_coords).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_grid_sample_exact_at_integer_coords():
    img = np.arange(24, dtype=np.float32).reshape(1, 4, 6, 1)
    coords = np.array([[[[2.0, 1.0], [0.0, 0.0], [5.0, 3.0]]]], dtype=np.float32)
    out = np.asarray(grid_sample(jnp.asarray(img), jnp.asarray(coords)))
    np.testing.assert_allclose(out[0, 0, :, 0], [8.0, 0.0, 23.0])


def test_bilinear_resize_align_corners_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 5, 7, 2)).astype(np.float32)
    ours = np.asarray(bilinear_resize_align_corners(jnp.asarray(x), (15, 21)))
    t = torch.from_numpy(x).permute(0, 3, 1, 2)
    theirs = (
        F.interpolate(t, size=(15, 21), mode="bilinear", align_corners=True)
        .permute(0, 2, 3, 1)
        .numpy()
    )
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_upflow_scales_values():
    flow = jnp.ones((1, 4, 4, 2))
    up = upflow(flow, 8, align_corners=True)
    assert up.shape == (1, 32, 32, 2)
    np.testing.assert_allclose(np.asarray(up), 8.0, atol=1e-6)


def test_upsample_nearest_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 3, 4, 2)).astype(np.float32)
    ours = np.asarray(upsample_nearest(jnp.asarray(x), 2))
    t = torch.from_numpy(x).permute(0, 3, 1, 2)
    theirs = F.interpolate(t, scale_factor=2, mode="nearest").permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours, theirs)


def test_adaptive_area_resize_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 4, 6, 3)).astype(np.float32)
    t = torch.from_numpy(x).permute(0, 3, 1, 2)
    # 2x upsample (the NCUP guidance path, H/8 -> H/4).
    ours_up = np.asarray(adaptive_area_resize(jnp.asarray(x), (8, 12)))
    theirs_up = F.interpolate(t, size=(8, 12), mode="area").permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours_up, theirs_up, atol=1e-6)
    # 2x downsample.
    ours_dn = np.asarray(adaptive_area_resize(jnp.asarray(x), (2, 3)))
    theirs_dn = F.interpolate(t, size=(2, 3), mode="area").permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours_dn, theirs_dn, atol=1e-6)


def test_avg_pool2_matches_torch_odd_shapes():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 5, 7, 1)).astype(np.float32)
    ours = np.asarray(avg_pool2(jnp.asarray(x)))
    t = torch.from_numpy(x).permute(0, 3, 1, 2)
    theirs = F.avg_pool2d(t, 2, stride=2).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)


def test_extract_patches_matches_unfold():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 4, 5, 2)).astype(np.float32)
    ours = np.asarray(extract_3x3_patches(jnp.asarray(x)))  # (B, H, W, 9, C)
    t = torch.from_numpy(x).permute(0, 3, 1, 2)
    unf = F.unfold(t, [3, 3], padding=1)  # (B, C*9, H*W)
    theirs = unf.reshape(1, 2, 9, 4, 5).permute(0, 3, 4, 2, 1).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)


def test_convex_upsample_matches_reference_math():
    """Mirror core/raft.py:73-84 in torch and compare."""
    rng = np.random.default_rng(0)
    B, H, W = 1, 3, 4
    flow = rng.standard_normal((B, H, W, 2)).astype(np.float32)
    mask = rng.standard_normal((B, H, W, 9 * 64)).astype(np.float32)

    ours = np.asarray(convex_upsample(jnp.asarray(flow), jnp.asarray(mask), 8))

    tf = torch.from_numpy(flow).permute(0, 3, 1, 2)
    # Our mask channel layout is c = k*64 + i*8 + j, identical to the
    # reference's view(N, 1, 9, 8, 8, H, W) on an NCHW tensor.
    tm = torch.from_numpy(mask).permute(0, 3, 1, 2)
    m = tm.view(B, 1, 9, 8, 8, H, W)
    m = torch.softmax(m, dim=2)
    up_flow = F.unfold(8 * tf, [3, 3], padding=1)
    up_flow = up_flow.view(B, 2, 9, 1, 1, H, W)
    up_flow = torch.sum(m * up_flow, dim=2)
    up_flow = up_flow.permute(0, 1, 4, 2, 5, 3)
    theirs = up_flow.reshape(B, 2, 8 * H, 8 * W).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


@pytest.mark.parametrize("mode", ["sintel", "kitti"])
def test_input_padder_roundtrip(mode):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 436, 1023, 3)).astype(np.float32)
    padder = InputPadder(x.shape, mode=mode)
    (padded,) = padder.pad(jnp.asarray(x))
    assert padded.shape[1] % 8 == 0 and padded.shape[2] % 8 == 0
    back = np.asarray(padder.unpad(padded))
    np.testing.assert_allclose(back, x)

    # Compare padded content against the reference's torch pad spec.
    t = torch.from_numpy(x).permute(0, 3, 1, 2)
    pad_ht = (((436 // 8) + 1) * 8 - 436) % 8
    pad_wd = (((1023 // 8) + 1) * 8 - 1023) % 8
    if mode == "sintel":
        tp = [pad_wd // 2, pad_wd - pad_wd // 2, pad_ht // 2, pad_ht - pad_ht // 2]
    else:
        tp = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]
    theirs = F.pad(t, tp, mode="replicate").permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(np.asarray(padded), theirs)


def test_input_padder_custom_divisor():
    """divisor=16 (8 * spatial=2) pads H so the 1/8-res feature height is
    even — required for the shard_map corr path in spatially-sharded
    eval; W still pads to 8 only."""
    import jax.numpy as jnp

    x = jnp.zeros((1, 436, 1024, 3))  # Sintel height
    padder = InputPadder(x.shape, divisor=16)
    (y,) = padder.pad(x)
    assert y.shape[1] % 16 == 0 and (y.shape[1] // 8) % 2 == 0
    assert y.shape[2] % 8 == 0
    assert padder.unpad(y).shape == x.shape
