"""raft_ncup_tpu/traffic.py: the deterministic multi-phase traffic
generator (first slice of ROADMAP item 4's scenario suite).

Everything here is a replay contract: the elasticity bench, the serve
bench, and the fleet acceptance tests all consume the SAME schedule, so
phase attribution, due-time arithmetic, frame determinism, and chaos
composition are each pinned exactly.
"""

import numpy as np
import pytest

from raft_ncup_tpu.resilience.chaos import ChaosSpec
from raft_ncup_tpu.traffic import StepTraffic, TrafficPhase


class TestPhases:
    def test_step_scenario_bounds(self):
        t = StepTraffic.step((32, 48))
        assert t.phase_bounds() == {
            "low": (0, 8), "high": (8, 32), "cooldown": (32, 40),
        }
        assert t.n_requests == 40 and len(t) == 40

    def test_validation(self):
        with pytest.raises(ValueError):
            StepTraffic((32, 48), [])
        with pytest.raises(ValueError):  # duplicate names
            StepTraffic((32, 48), [
                TrafficPhase("a", 1, 0.1), TrafficPhase("a", 1, 0.1),
            ])
        with pytest.raises(ValueError):
            TrafficPhase("a", -1, 0.1)
        with pytest.raises(ValueError):
            TrafficPhase("a", 1, -0.1)

    def test_due_times_accumulate_across_phases(self):
        """A step is a rate CHANGE at an instant, not a gap: phase k+1's
        first arrival is one of ITS intervals after phase k's last."""
        t = StepTraffic((32, 48), [
            TrafficPhase("low", 2, 0.5),
            TrafficPhase("high", 3, 0.1),
        ])
        dues = [item.due_s for item in t.schedule()]
        assert dues == pytest.approx([0.5, 1.0, 1.1, 1.2, 1.3])
        assert dues == sorted(dues)

    def test_phase_attribution_matches_bounds(self):
        t = StepTraffic.step((32, 48), low_n=2, high_n=3)
        bounds = t.phase_bounds()
        for item in t.schedule():
            lo, hi = bounds[item.phase]
            assert lo <= item.index < hi


class TestDeterminism:
    def test_same_seed_same_bytes_and_schedule(self):
        a = list(StepTraffic.step((32, 48), low_n=2, high_n=2, seed=7)
                 .schedule())
        b = list(StepTraffic.step((32, 48), low_n=2, high_n=2, seed=7)
                 .schedule())
        for x, y in zip(a, b):
            assert (x.index, x.phase, x.due_s) == (y.index, y.phase,
                                                   y.due_s)
            np.testing.assert_array_equal(x.image1, y.image1)
            np.testing.assert_array_equal(x.image2, y.image2)

    def test_different_seed_different_bytes(self):
        a = next(iter(StepTraffic.step((32, 48), seed=0).schedule()))
        b = next(iter(StepTraffic.step((32, 48), seed=1).schedule()))
        assert not np.array_equal(a.image1, b.image1)


class TestChaosComposition:
    def test_burst_multiplies_one_global_index(self):
        t = StepTraffic(
            (32, 48),
            [TrafficPhase("low", 2, 0.1), TrafficPhase("high", 3, 0.1)],
            chaos=ChaosSpec.parse("burst@3"), burst_size=4,
        )
        items = list(t.schedule())
        # Request 3 (in the HIGH phase — index is global) became 4
        # copies sharing its index, phase, and due time.
        assert len(items) == len(t) == 5 + 3
        copies = [i for i in items if i.index == 3]
        assert len(copies) == 4
        assert {(c.phase, c.due_s) for c in copies} == {
            (copies[0].phase, copies[0].due_s)
        }
        assert copies[0].phase == "high"

    def test_poison_nans_first_frame_only(self):
        t = StepTraffic(
            (32, 48), [TrafficPhase("low", 3, 0.1)],
            chaos=ChaosSpec.parse("poison@1"),
        )
        items = list(t.schedule())
        assert np.isnan(items[1].image1).all()
        assert np.isfinite(items[1].image2).all()
        assert np.isfinite(items[0].image1).all()

    def test_out_of_range_burst_is_inert(self):
        t = StepTraffic(
            (32, 48), [TrafficPhase("low", 2, 0.1)],
            chaos=ChaosSpec.parse("burst@99"), burst_size=4,
        )
        assert len(t) == 2 == len(list(t.schedule()))


class TestConsumptionShapes:
    def test_iter_matches_serving_replay_contract(self):
        t = StepTraffic.step((32, 48), low_n=2, high_n=2)
        triples = list(t)
        rich = list(t.schedule())
        assert len(triples) == len(rich)
        for (due, i1, i2), item in zip(triples, rich):
            assert due == item.due_s
            np.testing.assert_array_equal(i1, item.image1)
            assert i1.shape == (32, 48, 3)

    def test_items_matches_replay_fleet_contract(self):
        t = StepTraffic.step((32, 48), low_n=2, high_n=2)
        for d in t.items():
            assert set(d) == {
                "image1", "image2", "due_s", "phase", "index",
            }
            assert isinstance(d["image1"], np.ndarray)


class TestMixedResolution:
    """MixedResolutionTraffic (second slice of ROADMAP item 4): zipf
    popularity over frame SIZES, with the same determinism, phase
    attribution, and chaos composition contracts as the step schedule —
    the early-exit bench row's input (docs/PERF.md "Early exit")."""

    SIZES = [(32, 48), (24, 32), (40, 48)]

    def _t(self, n=20, **kw):
        from raft_ncup_tpu.traffic import MixedResolutionTraffic

        return MixedResolutionTraffic(self.SIZES, n, seed=5, **kw)

    def test_validation(self):
        from raft_ncup_tpu.traffic import MixedResolutionTraffic

        with pytest.raises(ValueError, match="needs sizes"):
            MixedResolutionTraffic([], 4)
        with pytest.raises(ValueError, match="unique"):
            MixedResolutionTraffic([(32, 48), (32, 48)], 4)
        with pytest.raises(ValueError, match="exponent"):
            MixedResolutionTraffic(self.SIZES, 4, exponent=0.0)
        with pytest.raises(ValueError, match="n_requests"):
            MixedResolutionTraffic(self.SIZES, -1)

    def test_deterministic_replay(self):
        a, b = list(self._t().schedule()), list(self._t().schedule())
        for x, y in zip(a, b):
            assert (x.index, x.phase, x.due_s) == (y.index, y.phase,
                                                   y.due_s)
            np.testing.assert_array_equal(x.image1, y.image1)

    def test_zipf_mix_and_phase_attribution(self):
        """Rank-0 (most popular) dominates; every item's frame shape
        matches its phase name; size_counts sums to n_requests."""
        t = self._t(n=60)
        counts = t.size_counts()
        assert sum(counts.values()) == 60
        assert counts["32x48"] >= counts["40x48"]  # rank 0 vs rank 2
        for item in t.schedule():
            h, w = (int(x) for x in item.phase.split("x"))
            assert item.image1.shape == (h, w, 3)

    def test_due_times_accumulate(self):
        t = self._t(n=4, interval_s=0.25)
        dues = [item.due_s for item in t.schedule()]
        assert dues == pytest.approx([0.25, 0.5, 0.75, 1.0])

    def test_chaos_composes_on_global_indices(self):
        from raft_ncup_tpu.resilience.chaos import ChaosSpec

        t = self._t(
            n=6, chaos=ChaosSpec.parse("burst@2,poison@0"), burst_size=3,
        )
        items = list(t.schedule())
        assert len(items) == len(t) == 6 + 2  # one burst adds 2 copies
        burst = [i for i in items if i.index == 2]
        assert len(burst) == 3
        assert len({b.phase for b in burst}) == 1  # copies share size
        assert np.isnan(items[0].image1).all()
        assert np.isfinite(items[0].image2).all()

    def test_consumption_contracts(self):
        t = self._t(n=3)
        triples = list(t)
        rich = list(t.schedule())
        assert len(triples) == 3
        for (due, i1, _i2), item in zip(triples, rich):
            assert due == item.due_s
            np.testing.assert_array_equal(i1, item.image1)
        for d in t.items():
            assert set(d) == {
                "image1", "image2", "due_s", "phase", "index",
            }
