"""Iteration-pipelined inference (inference/pipe_schedule.py;
docs/SHARDING.md "Pipeline axis").

The pipeline's claims split cleanly into CPU-pinnable invariants and a
chip-window throughput claim; these tests pin everything in the first
bucket on the forced 8-virtual-device platform (tests/conftest.py):

- segment math: iteration splitting and the budget quantization rule
  (``serving/budget.py`` validates at construction),
- PARITY: the streamed pipeline (S=2, S=4) is tolerance-equal to the
  monolithic scan for both variants and both precisions — segmented
  and monolithic execution share one step body by construction
  (models/raft.py ``_make_step``), and the stream exercises every
  carry-handoff seam,
- S=1 is EXACTLY the monolithic path (delegation, forward cache keys,
  no pipe machinery),
- shape algebra is segmentation-invariant (eval_shape, no compiles),
- steady state is guard-clean (0 recompiles, 0 implicit host
  transfers) and the state operand is donated,
- the compiled tick's HLO carries the collective-permute handoff
  fingerprint (``parallel.mesh.collective_stats`` per-op breakout),
- the tick executable lands in the cost ledger with structured
  pipe_tick meta and the per-segment cost split.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import small_model_config
from raft_ncup_tpu.inference.costs import CostLedger
from raft_ncup_tpu.inference.pipe_schedule import (
    PipelinedForward,
    split_iters,
    validate_segment_levels,
)
from raft_ncup_tpu.inference.pipeline import ShapeCachedForward
from raft_ncup_tpu.models import get_model
from raft_ncup_tpu.parallel.mesh import collective_stats, make_mesh
from raft_ncup_tpu.serving.budget import IterationBudgetController

HW = (32, 32)
ITERS = 4  # divisible by S in {1, 2, 4}


@pytest.fixture(scope="module")
def raft(request):
    cfg = small_model_config("raft", dataset="chairs")
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, *HW, 3))
    return model, variables


@pytest.fixture(scope="module")
def dbl(request):
    cfg = small_model_config("raft_nc_dbl", dataset="chairs")
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, *HW, 3))
    return model, variables


@pytest.fixture(scope="module")
def raft_mono(raft):
    model, variables = raft
    return ShapeCachedForward(model, variables)


@pytest.fixture(scope="module")
def pf_raft_s2(raft):
    model, variables = raft
    return PipelinedForward(
        model, variables, segments=2, cost_ledger=CostLedger(enabled=True)
    )


def _pairs(n, seed=0):
    g = np.random.default_rng(seed)
    return [
        (
            jnp.asarray(g.random((1, *HW, 3)) * 255.0, jnp.float32),
            jnp.asarray(g.random((1, *HW, 3)) * 255.0, jnp.float32),
        )
        for _ in range(n)
    ]


def _assert_stream_parity(outs, ref, rtol=1e-5, atol=1e-5):
    assert len(outs) == len(ref)
    for (lr_p, up_p), (lr_m, up_m) in zip(outs, ref):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(lr_p)),
            np.asarray(jax.device_get(lr_m)), rtol=rtol, atol=atol,
        )
        np.testing.assert_allclose(
            np.asarray(jax.device_get(up_p)),
            np.asarray(jax.device_get(up_m)), rtol=rtol, atol=atol,
        )


# ---------------------------------------------------------- segment math


class TestSegmentMath:
    def test_split_iters(self):
        assert split_iters(24, 1) == 24
        assert split_iters(24, 2) == 12
        assert split_iters(24, 4) == 6
        with pytest.raises(ValueError, match="does not split"):
            split_iters(24, 5)
        with pytest.raises(ValueError, match="segments must be >= 1"):
            split_iters(24, 0)

    def test_level_quantization_rule(self):
        # segments=1 imposes nothing — any valid level set passes.
        validate_segment_levels((24, 16, 8), 1)
        # The ISSUE's canonical counterexample: (24, 16, 8) with S=2
        # has segment length 12; 16 and 8 sit mid-segment.
        with pytest.raises(
            ValueError, match="quantize to the segment boundary"
        ):
            validate_segment_levels((24, 16, 8), 2)
        validate_segment_levels((24, 12), 2)
        validate_segment_levels((24, 18, 12, 6), 4)
        with pytest.raises(ValueError, match="does not split into 5"):
            validate_segment_levels((24, 12), 5)

    def test_error_names_a_valid_level_set(self):
        """The error must hand the operator a fix, not just a refusal."""
        with pytest.raises(ValueError, match=r"\(24, 12\)"):
            validate_segment_levels((24, 16, 8), 2)

    def test_serve_config_accepts_pipe_triple(self):
        """ServeConfig/StreamConfig mesh fields take (data, spatial,
        pipe) — resolve_config_mesh builds the 3-axis mesh from it and
        FlowServer passes the pipe size into the budget controller's
        quantization validation."""
        from raft_ncup_tpu.config import ServeConfig, StreamConfig

        assert ServeConfig(mesh=(1, 1, 2)).mesh == (1, 1, 2)
        assert StreamConfig(mesh=(1, 1, 2)).mesh == (1, 1, 2)
        with pytest.raises(ValueError, match="positive sizes"):
            ServeConfig(mesh=(1, 1, 0))
        with pytest.raises(ValueError, match="positive sizes"):
            ServeConfig(mesh=(1, 1, 2, 2))

    def test_budget_controller_validates_at_construction(self):
        with pytest.raises(
            ValueError, match="quantize to the segment boundary"
        ):
            IterationBudgetController((24, 16, 8), capacity=8, segments=2)
        # Default segments=1: the existing contract is untouched.
        ctl = IterationBudgetController((24, 16, 8), capacity=8)
        assert ctl.segments == 1
        ctl = IterationBudgetController((24, 12), capacity=8, segments=2)
        assert ctl.segments == 2
        assert ctl.decide(0) == 24  # quantized set still drives decisions


# ---------------------------------------------------------------- parity


class TestStreamParity:
    def test_raft_s2(self, raft, raft_mono, pf_raft_s2):
        _model, _variables = raft
        pairs = _pairs(3)
        ref = [raft_mono.forward_device(i1, i2, ITERS) for i1, i2 in pairs]
        _assert_stream_parity(pf_raft_s2.forward_many(pairs, ITERS), ref)

    def test_raft_s4(self, raft, raft_mono):
        model, variables = raft
        pf = PipelinedForward(model, variables, segments=4)
        assert pf.segments == 4 and pf.is_pipelined
        pairs = _pairs(5)
        ref = [raft_mono.forward_device(i1, i2, ITERS) for i1, i2 in pairs]
        _assert_stream_parity(pf.forward_many(pairs, ITERS), ref)

    def test_dbl_s2(self, dbl):
        model, variables = dbl
        mono = ShapeCachedForward(model, variables)
        pf = PipelinedForward(model, variables, segments=2)
        pairs = _pairs(3, seed=7)
        ref = [mono.forward_device(i1, i2, ITERS) for i1, i2 in pairs]
        _assert_stream_parity(pf.forward_many(pairs, ITERS), ref)

    def test_raft_s2_bf16(self, raft, raft_mono, pf_raft_s2):
        """Precision-policy override rides the pipeline: the bf16 tick
        is its own executable (policy fingerprint in the key) and
        matches the monolithic bf16 forward within bf16 slack."""
        pairs = _pairs(3, seed=3)
        ref = [
            raft_mono.forward_device(i1, i2, ITERS, policy="bf16_infer")
            for i1, i2 in pairs
        ]
        outs = pf_raft_s2.forward_many(pairs, ITERS, policy="bf16_infer")
        _assert_stream_parity(outs, ref, rtol=5e-2, atol=5e-2)

    def test_seam_composition_equals_full_scan(self, raft):
        """Model-level seam pin (no mesh): encode -> refine_segment x2
        -> finalize reproduces apply() exactly — the carry dict is the
        COMPLETE state at a segment boundary."""
        model, variables = raft
        g = np.random.default_rng(11)
        i1 = jnp.asarray(g.random((1, *HW, 3)) * 255.0, jnp.float32)
        i2 = jnp.asarray(g.random((1, *HW, 3)) * 255.0, jnp.float32)
        ref_lr, ref_up = model.apply(
            variables, i1, i2, iters=ITERS, test_mode=True
        )
        carry = model.encode(variables, i1, i2)
        carry = model.refine_segment(variables, carry, ITERS // 2)
        carry = model.refine_segment(variables, carry, ITERS // 2)
        lr, up = model.finalize(variables, carry)
        np.testing.assert_allclose(
            np.asarray(lr), np.asarray(ref_lr), rtol=1e-6, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(up), np.asarray(ref_up), rtol=1e-6, atol=1e-6
        )


# ----------------------------------------------------- shapes/delegation


class TestShapesAndDelegation:
    @pytest.mark.parametrize("variant", ["raft", "raft_nc_dbl"])
    def test_eval_shape_segmentation_invariant(self, variant, raft, dbl):
        """Output ShapeDtypeStructs are identical for S in {1, 2, 4} and
        match the monolithic apply — pure shape algebra, no compiles."""
        model, variables = raft if variant == "raft" else dbl
        img = jax.ShapeDtypeStruct((1, *HW, 3), jnp.float32)

        def seg_run(s):
            def run(v, a, b):
                c = model.encode(v, a, b)
                for _ in range(s):
                    c = model.refine_segment(v, c, ITERS // s)
                return model.finalize(v, c)

            return jax.eval_shape(run, variables, img, img)

        mono = jax.eval_shape(
            lambda v, a, b: model.apply(
                v, a, b, iters=ITERS, test_mode=True
            ),
            variables, img, img,
        )
        shapes = {s: seg_run(s) for s in (1, 2, 4)}
        assert shapes[1] == shapes[2] == shapes[4] == mono

    def test_s1_is_exactly_the_monolithic_path(self, raft, raft_mono):
        model, variables = raft
        pf = PipelinedForward(model, variables, segments=1)
        assert not pf.is_pipelined and pf.mesh is None
        pairs = _pairs(2)
        outs = pf.forward_many(pairs, ITERS)
        ref = [raft_mono.forward_device(i1, i2, ITERS) for i1, i2 in pairs]
        _assert_stream_parity(outs, ref, rtol=0, atol=0)
        # Cache holds plain forward keys only — no pipeline machinery
        # was compiled (and no pipe mesh exists to fingerprint them).
        keys = list(pf.cache._fns)
        assert keys and all("pipe" not in str(k) for k in keys)
        assert keys[0][0] == "nomesh"

    def test_constructor_rejects_mismatch_and_mixed_mesh(self, raft):
        model, variables = raft
        mesh = make_mesh(
            data=1, spatial=1, pipe=2, devices=jax.devices()[:2]
        )
        with pytest.raises(ValueError, match="disagrees with mesh"):
            PipelinedForward(model, variables, mesh=mesh, segments=4)
        mixed = make_mesh(
            data=2, spatial=1, pipe=2, devices=jax.devices()[:4]
        )
        with pytest.raises(ValueError, match="data/spatial sizes of 1"):
            PipelinedForward(model, variables, mesh=mixed)

    def test_unsplittable_iters_raise_before_compiling(self, raft):
        model, variables = raft
        pf = PipelinedForward(model, variables, segments=2)
        with pytest.raises(ValueError, match="does not split"):
            pf.forward_many(_pairs(1), 5)
        assert pf.cache.stats["compiles"] == 0


# -------------------------------------------------- steady state + seams


class TestSteadyState:
    def test_guard_clean_window_and_executable_reuse(
        self, raft, pf_raft_s2, forbid_host_transfers
    ):
        """Second stream over the same shapes: zero recompiles, zero
        implicit host transfers, cache hits instead of compiles — the
        0/0 steady-state acceptance window."""
        from raft_ncup_tpu.analysis.guards import RecompileWatchdog

        pairs = _pairs(4, seed=5)
        outs = pf_raft_s2.forward_many(pairs, ITERS)  # warm (maybe hit)
        # Pre-warm the scalar-slice sync program outside the window.
        jax.device_get(outs[-1][1][0, 0, 0, 0])
        hits_before = pf_raft_s2.cache.stats["hits"]
        compiles_before = pf_raft_s2.cache.stats["compiles"]
        with RecompileWatchdog() as wd, forbid_host_transfers():
            outs = pf_raft_s2.forward_many(pairs, ITERS)
        jax.device_get(outs[-1][1][0, 0, 0, 0])
        assert wd.count == 0
        assert pf_raft_s2.cache.stats["compiles"] == compiles_before
        assert pf_raft_s2.cache.stats["hits"] > hits_before

    def test_state_donation(self, raft, pf_raft_s2):
        """The tick's stacked-carry operand is donated: after one tick
        the previous state's buffers are gone — steady-state memory is
        ONE stacked carry, not one per tick."""
        enc, tick, model, _pol = pf_raft_s2._programs(
            (1, *HW, 3), ITERS, None
        )
        carry_sds = pf_raft_s2._carry_struct((1, *HW, 3), model)
        state = pf_raft_s2._zero_state(carry_sds)
        fresh = pf_raft_s2._zero_fresh(carry_sds)
        leaf = jax.tree.leaves(state)[0]
        new_state, _lr, _up = tick(
            pf_raft_s2.variables, state, fresh
        )
        jax.block_until_ready(jax.tree.leaves(new_state))
        assert leaf.is_deleted()


# -------------------------------------------- collectives + cost ledger


class TestCollectiveFingerprint:
    def test_tick_hlo_shows_permute_per_seam(self, raft):
        """The compiled tick carries >= S-1 collective-permutes (one
        per carry-handoff seam; in practice one per carry leaf) and the
        by_op breakout reconciles with the aggregate counters."""
        model, variables = raft
        pf = PipelinedForward(model, variables, segments=4)
        cs = collective_stats(pf.tick_hlo((1, *HW, 3), ITERS))
        cp = cs["by_op"]["collective-permute"]
        assert cp["count"] >= pf.segments - 1
        assert cp["bytes"] > 0
        assert cs["collectives"] == sum(
            v["count"] for v in cs["by_op"].values()
        )
        assert cs["collective_bytes"] == sum(
            v["bytes"] for v in cs["by_op"].values()
        )

    def test_tick_text_reads_warmed_executable(self, raft, pf_raft_s2):
        """tick_text: the zero-compile inspection path bench uses —
        None before any stream, the warmed program's HLO after."""
        assert pf_raft_s2.tick_text((1, 64, 64, 3), ITERS) is None
        pf_raft_s2.forward_many(_pairs(2), ITERS)
        hlo = pf_raft_s2.tick_text((1, *HW, 3), ITERS)
        assert hlo is not None
        cs = collective_stats(hlo)
        assert cs["by_op"]["collective-permute"]["count"] >= 1


class TestCostLedger:
    def test_pipe_tick_meta_parse(self):
        meta = ShapeCachedForward._ledger_meta(
            ("custom", "pipe_tick", (1, 32, 32, 3), 8, 4, "f32")
        )
        assert meta == {
            "kind": "pipe_tick", "shape": (1, 32, 32, 3), "iters": 8,
            "segments": 4, "policy": "f32",
        }
        meta = ShapeCachedForward._ledger_meta(
            ("custom", "pipe_encode", (1, 32, 32, 3), "f32")
        )
        assert meta == {
            "kind": "pipe_encode", "shape": (1, 32, 32, 3),
            "policy": "f32",
        }
        # Other custom keys keep the opaque kind.
        assert ShapeCachedForward._ledger_meta(("custom", "stream", 2)) == {
            "kind": "custom"
        }

    def test_per_segment_split_is_derived(self):
        class _Compiled:
            def cost_analysis(self):
                return {"flops": 120.0, "bytes accessed": 44.0}

            def memory_analysis(self):
                raise NotImplementedError

        ledger = CostLedger(enabled=True)
        entry = ledger.record_compiled(
            "k", _Compiled(), backend="cpu", kind="pipe_tick", segments=4
        )
        assert entry["flops_per_segment"] == 30.0
        assert entry["bytes_per_segment"] == 11.0
        # segments=1 (or absent) derives nothing.
        entry = ledger.record_compiled(
            "k2", _Compiled(), backend="cpu", kind="forward"
        )
        assert "flops_per_segment" not in entry

    def test_stream_lands_structured_tick_entry(self, raft, pf_raft_s2):
        """After a real stream the tick executable's ledger entry is
        findable by structured meta — the provenance the bench row and
        flip_recommendations read."""
        pf_raft_s2.forward_many(_pairs(2), ITERS)
        entry = pf_raft_s2.cache.costs.lookup(
            kind="pipe_tick", segments=2
        )
        assert entry is not None
        assert entry["meta"]["iters"] == ITERS
        assert entry["meta"]["shape"] == (1, *HW, 3)
        assert "flops_per_segment" in entry
        assert pf_raft_s2.cache.costs.lookup(kind="pipe_encode") is not None
