"""Streaming video engine: lifecycle, isolation, eviction, chaos.

The robustness matrix docs/STREAMING.md documents, pinned end to end:
bounded stream admission (shed + retry-after), per-stream in-graph
anomaly reset with BITWISE batch-mate isolation, frame-gap staleness,
idle/abandoned eviction with recompile-free slot reuse, graceful
SIGTERM drain, and the sync-free/recompile-free steady state.
"""

import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.config import StreamConfig, small_model_config
from raft_ncup_tpu.models import get_model
from raft_ncup_tpu.resilience.chaos import ChaosSpec
from raft_ncup_tpu.serving.admission import AdmissionQueue
from raft_ncup_tpu.serving.request import (
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHED,
    TERMINAL_STATUSES,
)
from raft_ncup_tpu.streaming import (
    SlotRegistry,
    StreamEngine,
    StreamTraffic,
    replay_streams,
)

HW = (24, 32)  # h8=3, w8=4: tiny slot table, fast compiles


# ------------------------------------------------------------- test rigs


class _DummyVideoModel:
    """apply()-compatible stand-in whose flow depends on flow_init, so
    warm vs cold starts are observable without a RAFT compile. Rows are
    batch-independent (pure elementwise math), like the real model in
    test mode."""

    cfg = SimpleNamespace(hidden_dim=4)

    def apply(self, variables, image1, image2, iters=1, flow_init=None,
              test_mode=True, return_net=False, net_init=None,
              net_warm=None, **kw):
        B, H, W, _ = image1.shape
        h8, w8 = H // 8, W // 8
        lr = image1[:, ::8, ::8, :2] * 0.01
        if flow_init is not None:
            lr = lr + flow_init
        up = jnp.repeat(jnp.repeat(lr, 8, axis=1), 8, axis=2)
        net = jnp.full((B, h8, w8, 4), 0.5, jnp.float32)
        if net_init is not None:
            net = jnp.where(net_warm[:, None, None, None], net_init + 1.0,
                            net)
        if return_net:
            return lr, up, net
        return lr, up


def _img(seed=0, hw=HW):
    g = np.random.default_rng(seed)
    return (g.random((*hw, 3)) * 255.0).astype(np.float32)


def _scfg(**kw):
    base = dict(
        capacity=3,
        frame_hw=HW,
        iters=1,
        batch_sizes=(1, 2, 4),
        queue_capacity=8,
        idle_timeout_s=100.0,
    )
    base.update(kw)
    return StreamConfig(**base)


class FakeClock:
    def __init__(self, t0=1000.0):
        self.t = t0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _engine(**kw):
    clock = kw.pop("clock", time.monotonic)
    return StreamEngine(_DummyVideoModel(), {}, _scfg(**kw), clock=clock)


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise TimeoutError(f"timed out waiting for {msg}")


# ----------------------------------------------------------- SlotRegistry


class TestSlotRegistry:
    def test_lowest_free_slot_first_and_deterministic_reuse(self):
        reg = SlotRegistry(3)
        assert reg.admit("a", HW, 0.0).slot == 0
        assert reg.admit("b", HW, 0.0).slot == 1
        assert reg.admit("c", HW, 0.0).slot == 2
        assert reg.admit("d", HW, 0.0) is None  # full
        assert reg.release("b") == 1
        assert reg.admit("e", HW, 1.0).slot == 1  # lowest freed slot
        assert reg.peak_occupancy == 3

    def test_evict_expired_skips_pending_and_orders_by_idle(self):
        reg = SlotRegistry(3)
        a = reg.admit("a", HW, 0.0)
        b = reg.admit("b", HW, 5.0)
        c = reg.admit("c", HW, 1.0)
        b.pending = 1  # in flight: not evictable
        evicted = reg.evict_expired(now=200.0, idle_timeout_s=100.0)
        assert [s.stream_id for s in evicted] == ["a", "c"]  # oldest first
        assert reg.get("b") is not None
        assert reg.evicted_total == 2

    def test_soonest_expiry_hint(self):
        reg = SlotRegistry(2)
        reg.admit("a", HW, 0.0)
        reg.admit("b", HW, 40.0)
        assert reg.soonest_expiry_s(now=50.0, idle_timeout_s=100.0) == 50.0
        assert reg.soonest_expiry_s(now=150.0, idle_timeout_s=100.0) == 0.0


# ----------------------------------------- AdmissionQueue distinct popping


class TestPopBatchDistinct:
    def _req(self, rid, stream, key="a"):
        return SimpleNamespace(request_id=rid, stream=stream, shape_key=key)

    def test_skips_same_stream_in_place(self):
        q = AdmissionQueue(capacity=8)
        for rid, s in enumerate(["A", "A", "B", "C"]):
            q.offer(self._req(rid, s))
        batch = q.pop_batch(
            4, key_fn=lambda r: r.shape_key,
            distinct_fn=lambda r: r.stream,
        )
        assert [(r.request_id, r.stream) for r in batch] == [
            (0, "A"), (2, "B"), (3, "C"),
        ]
        # The duplicate kept its position (and per-stream FIFO order).
        rest = q.pop_batch(4, key_fn=lambda r: r.shape_key,
                           distinct_fn=lambda r: r.stream)
        assert [(r.request_id, r.stream) for r in rest] == [(1, "A")]

    def test_stops_at_different_key(self):
        q = AdmissionQueue(capacity=8)
        q.offer(self._req(0, "A", key="x"))
        q.offer(self._req(1, "B", key="y"))
        q.offer(self._req(2, "C", key="x"))
        batch = q.pop_batch(
            4, key_fn=lambda r: r.shape_key,
            distinct_fn=lambda r: r.stream,
        )
        # Never reorders across shape keys: C stays behind B.
        assert [r.request_id for r in batch] == [0]
        assert len(q) == 2

    def test_respects_max_n(self):
        q = AdmissionQueue(capacity=8)
        for rid, s in enumerate(["A", "B", "C"]):
            q.offer(self._req(rid, s))
        batch = q.pop_batch(2, key_fn=lambda r: r.shape_key,
                            distinct_fn=lambda r: r.stream)
        assert [r.request_id for r in batch] == [0, 1]


# ------------------------------------------------------- chaos + traffic


class TestStreamChaos:
    def test_spec_round_trip_with_stream_kinds(self):
        spec = ChaosSpec.parse("corruptframe@3,abandon@7,burst@2,sigterm@9")
        assert spec.corrupt_frames == frozenset({3})
        assert spec.abandon_frames == frozenset({7})
        assert spec.burst_requests == frozenset({2})
        assert spec.sigterm_after == 9
        assert spec.active
        assert ChaosSpec.parse(spec.render()) == spec

    def test_bad_kind_rejected(self):
        with pytest.raises(ValueError):
            ChaosSpec.parse("corruptedframe@3")

    def test_corruptframe_is_nan_and_only_that_frame(self):
        chaos = ChaosSpec.parse("corruptframe@4")
        frames = list(StreamTraffic(HW, 3, 2, seed=1, chaos=chaos))
        # Emission order: (f0: s0 s1 s2), (f1: s0 s1 s2) -> index 4 is
        # stream-1's frame 1.
        assert frames[4][1] == "stream-1" and frames[4][2] == 1
        assert np.isnan(frames[4][3]).all()
        assert not any(
            np.isnan(f[3]).any() for i, f in enumerate(frames) if i != 4
        )

    def test_abandon_truncates_stream(self):
        chaos = ChaosSpec.parse("abandon@1")  # stream-1's frame 0
        frames = list(StreamTraffic(HW, 3, 3, seed=1, chaos=chaos))
        by_stream = {}
        for _, sid, f, _, _ in frames:
            by_stream.setdefault(sid, []).append(f)
        assert by_stream["stream-1"] == [0]  # nothing after the abandon
        assert by_stream["stream-0"] == [0, 1, 2]
        assert by_stream["stream-2"] == [0, 1, 2]

    def test_burst_adds_one_frame_streams(self):
        chaos = ChaosSpec.parse("burst@2")
        frames = list(
            StreamTraffic(HW, 2, 2, seed=1, chaos=chaos, burst_size=3)
        )
        burst = [f for f in frames if f[1].startswith("burst-")]
        assert len(burst) == 3
        steady = [f for f in frames if not f[1].startswith("burst-")]
        assert len(steady) == 4

    def test_schedule_is_deterministic(self):
        a = list(StreamTraffic(HW, 2, 3, seed=9))
        b = list(StreamTraffic(HW, 2, 3, seed=9))
        for (da, sa, fa, i1a, i2a), (db, sb, fb, i1b, i2b) in zip(a, b):
            assert (da, sa, fa) == (db, sb, fb)
            np.testing.assert_array_equal(i1a, i1b)
            np.testing.assert_array_equal(i2a, i2b)


# ------------------------------------------------------ engine lifecycle


class TestEngineLifecycle:
    def test_frames_complete_with_native_unpad_and_auto_index(self):
        eng = _engine()
        try:
            rs = []
            for f in range(3):  # auto frame indices
                rs.append(eng.submit("s0", _img(f), _img(f + 10)).result(30))
            assert [r.status for r in rs] == [STATUS_OK] * 3
            assert rs[0].flow.shape == (*HW, 2)
            assert rs[0].iters == 1
            with eng._reg_lock:
                state = eng.registry.get("s0")
                assert state.last_frame_index == 2
                assert state.frames_completed == 3
        finally:
            eng.drain()
        assert eng.stats.cold_starts == 1  # only the first frame

    def test_warm_start_changes_output_and_gap_forces_cold(self):
        """Frame 2 warm-started differs from the same images computed
        cold; a frame-index gap beyond max_frame_gap forces the cold
        result bitwise (never a stale warm start)."""
        img1, img2 = _img(100), _img(101)

        def run(indices):
            eng = _engine(capacity=1, batch_sizes=(1,))
            try:
                out = [
                    eng.submit("s", img1, img2, frame_index=i).result(30)
                    for i in indices
                ]
            finally:
                eng.drain()
            return out

        warm = run([0, 1])  # consecutive: frame 1 warm-starts
        gap = run([0, 5])  # gap > max_frame_gap: frame 5 forced cold
        cold = run([0])  # reference cold result for these images
        assert warm[1].ok and gap[1].ok
        assert not np.array_equal(
            np.asarray(warm[1].flow), np.asarray(cold[0].flow)
        )
        np.testing.assert_array_equal(
            np.asarray(gap[1].flow), np.asarray(cold[0].flow)
        )

    def test_stream_admission_sheds_at_capacity_with_retry_hint(self):
        clock = FakeClock()
        eng = _engine(capacity=2, clock=clock, idle_timeout_s=50.0)
        try:
            assert eng.submit("a", _img(1), _img(2)).result(30).ok
            assert eng.submit("b", _img(3), _img(4)).result(30).ok
            r = eng.submit("c", _img(5), _img(6)).result(30)
            assert r.status == STATUS_SHED
            assert r.detail == "stream table full"
            assert r.retry_after_s == 50.0  # soonest idle expiry
            assert eng.stats.shed_streams == 1
        finally:
            eng.drain()

    def test_frame_queue_full_sheds(self):
        eng = _engine(capacity=4, queue_capacity=2)
        try:
            eng.pause()
            h1 = eng.submit("a", _img(1), _img(2))
            h2 = eng.submit("b", _img(3), _img(4))
            h3 = eng.submit("c", _img(5), _img(6))
            r3 = h3.result(1)
            assert r3.status == STATUS_SHED
            assert r3.detail == "frame queue full"
            eng.resume()
            assert h1.result(30).ok and h2.result(30).ok
        finally:
            eng.drain()

    def test_malformed_and_out_of_order_rejected(self):
        eng = _engine()
        try:
            r = eng.submit("a", _img(1)[:, :, :2], _img(2)).result(1)
            assert r.status == STATUS_REJECTED and "(H, W, 3)" in r.detail
            # Wrong padded shape for this engine's slot table:
            big = _img(1, (64, 64))
            r = eng.submit("a", big, big).result(1)
            assert r.status == STATUS_REJECTED and "slot table" in r.detail
            assert eng.submit("a", _img(1), _img(2),
                              frame_index=5).result(30).ok
            r = eng.submit("a", _img(3), _img(4), frame_index=5).result(1)
            assert r.status == STATUS_REJECTED
            assert "out-of-order" in r.detail
            # A shed/rejected frame must not have advanced the index.
            with eng._reg_lock:
                assert eng.registry.get("a").last_frame_index == 5
        finally:
            eng.drain()

    def test_mid_stream_resolution_change_rejected(self):
        eng = _engine(pad_bucket=32, frame_hw=(24, 32))
        try:
            assert eng.submit("a", _img(1), _img(2)).result(30).ok
            # (26, 30) pads into the same 32x32-bucketed table but the
            # stream was opened at (24, 32): per-stream shape is fixed.
            other = _img(3, (26, 30))
            r = eng.submit("a", other, other).result(1)
            assert r.status == STATUS_REJECTED and "stream 'a' is" in r.detail
        finally:
            eng.drain()

    def test_close_stream_frees_slot_for_reuse(self):
        eng = _engine(capacity=1)
        try:
            assert eng.submit("a", _img(1), _img(2)).result(30).ok
            assert eng.close_stream("a")
            assert not eng.close_stream("nope")
            # Slot freed: a new stream admits immediately.
            assert eng.submit("b", _img(3), _img(4)).result(30).ok
            assert eng.stats.streams_closed == 1
        finally:
            eng.drain()

    def test_idle_eviction_frees_slot_and_reuse_has_no_recompile(self):
        """The abandoned-stream path: after idle_timeout the slot frees
        (dispatcher idle tick), a new stream reuses it, and NOTHING
        recompiles — the executable set was fixed at warmup."""
        clock = FakeClock()
        eng = _engine(capacity=1, clock=clock, idle_timeout_s=10.0)
        try:
            eng.warmup()
            compiles = eng._fwd.stats["compiles"]
            assert eng.submit("a", _img(1), _img(2)).result(30).ok
            clock.advance(11.0)
            _wait(
                lambda: eng.registry.occupancy == 0,
                msg="idle eviction by dispatcher tick",
            )
            r = eng.submit("b", _img(3), _img(4)).result(30)
            assert r.ok
            assert eng.stats.streams_evicted == 1
            assert eng._fwd.stats["compiles"] == compiles  # no recompile
            rep = eng.report()
            assert rep["evicted"] == 1 and rep["capacity"] == 1
        finally:
            eng.drain()

    def test_eviction_never_takes_streams_with_frames_in_flight(self):
        clock = FakeClock()
        eng = _engine(capacity=1, clock=clock, idle_timeout_s=10.0)
        try:
            eng.pause()  # keep the frame pending
            h = eng.submit("a", _img(1), _img(2))
            clock.advance(100.0)
            time.sleep(0.2)  # several dispatcher idle ticks
            with eng._reg_lock:
                assert eng.registry.get("a") is not None
            eng.resume()
            assert h.result(30).ok
        finally:
            eng.drain()

    def test_drain_flushes_admitted_sheds_new_and_is_idempotent(self):
        eng = _engine()
        eng.pause()
        hs = [
            eng.submit(f"s{i}", _img(i), _img(i + 10)) for i in range(3)
        ]
        eng.resume()
        stats = eng.drain()
        assert [h.result(1).status for h in hs] == [STATUS_OK] * 3
        late = eng.submit("s9", _img(9), _img(19)).result(1)
        assert late.status == STATUS_SHED and late.detail == "draining"
        assert eng.drain() is stats  # idempotent
        # No silent drops: every submission reached a terminal status.
        assert stats.completed == 3
        assert stats.submitted == 4

    def test_burst_of_streams_sheds_explicitly(self):
        chaos = ChaosSpec.parse("burst@1")  # after both steady admits
        eng = _engine(capacity=2)
        try:
            traffic = StreamTraffic(
                HW, 2, 2, seed=3, chaos=chaos, burst_size=4
            )
            handles, _ = replay_streams(eng, traffic)
            rs = [h.result(30) for h in handles]
        finally:
            eng.drain()
        assert all(r.status in TERMINAL_STATUSES for r in rs)
        # 2 steady streams fill the table; all 4 burst streams shed.
        shed = [r for r in rs if r.status == STATUS_SHED]
        assert len(shed) == 4
        assert all(r.retry_after_s is not None for r in shed)
        ok = [r for r in rs if r.ok]
        assert len(ok) == 4  # both steady streams' frames all served


# ------------------------------------------------- carry_net (GRU state)


class TestCarryNet:
    def test_net_carried_only_when_enabled_and_warm(self):
        img1, img2 = _img(200), _img(201)

        def second_frame(carry_net):
            eng = _engine(capacity=1, batch_sizes=(1,),
                          carry_net=carry_net)
            try:
                eng.submit("s", img1, img2).result(30)
                return eng.submit("s", img1, img2).result(30)
            finally:
                eng.drain()

        with_net = second_frame(True)
        without = second_frame(False)
        assert with_net.ok and without.ok
        # The dummy model folds net_init into nothing visible in flow,
        # so compare table state instead: carry_net allocates the net
        # plane and stores non-zero state after a good frame.
        eng = _engine(capacity=1, batch_sizes=(1,), carry_net=True)
        try:
            assert "net" in eng._table
            eng.submit("s", img1, img2).result(30)
            _wait(lambda: not eng._handles, msg="delivery")
            net = np.asarray(jax.device_get(eng._table["net"]))
            assert np.any(net[0] != 0)
        finally:
            eng.drain()
        eng2 = _engine(capacity=1, batch_sizes=(1,), carry_net=False)
        try:
            assert "net" not in eng2._table
        finally:
            eng2.drain()


# ------------------------------------------- real model: isolation matrix


@pytest.fixture(scope="module")
def tiny_model():
    cfg = small_model_config("raft", dataset="chairs")
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, *HW, 3))
    return model, variables


def _run_rounds(model, variables, frames, *, corrupt=None, skip=None,
                scfg=None):
    """Drive the engine in deterministic rounds (pause → one frame per
    stream → resume), returning {(stream, frame): response}.

    ``corrupt``: (stream_id, frame) whose first image is NaN.
    ``skip``: (stream_id, first_frame) — the stream only joins at
    first_frame (the same-batch-composition cold reference).
    """
    eng = StreamEngine(model, variables, scfg or _scfg(capacity=4))
    out = {}
    try:
        eng.warmup()
        n_frames = max(f for (_, f) in frames) + 1
        streams = sorted({s for (s, _) in frames})
        for f in range(n_frames):
            eng.pause()
            hs = []
            for sid in streams:
                if (sid, f) not in frames:
                    continue
                if skip and sid == skip[0] and f < skip[1]:
                    continue
                i1, i2 = frames[(sid, f)]
                if corrupt and (sid, f) == corrupt:
                    i1 = np.full(i1.shape, np.nan, np.float32)
                hs.append(((sid, f), eng.submit(sid, i1, i2,
                                                frame_index=f)))
            eng.resume()
            for k, h in hs:
                out[k] = h.result(120)
        stats = eng.stats
    finally:
        eng.drain()
    return out, stats


@pytest.mark.slow
class TestIsolationRealModel:
    N_STREAMS, N_FRAMES = 3, 3
    CORRUPT = ("stream-1", 1)

    def _frames(self):
        frames = {}
        for _, sid, f, i1, i2 in StreamTraffic(
            HW, self.N_STREAMS, self.N_FRAMES, seed=5
        ):
            frames[(sid, f)] = (i1, i2)
        return frames

    def test_corruptframe_isolation_bitwise(self, tiny_model):
        """The acceptance pin: under corruptframe chaos the corrupted
        stream resets to cold start while every co-batched stream's
        output flow is BITWISE identical to an uninjected run, in the
        corrupted frame's batch and every batch after it."""
        model, variables = tiny_model
        frames = self._frames()
        base, _ = _run_rounds(model, variables, frames)
        cha, stats = _run_rounds(
            model, variables, frames, corrupt=self.CORRUPT
        )

        # 1) batch-mates bitwise identical, every frame.
        for (sid, f), r in base.items():
            if sid == self.CORRUPT[0]:
                continue
            rc = cha[(sid, f)]
            assert r.ok and rc.ok
            np.testing.assert_array_equal(
                np.asarray(r.flow), np.asarray(rc.flow),
                err_msg=f"batch-mate {sid} frame {f} diverged",
            )

        # 2) the corrupted frame answers `rejected` with the anomaly
        #    detail; nothing was silently dropped.
        bad = cha[self.CORRUPT]
        assert bad.status == STATUS_REJECTED
        assert "anomaly" in bad.detail
        assert stats.resets == 1

        # 3) the corrupted stream's NEXT frame is bitwise a cold start:
        #    same batch composition, stream joining cold at that frame.
        ref, _ = _run_rounds(
            model, variables, frames,
            skip=(self.CORRUPT[0], self.CORRUPT[1] + 1),
        )
        for f in range(self.CORRUPT[1] + 1, self.N_FRAMES):
            np.testing.assert_array_equal(
                np.asarray(cha[(self.CORRUPT[0], f)].flow),
                np.asarray(ref[(self.CORRUPT[0], f)].flow),
                err_msg=f"post-reset frame {f} is not a cold start",
            )

    def test_steady_state_sync_free_recompile_free(
        self, tiny_model, forbid_host_transfers, max_recompiles
    ):
        """The invariant the bench row records: after warmup, a steady
        multi-stream window performs ZERO implicit host pulls and ZERO
        compiles; each batch's flow+flags pull is the one sanctioned
        explicit device_get in the AsyncDrain worker. Warm-starting,
        cold-starting, and slot scatter all ride the same programs."""
        model, variables = tiny_model
        eng = StreamEngine(model, variables, _scfg(capacity=2))
        try:
            eng.warmup()
            # Warm the pipeline (first frames of both streams).
            eng.pause()
            hs = [eng.submit(s, _img(7), _img(8)) for s in ("a", "b")]
            eng.resume()
            assert all(h.result(120).ok for h in hs)
            with forbid_host_transfers() as stats, max_recompiles(0):
                for _ in range(2):
                    eng.pause()
                    hs = [
                        eng.submit(s, _img(9), _img(10))
                        for s in ("a", "b")
                    ]
                    eng.resume()
                    rs = [h.result(120) for h in hs]
                    assert [r.status for r in rs] == [STATUS_OK] * 2
        finally:
            eng.drain()
        assert stats.host_transfers == 0
        assert stats.sanctioned_gets == 2  # one per dispatched batch

    def test_sigterm_mid_window_drains_all_admitted(self, tiny_model):
        """The drain contract under a REAL signal through the real
        handler: submission stops, every admitted frame is flushed
        through compute, nothing is silently dropped."""
        from raft_ncup_tpu.resilience import PreemptionHandler

        model, variables = tiny_model
        eng = StreamEngine(model, variables, _scfg(capacity=4))
        try:
            eng.warmup()
            traffic = StreamTraffic(HW, 2, 4, seed=6)
            with PreemptionHandler() as preempt:
                handles, interrupted = replay_streams(
                    eng, traffic, preempt=preempt, sigterm_after=3
                )
            stats = eng.drain()
        finally:
            eng.drain()
        assert interrupted
        assert len(handles) == 3  # stopped right after the signal
        rs = [h.result(30) for h in handles]
        assert [r.status for r in rs] == [STATUS_OK] * 3
        assert stats.completed == 3
        assert stats.errors == 0


class TestUhdAdmissibility:
    """4K (2176x3840) is a valid engine shape (docs/PERF.md "Banded
    dispatch"): the config validates, the slot table allocates, warmup
    compiles the executable set, and a re-warm is ALL LRU hits — no
    recompile on reuse. The dummy model sidesteps a RAFT compile, but
    warmup still EXECUTES the in-graph warm-start splat at 272x480
    slot resolution — real minutes-scale CPU work, hence the slow
    marker on the warmup test; the real-model 4K evidence is
    scripts/highres_forward.py + the residency pins in
    tests/test_pallas_lowering.py."""

    def test_4k_stream_config_is_admissible(self):
        cfg = _scfg(frame_hw=(2176, 3840), capacity=1, batch_sizes=(1,))
        assert cfg.frame_hw == (2176, 3840)
        # /8-clean: the padded slot-table shape IS the native shape.
        assert cfg.frame_hw[0] % 8 == 0 and cfg.frame_hw[1] % 8 == 0

    @pytest.mark.slow
    def test_4k_engine_warms_without_recompile_on_reuse(self):
        eng = _engine(frame_hw=(2176, 3840), capacity=1,
                      batch_sizes=(1,), queue_capacity=2)
        try:
            compiled = eng.warmup()
            assert compiled >= 1
            assert (2176, 3840, 1, eng.cfg.iters) in [
                (h, w, b, i) for (h, w, b, i) in eng.warmed
            ]
            before = dict(eng._fwd.stats)
            assert eng.warmup() == 0  # re-warm: pure LRU hits
            after = eng._fwd.stats
            assert after["compiles"] == before["compiles"]
            assert after["evictions"] == before["evictions"]
        finally:
            eng.drain(timeout=120.0)
