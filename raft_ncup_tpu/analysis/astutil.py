"""Shared AST plumbing for graftlint (pure stdlib — no jax import).

The analyses here are deliberately *syntactic*: graftlint runs in CI and
pre-commit where importing jax (and initializing a backend) is both slow
and, on a wedged accelerator tunnel, a hang risk (bench.py's probe exists
for exactly that failure mode). Everything a rule needs — import aliases,
dotted-name resolution, and the traced-region index — is derived from the
AST alone.

Traced-region detection is the load-bearing piece. A function is
considered *traced* (its body executes under jax tracing, where host
syncs, nondeterminism and Python control flow on tracers are bugs) when:

1. it is decorated with a jax transform (``@jax.jit``, ``@partial(jax.jit,
   ...)``, ``@jax.checkpoint``, ...);
2. it is passed by name (or as a lambda) to a transform call —
   ``jax.jit(f)``, ``jax.lax.scan(body, ...)``, ``shard_map(local, ...)``
   — including through simple assignment chains
   (``body = jax.checkpoint(step); jax.lax.scan(body, ...)``);
3. it is defined inside a traced function; or
4. it is called by name from a traced function in the same module
   (transitive closure).

This is a per-module approximation: calls that cross module boundaries
through attributes (``model.apply``) are not followed. That boundary is
documented in docs/ANALYSIS.md — the rules stay high-precision inside it
and the allowlist absorbs the rest.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator, Optional

_PARENT = "_graftlint_parent"

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# Fully-qualified callables whose function-valued arguments are traced.
TRACE_WRAPPERS = frozenset(
    {
        "jax.jit",
        "jax.pjit",
        "jax.experimental.pjit.pjit",
        "jax.vmap",
        "jax.pmap",
        "jax.grad",
        "jax.value_and_grad",
        "jax.jacfwd",
        "jax.jacrev",
        "jax.hessian",
        "jax.checkpoint",
        "jax.remat",
        "jax.ad_checkpoint.checkpoint",
        "jax.custom_jvp",
        "jax.custom_vjp",
        "jax.named_call",
        "jax.shard_map",
        "jax.experimental.shard_map.shard_map",
        "jax.lax.scan",
        "jax.lax.while_loop",
        "jax.lax.fori_loop",
        "jax.lax.cond",
        "jax.lax.switch",
        "jax.lax.map",
        "jax.lax.associative_scan",
        "jax.lax.custom_root",
    }
)

# Last-segment fallbacks: catches local rebinds like the repo's
# ``_shard_map = jax.shard_map`` compat alias, ``from jax import jit``,
# and ``self.jit_fn``-style wrappers. Conservative in the traced
# direction: a stray user function named ``scan`` marks its callees
# traced, which at worst produces an allowlistable finding, never a miss.
TRACE_WRAPPER_TAILS = frozenset(
    {
        "jit",
        "pjit",
        "vmap",
        "pmap",
        "grad",
        "value_and_grad",
        "checkpoint",
        "remat",
        "scan",
        "while_loop",
        "fori_loop",
        "cond",
        "switch",
        "shard_map",
    }
)

# Roots that can never be jax transforms even when a tail matches.
_NON_JAX_ROOTS = frozenset(
    {
        "numpy",
        "scipy",
        "torch",
        "tensorflow",
        "tf",
        "pandas",
        "itertools",
        "functools",
        "os",
        "re",
        "cv2",
    }
)


@dataclass(frozen=True)
class Finding:
    """One lint finding, addressable by the allowlist as
    ``path::rule::qualname``."""

    path: str
    line: int
    col: int
    rule: str
    message: str
    qualname: str = "<module>"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{self.qualname}] {self.message}"
        )


def attach_parents(tree: ast.AST) -> None:
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            setattr(child, _PARENT, parent)


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, _PARENT, None)


def enclosing_functions(node: ast.AST) -> Iterator[ast.AST]:
    """All function nodes containing ``node``, innermost first."""
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, FUNC_NODES):
            yield cur
        cur = parent(cur)


def qualname(node: ast.AST) -> str:
    """Dotted enclosing-function path, e.g. ``make_train_step.step``;
    ``<module>`` at top level."""
    names = []
    cur = node if isinstance(node, FUNC_NODES) else None
    if cur is None:
        for fn in enclosing_functions(node):
            cur = fn
            break
    while cur is not None:
        names.append(getattr(cur, "name", "<lambda>"))
        cur = next(enclosing_functions(cur), None)
    return ".".join(reversed(names)) if names else "<module>"


def collect_aliases(tree: ast.AST) -> dict:
    """Map local names to fully-qualified import paths.

    ``import jax.numpy as jnp`` -> ``{'jnp': 'jax.numpy'}``;
    ``from jax.sharding import PartitionSpec as P`` ->
    ``{'P': 'jax.sharding.PartitionSpec'}``; plain ``import numpy``
    binds the top-level name to itself.
    """
    aliases: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    top = a.name.split(".")[0]
                    aliases[top] = top
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST, aliases: dict) -> Optional[str]:
    """Resolve ``Name``/``Attribute`` chains to a dotted string with the
    leading segment expanded through import aliases; None for anything
    dynamic (subscripts, calls)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def is_trace_wrapper(func_node: ast.AST, aliases: dict) -> bool:
    dn = dotted_name(func_node, aliases)
    if dn is None:
        return False
    if dn in TRACE_WRAPPERS:
        return True
    tail = dn.split(".")[-1].lstrip("_")
    if tail not in TRACE_WRAPPER_TAILS:
        return False
    # Tail matches: accept unless rooted in a module known to be non-jax
    # (``scipy.signal.cond`` stays out; ``self._jit``, ``_shard_map`` and
    # jax-rooted paths are in — missing a wrapper silently un-traces a
    # region, so the bias is toward marking).
    root = dn.split(".")[0].lstrip("_")
    return root not in _NON_JAX_ROOTS


@dataclass
class TracedIndex:
    """Per-module index of function nodes whose bodies run under jax
    tracing (see module docstring for the marking rules)."""

    tree: ast.AST
    aliases: dict
    traced: set = field(default_factory=set)
    _defs_by_name: dict = field(default_factory=dict)
    _assigns: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs_by_name.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self._assigns.setdefault(tgt.id, []).append(node.value)
        self._seed()
        self._propagate()

    # ------------------------------------------------------------- marking

    def _visible_from(self, def_node: ast.AST, at: Optional[ast.AST]) -> bool:
        """Scope filter for by-name resolution: a def is visible from
        ``at`` when it lives at module level or inside one of ``at``'s
        enclosing functions. Without this, same-named inner functions in
        sibling factories (``make_train_step.step`` vs
        ``make_eval_step.step``) cross-contaminate."""
        owner = next(enclosing_functions(def_node), None)
        if owner is None:
            return True  # module-level defs are visible everywhere
        if at is None:
            return False  # module-level reference cannot see nested defs
        return owner is at or owner in set(enclosing_functions(at))

    def _resolve_funcarg(
        self,
        node: ast.AST,
        at: Optional[ast.AST] = None,
        seen: Optional[set] = None,
    ):
        """Function nodes a call argument may refer to (by-name defs,
        lambdas, and simple assignment chains), restricted to defs
        visible from the reference node ``at``."""
        seen = seen if seen is not None else set()
        if isinstance(node, ast.Lambda):
            yield node
            return
        if isinstance(node, ast.Call) and is_trace_wrapper(
            node.func, self.aliases
        ):
            # body = jax.checkpoint(step): the inner name is the function.
            for arg in node.args:
                yield from self._resolve_funcarg(arg, at, seen)
            return
        if not isinstance(node, ast.Name) or node.id in seen:
            return
        seen.add(node.id)
        for d in self._defs_by_name.get(node.id, []):
            if self._visible_from(d, at):
                yield d
        for value in self._assigns.get(node.id, []):
            yield from self._resolve_funcarg(value, at, seen)

    def _seed(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    target = deco.func if isinstance(deco, ast.Call) else deco
                    dn = dotted_name(target, self.aliases)
                    if dn == "functools.partial" and isinstance(deco, ast.Call):
                        target = deco.args[0] if deco.args else target
                    if is_trace_wrapper(target, self.aliases):
                        self.traced.add(node)
            elif isinstance(node, ast.Call) and is_trace_wrapper(
                node.func, self.aliases
            ):
                at = next(enclosing_functions(node), None)
                for arg in node.args:
                    for fn in self._resolve_funcarg(arg, at):
                        self.traced.add(fn)

    def _propagate(self) -> None:
        changed = True
        while changed:
            changed = False
            for fn in list(self.traced):
                for node in ast.walk(fn):
                    if (
                        isinstance(node, FUNC_NODES)
                        and node is not fn
                        and node not in self.traced
                    ):
                        self.traced.add(node)
                        changed = True
                    elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name
                    ):
                        at = next(enclosing_functions(node), None)
                        for cal in self._defs_by_name.get(node.func.id, []):
                            if cal not in self.traced and self._visible_from(
                                cal, at
                            ):
                                self.traced.add(cal)
                                changed = True

    # -------------------------------------------------------------- queries

    def is_traced(self, node: ast.AST) -> bool:
        """True when ``node`` executes inside any traced function."""
        if isinstance(node, FUNC_NODES) and node in self.traced:
            return True
        return any(fn in self.traced for fn in enclosing_functions(node))


@dataclass
class ModuleContext:
    """Everything a rule sees for one linted file."""

    path: str  # display path (as passed/discovered, posix separators)
    tree: ast.AST
    aliases: dict
    traced: TracedIndex
    declared_axes: frozenset  # mesh axis names visible to this lint run

    @classmethod
    def build(
        cls, path: str, source: str, declared_axes: frozenset
    ) -> "ModuleContext":
        tree = ast.parse(source, filename=path)
        attach_parents(tree)
        aliases = collect_aliases(tree)
        return cls(
            path=path,
            tree=tree,
            aliases=aliases,
            traced=TracedIndex(tree, aliases),
            declared_axes=declared_axes,
        )
