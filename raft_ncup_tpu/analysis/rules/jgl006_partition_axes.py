"""JGL006 — PartitionSpec axis names not declared by the mesh.

Every ``PartitionSpec`` axis string must name an axis of the mesh built in
``parallel/mesh.py`` (currently ``data``/``spatial``). A typo'd axis name
does not fail loudly: GSPMD treats the spec as unconstrained, silently
replicating the array — correctness survives, but the memory/perf plan
the spec encoded evaporates (a 1080p corr-volume "sharded" over a
misspelled axis OOMs a chip instead of erroring).

Declared axes are discovered from the lint run itself: any linted module
constructing ``jax.sharding.Mesh`` with literal axis names contributes
its names (engine-side; see ``lint.discover_declared_axes``). When the
linted set declares nothing, the engine falls back to the production
declarer ``parallel/mesh.py`` (``lint.production_declared_axes``) —
standalone lints of ``inference/``, ``serving/``, or ``streaming/``
must still judge new PartitionSpecs against the real mesh axes, not go
silent. Only when no declaration exists anywhere (callers passing an
explicit empty ``declared_axes``, partial checkouts without mesh.py)
does the rule stay silent rather than guess.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    qualname,
)

RULE_ID = "JGL006"
SUMMARY = "PartitionSpec axis name not declared by parallel/mesh.py"


def _is_pspec(func_node: ast.AST, aliases: dict) -> bool:
    dn = dotted_name(func_node, aliases)
    return dn is not None and dn.split(".")[-1] == "PartitionSpec"


def _literal_axes(call: ast.Call) -> Iterator[str]:
    """String-literal axis names in a PartitionSpec call (including tuple
    entries: ``P(('data', 'spatial'), None)``). Non-literals (variables)
    are runtime-determined and skipped."""
    for arg in call.args:
        elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                yield e.value


def check(ctx: ModuleContext) -> Iterator[Finding]:
    if not ctx.declared_axes:
        return  # no mesh declaration in scope — cannot judge names
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not _is_pspec(
            node.func, ctx.aliases
        ):
            continue
        for axis in _literal_axes(node):
            if axis not in ctx.declared_axes:
                yield Finding(
                    ctx.path,
                    node.lineno,
                    node.col_offset,
                    RULE_ID,
                    f"PartitionSpec axis {axis!r} is not a declared mesh "
                    f"axis ({sorted(ctx.declared_axes)}); GSPMD silently "
                    "replicates over unknown axes",
                    qualname(node),
                )
