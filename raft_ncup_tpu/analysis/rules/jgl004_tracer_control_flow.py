"""JGL004 — Python control flow on traced values.

``if``/``while`` on a value computed by jax ops inside a traced function
either raises TracerBoolConversionError at trace time or — when the value
is concrete because someone already synced it — hides a per-step host
round-trip behind an innocent-looking branch. Data-dependent control flow
in traced code must go through ``jax.lax.cond``/``jax.lax.while_loop``
(or ``jnp.where`` for selects).

Precision note: the rule only fires when the branch test *syntactically
contains* a jax/jnp call or an array-reduction method call
(``.any()``/``.all()``/...), so config flags and static-shape branches
(``if cfg.add_noise:``, ``if H % 8:``) never trigger it.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    qualname,
)

RULE_ID = "JGL004"
SUMMARY = "Python if/while on a traced (jax-computed) value"

_REDUCTION_METHODS = frozenset({"any", "all", "sum", "max", "min", "mean"})
# jax helpers that RETURN static python values — tests on these are fine.
_STATIC_JAX_CALLS = frozenset(
    {
        "jax.process_index",
        "jax.process_count",
        "jax.device_count",
        "jax.local_device_count",
        "jax.devices",
        "jax.local_devices",
        "jax.default_backend",
    }
)


def _array_call_in(test: ast.AST, aliases: dict) -> Optional[str]:
    """A jax-call (or reduction-method) subexpression of the branch test,
    rendered for the message; None when the test looks static."""
    for sub in ast.walk(test):
        if not isinstance(sub, ast.Call):
            continue
        dn = dotted_name(sub.func, aliases)
        if dn is not None and dn.split(".")[0] == "jax":
            if dn in _STATIC_JAX_CALLS:
                continue
            return dn
        if (
            isinstance(sub.func, ast.Attribute)
            and sub.func.attr in _REDUCTION_METHODS
            and not sub.args
            and not sub.keywords
        ):
            return f".{sub.func.attr}()"
    return None


def check(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
            continue
        if not ctx.traced.is_traced(node):
            continue
        culprit = _array_call_in(node.test, ctx.aliases)
        if culprit is None:
            continue
        kind = {ast.If: "if", ast.While: "while", ast.IfExp: "conditional"}[
            type(node)
        ]
        yield Finding(
            ctx.path,
            node.lineno,
            node.col_offset,
            RULE_ID,
            f"Python `{kind}` on a traced value (`{culprit}`) — use "
            "jax.lax.cond/while_loop (or jnp.where) inside traced code",
            qualname(node),
        )
