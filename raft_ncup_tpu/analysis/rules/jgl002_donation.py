"""JGL002 — jitted state-carrying step without buffer donation.

A compiled train step that takes the full TrainState and returns the next
one doubles its parameter+optimizer memory unless the input buffers are
donated (``donate_argnums``/``donate_argnames``). On TPU that halves the
largest fittable batch; the repo's contract is that every state-carrying
step donates (parallel/step.py:87-94). The rule fires on ``jax.jit``/
``pjit`` applications — call-form or decorator-form — of a function whose
signature carries a state-like first-class parameter with no donation
keyword at the jit site.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    enclosing_functions,
    qualname,
)

RULE_ID = "JGL002"
SUMMARY = "jit/pjit of a state-carrying step without donate_argnums"

_JIT_TAILS = frozenset({"jit", "pjit"})
_DONATE_KWARGS = frozenset({"donate_argnums", "donate_argnames"})
_STATE_PARAMS = frozenset({"state", "train_state", "opt_state", "carry"})


def _is_jit(func_node: ast.AST, aliases: dict) -> bool:
    dn = dotted_name(func_node, aliases)
    return dn is not None and dn.split(".")[-1].lstrip("_") in _JIT_TAILS


def _state_params(fn: ast.AST) -> list:
    args = getattr(fn, "args", None)
    if args is None:
        return []
    names = [a.arg for a in args.posonlyargs + args.args]
    return [n for n in names if n in _STATE_PARAMS]


def _finding(ctx: ModuleContext, node: ast.AST, fn_name: str, params) -> Finding:
    return Finding(
        ctx.path,
        node.lineno,
        node.col_offset,
        RULE_ID,
        f"jit of `{fn_name}` carries state parameter(s) "
        f"{sorted(params)} without donate_argnums/donate_argnames — "
        "the old state's buffers stay live and double step memory",
        qualname(node),
    )


def check(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        # Call form: jax.jit(step, ...)
        if isinstance(node, ast.Call) and _is_jit(node.func, ctx.aliases):
            if any(kw.arg in _DONATE_KWARGS for kw in node.keywords):
                continue
            if not node.args:
                continue
            at = next(enclosing_functions(node), None)
            for fn in ctx.traced._resolve_funcarg(node.args[0], at):
                params = _state_params(fn)
                if params:
                    yield _finding(
                        ctx, node, getattr(fn, "name", "<lambda>"), params
                    )
                    break
        # Decorator form: @jax.jit / @partial(jax.jit, ...)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = _state_params(node)
            if not params:
                continue
            for deco in node.decorator_list:
                target, keywords = deco, []
                if isinstance(deco, ast.Call):
                    target, keywords = deco.func, deco.keywords
                    dn = dotted_name(target, ctx.aliases)
                    if dn == "functools.partial" and deco.args:
                        target = deco.args[0]
                if _is_jit(target, ctx.aliases) and not any(
                    kw.arg in _DONATE_KWARGS for kw in keywords
                ):
                    yield _finding(ctx, deco, node.name, params)
