"""JGL009 — raw dtype literals bypassing the precision policy.

The precision-policy subsystem (``raft_ncup_tpu/precision/``;
docs/PRECISION.md) is the single authority for every dtype on the hot
path: module compute, correlation volume, coordinate carry, outputs.
A raw inline ``jnp.float32`` / ``jnp.bfloat16`` / ``jnp.float16`` in a
hot-path function body is a dtype decision the policy cannot see — it
either silently pins a tensor wide (the bf16 presets stop paying off
exactly where the literal sits) or, worse, silently narrows something
the policy pins f32 (coordinates, accumulators).

Scope: ``models/``, ``nn/``, ``inference/`` — the forward hot path —
plus ``resilience/anomaly.py`` (the divergence sentinel's arithmetic
must stay f32 *by policy*, so its literals are allowlisted with
justification rather than invisible).

Sanctioned routings (NOT flagged):

- reading a policy: ``self.policy.compute_jnp``, ``policy.coord_jnp`` —
  no literal appears;
- a class-body attribute default (``dtype: Any = jnp.float32`` — the
  flax idiom: the attribute *is* the policy-settable knob, and callers
  override it from the policy);
- a module-level named constant (``PARAM_DTYPE = jnp.float32`` with a
  comment saying which pinned policy dtype it mirrors — e.g.
  ``nn/layers.py``'s master-weight/norm constants, which the policy
  constructor's f32 pins make authoritative).

Everything else — an ``astype(jnp.float32)`` inside a forward, a
``jnp.zeros(..., jnp.bfloat16)`` in a pipeline stage — is a finding;
deliberate exceptions (the f32 metric accumulators in
``inference/metrics.py``, the sentinel arithmetic) carry
justification-mandatory allowlist entries.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    qualname,
)

RULE_ID = "JGL009"
SUMMARY = (
    "raw jnp.float32/bfloat16/float16 literal bypassing the precision "
    "policy in models/, nn/, inference/ (and the sentinel)"
)

_DTYPE_NAMES = frozenset(
    {
        "jax.numpy.float32",
        "jax.numpy.bfloat16",
        "jax.numpy.float16",
    }
)


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return (
        "/models/" in p
        or p.startswith("models/")
        or "/nn/" in p
        or p.startswith("nn/")
        or "/inference/" in p
        or p.startswith("inference/")
        or p.endswith("resilience/anomaly.py")
    )


def _exempt_nodes(tree: ast.AST) -> set:
    """ids of nodes inside sanctioned literal positions: the VALUE of an
    assignment sitting directly in a module or class body (named
    constants and flax attribute defaults)."""
    exempt: set = set()
    scopes = [tree] + [
        n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    ]
    for scope in scopes:
        for stmt in scope.body:
            value = None
            if isinstance(stmt, ast.Assign):
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign):
                value = stmt.value
            if value is None:
                continue
            for sub in ast.walk(value):
                exempt.add(id(sub))
    return exempt


def check(ctx: ModuleContext) -> Iterator[Finding]:
    if not _in_scope(ctx.path):
        return
    exempt = _exempt_nodes(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.Name, ast.Attribute)):
            continue
        if id(node) in exempt:
            continue
        # An Attribute chain is visited once per link; only report the
        # full chain (whose parent is not itself part of the match).
        dn = dotted_name(node, ctx.aliases)
        if dn not in _DTYPE_NAMES:
            continue
        from raft_ncup_tpu.analysis.astutil import parent

        p = parent(node)
        if isinstance(p, ast.Attribute) and dotted_name(
            p, ctx.aliases
        ) in _DTYPE_NAMES:
            continue  # inner link of the same dotted chain
        yield Finding(
            ctx.path,
            node.lineno,
            node.col_offset,
            RULE_ID,
            f"raw `jnp.{dn.split('.')[-1]}` literal on the hot path: dtype "
            "decisions route through the PrecisionPolicy "
            "(raft_ncup_tpu/precision/) — use policy.compute_jnp/"
            "coord_jnp/..., a policy-settable module attribute, or a "
            "named module-level constant documenting which pinned "
            "policy dtype it mirrors",
            qualname(node),
        )
