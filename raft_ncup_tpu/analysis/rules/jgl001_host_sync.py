"""JGL001 — host synchronization inside traced code.

``float()``/``int()``/``bool()``/``.item()``/``.tolist()``/``np.asarray``
(and friends) on a traced value either fail at trace time
(TracerConversionError) or — worse, on values that happen to be concrete —
silently bake a device→host round-trip into every execution of the traced
region. RAFT's scanned GRU refinement is latency-bound (PAPER.md), so one
stray pull inside the step erases the async pipeline's entire overlap win
(docs/PERF.md train_loop row). The sanctioned pattern is the Logger's:
accumulate on device, pull once per window with an explicit
``jax.device_get`` *outside* the traced region.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    qualname,
)

RULE_ID = "JGL001"
SUMMARY = "host sync (float()/.item()/np.asarray/...) inside traced code"

# Fully-qualified callables that force a transfer or a blocking sync.
_HOST_PULL_CALLS = frozenset(
    {
        "jax.device_get",
        "jax.block_until_ready",
        "numpy.asarray",
        "numpy.array",
        "numpy.copy",
        "numpy.save",
        "numpy.savez",
    }
)
_BUILTIN_CASTS = frozenset({"float", "int", "bool", "complex"})
_METHOD_PULLS = frozenset({"item", "tolist", "block_until_ready"})


def _is_static_arg(node: ast.AST) -> bool:
    """Casts of literals and len()/shape lookups are trace-time Python,
    not host syncs."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id == "len"
    return False


def check(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.traced.is_traced(node):
            continue
        dn = dotted_name(node.func, ctx.aliases)
        if dn in _HOST_PULL_CALLS:
            yield Finding(
                ctx.path,
                node.lineno,
                node.col_offset,
                RULE_ID,
                f"`{dn}` inside traced code forces a host transfer/sync; "
                "move it outside the traced region (batch explicit pulls "
                "via one jax.device_get at a window boundary)",
                qualname(node),
            )
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id in _BUILTIN_CASTS
            and node.func.id not in ctx.aliases  # not shadowed by an import
            and node.args
            and not _is_static_arg(node.args[0])
        ):
            yield Finding(
                ctx.path,
                node.lineno,
                node.col_offset,
                RULE_ID,
                f"`{node.func.id}(...)` on a traced value is a per-call "
                "device→host sync (or a TracerConversionError); keep the "
                "value on device",
                qualname(node),
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _METHOD_PULLS
            and not node.args
        ):
            yield Finding(
                ctx.path,
                node.lineno,
                node.col_offset,
                RULE_ID,
                f"`.{node.func.attr}()` inside traced code pulls the value "
                "to host; keep it on device",
                qualname(node),
            )
