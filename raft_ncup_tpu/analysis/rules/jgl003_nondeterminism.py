"""JGL003 — Python-side nondeterminism reachable from traced code.

``time.time()``, stdlib ``random.*`` and ``np.random.*`` inside a traced
function execute ONCE, at trace time: the sampled value is baked into the
compiled program as a constant, so every subsequent step reuses it — the
classic "my noise never changes" bug — and any value drift across
processes desynchronizes an SPMD pod (each host compiles a different
constant). Randomness in traced code must flow through keyed
``jax.random``; wall-clock reads belong on the host side of the step
boundary.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    qualname,
)

RULE_ID = "JGL003"
SUMMARY = "Python-side nondeterminism (time/random/np.random) in traced code"

_NONDET_PREFIXES = ("time.", "random.", "numpy.random.")
_NONDET_EXACT = frozenset({"os.urandom", "uuid.uuid4", "secrets.token_bytes"})


def _is_nondet(dn: str) -> bool:
    if dn in _NONDET_EXACT:
        return True
    # jax.random is keyed and deterministic — the prefix test must not
    # catch it ("random." only matches the stdlib module).
    return any(dn.startswith(p) for p in _NONDET_PREFIXES)


def check(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.traced.is_traced(node):
            continue
        dn = dotted_name(node.func, ctx.aliases)
        if dn is None or not _is_nondet(dn):
            continue
        yield Finding(
            ctx.path,
            node.lineno,
            node.col_offset,
            RULE_ID,
            f"`{dn}` in traced code executes once at trace time and bakes "
            "its value into the compiled program; use keyed jax.random "
            "(or move the read outside the traced region)",
            qualname(node),
        )
