"""JGL005 — dtype hygiene in the numeric core (``ops/``, ``nn/``).

Two hazards, both of which change the compiled program's signature or
numerics silently:

- ``jnp.array(...)``/``jnp.asarray(...)`` without an explicit dtype: the
  result depends on the input's dtype and on ``jax_enable_x64`` — a numpy
  float64 sneaking in promotes a whole dataflow chain and, worse, changes
  the jit signature between callers (recompile per caller dtype). In the
  numeric core every conversion states its dtype.
- explicit ``float64`` (``np.float64``/``jnp.float64``/``"float64"``):
  TPUs have no f64 MXU path; XLA emulates it at ~100x cost. f64 in the
  core is either a bug or belongs behind an allowlist entry explaining
  why (e.g. a host-side reference check).

Scoped to ``ops/`` and ``nn/`` paths — driver/test code converts freely.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    qualname,
)

RULE_ID = "JGL005"
SUMMARY = "dtype-less jnp.array/asarray or float64 in ops/ and nn/"

_CONVERTERS = frozenset({"jax.numpy.array", "jax.numpy.asarray"})
_F64_NAMES = frozenset({"numpy.float64", "jax.numpy.float64"})


_F64_STRINGS = frozenset({"float64", "f8", "double"})


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return "/ops/" in p or "/nn/" in p or p.startswith(("ops/", "nn/"))


def _is_f64_string(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value in _F64_STRINGS
    )


def _f64_string_in_call(node: ast.Call) -> bool:
    """String-spelled f64 in dtype position: ``dtype="float64"`` on any
    call, a second positional on array/asarray (handled by the caller's
    converter branch), or ``.astype("float64")``."""
    if any(kw.arg == "dtype" and _is_f64_string(kw.value) for kw in node.keywords):
        return True
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "astype"
        and node.args
        and _is_f64_string(node.args[0])
    ):
        return True
    return False


def check(ctx: ModuleContext) -> Iterator[Finding]:
    if not _in_scope(ctx.path):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func, ctx.aliases)
            if dn in _CONVERTERS:
                has_dtype = len(node.args) >= 2 or any(
                    kw.arg == "dtype" for kw in node.keywords
                )
                if not has_dtype:
                    yield Finding(
                        ctx.path,
                        node.lineno,
                        node.col_offset,
                        RULE_ID,
                        f"`{dn.split('.')[-1]}` without an explicit dtype: "
                        "result dtype depends on the input and on "
                        "jax_enable_x64 — state it (e.g. jnp.float32)",
                        qualname(node),
                    )
            # String-spelled f64 (dtype="float64" anywhere, a "float64"
            # second positional on the converters, .astype("float64")) —
            # the Name/Attribute scan below cannot see string constants.
            if _f64_string_in_call(node) or (
                dn in _CONVERTERS
                and len(node.args) >= 2
                and _is_f64_string(node.args[1])
            ):
                yield Finding(
                    ctx.path,
                    node.lineno,
                    node.col_offset,
                    RULE_ID,
                    "string-spelled float64 dtype in the numeric core: "
                    "TPUs emulate f64 at ~100x cost — use "
                    "float32/bfloat16 (allowlist deliberate host-side "
                    "reference checks)",
                    qualname(node),
                )
        dn = (
            dotted_name(node, ctx.aliases)
            if isinstance(node, (ast.Name, ast.Attribute))
            else None
        )
        if dn in _F64_NAMES:
            yield Finding(
                ctx.path,
                node.lineno,
                node.col_offset,
                RULE_ID,
                f"`{dn}` in the numeric core: TPUs emulate f64 at ~100x "
                "cost — use float32/bfloat16 (allowlist deliberate "
                "host-side reference checks)",
                qualname(node),
            )
