"""JGL007 — swallowed exceptions in the fault-handling layers.

A fault-tolerance stack is only as honest as its error paths: a bare
``except:`` or ``except Exception:`` whose body neither re-raises nor
does anything observable (no call — so no logging, no accounting, no
cleanup) converts a recoverable fault into silent corruption. In this
repo the canonical victims are the resilience protocol itself (a
swallowed save error masks a failed preemption checkpoint), the training
loop plumbing, and the data pipeline (a swallowed decode error becomes a
short epoch). The retry/quarantine layer (resilience/retry.py) exists
precisely so absorbing an error is always *accounted* — this rule keeps
everyone on that path.

Scoped to ``resilience/``, ``training/``, ``data/`` and ``fleet/`` —
the fleet supervisor most of all: a supervisor that silently eats a
child replica's death is the exact failure mode the fleet tier exists
to prevent (an unnoticed dead replica = silent capacity loss + hung
clients; docs/FLEET.md). Narrow handler types (``except queue.Empty:
pass``, ``except ImportError: pass``) are out of scope: catching a
*specific* expected exception and dropping it is a decision, not an
accident. Audited exceptions go through the allowlist with a
justification, like every other rule.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    qualname,
)

RULE_ID = "JGL007"
SUMMARY = (
    "swallowed exception (broad except, no re-raise/handling) in "
    "resilience/, training/, data/, fleet/"
)

_BROAD = frozenset({"Exception", "BaseException"})
_SCOPE_DIRS = ("resilience", "training", "data", "fleet")


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(
        f"/{d}/" in p or p.startswith(f"{d}/") for d in _SCOPE_DIRS
    )


def _is_broad(type_node, aliases) -> bool:
    """Bare ``except:`` or a handler type (or tuple member) named
    Exception/BaseException."""
    if type_node is None:
        return True
    elts = (
        type_node.elts
        if isinstance(type_node, ast.Tuple)
        else [type_node]
    )
    for e in elts:
        dn = dotted_name(e, aliases) or ""
        if dn.split(".")[-1] in _BROAD:
            return True
    return False


def _handles(body) -> bool:
    """A handler 'handles' when it re-raises or does anything observable
    (any call: logging, accounting, cleanup, a recorded fallback)."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Call)):
                return True
    return False


def check(ctx: ModuleContext) -> Iterator[Finding]:
    if not _in_scope(ctx.path):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node.type, ctx.aliases):
            continue
        if _handles(node.body):
            continue
        label = (
            "bare `except:`" if node.type is None
            else "broad `except " + (ast.unparse(node.type)) + "`"
        )
        yield Finding(
            ctx.path,
            node.lineno,
            node.col_offset,
            RULE_ID,
            f"{label} swallows the error (no re-raise, no logging/"
            "accounting call): in the fault-handling layers every "
            "absorbed exception must be narrow, re-raised, or accounted "
            "(resilience/retry.py)",
            qualname(node),
        )
