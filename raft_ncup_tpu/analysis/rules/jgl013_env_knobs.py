"""JGL013 — one env-knob registry, no stragglers.

Every ``RAFT_NCUP_*``/``BENCH_*`` environment knob is declared exactly
once in ``raft_ncup_tpu/utils/knobs.py`` (name, kind, default, doc) and
read exclusively through its ``knob_*`` getters — the same
one-declarative-object discipline the repo applies to fleet topology
and SLOs. Three checks, all whole-program:

- a direct ``os.environ`` read (``.get``/``[]``/``os.getenv``/``in``)
  whose name matches the knob prefixes, anywhere outside ``knobs.py``
  itself, is a finding — the knob exists but dodges the registry (so it
  has no declared type, no default documentation, and the PERF.md
  catalog misses it);
- a ``knob_*`` getter call naming a knob the registry does not declare
  is a finding (the getters also raise at runtime; the rule catches it
  before anything runs);
- a registered knob that no ``knob_*`` call ever reads is a finding —
  a dead knob, or a migration that silently dropped a reader. This
  half only runs when the linted set contains BOTH the registry
  (``knobs.py``) and every driver entry point (``train.py``,
  ``serve.py``, ``bench.py`` — where most knob readers live): a
  package-only lint sees the registry but not the drivers and cannot
  call a knob dead, the same scope-completeness gate JGL012 applies
  to its drift halves.

Names are resolved through module-level string constants and import
aliases (``os.environ.get(TELEMETRY_ENV)`` with ``TELEMETRY_ENV``
imported from another module still resolves); dynamic names are out of
static reach — the getters' runtime registry check covers them.
Internal child-process handshake variables (``_BENCH_*``) do not match
the prefixes and stay unmanaged on purpose.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List

from raft_ncup_tpu.analysis.astutil import Finding
from raft_ncup_tpu.analysis.project import ProjectIndex

RULE_ID = "JGL013"
SUMMARY = (
    "env knob read outside utils/knobs.py, unregistered knob name, or "
    "registered knob never read (whole-program)"
)

KNOB_PREFIX = re.compile(r"^(RAFT_NCUP_|BENCH_)")

# The entry points outside the package where knob readers live; the
# unread-knob half only runs when all of them are in the linted set.
DRIVER_BASENAMES = frozenset({"train.py", "serve.py", "bench.py"})


def _basename(path: str) -> str:
    return path.replace("\\", "/").rsplit("/", 1)[-1]


def _package_registry() -> Dict[str, None]:
    """Fallback registry: ``Knob("NAME", ...)`` declarations parsed
    from the package's own utils/knobs.py, so linting a subdirectory
    standalone still validates getter names. Empty on partial
    checkouts — silence, never a crash."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "utils", "knobs.py",
    )
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return {}
    out: Dict[str, None] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, (ast.Name, ast.Attribute))
            and (
                node.func.id if isinstance(node.func, ast.Name)
                else node.func.attr
            ) == "Knob"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out[node.args[0].value] = None
    return out


def check_project(proj: ProjectIndex) -> Iterator[Finding]:
    decls = [
        d for d in proj.knob_decls
        if _basename(d.site.path) == "knobs.py"
    ]
    registry_in_scope = bool(decls)
    registered = {d.name for d in decls} or set(_package_registry())

    findings: List[Finding] = []

    for read in proj.env_reads:
        if read.name is None or not KNOB_PREFIX.match(read.name):
            continue
        if _basename(read.site.path) == "knobs.py":
            continue  # the registry's own getters
        findings.append(Finding(
            path=read.site.path,
            line=read.site.line,
            col=read.site.col,
            rule=RULE_ID,
            message=(
                f"direct os.environ read of knob {read.name!r} outside "
                "the registry — read it through "
                "raft_ncup_tpu.utils.knobs (knob_raw/knob_int/"
                "knob_flag/...) so the name, type and default are "
                "declared once"
            ),
            qualname=read.site.qual,
        ))

    for call in proj.knob_calls:
        if call.name is None:
            continue  # dynamic name: the getter raises at runtime
        if call.name not in registered:
            findings.append(Finding(
                path=call.site.path,
                line=call.site.line,
                col=call.site.col,
                rule=RULE_ID,
                message=(
                    f"{call.getter}({call.name!r}) names a knob the "
                    "registry does not declare — add a Knob(...) entry "
                    "to raft_ncup_tpu/utils/knobs.py"
                ),
                qualname=call.site.qual,
            ))

    basenames = {_basename(p) for p in proj.paths}
    if registry_in_scope and DRIVER_BASENAMES <= basenames:
        read_names = {c.name for c in proj.knob_calls if c.name}
        for decl in sorted(decls, key=lambda d: d.name):
            if decl.name not in read_names:
                findings.append(Finding(
                    path=decl.site.path,
                    line=decl.site.line,
                    col=decl.site.col,
                    rule=RULE_ID,
                    message=(
                        f"knob {decl.name!r} is registered but no "
                        "knob_* getter ever reads it — dead knob, or a "
                        "reader was dropped in a migration"
                    ),
                    qualname=decl.site.qual,
                ))

    yield from findings
