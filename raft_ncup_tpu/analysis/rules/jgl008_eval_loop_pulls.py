"""JGL008 — per-iteration host pull in the eval/inference hot loop.

The eval pipeline's contract (inference/pipeline.py, docs/PERF.md "Eval
pipeline") is the Logger's, applied to validation: metrics accumulate ON
DEVICE inside the jitted forward and the host pulls a handful of scalars
ONCE per dataset window — never per batch. A ``jax.device_get`` (or an
``.item()``/``.tolist()``) inside the eval loop re-serializes dispatch
with device→host transfer every iteration, which is exactly the stall
the subsystem exists to remove (the pre-refactor validators pulled two
full flow fields per batch, ~4.4 MB/pair at 368x768).

Scoped to ``raft_ncup_tpu/inference/``, ``raft_ncup_tpu/serving/`` (the
serving dispatcher is the same hot loop facing an open-loop stream: its
per-batch result pull must ride the AsyncDrain worker, never the
dispatch thread), ``raft_ncup_tpu/streaming/`` (the stream dispatcher
batches stateful frames: per-stream recurrent state lives in the device
slot table precisely so that NOTHING needs pulling between frames) and
``evaluation.py``. Flags the
pull calls only when they execute per loop iteration (``for``/``while``
bodies and comprehensions); a function merely *defined* inside a loop is
not flagged at its definition site. ``jax.block_until_ready`` is
deliberately NOT flagged: it is a sync without a transfer — the
DispatchThrottle's bounded in-flight wait is part of the sanctioned
steady state. The one audited exception is the AsyncDrain worker, which
IS the sanctioned off-dispatch pull. (The Sintel warm-start's serial
low-res pull — the second historical entry — was deleted when the
forward splat moved on device: ``ops/warmstart.forward_interpolate_jax``
keeps the warm chain in HBM, so there is nothing left to pull.)
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    FUNC_NODES,
    Finding,
    ModuleContext,
    dotted_name,
    parent,
    qualname,
)

RULE_ID = "JGL008"
SUMMARY = (
    "per-iteration host pull (device_get/.item()/.tolist()) in the "
    "eval/serving hot loop (inference/, serving/, evaluation.py)"
)

_PULL_CALLS = frozenset({"jax.device_get"})
_PULL_METHODS = frozenset({"item", "tolist"})
_LOOP_NODES = (
    ast.For,
    ast.AsyncFor,
    ast.While,
    ast.ListComp,
    ast.SetComp,
    ast.DictComp,
    ast.GeneratorExp,
)


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return (
        "/inference/" in p
        or p.startswith("inference/")
        or "/serving/" in p
        or p.startswith("serving/")
        or "/streaming/" in p
        or p.startswith("streaming/")
        or p.endswith("/evaluation.py")
        or p == "evaluation.py"
    )


def _executes_per_iteration(node: ast.AST) -> bool:
    """True when ``node`` runs once per iteration of an enclosing loop:
    the nearest loop ancestor is reached before any function-definition
    boundary (a nested def's body runs when called, not when defined)."""
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, _LOOP_NODES):
            return True
        if isinstance(cur, FUNC_NODES):
            return False
        cur = parent(cur)
    return False


def check(ctx: ModuleContext) -> Iterator[Finding]:
    if not _in_scope(ctx.path):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _executes_per_iteration(node):
            continue
        dn = dotted_name(node.func, ctx.aliases)
        if dn in _PULL_CALLS:
            yield Finding(
                ctx.path,
                node.lineno,
                node.col_offset,
                RULE_ID,
                f"`{dn}` inside the eval loop pulls to host every "
                "iteration; keep the accumulator on device and pull once "
                "per window, or route full-field pulls through AsyncDrain",
                qualname(node),
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _PULL_METHODS
            and not node.args
        ):
            yield Finding(
                ctx.path,
                node.lineno,
                node.col_offset,
                RULE_ID,
                f"`.{node.func.attr}()` inside the eval loop is a "
                "per-iteration device→host sync; accumulate on device and "
                "pull once per window",
                qualname(node),
            )
