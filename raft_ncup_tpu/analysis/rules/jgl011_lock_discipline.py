"""JGL011 — cross-module lock discipline for the control plane.

For every class under ``fleet/`` or ``observability/`` that owns a
``threading.Lock/RLock/Condition`` instance attribute: an instance
attribute that is WRITTEN under the lock somewhere must not be read or
written outside it anywhere else — in any method, any nested closure,
or any other module that reaches the attribute through an object
reference. The finding names both sites, because that is what makes a
data race auditable: the guarded write proves the author considered the
attribute shared, the unguarded touch is the hole chaos tests can only
hope to hit (docs/ANALYSIS.md "Whole-program rules").

What does NOT count as unguarded:

- accesses directly in ``__init__`` (construction is single-threaded —
  no other thread holds a reference yet);
- accesses in a private method whose every observed call site holds the
  lock (or is itself such a method, or is ``__init__``) — the
  "always-locked helper" pattern (``FleetRouter._register``). Public
  methods and methods whose references escape (``target=self._loop``)
  are assumed to have callers the analysis cannot see;
- cross-module accesses guarded by ``with <obj>.<lock>:`` on the same
  base expression (``replay_fleet``'s ``with router._lock:``);
- attributes never written under the lock at all: a class that guards
  nothing about an attribute gets no opinion from this rule.

Lexical blind spots (a lock object shared across instances, a
``Condition.wait`` releasing mid-block) are allowlist material, not
rule extensions — see docs/ANALYSIS.md.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Set

from raft_ncup_tpu.analysis.astutil import Finding
from raft_ncup_tpu.analysis.project import ClassInfo, ProjectIndex

RULE_ID = "JGL011"
SUMMARY = (
    "attribute written under its class lock but read/written without "
    "it elsewhere (whole-program)"
)


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return (
        "/fleet/" in p
        or p.startswith("fleet/")
        or "/observability/" in p
        or p.startswith("observability/")
    )


def _always_locked(info: ClassInfo) -> Set[str]:
    """Private methods of ``info`` provably entered only with the lock
    held: every observed call site is lock-guarded, in ``__init__``, or
    in another always-locked method — and the method's reference never
    escapes. Fixpoint over the per-class call graph."""
    escaped = {e.callee for e in info.call_events if not e.is_call}
    calls: Dict[str, List] = {}
    for e in info.call_events:
        if e.is_call:
            calls.setdefault(e.callee, []).append(e)
    always: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for m in info.methods:
            if m in always:
                continue
            if not m.startswith("_") or m.startswith("__"):
                continue  # public / dunder: unseen callers assumed
            if m in escaped or m not in calls:
                continue
            if all(
                e.guarded
                or e.in_init
                or (not e.in_nested and e.method in always)
                for e in calls[m]
            ):
                always.add(m)
                changed = True
    return always


def _effectively_guarded(a, always: Set[str]) -> bool:
    if a.guarded:
        return True
    return not a.in_nested and a.method in always


def check_project(proj: ProjectIndex) -> Iterator[Finding]:
    # attr name -> lock-owning classes with a locked write to it, for
    # attributing cross-module accesses. Only private attrs are matched
    # externally, and only when exactly one class owns the name —
    # ambiguity would produce noise, not findings.
    ext_owners: Dict[str, List[tuple]] = {}
    findings: List[Finding] = []

    for info in proj.classes:
        if not _in_scope(info.path):
            continue
        always = _always_locked(info)
        by_attr: Dict[str, List] = {}
        for a in info.accesses:
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(by_attr.items()):
            locked_writes = [
                a for a in accs
                if a.kind == "write"
                and not a.in_init
                and _effectively_guarded(a, always)
            ]
            if not locked_writes:
                continue
            ext_owners.setdefault(attr, []).append(
                (info, locked_writes[0], always)
            )
            unguarded = [
                a for a in accs
                if not a.in_init and not _effectively_guarded(a, always)
            ]
            gw = locked_writes[0]
            for a in unguarded:
                verb = "written" if a.kind == "write" else "read"
                where = (
                    " (inside a nested function — the lock around its "
                    "definition is not held when it runs)"
                    if a.in_nested else ""
                )
                findings.append(Finding(
                    path=a.site.path,
                    line=a.site.line,
                    col=a.site.col,
                    rule=RULE_ID,
                    message=(
                        f"{info.name}.{attr} is written under the class "
                        f"lock at {gw.site.path}:{gw.site.line} "
                        f"[{gw.site.qual}] but {verb} without it "
                        f"here{where}"
                    ),
                    qualname=a.site.qual,
                ))

    for ea in proj.ext_accesses:
        owners = ext_owners.get(ea.attr, [])
        if len(owners) != 1:
            continue
        info, gw, _always = owners[0]
        if ea.attr in info.lock_attrs:
            continue
        if ea.base is None:
            continue  # dynamic base: cannot attribute a guard to it
        if any(
            f"{ea.base}.{lock}" in ea.held for lock in info.lock_attrs
        ):
            continue
        verb = "written" if ea.kind == "write" else "read"
        findings.append(Finding(
            path=ea.site.path,
            line=ea.site.line,
            col=ea.site.col,
            rule=RULE_ID,
            message=(
                f"{info.name}.{ea.attr} ({info.path}) is written under "
                f"the class lock at {gw.site.path}:{gw.site.line} "
                f"[{gw.site.qual}] but {verb} through {ea.base!r} "
                f"without holding {ea.base}.<lock> here"
            ),
            qualname=ea.site.qual,
        ))

    yield from findings
