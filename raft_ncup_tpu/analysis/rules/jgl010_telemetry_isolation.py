"""JGL010 — device-array access inside the telemetry subsystem.

The observability package's hard constraint is the platform's own,
inverted: every other subsystem must not *leak* host syncs; telemetry
must not *add* them. A metrics registry that calls ``float()`` on a
device scalar, a span that stashes a ``jax.Array`` in its attrs, a
snapshot thread that ``np.asarray``-pulls a buffer — each would put a
device round-trip on the hot path *from the observer*, and an observer
that perturbs the observed steady state is worse than none (the bench's
telemetry-on-vs-off overhead row measures exactly this).

So ``raft_ncup_tpu/observability/`` is host-only stdlib by
construction, and ``raft_ncup_tpu/fleet/`` (host-only stdlib + numpy)
shares the contract with the constraint sharpened: the fleet router
sits in front of EVERY request — a router that can touch a device array
can add a device sync to the whole fleet's traffic, and a replica
supervisor that imports jax initializes a backend in a process whose
entire job is to watch other processes own the devices. This rule
enforces both statically:

- **no jax import at all** (``import jax``, ``from jax import ...``,
  ``import jax.numpy``): the package must stay importable — and
  correct — on hosts where touching jax would initialize a backend,
  exactly like ``analysis/`` itself;
- **no device pulls**: ``jax.device_get`` / ``device_put`` /
  ``block_until_ready`` calls (however aliased), and the implicit-pull
  shapes the runtime guard intercepts — ``.item()`` / ``.tolist()``
  method calls and ``numpy.asarray`` / ``numpy.array`` calls.

The scope covers every module in both packages — including
``observability/aggregate.py``, the fleet trace/registry merger, which
is exactly the kind of "offline tool" that would otherwise be tempted
to import jax for convenience and drag a backend into every laptop
postmortem.

One more contract, specific to ``fleet/``: the **trace-context wire
header stays optional**. The frame schema's ``trace`` field
(``wire.TRACE_KEY``) is how cross-process trace propagation rides the
router → replica hop, and the compatibility rule is that old peers must
parse new frames and vice versa — so no code in ``fleet/`` may READ it
with a mandatory subscript (``header["trace"]``); consumers use
``.get`` (and ``TraceContext.from_wire`` tolerates ``None``). The rule
flags Load-context ``["trace"]`` subscripts in ``fleet/`` statically;
writing the field (``header["trace"] = ...``) is fine — a producer
always knows its own schema.

Values crossing into telemetry must already be host scalars, pulled at
the producers' sanctioned boundaries (the AsyncDrain worker's one
``device_get`` per batch, the Logger's one per window);
``telemetry.host_number`` backs this rule up at runtime by rejecting
jax-typed values before any conversion could sync.
"""

from __future__ import annotations

import ast
from typing import Iterator

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    dotted_name,
    qualname,
)

RULE_ID = "JGL010"
SUMMARY = (
    "jax import or device-array access inside observability/ or fleet/ "
    "— telemetry and the fleet control plane are host-only and must "
    "never add a sync"
)

_JAX_CALLS = frozenset(
    {
        "jax.device_get",
        "jax.device_put",
        "jax.block_until_ready",
    }
)
_NUMPY_PULLS = frozenset({"numpy.asarray", "numpy.array"})
_METHOD_PULLS = frozenset({"item", "tolist"})


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(
        f"/{d}/" in p or p.startswith(f"{d}/")
        for d in ("observability", "fleet")
    )


def _in_fleet(path: str) -> bool:
    p = path.replace("\\", "/")
    return "/fleet/" in p or p.startswith("fleet/")


def check(ctx: ModuleContext) -> Iterator[Finding]:
    if not _in_scope(ctx.path):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root == "jax":
                    yield Finding(
                        ctx.path, node.lineno, node.col_offset, RULE_ID,
                        f"`import {alias.name}` in observability//fleet/: "
                        "telemetry is host-only stdlib — a jax import "
                        "here puts device-array access (and backend "
                        "initialization) one attribute away from every "
                        "metric call; record host scalars pulled at the "
                        "producers' sanctioned boundaries instead",
                        qualname(node),
                    )
        elif isinstance(node, ast.ImportFrom):
            root = (node.module or "").split(".")[0]
            if root == "jax":
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, RULE_ID,
                    f"`from {node.module} import ...` in observability//fleet/: "
                    "telemetry is host-only stdlib (see JGL010)",
                    qualname(node),
                )
        elif isinstance(node, ast.Call):
            dn = dotted_name(node.func, ctx.aliases)
            if dn in _JAX_CALLS or (
                dn is not None and dn.split(".")[0] == "jax"
            ):
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, RULE_ID,
                    f"`{dn}` call in observability//fleet/: a device access "
                    "inside telemetry adds the very sync the guarded "
                    "hot path forbids — pull at the producer's "
                    "sanctioned boundary and hand telemetry the host "
                    "scalar",
                    qualname(node),
                )
            elif dn in _NUMPY_PULLS:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, RULE_ID,
                    f"`{dn}` call in observability//fleet/: on a jax array this "
                    "is an implicit device→host pull (the runtime "
                    "guard's exact intercept list) — telemetry receives "
                    "host numbers, it never converts",
                    qualname(node),
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHOD_PULLS
                and not node.args
                and not node.keywords
            ):
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, RULE_ID,
                    f"`.{node.func.attr}()` call in observability//fleet/: on a "
                    "jax array this is an implicit device→host pull — "
                    "telemetry receives host numbers, it never converts",
                    qualname(node),
                )
        elif (
            isinstance(node, ast.Subscript)
            and isinstance(node.ctx, ast.Load)
            and isinstance(node.slice, ast.Constant)
            and node.slice.value == "trace"
            and _in_fleet(ctx.path)
        ):
            # Wire-compat contract: the trace-context header field is
            # OPTIONAL in every frame schema — a mandatory read would
            # make old peers' frames unparsable by new fleet code.
            yield Finding(
                ctx.path, node.lineno, node.col_offset, RULE_ID,
                "mandatory `[\"trace\"]` read in fleet/: the "
                "trace-context wire header is OPTIONAL (old peers must "
                "parse new frames and vice versa) — read it with "
                "`.get('trace')` and tolerate None "
                "(TraceContext.from_wire does)",
                qualname(node),
            )
