"""JGL012 — wire-protocol contract between frame producers and
consumers.

The fleet wire protocol (fleet/wire.py) is length-prefixed JSON whose
producers and consumers live in different modules and different
PROCESSES: the router writes a request header in ``fleet/router.py``,
the replica loop reads it in ``serve.py``, and nothing but convention
keeps the two ends naming the same keys. This rule collects every
header-key write (constant keys of any dict literal carrying a
``"kind"`` key — every frame has one — plus ``header["k"] = ...``
store subscripts) and every read (``header.get("k")`` and bare
subscripts) across ``fleet/*.py`` and ``serve.py``, then flags:

- **drift**: a key read but never written by any in-scope producer, or
  written but never read by any in-scope consumer — a renamed or dead
  protocol field that will otherwise surface as an unexplainable
  behavior gap between router and replica versions;
- **bare-subscript reads**: every field beyond ``kind`` is OPTIONAL
  (the schema-evolution contract in fleet/wire.py's docstring), so a
  consumer must read with ``.get``, never ``header["k"]`` — the
  generalization of JGL010's one-off trace-key check, which keeps
  ownership of the ``"trace"`` key in ``fleet/`` (carved out here to
  avoid double findings).

``fleet/wire.py`` itself is the codec, not a producer or consumer of
protocol fields (its ``header.pop("arrays")`` handles the reserved
descriptor key) — it is excluded from collection, as is the reserved
``"arrays"`` key. The two drift halves only run when the linted set
contains BOTH ends (``serve.py`` and ``fleet/`` modules); a standalone
lint of one directory cannot distinguish drift from out-of-scope use.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

from raft_ncup_tpu.analysis.astutil import Finding
from raft_ncup_tpu.analysis.project import (
    WIRE_RESERVED_KEYS,
    ProjectIndex,
)

RULE_ID = "JGL012"
SUMMARY = (
    "wire header key drift or bare-subscript read across fleet/*.py "
    "and serve.py (whole-program)"
)


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _basename(path: str) -> str:
    return _norm(path).rsplit("/", 1)[-1]


def _in_fleet(path: str) -> bool:
    p = _norm(path)
    return "/fleet/" in p or p.startswith("fleet/")


def _in_scope(path: str) -> bool:
    if _basename(path) == "serve.py":
        return True
    return _in_fleet(path) and _basename(path) != "wire.py"


def check_project(proj: ProjectIndex) -> Iterator[Finding]:
    writes: Dict[str, List] = {}
    reads: Dict[str, List] = {}
    bare_reads: List = []
    for wk in proj.wire_keys:
        if not _in_scope(wk.site.path) or wk.key in WIRE_RESERVED_KEYS:
            continue
        if wk.kind == "write":
            writes.setdefault(wk.key, []).append(wk)
        else:
            reads.setdefault(wk.key, []).append(wk)
            if wk.kind == "read_subscript":
                bare_reads.append(wk)

    findings: List[Finding] = []

    # Bare-subscript reads: per-site, regardless of scope completeness.
    for wk in bare_reads:
        if wk.key == "kind":
            continue  # the one REQUIRED field — a subscript is honest
        if wk.key == "trace" and _in_fleet(wk.site.path):
            continue  # JGL010's trace-key check owns this site
        findings.append(Finding(
            path=wk.site.path,
            line=wk.site.line,
            col=wk.site.col,
            rule=RULE_ID,
            message=(
                f"wire header key {wk.key!r} read with a bare "
                "subscript — every field beyond 'kind' is OPTIONAL "
                "(schema-evolution contract, fleet/wire.py); read it "
                "with .get() and handle None"
            ),
            qualname=wk.site.qual,
        ))

    # Drift needs both ends of the protocol in the linted set.
    has_serve = any(_basename(p) == "serve.py" for p in proj.paths)
    has_fleet = any(_in_scope(p) and _in_fleet(p) for p in proj.paths)
    if has_serve and has_fleet:
        for key in sorted(set(reads) - set(writes)):
            wk = min(reads[key], key=lambda w: (w.site.path, w.site.line))
            findings.append(Finding(
                path=wk.site.path,
                line=wk.site.line,
                col=wk.site.col,
                rule=RULE_ID,
                message=(
                    f"wire header key {key!r} is read here but never "
                    "written by any producer in fleet/ or serve.py — "
                    "renamed or dead protocol field (drift)"
                ),
                qualname=wk.site.qual,
            ))
        for key in sorted(set(writes) - set(reads)):
            wk = min(writes[key], key=lambda w: (w.site.path, w.site.line))
            findings.append(Finding(
                path=wk.site.path,
                line=wk.site.line,
                col=wk.site.col,
                rule=RULE_ID,
                message=(
                    f"wire header key {key!r} is written here but never "
                    "read by any consumer in fleet/ or serve.py — "
                    "renamed or dead protocol field (drift)"
                ),
                qualname=wk.site.qual,
            ))

    yield from findings
