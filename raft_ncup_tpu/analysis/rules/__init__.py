"""graftlint rule registry — one module per JGL rule.

Per-module rules expose ``RULE_ID``, ``SUMMARY`` and
``check(ctx: ModuleContext) -> Iterator[Finding]``; whole-program rules
(JGL011+) expose ``check_project(proj: ProjectIndex)`` instead and run
once over the cross-module graph after the per-module pass. Adding a
rule means adding a module here and listing it in ``ALL_RULES``; the
engine, CLI ``--select`` filtering, catalog output and tests pick it up
from the registry.
"""

from __future__ import annotations

from raft_ncup_tpu.analysis.rules import (
    jgl001_host_sync,
    jgl002_donation,
    jgl003_nondeterminism,
    jgl004_tracer_control_flow,
    jgl005_dtype_hygiene,
    jgl006_partition_axes,
    jgl007_swallowed_exceptions,
    jgl008_eval_loop_pulls,
    jgl009_precision_policy,
    jgl010_telemetry_isolation,
    jgl011_lock_discipline,
    jgl012_wire_contract,
    jgl013_env_knobs,
)

ALL_RULES = (
    jgl001_host_sync,
    jgl002_donation,
    jgl003_nondeterminism,
    jgl004_tracer_control_flow,
    jgl005_dtype_hygiene,
    jgl006_partition_axes,
    jgl007_swallowed_exceptions,
    jgl008_eval_loop_pulls,
    jgl009_precision_policy,
    jgl010_telemetry_isolation,
    jgl011_lock_discipline,
    jgl012_wire_contract,
    jgl013_env_knobs,
)

RULES_BY_ID = {mod.RULE_ID: mod for mod in ALL_RULES}
