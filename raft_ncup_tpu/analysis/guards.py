"""Runtime guard rails: assert the sync-free, recompile-free hot path live.

graftlint (the sibling ``lint`` module) proves statically that traced code
contains no host syncs; this module asserts the same invariants on the
*running* loop, where the failure modes static analysis cannot see live:
dispatch-time implicit transfers, and silent recompilation from shape or
dtype drift. Three primitives:

- :func:`forbid_host_transfers` — context manager that intercepts
  implicit device→host pulls (``float()``/``int()``/``bool()``/
  ``.item()``/``.tolist()``/``np.asarray``/``np.array`` on a
  ``jax.Array``) and raises :class:`GuardViolation` (or counts, with
  ``raise_on_violation=False``). The *explicit* ``jax.device_get`` stays
  sanctioned — it is the contract for window-boundary pulls (the
  Logger's one-get-per-``sum_freq``; the bench loop's one-get-per-window).
  Layered on top, ``jax.transfer_guard_device_to_host("disallow")``
  catches native-path transfers on real accelerators; the Python-level
  interception exists because on the CPU backend device→host is zero-copy
  and the native guard never fires — without it the tier-1 tests would
  vacuously pass.
- :class:`RecompileWatchdog` / :func:`max_recompiles` — counts XLA
  backend compiles via ``jax.monitoring``'s
  ``/jax/core/compile/backend_compile_duration`` event; ``max_recompiles``
  raises at scope exit when the count exceeds the budget (a steady-state
  train loop compiles its step exactly once).
- :class:`StepGuard` — the train-loop integration (``--strict_guards``):
  registered once for the loop, armed per step via :meth:`StepGuard.scope`
  so validation/checkpoint boundaries (which legitimately pull to host
  and compile new shapes) stay outside the guarded region.

Interception patches are process-global while a scope is active (a
violating pull from *any* thread is a violation — the DevicePrefetcher
worker only does host→device work and is unaffected); the sanctioning
flag is thread-local so one thread's ``jax.device_get`` cannot blanket
another thread's stray pull.

tests/conftest.py re-exports :func:`forbid_host_transfers` and
:func:`max_recompiles` as pytest fixtures; tests/test_guards.py pins the
train loop's invariants with them. docs/ANALYSIS.md documents the layer.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Iterator, List, Optional

import jax

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Implicit-pull methods intercepted on the concrete array type. __array__
# covers jax.device_get's own path and (on non-CPU backends) np.asarray;
# on CPU, np.asarray takes the buffer protocol around __array__, which is
# why numpy's module-level asarray/array are wrapped as well.
_PULL_METHODS = (
    "__array__",
    "__float__",
    "__int__",
    "__bool__",
    "__complex__",
    "item",
    "tolist",
)
_NUMPY_FUNCS = ("asarray", "array")


class GuardViolation(RuntimeError):
    """A guarded invariant (no implicit host pulls / compile budget) broke."""


@dataclass(eq=False)  # a counter object: identity, not value, equality
class GuardStats:
    """Counters a guard scope fills in; the bench row and --strict_guards
    report these."""

    host_transfers: int = 0  # forbidden implicit pulls observed
    sanctioned_gets: int = 0  # explicit jax.device_get calls
    recompiles: int = 0  # steady-state compiles (see StepGuard)
    warmup_compiles: int = 0  # first-scope compiles (step + aux programs)
    violations: List[str] = field(default_factory=list)


def _array_impl_type():
    from jax._src.array import ArrayImpl

    return ArrayImpl


# ----------------------------------------------------------- pull guard

_tl = threading.local()  # .sanctioned: inside an explicit device_get
_lock = threading.RLock()
_active: list = []  # stack of _ScopeEntry (patches installed while non-empty)
_saved: dict = {}


class _ScopeEntry:
    """One active guard scope. ``armed=False`` keeps the patches installed
    but inert — StepGuard's between-step state, so the hot loop never
    pays per-step install/uninstall (the watchdog's arm()/disarm()
    pattern applied to the pull patches)."""

    __slots__ = ("stats", "raise_on_violation", "armed")

    def __init__(self, stats, raise_on_violation: bool, armed: bool = True):
        self.stats = stats
        self.raise_on_violation = raise_on_violation
        self.armed = armed


def _push_scope(
    stats: "GuardStats", raise_on_violation: bool, armed: bool = True
) -> _ScopeEntry:
    with _lock:
        if not _active:
            _install()
        entry = _ScopeEntry(stats, raise_on_violation, armed)
        _active.append(entry)
        return entry


def _pop_scope(entry: _ScopeEntry) -> None:
    with _lock:
        _active.remove(entry)  # identity-based: plain object equality
        if not _active:
            _uninstall()


def _record_violation(desc: str) -> None:
    with _lock:
        entry = next((e for e in reversed(_active) if e.armed), None)
        if entry is None:
            return
        entry.stats.host_transfers += 1
        entry.stats.violations.append(desc)
        raise_on_violation = entry.raise_on_violation
    # Mirror into the unified telemetry stream (observability/): a guard
    # violation is exactly the kind of lifecycle fact a later stall
    # diagnosis wants on the correlated timeline. GuardStats stays the
    # scope-local source of truth.
    from raft_ncup_tpu.observability import get_telemetry

    tel = get_telemetry()
    tel.event("guard_host_transfer_violation", desc=desc)
    # Fault trigger (observability/flight.py): a guard violation means
    # a sync leaked onto the hot path — bank the timeline that led to
    # it. Rate-limited in the recorder, no-op without one.
    tel.flight_dump("guard_violation", desc=desc)
    if raise_on_violation:
        raise GuardViolation(
            f"implicit device->host transfer under forbid_host_transfers: "
            f"{desc}. Keep values on device between window boundaries and "
            "batch explicit pulls through one jax.device_get."
        )


def _install() -> None:
    import numpy as np

    arr_t = _array_impl_type()
    for name in _PULL_METHODS:
        orig = getattr(arr_t, name)
        _saved[("arr", name)] = orig

        def make(nm, o):
            def patched(self, *a, **kw):
                if not getattr(_tl, "sanctioned", False):
                    _record_violation(
                        f"jax.Array.{nm} on shape {getattr(self, 'shape', '?')}"
                    )
                return o(self, *a, **kw)

            return patched

        setattr(arr_t, name, make(name, orig))

    for name in _NUMPY_FUNCS:
        orig = getattr(np, name)
        _saved[("np", name)] = orig

        def make_np(nm, o):
            def patched(obj, *a, **kw):
                if isinstance(obj, arr_t) and not getattr(
                    _tl, "sanctioned", False
                ):
                    _record_violation(
                        f"np.{nm} on jax.Array of shape "
                        f"{getattr(obj, 'shape', '?')}"
                    )
                return o(obj, *a, **kw)

            return patched

        setattr(np, name, make_np(name, orig))

    orig_get = jax.device_get
    _saved[("jax", "device_get")] = orig_get

    def sanctioned_get(tree):
        with _lock:
            entry = next((e for e in reversed(_active) if e.armed), None)
            if entry is not None:
                entry.stats.sanctioned_gets += 1
        if entry is not None:
            # Canonical counter for GuardStats.sanctioned_gets (host
            # int bump — the pull itself is unaffected).
            from raft_ncup_tpu.observability import get_telemetry

            get_telemetry().inc("guard_sanctioned_gets_total")
        prev = getattr(_tl, "sanctioned", False)
        _tl.sanctioned = True
        try:
            return orig_get(tree)
        finally:
            _tl.sanctioned = prev

    jax.device_get = sanctioned_get


def _uninstall() -> None:
    import numpy as np

    arr_t = _array_impl_type()
    for (kind, name), orig in _saved.items():
        target = {"arr": arr_t, "np": np, "jax": jax}[kind]
        setattr(target, name, orig)
    _saved.clear()


@contextlib.contextmanager
def forbid_host_transfers(
    stats: Optional[GuardStats] = None,
    raise_on_violation: bool = True,
    native_guard: bool = True,
) -> Iterator[GuardStats]:
    """Forbid implicit device→host pulls inside the scope.

    Yields the :class:`GuardStats` being filled. With
    ``raise_on_violation=False`` violations only count (the bench row's
    mode). ``native_guard`` additionally arms jax's own
    ``transfer_guard_device_to_host("disallow")`` — real coverage on
    accelerators, inert on zero-copy CPU.
    """
    stats = stats if stats is not None else GuardStats()
    entry = _push_scope(stats, raise_on_violation)
    native = (
        jax.transfer_guard_device_to_host("disallow")
        if native_guard
        else contextlib.nullcontext()
    )
    try:
        with native:
            yield stats
    finally:
        _pop_scope(entry)


# ----------------------------------------------------- recompile watchdog


class RecompileWatchdog:
    """Counts XLA backend compiles while armed (jax.monitoring listener).

    Use as a context manager; ``.count`` is the number of compiles
    observed inside the scope. ``arm()``/``disarm()`` gate counting
    within a longer registration (StepGuard counts step-scope compiles
    only, not validation's)."""

    def __init__(self) -> None:
        self.count = 0
        self._armed = True
        self._registered = False

    def _listener(self, event: str, duration: float, **kw) -> None:
        if self._armed and event.startswith(_COMPILE_EVENT):
            self.count += 1
            from raft_ncup_tpu.observability import get_telemetry

            get_telemetry().inc("guard_recompiles_total")

    def arm(self) -> None:
        self._armed = True

    def disarm(self) -> None:
        self._armed = False

    def __enter__(self) -> "RecompileWatchdog":
        jax.monitoring.register_event_duration_secs_listener(self._listener)
        self._registered = True
        return self

    def __exit__(self, *exc) -> None:
        if not self._registered:
            return
        self._registered = False
        try:
            from jax._src import monitoring as _mon

            _mon._unregister_event_duration_listener_by_callback(
                self._listener
            )
        except Exception:
            # Private unregister API moved: leave the listener registered
            # but permanently disarmed — correct, just not tidy.
            self._armed = False


@contextlib.contextmanager
def max_recompiles(n: int = 1) -> Iterator[RecompileWatchdog]:
    """Assert at most ``n`` XLA compiles happen inside the scope; raises
    :class:`GuardViolation` at exit otherwise. A fixed-shape train loop
    compiles its step once — every extra compile is shape/dtype drift
    silently re-paying (multi-minute, at scale) compile latency."""
    with RecompileWatchdog() as wd:
        yield wd
    if wd.count > n:
        raise GuardViolation(
            f"{wd.count} XLA compiles inside a max_recompiles({n}) scope — "
            "an input aval (shape/dtype/sharding) is drifting between steps"
        )


# --------------------------------------------------------- loop integration


class StepGuard:
    """``--strict_guards`` integration for a training loop.

    Register once around the loop (context manager), then wrap each
    steady-state iteration in :meth:`scope`::

        with StepGuard() as guard:
            while step_i < total:
                with guard.scope():
                    batch = next(prefetcher)   # device-resident already
                    state, metrics = step_fn(state, batch, rng)
                    logger.push(...)           # explicit get at boundary ok
                if step_i % val_freq == 0:
                    validate(...)              # outside: may pull/compile
            guard.check()

    Inside ``scope()``: implicit host pulls raise immediately; compiles
    are counted. Outside: nothing is patched or counted, so validation
    and checkpointing behave normally.

    Compile accounting is per scope: the first ``warmup_scopes`` scopes
    legitimately compile the train step plus its small satellite programs
    and are recorded as ``stats.warmup_compiles``; compiles in any LATER
    scope land in ``stats.recompiles`` and mean an input aval is
    drifting. The default warm-up is TWO scopes, not one: the step, rng
    fold-in etc. compile in scope 0, but the Logger's on-device metric
    accumulate (``prev + v``) first runs — and compiles — at push #2,
    i.e. in scope 1. :meth:`check` enforces
    ``stats.recompiles <= max_steady_recompiles`` (default 0 — a
    steady-state loop never compiles).
    """

    def __init__(
        self,
        max_steady_recompiles: int = 0,
        raise_on_violation: bool = True,
        warmup_scopes: int = 2,
    ) -> None:
        self.max_steady_recompiles = max_steady_recompiles
        self.raise_on_violation = raise_on_violation
        self.warmup_scopes = warmup_scopes
        self.stats = GuardStats()
        self._watchdog = RecompileWatchdog()
        self._entry: Optional[_ScopeEntry] = None
        self._scopes = 0

    def __enter__(self) -> "StepGuard":
        self._watchdog.__enter__()
        self._watchdog.disarm()
        # Patches install ONCE here and stay (disarmed) between scopes:
        # per-step install/uninstall would put ~20 setattrs on the exact
        # loop this subsystem exists to keep host-light.
        self._entry = _push_scope(
            self.stats, self.raise_on_violation, armed=False
        )
        return self

    def __exit__(self, *exc) -> None:
        if self._entry is not None:
            _pop_scope(self._entry)
            self._entry = None
        self._watchdog.__exit__(*exc)

    @contextlib.contextmanager
    def scope(self) -> Iterator[None]:
        """One guarded steady-state iteration."""
        before = self._watchdog.count
        self._watchdog.arm()
        self._entry.armed = True
        try:
            with jax.transfer_guard_device_to_host("disallow"):
                yield
        finally:
            self._entry.armed = False
            self._watchdog.disarm()
            delta = self._watchdog.count - before
            if self._scopes < self.warmup_scopes:
                self.stats.warmup_compiles += delta
            else:
                self.stats.recompiles += delta
            self._scopes += 1

    def check(self) -> None:
        """Enforce the steady-state compile budget over all scopes so far."""
        if self.stats.recompiles > self.max_steady_recompiles:
            raise GuardViolation(
                f"train step recompiled {self.stats.recompiles}x after its "
                f"warm-up scope (budget {self.max_steady_recompiles}) — an "
                "input aval (shape, dtype or sharding) is drifting between "
                "steps"
            )
