"""Correctness tooling for the sync-free, recompile-free hot path.

Two layers, one invariant set:

- **graftlint** (``lint.py`` + ``rules/``): AST-based static analysis
  with JAX-specific rules JGL001-JGL007 — host syncs in traced code,
  donation-less state-carrying jits, trace-time nondeterminism, Python
  control flow on tracers, dtype hygiene in the numeric core,
  undeclared PartitionSpec axes, and swallowed exceptions in the
  fault-handling layers (resilience//training//data/). Run it with
  ``python -m raft_ncup_tpu.analysis [paths...]``; audited exceptions
  live in ``allowlist.txt``. Pure stdlib — safe on hosts with a wedged
  accelerator backend.
- **runtime guards** (``guards.py``): ``forbid_host_transfers`` /
  ``RecompileWatchdog`` / ``max_recompiles`` / ``strict_guards`` assert
  the same invariants live, on the actual train/bench loop (pytest
  fixtures in tests/conftest.py; ``--strict_guards`` in train.py;
  counter rows in bench.py).

The linter proves the invariants statically; the guards catch what
static analysis cannot see (dispatch-time transfers, shape-drift
recompiles). docs/ANALYSIS.md documents both layers.

This module intentionally does NOT import ``guards`` (which imports
jax) at package import: the lint CLI must not initialize a backend.
"""

from __future__ import annotations

from raft_ncup_tpu.analysis.astutil import Finding  # noqa: F401
from raft_ncup_tpu.analysis.lint import (  # noqa: F401
    LintResult,
    load_allowlist,
    main,
    run_lint,
)

__all__ = ["Finding", "LintResult", "load_allowlist", "main", "run_lint"]


def __getattr__(name: str):
    # Lazy: `from raft_ncup_tpu.analysis import guards` works without the
    # lint CLI paying the jax import.
    if name == "guards":
        import importlib

        return importlib.import_module("raft_ncup_tpu.analysis.guards")
    raise AttributeError(name)
