"""``python -m raft_ncup_tpu.analysis`` — graftlint CLI entry point."""

import sys

from raft_ncup_tpu.analysis.lint import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
