"""graftlint engine: file discovery, allowlist, rule driving, CLI.

Run as ``python -m raft_ncup_tpu.analysis [paths...]`` (see
``scripts/lint.sh``); the acceptance contract is that
``python -m raft_ncup_tpu.analysis raft_ncup_tpu/`` exits 0 on the
shipped tree. Pure stdlib — linting must work (and stay fast) on hosts
where importing jax would initialize a wedged accelerator backend.

Allowlist format (default file: ``raft_ncup_tpu/analysis/allowlist.txt``)
— one audited exception per line::

    path/suffix.py::RULE::qualname  # justification (mandatory)

``qualname`` is the finding's enclosing-function path (``<module>`` at
top level) or ``*`` to cover the whole file for that rule. The path
matches by suffix so the file works from any checkout root. Entries
without a ``#`` justification are a configuration error (exit 2);
entries that suppress nothing are reported as stale (exit 1 under
``--strict-allowlist``, warning otherwise).
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Optional, Sequence

from raft_ncup_tpu.analysis.astutil import (
    Finding,
    ModuleContext,
    attach_parents,
    collect_aliases,
    dotted_name,
)
from raft_ncup_tpu.analysis.rules import ALL_RULES, RULES_BY_ID

DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(__file__), "allowlist.txt")

_SKIP_DIRS = frozenset({"__pycache__", ".git", ".venv", "node_modules"})


class AllowlistError(Exception):
    """Malformed allowlist (bad syntax or missing justification)."""


@dataclass
class AllowEntry:
    path_suffix: str
    rule: str
    qual: str
    justification: str
    lineno: int
    used: bool = False

    def matches(self, f: Finding) -> bool:
        path = f.path.replace("\\", "/")
        if not (path == self.path_suffix or path.endswith("/" + self.path_suffix)):
            return False
        if self.rule != "*" and self.rule != f.rule:
            return False
        return self.qual in ("*", f.qualname)

    def render(self) -> str:
        return f"{self.path_suffix}::{self.rule}::{self.qual} (line {self.lineno})"


@dataclass
class LintResult:
    findings: list = field(default_factory=list)  # unsuppressed, reportable
    suppressed: list = field(default_factory=list)  # (finding, entry)
    stale_entries: list = field(default_factory=list)
    parse_errors: list = field(default_factory=list)  # (path, message)
    files_checked: int = 0
    declared_axes: frozenset = frozenset()


def load_allowlist(path: str) -> list:
    entries = []
    with open(path, encoding="utf-8") as fh:
        for lineno, raw in enumerate(fh, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, sep, justification = line.partition("#")
            justification = justification.strip()
            if not sep or not justification:
                raise AllowlistError(
                    f"{path}:{lineno}: allowlist entry has no justification "
                    "(append `# why this is an audited exception`)"
                )
            parts = [p.strip() for p in body.strip().split("::")]
            if len(parts) == 2:
                parts.append("*")
            if len(parts) != 3 or not all(parts[:2]):
                raise AllowlistError(
                    f"{path}:{lineno}: expected `path::RULE[::qualname]  "
                    f"# justification`, got {body.strip()!r}"
                )
            path_suffix, rule, qual = parts
            if rule != "*" and rule not in RULES_BY_ID:
                raise AllowlistError(
                    f"{path}:{lineno}: unknown rule {rule!r} "
                    f"(known: {sorted(RULES_BY_ID)})"
                )
            entries.append(
                AllowEntry(
                    path_suffix.replace("\\", "/"),
                    rule,
                    qual or "*",
                    justification,
                    lineno,
                )
            )
    return entries


def find_py_files(paths: Sequence[str]) -> list:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in _SKIP_DIRS and not d.startswith(".")
                )
                out.extend(
                    os.path.join(root, n) for n in sorted(names)
                    if n.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(f"not a directory or .py file: {p}")
    # de-dupe while preserving order (overlapping path arguments)
    seen: set = set()
    uniq = []
    for f in out:
        key = os.path.abspath(f)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


def discover_declared_axes(trees: dict) -> frozenset:
    """Mesh axis names declared anywhere in the linted set: literal string
    tuples passed to ``jax.sharding.Mesh`` (positionally or via
    ``axis_names=``). parallel/mesh.py is the only production declarer."""
    axes: set = set()
    for tree, aliases in trees.values():
        axes |= _axes_in_tree(tree, aliases)
    return frozenset(axes)


def production_declared_axes() -> frozenset:
    """Axis names declared by the package's production mesh declarer
    (``parallel/mesh.py``), parsed directly so JGL006 has a judgment
    baseline even when the linted set does not include it — e.g.
    linting ``inference/``, ``serving/``, or ``streaming/`` standalone.
    Before this fallback those runs had no declaration in scope, the
    rule stayed silent, and a typo'd PartitionSpec axis in a serving
    module would silently replicate (the exact hazard JGL006 exists
    for). Returns the empty set when the file is missing/unparseable
    (vendored partial checkouts): silence, never a crash."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "parallel", "mesh.py"
    )
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return frozenset()
    return frozenset(_axes_in_tree(tree, collect_aliases(tree)))


def _axes_in_tree(tree, aliases) -> set:
    axes: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func, aliases)
        if dn is None or dn.split(".")[-1] != "Mesh":
            continue
        cand = None
        if len(node.args) >= 2:
            cand = node.args[1]
        for kw in node.keywords:
            if kw.arg == "axis_names":
                cand = kw.value
        axes |= _axis_literals(cand)
    return axes


def _axis_literals(node) -> set:
    """Literal axis-name strings reachable from one ``Mesh`` axis-names
    expression. Descends conditional expressions — the production
    declarer (parallel/mesh.py) declares its pipeline axis as
    ``("data", "spatial", "pipe") if pipe > 1 else ("data", "spatial")``
    and BOTH branches are real declarations (whichever the runtime
    picks, a PartitionSpec naming 'pipe' is judged against a mesh that
    can legally carry it)."""
    out: set = set()
    if isinstance(node, ast.IfExp):
        out |= _axis_literals(node.body)
        out |= _axis_literals(node.orelse)
        return out
    elts = (
        node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    )
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.add(e.value)
    return out


def run_lint(
    paths: Sequence[str],
    allowlist_path: Optional[str] = None,
    select: Optional[Sequence[str]] = None,
    declared_axes: Optional[frozenset] = None,
) -> LintResult:
    """Lint ``paths`` and return the full result (the CLI renders it).

    ``select`` restricts to the given rule IDs. ``declared_axes``
    overrides mesh-axis discovery (fixture tests use this).
    """
    result = LintResult()
    entries = []
    if allowlist_path:
        entries = load_allowlist(allowlist_path)

    rules = ALL_RULES
    if select:
        unknown = set(select) - set(RULES_BY_ID)
        if unknown:
            raise AllowlistError(f"unknown rule id(s): {sorted(unknown)}")
        rules = tuple(RULES_BY_ID[r] for r in sorted(select))

    # Pass 1: parse everything once (axis discovery and the
    # whole-program graph need the full set before any rule runs).
    trees: dict = {}
    for path in find_py_files(paths):
        display = path.replace("\\", "/")
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            result.parse_errors.append((display, str(e)))
            continue
        attach_parents(tree)
        trees[display] = (tree, collect_aliases(tree))
    result.files_checked = len(trees)
    if declared_axes is not None:
        result.declared_axes = declared_axes
    else:
        result.declared_axes = discover_declared_axes(trees)
        if not result.declared_axes:
            # No Mesh declaration in the linted set (standalone lint of
            # inference//serving//streaming/): fall back to the
            # production declarer so PartitionSpec axes there are still
            # judged instead of silently skipped.
            result.declared_axes = production_declared_axes()

    # Pass 2: per-module rules, then whole-program rules once over the
    # full graph (JGL011+ expose check_project instead of check).
    from raft_ncup_tpu.analysis.astutil import TracedIndex

    module_rules = tuple(r for r in rules if hasattr(r, "check"))
    project_rules = tuple(r for r in rules if hasattr(r, "check_project"))

    def _record(finding) -> None:
        entry = next((e for e in entries if e.matches(finding)), None)
        if entry is not None:
            entry.used = True
            result.suppressed.append((finding, entry))
        else:
            result.findings.append(finding)

    for display, (tree, aliases) in trees.items():
        ctx = ModuleContext(
            path=display,
            tree=tree,
            aliases=aliases,
            traced=TracedIndex(tree, aliases),
            declared_axes=result.declared_axes,
        )
        for rule in module_rules:
            for finding in rule.check(ctx):
                _record(finding)

    if project_rules:
        from raft_ncup_tpu.analysis.project import ProjectIndex

        proj = ProjectIndex.build(trees)
        for rule in project_rules:
            for finding in rule.check_project(proj):
                _record(finding)

    # Staleness is only decidable for entries whose rule actually ran:
    # under --select, an entry for a deselected rule (or a "*" entry) is
    # unused because the rule was skipped, not because the finding went
    # away — marking it stale would fail lint.sh --select spuriously.
    if select:
        ran = {r.RULE_ID for r in rules}
        result.stale_entries = [
            e for e in entries if not e.used and e.rule in ran
        ]
    else:
        result.stale_entries = [e for e in entries if not e.used]
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result


def render_json(result: LintResult, failed: bool) -> dict:
    """The ``--format json`` document. STABLE schema (pinned by
    tests/test_lint.py): CI and future tooling diff lint runs on it, so
    fields are only ever added, never renamed or removed. Findings are
    the union of reported and allowlist-suppressed ones, each carrying a
    ``suppressed`` flag (suppressed entries add the justification)."""
    findings = [
        {
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "col": f.col,
            "qualname": f.qualname,
            "message": f.message,
            "suppressed": False,
        }
        for f in result.findings
    ] + [
        {
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "col": f.col,
            "qualname": f.qualname,
            "message": f.message,
            "suppressed": True,
            "justification": entry.justification,
        }
        for f, entry in result.suppressed
    ]
    findings.sort(
        key=lambda d: (d["path"], d["line"], d["col"], d["rule"])
    )
    return {
        "files_checked": result.files_checked,
        "findings": findings,
        "parse_errors": [
            {"path": p, "message": m} for p, m in result.parse_errors
        ],
        "stale_allowlist_entries": [
            e.render() for e in result.stale_entries
        ],
        "exit_code": 1 if failed else 0,
    }


def _print_catalog() -> None:
    print("graftlint rule catalog:")
    for mod in ALL_RULES:
        print(f"  {mod.RULE_ID}  {mod.SUMMARY}")


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m raft_ncup_tpu.analysis",
        description="graftlint: JAX-aware static analysis enforcing the "
        "sync-free, recompile-free hot path, honest error handling, and "
        "the cross-module control-plane contracts — lock discipline, "
        "wire-protocol keys, the env-knob registry (rules "
        "JGL001-JGL013).",
    )
    parser.add_argument("paths", nargs="*", default=["raft_ncup_tpu"],
                        help="files/directories to lint (default: the "
                        "package)")
    parser.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                        help="audited-exception file (default: "
                        "%(default)s)")
    parser.add_argument("--no-allowlist", action="store_true",
                        help="report raw findings, ignoring the allowlist")
    parser.add_argument("--select", nargs="+", metavar="RULE",
                        help="run only these rule IDs")
    parser.add_argument("--strict-allowlist", action="store_true",
                        help="fail when an allowlist entry suppresses "
                        "nothing (stale)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print allowlisted findings with their "
                        "justifications")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text",
                        help="output format; 'json' emits one machine-"
                        "readable document (schema pinned in "
                        "tests/test_lint.py) for CI diffing")
    args = parser.parse_args(argv)

    if args.list_rules:
        _print_catalog()
        return 0

    allowlist = None if args.no_allowlist else args.allowlist
    if allowlist and not os.path.exists(allowlist):
        allowlist = None  # a missing default allowlist is simply empty
    try:
        result = run_lint(args.paths, allowlist, args.select)
    except (AllowlistError, FileNotFoundError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    failed = bool(
        result.findings
        or result.parse_errors
        or (args.strict_allowlist and result.stale_entries)
    )

    if args.format == "json":
        print(json.dumps(render_json(result, failed), indent=2,
                         sort_keys=True))
        return 1 if failed else 0

    for path, msg in result.parse_errors:
        print(f"{path}: parse error: {msg}")
    for f in result.findings:
        print(f.render())
    if args.show_suppressed:
        for f, entry in result.suppressed:
            print(f"[allowed] {f.render()}  # {entry.justification}")
    for entry in result.stale_entries:
        stream = sys.stdout if args.strict_allowlist else sys.stderr
        print(
            f"graftlint: stale allowlist entry suppresses nothing: "
            f"{entry.render()}",
            file=stream,
        )

    print(
        f"graftlint: {result.files_checked} files, "
        f"{len(result.findings)} finding(s), "
        f"{len(result.suppressed)} allowlisted, "
        f"{len(result.stale_entries)} stale allowlist entr(y/ies)",
        file=sys.stderr,
    )
    return 1 if failed else 0
