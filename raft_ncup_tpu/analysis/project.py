"""Whole-program symbol/usage graph for graftlint's cross-module rules.

Per-module rules (JGL001-JGL010) see one file at a time; the invariants
the fleet/observability control plane lives by are cross-file — an
attribute is written under ``self._lock`` in one method and read without
it in another, a wire header key is produced in ``serve.py`` and
consumed in ``fleet/router.py``, an env knob is read in ``bench.py`` and
declared (or not) in ``utils/knobs.py``. :class:`ProjectIndex` walks
every parsed module ONCE and collects the per-site facts those rules
need:

- classes that own a ``threading.Lock/RLock/Condition`` instance
  attribute, with every ``self.<attr>`` data access classified
  read/write and tagged with its lexical lock-guard state, enclosing
  method, and whether it sits directly in ``__init__`` (construction
  time, single-threaded by definition) or inside a nested function
  (a closure runs later — a ``with self._lock`` around its *definition*
  guards nothing);
- the per-class method call graph over ``self._method(...)`` calls plus
  the set of methods whose *references* escape (``target=self._loop``)
  — rule JGL011 runs an "always locked" fixpoint over it;
- cross-module attribute accesses to private (``_name``) attributes,
  with the ``with``-held dotted expressions at the site, so
  ``with router._lock: router._pending[...]`` counts as guarded;
- wire header key writes (constant keys of any dict literal that
  carries a ``"kind"`` key — every frame does — and ``header[...] =``
  store subscripts) and reads (``header.get("k")`` / bare subscripts)
  for JGL012;
- ``os.environ`` reads with their names resolved through module-level
  string constants and import aliases (``os.environ.get(TELEMETRY_ENV)``
  resolves even when the constant lives in another module), plus every
  ``knob_*`` getter call and ``Knob(...)`` declaration for JGL013.

Like the rest of the analysis package: pure stdlib, syntactic only.
The guard analysis is deliberately lexical — ``with self._lock:`` in
the same function body, or a call reached only from such bodies — and
its known blind spots (locks passed across objects, ``Condition.wait``
temporarily releasing) are documented in docs/ANALYSIS.md; the
allowlist absorbs what the approximation cannot see.

Trees handed to :meth:`ProjectIndex.build` must already have parents
attached (``astutil.attach_parents``) — the engine does this in its
parse pass.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from raft_ncup_tpu.analysis.astutil import dotted_name, parent, qualname

# Callables whose result is a lock-like object worth guard-tracking.
_LOCK_FACTORIES = frozenset(
    {"threading.Lock", "threading.RLock", "threading.Condition"}
)
_LOCK_TAILS = frozenset({"Lock", "RLock", "Condition"})

# Method calls on a container attribute that mutate it in place:
# ``self._pending.pop(...)`` is a WRITE to ``_pending`` for lock
# discipline even though the attribute itself is only loaded.
MUTATOR_METHODS = frozenset(
    {
        "add", "append", "appendleft", "clear", "discard", "extend",
        "insert", "pop", "popleft", "put", "remove", "setdefault",
        "update",
    }
)

# Variable names (last dotted segment) that hold a decoded wire header
# on the consumer side. Producer-side writes additionally come from
# dict literals carrying a "kind" key.
WIRE_READ_BASES = frozenset({"header", "hdr", "reply", "republish"})
WIRE_WRITE_BASES = frozenset({"header", "reply"})

# The wire layer strips/injects this key itself; it is reserved, not a
# protocol field (wire.send_msg rejects it in user headers).
WIRE_RESERVED_KEYS = frozenset({"arrays"})

KNOB_GETTERS = frozenset(
    {
        "knob_raw", "knob_str", "knob_int", "knob_float", "knob_flag",
        "knob_enabled", "knob_positive_int",
    }
)


@dataclass(frozen=True)
class Site:
    """One source location, pre-rendered for Finding construction."""

    path: str
    line: int
    col: int
    qual: str


@dataclass
class AttrAccess:
    """One ``self.<attr>`` data access inside a lock-owning class."""

    attr: str
    kind: str  # "read" | "write"
    guarded: bool  # lexically inside `with self.<lock>` in this function
    method: str  # directly-enclosing method of the class ("" at class level)
    in_init: bool  # directly in __init__'s body (not in a nested def)
    in_nested: bool  # inside a def/lambda nested in the method (closure)
    site: Site = None  # type: ignore[assignment]


@dataclass
class CallEvent:
    """One ``self.<method>(...)`` call or escaped method reference."""

    callee: str
    guarded: bool
    method: str
    in_init: bool
    in_nested: bool
    is_call: bool  # False: the method object escaped (e.g. thread target)
    site: Site = None  # type: ignore[assignment]


@dataclass
class ExtAccess:
    """A private-attribute access through something other than ``self``
    (``router._pending``, ``self.sup._dead_hosts``)."""

    attr: str
    kind: str  # "read" | "write"
    base: Optional[str]  # dotted base expression, None when dynamic
    held: frozenset  # dotted `with` expressions held at the site
    site: Site = None  # type: ignore[assignment]


@dataclass
class ClassInfo:
    """One class that owns at least one lock-like instance attribute."""

    name: str
    path: str
    lock_attrs: frozenset
    methods: frozenset = frozenset()
    accesses: List[AttrAccess] = field(default_factory=list)
    call_events: List[CallEvent] = field(default_factory=list)


@dataclass
class EnvRead:
    """One ``os.environ`` read (``.get``/``[]``/``getenv``/``in``)."""

    name: Optional[str]  # resolved constant name; None when dynamic
    form: str  # "get" | "subscript" | "getenv" | "in"
    site: Site = None  # type: ignore[assignment]


@dataclass
class KnobCall:
    """One ``knob_*`` getter call (utils/knobs.py API)."""

    getter: str
    name: Optional[str]  # resolved constant first argument
    site: Site = None  # type: ignore[assignment]


@dataclass
class KnobDecl:
    """One ``Knob("NAME", ...)`` declaration."""

    name: str
    site: Site = None  # type: ignore[assignment]


@dataclass
class WireKey:
    """One wire header key production or consumption site."""

    key: str
    kind: str  # "write" | "read_get" | "read_subscript"
    site: Site = None  # type: ignore[assignment]


class _Ref:
    """A not-yet-resolved constant reference (``Name``/``Attribute``
    pointing at a module-level string constant, possibly in another
    module). Resolved after every module has been walked."""

    __slots__ = ("fq",)

    def __init__(self, fq: str):
        self.fq = fq


@dataclass
class ProjectIndex:
    """Everything the cross-module rules see, from one walk of every
    parsed module. Built by :meth:`build`; all ``name``/``key`` fields
    are fully resolved strings (or None for dynamic expressions)."""

    paths: frozenset = frozenset()
    classes: List[ClassInfo] = field(default_factory=list)
    ext_accesses: List[ExtAccess] = field(default_factory=list)
    env_reads: List[EnvRead] = field(default_factory=list)
    knob_calls: List[KnobCall] = field(default_factory=list)
    knob_decls: List[KnobDecl] = field(default_factory=list)
    wire_keys: List[WireKey] = field(default_factory=list)
    # module dotted path -> {CONST_NAME: string value}
    constants: Dict[str, Dict[str, str]] = field(default_factory=dict)

    # ------------------------------------------------------------ build

    @classmethod
    def build(cls, trees: Dict[str, Tuple[ast.AST, dict]]) -> "ProjectIndex":
        proj = cls(paths=frozenset(trees))
        # Module-level string constants first: name resolution inside
        # the main walk may reference a constant declared later in the
        # same module (or in a module walked later).
        for display, (tree, _aliases) in trees.items():
            proj.constants[_module_dotted(display)] = _module_constants(tree)
        for display, (tree, aliases) in trees.items():
            _ModuleWalker(proj, display, tree, aliases).walk()
        proj._resolve_refs()
        return proj

    def _resolve_refs(self) -> None:
        for read in self.env_reads:
            read.name = self._resolve(read.name)
        for call in self.knob_calls:
            call.name = self._resolve(call.name)
        for wk in self.wire_keys:
            wk.key = self._resolve(wk.key)
        self.wire_keys = [w for w in self.wire_keys if w.key is not None]

    def _resolve(self, value):
        if not isinstance(value, _Ref):
            return value
        module, _, name = value.fq.rpartition(".")
        if not module:
            return None
        for mod_dotted, consts in self.constants.items():
            if mod_dotted == module or mod_dotted.endswith("." + module):
                if name in consts:
                    return consts[name]
        return None


def _module_dotted(display: str) -> str:
    p = display.replace("\\", "/")
    if p.endswith(".py"):
        p = p[: -len(".py")]
    return p.strip("/").replace("/", ".")


def _module_constants(tree: ast.AST) -> Dict[str, str]:
    consts: Dict[str, str] = {}
    for stmt in getattr(tree, "body", ()):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            if (
                isinstance(tgt, ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)
            ):
                consts[tgt.id] = stmt.value.value
    return consts


def _basename(display: str) -> str:
    return display.replace("\\", "/").rsplit("/", 1)[-1]


@dataclass
class _State:
    """Lexical walk state threaded through one module's AST."""

    cls: Optional[ClassInfo] = None
    method: str = ""
    fn_depth: int = -1  # -1: not inside any function
    held_locks: frozenset = frozenset()  # self lock attrs held here
    held_dotted: frozenset = frozenset()  # all dotted `with` exprs held


class _ModuleWalker:
    def __init__(self, proj: ProjectIndex, display: str,
                 tree: ast.AST, aliases: dict):
        self.proj = proj
        self.display = display
        self.tree = tree
        self.aliases = aliases
        self.local_consts = proj.constants.get(_module_dotted(display), {})

    # ------------------------------------------------------- utilities

    def _site(self, node: ast.AST) -> Site:
        return Site(
            path=self.display,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            qual=qualname(node),
        )

    def _const_or_ref(self, node: Optional[ast.AST]):
        """A string value for ``node``: literal, local module constant,
        or a :class:`_Ref` to another module's constant; None when the
        expression is dynamic."""
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, str) else None
        if isinstance(node, ast.Name):
            if node.id in self.local_consts:
                return self.local_consts[node.id]
            fq = self.aliases.get(node.id)
            return _Ref(fq) if fq and "." in fq else None
        if isinstance(node, ast.Attribute):
            fq = dotted_name(node, self.aliases)
            return _Ref(fq) if fq else None
        return None

    def _is_lock_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        dn = dotted_name(node.func, self.aliases)
        if dn is None:
            return False
        return dn in _LOCK_FACTORIES or dn.split(".")[-1] in _LOCK_TAILS

    # ------------------------------------------------------------ walk

    def walk(self) -> None:
        self._visit_body(self.tree.body, _State())

    def _visit_body(self, body, st: _State) -> None:
        for stmt in body:
            self._visit(stmt, st)

    def _visit(self, node: ast.AST, st: _State) -> None:
        if isinstance(node, ast.ClassDef):
            self._enter_class(node, st)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            self._enter_function(node, st)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._enter_with(node, st)
            return
        self._collect(node, st)
        for child in ast.iter_child_nodes(node):
            self._visit(child, st)

    def _enter_class(self, node: ast.ClassDef, st: _State) -> None:
        lock_attrs = self._scan_lock_attrs(node)
        if not lock_attrs:
            # Still walk the body for env/wire facts; a nested class in
            # a method keeps the outer class context deliberately off.
            inner = _State()
            self._visit_body(node.body, inner)
            return
        methods = frozenset(
            s.name for s in node.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        )
        info = ClassInfo(
            name=node.name, path=self.display,
            lock_attrs=lock_attrs, methods=methods,
        )
        self.proj.classes.append(info)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mst = _State(cls=info, method=stmt.name, fn_depth=0)
                for deco in stmt.decorator_list:
                    self._visit(deco, _State())
                self._visit_defaults(stmt, _State())
                self._visit_body(stmt.body, mst)
            else:
                self._visit(stmt, _State(cls=info, method="", fn_depth=-1))

    def _scan_lock_attrs(self, node: ast.ClassDef) -> frozenset:
        """Attributes of ``node`` bound to a lock-like object: any
        ``self.X = threading.Lock()`` in a method, or a class-level
        ``X = threading.Lock()`` (shared lock)."""
        locks = set()
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            if not self._is_lock_call(sub.value):
                continue
            for tgt in sub.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    locks.add(tgt.attr)
                elif isinstance(tgt, ast.Name):
                    # class-level only: sub's parent chain is not
                    # checked — a local `lock = Lock()` in a method is
                    # not a self attribute and never matches self.X.
                    locks.add(tgt.id)
        return frozenset(locks)

    def _visit_defaults(self, node, st: _State) -> None:
        """Default argument values evaluate at def time, in the
        enclosing scope — walk them with the OUTER state."""
        a = getattr(node, "args", None)
        if a is None:
            return
        for d in list(a.defaults) + list(a.kw_defaults):
            if d is not None:
                self._visit(d, st)

    def _enter_function(self, node, st: _State) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                self._visit(deco, st)
        self._visit_defaults(node, st)
        if isinstance(node, ast.Lambda):
            # Lambdas in this codebase are sort keys and tiny adapters
            # that run where they are written (min(..., key=lambda ...))
            # — treat them as executing at the definition site, locks
            # included. A lambda STORED for later execution is the rare
            # case and allowlist material.
            self._visit(node.body, st)
            return
        # A nested def's body runs LATER: whatever locks are held
        # around its definition are not held at its call time.
        inner = _State(
            cls=st.cls,
            method=st.method,
            fn_depth=(st.fn_depth + 1) if st.fn_depth >= 0 else -1,
        )
        self._visit_body(node.body, inner)

    def _enter_with(self, node, st: _State) -> None:
        held_locks = set(st.held_locks)
        held_dotted = set(st.held_dotted)
        for item in node.items:
            self._visit(item.context_expr, st)
            dn = dotted_name(item.context_expr, {})
            if dn is None:
                continue
            held_dotted.add(dn)
            if st.cls is not None and dn.startswith("self."):
                attr = dn[len("self."):]
                if attr in st.cls.lock_attrs:
                    held_locks.add(attr)
        inner = _State(
            cls=st.cls, method=st.method, fn_depth=st.fn_depth,
            held_locks=frozenset(held_locks),
            held_dotted=frozenset(held_dotted),
        )
        self._visit_body(node.body, inner)

    # ------------------------------------------------------ collection

    def _collect(self, node: ast.AST, st: _State) -> None:
        if isinstance(node, ast.Call):
            self._collect_call(node, st)
        elif isinstance(node, ast.Subscript):
            self._collect_subscript(node, st)
        elif isinstance(node, ast.Compare):
            self._collect_compare(node)
        elif isinstance(node, ast.Dict):
            self._collect_dict(node)
        elif isinstance(node, ast.Attribute):
            self._collect_attribute(node, st)

    # -- env / knobs

    def _collect_call(self, node: ast.Call, st: _State) -> None:
        dn = dotted_name(node.func, self.aliases)
        if dn is not None:
            tail = dn.split(".")[-1]
            if dn in ("os.environ.get", "os.getenv"):
                self.proj.env_reads.append(EnvRead(
                    name=self._const_or_ref(
                        node.args[0] if node.args else None
                    ),
                    form="getenv" if dn == "os.getenv" else "get",
                    site=self._site(node),
                ))
            elif tail in KNOB_GETTERS:
                self.proj.knob_calls.append(KnobCall(
                    getter=tail,
                    name=self._const_or_ref(
                        node.args[0] if node.args else None
                    ),
                    site=self._site(node),
                ))
            elif tail == "Knob" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    self.proj.knob_decls.append(KnobDecl(
                        name=first.value, site=self._site(node),
                    ))
        # header.get("k") consumer reads.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and node.args
        ):
            base = dotted_name(node.func.value, {})
            if base and base.split(".")[-1] in WIRE_READ_BASES:
                key = self._const_or_ref(node.args[0])
                if key is not None:
                    self.proj.wire_keys.append(WireKey(
                        key=key, kind="read_get", site=self._site(node),
                    ))

    def _collect_subscript(self, node: ast.Subscript, st: _State) -> None:
        base = dotted_name(node.value, {})
        if base == "os.environ":
            if isinstance(node.ctx, ast.Load):
                self.proj.env_reads.append(EnvRead(
                    name=self._const_or_ref(node.slice),
                    form="subscript",
                    site=self._site(node),
                ))
            return
        if base is None:
            return
        tail = base.split(".")[-1]
        key = self._const_or_ref(node.slice)
        if key is None:
            return
        if isinstance(node.ctx, ast.Store) and tail in WIRE_WRITE_BASES:
            self.proj.wire_keys.append(WireKey(
                key=key, kind="write", site=self._site(node),
            ))
        elif isinstance(node.ctx, ast.Load) and tail in WIRE_READ_BASES:
            self.proj.wire_keys.append(WireKey(
                key=key, kind="read_subscript", site=self._site(node),
            ))

    def _collect_compare(self, node: ast.Compare) -> None:
        if len(node.ops) != 1 or not isinstance(node.ops[0], ast.In):
            return
        if dotted_name(node.comparators[0], self.aliases) != "os.environ":
            return
        self.proj.env_reads.append(EnvRead(
            name=self._const_or_ref(node.left),
            form="in",
            site=self._site(node),
        ))

    def _collect_dict(self, node: ast.Dict) -> None:
        keys = []
        has_kind = False
        for k in node.keys:
            if k is None:  # **spread
                continue
            key = self._const_or_ref(k)
            if key is None:
                continue
            keys.append((key, k))
            if key == "kind":
                has_kind = True
        if not has_kind:
            return
        for key, knode in keys:
            self.proj.wire_keys.append(WireKey(
                key=key, kind="write", site=self._site(knode),
            ))

    # -- lock discipline

    def _collect_attribute(self, node: ast.Attribute, st: _State) -> None:
        is_self = isinstance(node.value, ast.Name) and node.value.id == "self"
        par = parent(node)

        if is_self and st.cls is not None:
            info = st.cls
            attr = node.attr
            if attr in info.lock_attrs:
                return
            guarded = bool(st.held_locks)
            in_init = st.method == "__init__" and st.fn_depth == 0
            in_nested = st.fn_depth > 0
            if attr in info.methods:
                is_call = isinstance(par, ast.Call) and par.func is node
                info.call_events.append(CallEvent(
                    callee=attr, guarded=guarded, method=st.method,
                    in_init=in_init, in_nested=in_nested,
                    is_call=is_call, site=self._site(node),
                ))
                return
            kind = self._access_kind(node, par)
            if kind is None:
                return
            info.accesses.append(AttrAccess(
                attr=attr, kind=kind, guarded=guarded,
                method=st.method, in_init=in_init, in_nested=in_nested,
                site=self._site(node),
            ))
            return

        # Cross-object access to a private attribute.
        if is_self:
            return
        attr = node.attr
        if not attr.startswith("_") or attr.startswith("__"):
            return
        kind = self._access_kind(node, par)
        if kind is None:
            return
        self.proj.ext_accesses.append(ExtAccess(
            attr=attr,
            kind=kind,
            base=dotted_name(node.value, {}),
            held=st.held_dotted,
            site=self._site(node),
        ))

    @staticmethod
    def _access_kind(node: ast.Attribute, par) -> Optional[str]:
        """Classify one attribute node as a data read or write; None for
        non-data uses (a method call on the attribute that does not
        mutate, handled as "read"; the call's own func attribute)."""
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return "write"
        # self.x[...] = / del self.x[...] / self.x[...] += ...
        if (
            isinstance(par, ast.Subscript)
            and par.value is node
            and isinstance(par.ctx, (ast.Store, ast.Del))
        ):
            return "write"
        # self.x.append(...) and friends.
        if isinstance(par, ast.Attribute) and par.value is node:
            grand = parent(par)
            if (
                isinstance(grand, ast.Call)
                and grand.func is par
                and par.attr in MUTATOR_METHODS
            ):
                return "write"
        return "read"
