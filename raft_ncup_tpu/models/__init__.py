from raft_ncup_tpu.models.raft import RAFT, get_model  # noqa: F401
