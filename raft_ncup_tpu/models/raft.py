"""RAFT / RAFT-NCUP model orchestration, TPU-first.

Rather than one monolithic module, the model is a bundle of linen
components (fnet/cnet/update_block/upsampler) plus a pure-JAX forward that
wires them together. This keeps the recurrent refinement a plain
``jax.lax.scan`` — one compiled iteration body regardless of iteration
count — with the GRU hidden state, query coordinates and (when BatchNorm
lives inside the upsampler) mutable batch statistics as the scan carry.
Gradient rematerialization wraps the body during training so the 12
full-resolution NCUP passes don't hold live activations.

Reference call structure: core/raft.py:87-143 (baseline) and
core/raft_nc_dbl.py:115-173 (NCUP variant: mask head removed, per-iter
nearest x2 -> NCUP x4 -> values x8).

The refinement is COMPOSABLE (inference/pipe_schedule.py; docs/SHARDING.md
"Pipeline axis"): ``encode`` produces a segment carry (GRU state, query
coordinates, context features and the correlation feature maps for one
micro-batch), ``refine_segment`` advances it by any contiguous block of
iterations, and ``finalize`` upsamples the final carry — so N iterations
can run as one monolithic scan (``apply``, unchanged semantics) or as S
scan segments on S pipeline stages with the carry handed between device
groups. All three share the same step body and upsampling head as
``apply``, so segmented and monolithic execution agree by construction.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

# jax.shard_map was promoted out of jax.experimental after 0.4.x; resolve
# whichever this jax ships so the spatially-sharded corr lookup works on
# both (the call sites use the keyword form, identical in both APIs).
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map

from raft_ncup_tpu.config import ModelConfig
from raft_ncup_tpu.nn.extractor import Encoder
from raft_ncup_tpu.nn.update import BasicUpdateBlock, SmallUpdateBlock
from raft_ncup_tpu.nn.upsampler import build_upsampler
from raft_ncup_tpu.ops.corr import (
    build_corr_pyramid,
    corr_lookup,
    corr_lookup_onthefly,
)
from raft_ncup_tpu.ops.geometry import (
    convex_upsample,
    coords_grid,
    upflow,
    upsample_nearest,
)


class RAFT:
    """Model bundle + functional forward.

    Usage::

        model = RAFT(cfg)
        variables = model.init(rng, (1, 368, 768, 3))
        flows = model.apply(variables, img1, img2, iters=12, train=True)
        flow_lr, flow_up = model.apply(variables, img1, img2, iters=32,
                                       test_mode=True)

    ``variables`` is ``{'params': {...}, 'batch_stats': {...}}``; images are
    NHWC uint8-range float32 in [0, 255] (normalization happens inside, as
    in reference: core/raft.py:90-91).
    """

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        # The precision policy (raft_ncup_tpu/precision/; docs/PRECISION.md)
        # is the single dtype authority: module compute dtype, correlation
        # feature/volume dtype, and the pinned-f32 set (coords, upsampler,
        # outputs, master weights) all come from here.
        self.policy = cfg.precision_policy
        dtype = self.policy.module_dtype
        hdim, cdim = cfg.hidden_dim, cfg.context_dim

        if cfg.small:
            self.fnet = Encoder(128, "instance", cfg.dropout, small=True, dtype=dtype)
            self.cnet = Encoder(
                hdim + cdim, "none", cfg.dropout, small=True, dtype=dtype
            )
            self.update_block = SmallUpdateBlock(
                cfg.corr_planes, hdim, dtype=dtype
            )
        else:
            self.fnet = Encoder(256, "instance", cfg.dropout, small=False, dtype=dtype)
            self.cnet = Encoder(
                hdim + cdim, "batch", cfg.dropout, small=False, dtype=dtype
            )
            self.update_block = BasicUpdateBlock(
                cfg.corr_planes,
                hdim,
                # raft_nc_dbl deletes the convex mask head (reference:
                # core/raft_nc_dbl.py:68).
                use_mask_head=(cfg.variant == "raft"),
                dtype=dtype,
            )

        self.upsampler = None
        if cfg.variant == "raft_nc_dbl":
            # NCUP consumes 2-channel flow with 128-channel GRU guidance
            # (reference: core/raft_nc_dbl.py:75).
            self.upsampler = build_upsampler(cfg.upsampler, cfg.dataset)

    # ------------------------------------------------------------------ init

    def init(self, rng: jax.Array, image_shape: tuple[int, ...]) -> dict:
        """Initialize all components. ``image_shape`` is NHWC with H, W
        divisible by 8."""
        B, H, W, _ = image_shape
        h8, w8 = H // 8, W // 8
        cfg = self.cfg
        # Template arrays for parameter init ride the policy's master-
        # weight dtype (f32 in every preset).
        pdt = self.policy.param_jnp
        hdim, cdim = cfg.hidden_dim, cfg.context_dim
        kf, kc, ku, kup = jax.random.split(rng, 4)

        img = jnp.zeros((B, H, W, 3), pdt)
        vf = self.fnet.init(kf, img)
        vc = self.cnet.init(kc, img)

        net = jnp.zeros((B, h8, w8, hdim), pdt)
        inp = jnp.zeros((B, h8, w8, cdim), pdt)
        corr = jnp.zeros((B, h8, w8, cfg.corr_planes), pdt)
        flow = jnp.zeros((B, h8, w8, 2), pdt)
        vu = self.update_block.init(ku, net, inp, corr, flow)

        params = {
            "fnet": vf["params"],
            "cnet": vc["params"],
            "update_block": vu["params"],
        }
        batch_stats = {}
        for name, v in (("fnet", vf), ("cnet", vc), ("update_block", vu)):
            if "batch_stats" in v:
                batch_stats[name] = v["batch_stats"]

        if self.upsampler is not None:
            flow2 = jnp.zeros((B, h8 * 2, w8 * 2, 2), pdt)
            guidance = jnp.zeros((B, h8, w8, hdim), pdt)
            vup = self.upsampler.init(kup, flow2, guidance)
            # Parameter-free heads (bilinear) init to an empty group so the
            # apply-side scoping stays uniform across upsampler kinds.
            params["upsampler"] = vup.get("params", {})
            if "batch_stats" in vup:
                batch_stats["upsampler"] = vup["batch_stats"]

        out = {"params": params}
        if batch_stats:
            out["batch_stats"] = batch_stats
        return out

    # ------------------------------------------------- shared forward pieces

    def _make_run(self, params, bstats, bn_train, rngs):
        """The submodule-application closure shared by every forward
        entry point; mutates ``bstats`` in place when ``bn_train``."""

        def run(name, module, *args, **kwargs):
            # Only the upsampler may be parameter-free (bilinear head): its
            # empty group gets dropped by flatten/unflatten round-trips
            # (checkpoint merge). For every other submodule absence is a
            # truncated checkpoint and must keep failing loudly.
            if name == "upsampler":
                v = {"params": params.get(name, {})}
            else:
                v = {"params": params[name]}
            if name in bstats:
                v["batch_stats"] = bstats[name]
            if bn_train and name in bstats:
                out, mut = module.apply(
                    v, *args, mutable=["batch_stats"], rngs=rngs, **kwargs
                )
                bstats[name] = mut["batch_stats"]
                return out
            return module.apply(v, *args, rngs=rngs, **kwargs)

        return run

    def _encode(
        self, run, image1, image2, *, train=False, bn_train=False,
        flow_init=None, net_init=None, net_warm=None,
    ):
        """Everything before the first refinement iteration: normalize,
        siamese fnet, context cnet, warm-start select, initial query
        coordinates. Returns ``(fmap1, fmap2, net, inp, coords1)``."""
        cfg = self.cfg
        policy = self.policy
        if image1.shape[1] % 8 or image1.shape[2] % 8:
            raise ValueError(
                f"image H, W must be divisible by 8, got {image1.shape[1:3]}; "
                "pad inputs with raft_ncup_tpu.ops.InputPadder first"
            )
        hdim = cfg.hidden_dim

        img1 = 2.0 * (image1 / 255.0) - 1.0
        img2 = 2.0 * (image2 / 255.0) - 1.0

        # Siamese feature extraction: both frames through fnet in one batch
        # (reference: core/extractor.py:168-174). jax.named_scope labels
        # carry into the HLO metadata, so an xprof capture of this
        # program is stage-labeled (docs/OBSERVABILITY.md) — staged for
        # the ROADMAP item-1 hardware window.
        with jax.named_scope("raft.fnet"):
            fmaps = run(
                "fnet",
                self.fnet,
                jnp.concatenate([img1, img2], axis=0),
                train=train,
                bn_train=bn_train,
            )
        fmap1, fmap2 = jnp.split(fmaps, 2, axis=0)
        # Correlation features/volume ride the policy's corr dtype — the
        # dominant memory term, so the bf16 presets halve it (and double
        # the Pallas VMEM dispatch thresholds). Coordinates stay at the
        # policy's pinned f32; the lookups promote through them.
        fmap1 = fmap1.astype(policy.corr_jnp)
        fmap2 = fmap2.astype(policy.corr_jnp)

        with jax.named_scope("raft.cnet"):
            cnet_out = run(
                "cnet", self.cnet, img1, train=train, bn_train=bn_train
            )
        net = jnp.tanh(cnet_out[..., :hdim])
        inp = jax.nn.relu(cnet_out[..., hdim:])
        if net_init is not None:
            # Carried GRU state replaces the cold init per batch row; the
            # select (not arithmetic blend) keeps cold rows bitwise equal
            # to a run without any carry. `inp` is deliberately NOT
            # carried: it is the context encoding of the CURRENT frame —
            # an input feature, not recurrent state — and reusing a stale
            # frame's encoding would feed the update GRU wrong data.
            carried = net_init.astype(net.dtype)
            if net_warm is None:
                net = carried
            else:
                net = jnp.where(
                    net_warm[:, None, None, None], carried, net
                )

        B, H, W, _ = image1.shape
        coords1 = coords_grid(B, H // 8, W // 8)
        if flow_init is not None:
            coords1 = coords1 + flow_init
        return fmap1, fmap2, net, inp, coords1

    def _build_corr_fn(self, fmap1, fmap2, mesh=None, spatial_axis="spatial"):
        """Correlation-lookup closure over a micro-batch's feature maps,
        per ``cfg.corr_impl`` (volume / onthefly / pallas)."""
        cfg = self.cfg
        policy = self.policy
        radius = cfg.resolved_corr_radius
        if cfg.corr_impl == "volume":
            pyramid = build_corr_pyramid(
                fmap1, fmap2, cfg.corr_levels, dtype=policy.corr_jnp
            )

            def corr_fn(coords):
                return corr_lookup(pyramid, coords, radius)

        elif cfg.corr_impl == "onthefly":
            n_spatial = (
                mesh.shape.get(spatial_axis, 1) if mesh is not None else 1
            )
            n_data = mesh.shape.get("data", 1) if mesh is not None else 1
            shardable = (
                n_spatial > 1
                and "data" in (mesh.shape if mesh is not None else {})
                and fmap1.shape[1] % n_spatial == 0
                and fmap1.shape[0] % n_data == 0
            )
            if shardable:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                def corr_fn(coords):
                    f2r = jax.lax.with_sharding_constraint(
                        fmap2, NamedSharding(mesh, P())
                    )

                    def local(f1_loc, f2_full, c_loc):
                        return corr_lookup_onthefly(
                            f1_loc, f2_full, c_loc, radius, cfg.corr_levels,
                            dtype=policy.corr_jnp,
                        )

                    return _shard_map(
                        local,
                        mesh=mesh,
                        in_specs=(
                            P("data", spatial_axis),
                            P(),
                            P("data", spatial_axis),
                        ),
                        out_specs=P("data", spatial_axis),
                    )(fmap1, f2r, coords)

            else:

                def corr_fn(coords):
                    return corr_lookup_onthefly(
                        fmap1, fmap2, coords, radius, cfg.corr_levels,
                        dtype=policy.corr_jnp,
                    )

        elif cfg.corr_impl == "pallas":
            try:
                from raft_ncup_tpu.ops.corr_pallas import corr_lookup_pallas
            except ImportError as e:
                raise NotImplementedError(
                    "corr_impl='pallas' requires raft_ncup_tpu.ops.corr_pallas"
                ) from e

            # Dispatch is per pyramid level inside the op, THREE tiers:
            # levels whose padded slab fits the VMEM budget take the
            # resident kernel, levels past residency with a fitting
            # band_plan take the BANDED kernel (at 1080p f32: levels
            # 0-1 banded, 2-3 resident; at 4K every level lands on a
            # kernel tier), and only the remainder takes the XLA
            # on-the-fly path. Shapes are static at trace time, so this
            # is a compile-time choice.
            # Mosaic lowers only on TPU-class backends; on non-TPU
            # platforms the kernel runs in interpret mode (slow but
            # correct) so corr_impl='pallas' works everywhere.
            from raft_ncup_tpu.utils.runtime import is_tpu_class_backend

            interpret = not is_tpu_class_backend()

            def corr_fn(coords):
                return corr_lookup_pallas(
                    fmap1, fmap2, coords, radius, cfg.corr_levels, interpret,
                    policy.corr_jnp,
                )

        else:
            raise ValueError(f"unknown corr_impl: {cfg.corr_impl!r}")
        return corr_fn

    def _upsample(self, run, flow_lr, net, up_mask, bn_train=False):
        """Low-res flow -> full-res prediction, per variant."""
        cfg = self.cfg
        policy = self.policy
        if cfg.variant == "raft_nc_dbl":
            # nearest x2, NCUP x4, values x8 (reference:
            # core/raft_nc_dbl.py:107-112,161). The upsampler runs at
            # the policy's pinned f32 — outside the reference's
            # autocast region, and NCUP's confidence arithmetic is
            # ratio-of-sums (docs/PRECISION.md).
            flow2 = upsample_nearest(flow_lr, 2)
            guidance = net.astype(policy.upsampler_jnp)
            # The upsampler's only train-dependent piece is BatchNorm in
            # the weights-estimation net, so it takes the bn flag.
            hr = run(
                "upsampler", self.upsampler, flow2, guidance, train=bn_train
            )
            return 8.0 * hr
        if up_mask is None:
            return upflow(flow_lr, 8, align_corners=cfg.align_corners)
        return convex_upsample(
            flow_lr, up_mask.astype(policy.upsampler_jnp), 8
        )

    def _make_step(
        self, run, corr_fn, coords0, inp, bstats, *, test_mode,
        carry_mask, bn_train, early_exit_tol=None,
    ):
        """One refinement iteration on the ``(net, coords1, stats)``
        carry — the single step body every scan (monolithic or segment)
        runs, so segmented execution can never drift from ``apply``.

        ``early_exit_tol`` (test mode only; docs/PERF.md "Early exit"):
        per-sample convergence detection on the GRU's own flow delta.
        The carry's ``stats['converged']`` (B,) bool marks lanes whose
        mean |delta| fell below the tolerance on an EARLIER iteration;
        those lanes' ``(net, coords1, up_mask)`` are frozen via
        ``jnp.where`` — a select, so a lane converged at iteration k is
        BITWISE the state it had after k (the same select contract as
        the streaming warm start). The mask is sticky and the freeze
        reads the mask from step ENTRY, so the iteration that detects
        convergence still commits its own update. Everything stays on
        device: no shape change, no host pull, no recompile.
        """
        policy = self.policy
        if early_exit_tol is not None and not test_mode:
            raise ValueError("early_exit_tol requires test_mode=True")

        def step(carry, _):
            net, coords1, stats = carry
            # Restore mutable stats captured in the carry so `run` sees the
            # per-iteration BatchNorm state (upsampler only).
            if "upsampler" in stats:
                bstats["upsampler"] = stats["upsampler"]
            net_in, coords1_in = net, coords1
            coords1 = jax.lax.stop_gradient(coords1)  # .detach() per iter
            # Stage labels inside the scanned refinement iteration: the
            # lookup and the GRU update are the two halves an xprof
            # trace needs separated (correlation memory wall vs compute).
            with jax.named_scope("raft.corr_lookup"):
                corr = corr_fn(coords1)
            flow = coords1 - coords0
            with jax.named_scope("raft.update_block"):
                net, up_mask, delta = run(
                    "update_block",
                    self.update_block,
                    net,
                    inp,
                    corr,
                    flow.astype(net.dtype),
                )
            # The coordinate carry is the refinement's f32 backbone: the
            # (possibly bf16) delta joins it at the policy's pinned
            # coord dtype, so per-iteration compute error never narrows
            # the carried state (the error-budget argument).
            coords1 = coords1 + delta.astype(policy.coord_jnp)

            converged = None
            if early_exit_tol is not None:
                frozen = stats["converged"]  # mask at step ENTRY
                keep = frozen[:, None, None, None]
                net = jnp.where(keep, net_in, net)
                coords1 = jnp.where(keep, coords1_in, coords1)
                if carry_mask:
                    up_mask = jnp.where(keep, stats["up_mask"], up_mask)
                # Detection norm: mean |delta| per sample, in the pinned
                # coord dtype and in LOW-RES pixels (the 8x upsampling
                # scales displacements, so tol=t low-res px bounds the
                # remaining full-res drift by ~8t px per skipped iter).
                dnorm = jnp.mean(
                    jnp.abs(delta.astype(policy.coord_jnp)), axis=(1, 2, 3)
                )
                converged = frozen | (dnorm < early_exit_tol)

            if test_mode:
                out = None
            else:
                out = self._upsample(
                    run, coords1 - coords0, net, up_mask, bn_train
                )
            new_stats = dict(stats)
            if "upsampler" in stats:
                new_stats["upsampler"] = bstats["upsampler"]
            if carry_mask:
                new_stats["up_mask"] = up_mask
            if converged is not None:
                new_stats["converged"] = converged
                if "exec_iters" in stats:
                    # Per-lane executed-iteration count: a lane active at
                    # step entry pays this iteration; a frozen lane does
                    # not. (Segment-granularity counting — the pipelined
                    # path — happens in refine_segment instead.)
                    new_stats["exec_iters"] = stats["exec_iters"] + (
                        ~frozen
                    ).astype(jnp.int32)
            return (net, coords1, new_stats), out

        return step

    @property
    def _has_mask(self) -> bool:
        # The raft (non-small) variant's convex upsampling needs the final
        # iteration's mask; in test mode the mask rides the scan carry so
        # upsampling runs once after the loop instead of every iteration.
        return self.cfg.variant == "raft" and not self.cfg.small

    # ----------------------------------------------------------------- apply

    def apply(
        self,
        variables: dict,
        image1: jax.Array,
        image2: jax.Array,
        iters: int = 12,
        flow_init: Optional[jax.Array] = None,
        test_mode: bool = False,
        train: bool = False,
        freeze_bn: bool = False,
        rngs: Optional[dict] = None,
        remat: bool = True,
        mutable: bool = False,
        mesh=None,
        spatial_axis: str = "spatial",
        metric_head: Optional[Any] = None,
        net_init: Optional[jax.Array] = None,
        net_warm: Optional[jax.Array] = None,
        return_net: bool = False,
        early_exit_tol: Optional[float] = None,
        return_exec_iters: bool = False,
    ):
        """Estimate optical flow between a pair of NHWC image batches.

        Returns (train mode) the stacked per-iteration high-res flow
        predictions (iters, B, H, W, 2); (test_mode) the tuple
        ``(flow_lowres, flow_up)``. With ``mutable=True`` additionally
        returns the updated batch_stats as a second element.

        ``metric_head`` (test mode only): a traceable callable applied to
        the final high-res flow INSIDE this program; the second result
        element becomes ``metric_head(flow_up)`` instead of the full
        field. Evaluation folds its on-device metric accumulators
        (inference/metrics.py) through this hook so the compiled eval
        program emits a handful of scalars per batch — the full flow
        field never leaves the device on the validation path.

        ``net_init``/``net_warm``/``return_net`` (streaming warm start,
        raft_ncup_tpu/streaming/): ``net_init`` is a (B, H/8, W/8,
        hidden_dim) GRU hidden state carried from a previous frame;
        rows where the (B,)-bool ``net_warm`` is True START the
        refinement from it instead of the context encoder's
        ``tanh`` initialization (a ``jnp.where`` select, so cold rows
        are BITWISE the default cold start — the streaming engine's
        per-stream isolation contract). ``return_net=True`` (test mode
        only) appends the final hidden state to the result:
        ``(flow_lr, flow_up, net)``.

        ``mesh``/``spatial_axis``: when running under a (data x spatial)
        SPMD mesh, the on-the-fly correlation lookup is wrapped in
        ``jax.shard_map`` over the spatial axis — queries stay row-sharded
        while fmap2 is replicated (33 MB at 1/8 res of 1080p). Left to the
        GSPMD partitioner, the lookup's scan-over-row-chunks structure
        partitions pathologically (6x the single-device temp memory,
        measured in tests/test_highres.py); the explicit map makes spatial
        sharding actually reduce per-device memory.

        ``early_exit_tol``/``return_exec_iters`` (test mode only;
        docs/PERF.md "Early exit"): with a tolerance set, the refinement
        runs as a ``lax.while_loop`` whose condition is ``t < iters AND
        any lane still active`` — per-sample convergence freezes a
        lane's carry bitwise (see ``_make_step``), and the batch-level
        condition genuinely stops the loop once every lane converged,
        which is what makes the FLOP cut real rather than
        compute-and-discard. The condition never leaves the device and
        the carry shapes are identical to the scan's, so the cache key,
        sharding and donation story are unchanged.
        ``return_exec_iters=True`` appends the per-sample (B,) int32
        executed-iteration count as the LAST result element.
        """
        if early_exit_tol is not None and not test_mode:
            raise ValueError("early_exit_tol requires test_mode=True")
        if return_exec_iters and early_exit_tol is None:
            raise ValueError(
                "return_exec_iters requires early_exit_tol (without "
                "detection every lane runs the full budget by definition)"
            )

        policy = self.policy
        params = variables["params"]
        bstats = dict(variables.get("batch_stats", {}))
        bn_train = train and not freeze_bn

        run = self._make_run(params, bstats, bn_train, rngs)
        fmap1, fmap2, net, inp, coords1 = self._encode(
            run, image1, image2, train=train, bn_train=bn_train,
            flow_init=flow_init, net_init=net_init, net_warm=net_warm,
        )
        corr_fn = self._build_corr_fn(fmap1, fmap2, mesh, spatial_axis)

        B, H, W, _ = image1.shape
        coords0 = coords_grid(B, H // 8, W // 8)

        carry_mask = self._has_mask and test_mode
        step = self._make_step(
            run, corr_fn, coords0, inp, bstats,
            test_mode=test_mode, carry_mask=carry_mask, bn_train=bn_train,
            early_exit_tol=early_exit_tol,
        )

        init_stats: dict = {}
        if bn_train and "upsampler" in bstats:
            init_stats["upsampler"] = bstats["upsampler"]
        if carry_mask:
            init_stats["up_mask"] = jnp.zeros(
                (B, H // 8, W // 8, 9 * 64), net.dtype
            )
        if early_exit_tol is not None:
            init_stats["converged"] = jnp.zeros((B,), jnp.bool_)
            init_stats["exec_iters"] = jnp.zeros((B,), jnp.int32)

        body = step
        if train and remat:
            body = jax.checkpoint(step)

        with jax.named_scope("raft.refinement"):
            if early_exit_tol is not None:
                # while_loop, not scan: the loop condition — all on
                # device — exits the moment every lane converged, so
                # trailing iterations cost nothing at all (test mode has
                # no per-iteration outputs, so no stacked outs to keep).
                def _cond(state):
                    t, (_n, _c, stats) = state
                    return jnp.logical_and(
                        t < iters, jnp.any(~stats["converged"])
                    )

                def _body(state):
                    t, carry = state
                    carry, _ = body(carry, None)
                    return t + jnp.int32(1), carry

                _, (net, coords1, final_stats) = jax.lax.while_loop(
                    _cond, _body,
                    (jnp.int32(0), (net, coords1, init_stats)),
                )
                flow_seq = None
            else:
                (net, coords1, final_stats), flow_seq = jax.lax.scan(
                    body, (net, coords1, init_stats), None, length=iters
                )
        if "upsampler" in final_stats:
            bstats["upsampler"] = final_stats["upsampler"]

        if test_mode:
            with jax.named_scope("raft.upsample"):
                flow_up = self._upsample(
                    run, coords1 - coords0, net, final_stats.get("up_mask"),
                    bn_train,
                ).astype(policy.output_jnp)  # serving/metrics contract: f32
            if metric_head is not None:
                with jax.named_scope("raft.metric_head"):
                    flow_up = metric_head(flow_up)
            if return_net:
                result = (coords1 - coords0, flow_up, net)
            else:
                result = (coords1 - coords0, flow_up)
            if return_exec_iters:
                result = result + (final_stats["exec_iters"],)
        else:
            if metric_head is not None:
                raise ValueError("metric_head requires test_mode=True")
            if return_net:
                raise ValueError("return_net requires test_mode=True")
            result = flow_seq

        if mutable:
            return result, bstats
        return result

    # ------------------------------------------- composable scan segments

    def encode(
        self,
        variables: dict,
        image1: jax.Array,
        image2: jax.Array,
        flow_init: Optional[jax.Array] = None,
        net_init: Optional[jax.Array] = None,
        net_warm: Optional[jax.Array] = None,
        rngs: Optional[dict] = None,
        early_exit: bool = False,
    ) -> dict:
        """Pipeline front half (inference): everything before the first
        refinement iteration, returned as a SEGMENT CARRY dict —

        - ``net`` / ``coords1``: the live recurrent state a refinement
          iteration mutates (plus ``up_mask`` for the raft non-small
          variant, whose final-iteration mask the upsampler needs);
        - ``inp`` / ``fmap1`` / ``fmap2``: the micro-batch's immutable
          context, which must TRAVEL WITH the state between pipeline
          stages (stage s+1 refining this micro-batch needs its feature
          maps, not its neighbor's).

        ``early_exit=True`` seeds the convergence-detection keys the
        early-exit segments read and update: ``converged`` (B,) bool
        (all False — every lane starts active) and ``exec_iters`` (B,)
        int32 (zeros). They ride the carry between stages like the rest
        of the state; ``finalize`` ignores them.

        ``encode -> refine_segment x S -> finalize`` reproduces
        ``apply(test_mode=True)`` exactly: same submodule code, same
        step body, same upsampling head.
        """
        run = self._make_run(
            variables["params"], dict(variables.get("batch_stats", {})),
            False, rngs,
        )
        fmap1, fmap2, net, inp, coords1 = self._encode(
            run, image1, image2,
            flow_init=flow_init, net_init=net_init, net_warm=net_warm,
        )
        carry = {
            "net": net, "coords1": coords1, "inp": inp,
            "fmap1": fmap1, "fmap2": fmap2,
        }
        B = net.shape[0]
        if self._has_mask:
            _, h8, w8 = net.shape[:3]
            carry["up_mask"] = jnp.zeros((B, h8, w8, 9 * 64), net.dtype)
        if early_exit:
            carry["converged"] = jnp.zeros((B,), jnp.bool_)
            carry["exec_iters"] = jnp.zeros((B,), jnp.int32)
        return carry

    def refine_segment(
        self,
        variables: dict,
        carry: dict,
        iters: int,
        mesh=None,
        spatial_axis: str = "spatial",
        rngs: Optional[dict] = None,
        early_exit_tol: Optional[float] = None,
    ) -> dict:
        """Advance a segment carry by ``iters`` contiguous refinement
        iterations (one ``lax.scan`` — one compiled iteration body, as
        in ``apply``) and return the updated carry. The correlation
        closure is rebuilt from the carry's own feature maps, so a
        carry handed in from another device group (or another jit
        boundary) refines identically to one that never moved; for the
        'volume' impl this re-derives the pyramid per segment — one
        matmul + avg-pools, cheap against a segment of GRU iterations,
        and bitwise the same pyramid every time.

        ``early_exit_tol`` (carry must be seeded with
        ``encode(..., early_exit=True)``): per-iteration convergence
        detection and freeze run INSIDE the segment — flow is identical
        to the monolithic early-exit path — but the executed-iters
        count quantizes to SEGMENT boundaries: a lane active at segment
        entry is billed the whole segment, because under the pipe axis
        the tick executable runs on schedule regardless and a segment
        seam is the first point a lane's exit is observable. So
        ``exec_iters(pipelined) == ceil(exec_iters(monolithic) /
        seg_len) * seg_len`` — the quantization contract
        tests/test_earlyexit.py pins for S in {1, 2, 4}.
        """
        run = self._make_run(
            variables["params"], dict(variables.get("batch_stats", {})),
            False, rngs,
        )
        corr_fn = self._build_corr_fn(
            carry["fmap1"], carry["fmap2"], mesh, spatial_axis
        )
        B, h8, w8 = carry["net"].shape[:3]
        coords0 = coords_grid(B, h8, w8)
        carry_mask = "up_mask" in carry
        stats = {"up_mask": carry["up_mask"]} if carry_mask else {}
        if early_exit_tol is not None:
            if "converged" not in carry:
                raise ValueError(
                    "early_exit_tol requires a carry seeded with "
                    "encode(..., early_exit=True)"
                )
            stats["converged"] = carry["converged"]
        step = self._make_step(
            run, corr_fn, coords0, carry["inp"], {},
            test_mode=True, carry_mask=carry_mask, bn_train=False,
            early_exit_tol=early_exit_tol,
        )
        with jax.named_scope("raft.refinement"):
            (net, coords1, out_stats), _ = jax.lax.scan(
                step, (carry["net"], carry["coords1"], stats),
                None, length=iters,
            )
        out = dict(carry)
        out["net"] = net
        out["coords1"] = coords1
        if carry_mask:
            out["up_mask"] = out_stats["up_mask"]
        if early_exit_tol is not None:
            out["converged"] = out_stats["converged"]
            # Segment-granularity billing (see docstring): lanes active
            # at segment ENTRY pay the full segment.
            entry_active = ~carry["converged"]
            out["exec_iters"] = carry["exec_iters"] + iters * (
                entry_active.astype(jnp.int32)
            )
        return out

    def finalize(
        self,
        variables: dict,
        carry: dict,
        rngs: Optional[dict] = None,
        return_net: bool = False,
    ):
        """Pipeline back half: upsample a finished segment carry to the
        test-mode result ``(flow_lr, flow_up)`` (plus ``net`` with
        ``return_net`` — the streaming warm-start handoff)."""
        run = self._make_run(
            variables["params"], dict(variables.get("batch_stats", {})),
            False, rngs,
        )
        B, h8, w8 = carry["net"].shape[:3]
        coords0 = coords_grid(B, h8, w8)
        flow_lr = carry["coords1"] - coords0
        with jax.named_scope("raft.upsample"):
            flow_up = self._upsample(
                run, flow_lr, carry["net"], carry.get("up_mask")
            ).astype(self.policy.output_jnp)
        if return_net:
            return flow_lr, flow_up, carry["net"]
        return flow_lr, flow_up


@functools.lru_cache(maxsize=8)
def get_model(cfg: ModelConfig) -> RAFT:
    """Model registry/factory keyed by (hashable, frozen) config."""
    return RAFT(cfg)
