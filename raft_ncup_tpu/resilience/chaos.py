"""Deterministic fault injection: the chaos harness the tests drive.

Every resilience claim in this package is backed by an end-to-end test
that injects the fault into the REAL pipeline (synthetic dataset →
FlowLoader → DevicePrefetcher → jitted step) and asserts the documented
recovery. Faults are addressed by deterministic coordinates — a step
number or a global read count — so a failing test replays exactly.

Spec grammar (``--chaos`` flag / ``RAFT_NCUP_CHAOS`` env), comma-joined:

- ``nan@S`` — the batch consumed by (0-based) training step ``S`` gets
  an all-NaN flow field → non-finite loss/grads → the sentinel must
  skip-update (anomaly.py).
- ``ioerror@N`` — the ``N``-th (0-based, global) ``dataset.sample``
  read raises ``IOError`` → the loader must retry with backoff
  (retry.py) and the run must be unaffected.
- ``sigterm@S`` — a real SIGTERM is delivered to the training process
  right after it completes ``S`` attempted steps → the preemption path
  must save an atomic checkpoint and exit :data:`EXIT_PREEMPTED`.
  (The self-``os.kill`` exercises the same signal machinery as an
  external kill; tests/test_chaos_train.py also covers the
  child-process external-SIGTERM variant.)

Serving events (consumed by ``serving/traffic.py`` + serve.py; the
coordinate is a request index in the deterministic traffic stream):

- ``burst@N`` — request ``N`` arrives as a simultaneous burst of
  ``burst_size`` requests → admission control must shed the overflow
  explicitly and the iteration budget must degrade, not the latency.
- ``poison@N`` — request ``N``'s first frame is all-NaN → the server
  must quarantine it alone (``rejected``) while its batch-mates return
  correct flow.
- ``sigterm@N`` — reused for serving: a real SIGTERM right after ``N``
  requests have been submitted → the driver stops submitting and the
  server drains everything admitted, then exits clean
  (:data:`EXIT_PREEMPTED`).

Streaming events (consumed by ``streaming/traffic.py`` + the
``serve.py --stream`` driver; the coordinate is a frame index in the
deterministic multi-stream schedule):

- ``corruptframe@N`` — frame ``N``'s first image is all-NaN → the
  engine's IN-GRAPH anomaly check must reset only the owning stream's
  slot to cold-start while co-batched streams' flow stays bitwise
  identical to an uninjected run (the streaming analogue of serving's
  poison quarantine).
- ``abandon@N`` — the stream that owns frame ``N`` stops submitting
  after it (no close) → idle eviction must free its slot after
  ``idle_timeout_s`` and the slot must be reusable without a recompile.
- ``burst@N`` — reused for streaming: at frame ``N``'s due time a burst
  of ``burst_size`` EXTRA single-frame streams arrives → stream
  admission must shed the overflow (slots are a hard capacity), not
  queue it.

Fleet events (consumed by ``fleet/router.replay_fleet`` + the fleet
tests/bench; the coordinate is a *fleet-wide submission index* in the
deterministic schedule, and the TARGET is the replica that carried that
submission — deterministic because routing is):

- ``killreplica@N`` — right after submission ``N`` dispatches, its
  replica is SIGKILLed (no drain, no flush) → the dead replica's
  streams must re-admit elsewhere cold, batch-mates on surviving
  replicas must be bitwise unaffected, and every stranded request must
  fail over (deadline permitting) or terminate honestly.
- ``stallreplica@N`` — submission ``N``'s replica is SIGSTOPped: the
  process lingers but stops heartbeating → detection must ride the
  healthz staleness contract (file older than ``stale_after_s`` ⇒
  presumed dead), the supervisor SIGKILLs the zombie, and failover
  proceeds as for a death.
- ``drainreplica@N`` — submission ``N``'s replica is SIGTERMed → the
  drain contract: healthz shows DRAINING before the flush, zero
  in-flight losses, child exits 75, and the router routes nothing new
  there from the moment it observes DRAINING.

Fleet-scale (multi-host) events — the coordinate is still a submission
index; the TARGET is the HOST of the replica that carried it (derived
through the FleetConfig placement, so the blast is deterministic).
Both need a ``fleet/host_supervisor.FleetManager``:

- ``partitionhost@N`` — the TCP links to submission ``N``'s host drop,
  both directions (the manager stops hearing the host's agent AND the
  router's links there are torn) → the staleness contract declares the
  whole host dead, in-flight work fails over, and the partitioned
  replicas are fenced so they cannot answer after the failover.
- ``killsupervisor@N`` — submission ``N``'s host AGENT is SIGKILLed;
  its replica processes linger (orphans, still heartbeating their
  local files) → the wire republish stops, the fleet-level staleness
  contract declares the host dead, and the lingering replicas are
  reaped (SIGKILL) before failover completes — zombies must never
  answer a request the router already re-dispatched.

NaN injection wraps the *host batch stream* (order-preserving, so batch
``i`` of the stream is exactly the batch step ``start_step + i``
consumes, prefetch depth notwithstanding); the SIGTERM trigger lives in
the train loop itself so it lands on a precise step boundary. Usage:
docs/RESILIENCE.md.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

ENV_VAR = "RAFT_NCUP_CHAOS"

_KINDS = ("nan", "ioerror", "sigterm", "burst", "poison", "corruptframe",
          "abandon", "killreplica", "stallreplica", "drainreplica",
          "partitionhost", "killsupervisor")


@dataclass(frozen=True)
class ChaosSpec:
    """Parsed fault-injection plan. Empty spec = no chaos."""

    nan_steps: frozenset = frozenset()
    ioerror_reads: frozenset = frozenset()
    sigterm_after: Optional[int] = None
    burst_requests: frozenset = frozenset()
    poison_requests: frozenset = frozenset()
    corrupt_frames: frozenset = frozenset()
    abandon_frames: frozenset = frozenset()
    kill_replica_at: frozenset = frozenset()
    stall_replica_at: frozenset = frozenset()
    drain_replica_at: frozenset = frozenset()
    partition_host_at: frozenset = frozenset()
    kill_supervisor_at: frozenset = frozenset()

    @classmethod
    def parse(cls, spec: Optional[str]) -> "ChaosSpec":
        sets: dict = {k: set() for k in _KINDS if k != "sigterm"}
        sig: Optional[int] = None
        for token in (spec or "").split(","):
            token = token.strip()
            if not token:
                continue
            kind, sep, value = token.partition("@")
            if not sep or kind not in _KINDS:
                raise ValueError(
                    f"bad chaos event {token!r} (want one of "
                    f"{'/'.join(_KINDS)}@N, comma-joined)"
                )
            n = int(value)
            if kind == "sigterm":
                sig = n
            else:
                sets[kind].add(n)
        return cls(
            frozenset(sets["nan"]),
            frozenset(sets["ioerror"]),
            sig,
            frozenset(sets["burst"]),
            frozenset(sets["poison"]),
            frozenset(sets["corruptframe"]),
            frozenset(sets["abandon"]),
            frozenset(sets["killreplica"]),
            frozenset(sets["stallreplica"]),
            frozenset(sets["drainreplica"]),
            frozenset(sets["partitionhost"]),
            frozenset(sets["killsupervisor"]),
        )

    @property
    def active(self) -> bool:
        return bool(self.nan_steps or self.ioerror_reads
                    or self.burst_requests or self.poison_requests
                    or self.corrupt_frames or self.abandon_frames
                    or self.kill_replica_at or self.stall_replica_at
                    or self.drain_replica_at or self.partition_host_at
                    or self.kill_supervisor_at
                    or self.sigterm_after is not None)

    def render(self) -> str:
        parts = [f"nan@{s}" for s in sorted(self.nan_steps)]
        parts += [f"ioerror@{n}" for n in sorted(self.ioerror_reads)]
        parts += [f"burst@{n}" for n in sorted(self.burst_requests)]
        parts += [f"poison@{n}" for n in sorted(self.poison_requests)]
        parts += [f"corruptframe@{n}" for n in sorted(self.corrupt_frames)]
        parts += [f"abandon@{n}" for n in sorted(self.abandon_frames)]
        parts += [f"killreplica@{n}" for n in sorted(self.kill_replica_at)]
        parts += [f"stallreplica@{n}" for n in sorted(self.stall_replica_at)]
        parts += [f"drainreplica@{n}" for n in sorted(self.drain_replica_at)]
        parts += [
            f"partitionhost@{n}" for n in sorted(self.partition_host_at)
        ]
        parts += [
            f"killsupervisor@{n}" for n in sorted(self.kill_supervisor_at)
        ]
        if self.sigterm_after is not None:
            parts.append(f"sigterm@{self.sigterm_after}")
        return ",".join(parts) or "<none>"


def chaos_batches(
    batches: Iterable[dict],
    nan_steps: frozenset,
    start_step: int = 0,
    log: Optional[Callable[[str], None]] = None,
) -> Iterator[dict]:
    """Wrap a host-batch stream, poisoning the flow of selected steps.

    Batch ``i`` of the stream is the one training step ``start_step + i``
    consumes (the loader/prefetcher are order-preserving), so ``nan@S``
    lands on exactly step ``S`` regardless of prefetch depth.
    """
    for i, batch in enumerate(batches):
        step = start_step + i
        if step in nan_steps:
            batch = dict(batch)
            flow = np.array(batch["flow"], dtype=np.float32, copy=True)
            flow[...] = np.nan
            batch["flow"] = flow
            if log is not None:
                log(f"chaos: NaN flow injected into the batch for step {step}")
        yield batch


class ChaosDataset:
    """Dataset wrapper raising ``IOError`` on configured global reads.

    The read counter is process-global across loader worker threads
    (lock-guarded), so ``ioerror@N`` means "the N-th sample() call this
    process makes", independent of which worker lands on it.
    """

    def __init__(self, dataset, ioerror_reads: frozenset):
        self._dataset = dataset
        self._fail = frozenset(int(n) for n in ioerror_reads)
        self._lock = threading.Lock()
        self._reads = 0

    def __len__(self) -> int:
        return len(self._dataset)

    def __getattr__(self, name):  # is_test etc. pass through
        return getattr(self._dataset, name)

    def sample(self, index: int, rng=None):
        with self._lock:
            n = self._reads
            self._reads += 1
        if n in self._fail:
            raise IOError(
                f"chaos: injected IOError on dataset read {n} "
                f"(sample index {index})"
            )
        return self._dataset.sample(index, rng)
