"""On-device divergence sentinel, folded INTO the jitted train step.

A non-finite loss or gradient (bad batch, fp overflow) poisons Adam's
moments the moment ``apply_gradients`` runs — and a naive host-side
check (``if not np.isfinite(float(loss))``) would reintroduce the exact
per-step device→host sync the async pipeline removed (PR 1) and the
guards forbid (PR 2). So the sentinel lives inside the compiled step:

- **detect** — ``bad = ~isfinite(loss) | ~isfinite(grad_norm) | spike``,
  where a *spike* is a grad norm above ``sentinel_spike_factor`` times
  its EMA (armed only after ``sentinel_warmup`` good steps, so init
  noise never trips it);
- **skip-update** — every leaf of params / opt_state / batch_stats is
  ``jnp.where(bad, old, new)``: a bad step leaves the train state
  untouched bit-for-bit (the step counter still advances — it counts
  *attempted* steps, which is what the resumable data-stream position is
  derived from);
- **account on device** — skipped/consecutive/EMA counters ride the
  sentinel pytree carried in ``TrainState.sentinel``; the host reads
  them only at the per-window sanctioned ``jax.device_get`` boundary
  (train.py, same cadence as the Logger's single pull), so steady-state
  host transfers and recompiles stay 0 under ``--strict_guards``.

The *halt* policy (``sentinel_halt_after`` consecutive bad steps ⇒ stop
the run, roll back to the last good checkpoint, exit
:data:`raft_ncup_tpu.resilience.preemption.EXIT_DIVERGED`) is host-side
policy in train.py — by the skip-update invariant the in-memory params
are still last-good, but a persistent bad streak means the *inputs* or
the run itself have gone wrong, and burning compute on skipped steps
helps nobody. Semantics: docs/RESILIENCE.md.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def init_sentinel() -> dict:
    """Initial sentinel accumulator pytree (carried in TrainState)."""
    return {
        "skipped": jnp.zeros((), jnp.int32),  # cumulative skipped steps
        "consecutive": jnp.zeros((), jnp.int32),  # current bad streak
        "good": jnp.zeros((), jnp.int32),  # good steps seen (EMA warm-up)
        "ema_grad_norm": jnp.zeros((), jnp.float32),
    }


def guard_update(
    prev_state: Any,
    new_state: Any,
    loss: jax.Array,
    grad_norm: jax.Array,
    cfg: Any,
) -> Tuple[Any, dict]:
    """Select between ``new_state`` (good step) and ``prev_state``'s
    params/opt_state/batch_stats (bad step), update the sentinel
    accumulators, and return ``(state, sentinel_metrics)``.

    Traced code: runs inside the jitted step, one fixed program — the
    skip is a data-dependent ``jnp.where``, never Python control flow.
    ``cfg`` supplies ``sentinel_spike_factor`` / ``sentinel_ema_decay`` /
    ``sentinel_warmup`` (TrainConfig).
    """
    sen = prev_state.sentinel
    finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
    warmed = sen["good"] >= cfg.sentinel_warmup
    spike = warmed & (
        grad_norm > cfg.sentinel_spike_factor * sen["ema_grad_norm"]
    )
    bad = jnp.logical_or(~finite, spike)

    def keep_good(new, old):
        return jax.tree.map(lambda n, o: jnp.where(bad, o, n), new, old)

    bad_i = bad.astype(jnp.int32)
    decay = jnp.float32(cfg.sentinel_ema_decay)
    ema = jnp.where(
        bad,
        sen["ema_grad_norm"],
        jnp.where(
            sen["good"] == 0,
            grad_norm,  # first good step seeds the EMA
            decay * sen["ema_grad_norm"] + (1.0 - decay) * grad_norm,
        ),
    )
    sentinel = {
        "skipped": sen["skipped"] + bad_i,
        "consecutive": jnp.where(bad, sen["consecutive"] + 1, 0),
        "good": sen["good"] + (1 - bad_i),
        "ema_grad_norm": ema,
    }
    state = new_state.replace(
        params=keep_good(new_state.params, prev_state.params),
        opt_state=keep_good(new_state.opt_state, prev_state.opt_state),
        batch_stats=keep_good(new_state.batch_stats, prev_state.batch_stats),
        sentinel=sentinel,
    )
    # bad_step means over a Logger window = fraction of the window
    # skipped; 0.0000 in a healthy run.
    metrics = {"bad_step": bad.astype(jnp.float32)}
    return state, metrics
