"""Bounded exponential-backoff retry for host-side IO.

The failure class this covers is *transient host IO*: an NFS hiccup mid
``dataset.sample``, a filesystem stall under an orbax save. Those must
not kill a 100k-step run — but an unbounded retry loop must not hang it
either, and a retry that silently absorbs faults is its own bug (JGL007
exists for exactly that). So every retry here is **bounded**, **backs
off exponentially**, and **accounts**: callers hand in a
:class:`RetryStats` whose totals the train driver writes to log.txt at
run end, so a run that survived on retries says so.

Pure stdlib — no jax import: retry wraps host IO only, never device
work (a failed collective is not retryable; it needs the preemption
path). Every retry/giveup/quarantine additionally lands as a telemetry
ring event + canonical counter (``io_retry_total`` / ``io_giveup_total``
/ ``io_sample_quarantined_total``; observability/, docs/OBSERVABILITY.md)
— the log.txt accounting lines and :class:`RetryStats` fields are
unchanged.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, TypeVar

from raft_ncup_tpu.observability import get_telemetry

T = TypeVar("T")


@dataclass(eq=False)  # a counter object: identity, not value, equality
class RetryStats:
    """Per-run IO-fault accounting, rendered into log.txt.

    Thread-safe: loader pool workers fail concurrently, and accounting
    that undercounts under exactly the concurrent-failure load it exists
    for would defeat its purpose. Mutate through the ``note_*`` /
    ``quarantine`` methods, not the fields."""

    retries: int = 0  # failed attempts that were retried
    giveups: int = 0  # operations that exhausted their attempt budget
    quarantined: list = field(default_factory=list)  # poisoned sample indices
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def note_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def note_giveup(self) -> None:
        with self._lock:
            self.giveups += 1

    def quarantine(self, index: int) -> bool:
        """Record a quarantined index once; False if already recorded."""
        with self._lock:
            if index in self.quarantined:
                return False
            self.quarantined.append(index)
        get_telemetry().event("io_sample_quarantined", index=index)
        return True

    @property
    def clean(self) -> bool:
        return not (self.retries or self.giveups or self.quarantined)

    def summary(self) -> str:
        q = ",".join(str(i) for i in self.quarantined) or "-"
        return (
            f"retries={self.retries} giveups={self.giveups} "
            f"quarantined=[{q}]"
        )


def retry_io(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay_s: float = 0.05,
    max_delay_s: float = 2.0,
    retry_on: Tuple[type, ...] = (OSError,),
    stats: Optional[RetryStats] = None,
    desc: str = "io",
    sleep: Callable[[float], None] = time.sleep,
    log: Optional[Callable[[str], None]] = None,
) -> T:
    """Call ``fn`` with up to ``attempts`` retries on ``retry_on``.

    The first call plus ``attempts`` retries; delays double from
    ``base_delay_s`` up to ``max_delay_s``. The final failure re-raises
    the original exception (after counting a giveup) — this helper never
    swallows. ``sleep`` is injectable so tests run on a fake clock.
    """
    delay = base_delay_s
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if attempt >= attempts:
                if stats is not None:
                    stats.note_giveup()
                get_telemetry().event("io_giveup", desc=desc)
                raise
            attempt += 1
            if stats is not None:
                stats.note_retry()
            get_telemetry().event(
                "io_retry", desc=desc, attempt=attempt
            )
            if log is not None:
                log(
                    f"{desc}: attempt {attempt}/{attempts} failed ({e}); "
                    f"retrying in {delay:.2f}s"
                )
            sleep(delay)
            delay = min(delay * 2.0, max_delay_s)
