"""Fault tolerance for long training runs.

RAFT-style schedules run 100k+ steps; on preemptible TPU pods eviction
mid-run is the norm, a single non-finite batch can poison the optimizer
state, and a flaky dataset read should never cost `val_freq` steps of
work. This package makes those events first-class:

- :mod:`anomaly` — an on-device divergence sentinel folded into the
  jitted train step: non-finite loss/grad and grad-norm spikes select a
  ``jnp.where`` skip-update (params/opt_state/batch_stats unchanged),
  counters accumulate on device and are pulled only at the existing
  per-window sanctioned ``jax.device_get`` boundary, so the
  zero-host-sync / zero-recompile invariants (docs/ANALYSIS.md) hold.
- :mod:`preemption` — SIGTERM/SIGINT handlers that set a flag checked at
  the step boundary; the run saves one atomic (multihost-agreed)
  checkpoint plus exact-resume metadata and exits with
  :data:`EXIT_PREEMPTED`.
- :mod:`retry` — bounded exponential-backoff retry for host-side IO
  (dataset reads, checkpoint saves) with poison-sample quarantine and
  per-run accounting (``RetryStats`` lands in log.txt).
- :mod:`chaos` — deterministic fault injection (NaN batches, IOError on
  the Nth read, SIGTERM at step N) driving the end-to-end resilience
  tests against the real synthetic pipeline.

Protocol and knobs: docs/RESILIENCE.md.
"""

from raft_ncup_tpu.resilience.anomaly import (  # noqa: F401
    guard_update,
    init_sentinel,
)
from raft_ncup_tpu.resilience.chaos import (  # noqa: F401
    ChaosDataset,
    ChaosSpec,
    chaos_batches,
)
from raft_ncup_tpu.resilience.preemption import (  # noqa: F401
    EXIT_DIVERGED,
    EXIT_PREEMPTED,
    PreemptionHandler,
    resume_metadata,
)
from raft_ncup_tpu.resilience.retry import RetryStats, retry_io  # noqa: F401

__all__ = [
    "ChaosDataset",
    "ChaosSpec",
    "EXIT_DIVERGED",
    "EXIT_PREEMPTED",
    "PreemptionHandler",
    "RetryStats",
    "chaos_batches",
    "guard_update",
    "init_sentinel",
    "resume_metadata",
    "retry_io",
]
