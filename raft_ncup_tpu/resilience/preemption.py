"""Preemption-safe shutdown: signal handling, agreed save, exact resume.

Preemptible TPU pods deliver SIGTERM with a grace window; a run that
dies mid-step throws away up to ``val_freq`` steps of work and — worse —
can leave a half-written "latest" state. The protocol here:

1. :class:`PreemptionHandler` turns SIGTERM/SIGINT into a *flag*; the
   train loop checks it at the step boundary (signal handlers must never
   touch jax — the interrupted frame may be mid-dispatch).
2. On a flagged boundary the driver saves ONE atomic checkpoint (orbax's
   per-step directory commit) and exits with :data:`EXIT_PREEMPTED` so
   the scheduler can tell "requeue me" from a crash.
3. Multi-host, the flag is *agreed* before anyone saves: orbax saves are
   collective, so a host acting alone on its local signal would wedge
   the pod. ``poll`` all-reduces the flag at a fixed step cadence —
   every process breaks at the same step and saves the same step.
4. :func:`resume_metadata` pins the run's identity (model variant,
   config fingerprint, seed) next to the orbax payload; restore verifies
   it (training/checkpoint.py) and fails with a *clear* message on
   mismatch instead of orbax's opaque pytree-structure error.

Exit-code registry (distinct from 0/1 so wrappers can branch):
``EXIT_PREEMPTED`` — clean preemption shutdown, checkpoint saved, safe
to requeue; ``EXIT_DIVERGED`` — sentinel halt (anomaly.py), rolled back
to the last good checkpoint, requeueing without investigation will
likely diverge again. Protocol details: docs/RESILIENCE.md.
"""

from __future__ import annotations

import hashlib
import signal
import sys
from typing import Any, Optional, Sequence

# BSD sysexits-adjacent, away from shell/python conventions (1/2/126+).
EXIT_PREEMPTED = 75  # EX_TEMPFAIL: re-runnable, state saved
EXIT_DIVERGED = 76  # EX_PROTOCOL: training diverged, rolled back


def resume_metadata(model_cfg: Any, train_cfg: Any) -> dict:
    """The identity blob saved next to the orbax payload and verified on
    restore: enough to refuse a wrong-architecture / wrong-seed resume
    before orbax dives into the pytree."""
    from raft_ncup_tpu.config import config_to_json

    fingerprint = hashlib.sha256(
        config_to_json(model_cfg).encode("utf-8")
    ).hexdigest()[:16]
    return {
        "model_variant": model_cfg.variant,
        "config_fingerprint": fingerprint,
        "seed": int(train_cfg.seed),
    }


class PreemptionHandler:
    """Context manager: SIGTERM/SIGINT set a flag; the loop polls it.

    The first signal requests a graceful stop. A second signal restores
    the previous dispositions, so a third delivery gets the default
    (fatal) behavior — an operator mashing Ctrl-C is not held hostage by
    graceful shutdown.

    ``poll(step)`` is the step-boundary check. Single-process it is a
    plain attribute read (zero overhead — safe to call every step).
    Multi-host it all-reduces the flag across processes every
    ``check_every`` steps (a host collective via
    ``parallel.multihost.allreduce_sum_across_hosts``), returning True
    on the same step for every process; off-cadence steps return False
    without communicating.
    """

    def __init__(
        self,
        signals: Sequence[int] = (signal.SIGTERM, signal.SIGINT),
        check_every: int = 16,
    ):
        self.signals = tuple(signals)
        self.check_every = max(1, int(check_every))
        self._requested = False
        self._previous: dict = {}

    @property
    def requested(self) -> bool:
        """This process's local flag (pre-agreement)."""
        return self._requested

    def _handle(self, signum, frame) -> None:
        if self._requested:
            # Second signal: stop intercepting so the next one is fatal.
            self._restore()
            return
        self._requested = True
        # Telemetry: the preemption request is a lifecycle event every
        # later stall diagnosis wants on the timeline (observability/;
        # host-only, async-signal-cheap: one dict append + counter).
        from raft_ncup_tpu.observability import get_telemetry

        get_telemetry().event("preemption_signal", signum=int(signum))
        # stderr, not stdout: child stdout is a parsed protocol stream in
        # the test/bench harnesses around the trainer.
        print(
            f"preemption: received signal {signum}; will checkpoint and "
            "exit at the next step boundary",
            file=sys.stderr,
        )

    def __enter__(self) -> "PreemptionHandler":
        for s in self.signals:
            self._previous[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        self._restore()

    def _restore(self) -> None:
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except (OSError, ValueError) as e:  # non-main thread / teardown
                print(f"preemption: could not restore signal {s}: {e}",
                      file=sys.stderr)
        self._previous = {}

    def poll(self, step: int) -> bool:
        """Agreed should-we-stop decision at step boundary ``step``."""
        from raft_ncup_tpu.parallel.multihost import (
            allreduce_sum_across_hosts,
            is_multihost,
        )

        if not is_multihost():
            return self._requested
        if step % self.check_every:
            return False
        import numpy as np

        flag = np.asarray(int(self._requested), np.int32)
        return bool(allreduce_sum_across_hosts(flag) > 0)
