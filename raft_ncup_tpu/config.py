"""Typed, immutable configuration for models, training, and data.

The reference drives everything through argparse plus a reflective flag
generator (reference: core/utils/args.py:8-114) and mutates ``args`` from
inside model constructors (reference: core/raft.py:32-42). Here the full
used surface of those flags (reference: train_raft_nc_things.sh:19-50) is
captured as frozen dataclasses resolved *before* model construction.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Sequence

# The FlyingChairs train/val split is defined by a 22,871-line 1/2-label
# file the reference ships at its root (reference: chairs_split.txt,
# loaded at core/datasets.py:128). It is vendored as package data so the
# chairs stage works out of the box (22,232 train / 640 val pairs).
PACKAGED_CHAIRS_SPLIT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "data", "chairs_split.txt"
)


@dataclass(frozen=True)
class UpsamplerConfig:
    """Configuration of the final flow upsampler.

    Mirrors the capability surface of the reference upsampler factory
    (reference: core/upsampler.py:10-72) and the NConvUNet / weights-net
    constructor flags (reference: train_raft_nc_things.sh:31-50).
    """

    # 'nconv' (NCUP), 'bilinear', 'pac', 'djif'. The RAFT baseline's convex
    # upsampler is part of the model itself, not this registry — as in the
    # reference (core/raft.py:73-84).
    kind: str = "nconv"
    # Upsampling factor applied by the upsampler itself. The NCUP path does
    # nearest x2 first and NCUP x4 after (reference: core/raft_nc_dbl.py:110).
    scale: int = 4
    use_data_for_guidance: bool = True
    channels_to_batch: bool = True
    use_residuals: bool = False
    est_on_high_res: bool = False

    # --- interpolation (NConvUNet) net (reference: core/nconv_modules.py:25-92)
    channels_multiplier: int = 2
    num_downsampling: int = 1
    encoder_filter_sz: int = 5
    decoder_filter_sz: int = 3
    out_filter_sz: int = 1
    use_bias: bool = False
    data_pooling: str = "conf_based"  # 'conf_based' | 'max_pooling'
    shared_encoder: bool = True
    use_double_conv: bool = False
    pos_fn: str = "softplus"  # 'softplus' | 'exp' | 'sigmoid' | 'softmax'

    # --- weights estimation net (reference: core/interp_weights_est.py:10-82)
    weights_est_net: str = "simple"  # 'simple' | 'unet' | 'binary'
    weights_est_num_ch: tuple[int, ...] = (64, 32)
    weights_est_filter_sz: tuple[int, ...] = (3, 3, 1)
    weights_est_dilation: tuple[int, ...] = (1, 1, 1)


@dataclass(frozen=True)
class ModelConfig:
    """Model architecture configuration.

    ``variant`` selects between the working model set of the reference:
    'raft' (reference: core/raft.py) and 'raft_nc_dbl' (reference:
    core/raft_nc_dbl.py). hidden/context dims and correlation geometry
    follow reference: core/raft.py:29-39.
    """

    variant: str = "raft_nc_dbl"  # 'raft' | 'raft_nc_dbl'
    small: bool = False
    dropout: float = 0.0
    # Precision-policy preset (raft_ncup_tpu/precision/; docs/PRECISION.md):
    # 'f32' | 'bf16_infer' | 'bf16_train'. The resolved PrecisionPolicy is
    # the single authority for every dtype on the hot path — module compute,
    # correlation volume, Pallas VMEM budgeting — with coords/metrics/
    # upsampler/master-weights pinned f32 by the policy itself.
    precision: str = "f32"
    # Legacy bool knob, kept for the reference CLI surface
    # (--mixed_precision): True with the default precision resolves to
    # the 'bf16_infer' preset. DELIBERATE divergence from the reference's
    # CUDA AMP autocast (core/raft.py:100-112): under the policy the
    # correlation volume now narrows too — it is the dominant memory
    # term, and parity is test-pinned rather than assumed
    # (docs/PRECISION.md; CHANGES.md PR 7). An explicit `precision` wins
    # (the CLI sets mixed_precision=False whenever --precision is given).
    mixed_precision: bool = False
    # align_corners for the bilinear x8 upsampling used by the small/no-mask
    # path (reference: core/raft.py:134; fixes the upflow8 signature bug
    # noted in SURVEY.md §0.3).
    align_corners: bool = True
    corr_levels: int = 4
    corr_radius: int = 4
    # 'volume' materializes the all-pairs volume (reference semantics,
    # core/corr.py:13-21); 'onthefly' recomputes windowed correlation per
    # lookup (memory-efficient for 1080p); 'pallas' = fused TPU kernel.
    corr_impl: str = "volume"
    # Dataset the model is configured for. Controls BatchNorm in the NCUP
    # weights-estimation net: ON for sintel, OFF otherwise (reference:
    # core/upsampler.py:41-46 — and carried everywhere to avoid the
    # reference's missing-``args.dataset`` crash, SURVEY.md §0.2).
    dataset: str = "sintel"
    # Freeze the RAFT trunk and train only the NCUP upsampler (reference:
    # core/raft_nc_dbl.py:70-72).
    freeze_raft: bool = False
    upsampler: UpsamplerConfig = field(default_factory=UpsamplerConfig)

    def __post_init__(self) -> None:
        if self.variant not in ("raft", "raft_nc_dbl"):
            raise ValueError(f"unknown model variant: {self.variant!r}")
        from raft_ncup_tpu.precision import resolve_policy

        resolve_policy(self.precision)  # raises on an unknown preset

    @property
    def precision_policy(self):
        """The resolved :class:`~raft_ncup_tpu.precision.PrecisionPolicy`
        (the legacy ``mixed_precision`` bool maps onto 'bf16_infer' when
        no explicit preset was chosen)."""
        from raft_ncup_tpu.precision import resolve_policy

        if self.precision == "f32" and self.mixed_precision:
            return resolve_policy("bf16_infer")
        return resolve_policy(self.precision)

    @property
    def hidden_dim(self) -> int:
        return 96 if self.small else 128

    @property
    def context_dim(self) -> int:
        return 64 if self.small else 128

    @property
    def fnet_dim(self) -> int:
        return 128 if self.small else 256

    @property
    def resolved_corr_radius(self) -> int:
        # reference: core/raft.py:29-39 — the model overrides the radius.
        return 3 if self.small else self.corr_radius

    @property
    def corr_planes(self) -> int:
        r = self.resolved_corr_radius
        return self.corr_levels * (2 * r + 1) ** 2


@dataclass(frozen=True)
class TrainConfig:
    """Training hyperparameters (reference: train.py:264-297 defaults and
    the shipped launch scripts, e.g. train_raft_nc_things.sh:24-31)."""

    name: str = "raft"
    stage: str = "chairs"  # 'chairs' | 'things' | 'sintel' | 'kitti'
    lr: float = 2e-5
    num_steps: int = 100_000
    batch_size: int = 6
    image_size: tuple[int, int] = (384, 512)
    iters: int = 12
    wdecay: float = 5e-5
    epsilon: float = 1e-8
    clip: float = 1.0
    gamma: float = 0.8
    max_flow: float = 400.0
    optimizer: str = "adamw"  # 'adamw' | 'adam'
    scheduler: str = "cyclic"  # 'cyclic' (OneCycle-linear) | 'step'
    scheduler_step: int = 20_000
    add_noise: bool = False
    validation: tuple[str, ...] = ()
    val_freq: int = 5000
    sum_freq: int = 100
    seed: int = 1234
    restore_ckpt: str | None = None
    load_pretrained: str | None = None
    checkpoint_dir: str = "checkpoints"
    # parallelism: data-parallel size (None = all devices) and spatial size.
    data_parallel: int | None = None
    spatial_parallel: int = 1
    # --- divergence sentinel (resilience/anomaly.py; docs/RESILIENCE.md).
    # Folded into the jitted step when enabled: non-finite loss/grad and
    # grad-norm spikes become skip-updates (state unchanged), counted on
    # device; K consecutive bad steps halt the run with a rollback.
    # Default ON so the CLI, the library, and the bench all compile the
    # SAME production step program — a sentinel-off bench would never see
    # a sentinel-induced throughput regression.
    anomaly_sentinel: bool = True
    sentinel_spike_factor: float = 20.0  # grad_norm > factor * EMA = spike
    sentinel_ema_decay: float = 0.99
    sentinel_warmup: int = 10  # good steps before spike detection arms
    sentinel_halt_after: int = 10  # K consecutive bad steps => halt
    # Training precision preset (docs/PRECISION.md): 'f32' or 'bf16_train'
    # (bf16 module compute with f32 master weights; loss/grad-norm/
    # sentinel arithmetic stays f32 because the param leaves do). The CLI
    # threads this into ModelConfig.precision so the step program and the
    # policy agree; bookkept here so checkpoints' resume metadata and the
    # bench's train rows can say which phase opted in.
    precision: str = "f32"

    def __post_init__(self) -> None:
        from raft_ncup_tpu.precision import resolve_policy

        resolve_policy(self.precision)  # raises on an unknown preset

    @property
    def total_schedule_steps(self) -> int:
        # reference: train.py:93-94 — OneCycle over num_steps + 100.
        return self.num_steps + 100


@dataclass(frozen=True)
class DataConfig:
    """Dataset roots and pipeline knobs (reference: core/datasets.py)."""

    root_chairs: str = "datasets/FlyingChairs_release/data"
    root_things: str = "datasets/FlyingThings3D"
    root_sintel: str = "datasets/Sintel"
    root_kitti: str = "datasets/KITTI"
    root_hd1k: str = "datasets/HD1k"
    chairs_split_file: str = PACKAGED_CHAIRS_SPLIT
    compressed_ft: bool = False
    num_workers: int = 2
    prefetch: int = 2
    # Device-side prefetch depth: host batches are moved to device this
    # many steps ahead of compute (DevicePrefetcher). >= 2 keeps one batch
    # in flight while the next transfers, so the accelerator never waits
    # on host→device transfer in steady state.
    device_prefetch: int = 2
    # Transient-IO resilience (resilience/retry.py): failed dataset reads
    # are retried with exponential backoff this many times before the
    # sample is quarantined and substituted; accounting lands in log.txt.
    io_retries: int = 3
    io_retry_backoff_s: float = 0.05
    # --- eval/inference pipeline (inference/pipeline.py) ----------------
    # Bound on the shape-cached compiled eval executables (LRU). Each
    # distinct (padded shape, iters, metric kind) compiles once; KITTI's
    # native-shape diversity is what the bound protects against —
    # evictions are counted and logged loudly.
    eval_cache_size: int = 8
    # Round padded eval shapes up to multiples of this bucket (0 = off).
    # Collapses KITTI's couple-dozen native resolutions onto a small
    # fixed shape set so the executable count is known up front. Must be
    # a multiple of 8 when set; applied to the KITTI validator/submission.
    eval_pad_bucket: int = 0
    # When no dataset is present on disk, the loader can serve procedurally
    # generated pairs so training/benchmarking still exercises the full path.
    synthetic_ok: bool = False
    # Procedural generator: "smooth" (dense smooth flow) or "rigid"
    # (piecewise-rigid scenes with sharp motion boundaries + occlusion —
    # the split that can separate NCUP from bilinear upsampling).
    synthetic_style: str = "smooth"


def _check_mesh_field(mesh, batch_sizes: tuple, pad_bucket: int = 0) -> None:
    """Shared (data, spatial[, pipe]) mesh-field validation for the
    serving and streaming configs: jit's in_shardings require every
    allowed batch size to divide the `data` axis, and under a mesh
    every pad rounds to 8*spatial, so an explicit ``pad_bucket`` must
    be a multiple of that divisor (InputPadder rejects the combination
    per call — a violation must be a clear error at config time, not
    an exception escaping FlowServer.submit() past the terminal-status
    contract). An optional third element is the ``pipe`` axis
    (parallel/mesh.py; docs/SHARDING.md "Pipeline axis") — it shards
    neither the batch nor the image dims, so it adds nothing to either
    rule here."""
    if mesh is None:
        return
    m = tuple(int(x) for x in mesh)
    if len(m) not in (2, 3) or any(x < 1 for x in m):
        raise ValueError(
            f"mesh must be (data, spatial[, pipe]) positive sizes: {mesh!r}"
        )
    data, spatial = m[0], m[1]
    bad = [b for b in batch_sizes if b % data]
    if bad:
        raise ValueError(
            f"batch sizes {bad} are not divisible by mesh data={data}; "
            "every allowed batch program shards its batch axis over the "
            "data mesh axis"
        )
    if pad_bucket and pad_bucket % (8 * spatial):
        raise ValueError(
            f"pad_bucket {pad_bucket} must be a multiple of the mesh "
            f"pad divisor 8*spatial = {8 * spatial}"
        )


@dataclass(frozen=True)
class ServeConfig:
    """Online flow-serving knobs (raft_ncup_tpu/serving/; docs/SERVING.md).

    The executable-set arithmetic the bounds below control: every
    compiled serving program is keyed by (padded shape, batch size,
    iteration level), so the steady-state program count is
    ``n_padded_shapes x len(batch_sizes) x len(iter_levels)`` —
    ``pad_bucket`` bounds the first factor, the two fixed tuples bound
    the rest, and ``cache_size`` must be at least their product or the
    LRU evicts programs the next burst re-pays (ShapeCachedForward logs
    evictions loudly).
    """

    # Admission-queue capacity: the backpressure contract. Open-loop
    # arrivals + an unbounded queue = unbounded p99; a full queue sheds
    # with an explicit retry_after_s hint instead of queueing.
    queue_capacity: int = 64
    # Allowed batch programs, ascending. A micro-batch is padded up to
    # the nearest size with zero rows so the batch dimension never
    # compiles a fresh executable mid-burst.
    batch_sizes: tuple[int, ...] = (1, 2, 4)
    # Anytime iteration budget levels, descending quality (serving/
    # budget.py). Level 0 is the idle-load quality; under burst the
    # controller walks down one level per high-water observation.
    iter_levels: tuple[int, ...] = (24, 16, 8)
    high_water: float = 0.75  # occupancy that degrades one level (fast)
    low_water: float = 0.25  # occupancy that counts toward recovery
    recover_patience: int = 4  # consecutive calm decisions to recover
    # Default per-request deadline (seconds from admission; None = no
    # deadline). Expired requests get a `timeout` response at batch
    # assembly, before any compute is spent on them.
    default_deadline_s: float | None = None
    # Shed hint when no service-time estimate exists yet.
    default_retry_after_s: float = 0.25
    # Round padded request shapes up to multiples of this bucket (0 =
    # off; must be a multiple of 8) — same knob as eval_pad_bucket, so
    # mixed native resolutions batch together and the padded-shape
    # factor of the executable set stays small.
    pad_bucket: int = 0
    # ShapeCachedForward LRU bound; >= the executable-set product above.
    cache_size: int = 16
    # DispatchThrottle in-flight bound (None = per-backend default:
    # 1 on CPU, 2 on accelerators).
    inflight: int | None = None
    # AsyncDrain queue depth (bounds device memory pinned by pulls).
    drain_depth: int = 2
    # Admission shape limits: smaller than min breaks the feature
    # pyramid; larger than max is rejected rather than compiled. The
    # ceiling is UHD (2176x3840 = 4K padded to /8): the banded Pallas
    # corr tier (ops/corr_pallas.py) keeps every pyramid level on a
    # kernel tier at that shape, and the onthefly fallback bounds the
    # working set, so a 4K request is servable rather than a
    # memory-wall crash (docs/PERF.md "Banded dispatch").
    min_image_hw: int = 16
    max_image_hw: tuple[int, int] = (2176, 3840)
    # Per-ServeConfig precision policy (docs/PRECISION.md): the server's
    # whole executable set compiles under this preset, and the policy
    # name is part of every compiled-program key, so two servers (or one
    # redeployed with a different preset) can never collide executables.
    # None (default) inherits the model's own policy — a server wrapped
    # around a bf16-configured model serves bf16 unless told otherwise.
    precision: str | None = None
    # (data, spatial[, pipe]) device-mesh sizes (docs/SHARDING.md): the
    # server's whole executable set compiles as SPMD programs over this
    # mesh — request batches shard over `data`, image height over
    # `spatial` (pads round up to 8*spatial so the 1/8-res feature
    # height divides the spatial axis); an optional third size is the
    # iteration-pipeline axis (docs/SHARDING.md "Pipeline axis"), which
    # shards neither. The mesh fingerprint rides every compiled-program
    # key. None (default) = unsharded single-device serving.
    mesh: tuple[int, ...] | None = None

    def __post_init__(self) -> None:
        if self.precision is not None:
            from raft_ncup_tpu.precision import resolve_policy

            resolve_policy(self.precision)  # raises on an unknown preset
        bs = tuple(int(b) for b in self.batch_sizes)
        if not bs or any(b <= 0 for b in bs) or list(bs) != sorted(set(bs)):
            raise ValueError(
                f"batch_sizes must be ascending unique positives: {bs!r}"
            )
        _check_mesh_field(self.mesh, bs, self.pad_bucket)
        lv = tuple(int(x) for x in self.iter_levels)
        if not lv or any(x <= 0 for x in lv) or list(lv) != sorted(
            lv, reverse=True
        ) or len(set(lv)) != len(lv):
            raise ValueError(
                f"iter_levels must be strictly descending positives: {lv!r}"
            )

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]


@dataclass(frozen=True)
class StreamConfig:
    """Streaming video engine knobs (raft_ncup_tpu/streaming/;
    docs/STREAMING.md).

    One engine serves ONE padded frame shape: every admitted frame must
    pad (``InputPadder(mode='sintel', bucket=pad_bucket)``) to the same
    (H, W) the slot table was allocated at, so the executable set is
    exactly ``len(batch_sizes)`` programs and a stream lifecycle event
    (admission, eviction, anomaly reset, slot reuse) can never compile
    anything. ``capacity`` bounds the device slot table — the HBM
    contract: per-stream recurrent state is ``h/8 * w/8 * (2 +
    hidden_dim if carry_net)`` floats, allocated once, never grown.
    """

    # Concurrent-stream bound = slot-table size. Stream admission beyond
    # it sheds with a retry_after hint (soonest idle-expiry), it never
    # queues: a stream that cannot get a slot cannot make progress.
    capacity: int = 8
    # Native frame size the engine serves (frames whose PADDED shape
    # matches are also admitted — pad bucketing collapses near-identical
    # camera resolutions onto one slot-table shape). Any /8-padded shape
    # up to UHD (2176, 3840) is warmable: the banded corr tier keeps 4K
    # per-level lookups on-kernel (ops/corr_pallas.py; docs/PERF.md
    # "Banded dispatch").
    frame_hw: tuple[int, int] = (96, 128)
    pad_bucket: int = 0  # same semantics as ServeConfig.pad_bucket
    iters: int = 12  # fixed GRU iterations (one executable per batch size)
    # Allowed batch programs, ascending (zero-row padding up to the
    # nearest size, exactly like serving). A batch never holds two
    # frames of the SAME stream — state must flow through the slot table
    # between them — so sizes beyond `capacity` are never filled.
    batch_sizes: tuple[int, ...] = (1, 2, 4)
    # Frame admission queue bound (frames, across all streams).
    queue_capacity: int = 64
    # Warm-start staleness: a frame whose index gap to the previously
    # ADMITTED frame of its stream exceeds this warm-starts from COLD
    # (never from stale state). 1 = only strictly consecutive frames
    # may warm-start.
    max_frame_gap: int = 1
    # Idle/abandoned-stream eviction: a stream with no admitted frame
    # for this long (and nothing in flight) loses its slot.
    idle_timeout_s: float = 30.0
    # Also carry the GRU hidden state (net) across frames, not just the
    # forward-splatted flow. OFF by default: the reference's warm-start
    # carries flow only (core/utils/utils.py:28-56); net carry is an
    # extension and changes numerics vs the reference eval.
    carry_net: bool = False
    # In-graph anomaly bound: a frame whose low-res flow is non-finite
    # or exceeds this magnitude resets ITS stream's slot to cold-start
    # (batch-mates untouched).
    anomaly_max_flow: float = 1e4
    # Shed hint before any service-time estimate exists.
    default_retry_after_s: float = 0.25
    # ShapeCachedForward LRU bound; >= len(batch_sizes) (+1 when the
    # engine shares its cache with a warmstart splat program).
    cache_size: int = 8
    inflight: int | None = None  # DispatchThrottle bound (None = default)
    drain_depth: int = 2  # AsyncDrain queue depth
    # Query-chunk size of the in-graph warm-start splat
    # (ops/warmstart.forward_interpolate_jax): bounds the transient
    # distance matrix at chunk * (h/8 * w/8) * 4 bytes per stream row.
    splat_chunk: int = 1024
    # Per-engine precision policy (docs/PRECISION.md). Under the bf16
    # presets the slot table's recurrent state (prev low-res flow,
    # optional GRU net) is STORED in bf16 — halving per-stream HBM —
    # while the warm-start splat and coordinate arithmetic upcast to the
    # policy's pinned f32 coord dtype in-graph. None (default) inherits
    # the model's own policy.
    precision: str | None = None
    # (data, spatial[, pipe]) device-mesh sizes (docs/SHARDING.md): the
    # step programs compile as SPMD over this mesh — frame batches shard
    # over `data`, frame height over `spatial`, and the slot table
    # shards over `data` when (capacity + 1) divides it (else it
    # replicates). Frames pad to 8*spatial. None (default) = unsharded.
    mesh: tuple[int, ...] | None = None

    def __post_init__(self) -> None:
        if self.precision is not None:
            from raft_ncup_tpu.precision import resolve_policy

            resolve_policy(self.precision)  # raises on an unknown preset
        bs = tuple(int(b) for b in self.batch_sizes)
        if not bs or any(b <= 0 for b in bs) or list(bs) != sorted(set(bs)):
            raise ValueError(
                f"batch_sizes must be ascending unique positives: {bs!r}"
            )
        _check_mesh_field(self.mesh, bs, self.pad_bucket)
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1: {self.capacity}")
        if self.iters < 1:
            raise ValueError(f"iters must be >= 1: {self.iters}")
        if self.max_frame_gap < 1:
            raise ValueError(
                f"max_frame_gap must be >= 1: {self.max_frame_gap}"
            )

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]


def _to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _to_jsonable(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    return obj


def config_to_json(cfg: Any) -> str:
    return json.dumps(_to_jsonable(cfg), indent=2, sort_keys=True)


def _from_dict(cls: type, d: dict) -> Any:
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in d:
            continue
        v = d[f.name]
        if f.name == "upsampler" and isinstance(v, dict):
            v = _from_dict(UpsamplerConfig, v)
        elif isinstance(v, list):
            v = tuple(tuple(x) if isinstance(x, list) else x for x in v)
        kwargs[f.name] = v
    return cls(**kwargs)


def model_config_from_json(s: str) -> ModelConfig:
    return _from_dict(ModelConfig, json.loads(s))


def small_model_config(variant: str = "raft", **overrides: Any) -> ModelConfig:
    """RAFT-small preset (reference: core/raft.py:29-33)."""
    return ModelConfig(variant=variant, small=True, **overrides)


def flagship_config(dataset: str = "sintel", **overrides: Any) -> ModelConfig:
    """The configuration every shipped reference script trains/evaluates:
    raft_nc_dbl with the NCUP upsampler (reference:
    train_raft_nc_things.sh:19-50)."""
    return ModelConfig(variant="raft_nc_dbl", dataset=dataset, **overrides)
