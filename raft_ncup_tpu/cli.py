"""Reference-compatible command-line interface.

Accepts the flag surface of the reference's argparse blocks plus its
reflective flag generator (reference: train.py:264-343,
core/utils/args.py:8-114) — including ``--final_upsampling=NConvUpsampler``
-style class-choice flags and ``"[3, 3, 1]"`` int-list values — and
resolves everything into the typed frozen configs of
``raft_ncup_tpu.config`` before any model is built (the reference instead
mutates ``args`` inside model constructors; SURVEY.md §3.4).

TPU-specific additions (not in the reference): ``--data_parallel``,
``--spatial_parallel`` mesh sizes, per-dataset root overrides, and
``--synthetic_ok`` for data-free smoke runs.
"""

from __future__ import annotations

import argparse
import ast
from typing import Optional, Sequence

from raft_ncup_tpu.utils.knobs import knob_raw

from raft_ncup_tpu.config import (
    DataConfig,
    ModelConfig,
    ServeConfig,
    StreamConfig,
    TrainConfig,
    UpsamplerConfig,
)


def str2bool(v: str) -> bool:
    if isinstance(v, bool):
        return v
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError(f"boolean value expected, got {v!r}")


def str2intlist(v: str) -> tuple[int, ...]:
    """Parse the reference's quoted list syntax ``"[3, 3, 1]"``
    (reference: core/utils/args.py:174-175)."""
    out = ast.literal_eval(v)
    if not isinstance(out, (list, tuple)):
        raise argparse.ArgumentTypeError(f"int list expected, got {v!r}")
    return tuple(int(x) for x in out)


_UPSAMPLER_CLASSES = {
    # reference class names (core/upsampler.py) -> our registry kinds
    "NConvUpsampler": "nconv",
    "Bilinear": "bilinear",
    "PacJointUpsampleFull": "pac",
    "DjifOriginal": "djif",
}


def add_model_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--model", default="raft", help="model variant (train/eval) ")
    parser.add_argument("--small", action="store_true")
    parser.add_argument("--dropout", type=float, default=0.0)
    parser.add_argument("--mixed_precision", action="store_true")
    parser.add_argument("--precision", default=None,
                        choices=["f32", "bf16_infer", "bf16_train"],
                        help="precision-policy preset (docs/PRECISION.md): "
                        "the single dtype authority for the hot path. "
                        "'bf16_infer' for eval/serving, 'bf16_train' for "
                        "bf16-compute training with f32 master weights; "
                        "coords/metrics/upsampler stay f32 under every "
                        "preset. Overrides --mixed_precision when set.")
    parser.add_argument("--align_corners", action="store_true")
    parser.add_argument("--upsampler_bi", action="store_true",
                        help="use bilinear final upsampling")
    parser.add_argument("--freeze_raft", action="store_true")
    parser.add_argument("--load_pretrained", default=None)
    parser.add_argument("--corr_impl", default="volume",
                        choices=["volume", "onthefly", "pallas"])

    # --- reflective upsampler flags (reference: train.py:300-343)
    parser.add_argument("--final_upsampling", default="NConvUpsampler",
                        choices=sorted(_UPSAMPLER_CLASSES))
    parser.add_argument("--final_upsampling_scale", type=int, default=4)
    parser.add_argument("--final_upsampling_use_data_for_guidance",
                        type=str2bool, default=True)
    parser.add_argument("--final_upsampling_channels_to_batch",
                        type=str2bool, default=True)
    parser.add_argument("--final_upsampling_use_residuals",
                        type=str2bool, default=False)
    parser.add_argument("--final_upsampling_est_on_high_res",
                        type=str2bool, default=False)
    parser.add_argument("--interp_net", default="NConvUNet",
                        choices=["NConvUNet"])
    parser.add_argument("--interp_net_channels_multiplier", type=int, default=2)
    parser.add_argument("--interp_net_num_downsampling", type=int, default=1)
    parser.add_argument("--interp_net_data_pooling", default="conf_based",
                        choices=["conf_based", "max_pooling"])
    parser.add_argument("--interp_net_encoder_filter_sz", type=int, default=5)
    parser.add_argument("--interp_net_decoder_filter_sz", type=int, default=3)
    parser.add_argument("--interp_net_out_filter_sz", type=int, default=1)
    parser.add_argument("--interp_net_shared_encoder", type=str2bool, default=True)
    parser.add_argument("--interp_net_use_double_conv", type=str2bool, default=False)
    parser.add_argument("--interp_net_use_bias", type=str2bool, default=False)
    parser.add_argument("--interp_net_pos_fn", default="softplus")
    parser.add_argument("--weights_est_net", default="Simple",
                        choices=["Simple", "UNet"])
    parser.add_argument("--weights_est_net_num_ch", type=str2intlist,
                        default=(64, 32))
    parser.add_argument("--weights_est_net_filter_sz", type=str2intlist,
                        default=(3, 3, 1))
    parser.add_argument("--weights_est_net_dilation", type=str2intlist,
                        default=(1, 1, 1))


def add_platform_arg(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--platform", default=knob_raw("RAFT_NCUP_PLATFORM"),
        help="force the jax platform (e.g. 'cpu', 'tpu'). The container's "
        "boot hook bakes its accelerator platform into jax.config at "
        "interpreter start — env JAX_PLATFORMS alone cannot override it, "
        "and a wedged accelerator backend hangs inside jax.devices() — so "
        "this is applied via jax.config.update before any device use. "
        "Env fallback: RAFT_NCUP_PLATFORM.",
    )


def apply_platform(args: argparse.Namespace) -> None:
    if getattr(args, "platform", None):
        from raft_ncup_tpu.utils.runtime import force_platform

        force_platform(args.platform)


def add_data_args(parser: argparse.ArgumentParser) -> None:
    d = DataConfig()
    parser.add_argument("--root_chairs", default=d.root_chairs)
    parser.add_argument("--root_things", default=d.root_things)
    parser.add_argument("--root_sintel", default=d.root_sintel)
    parser.add_argument("--root_kitti", default=d.root_kitti)
    parser.add_argument("--root_hd1k", default=d.root_hd1k)
    parser.add_argument("--chairs_split_file", default=d.chairs_split_file)
    parser.add_argument("--compressed_ft", action="store_true")
    parser.add_argument("--num_workers", type=int, default=4)
    parser.add_argument("--device_prefetch", type=int, default=d.device_prefetch,
                        help="device-side prefetch depth: batches staged on "
                        "device ahead of compute (>=2 hides the transfer)")
    parser.add_argument("--io_retries", type=int, default=d.io_retries,
                        help="bounded-backoff retries for failed dataset "
                        "reads before quarantining the sample "
                        "(resilience/retry.py)")
    parser.add_argument("--eval_cache_size", type=int, default=d.eval_cache_size,
                        help="LRU bound on shape-cached compiled eval "
                        "executables (inference/pipeline.py); evictions "
                        "are counted and logged")
    parser.add_argument("--eval_pad_bucket", type=int, default=d.eval_pad_bucket,
                        help="round padded eval shapes up to multiples of "
                        "this bucket (0=off) so KITTI's shape diversity "
                        "compiles a small fixed executable set")
    parser.add_argument("--synthetic_ok", action="store_true",
                        help="fall back to procedural data if roots missing")
    parser.add_argument("--synthetic_style", default=d.synthetic_style,
                        choices=["smooth", "rigid"],
                        help="procedural generator for the fallback")


def str2ints(v: str) -> tuple[int, ...]:
    """Parse a bare comma list ``"24,16,8"`` (serving-tier flags)."""
    try:
        return tuple(int(x) for x in v.split(","))
    except ValueError:
        raise argparse.ArgumentTypeError(f"comma-joined ints expected: {v!r}")


def str2mesh(v: str) -> tuple[int, ...]:
    """Parse the ``--mesh DATA,SPATIAL[,PIPE]`` device-mesh spec."""
    out = str2ints(v)
    if len(out) not in (2, 3) or any(x < 1 for x in out):
        raise argparse.ArgumentTypeError(
            f"mesh spec must be DATA,SPATIAL[,PIPE] positive sizes: {v!r}"
        )
    return out


def add_mesh_arg(parser: argparse.ArgumentParser) -> None:
    """The (data x spatial[, pipe]) SPMD mesh flag shared by evaluate.py,
    serve.py, and bench.py (docs/SHARDING.md)."""
    parser.add_argument(
        "--mesh", type=str2mesh, default=None, metavar="DATA,SPATIAL[,PIPE]",
        help="run the inference/serving stack sharded on a "
        "(data x spatial[, pipe]) device mesh, e.g. '1,2' or '1,1,2' "
        "(docs/SHARDING.md). Batches shard over data, image height over "
        "spatial; pads round up to 8*spatial. A third element adds the "
        "iteration-pipeline axis (\"Pipeline axis\"). Default: unsharded.",
    )


def mesh_from_args(args: argparse.Namespace):
    """Build the jax Mesh named by ``--mesh`` (None when unset)."""
    spec = getattr(args, "mesh", None)
    if not spec:
        return None
    from raft_ncup_tpu.parallel.mesh import make_mesh

    pipe = spec[2] if len(spec) == 3 else 1
    return make_mesh(data=spec[0], spatial=spec[1], pipe=pipe)


def add_serve_args(parser: argparse.ArgumentParser) -> None:
    """Serving-tier knobs (ServeConfig; raft_ncup_tpu/serving/,
    docs/SERVING.md)."""
    d = ServeConfig()
    parser.add_argument("--queue_capacity", type=int,
                        default=d.queue_capacity,
                        help="bounded admission queue size; a full queue "
                        "sheds with an explicit retry-after hint")
    parser.add_argument("--serve_batch_sizes", type=str2ints,
                        default=d.batch_sizes,
                        help="allowed micro-batch programs, ascending "
                        "(e.g. '1,2,4'); batches pad up to the nearest "
                        "size so the executable set stays fixed")
    parser.add_argument("--iter_levels", type=str2ints,
                        default=d.iter_levels,
                        help="anytime GRU iteration budget levels, "
                        "descending quality (e.g. '24,16,8'); the "
                        "controller walks down under burst")
    parser.add_argument("--high_water", type=float, default=d.high_water,
                        help="queue occupancy that degrades the budget "
                        "one level (immediate)")
    parser.add_argument("--low_water", type=float, default=d.low_water,
                        help="occupancy counting toward budget recovery")
    parser.add_argument("--recover_patience", type=int,
                        default=d.recover_patience,
                        help="consecutive calm decisions before the "
                        "budget recovers one level (hysteresis)")
    parser.add_argument("--deadline_s", type=float,
                        default=d.default_deadline_s,
                        help="default per-request deadline in seconds "
                        "(unset = no deadline); expired requests get a "
                        "timeout response before any compute")
    parser.add_argument("--serve_pad_bucket", type=int, default=d.pad_bucket,
                        help="round padded request shapes up to multiples "
                        "of this bucket (0=off) so mixed resolutions "
                        "batch together")
    parser.add_argument("--serve_cache_size", type=int, default=d.cache_size,
                        help="compiled-executable LRU bound; keep >= "
                        "shapes x batch_sizes x iter_levels")
    parser.add_argument("--serve_precision", default=d.precision,
                        choices=["f32", "bf16_infer", "bf16_train"],
                        help="precision-policy preset the server's whole "
                        "executable set compiles under "
                        "(docs/PRECISION.md); part of every compiled-"
                        "program key. Default: inherit the model's policy")


def serve_config_from_args(args: argparse.Namespace) -> ServeConfig:
    return ServeConfig(
        queue_capacity=args.queue_capacity,
        batch_sizes=tuple(args.serve_batch_sizes),
        iter_levels=tuple(args.iter_levels),
        high_water=args.high_water,
        low_water=args.low_water,
        recover_patience=args.recover_patience,
        default_deadline_s=args.deadline_s,
        pad_bucket=args.serve_pad_bucket,
        cache_size=args.serve_cache_size,
        precision=args.serve_precision,
        mesh=getattr(args, "mesh", None),
    )


def add_stream_args(parser: argparse.ArgumentParser) -> None:
    """Streaming-engine knobs (StreamConfig; raft_ncup_tpu/streaming/,
    docs/STREAMING.md)."""
    d = StreamConfig()
    parser.add_argument("--stream_capacity", type=int, default=d.capacity,
                        help="slot-table size = concurrent-stream bound; "
                        "admission beyond it sheds with a retry hint")
    parser.add_argument("--stream_batch_sizes", type=str2ints,
                        default=d.batch_sizes,
                        help="allowed step programs, ascending (e.g. "
                        "'1,2,4'); one executable per size, compiled at "
                        "warmup")
    parser.add_argument("--stream_iters", type=int, default=d.iters,
                        help="fixed GRU iterations per frame")
    parser.add_argument("--stream_queue_capacity", type=int,
                        default=d.queue_capacity,
                        help="bounded frame admission queue (frames, "
                        "across all streams)")
    parser.add_argument("--max_frame_gap", type=int, default=d.max_frame_gap,
                        help="frame-index gap beyond which warm state is "
                        "stale and the frame cold-starts")
    parser.add_argument("--idle_timeout_s", type=float,
                        default=d.idle_timeout_s,
                        help="idle/abandoned streams lose their slot "
                        "after this long with nothing in flight")
    parser.add_argument("--carry_net", type=str2bool, nargs="?",
                        const=True, default=d.carry_net,
                        help="also carry the GRU hidden state across "
                        "frames (extension beyond the reference's "
                        "flow-only warm start)")
    parser.add_argument("--anomaly_max_flow", type=float,
                        default=d.anomaly_max_flow,
                        help="in-graph divergence bound: low-res flow "
                        "beyond this resets the stream to cold start")
    parser.add_argument("--stream_pad_bucket", type=int,
                        default=d.pad_bucket,
                        help="round padded frame shapes up to multiples "
                        "of this bucket (0=off)")
    parser.add_argument("--stream_precision", default=d.precision,
                        choices=["f32", "bf16_infer", "bf16_train"],
                        help="precision-policy preset for the engine's "
                        "step programs AND the slot-table state dtype "
                        "(bf16 halves per-stream HBM; docs/PRECISION.md). "
                        "Default: inherit the model's policy")


def stream_config_from_args(
    args: argparse.Namespace, frame_hw: tuple[int, int]
) -> StreamConfig:
    return StreamConfig(
        capacity=args.stream_capacity,
        frame_hw=tuple(frame_hw),
        pad_bucket=args.stream_pad_bucket,
        iters=args.stream_iters,
        batch_sizes=tuple(args.stream_batch_sizes),
        queue_capacity=args.stream_queue_capacity,
        max_frame_gap=args.max_frame_gap,
        idle_timeout_s=args.idle_timeout_s,
        carry_net=args.carry_net,
        anomaly_max_flow=args.anomaly_max_flow,
        precision=args.stream_precision,
        mesh=getattr(args, "mesh", None),
    )


def add_train_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--name", default="raft")
    parser.add_argument("--stage", required=True,
                        choices=["chairs", "things", "sintel", "kitti"])
    parser.add_argument("--restore_ckpt", default=None)
    parser.add_argument("--validation", type=str, nargs="+", default=[])
    parser.add_argument("--lr", type=float, default=0.00002)
    parser.add_argument("--num_steps", type=int, default=100000)
    parser.add_argument("--batch_size", type=int, default=6)
    parser.add_argument("--image_size", type=int, nargs="+",
                        default=[384, 512])
    parser.add_argument("--gpus", type=int, nargs="+", default=None,
                        help="accepted for reference-script compatibility; "
                        "ignored (device mesh comes from --data_parallel)")
    parser.add_argument("--iters", type=int, default=12)
    parser.add_argument("--wdecay", type=float, default=0.00005)
    parser.add_argument("--epsilon", type=float, default=1e-8)
    parser.add_argument("--clip", type=float, default=1.0)
    parser.add_argument("--add_noise", action="store_true")
    parser.add_argument("--gamma", type=float, default=0.8)
    parser.add_argument("--optimizer", default="adamw", type=str.lower)
    parser.add_argument("--scheduler", default="cyclic")
    parser.add_argument("--scheduler_step", type=int, default=20000)
    parser.add_argument("--val_freq", type=int, default=5000)
    parser.add_argument("--sum_freq", type=int, default=100)
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument("--checkpoint_dir", default="checkpoints")
    parser.add_argument("--data_parallel", type=int, default=None,
                        help="data-parallel mesh size (default: all devices)")
    parser.add_argument("--spatial_parallel", type=int, default=1)
    parser.add_argument("--profile_steps", type=int, default=0,
                        help="capture a jax.profiler device trace of this "
                        "many early steps into <run_dir>/profile")
    parser.add_argument("--strict_guards", action="store_true",
                        help="assert the sync-free, recompile-free steady "
                        "state live: implicit host transfers inside the "
                        "step loop raise, and steady-state recompilation "
                        "fails the run (analysis/guards.py; docs/ANALYSIS.md)")
    # --- resilience (resilience/; docs/RESILIENCE.md) ------------------
    d = TrainConfig()
    parser.add_argument("--anomaly_sentinel", type=str2bool,
                        default=d.anomaly_sentinel,
                        help="fold the divergence sentinel into the jitted "
                        "step: non-finite loss/grad and grad-norm spikes "
                        "become skip-updates (state unchanged), counted on "
                        "device; K consecutive bad steps halt with rollback")
    parser.add_argument("--sentinel_spike_factor", type=float,
                        default=d.sentinel_spike_factor,
                        help="grad-norm above this multiple of its EMA "
                        "counts as a bad step")
    parser.add_argument("--sentinel_ema_decay", type=float,
                        default=d.sentinel_ema_decay)
    parser.add_argument("--sentinel_warmup", type=int,
                        default=d.sentinel_warmup,
                        help="good steps before spike detection arms")
    parser.add_argument("--sentinel_halt_after", type=int,
                        default=d.sentinel_halt_after,
                        help="consecutive bad steps that halt the run "
                        "(exit code 76, rollback to last good checkpoint)")
    parser.add_argument("--chaos",
                        default=knob_raw("RAFT_NCUP_CHAOS"),
                        help="deterministic fault injection for resilience "
                        "tests: comma-joined nan@STEP / ioerror@READ / "
                        "sigterm@STEP (resilience/chaos.py; env fallback "
                        "RAFT_NCUP_CHAOS)")


def model_config_from_args(
    args: argparse.Namespace, dataset: Optional[str] = None
) -> ModelConfig:
    """Resolve a ModelConfig. ``dataset`` controls upsampler BatchNorm
    (reference: core/upsampler.py:41-46) — for training it is the stage,
    for eval the --dataset flag."""
    kind = _UPSAMPLER_CLASSES[args.final_upsampling]
    if args.upsampler_bi:
        kind = "bilinear"
    ups = UpsamplerConfig(
        kind=kind,
        scale=args.final_upsampling_scale,
        use_data_for_guidance=args.final_upsampling_use_data_for_guidance,
        channels_to_batch=args.final_upsampling_channels_to_batch,
        use_residuals=args.final_upsampling_use_residuals,
        est_on_high_res=args.final_upsampling_est_on_high_res,
        channels_multiplier=args.interp_net_channels_multiplier,
        num_downsampling=args.interp_net_num_downsampling,
        encoder_filter_sz=args.interp_net_encoder_filter_sz,
        decoder_filter_sz=args.interp_net_decoder_filter_sz,
        out_filter_sz=args.interp_net_out_filter_sz,
        use_bias=args.interp_net_use_bias,
        data_pooling=args.interp_net_data_pooling,
        shared_encoder=args.interp_net_shared_encoder,
        use_double_conv=args.interp_net_use_double_conv,
        pos_fn=args.interp_net_pos_fn.lower(),
        weights_est_net=args.weights_est_net.lower(),
        weights_est_num_ch=tuple(args.weights_est_net_num_ch),
        weights_est_filter_sz=tuple(args.weights_est_net_filter_sz),
        weights_est_dilation=tuple(args.weights_est_net_dilation),
    )
    if dataset is None:
        dataset = getattr(args, "dataset", None) or getattr(args, "stage", "sintel")
    return ModelConfig(
        variant=args.model,
        small=args.small,
        dropout=args.dropout,
        # An explicit --precision (any preset, 'f32' included) wins over
        # the legacy --mixed_precision bool; only the unset default lets
        # the bool map to bf16_infer.
        precision=getattr(args, "precision", None) or "f32",
        mixed_precision=(
            args.mixed_precision
            and getattr(args, "precision", None) is None
        ),
        align_corners=args.align_corners,
        corr_impl=args.corr_impl,
        dataset=dataset,
        freeze_raft=args.freeze_raft,
        upsampler=ups,
    )


def train_config_from_args(args: argparse.Namespace) -> TrainConfig:
    size = args.image_size
    return TrainConfig(
        name=args.name,
        stage=args.stage,
        lr=args.lr,
        num_steps=args.num_steps,
        batch_size=args.batch_size,
        image_size=(size[0], size[1]),
        iters=args.iters,
        wdecay=args.wdecay,
        epsilon=args.epsilon,
        clip=args.clip,
        gamma=args.gamma,
        optimizer=args.optimizer,
        scheduler=args.scheduler,
        scheduler_step=args.scheduler_step,
        add_noise=args.add_noise,
        validation=tuple(args.validation),
        val_freq=args.val_freq,
        sum_freq=args.sum_freq,
        seed=args.seed,
        restore_ckpt=args.restore_ckpt,
        load_pretrained=args.load_pretrained,
        checkpoint_dir=args.checkpoint_dir,
        data_parallel=args.data_parallel,
        spatial_parallel=args.spatial_parallel,
        anomaly_sentinel=args.anomaly_sentinel,
        sentinel_spike_factor=args.sentinel_spike_factor,
        sentinel_ema_decay=args.sentinel_ema_decay,
        sentinel_warmup=args.sentinel_warmup,
        sentinel_halt_after=args.sentinel_halt_after,
        precision=getattr(args, "precision", None) or "f32",
    )


def data_config_from_args(args: argparse.Namespace) -> DataConfig:
    return DataConfig(
        root_chairs=args.root_chairs,
        root_things=args.root_things,
        root_sintel=args.root_sintel,
        root_kitti=args.root_kitti,
        root_hd1k=args.root_hd1k,
        chairs_split_file=args.chairs_split_file,
        compressed_ft=args.compressed_ft,
        num_workers=args.num_workers,
        device_prefetch=args.device_prefetch,
        eval_cache_size=args.eval_cache_size,
        eval_pad_bucket=args.eval_pad_bucket,
        io_retries=args.io_retries,
        synthetic_ok=args.synthetic_ok,
        synthetic_style=args.synthetic_style,
    )


def build_train_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Train RAFT / RAFT-NCUP on TPU (JAX)"
    )
    add_train_args(parser)
    add_model_args(parser)
    add_data_args(parser)
    add_platform_arg(parser)
    return parser


def build_eval_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Evaluate RAFT / RAFT-NCUP on TPU (JAX)"
    )
    parser.add_argument("--restore_ckpt", default=None,
                        help="orbax run dir or torch .pth")
    parser.add_argument("--dataset", required=True,
                        choices=["chairs", "sintel", "sintel_warm",
                                 "kitti"])
    parser.add_argument("--submission", action="store_true",
                        help="write leaderboard files instead of validating")
    parser.add_argument("--warm_start", action="store_true",
                        help="submission: warm-start each Sintel "
                        "sequence from the previous frame's device "
                        "forward-splat (validator analogue: --dataset "
                        "sintel_warm)")
    parser.add_argument("--write_png", action="store_true")
    parser.add_argument("--output_path", default=None)
    parser.add_argument("--export_pth", default=None, metavar="PATH",
                        help="write the loaded checkpoint as a reference-"
                             "keyed PyTorch .pth and exit")
    parser.add_argument("--spatial_parallel", type=int, default=1,
                        help="shard eval height over this many devices "
                        "(high-res inference; pairs with --corr_impl "
                        "onthefly). Shorthand for --mesh 1,N")
    add_mesh_arg(parser)
    parser.add_argument("--iters", type=int, default=None,
                        help="GRU iteration override; default keeps each "
                        "validator's reference setting (sintel 32, "
                        "chairs/kitti 24 — reference evaluate.py)")
    parser.add_argument("--batch_size", type=int, default=None,
                        help="validation batch-size override (default "
                        "keeps each validator's preset); frames group "
                        "per padded shape, short groups on shape change")
    add_model_args(parser)
    add_data_args(parser)
    add_platform_arg(parser)
    return parser


def parse_train(argv: Optional[Sequence[str]] = None):
    args = build_train_parser().parse_args(argv)
    apply_platform(args)
    model_cfg = model_config_from_args(args, dataset=args.stage)
    return args, model_cfg, train_config_from_args(args), data_config_from_args(args)


def parse_eval(argv: Optional[Sequence[str]] = None):
    args = build_eval_parser().parse_args(argv)
    apply_platform(args)
    model_cfg = model_config_from_args(args, dataset=args.dataset)
    return args, model_cfg, data_config_from_args(args)
