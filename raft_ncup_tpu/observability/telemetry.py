"""Host-only, thread-safe metrics registry: counters, gauges, and
fixed-bucket latency histograms.

One registry is the single place every subsystem's counters live — the
multi-GPU-abstraction pattern (PAPERS.md, arXiv:2606.11390: one
declarative object the whole stack reads) applied to observability: the
mesh made "where does this tensor live" one object; the registry makes
"what has this process done" one object. Producers (``ServeStats``,
``StreamStats``, ``RetryStats``, the span tracer, the admission queue)
mirror into it; consumers (``export.telemetry_report``, the Prometheus
dump, bench rows, ``flip_recommendations``) read it.

The platform's own hard constraint applies to telemetry itself:
**recording a metric must never touch a device array or add a sync**.
This module is pure stdlib — importing jax here is a JGL010 lint
violation — and every recorded value is validated to be a host number
(:func:`host_number` rejects anything from a ``jax*`` module *without*
converting it, because the conversion IS the sync).

Naming convention (the one ``snake_case`` scheme the satellite task
consolidates; docs/OBSERVABILITY.md has the full table):

- counters: ``{subsystem}_{object}_{event}_total`` — e.g.
  ``serve_requests_shed_total``, ``stream_slots_reset_total``,
  ``io_retries_total``;
- gauges:   ``{subsystem}_{quantity}`` — e.g. ``serve_queue_depth``,
  ``stream_service_time_ema_ms``;
- histograms: ``{subsystem}_{stage}_ms`` — per-stage latency, always
  milliseconds — e.g. ``serve_queue_wait_ms``, ``stream_dispatch_ms``.

Every *legacy* ``report()``/``summary()`` key keeps working verbatim —
:data:`LEGACY_KEY_ALIASES` is the pinned alias table mapping each legacy
stats field to its canonical registry counter, and the stat classes
import it as their single mirroring source (tests/test_observability.py
pins both directions).

Percentiles follow the shared nearest-rank discipline of
``serving.nearest_rank_ms`` (value at index ``ceil(p*n) - 1`` of the
sorted sample, rounded to 0.1 ms). The histogram keeps its fixed bucket
counts for the Prometheus dump *and* a bounded sliding window of raw
samples for exact nearest-rank percentiles; parity with
``serving.nearest_rank_ms`` is test-pinned. (The function is deliberately
re-implemented here rather than imported: ``serving`` imports the jax
inference stack, and this module must stay importable without jax.)
"""

from __future__ import annotations

import math
import re
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

# Default latency buckets (ms upper bounds). Chosen to straddle the
# measured serving stages: sub-ms queue pops up to multi-second compiles.
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1000.0, 2000.0, 5000.0, 10000.0, float("inf"),
)

# Bounded raw-sample window per histogram: nearest-rank percentiles are
# exact while a window fits (every bench/serve window does), sliding
# (most recent) beyond it. Bounds memory: 8 KB/histogram at the default.
DEFAULT_SAMPLE_CAP = 4096


def host_number(value, what: str = "metric value") -> float:
    """Return ``value`` as a host float, REJECTING device arrays.

    ``float(jax_array)`` would silently block on the device — the exact
    sync telemetry must never add — so the check inspects the type's
    module and raises *before* any conversion could synchronize. The
    concrete array type lives under ``jaxlib`` (``jaxlib.xla_extension``
    on this build), tracers under ``jax.*`` — both roots are device-side.
    """
    mod = type(value).__module__ or ""
    if mod.partition(".")[0] in ("jax", "jaxlib"):
        raise TypeError(
            f"telemetry {what} is a jax value ({type(value).__name__}): "
            "recording it would add a device sync. Pull it through the "
            "sanctioned boundary device_get first and record the host "
            "scalar."
        )
    return float(value)


_PROM_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str) -> str:
    """Sanitize a registry name into the exposition-format charset
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``): invalid characters become ``_``, a
    leading digit gets a ``_`` prefix. Registry names follow the
    snake_case convention and pass through untouched; the sanitizer
    exists so a free-form span name can never emit a line a real
    scraper rejects (scrapers fail the WHOLE scrape on one bad line)."""
    if _PROM_NAME_OK.match(name):
        return name
    safe = _PROM_BAD_CHARS.sub("_", name)
    if not safe or not (safe[0].isalpha() or safe[0] in "_:"):
        safe = "_" + safe
    return safe


def prometheus_help(text: str) -> str:
    """Escape HELP text per the exposition format (backslash and
    newline are the two escaped characters on HELP lines)."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def nearest_rank_ms(latencies_ms: Sequence[float], p: float) -> Optional[float]:
    """Nearest-rank percentile of an ms sample (``serving.nearest_rank_ms``
    discipline, already-in-ms variant): sorted value at index
    ``ceil(p*n) - 1``, rounded to 0.1 ms; ``None`` on empty."""
    if not latencies_ms:
        return None
    xs = sorted(latencies_ms)
    idx = max(0, math.ceil(p * len(xs)) - 1)
    return round(xs[min(idx, len(xs) - 1)], 1)


class Counter:
    """Monotonic event counter. ``inc`` is the only mutation."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n=1) -> None:
        n = host_number(n, f"counter {self.name} increment")
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value, with a high-water mark
    (``peak``) so a burst that is gone by snapshot time still shows."""

    __slots__ = ("name", "help", "_value", "_peak", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._peak = 0.0
        self._lock = threading.Lock()

    def set(self, value) -> None:
        value = host_number(value, f"gauge {self.name}")
        with self._lock:
            self._value = value
            if value > self._peak:
                self._peak = value

    def add(self, delta) -> None:
        delta = host_number(delta, f"gauge {self.name} delta")
        with self._lock:
            self._value += delta
            if self._value > self._peak:
                self._peak = self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def peak(self) -> float:
        with self._lock:
            return self._peak


class Histogram:
    """Fixed-bucket latency histogram (milliseconds) with exact
    nearest-rank percentiles over a bounded sliding sample window."""

    __slots__ = (
        "name", "help", "buckets_ms", "_counts", "_count", "_sum_ms",
        "_samples", "_lock",
    )

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS,
        sample_cap: int = DEFAULT_SAMPLE_CAP,
    ):
        bs = tuple(sorted(float(b) for b in buckets_ms))
        if not bs or bs[-1] != float("inf"):
            bs = bs + (float("inf"),)
        self.name = name
        self.help = help
        self.buckets_ms = bs
        self._counts = [0] * len(bs)
        self._count = 0
        self._sum_ms = 0.0
        # deque(maxlen): O(1) append-with-evict on the hot path (a list
        # pop(0) would memmove sample_cap floats per observation once
        # full); percentile/snapshot copy before sorting anyway.
        self._samples: deque = deque(maxlen=max(1, int(sample_cap)))
        self._lock = threading.Lock()

    def observe_ms(self, ms) -> None:
        ms = host_number(ms, f"histogram {self.name} observation")
        with self._lock:
            for i, upper in enumerate(self.buckets_ms):
                if ms <= upper:
                    self._counts[i] += 1
                    break
            self._count += 1
            self._sum_ms += ms
            self._samples.append(ms)  # maxlen evicts the oldest

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum_ms(self) -> float:
        with self._lock:
            return self._sum_ms

    def percentile_ms(self, p: float) -> Optional[float]:
        """Exact nearest-rank percentile over the (windowed) raw sample —
        the ``serving.nearest_rank_ms`` discipline; parity test-pinned."""
        with self._lock:
            samples = list(self._samples)
        return nearest_rank_ms(samples, p)

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            count, total = self._count, self._sum_ms
            samples = list(self._samples)
        return {
            "count": count,
            "sum_ms": round(total, 3),
            "p50_ms": nearest_rank_ms(samples, 0.50),
            "p99_ms": nearest_rank_ms(samples, 0.99),
            "buckets": {
                ("+Inf" if math.isinf(u) else f"{u:g}"): c
                for u, c in zip(self.buckets_ms, counts)
            },
        }


class MetricsRegistry:
    """Thread-safe name -> metric map with get-or-create accessors.

    A name is permanently bound to its first-registered kind — asking for
    ``counter(x)`` after ``gauge(x)`` is a programming error and raises
    (two subsystems silently sharing one name across kinds is exactly the
    accounting corruption a registry exists to prevent).
    """

    def __init__(self, sample_cap: int = DEFAULT_SAMPLE_CAP):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._sample_cap = sample_cap

    def _get_or_create(self, name: str, kind, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {kind.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(
            name, Counter, lambda: Counter(name, help)
        )

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, lambda: Gauge(name, help))

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS,
    ) -> Histogram:
        return self._get_or_create(
            name,
            Histogram,
            lambda: Histogram(name, help, buckets_ms, self._sample_cap),
        )

    def get(self, name: str):
        """The metric or None — readers must not create phantom zeros."""
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> dict:
        """One JSON-able view: {counters: {...}, gauges: {...},
        histograms: {name: {count, sum_ms, p50_ms, p99_ms, buckets}}}."""
        with self._lock:
            items = sorted(self._metrics.items())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in items:
            if isinstance(m, Counter):
                v = m.value
                out["counters"][name] = int(v) if v == int(v) else v
            elif isinstance(m, Gauge):
                out["gauges"][name] = {"value": m.value, "peak": m.peak}
            elif isinstance(m, Histogram):
                out["histograms"][name] = m.snapshot()
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition of every metric, compliant with
        the text format a real scraper parses unmodified (pinned by
        tests/test_observability.py's mini-parser):

        - metric names sanitized to ``[a-zA-Z_:][a-zA-Z0-9_:]*``
          (:func:`prometheus_name`) — registry names are free-form
          strings, exposition names are not;
        - ``# HELP`` text escaped (backslash, newline);
        - every exposed metric family gets its own ``# TYPE`` line — in
          particular the gauge's ``_peak`` companion is its own gauge
          family, not an untyped stray sample;
        - histograms expose the full ``_bucket{le=...}`` (cumulative,
          ending at ``le="+Inf"`` == ``_count``) + ``_sum`` + ``_count``
          triplet.
        """
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        for raw_name, m in items:
            name = prometheus_name(raw_name)
            if m.help:
                lines.append(f"# HELP {name} {prometheus_help(m.help)}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {m.value:g}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {m.value:g}")
                lines.append(f"# TYPE {name}_peak gauge")
                lines.append(f"{name}_peak {m.peak:g}")
            elif isinstance(m, Histogram):
                snap = m.snapshot()
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for upper, c in snap["buckets"].items():
                    cum += c
                    lines.append(
                        f'{name}_bucket{{le="{upper}"}} {cum}'
                    )
                lines.append(f"{name}_sum {snap['sum_ms']:g}")
                lines.append(f"{name}_count {snap['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric (tests and bench-window isolation)."""
        with self._lock:
            self._metrics.clear()


# --------------------------------------------------------- alias tables
#
# The pinned legacy-alias map: every existing ``report()``/``summary()``
# field of the pre-telemetry stat classes, keyed by subsystem prefix,
# mapped to its canonical registry counter. The stat classes import THIS
# table to mirror (single source of truth), and
# tests/test_observability.py pins (a) that every legacy field has an
# alias and (b) that mirrored counter values equal the legacy fields.
# Downstream readers (bench, flip_recommendations, log parsers) keep
# reading the legacy keys verbatim.

LEGACY_KEY_ALIASES: Dict[str, Dict[str, str]] = {
    "serve": {
        "submitted": "serve_requests_submitted_total",
        "accepted": "serve_requests_accepted_total",
        "completed": "serve_requests_completed_total",
        "shed": "serve_requests_shed_total",
        "timeouts": "serve_requests_timeout_total",
        "rejected": "serve_requests_rejected_total",
        "errors": "serve_requests_error_total",
        "batches": "serve_batches_total",
        "padded_rows": "serve_batch_padded_rows_total",
    },
    "stream": {
        "submitted": "stream_frames_submitted_total",
        "accepted": "stream_frames_accepted_total",
        "completed": "stream_frames_completed_total",
        "shed_streams": "stream_streams_shed_total",
        "shed_frames": "stream_frames_shed_total",
        "rejected": "stream_frames_rejected_total",
        "resets": "stream_slots_reset_total",
        "errors": "stream_frames_error_total",
        "batches": "stream_batches_total",
        "padded_rows": "stream_batch_padded_rows_total",
        "streams_opened": "stream_streams_opened_total",
        "streams_closed": "stream_streams_closed_total",
        "streams_evicted": "stream_streams_evicted_total",
        "cold_starts": "stream_frames_cold_start_total",
    },
    # RetryStats fields: counted via the retry layer's ring events
    # (`io_retry`/`io_giveup`), whose auto-counters carry the canonical
    # names below.
    "retry": {
        "retries": "io_retry_total",
        "giveups": "io_giveup_total",
    },
    "inference": {
        "compiles": "inference_executable_compiles_total",
        "hits": "inference_executable_hits_total",
        "evictions": "inference_executable_evictions_total",
    },
}
